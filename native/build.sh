#!/bin/sh
# Build the native Medit tokenizer (see medit_tok.cpp).
set -e
cd "$(dirname "$0")"
g++ -O2 -shared -fPIC -o libmedit_tok.so medit_tok.cpp
echo "built $(pwd)/libmedit_tok.so"
