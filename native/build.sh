#!/bin/sh
# Build the native Medit tokenizer (see medit_tok.cpp).
set -e
cd "$(dirname "$0")"
g++ -O2 -shared -fPIC -o libmedit_tok.so medit_tok.cpp
echo "built $(pwd)/libmedit_tok.so"

# C ABI shim (Fortran/ISO_C_BINDING surface; embeds CPython)
PYINC=$(python3 -c "import sysconfig; print(sysconfig.get_paths()['include'])")
PYLIB=$(python3 -c "import sysconfig; print(sysconfig.get_config_var('LIBDIR'))")
PYVER=$(python3 -c "import sysconfig; print(sysconfig.get_config_var('LDVERSION'))")
gcc -O2 -shared -fPIC -I"$PYINC" -o libparmmg_capi.so parmmg_capi.c \
    -L"$PYLIB" -lpython"$PYVER"
echo "built $(pwd)/libparmmg_capi.so"
