// Native Medit tokenizer — the hot I/O loop of the framework's reader.
//
// The reference's Medit I/O layer is native C (inout_pmmg.c building on
// Mmg's readers); here the performance-critical part of loading —
// turning a multi-hundred-MB ASCII .mesh/.sol file into a token stream —
// is native C++, while section parsing/assembly stays in numpy
// (parmmg_tpu/io/medit.py). Exposed via ctypes (no pybind11 in the
// toolchain): medit_tokenize() returns a heap buffer of NUL-separated
// tokens ('#' comments stripped to end of line), medit_free() releases
// it.
//
// Build: native/build.sh  (g++ -O2 -shared -fPIC)

#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Reads `path`, writes token bytes ('\0'-separated, no trailing
// separator) into a malloc'd buffer, stores the byte count in *nbytes.
// Returns nullptr on I/O failure. Caller frees with medit_free().
char *medit_tokenize(const char *path, long *nbytes) {
    FILE *f = std::fopen(path, "rb");
    if (!f) return nullptr;
    if (std::fseek(f, 0, SEEK_END) != 0) { std::fclose(f); return nullptr; }
    long sz = std::ftell(f);
    if (sz < 0) { std::fclose(f); return nullptr; }
    std::rewind(f);
    char *raw = static_cast<char *>(std::malloc(sz > 0 ? sz : 1));
    if (!raw) { std::fclose(f); return nullptr; }
    long got = static_cast<long>(std::fread(raw, 1, sz, f));
    std::fclose(f);
    if (got != sz) { std::free(raw); return nullptr; }

    // output can never exceed input size + 1 (one separator per token,
    // tokens shrink relative to the whitespace they replace)
    char *out = static_cast<char *>(std::malloc(sz + 1));
    if (!out) { std::free(raw); return nullptr; }
    long w = 0;
    bool in_tok = false;
    for (long i = 0; i < sz; ++i) {
        unsigned char c = static_cast<unsigned char>(raw[i]);
        if (c == '#') {  // comment to end of line
            while (i < sz && raw[i] != '\n') ++i;
            in_tok = false;
            continue;
        }
        if (c == ' ' || c == '\t' || c == '\r' || c == '\n' ||
            c == '\v' || c == '\f') {
            in_tok = false;
            continue;
        }
        if (!in_tok && w > 0) out[w++] = '\0';
        in_tok = true;
        out[w++] = static_cast<char>(c);
    }
    std::free(raw);
    *nbytes = w;
    return out;
}

void medit_free(char *buf) { std::free(buf); }

}  // extern "C"
