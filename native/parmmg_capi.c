/* C ABI for the parmmg_tpu library — the Fortran-surface role.
 *
 * The reference ships hand-written Fortran wrappers for every API
 * function (`src/API_functionsf_pmmg.c`, 1,297 LoC of FORTRAN_NAME
 * macros). Here the full setter/getter surface lives in Python
 * (`parmmg_tpu/api.py`); foreign callers — Fortran via ISO_C_BINDING,
 * C, or anything with a C FFI — consume this thin embedded-CPython shim
 * instead of per-function name-mangled wrappers. The file-driven entry
 * point below covers the reference CLI workflow (load → adapt → save,
 * the `PMMG_parmmglib_centralized` path, `src/libparmmg.c:1444`);
 * richer programs drive `parmmg_tpu.api.ParMesh` through Python.
 *
 * Build: native/build.sh (produces libparmmg_capi.so).
 * Fortran usage sketch (ISO_C_BINDING):
 *
 *   interface
 *     integer(c_int) function pmmgtpu_adapt_file(inmesh, insol, out, &
 *         hsiz, niter, nparts) bind(c, name="pmmgtpu_adapt_file")
 *       use iso_c_binding
 *       character(kind=c_char), dimension(*) :: inmesh, insol, out
 *       real(c_double), value :: hsiz
 *       integer(c_int), value :: niter, nparts
 *     end function
 *   end interface
 *
 * Returns the graded status of the run: 0 = PMMG_SUCCESS,
 * 1 = PMMG_LOWFAILURE (conformal mesh was still saved),
 * 2 = PMMG_STRONGFAILURE (reference `src/libparmmgtypes.h:45-66`).
 */

#include <Python.h>
#include <pthread.h>
#include <string.h>

static pthread_mutex_t init_lock = PTHREAD_MUTEX_INITIALIZER;

static int ensure_python(void) {
    /* mutex-guarded (NOT pthread_once): concurrent first calls from
     * multiple foreign threads must not race Py_InitializeEx, but a
     * failed init (e.g. a PYTHONHOME the host app fixes later) must
     * stay retryable on the next call */
    int ok;
    pthread_mutex_lock(&init_lock);
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        if (Py_IsInitialized()) {
            /* release the GIL the interpreter holds after init, so
             * later PyGILState_Ensure calls (from ANY caller thread)
             * can acquire it instead of deadlocking */
            PyEval_SaveThread();
        }
    }
    ok = Py_IsInitialized();
    pthread_mutex_unlock(&init_lock);
    return ok ? 0 : -1;
}

/* Adapt `inmesh` (Medit ASCII) to the metric in `insol` (may be NULL or
 * "" for -optim implied sizes), writing `outmesh`. hsiz <= 0 means "use
 * the sol metric"; nparts > 1 runs the distributed driver. */
int pmmgtpu_adapt_file(const char *inmesh, const char *insol,
                       const char *outmesh, double hsiz, int niter,
                       int nparts) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *fn = NULL, *res = NULL;
    int rc = 2; /* STRONGFAILURE until proven otherwise */

    if (ensure_python() != 0) return 2;
    g = PyGILState_Ensure();

    mod = PyImport_ImportModule("parmmg_tpu.api");
    if (!mod) goto done;
    fn = PyObject_GetAttrString(mod, "adapt_file");
    if (!fn) goto done;
    res = PyObject_CallFunction(
        fn, "sssdii",
        inmesh,
        (insol && insol[0]) ? insol : "",
        outmesh, hsiz, niter, nparts);
    if (!res) goto done;
    rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) rc = 2;

done:
    if (PyErr_Occurred()) PyErr_Print();
    Py_XDECREF(res);
    Py_XDECREF(fn);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* ------------------------------------------------------------------ */
/* Staged-arrays surface: the PMMG_Init_parMesh / PMMG_Set_* /
 * PMMG_parmmglib_centralized / PMMG_Get_* workflow for foreign callers
 * holding raw buffers (the `src/API_functions_pmmg.c` role). Entity
 * indices cross this ABI 1-BASED like the reference API. The handle is
 * an opaque pointer; every call is GIL-safe from any thread.
 * Conversions live in `parmmg_tpu/capi_support.py`. */

static PyObject *capi_mod(void) {
    return PyImport_ImportModule("parmmg_tpu.capi_support");
}

/* Create a parmesh handle (nparts > 1 = distributed driver). NULL on
 * failure. Release with pmmgtpu_free. */
void *pmmgtpu_init(int nparts) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *pm = NULL;
    if (ensure_python() != 0) return NULL;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        pm = PyObject_CallMethod(mod, "make_parmesh", "i", nparts);
    if (PyErr_Occurred()) PyErr_Print();
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return (void *)pm; /* owned reference held by the caller's handle */
}

int pmmgtpu_free(void *h) {
    PyGILState_STATE g;
    if (!h) return 0;
    g = PyGILState_Ensure();
    Py_DECREF((PyObject *)h);
    PyGILState_Release(g);
    return 0;
}

/* Shared call helper: method(pm, bytes(buf1), bytes(buf2)|None, n)
 * for the entity setters. refs may be NULL. */
static int capi_set_entities(void *h, const char *meth, const void *buf,
                             size_t nbytes, const int *refs, int n) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL, *b = NULL, *r = NULL;
    int rc = -1;
    if (!h) return -1;
    if (ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (!mod) goto done;
    b = PyBytes_FromStringAndSize((const char *)buf, (Py_ssize_t)nbytes);
    if (!b) goto done;
    if (refs) {
        r = PyBytes_FromStringAndSize((const char *)refs,
                                      (Py_ssize_t)(sizeof(int) * (size_t)n));
        if (!r) goto done;
    } else {
        r = Py_None;
        Py_INCREF(Py_None);
    }
    res = PyObject_CallMethod(mod, meth, "OOOi", (PyObject *)h, b, r, n);
    if (res) rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) rc = -1;
done:
    if (PyErr_Occurred()) PyErr_Print();
    Py_XDECREF(res);
    Py_XDECREF(r);
    Py_XDECREF(b);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* coords: np x 3 doubles (C order); refs: np ints or NULL */
int pmmgtpu_set_vertices(void *h, const double *coords, const int *refs,
                         int np) {
    return capi_set_entities(h, "set_vertices", coords,
                             sizeof(double) * 3u * (size_t)np, refs, np);
}

/* tets: ne x 4 ints, 1-BASED vertex ids; refs: ne ints or NULL */
int pmmgtpu_set_tetrahedra(void *h, const int *tets, const int *refs,
                           int ne) {
    return capi_set_entities(h, "set_tetrahedra", tets,
                             sizeof(int) * 4u * (size_t)ne, refs, ne);
}

/* trias: nt x 3 ints, 1-BASED vertex ids; refs: nt ints or NULL */
int pmmgtpu_set_triangles(void *h, const int *trias, const int *refs,
                          int nt) {
    return capi_set_entities(h, "set_triangles", trias,
                             sizeof(int) * 3u * (size_t)nt, refs, nt);
}

/* met: np x ncomp doubles; ncomp 1 (iso) or 6 (aniso tensor) */
int pmmgtpu_set_metric(void *h, const double *met, int np, int ncomp) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL, *b = NULL;
    int rc = -1;
    if (!h) return -1;
    if (ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod) {
        b = PyBytes_FromStringAndSize(
            (const char *)met,
            (Py_ssize_t)(sizeof(double) * (size_t)np * (size_t)ncomp));
        if (b)
            res = PyObject_CallMethod(mod, "set_metric", "OOii",
                                      (PyObject *)h, b, np, ncomp);
        if (res) rc = (int)PyLong_AsLong(res);
        if (PyErr_Occurred()) rc = -1;
    }
    if (PyErr_Occurred()) PyErr_Print();
    Py_XDECREF(res);
    Py_XDECREF(b);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* param enums match parmmg_tpu.api.Param (documented there). */
int pmmgtpu_set_iparameter(void *h, int param, int value) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = -1;
    if (!h || ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, "set_iparameter", "Oii",
                                  (PyObject *)h, param, value);
    if (res) rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) { PyErr_Print(); rc = -1; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

int pmmgtpu_set_dparameter(void *h, int param, double value) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = -1;
    if (!h || ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, "set_dparameter", "Oid",
                                  (PyObject *)h, param, value);
    if (res) rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) { PyErr_Print(); rc = -1; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* Run the centralized pipeline on the staged mesh. Returns graded
 * status (0/1/2 like pmmgtpu_adapt_file). */
int pmmgtpu_run(void *h) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = 2;
    if (!h || ensure_python() != 0) return 2;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, "run", "O", (PyObject *)h);
    if (res) rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) { PyErr_Print(); rc = 2; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* Result sizes, for the caller to allocate get_* buffers. */
int pmmgtpu_get_meshsize(void *h, int *np, int *ne, int *nt) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = -1;
    if (!h || ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, "get_mesh_size", "O", (PyObject *)h);
    if (res && PyArg_ParseTuple(res, "iii", np, ne, nt)) rc = 0;
    if (PyErr_Occurred()) { PyErr_Print(); rc = -1; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* Shared getter: calls `meth` returning (data_bytes, refs_bytes) and
 * memcpy's into caller buffers (either may be NULL to skip). */
static int capi_get_pair(void *h, const char *meth, void *data,
                         size_t dbytes, int *refs, size_t rbytes) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = -1;
    if (!h || ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, meth, "O", (PyObject *)h);
    if (res && PyTuple_Check(res) && PyTuple_GET_SIZE(res) == 2) {
        PyObject *d = PyTuple_GET_ITEM(res, 0);
        PyObject *r = PyTuple_GET_ITEM(res, 1);
        rc = 0;
        if (data) {
            if ((size_t)PyBytes_GET_SIZE(d) == dbytes)
                memcpy(data, PyBytes_AS_STRING(d), dbytes);
            else rc = -1;
        }
        if (refs && rc == 0) {
            if ((size_t)PyBytes_GET_SIZE(r) == rbytes)
                memcpy(refs, PyBytes_AS_STRING(r), rbytes);
            else rc = -1;
        }
    }
    if (PyErr_Occurred()) { PyErr_Print(); rc = -1; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

int pmmgtpu_get_vertices(void *h, double *coords, int *refs, int np) {
    return capi_get_pair(h, "get_vertices", coords,
                         sizeof(double) * 3u * (size_t)np, refs,
                         sizeof(int) * (size_t)np);
}

/* tets out 1-BASED */
int pmmgtpu_get_tetrahedra(void *h, int *tets, int *refs, int ne) {
    return capi_get_pair(h, "get_tetrahedra", tets,
                         sizeof(int) * 4u * (size_t)ne, refs,
                         sizeof(int) * (size_t)ne);
}

/* trias out 1-BASED */
int pmmgtpu_get_triangles(void *h, int *trias, int *refs, int nt) {
    return capi_get_pair(h, "get_triangles", trias,
                         sizeof(int) * 3u * (size_t)nt, refs,
                         sizeof(int) * (size_t)nt);
}

int pmmgtpu_get_metric(void *h, double *met, int np, int ncomp) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *res = NULL;
    int rc = -1;
    if (!h || ensure_python() != 0) return -1;
    g = PyGILState_Ensure();
    mod = capi_mod();
    if (mod)
        res = PyObject_CallMethod(mod, "get_metric", "O", (PyObject *)h);
    if (res && PyBytes_Check(res)) {
        size_t want = sizeof(double) * (size_t)np * (size_t)ncomp;
        if ((size_t)PyBytes_GET_SIZE(res) == want) {
            memcpy(met, PyBytes_AS_STRING(res), want);
            rc = 0;
        }
    }
    if (PyErr_Occurred()) { PyErr_Print(); rc = -1; }
    Py_XDECREF(res);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* Library version string (static storage, do not free). */
const char *pmmgtpu_version(void) {
    static char buf[64] = "";
    PyGILState_STATE g;
    PyObject *mod = NULL, *v = NULL;

    if (buf[0]) return buf;
    if (ensure_python() != 0) return "unknown";
    g = PyGILState_Ensure();
    mod = PyImport_ImportModule("parmmg_tpu");
    if (mod) {
        v = PyObject_GetAttrString(mod, "__version__");
        if (v) {
            const char *s = PyUnicode_AsUTF8(v);
            if (s) {
                strncpy(buf, s, sizeof(buf) - 1);
            }
        }
    }
    if (PyErr_Occurred()) PyErr_Clear();
    Py_XDECREF(v);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return buf[0] ? buf : "unknown";
}
