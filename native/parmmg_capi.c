/* C ABI for the parmmg_tpu library — the Fortran-surface role.
 *
 * The reference ships hand-written Fortran wrappers for every API
 * function (`src/API_functionsf_pmmg.c`, 1,297 LoC of FORTRAN_NAME
 * macros). Here the full setter/getter surface lives in Python
 * (`parmmg_tpu/api.py`); foreign callers — Fortran via ISO_C_BINDING,
 * C, or anything with a C FFI — consume this thin embedded-CPython shim
 * instead of per-function name-mangled wrappers. The file-driven entry
 * point below covers the reference CLI workflow (load → adapt → save,
 * the `PMMG_parmmglib_centralized` path, `src/libparmmg.c:1444`);
 * richer programs drive `parmmg_tpu.api.ParMesh` through Python.
 *
 * Build: native/build.sh (produces libparmmg_capi.so).
 * Fortran usage sketch (ISO_C_BINDING):
 *
 *   interface
 *     integer(c_int) function pmmgtpu_adapt_file(inmesh, insol, out, &
 *         hsiz, niter, nparts) bind(c, name="pmmgtpu_adapt_file")
 *       use iso_c_binding
 *       character(kind=c_char), dimension(*) :: inmesh, insol, out
 *       real(c_double), value :: hsiz
 *       integer(c_int), value :: niter, nparts
 *     end function
 *   end interface
 *
 * Returns the graded status of the run: 0 = PMMG_SUCCESS,
 * 1 = PMMG_LOWFAILURE (conformal mesh was still saved),
 * 2 = PMMG_STRONGFAILURE (reference `src/libparmmgtypes.h:45-66`).
 */

#include <Python.h>
#include <pthread.h>
#include <string.h>

static pthread_mutex_t init_lock = PTHREAD_MUTEX_INITIALIZER;

static int ensure_python(void) {
    /* mutex-guarded (NOT pthread_once): concurrent first calls from
     * multiple foreign threads must not race Py_InitializeEx, but a
     * failed init (e.g. a PYTHONHOME the host app fixes later) must
     * stay retryable on the next call */
    int ok;
    pthread_mutex_lock(&init_lock);
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        if (Py_IsInitialized()) {
            /* release the GIL the interpreter holds after init, so
             * later PyGILState_Ensure calls (from ANY caller thread)
             * can acquire it instead of deadlocking */
            PyEval_SaveThread();
        }
    }
    ok = Py_IsInitialized();
    pthread_mutex_unlock(&init_lock);
    return ok ? 0 : -1;
}

/* Adapt `inmesh` (Medit ASCII) to the metric in `insol` (may be NULL or
 * "" for -optim implied sizes), writing `outmesh`. hsiz <= 0 means "use
 * the sol metric"; nparts > 1 runs the distributed driver. */
int pmmgtpu_adapt_file(const char *inmesh, const char *insol,
                       const char *outmesh, double hsiz, int niter,
                       int nparts) {
    PyGILState_STATE g;
    PyObject *mod = NULL, *fn = NULL, *res = NULL;
    int rc = 2; /* STRONGFAILURE until proven otherwise */

    if (ensure_python() != 0) return 2;
    g = PyGILState_Ensure();

    mod = PyImport_ImportModule("parmmg_tpu.api");
    if (!mod) goto done;
    fn = PyObject_GetAttrString(mod, "adapt_file");
    if (!fn) goto done;
    res = PyObject_CallFunction(
        fn, "sssdii",
        inmesh,
        (insol && insol[0]) ? insol : "",
        outmesh, hsiz, niter, nparts);
    if (!res) goto done;
    rc = (int)PyLong_AsLong(res);
    if (PyErr_Occurred()) rc = 2;

done:
    if (PyErr_Occurred()) PyErr_Print();
    Py_XDECREF(res);
    Py_XDECREF(fn);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return rc;
}

/* Library version string (static storage, do not free). */
const char *pmmgtpu_version(void) {
    static char buf[64] = "";
    PyGILState_STATE g;
    PyObject *mod = NULL, *v = NULL;

    if (buf[0]) return buf;
    if (ensure_python() != 0) return "unknown";
    g = PyGILState_Ensure();
    mod = PyImport_ImportModule("parmmg_tpu");
    if (mod) {
        v = PyObject_GetAttrString(mod, "__version__");
        if (v) {
            const char *s = PyUnicode_AsUTF8(v);
            if (s) {
                strncpy(buf, s, sizeof(buf) - 1);
            }
        }
    }
    if (PyErr_Occurred()) PyErr_Clear();
    Py_XDECREF(v);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return buf[0] ? buf : "unknown";
}
