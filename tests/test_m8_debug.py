"""Debug/observability tests: quality/partition dumps, stats, comm
printer, Morton renumbering (debug_pmmg.c + Scotch-renumber roles)."""

import os

import numpy as np

from parmmg_tpu.utils import debug
from parmmg_tpu.utils.gen import unit_cube_mesh


def test_quality_dump_roundtrip(tmp_path):
    m = unit_cube_mesh(2)
    base = str(tmp_path / "dump")
    debug.save_quality(m, base)
    assert os.path.exists(base + ".mesh")
    sol = open(base + ".sol").read()
    assert "SolAtTetrahedra" in sol
    vals = [float(x) for x in sol.split("1 1\n")[1].split("\nEnd")[0].split()]
    assert len(vals) == int(m.ntet)
    assert all(0 < v <= 1 for v in vals)


def test_partition_dump_and_comm_printer(tmp_path):
    import jax

    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition

    m = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(m, 4)))
    debug.save_partition(m, part, str(tmp_path / "part"))
    assert os.path.exists(str(tmp_path / "part.sol"))

    stacked, comm = split_mesh(m, part, 4)
    txt = debug.format_comm(comm)
    assert "4 shards" in txt and "shard 0" in txt
    # distinct interface gids matches the PARBDY population per shard
    assert "distinct interface gids" in txt
    debug.save_stacked_quality(stacked, str(tmp_path / "grp"))
    for s in range(4):
        assert os.path.exists(str(tmp_path / f"grp-S{s:02d}.mesh"))


def test_mesh_stats_lines():
    from parmmg_tpu.ops import analysis

    m = analysis.analyze(unit_cube_mesh(2))
    txt = debug.mesh_stats(m)
    assert "vertices 27" in txt and "RIDGE" in txt


def test_renumber_sfc_preserves_mesh():
    from parmmg_tpu.core.adjacency import build_adjacency
    from parmmg_tpu.parallel.partition import renumber_sfc
    from parmmg_tpu.utils.conformity import check_mesh

    m = unit_cube_mesh(3)
    r = build_adjacency(renumber_sfc(m))
    assert int(r.ntet) == int(m.ntet)
    rep = check_mesh(r)
    assert rep.ok, str(rep)
    # same multiset of tets, new order
    a = np.sort(np.sort(np.asarray(m.tet)[np.asarray(m.tmask)], 1), 0)
    b = np.sort(np.sort(np.asarray(r.tet)[np.asarray(r.tmask)], 1), 0)
    np.testing.assert_array_equal(a, b)
