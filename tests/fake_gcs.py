"""Hermetic stdlib fake-GCS server for the store-contract suite and
the `io.gcs.GCSStore` fault matrix.

Implements the JSON/upload API subset the adapter speaks — media
upload with ``ifGenerationMatch``, media/metadata GET, paginated
list, DELETE — over an in-memory object map with real per-object
generation numbers, plus **per-op fault injection**:

    srv = FakeGCS()
    base = srv.start()                 # http://127.0.0.1:<port>
    srv.inject("upload", status=429, retry_after=2)   # next upload
    srv.inject("get", stall=1.0)       # next get sleeps 1 s
    srv.inject("get", truncate=0.5)    # next get sends half the body
    srv.stop()

Fault ops: ``upload`` (put/publish data writes), ``get`` (media
reads), ``meta`` (metadata/generation stats), ``list``, ``delete``.
Each injected fault consumes ``times`` matching requests (FIFO per
op). ``status`` faults answer with that HTTP code (and an optional
``Retry-After`` header); ``stall`` sleeps with the connection open (a
slow backend — trips socket/per-op timeouts); ``truncate`` advertises
the full Content-Length but sends only that fraction and drops the
connection (a torn read).

Optional ``require_token`` arms bearer-token auth: requests without
``Authorization: Bearer <token>`` get a 401 — the terminal
`CheckpointAuthError` leg of the taxonomy.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

FAULT_OPS = ("upload", "get", "meta", "list", "delete")


class _Handler(BaseHTTPRequestHandler):
    server_version = "FakeGCS/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: tests read assertions
        pass

    # -- helpers ---------------------------------------------------------
    @property
    def gcs(self) -> "FakeGCS":
        return self.server.gcs  # type: ignore[attr-defined]

    def _reply(self, status: int, body: bytes = b"",
               content_type: str = "application/json",
               headers: Optional[dict] = None,
               truncate: Optional[float] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        if truncate is not None:
            # advertise the full length, deliver a prefix, kill the
            # connection: the client sees a torn read (IncompleteRead)
            self.wfile.write(body[: int(len(body) * truncate)])
            self.wfile.flush()
            self.close_connection = True
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return
        self.wfile.write(body)

    def _error(self, status: int, message: str,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(
            {"error": {"code": status, "message": message}}
        ).encode()
        self._reply(status, body, headers=headers)

    def _fault(self, op: str) -> Optional[dict]:
        """Consume + apply a pending fault for `op`. Returns the fault
        when it already ANSWERED the request (status faults), or a
        truncate fault for the normal path to apply; stalls sleep here
        and fall through to normal handling."""
        f = self.gcs._take_fault(op)
        if f is None:
            return None
        if f.get("stall"):
            time.sleep(float(f["stall"]))
        if f.get("status"):
            hdrs = {}
            if f.get("retry_after") is not None:
                hdrs["Retry-After"] = f["retry_after"]
            self._error(int(f["status"]),
                        f.get("message", "injected fault"), hdrs)
            return f
        return f if f.get("truncate") is not None else None

    def _authorized(self) -> bool:
        want = self.gcs.require_token
        if want is None:
            return True
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {want}":
            return True
        self._error(401, "missing or invalid bearer token")
        return False

    # -- routes ----------------------------------------------------------
    def do_POST(self):
        split = urllib.parse.urlsplit(self.path)
        qs = urllib.parse.parse_qs(split.query)
        parts = split.path.strip("/").split("/")
        # /upload/storage/v1/b/<bucket>/o
        if len(parts) == 6 and parts[0] == "upload" and parts[5] == "o":
            self.gcs._count("upload")
            if not self._authorized():
                return
            fault = self._fault("upload")
            if fault and fault.get("status"):
                return
            name = (qs.get("name") or [""])[0]
            if not name:
                return self._error(400, "missing object name")
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length)
            with self.gcs._lock:
                cur = self.gcs.objects.get(name)
                cur_gen = cur[1] if cur else 0
                want = (qs.get("ifGenerationMatch") or [None])[0]
                if want is not None and int(want) != cur_gen:
                    return self._error(
                        412,
                        f"ifGenerationMatch {want} != current {cur_gen}",
                    )
                self.gcs._gen += 1
                self.gcs.objects[name] = (data, self.gcs._gen)
                gen = self.gcs._gen
            body = json.dumps(
                {"name": name, "generation": str(gen),
                 "size": str(len(data))}
            ).encode()
            return self._reply(200, body)
        self._error(404, f"no route for POST {split.path}")

    def do_GET(self):
        split = urllib.parse.urlsplit(self.path)
        qs = urllib.parse.parse_qs(split.query)
        parts = split.path.strip("/").split("/")
        # /storage/v1/b/<bucket>/o[/<object>]
        if len(parts) >= 5 and parts[0] == "storage" and parts[4] == "o":
            if len(parts) == 5:
                return self._do_list(qs)
            name = urllib.parse.unquote(parts[5])
            if (qs.get("alt") or [""])[0] == "media":
                return self._do_get_media(name)
            return self._do_get_meta(name)
        self._error(404, f"no route for GET {split.path}")

    def _do_list(self, qs):
        self.gcs._count("list")
        if not self._authorized():
            return
        fault = self._fault("list")
        if fault and fault.get("status"):
            return
        prefix = (qs.get("prefix") or [""])[0]
        token = (qs.get("pageToken") or ["0"])[0]
        with self.gcs._lock:
            names = sorted(
                n for n in self.gcs.objects if n.startswith(prefix)
            )
        start = int(token)
        page = names[start:start + self.gcs.page_size]
        doc: dict = {"items": [{"name": n} for n in page]}
        if start + self.gcs.page_size < len(names):
            doc["nextPageToken"] = str(start + self.gcs.page_size)
        self._reply(200, json.dumps(doc).encode())

    def _do_get_media(self, name):
        self.gcs._count("get")
        if not self._authorized():
            return
        fault = self._fault("get")
        if fault and fault.get("status"):
            return
        with self.gcs._lock:
            cur = self.gcs.objects.get(name)
        if cur is None:
            return self._error(404, f"object {name!r} not found")
        self._reply(
            200, cur[0], content_type="application/octet-stream",
            truncate=fault.get("truncate") if fault else None,
        )

    def _do_get_meta(self, name):
        self.gcs._count("meta")
        if not self._authorized():
            return
        fault = self._fault("meta")
        if fault and fault.get("status"):
            return
        with self.gcs._lock:
            cur = self.gcs.objects.get(name)
        if cur is None:
            return self._error(404, f"object {name!r} not found")
        body = json.dumps(
            {"name": name, "generation": str(cur[1]),
             "size": str(len(cur[0]))}
        ).encode()
        self._reply(200, body)

    def do_DELETE(self):
        split = urllib.parse.urlsplit(self.path)
        parts = split.path.strip("/").split("/")
        if len(parts) == 6 and parts[0] == "storage" and parts[4] == "o":
            self.gcs._count("delete")
            if not self._authorized():
                return
            fault = self._fault("delete")
            if fault and fault.get("status"):
                return
            name = urllib.parse.unquote(parts[5])
            with self.gcs._lock:
                if name not in self.gcs.objects:
                    return self._error(404, f"object {name!r} not found")
                del self.gcs.objects[name]
            return self._reply(204)
        self._error(404, f"no route for DELETE {split.path}")


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        # broken pipes from clients that timed out mid-stall are part
        # of the fault matrix, not test noise
        pass


class FakeGCS:
    """In-process fake GCS bucket server (see module docstring)."""

    def __init__(self, require_token: Optional[str] = None,
                 page_size: int = 1000):
        self.objects: Dict[str, Tuple[bytes, int]] = {}
        self.require_token = require_token
        self.page_size = page_size
        self.counts: Dict[str, int] = {op: 0 for op in FAULT_OPS}
        self._gen = 0
        self._faults: Dict[str, List[dict]] = {op: [] for op in FAULT_OPS}
        self._lock = threading.RLock()
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> str:
        self._server = _Server(("127.0.0.1", 0), _Handler)
        self._server.gcs = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="fake-gcs",
            daemon=True,
        )
        self._thread.start()
        return self.base_url

    @property
    def base_url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # -- fault injection / accounting -----------------------------------
    def inject(self, op: str, *, status: Optional[int] = None,
               times: int = 1, stall: Optional[float] = None,
               retry_after: Optional[float] = None,
               truncate: Optional[float] = None,
               message: str = "injected fault") -> None:
        """Queue a fault for the next `times` requests of `op`."""
        if op not in FAULT_OPS:
            raise ValueError(f"op {op!r} not one of {FAULT_OPS}")
        with self._lock:
            self._faults[op].append(dict(
                status=status, times=int(times), stall=stall,
                retry_after=retry_after, truncate=truncate,
                message=message,
            ))

    def clear_faults(self) -> None:
        with self._lock:
            for q in self._faults.values():
                q.clear()

    def _take_fault(self, op: str) -> Optional[dict]:
        with self._lock:
            q = self._faults[op]
            if not q:
                return None
            f = q[0]
            f["times"] -= 1
            if f["times"] <= 0:
                q.pop(0)
            return f

    def _count(self, op: str) -> None:
        with self._lock:
            self.counts[op] += 1

    def request_count(self, op: str) -> int:
        with self._lock:
            return self.counts[op]

    def reset_counts(self) -> None:
        with self._lock:
            for op in self.counts:
                self.counts[op] = 0
