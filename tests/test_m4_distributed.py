"""M4: distributed iterative remesh loop (reference `PMMG_parmmglib1`,
src/libparmmg1.c:550-896) on the 8-virtual-device CPU simulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parmmg_tpu.core import tags
from parmmg_tpu.core.mesh import Mesh, tet_volumes
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.models.distributed import (
    DistOptions,
    adapt_distributed,
    assign_global_ids,
    merge_adapted,
    rebuild_comm,
)
from parmmg_tpu.ops import quality
from parmmg_tpu.parallel import chkcomm
from parmmg_tpu.parallel.distribute import split_mesh, unstack_mesh
from parmmg_tpu.parallel.shard import device_mesh
from parmmg_tpu.utils.conformity import check_mesh
from parmmg_tpu.utils.gen import unit_cube_mesh


def _total_volume(mesh: Mesh) -> float:
    return float(jnp.sum(jnp.where(mesh.tmask, tet_volumes(mesh), 0.0)))


@pytest.fixture(scope="module")
def dist_result():
    mesh = unit_cube_mesh(5)
    # min_shard_elts=16: skip the single-shard pre-growth so the
    # distributed sweeps themselves do the refinement under test
    opts = DistOptions(
        nparts=8, niter=2, hsiz=0.18, max_sweeps=6, check_comm=True,
        min_shard_elts=16,
    )
    st, comm, info = adapt_distributed(mesh, opts)
    return mesh, st, comm, info


def test_distributed_adapt_runs_and_comm_stays_valid(dist_result):
    # check_comm=True already asserted chkcomm INSIDE every iteration;
    # assert once more on the final state
    _, st, comm, info = dist_result
    rep = chkcomm.check_node_comm(st, comm, device_mesh(8))
    assert rep["max_coord_err"] <= 1e-12
    assert rep["gid_mismatch"] == 0
    assert rep["count_mismatch"] == 0
    assert rep["valid_mismatch"] == 0
    # remeshing actually happened
    assert info["history"][0]["nsplit"] > 0


def test_each_shard_conforming_after_loop(dist_result):
    _, st, _, _ = dist_result
    for s, m in enumerate(unstack_mesh(st)):
        rep = check_mesh(m, check_boundary=False)
        assert rep.ok, f"shard {s}: {rep}"


def test_merge_after_adapt_conforms_and_conserves_volume(dist_result):
    mesh, st, comm, _ = dist_result
    merged = merge_adapted(st, comm)
    rep = check_mesh(merged)
    assert rep.ok, str(rep)
    assert _total_volume(merged) == pytest.approx(_total_volume(mesh), rel=1e-5)
    # no interface bookkeeping bits must survive centralization
    vt = np.asarray(merged.vtag)[np.asarray(merged.vmask)]
    assert not (vt & (tags.PARBDY | tags.PARBDYBDY)).any()


def test_global_ids_unique_and_complete(dist_result):
    _, st, _, _ = dist_result
    vglob = np.asarray(st.vglob)
    vmask = np.asarray(st.vmask)
    vtag = np.asarray(st.vtag)
    assert (vglob[vmask] >= 0).all()
    # interface copies share a gid; every non-PARBDY gid is globally unique
    inner = vmask & ((vtag & tags.PARBDY) == 0)
    inner_gids = vglob[inner]
    assert len(np.unique(inner_gids)) == len(inner_gids)
    # PARBDY gids appear in >= 2 shards with identical coordinates
    par = vmask & ((vtag & tags.PARBDY) != 0)
    gids, counts = np.unique(vglob[par], return_counts=True)
    assert (counts >= 2).all()


def test_rebuild_comm_matches_geometric_truth():
    """The gid-derived comm tables must agree with a brute-force
    COORDINATE match between shard pairs — an implementation-independent
    ground truth (the role of the reference's geometric chkcomm,
    `src/chkcomm_pmmg.c:815`)."""
    mesh = unit_cube_mesh(4)
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.core import adjacency

    mesh = adjacency.build_adjacency(mesh)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    comm_idx = np.asarray(comm.comm_idx)
    counts = np.asarray(comm.counts)
    vert = np.asarray(st.vert)
    vmask = np.asarray(st.vmask)
    D = vert.shape[0]
    for s in range(D):
        for r in range(s + 1, D):
            # ground truth: exact coordinate intersection of live vertices
            vs = {tuple(v) for v in vert[s][vmask[s]].tolist()}
            vr = {tuple(v) for v in vert[r][vmask[r]].tolist()}
            shared_coords = vs & vr
            assert counts[s, r] == len(shared_coords), (s, r)
            k = counts[s, r]
            # the table's matched slots carry the same coordinates in the
            # same k-order on both sides
            cs = vert[s][comm_idx[s, r, :k]]
            cr = vert[r][comm_idx[r, s, :k]]
            assert np.array_equal(cs, cr), (s, r)
            assert {tuple(v) for v in cs.tolist()} == shared_coords
            assert (comm_idx[s, r, k:] == -1).all()
    # owner: exactly one shard owns each shared vertex
    owner = np.asarray(comm.owner)
    l2g = np.asarray(comm.l2g)
    live = vmask & (l2g >= 0)
    gids = l2g[live]
    own_count = np.zeros(gids.max() + 1, np.int64)
    np.add.at(own_count, l2g[live & owner], 1)
    present = np.zeros(gids.max() + 1, bool)
    present[gids] = True
    assert (own_count[present] == 1).all()


def test_quality_parity_away_from_interfaces(dist_result):
    """Interior (non-frozen) regions must reach the same quality class as
    a single-shard adaptation of the same mesh (SURVEY M4 test goal)."""
    mesh, st, _, _ = dist_result
    single, _ = adapt(mesh, AdaptOptions(niter=2, hsiz=0.18, max_sweeps=6))
    qs = quality.tet_quality(single)
    ms = np.asarray(single.tmask)
    med_single = float(np.median(np.asarray(qs)[ms]))

    # distributed: quality of tets with NO vertex on an interface
    qual, msk = [], []
    for m in unstack_mesh(st):
        q = np.asarray(quality.tet_quality(m))
        par_v = (np.asarray(m.vtag) & tags.PARBDY) != 0
        touches = par_v[np.asarray(m.tet)].any(axis=1)
        sel = np.asarray(m.tmask) & ~touches
        qual.append(q[sel])
    q_int = np.concatenate(qual)
    assert len(q_int) > 100
    med_dist = float(np.median(q_int))
    # same quality class: medians within 15%, both meshes mostly good
    assert med_dist > 0.85 * med_single
    assert (q_int > 0.2).mean() > 0.95


def test_merge_after_coarsening():
    """Coarsening collapses away ORIGINAL vertices, leaving gaps in the
    gid space — merge must compress, not crash (review regression)."""
    mesh = unit_cube_mesh(6)  # h=1/6 grid, then ask for h=0.4: coarsen
    opts = DistOptions(
        nparts=4, niter=2, hsiz=0.4, max_sweeps=6, min_shard_elts=16
    )
    st, comm, info = adapt_distributed(mesh, opts)
    assert sum(r["ncollapse"] for r in info["history"]) > 0
    merged = merge_adapted(st, comm)
    rep = check_mesh(merged)
    assert rep.ok, str(rep)
    assert _total_volume(merged) == pytest.approx(1.0, rel=1e-5)
    # coarsening actually happened
    assert int(merged.ntet) < int(mesh.ntet)


def test_interface_displacement_refines_frozen_bands():
    """With displacement (default), bands frozen in one iteration are
    interior in the next — the count of metric-overlong edges left in the
    output must drop far below the frozen-interfaces (-nobalance) run
    (reference PMMG_part_moveInterfaces, src/moveinterfaces_pmmg.c:1306)."""
    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core import metric as mm

    def nlong(mesh):
        mesh = adjacency.build_adjacency(mesh)
        edges, emask, _, _ = adjacency.unique_edges(
            mesh, int(mesh.tcap * 2) + 64
        )
        a, b = edges[:, 0], edges[:, 1]
        l = mm.edge_length(
            mesh.vert[a], mesh.vert[b], mesh.met[a], mesh.met[b]
        )
        return int((np.asarray(jnp.where(emask, l, 0.0)) > 1.5).sum())

    mesh = unit_cube_mesh(8)
    counts = {}
    for nobal in (True, False):
        opts = DistOptions(
            nparts=8, niter=3, hsiz=0.1, max_sweeps=8,
            min_shard_elts=16, nobalancing=nobal,
        )
        st, comm, info = adapt_distributed(mesh, opts)
        counts[nobal] = nlong(merge_adapted(st, comm))
    # displacement must clear the majority of the frozen long edges
    assert counts[False] < 0.5 * counts[True], counts


def test_stacked_graph_colors_rebalances_weights():
    """The device-resident global weighted SFC cut (graph-balancing
    redistribution, reference PMMG_REDISTRIBUTION_graph_balancing,
    src/libparmmgtypes.h:173-178): starting from a COUNT-balanced
    partition under a localized-refinement metric, the recomputed colors
    must rebalance the PREDICTED-element weights across shards without
    centralizing the mesh."""
    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.parallel import partition as pm
    from parmmg_tpu.parallel.distribute import split_mesh

    mesh = unit_cube_mesh(5)
    hv = np.full(mesh.pcap, 0.2, np.float64)
    vert = np.asarray(mesh.vert)
    hv[np.linalg.norm(vert - 0.15, axis=1) < 0.3] = 0.02
    mesh = mesh.replace(
        met=jnp.asarray(hv[:, None], mesh.dtype), met_set=True
    )
    mesh = adj.build_adjacency(mesh)
    # unweighted cut: tet COUNTS balanced, predicted weights skewed
    part = np.asarray(jax.device_get(pm.sfc_partition(mesh, 4)))
    stacked, _ = split_mesh(mesh, part, 4)

    w = np.asarray(jax.device_get(jax.vmap(pm.metric_weights)(stacked)))
    color = np.asarray(jax.device_get(
        pm.stacked_graph_colors(stacked, 4)
    ))
    tm = np.asarray(jax.device_get(stacked.tmask))
    assert (color[tm] >= 0).all() and (color[~tm] == -1).all()
    before = np.array([w[tm][np.where(tm)[0] == s].sum()
                       for s in range(4)])
    after = np.array([w[tm][color[tm] == s].sum() for s in range(4)])
    assert before.max() / before.min() > 2.0, (
        f"fixture not skewed enough to discriminate: {before}"
    )
    assert after.max() / after.min() < 1.5, (
        f"graph cut left weights imbalanced: {after}"
    )
    # something actually moves
    own = np.where(tm, np.arange(4)[:, None], -1)
    assert (color[tm] != own[tm]).any()


def test_graph_balancing_mode_end_to_end():
    """adapt_distributed under repartitioning=graph_balancing: green
    loop, conformal merged output, conserved volume — the driver-level
    counterpart of the unit cut test (reference mode dispatch
    src/distributegrps_pmmg.c:2055)."""
    from parmmg_tpu.models.distributed import (
        REDISTRIBUTION_GRAPH_BALANCING,
    )

    mesh = unit_cube_mesh(5)
    opts = DistOptions(
        nparts=4, niter=2, hsiz=0.18, max_sweeps=5, min_shard_elts=16,
        repartitioning=REDISTRIBUTION_GRAPH_BALANCING, check_comm=True,
    )
    st, comm, info = adapt_distributed(mesh, opts)
    assert info["status"] == tags.ReturnStatus.SUCCESS
    merged = merge_adapted(st, comm)
    rep = check_mesh(merged)
    assert rep.ok, str(rep)
    assert _total_volume(merged) == pytest.approx(1.0, rel=1e-5)
    # the final shard tet counts respect the balance discipline
    ne = np.asarray(jax.device_get(jnp.sum(st.tmask, axis=1)))
    assert ne.max() <= opts.grps_ratio * max(ne.mean(), 1.0), ne


def test_fix_contiguity_reattaches_pinched_island():
    """A component the front pinched off gets reassigned to its majority
    neighbor color (the PMMG_fix_contiguity / PMMG_check_reachability
    role, reference src/moveinterfaces_pmmg.c:475-700); main components
    and every other tet stay untouched."""
    import jax

    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.parallel import migrate as mig
    from parmmg_tpu.parallel.distribute import (
        assign_global_ids, split_mesh,
    )
    from parmmg_tpu.parallel.partition import sfc_partition

    mesh = adj.build_adjacency(unit_cube_mesh(5))
    part = np.asarray(jax.device_get(sfc_partition(mesh, 2)))
    stacked, _ = split_mesh(mesh, part, 2)
    stacked = assign_global_ids(stacked)
    stacked = jax.vmap(adj.build_adjacency)(stacked)

    # colors = shard ids, then strand one interior shard-0 tet as a
    # fake color-1 island: every face neighbor live and color 0
    adja = np.asarray(jax.device_get(stacked.adja))
    tmask = np.asarray(jax.device_get(stacked.tmask))
    color = np.where(tmask, np.arange(2)[:, None], -1).astype(np.int32)
    interior = tmask[0] & (adja[0] >= 0).all(axis=1)
    nb0 = adja[0] >> 2
    nb_ok = interior & np.array([
        tmask[0][nb0[t]].all() and interior[nb0[t]].all()
        for t in range(len(nb0))
    ])
    island = int(np.nonzero(nb_ok)[0][0])
    color[0, island] = 1

    fixed = np.asarray(jax.device_get(mig.fix_contiguity(
        stacked, jnp.asarray(color), 2
    )))
    assert fixed[0, island] == 0, "island not reattached"
    keep = np.ones_like(color, bool)
    keep[0, island] = False
    assert (fixed[keep] == color[keep]).all(), "non-island colors changed"


def test_device_migration_conserves_and_retags():
    """One displacement + fixed-slot migration round (parallel.migrate):
    tets conserved, every shard conformal, interface discipline
    re-derived (the PMMG_transfer_all_grps + PMMG_updateTag roles,
    reference src/distributegrps_pmmg.c:1843, src/tag_pmmg.c:267)."""
    import jax
    import jax.numpy as jnp

    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import AdaptOptions, prepare_metric
    from parmmg_tpu.models.distributed import grow_stacked
    from parmmg_tpu.ops import analysis
    from parmmg_tpu.parallel import migrate as mig
    from parmmg_tpu.parallel.distribute import (
        assign_global_ids, merge_shards, rebuild_comm, split_mesh,
    )
    from parmmg_tpu.parallel.partition import sfc_partition

    mesh = unit_cube_mesh(5)
    mesh = adj.build_adjacency(mesh)
    mesh = analysis.analyze(mesh)
    mesh = prepare_metric(
        mesh, AdaptOptions(hsiz=0.2, hgrad=None), int(mesh.tcap * 1.6) + 64
    )
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    stacked, comm = split_mesh(mesh, part, 8)
    stacked = assign_global_ids(stacked)
    comm = rebuild_comm(stacked)
    stacked = jax.vmap(adj.build_adjacency)(stacked)
    ne0 = int(jnp.sum(stacked.tmask))

    color = mig.displace_colors(stacked, comm, 8, round_id=0, layers=2)
    cnts = np.asarray(jax.device_get(mig.migration_counts(stacked, color, 8)))
    assert cnts.sum() > 0, "displacement moved nothing"
    inc = cnts.sum(axis=0)
    ne_s = np.asarray(jax.device_get(jnp.sum(stacked.tmask, axis=1)))
    np_s = np.asarray(jax.device_get(jnp.sum(stacked.vmask, axis=1)))
    stacked = grow_stacked(
        stacked,
        pcap=int((np_s + 4 * inc).max() * 1.5) + 8,
        tcap=int((ne_s + inc).max() * 1.5) + 8,
        fcap=stacked.tria.shape[1] * 2,
        ecap=stacked.edge.shape[1] * 2,
    )
    color = jnp.pad(
        color, ((0, 0), (0, stacked.tet.shape[1] - color.shape[1])),
        constant_values=-1,
    )
    st2 = mig.migrate(stacked, color, 8, int(cnts.max()) + 8)
    st2 = jax.vmap(compact)(st2)
    assert int(jnp.sum(st2.tmask)) == ne0, "migration lost/duplicated tets"

    st3, comm2 = mig.retag_interfaces(st2)
    # every shard conformal, merged mesh conformal (dedup by gid works)
    for s in range(8):
        m = jax.tree_util.tree_map(lambda a: a[s], st3)
        rep = check_mesh(m)
        assert rep.ok, f"shard {s}: {rep}"
    merged = merge_shards(st3, comm2)
    rep = check_mesh(merged)
    assert rep.ok, str(rep)
    assert int(merged.ntet) == ne0


def test_retag_device_matches_host(monkeypatch):
    """The device-resident retag (`_retag_device_core`: gid-histogram
    PARBDY, one global sort-merge for cross-shard faces, vmapped
    synthetic-tria bookkeeping) must reproduce the host-numpy reference
    path exactly: same vertex tags, same live-tria multiset with the
    same tags/refs, same rebuilt comm tables. Only the free-slot
    placement of NEW synthetic trias may differ (host inserts in
    lexicographic row order, device in enumeration order) — hence the
    multiset comparison."""
    import jax

    from parmmg_tpu.core import adjacency as adj
    from parmmg_tpu.core.mesh import compact
    from parmmg_tpu.models.adapt import AdaptOptions, prepare_metric
    from parmmg_tpu.models.distributed import grow_stacked
    from parmmg_tpu.ops import analysis
    from parmmg_tpu.parallel import migrate as mig
    from parmmg_tpu.parallel.distribute import (
        assign_global_ids, rebuild_comm, split_mesh,
    )
    from parmmg_tpu.parallel.partition import sfc_partition

    mesh = unit_cube_mesh(5)
    mesh = adj.build_adjacency(mesh)
    mesh = analysis.analyze(mesh)
    mesh = prepare_metric(
        mesh, AdaptOptions(hsiz=0.2, hgrad=None), int(mesh.tcap * 1.6) + 64
    )
    part = np.asarray(jax.device_get(sfc_partition(mesh, 4)))
    stacked, comm = split_mesh(mesh, part, 4)
    stacked = assign_global_ids(stacked)
    comm = rebuild_comm(stacked)
    stacked = jax.vmap(adj.build_adjacency)(stacked)
    color = mig.displace_colors(stacked, comm, 4, round_id=0, layers=2)
    cnts = np.asarray(jax.device_get(
        mig.migration_counts(stacked, color, 4)
    ))
    assert cnts.sum() > 0
    stacked = grow_stacked(
        stacked,
        pcap=stacked.vert.shape[1] * 2,
        tcap=stacked.tet.shape[1] * 2,
        fcap=stacked.tria.shape[1] * 2,
        ecap=stacked.edge.shape[1] * 2,
    )
    color = jnp.pad(
        color, ((0, 0), (0, stacked.tet.shape[1] - color.shape[1])),
        constant_values=-1,
    )
    st2 = mig.migrate(stacked, color, 4, int(cnts.max()) + 8)
    st2 = jax.vmap(compact)(st2)

    dev, comm_dev = mig.retag_interfaces(st2)
    monkeypatch.setenv("PARMMG_HOST_RETAG", "1")
    host, comm_host = mig.retag_interfaces(st2)

    vm = np.asarray(dev.vmask)
    np.testing.assert_array_equal(
        np.asarray(dev.vtag)[vm], np.asarray(host.vtag)[vm]
    )

    def tria_multiset(st):
        out = []
        for s in range(4):
            live = np.asarray(st.trmask[s])
            rows = np.sort(
                np.asarray(st.vglob[s])[np.asarray(st.tria[s])[live]],
                axis=1,
            )
            rec = np.concatenate(
                [rows,
                 np.asarray(st.trtag[s])[live][:, None],
                 np.asarray(st.trref[s])[live][:, None]], axis=1
            )
            out.append(rec[np.lexsort(rec.T[::-1])])
        return out

    for a, b in zip(tria_multiset(dev), tria_multiset(host)):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        np.asarray(comm_dev.counts), np.asarray(comm_host.counts)
    )
    np.testing.assert_array_equal(
        np.asarray(comm_dev.comm_idx), np.asarray(comm_host.comm_idx)
    )


def test_distributed_unfused_sweep_path(monkeypatch):
    """Above UNFUSED_TCAP the stacked sweep dispatches per-op instead of
    one fused program (the same large-shape compile guard as the
    single-shard engine; the north-star shards exceed the threshold)."""
    import parmmg_tpu.models.adapt as A
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, merge_adapted,
    )

    monkeypatch.setattr(A, "UNFUSED_TCAP", 64)
    # this test compiles many small per-op programs late in the module;
    # after the ~60 compile-heavy tests before it, the next big compile
    # can segfault the jaxlib CPU compiler (conftest note; same
    # workaround as the m6 option sweep) — drop executable caches first
    jax.clear_caches()
    mesh = unit_cube_mesh(3)
    stacked, comm, info = adapt_distributed(
        mesh, DistOptions(niter=1, max_sweeps=3, nparts=2, hsiz=0.25,
                          min_shard_elts=8, hgrad=None)
    )
    out = merge_adapted(stacked, comm)
    rep = check_mesh(out)
    assert rep.ok, str(rep)
    assert int(out.ntet) > 162
