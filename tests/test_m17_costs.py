"""M17: device cost attribution + perf-history regression gate.

Covers the PR-8 tentpole contracts:
- XLA cost-doc schema via the AOT path on a tiny jitted fn (flops /
  bytes accessed / memory_analysis sizes present and positive);
- roofline classification math on synthetic flops/bytes fixtures
  (ridge point, bound verdict, achieved fraction of the binding roof);
- PERF_DB envelope stamping: `obs.history.make_record` populates every
  envelope field, and `bench.partial_record` routes through the SAME
  constructor as the full records (the two-dict drift bugfix);
- backfill of a fixture BENCH dir (wrapper with multi-line tail, blind
  wrapper, raw record, SCALE_RUNS lines);
- gate pass / regress / ratchet behavior with seeded noise;
- HBM watermark gauges + captured cost docs + report cost/memory
  sections on one shared tiny traced adapt run.
"""

import json
import random

import pytest

from parmmg_tpu.obs import costs as obs_costs
from parmmg_tpu.obs import history as obs_history
from parmmg_tpu.obs import metrics as obs_metrics
from parmmg_tpu.obs import report as obs_report
from parmmg_tpu.obs import trace as obs_trace


# --- cost docs ------------------------------------------------------------


def test_cost_doc_schema_tiny_jit():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x @ x.T).sum(axis=0) * 2.0)
    doc = obs_costs.cost_doc(f, (jnp.ones((48, 48), jnp.float32),))
    assert doc["flops"] > 0
    assert doc["bytes_accessed"] > 0
    for key in ("transcendentals", "argument_bytes", "output_bytes",
                "temp_bytes", "code_bytes", "platform"):
        assert key in doc, (key, sorted(doc))
    assert doc["argument_bytes"] >= 48 * 48 * 4
    assert doc["platform"] == "cpu"


def test_capture_once_per_signature_and_requires_armed_tracer(tmp_path):
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2.0)
    col = obs_costs.collector()
    col.reset()
    # no tracer installed: capture must be inert
    obs_costs.capture("twice", f, (jnp.ones(8),))
    assert "twice" not in col.docs()
    tr = obs_trace.Tracer(str(tmp_path))
    prev = obs_trace.install(tr)
    try:
        obs_costs.capture("twice", f, (jnp.ones(8),))
        obs_costs.capture("twice", f, (jnp.ones(8),))       # same sig
        obs_costs.capture("twice", f, (jnp.ones(16),))      # new sig
        docs = col.docs()
        assert docs["twice"]["variants"] == 2
        # the larger-bytes variant wins the stored doc
        assert docs["twice"]["bytes_accessed"] >= 16 * 4
        tr.flush()
        on_disk = obs_costs.load_cost_docs(str(tmp_path))
        assert "twice" in on_disk
    finally:
        obs_trace.install(prev)
        col.reset()


def test_capture_failure_never_raises(tmp_path):
    tr = obs_trace.Tracer(str(tmp_path))
    prev = obs_trace.install(tr)
    col = obs_costs.collector()
    col.reset()
    try:
        obs_costs.capture("broken", object(), (1,))  # no .lower
        doc = col.docs()["broken"]
        assert "error" in doc and doc["flops"] == 0.0
    finally:
        obs_trace.install(prev)
        col.reset()


# --- roofline math --------------------------------------------------------


def test_roofline_classification_synthetic():
    p = obs_costs.peaks_for("cpu")
    ridge = p["flops"] / p["bw"]
    # intensity 10x above the ridge: compute-bound
    r = obs_costs.roofline(flops=ridge * 10 * 1e6, bytes_accessed=1e6,
                           seconds=0.0, platform="cpu")
    assert r["bound"] == "compute"
    assert r["intensity"] == pytest.approx(ridge * 10)
    assert r["ridge"] == pytest.approx(ridge)
    # intensity 10x below: memory-bound
    r = obs_costs.roofline(flops=ridge * 0.1 * 1e6, bytes_accessed=1e6,
                           seconds=0.0, platform="cpu")
    assert r["bound"] == "memory"
    # measured seconds: achieved fractions of the binding roof
    r = obs_costs.roofline(flops=1e6, bytes_accessed=1e9, seconds=0.1,
                           platform="cpu")
    assert r["bound"] == "memory"
    assert r["achieved_bw"] == pytest.approx(1e10)
    assert r["pct_peak_bw"] == pytest.approx(1e10 / p["bw"])
    assert r["pct_of_roof"] == pytest.approx(r["pct_peak_bw"])
    # degenerate: no flops, no bytes
    assert obs_costs.roofline(0, 0, 0, "cpu")["bound"] == "n/a"


def test_roofline_peaks_env_override(monkeypatch):
    monkeypatch.setenv("PMMGTPU_PEAKS", "2e12,1e11")
    p = obs_costs.peaks_for("tpu")
    assert p["flops"] == 2e12 and p["bw"] == 1e11
    monkeypatch.delenv("PMMGTPU_PEAKS")
    assert obs_costs.peaks_for("nosuch") == obs_costs.PEAKS["cpu"]


# --- envelope -------------------------------------------------------------


def test_make_record_envelope_fields():
    rec = obs_history.make_record(
        dict(metric="m", value=1.0, platform="cpu"), rung="r1"
    )
    assert rec["schema"] == obs_history.SCHEMA
    for key in ("run_id", "git_sha", "timestamp", "platform", "rung"):
        assert rec.get(key), key
    assert rec["rung"] == "r1" and rec["platform"] == "cpu"
    # timestamp is ISO-8601 UTC
    import time as _t

    _t.strptime(rec["timestamp"], "%Y-%m-%dT%H:%M:%SZ")
    # idempotent normalization: an enveloped record passes through
    assert obs_history.normalize(rec) is rec


def test_bench_partial_record_carries_envelope():
    """The bugfix contract: parent-synthesized partials and worker
    records are built by ONE constructor, so a partial carries the
    same envelope fields as a full record."""
    import bench

    pr = bench.partial_record(dict(n=10, hsiz=0.05),
                              died_in="steady:sweeps", reason="test")
    assert pr["schema"] == obs_history.SCHEMA
    for key in ("run_id", "git_sha", "timestamp", "platform", "rung"):
        assert pr.get(key), key
    assert pr["partial"] is True
    assert pr["rung"] == "n10-hsiz0.05"
    assert pr["died_in"] == "steady:sweeps"
    # dist configs group under the dist rung with the dist metric
    pd = bench.partial_record(dict(dist=True, n=8, hsiz=0.08, nparts=2))
    assert pd["rung"] == "dist-p2"
    assert pd["metric"] == "tets_per_sec_distributed"


def test_infer_rung_maps_historical_records():
    assert obs_history.infer_rung(dict(ne=93788)) == "n10-hsiz0.05"
    assert obs_history.infer_rung(dict(ne=232546)) == "n12-hsiz0.04"
    assert obs_history.infer_rung(
        dict(metric="tets_per_sec_distributed", nparts=2)
    ) == "dist-p2"
    assert obs_history.infer_rung(
        dict(metric="tets_per_sec_cold", rung="m")
    ) == "xl-m"


# --- backfill -------------------------------------------------------------


def test_backfill_fixture_bench_dir(tmp_path):
    # wrapper with a 2-record tail (the r04 shape)
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(dict(
        n=1, cmd="python bench.py", rc=124,
        tail=json.dumps(dict(metric="tets_per_sec", value=100.0,
                             ne=93788, wall_s=9.0, platform="tpu"))
        + "\n"
        + json.dumps(dict(metric="tets_per_sec", value=120.0,
                          ne=232546, wall_s=19.0, platform="tpu"))
        + "\n",
        parsed=None,
    )))
    # blind wrapper (the r01/r03 shape): synthesized partial
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(dict(
        n=2, cmd="python bench.py", rc=124, tail="", parsed=None,
    )))
    # raw record file (the r06 shape)
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(dict(
        metric="tets_per_sec_distributed", value=542.6, ne=20024,
        nparts=2, wall_s=36.9, platform="cpu",
    )))
    (tmp_path / "SCALE_RUNS.jsonl").write_text(json.dumps(dict(
        metric="tets_per_sec_cold", value=1651.5, ne=333679,
        wall_s=202.0, platform="tpu", rung="m",
    )) + "\n")
    recs = obs_history.backfill_records(str(tmp_path))
    assert len(recs) == 5
    for rec in recs:
        for key in ("schema", "run_id", "git_sha", "timestamp",
                    "platform", "rung"):
            assert rec.get(key), (key, rec)
    by_id = {r["run_id"]: r for r in recs}
    assert by_id["bench_r01.0"]["rung"] == "n10-hsiz0.05"
    assert by_id["bench_r01.1"]["rung"] == "n12-hsiz0.04"
    assert by_id["bench_r02"]["partial"] is True
    assert by_id["bench_r03"]["rung"] == "dist-p2"
    assert by_id["scale-runs.0"]["rung"] == "xl-m"


def test_repo_perf_db_backfilled():
    """Acceptance: the committed PERF_DB.jsonl holds the normalized
    historical trajectory — >= 7 records, every envelope field
    populated."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PERF_DB.jsonl")
    recs = obs_history.load_db(path)
    assert len(recs) >= 7, len(recs)
    for rec in recs:
        for key in ("schema", "run_id", "git_sha", "timestamp",
                    "platform"):
            assert rec.get(key), (key, rec.get("run_id"))


# --- gate -----------------------------------------------------------------


def _noisy_db(tmp_path, n=6, seed=0, value=1000.0, wall=10.0):
    rng = random.Random(seed)
    path = tmp_path / "db.jsonl"
    for i in range(n):
        obs_history.append_db(str(path), obs_history.make_record(dict(
            metric="m",
            value=value * (1 + rng.uniform(-0.02, 0.02)),
            wall_s=wall * (1 + rng.uniform(-0.05, 0.05)),
            platform="cpu",
        ), rung="g", run_id=f"base.{i}"))
    return str(path)


def test_gate_pass_within_noise(tmp_path):
    db = obs_history.load_db(_noisy_db(tmp_path))
    cand = obs_history.make_record(dict(
        metric="m", value=990.0, wall_s=10.4, platform="cpu",
    ), rung="g")
    res = obs_history.gate(db, cand)
    assert res.ok and res.baseline_n == 6
    assert not res.no_baseline
    assert any("OK" in ln for ln in res.lines())


def test_gate_regress_value_and_wall(tmp_path):
    db = obs_history.load_db(_noisy_db(tmp_path))
    slow = obs_history.make_record(dict(
        metric="m", value=1000.0, wall_s=31.0, platform="cpu",
    ), rung="g")
    res = obs_history.gate(db, slow)
    assert not res.ok and res.regressions == ["wall_s"]
    low = obs_history.make_record(dict(
        metric="m", value=400.0, wall_s=10.0, platform="cpu",
    ), rung="g")
    res = obs_history.gate(db, low)
    assert not res.ok and res.regressions == ["value"]
    # one-sided: a large IMPROVEMENT never regresses
    fast = obs_history.make_record(dict(
        metric="m", value=5000.0, wall_s=1.0, platform="cpu",
    ), rung="g")
    assert obs_history.gate(db, fast).ok


def test_gate_no_baseline_and_partial_skip(tmp_path):
    db = obs_history.load_db(_noisy_db(tmp_path))
    other = obs_history.make_record(dict(
        metric="other_metric", value=5.0, platform="cpu",
    ), rung="nowhere")
    res = obs_history.gate(db, other)
    assert res.ok and res.no_baseline
    # a partial candidate's zeroed keys are SKIPped, not failed
    part = obs_history.make_record(dict(
        metric="m", value=0.0, partial=True, platform="cpu",
    ), rung="g")
    res = obs_history.gate(db, part)
    assert res.ok
    assert all(r["verdict"] == "SKIP(partial)" for r in res.rows)
    # and partial records never enter a baseline
    obs_history.append_db(str(tmp_path / "db.jsonl"), part)
    db2 = obs_history.load_db(str(tmp_path / "db.jsonl"))
    res2 = obs_history.gate(db2, obs_history.make_record(dict(
        metric="m", value=990.0, wall_s=10.0, platform="cpu",
    ), rung="g"))
    assert res2.baseline_n == 6


def test_gate_ratchet_moves_baseline(tmp_path):
    """Appending improved records shifts the rolling median, so a
    return to the OLD level becomes a regression — the ratchet."""
    path = _noisy_db(tmp_path, n=4, wall=10.0)
    old_level = obs_history.make_record(dict(
        metric="m", value=1000.0, wall_s=10.0, platform="cpu",
    ), rung="g")
    assert obs_history.gate(obs_history.load_db(path), old_level).ok
    for i in range(8):  # the window fills with the improved level
        obs_history.append_db(path, obs_history.make_record(dict(
            metric="m", value=3000.0 + i, wall_s=2.0, platform="cpu",
        ), rung="g", run_id=f"fast.{i}"))
    res = obs_history.gate(obs_history.load_db(path), old_level)
    assert not res.ok
    assert set(res.regressions) == {"value", "wall_s"}


# --- HBM watermarks + capture on a real run -------------------------------


@pytest.fixture(scope="module")
def traced_cost_run(tmp_path_factory):
    """One tiny traced adapt run shared by the watermark/capture/report
    tests (costs armed — the Tracer default)."""
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    d = str(tmp_path_factory.mktemp("cost_run"))
    tr = obs_trace.Tracer(d)
    obs_metrics.registry().reset()
    obs_costs.collector().reset()
    out, info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(hsiz=0.5, niter=1, max_sweeps=3, hgrad=None,
                     polish_sweeps=0),
        tracer=tr,
    )
    return d, out, info


def test_hbm_watermark_gauges_present(traced_cost_run):
    d, _, _ = traced_cost_run
    reg = obs_metrics.registry()
    assert reg.gauge("hbm/peak_bytes").value > 0
    assert reg.gauge("hbm/bytes_in_use").value > 0
    # per-phase boundary watermarks for the driver phases
    phases = [k for k in reg.to_doc()["gauges"]
              if k.startswith("hbm/phase_bytes/")]
    assert any(k.endswith("/sweeps") for k in phases), phases
    assert any(k.endswith("/analysis") for k in phases), phases
    # peak is monotone >= every boundary snapshot
    doc = reg.to_doc()["gauges"]
    assert all(doc["hbm/peak_bytes"] >= doc[k] for k in phases)


def test_memory_watermark_shape():
    w = obs_costs.memory_watermark()
    assert w is not None
    assert w["source"] in ("device", "host_rss")
    assert w["peak_bytes"] >= w["bytes_in_use"] >= 0


def test_cost_docs_captured_and_report_renders(traced_cost_run):
    d, _, _ = traced_cost_run
    docs = obs_costs.load_cost_docs(d)
    assert "remesh_sweeps" in docs, sorted(docs)
    assert docs["remesh_sweeps"]["flops"] > 0
    assert docs["remesh_sweeps"]["bytes_accessed"] > 0
    s = obs_report.summarize(d)
    row = next(r for r in s["costs"] if r["name"] == "remesh_sweeps")
    assert row["bound"] in ("compute", "memory")
    assert row["calls"] > 0 and row["mean_s"] > 0
    assert 0 < row["pct_of_roof"]
    assert s["memory"]["peak_bytes"] > 0
    assert s["memory"]["source"] in ("device", "host_rss")
    text = obs_report.render(d)
    assert "cost attribution" in text
    assert "HBM peak bytes" in text
    assert "remesh_sweeps" in text


def test_attribute_drops_cold_first_sample():
    """The PR-8 wart: on a cold-cache trace the FIRST sample of every
    span folds the jit compile into the device-span mean, so %-of-roof
    was fiction. attribute() must drop the first sample per span —
    i.e. a table with (and without) one huge warmup sample reports a
    different, warm mean."""
    docs = {"phase": dict(flops=1e6, bytes_accessed=1e9,
                          platform="cpu")}
    # 1 cold sample of 1 s + 4 warm samples of 1 ms each
    cold = dict(count=5, total_us=1_000_000 + 4_000, max_us=1_000_000,
                first_us=1_000_000)
    rows = obs_costs.attribute(docs, {"phase": cold})
    assert rows[0]["mean_s"] == pytest.approx(1_000 / 1e6)
    naive = cold["total_us"] / cold["count"] / 1e6
    assert rows[0]["mean_s"] != naive  # the 1-warmup trace changed it
    assert "cold" not in rows[0]
    # the %-of-roof follows the warm mean, not the compile-diluted one
    warm_pct = rows[0]["pct_of_roof"]
    legacy = obs_costs.attribute(
        docs, {"phase": dict(count=5, total_us=cold["total_us"],
                             max_us=1_000_000)}  # no first_us: old trace
    )[0]
    assert warm_pct > legacy["pct_of_roof"] * 10
    # a single-sample span cannot be separated from its compile: kept,
    # flagged cold
    single = obs_costs.attribute(
        docs, {"phase": dict(count=1, total_us=50, max_us=50,
                             first_us=50)}
    )[0]
    assert single["cold"] is True
    assert single["mean_s"] == pytest.approx(50 / 1e6)


def test_span_table_records_first_sample():
    events = [
        dict(name="p", ph="X", ts=0, dur=900),
        dict(name="p", ph="X", ts=1000, dur=10),
        dict(name="p", ph="X", ts=2000, dur=12),
    ]
    table = obs_report._span_table(events)
    assert table["p"]["first_us"] == 900
    assert table["p"]["count"] == 3 and table["p"]["total_us"] == 922


def test_kernels_rung_marker_and_gate_fallback_isolation():
    """Kernel-on benches get a distinct `-pk` rung, and the gate's
    coarse (platform, metric) fallback never mixes -pk and lax
    history — kernel-on/off are distinct baseline keys."""
    import bench

    assert bench._rung_for_cfg(
        dict(n=10, hsiz=0.05, kernels="on")) == "n10-hsiz0.05-pk"
    assert bench._rung_for_cfg(
        dict(n=10, hsiz=0.05, kernels="off")) == "n10-hsiz0.05"
    assert bench._rung_for_cfg(
        dict(dist=True, nparts=2, kernels="on")) == "dist-p2-pk"

    db = [obs_history.make_record(
        dict(metric="tets_per_sec", value=100.0, wall_s=10.0,
             platform="cpu"), rung="n9-hsiz0.06") for _ in range(3)]
    pk = obs_history.make_record(
        dict(metric="tets_per_sec", value=1.0, wall_s=1000.0,
             platform="cpu"), rung="n10-hsiz0.05-pk")
    res = obs_history.gate(db, pk)
    assert res.no_baseline  # lax history must not gate a -pk record
    lax = obs_history.make_record(
        dict(metric="tets_per_sec", value=90.0, wall_s=11.0,
             platform="cpu"), rung="n10-hsiz0.05")
    res2 = obs_history.gate(db, lax)
    assert res2.baseline_n == 3  # same-marker coarse fallback intact
