"""M21: the adaptation service — admission, isolation, journal, drain.

Unit + integration coverage of `parmmg_tpu/service/` (the job server
behind `tools/serve.py`):

- the admission/refusal matrix: size-class classification, header
  peeks, bounded-queue backpressure — every refusal typed with a
  stable code and a machine-readable doc;
- bucketing + padding exactness: a class-admitted mesh loads at
  EXACTLY the class capacities (margin 2.0 > the loader's 1.5
  headroom), which is what makes a class one shared compile;
- poisoned-batch containment: a nan-faulted batch member ends
  ``failed`` (typed NumericalError) while its batch-mates' digests are
  BIT-IDENTICAL to a fresh-server solo run;
- the journal state machine on all three store backends (LocalFS,
  ``mem://``, fake-GCS): transition validation, crash replay,
  attempt counting;
- drain-on-notice requeue and per-job deadline/cancellation through
  the phase-boundary hook.

The process-level story (spool ingestion, SIGKILL mid-batch, restart
replay, ``obs_report --serve``) lives in ``tools/serve_smoke.py``.
"""

import json
import os
import threading
import time

import pytest

from fake_gcs import FakeGCS
from parmmg_tpu.io import ckpt_store, medit
from parmmg_tpu.service import (
    AdmissionQueue,
    BadJobError,
    DEFAULT_CLASSES,
    JobJournal,
    JobServer,
    JobSpec,
    JobTooLargeError,
    JournalStateError,
    QueueFullError,
    ServerDrainingError,
    SizeClass,
    TERMINAL_STATES,
    classify,
    peek_counts,
)
from parmmg_tpu.service import jobs as J
from parmmg_tpu.utils.gen import unit_cube_mesh

# one tiny class: every adapt in this module shares one compile
TINY = SizeClass("t", pcap=256, tcap=1024, fcap=256, ecap=256)


@pytest.fixture(scope="module")
def cube_mesh_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("m21") / "cube.mesh")
    medit.save_mesh(unit_cube_mesh(2), path)
    return path


def _mem_store(name):
    ckpt_store.memory_bucket(name).clear()
    return ckpt_store.make_store(f"mem://{name}", None)


def _server(name, **kw):
    kw.setdefault("classes", (TINY,))
    return JobServer(_mem_store(name), **kw)


# ---------------------------------------------------------------------------
# admission: classification, peeks, backpressure
# ---------------------------------------------------------------------------


def test_classify_picks_smallest_fit_with_margin():
    classes = DEFAULT_CLASSES
    assert classify(27, 48, classes).name == "tiny"
    # 2x margin: 300 verts * 2 > tiny's 512? 600 > 512 -> small
    assert classify(300, 48, classes).name == "small"
    assert classify(3000, 12000, classes).name == "medium"


def test_classify_too_large_refusal_is_typed():
    with pytest.raises(JobTooLargeError) as ei:
        classify(50000, 200000, DEFAULT_CLASSES)
    err = ei.value
    assert err.code == "too-large" and not err.transient
    doc = err.doc()
    assert doc["code"] == "too-large" and doc["transient"] is False
    assert doc["largest_class"] == "medium"
    assert doc["npoin"] == 50000 and doc["margin"] == 2.0


def test_peek_counts_medit_header(cube_mesh_path, tmp_path):
    npoin, ntet = peek_counts(cube_mesh_path)
    assert (npoin, ntet) == (27, 48)
    # the peek is a header scan: declared counts rule, nothing loads
    big = tmp_path / "big.mesh"
    big.write_text("MeshVersionFormatted 2\nDimension\n3\n"
                   "Vertices\n50000\nTetrahedra\n200000\nEnd\n")
    assert peek_counts(str(big)) == (50000, 200000)


def test_peek_counts_vtu_header(tmp_path):
    p = tmp_path / "m.vtu"
    p.write_text('<VTKFile type="UnstructuredGrid">\n<UnstructuredGrid>'
                 '\n<Piece NumberOfPoints="27" NumberOfCells="48">\n')
    assert peek_counts(str(p)) == (27, 48)


def test_peek_counts_bad_inputs(tmp_path):
    with pytest.raises(BadJobError) as ei:
        peek_counts(str(tmp_path / "missing.mesh"))
    assert ei.value.code == "bad-input" and not ei.value.transient
    weird = tmp_path / "m.stl"
    weird.write_text("solid\n")
    with pytest.raises(BadJobError):
        peek_counts(str(weird))
    corrupt = tmp_path / "c.mesh"
    corrupt.write_text("not a medit header at all\n")
    with pytest.raises(BadJobError):
        peek_counts(str(corrupt))


def test_queue_backpressure_and_class_homogeneous_batches():
    q = AdmissionQueue(cap=3)
    small = DEFAULT_CLASSES[1]
    s = [JobSpec(job_id=f"j{i}", inmesh="x.mesh") for i in range(4)]
    q.offer(s[0], TINY)
    q.offer(s[1], small)
    q.offer(s[2], TINY)
    with pytest.raises(QueueFullError) as ei:
        q.offer(s[3], TINY)
    assert ei.value.doc()["queue_depth"] == 3
    assert ei.value.doc()["queue_cap"] == 3
    # head job + later SAME-class jobs; others keep their order
    batch = q.take_batch(4)
    assert [sp.job_id for sp, _ in batch] == ["j0", "j2"]
    assert len(q) == 1
    # push_front restores drain-interrupted members at the head
    q.push_front(batch)
    assert [sp.job_id for sp, _ in q.take_batch(4)] == ["j0", "j2"]
    assert q.remove("j1").job_id == "j1"
    assert q.remove("nope") is None


def test_submit_refusal_matrix(cube_mesh_path, tmp_path):
    srv = _server("m21-adm", queue_cap=1)
    # queue-full: transient, NOT journaled
    srv.submit(JobSpec(job_id="a", inmesh=cube_mesh_path))
    with pytest.raises(QueueFullError):
        srv.submit(JobSpec(job_id="b", inmesh=cube_mesh_path))
    assert srv.journal.load("b") is None
    # too-large / bad-input: permanent, journaled as typed terminals
    big = tmp_path / "big.mesh"
    big.write_text("MeshVersionFormatted 2\nDimension\n3\n"
                   "Vertices\n50000\nTetrahedra\n200000\nEnd\n")
    with pytest.raises(JobTooLargeError):
        srv.submit(JobSpec(job_id="o", inmesh=str(big)))
    assert srv.journal.load("o")["state"] == J.REJECTED
    assert srv.journal.load("o")["error"]["code"] == "too-large"
    with pytest.raises(BadJobError):
        srv.submit(JobSpec(job_id="m",
                           inmesh=str(tmp_path / "gone.mesh")))
    assert srv.journal.load("m")["error"]["code"] == "bad-input"
    # idempotent resubmission returns the journaled record
    rec = srv.submit(JobSpec(job_id="a", inmesh=cube_mesh_path))
    assert rec["state"] == J.SUBMITTED and len(srv.queue) == 1
    # draining: transient refusal, nothing journaled
    srv.request_drain()
    with pytest.raises(ServerDrainingError):
        srv.submit(JobSpec(job_id="z", inmesh=cube_mesh_path))
    assert srv.journal.load("z") is None


def test_bucketing_pads_to_exact_class_capacities(cube_mesh_path):
    """Padding exactness: a class-admitted mesh loads at EXACTLY the
    class capacities (one class = one compile key), and the 2.0
    admission margin clears the loader's 1.5 growth headroom."""
    srv = _server("m21-pad")
    npoin, ntet = peek_counts(cube_mesh_path)
    cls = classify(npoin, ntet, srv.classes, srv.margin)
    assert cls is TINY
    mesh = srv._load_mesh(JobSpec(job_id="p", inmesh=cube_mesh_path),
                          cls)
    assert mesh.vert.shape[0] == cls.pcap
    assert mesh.tet.shape[0] == cls.tcap
    assert int(mesh.npoin) == npoin and int(mesh.ntet) == ntet
    # margin discipline: admission (x2) is strictly stricter than the
    # loader headroom (x1.5), so admitted => loads below caps
    assert npoin * 1.5 < cls.pcap and ntet * 1.5 < cls.tcap


# ---------------------------------------------------------------------------
# the journal state machine on every store backend
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gcs_server():
    srv = FakeGCS()
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(params=("localfs", "mem", "gcs"))
def journal_store(request, tmp_path, gcs_server, monkeypatch):
    if request.param == "localfs":
        return ckpt_store.make_store(str(tmp_path / "j"), None)
    if request.param == "mem":
        return _mem_store("m21-journal")
    monkeypatch.setenv("PMMGTPU_GCS_ENDPOINT", gcs_server.base_url)
    monkeypatch.setenv("PMMGTPU_GCS_AUTH", "anon")
    return ckpt_store.make_store(
        f"gs://m21-journal/{time.monotonic_ns()}", None
    )


def test_journal_roundtrip_and_replay(journal_store):
    j = JobJournal(journal_store)
    spec = JobSpec(job_id="r1", inmesh="x.mesh", tenant="acme")
    j.submit(spec, "tiny")
    assert j.load("r1")["state"] == J.SUBMITTED
    j.running("r1")
    doc = j.load("r1")
    assert doc["state"] == J.RUNNING and doc["attempts"] == 1
    # crash: a second journal on the same store replays RUNNING back
    # to SUBMITTED (requeue) and reports it; terminals stay put
    spec2 = JobSpec(job_id="r2", inmesh="x.mesh")
    j.submit(spec2, "tiny")
    j.running("r2")
    j.terminal("r2", J.DONE, result=dict(digest="abc"))
    parts = JobJournal(journal_store).replay()
    assert [d["job_id"] for d in parts["requeue"]] == ["r1"]
    assert [d["job_id"] for d in parts["terminal"]] == ["r2"]
    requeued = j.load("r1")
    assert requeued["state"] == J.SUBMITTED
    assert "crash replay" in requeued["history"][-1]["detail"]
    # the requeued attempt counts up on the NEXT running edge
    j.running("r1")
    assert j.load("r1")["attempts"] == 2
    j.terminal("r1", J.FAILED, error=dict(code="x", message="boom"))
    # illegal edges refuse before writing
    with pytest.raises(JournalStateError):
        j.running("r1")            # terminal -> running
    with pytest.raises(JournalStateError):
        j.transition("r1", J.SUBMITTED)
    with pytest.raises(JournalStateError):
        j.terminal("new", J.DONE)  # unjournaled -> terminal
    with pytest.raises(JournalStateError):
        j.terminal("r2", "sideways")   # not a terminal state
    # spec roundtrips through the record
    back = JobSpec.from_doc(j.load("r1")["spec"])
    assert back.job_id == "r1" and back.tenant == "acme"


def test_journal_skips_corrupt_records():
    store = _mem_store("m21-corrupt")
    j = JobJournal(store)
    j.submit(JobSpec(job_id="ok", inmesh="x.mesh"), "tiny")
    store.put("job_torn.json", b"{ not json")
    docs = j.jobs()
    assert [d["job_id"] for d in docs] == ["ok"]


# ---------------------------------------------------------------------------
# execution: containment, deadlines, cancellation, drain
# ---------------------------------------------------------------------------


def test_poisoned_batch_containment_bit_identical(cube_mesh_path):
    """One nan-faulted member ends ``failed`` (typed NumericalError);
    its batch-mates end ``done`` with digests bit-identical to a
    fresh-server SOLO run — the blast-radius contract, stated at the
    strictest (full-capacity byte) level."""
    solo = _server("m21-solo")
    solo.submit(JobSpec(job_id="s", inmesh=cube_mesh_path, niter=1))
    solo.run_once()
    sdoc = solo.journal.load("s")
    assert sdoc["state"] == J.DONE
    solo_digest = sdoc["result"]["digest"]

    srv = _server("m21-batch")
    srv.submit(JobSpec(job_id="a", inmesh=cube_mesh_path, niter=1,
                       tenant="acme"))
    srv.submit(JobSpec(job_id="e", inmesh=cube_mesh_path, niter=1,
                       tenant="evil", faults="it0:remesh:nan"))
    srv.submit(JobSpec(job_id="f", inmesh=cube_mesh_path, niter=1,
                       tenant="acme"))
    finished = srv.run_once()
    assert finished == 3
    docs = {j: srv.journal.load(j) for j in ("a", "e", "f")}
    assert docs["e"]["state"] == J.FAILED
    assert "Numerical" in docs["e"]["error"]["type"]
    for jid in ("a", "f"):
        assert docs[jid]["state"] == J.DONE
        assert docs[jid]["result"]["digest"] == solo_digest, (
            f"batch-mate {jid} contaminated by the poisoned member"
        )


def test_deadline_is_typed_terminal(cube_mesh_path):
    srv = _server("m21-deadline")
    srv.submit(JobSpec(job_id="d", inmesh=cube_mesh_path, niter=1,
                       deadline_s=1e-4))
    srv.run_once()
    doc = srv.journal.load("d")
    assert doc["state"] == J.DEADLINE
    assert doc["error"]["code"] == "deadline"
    assert "deadline" in doc["error"]["message"]


def test_cancellation_queued_and_running(cube_mesh_path):
    srv = _server("m21-cancel")
    srv.submit(JobSpec(job_id="c1", inmesh=cube_mesh_path))
    # queued: immediate typed terminal, removed from the queue
    assert srv.cancel("c1") == J.CANCELLED
    assert srv.journal.load("c1")["state"] == J.CANCELLED
    assert len(srv.queue) == 0
    assert srv.cancel("unknown") is None
    # running: honored at the next phase boundary
    srv.submit(JobSpec(job_id="c2", inmesh=cube_mesh_path, niter=1))
    srv._cancel_requested.add("c2")
    srv.run_once()
    doc = srv.journal.load("c2")
    assert doc["state"] == J.CANCELLED
    assert doc["error"]["code"] == "cancelled"


def test_drain_requeues_unstarted_and_inflight(cube_mesh_path,
                                               monkeypatch):
    # unstarted members: a draining server pushes the batch back
    srv = _server("m21-drain")
    srv.submit(JobSpec(job_id="u1", inmesh=cube_mesh_path))
    srv.submit(JobSpec(job_id="u2", inmesh=cube_mesh_path))
    srv.request_drain()
    assert srv.run_once() == 0
    assert len(srv.queue) == 2
    assert srv.journal.load("u1")["state"] == J.SUBMITTED
    # in-flight member: the drain lands at the next phase boundary —
    # journaled running -> submitted (requeue), queue restored
    monkeypatch.setenv("PMMGTPU_SERVE_TEST_SLEEP_S", "0.5")
    srv2 = _server("m21-drain2")
    srv2.submit(JobSpec(job_id="i1", inmesh=cube_mesh_path, niter=1))
    t = threading.Timer(0.1, srv2.request_drain)
    t.start()
    try:
        srv2.run_once()
    finally:
        t.cancel()
    doc = srv2.journal.load("i1")
    assert doc["state"] == J.SUBMITTED
    assert "requeued" in doc["history"][-1]["detail"]
    assert len(srv2.queue) == 1
    # restart path: a fresh server on the same store replays it
    srv3 = JobServer(ckpt_store.make_store("mem://m21-drain2", None),
                     classes=(TINY,))
    assert srv3.replay() == 1
    assert len(srv3.queue) == 1


def test_replay_restores_queue_from_journal(cube_mesh_path):
    srv = _server("m21-replay")
    srv.submit(JobSpec(job_id="q1", inmesh=cube_mesh_path))
    srv.submit(JobSpec(job_id="q2", inmesh=cube_mesh_path))
    srv.journal.running("q1")   # simulate a crash mid-run
    srv2 = JobServer(ckpt_store.make_store("mem://m21-replay", None),
                     classes=(TINY,))
    assert srv2.replay() == 2
    assert {sp.job_id for sp, _ in srv2.queue.take_batch(4)} \
        == {"q1", "q2"}
    assert srv2.journal.load("q1")["state"] == J.SUBMITTED


def test_terminal_states_cover_every_exit():
    assert TERMINAL_STATES == {J.DONE, J.FAILED, J.DEADLINE,
                               J.REJECTED, J.CANCELLED}
    # every refusal doc is json-serializable end to end
    for err in (QueueFullError("q", queue_depth=1, queue_cap=1),
                JobTooLargeError("t", npoin=9),
                BadJobError("b", path="x"),
                ServerDrainingError("d")):
        doc = json.loads(json.dumps(err.doc()))
        assert doc["code"] == err.code
        assert doc["transient"] is err.transient


# ---------------------------------------------------------------------------
# --status endpoint (round 11): Prometheus text over the live registry
# ---------------------------------------------------------------------------


def test_status_text_counters_queue_and_occupancy(cube_mesh_path):
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.service import status_text

    obs_metrics.registry().reset()
    srv = _server("m21-status", queue_cap=4)
    srv.submit(JobSpec(job_id="s1", inmesh=cube_mesh_path))
    srv.submit(JobSpec(job_id="s2", inmesh=cube_mesh_path))
    text = status_text(srv)
    lines = text.splitlines()
    assert "# TYPE parmmg_serve_submitted counter" in lines
    assert "parmmg_serve_submitted 2" in lines
    assert "parmmg_serve_queue_depth 2" in lines
    assert 'parmmg_serve_queue_occupancy{size_class="t"} 2' in lines
    assert "parmmg_serve_draining 0" in lines
    srv.request_drain()
    assert "parmmg_serve_draining 1" in status_text(srv).splitlines()
    obs_metrics.registry().reset()


def test_status_http_endpoint_scrapes(cube_mesh_path):
    import urllib.request

    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.service import StatusServer

    obs_metrics.registry().reset()
    srv = _server("m21-status-http", queue_cap=4)
    srv.submit(JobSpec(job_id="h1", inmesh=cube_mesh_path))
    status = StatusServer(srv, port=0).start()
    try:
        base = f"http://{status.host}:{status.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            assert r.status == 200
            assert "version=0.0.4" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "parmmg_serve_queue_depth 1" in body
        assert 'parmmg_serve_queue_occupancy{size_class="t"} 1' in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert r.read() == b"ok\n"
    finally:
        status.close()
    obs_metrics.registry().reset()


def test_admission_queue_occupancy_counts_per_class(cube_mesh_path):
    q = AdmissionQueue(cap=8)
    small = SizeClass("s2", pcap=512, tcap=2048, fcap=512, ecap=512)
    q.offer(JobSpec(job_id="a", inmesh=cube_mesh_path), TINY)
    q.offer(JobSpec(job_id="b", inmesh=cube_mesh_path), small)
    q.offer(JobSpec(job_id="c", inmesh=cube_mesh_path), TINY)
    assert q.occupancy() == {"t": 2, "s2": 1}
    q.take_batch(4)
    assert q.occupancy() == {"s2": 1}
