"""M25: the closed-loop run governor and PERF_DB-quoted SLO admission.

Coverage of `parmmg_tpu/control/` + the quote API + admission:

- `obs.history.quote` shares the EXACT baseline selection of the perf
  gate (`baseline_records`): rolling window, partial-record skip,
  rung fallback at matching ``-pk`` parity — admission can never
  promise a latency the gate would not hold the server to;
- empty-history fallbacks: `quote` -> {}, `SloPolicy.quote` -> None,
  admission passes specs through unchanged (the policy arms itself as
  records accumulate);
- the admission decision matrix: infeasible explicit deadlines refused
  typed (`SloInfeasibleError`, journaled ``rejected`` through the
  server) and deadline-less jobs stamped with the data-derived
  ``quote x margin`` default;
- `RunGovernor` decision semantics on synthetic histories: the
  evidence floor, the in_band slope guard (hold, once per iteration),
  early-stop refund accounting (state + counter), drain-ETA budget
  tuning, drained/idle iteration shortening, and `finalize` folding
  the stop into the run verdict;
- the live governor and the killed-run post-mortem judge the SAME
  rolling window (`assess(window=GOVERN_WINDOW)`);
- the history-quoted balance band (`parallel.migrate`): derived from
  the median measured dist imbalance when a PERF_DB is named, else
  the 1.5 default.
"""

import json
import os

import pytest

from parmmg_tpu import control
from parmmg_tpu.obs import health, history
from parmmg_tpu.obs import metrics as obs_metrics
from parmmg_tpu.service.admission import SloPolicy, resolve_slo_margin
from parmmg_tpu.service.jobs import JobSpec, SloInfeasibleError


def _rec(it, sw, nsplit=0, ncollapse=0, nswap=0, ne=1000,
         n_unique=500, n_active=100, capped=False, **kw):
    r = dict(iter=it, sweep=sw, nsplit=nsplit, ncollapse=ncollapse,
             nswap=nswap, nmoved=0, ne=ne, np=300, n_unique=n_unique,
             n_active=n_active, capped=capped)
    r.update(kw)
    return r


def _churn_tail(it=0, n=6, in_band=0.5, start=0):
    """n sweeps of sustained split<->collapse thrash (oscillating
    under the rolling assess) at a FLAT in_band."""
    out = []
    for k in range(n):
        big, small = (100, 5) if k % 2 == 0 else (8, 95)
        out.append(_rec(it, start + k, nsplit=big, ncollapse=small,
                        in_band=in_band))
    return out


def _db_rec(rung, metric, value, platform="cpu", **kw):
    r = dict(rung=rung, metric=metric, value=value, platform=platform)
    r.update(kw)
    return r


# ---------------------------------------------------------------------------
# quote: the gate's baseline selection, verbatim
# ---------------------------------------------------------------------------


def test_quote_rolling_median_shares_gate_selection():
    db = [_db_rec("serve-tiny", "jobs_per_min", 100.0 + i,
                  run_id=f"r{i}", wall_s=3.0 + i)
          for i in range(12)]
    q = history.quote(db, "cpu", "serve-tiny", window=8)
    jm = q["jobs_per_min"]
    # only the LAST 8 records quote — same [-window:] the gate gates on
    assert jm["n"] == 8
    assert jm["value"] == pytest.approx(
        history._median([104.0 + i for i in range(8)]))
    base = history.baseline_records(
        db, ("cpu", "serve-tiny", "jobs_per_min"), window=8)
    assert [r["value"] for r in base] == [104.0 + i for i in range(8)]


def test_quote_skips_partial_records_like_the_gate():
    db = [
        _db_rec("serve-tiny", "jobs_per_min", 100.0),
        _db_rec("serve-tiny", "jobs_per_min", 9999.0, partial=True),
        _db_rec("serve-tiny", "jobs_per_min", 110.0),
    ]
    q = history.quote(db, "cpu", "serve-tiny")
    assert q["jobs_per_min"]["n"] == 2
    assert q["jobs_per_min"]["value"] == pytest.approx(105.0)


def test_quote_rung_fallback_honors_pk_parity():
    db = [
        _db_rec("n6-hsiz0.15", "tets_per_sec", 1000.0),
        _db_rec("n6-hsiz0.15-pk", "tets_per_sec", 5000.0),
    ]
    # unknown non-pk rung degrades to the non-pk (platform, metric)
    # history — never to the Pallas-kernel baseline
    q = history.quote(db, "cpu", "n8-hsiz0.10")
    assert q["tets_per_sec"]["value"] == pytest.approx(1000.0)
    qpk = history.quote(db, "cpu", "n8-hsiz0.10-pk")
    assert qpk["tets_per_sec"]["value"] == pytest.approx(5000.0)


def test_quote_empty_history_returns_empty_dict():
    assert history.quote([], "cpu", "serve-tiny") == {}
    # wrong platform is no history either
    db = [_db_rec("serve-tiny", "jobs_per_min", 100.0, platform="tpu")]
    assert history.quote(db, "cpu", "serve-tiny") == {}


# ---------------------------------------------------------------------------
# SloPolicy: quotes -> admission decisions
# ---------------------------------------------------------------------------


def test_slo_policy_quote_and_derived_deadline():
    db = [_db_rec("serve-t", "jobs_per_min", v, wall_s=3.0)
          for v in (140.0, 150.0, 145.0)]
    pol = SloPolicy(db, platform="cpu", margin=4.0)
    q = pol.quote("t")
    assert q["baseline_n"] == 3
    assert q["latency_s"] == pytest.approx(60.0 / 145.0, abs=1e-3)
    spec = pol.admit(JobSpec(job_id="a", inmesh="x.mesh"), "t")
    assert spec.deadline_s == pytest.approx(q["latency_s"] * 4.0,
                                            abs=1e-3)


def test_slo_derived_deadline_adds_cold_start_allowance():
    # the quote is WARMED throughput — a recorded warmup_s must ride
    # the derived default so a cold class (solo run, post-restart
    # replay before warmup) doesn't kill deadline-less jobs on its
    # own stamp; the explicit-deadline refusal threshold stays the
    # raw latency (infeasible even warm)
    db = [_db_rec("serve-t", "jobs_per_min", 60.0, warmup_s=50.0)]
    pol = SloPolicy(db, platform="cpu", margin=4.0)
    q = pol.quote("t")
    assert q["warmup_s"] == pytest.approx(50.0)
    spec = pol.admit(JobSpec(job_id="a", inmesh="x.mesh"), "t")
    assert spec.deadline_s == pytest.approx(1.0 * 4.0 + 50.0, abs=1e-3)
    with pytest.raises(SloInfeasibleError):
        pol.admit(JobSpec(job_id="b", inmesh="x.mesh",
                          deadline_s=0.5), "t")


def test_slo_policy_refuses_infeasible_deadline_typed():
    db = [_db_rec("serve-t", "jobs_per_min", 60.0)]  # 1 s/job quote
    pol = SloPolicy(db, platform="cpu", margin=4.0)
    with pytest.raises(SloInfeasibleError) as ei:
        pol.admit(JobSpec(job_id="a", inmesh="x.mesh",
                          deadline_s=0.25), "t")
    err = ei.value
    assert err.code == "slo-infeasible" and not err.transient
    doc = err.doc()
    assert doc["quoted_s"] == pytest.approx(1.0)
    assert doc["deadline_s"] == 0.25
    assert doc["size_class"] == "t" and doc["baseline_n"] == 1
    # a feasible explicit deadline passes through untouched
    ok = pol.admit(JobSpec(job_id="b", inmesh="x.mesh",
                           deadline_s=30.0), "t")
    assert ok.deadline_s == 30.0


def test_slo_policy_no_history_passes_through():
    pol = SloPolicy([], platform="cpu")
    assert pol.quote("t") is None
    spec = JobSpec(job_id="a", inmesh="x.mesh", deadline_s=0.001)
    assert pol.admit(spec, "t") is spec


def test_slo_margin_env_override(monkeypatch):
    monkeypatch.delenv("PMMGTPU_SLO_MARGIN", raising=False)
    assert resolve_slo_margin() == 4.0
    monkeypatch.setenv("PMMGTPU_SLO_MARGIN", "2.5")
    assert resolve_slo_margin() == 2.5
    assert resolve_slo_margin(6.0) == 6.0


def test_server_submit_journals_slo_refusal(tmp_path):
    from parmmg_tpu.io import ckpt_store, medit
    from parmmg_tpu.service import JobServer, SizeClass
    from parmmg_tpu.utils.gen import unit_cube_mesh

    obs_metrics.registry().reset()
    tiny = SizeClass("t", pcap=256, tcap=1024, fcap=256, ecap=256)
    ckpt_store.memory_bucket("m25-slo").clear()
    db = [_db_rec("serve-t", "jobs_per_min", 60.0)]
    srv = JobServer(ckpt_store.make_store("mem://m25-slo", None),
                    classes=(tiny,),
                    slo=SloPolicy(db, platform="cpu", margin=4.0))
    inmesh = str(tmp_path / "cube.mesh")
    medit.save_mesh(unit_cube_mesh(2), inmesh)
    with pytest.raises(SloInfeasibleError):
        srv.submit(JobSpec(job_id="bad", inmesh=inmesh,
                           deadline_s=0.01))
    doc = srv.journal.load("bad")
    assert doc["state"] == "rejected"
    assert doc["error"]["code"] == "slo-infeasible"
    c = obs_metrics.registry().counter("serve/refused_slo_infeasible")
    assert c.value == 1
    # the deadline-less job is admitted with the derived default
    rec = srv.submit(JobSpec(job_id="ok", inmesh=inmesh))
    assert rec["spec"]["deadline_s"] == pytest.approx(4.0, abs=1e-3)


# ---------------------------------------------------------------------------
# RunGovernor: decisions on synthetic histories
# ---------------------------------------------------------------------------


def _governor(**kw):
    kw.setdefault("window", health.GOVERN_WINDOW)
    kw.setdefault("min_slope", control.IN_BAND_SLOPE_MIN)
    return control.RunGovernor(**kw)


def test_governor_needs_evidence_before_stopping():
    gov = _governor()
    hist = _churn_tail(n=3)
    d = gov.check_sweep(hist, it=0, sweep=2, budget=30)
    assert d["action"] is None and gov.stop_info is None


def test_governor_early_stops_oscillation_with_refund():
    obs_metrics.registry().reset()
    gov = _governor()
    hist = _churn_tail(n=6)
    d = gov.check_sweep(hist, it=0, sweep=5, budget=30)
    assert d["action"] == "early_stop"
    assert d["verdict"] == "oscillating"
    assert d["refunded"] == 30 - 6
    assert gov.refunded == 24
    assert gov.stop_info["verdict"] == "oscillating"
    c = obs_metrics.registry().counter("control/refunded_sweeps")
    assert c.value == 24


def test_governor_slope_guard_holds_improving_run():
    gov = _governor()
    # same churn, but in_band still climbing 5%/sweep: REFUSE the stop
    hist = [dict(r, in_band=0.3 + 0.05 * k)
            for k, r in enumerate(_churn_tail(n=6))]
    d = gov.check_sweep(hist, it=0, sweep=5, budget=30)
    assert d["action"] == "hold"
    assert gov.stop_info is None and gov.refunded == 0
    # the hold is emitted once per iteration, then goes quiet
    d2 = gov.check_sweep(hist, it=0, sweep=5, budget=30)
    assert d2["action"] is None
    assert [x["action"] for x in gov.decisions] == ["hold"]


def test_governor_never_stops_healthy_decay():
    gov = _governor()
    # cleanly decaying ops: the rolling verdict is budget_exhausted
    # (never oscillating/stalled), so no stop can fire
    hist = [_rec(0, k, nsplit=max(400 - 120 * k, 1), n_active=0,
                 in_band=0.5)
            for k in range(6)]
    d = gov.check_sweep(hist, it=0, sweep=5, budget=30)
    assert d["action"] != "early_stop"
    assert gov.stop_info is None


def test_governor_tunes_budget_from_drain_eta():
    obs_metrics.registry().reset()
    gov = _governor()
    # frontier draining linearly: 0.8 -> 0.2 projects empty in ~1 sweep
    hist = [_rec(0, k, nsplit=300 - 60 * k,
                 n_active=400 - 100 * k, in_band=0.5)
            for k in range(4)]
    d = gov.check_sweep(hist, it=0, sweep=3, budget=30)
    assert d["action"] == "tune_budget"
    assert d["budget"] < 30 and d["budget"] >= 4
    assert gov.refunded == 30 - d["budget"]


def test_governor_iteration_shortens_after_stop_and_on_drain():
    gov = _governor()
    gov.stop_info = dict(verdict="oscillating", reason="x", it=0,
                         sweep=6, refunded_sweeps=10)
    assert gov.check_iteration([], it=0, niter=3) is True
    assert gov.decisions[-1]["action"] == "shorten_niter"

    gov2 = _governor()
    drained = [_rec(1, 0, nsplit=5, n_active=100),
               _rec(1, 1, n_active=0, skipped=True)]
    assert gov2.check_iteration(drained, it=1, niter=3) is True

    gov3 = _governor()
    idle = [_rec(0, 0), _rec(0, 1)]
    assert gov3.check_iteration(idle, it=0, niter=3) is True

    # the LAST iteration never needs shortening
    gov4 = _governor()
    gov4.stop_info = gov.stop_info
    assert gov4.check_iteration([], it=2, niter=3) is False

    # active work continues
    gov5 = _governor()
    busy = [_rec(0, 0, nsplit=50, n_active=200)]
    assert gov5.check_iteration(busy, it=0, niter=3) is False


def test_governor_finalize_folds_stop_into_verdict():
    gov = _governor()
    hist = _churn_tail(n=6)
    gov.check_sweep(hist, it=0, sweep=5, budget=30)
    v = gov.finalize(dict(verdict="budget_exhausted", reason="budget"))
    assert v["verdict"] == "oscillating"
    assert v["reason"].startswith("governor early stop:")
    assert v["early_stop"] is True
    assert v["control"]["refunded_sweeps"] == 24
    assert v["control"]["window"] == gov.window
    # no stop: the verdict passes through, control block still rides
    gov2 = _governor()
    v2 = gov2.finalize(dict(verdict="converged", reason="ok"))
    assert v2["verdict"] == "converged" and "early_stop" not in v2
    assert v2["control"]["decisions"] == 0


def test_governor_and_postmortem_share_the_rolling_window():
    # one big ancient drop, then a WHOLE governor window flat at the
    # same ops: the full history still reads "decaying" off that first
    # sweep (budget_exhausted), the rolling window reads the flatline
    # for what it is (stalled) — and the live governor stops on the
    # SAME windowed judgment the killed-run re-assessment would make
    hist = [_rec(0, 0, nsplit=1000, in_band=0.5)] + [
        _rec(0, 1 + k, nsplit=100, in_band=0.5)
        for k in range(health.GOVERN_WINDOW + 2)
    ]
    full = health.assess(hist, max_sweeps=None)
    rolled = health.assess(hist, max_sweeps=None,
                           window=health.GOVERN_WINDOW)
    assert full["verdict"] == "budget_exhausted"
    assert rolled["verdict"] == "stalled"
    assert rolled["window"] == health.GOVERN_WINDOW
    gov = _governor()
    d = gov.check_sweep(hist, it=0, sweep=len(hist) - 1, budget=30)
    assert d["action"] == "early_stop" and d["verdict"] == "stalled"


def test_in_band_slope():
    assert health.in_band_slope([]) is None
    assert health.in_band_slope([_rec(0, 0, in_band=0.5)]) is None
    hist = [_rec(0, k, in_band=0.2 + 0.1 * k) for k in range(5)]
    assert health.in_band_slope(hist) == pytest.approx(0.1)
    assert health.in_band_slope(hist, window=2) == pytest.approx(0.1)


def test_resolve_governor_env_and_option(monkeypatch):
    class Opts:
        govern = None
        converge_frac = 0.01

    monkeypatch.delenv(control.GOVERN_ENV, raising=False)
    assert control.resolve_governor(Opts()) is None
    monkeypatch.setenv(control.GOVERN_ENV, "1")
    gov = control.resolve_governor(Opts())
    assert gov is not None and gov.converge_frac == 0.01
    monkeypatch.setenv(control.GOVERN_ENV, "0")
    assert control.resolve_governor(Opts()) is None
    # the option beats the env in both directions
    on = Opts()
    on.govern = True
    assert control.resolve_governor(on) is not None
    monkeypatch.setenv(control.GOVERN_ENV, "1")
    off = Opts()
    off.govern = False
    assert control.resolve_governor(off) is None


def test_governor_window_env_override(monkeypatch):
    monkeypatch.setenv("PMMGTPU_GOVERN_WINDOW", "5")
    monkeypatch.setenv("PMMGTPU_GOVERN_SLOPE", "0.02")
    gov = control.RunGovernor()
    assert gov.window == 5 and gov.min_slope == 0.02


# ---------------------------------------------------------------------------
# history-quoted balance band (parallel.migrate)
# ---------------------------------------------------------------------------


def test_balance_band_quoted_from_history(tmp_path, monkeypatch):
    from parmmg_tpu.parallel import migrate

    class Opts:
        balance_band = None

    db = tmp_path / "db.jsonl"
    rows = [_db_rec("dist-p2", "tets_per_sec_distributed", 500.0,
                    imbalance=imb, wall_s=30.0)
            for imb in (1.30, 1.20, 1.40)]
    db.write_text("".join(json.dumps(r) + "\n" for r in rows))
    monkeypatch.delenv("PMMGTPU_BALANCE_BAND", raising=False)
    monkeypatch.setenv(migrate.BALANCE_DB_ENV, str(db))
    migrate._BAND_CACHE.clear()
    band = migrate.resolve_balance_band(Opts())
    assert band == pytest.approx(1.25 * 1.30)
    # explicit env band still wins over the quote
    monkeypatch.setenv("PMMGTPU_BALANCE_BAND", "1.9")
    assert migrate.resolve_balance_band(Opts()) == 1.9


def test_balance_band_falls_back_without_imbalance(tmp_path,
                                                   monkeypatch):
    from parmmg_tpu.parallel import migrate

    class Opts:
        balance_band = None

    monkeypatch.delenv("PMMGTPU_BALANCE_BAND", raising=False)
    # no db named: the conservative default
    monkeypatch.delenv(migrate.BALANCE_DB_ENV, raising=False)
    migrate._BAND_CACHE.clear()
    assert migrate.resolve_balance_band(Opts()) == \
        migrate.BALANCE_BAND_DEFAULT
    # a db whose dist records carry no imbalance: same fallback
    db = tmp_path / "db.jsonl"
    db.write_text(json.dumps(_db_rec(
        "dist-p2", "tets_per_sec_distributed", 500.0)) + "\n")
    monkeypatch.setenv(migrate.BALANCE_DB_ENV, str(db))
    migrate._BAND_CACHE.clear()
    assert migrate.resolve_balance_band(Opts()) == \
        migrate.BALANCE_BAND_DEFAULT
    # the derived band is cached per (path, platform)
    assert migrate._BAND_CACHE
