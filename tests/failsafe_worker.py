"""Subprocess worker for the kill/resume (simulated preemption) test.

Mirrors conftest's hermetic-CPU environment dance, then runs a small
centralized adaptation with checkpointing under a PARMMG_FAULTS plan
that kills the process (os._exit(failsafe.KILL_EXIT_CODE)) at an
iteration boundary. The parent test asserts the exit code, then resumes
from the checkpoint directory in-process and compares against an
uninterrupted run.

Usage: python failsafe_worker.py <checkpoint_dir>
"""

import os
import sys

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402

# KEEP IN SYNC with test_m13_failsafe.C_OPTS: the resume in the
# parent process must produce a matching options fingerprint.
OPTS = dict(hsiz=0.35, niter=2, max_sweeps=4, hgrad=None,
            polish_sweeps=0)


def main() -> None:
    ckdir = sys.argv[1]
    mesh = unit_cube_mesh(3)
    # the PARMMG_FAULTS env (set by the parent) kills this process at
    # the scheduled iteration boundary — after the checkpoint commit
    adapt(mesh, AdaptOptions(**OPTS), checkpoint_dir=ckdir)
    # reaching here means the fault plan did not fire
    print("worker finished without being killed", flush=True)
    sys.exit(3)


if __name__ == "__main__":
    main()
