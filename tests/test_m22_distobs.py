"""Round 11 — the cross-rank performance observatory (obs.dist).

Synthetic-fixture tests for the pieces a 2-process smoke cannot pin
down numerically: clock-segment parsing, aligned merge under skewed
AND resume-restarted clocks, the straggler-lag/transfer decomposition
math, critical-path attribution, the merged Perfetto trace shift, and
the compile_s capture closing the PR-8 cold-cache caveat. The live
2-rank end-to-end lives in tools/dist_obs_smoke.py (check.sh stage
``dist-obs``).
"""

import json
import os

import pytest

jax = pytest.importorskip("jax")

from parmmg_tpu.obs import dist as obs_dist  # noqa: E402
from parmmg_tpu.obs import report as obs_report  # noqa: E402
from parmmg_tpu.obs import trace as obs_trace  # noqa: E402


def _w(path, recs):
    with open(path, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _clock(rank, t0_us, offset_us=0.0, restart=True, **kw):
    return dict(type="clock", rank=rank, restart=restart,
                t0_us=t0_us, offset_us=offset_us, **kw)


def _span(rank, name, ts_us, dur_us, depth=0, **args):
    return dict(type="span", rank=rank, name=name, ts_us=ts_us,
                dur_us=dur_us, depth=depth, args=args)


# ---------------------------------------------------------------------------
# clock segments + aligned merge
# ---------------------------------------------------------------------------


def test_rank_segments_parse_headers_and_offset_updates(tmp_path):
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=1000.0),
        _clock(0, t0_us=1000.0, restart=False, offset_us=0.0,
               err_us=0.5, rounds=5),
        _span(0, "iteration", 10.0, 100.0, it=0),
    ])
    segs = obs_dist.rank_segments(d)
    assert list(segs) == [0]
    (s,) = segs[0]
    assert s["t0_us"] == 1000.0
    assert s["aligned"] is True
    assert s["rounds"] == 5
    assert len(s["records"]) == 1


def test_aligned_merge_under_skewed_clocks(tmp_path):
    # rank 1's monotonic clock reads 5000us AHEAD of rank 0's for the
    # same world instant -> its offset to rank 0's timebase is -5000.
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=0.0),
        _clock(0, t0_us=0.0, restart=False, offset_us=0.0),
        _span(0, "coll:barrier", 100.0, 10.0, seq=0, tag="t"),
    ])
    _w(os.path.join(d, "events_rank1.jsonl"), [
        _clock(1, t0_us=0.0),
        _clock(1, t0_us=0.0, restart=False, offset_us=-5000.0),
        _span(1, "coll:barrier", 5103.0, 7.0, seq=0, tag="t"),
    ])
    tls = obs_dist.aligned_timelines(d)
    e0 = [r for r in tls[0] if r["name"] == "coll:barrier"][0]
    e1 = [r for r in tls[1] if r["name"] == "coll:barrier"][0]
    # raw timestamps are 5003us apart; aligned they are 3us apart
    assert abs(e1["ats_us"] - e0["ats_us"]) == pytest.approx(3.0)


def test_aligned_merge_across_midfile_clock_restart(tmp_path):
    # a resume appends a FRESH tracer to the same file: new t0, new
    # offset. Aligned timestamps must stay monotone across the seam
    # even though raw ts_us resets to ~0.
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=10_000.0),
        _clock(0, t0_us=10_000.0, restart=False, offset_us=0.0),
        _span(0, "iteration", 100.0, 500.0, it=0),
        # restart: clock origin jumped forward (same machine, later
        # boot of the tracer) and raw ts_us starts over
        _clock(0, t0_us=60_000.0),
        _clock(0, t0_us=60_000.0, restart=False, offset_us=0.0),
        _span(0, "iteration", 5.0, 400.0, it=1),
    ])
    segs = obs_dist.rank_segments(d)
    assert len(segs[0]) == 2
    tls = obs_dist.aligned_timelines(d)
    ats = [r["ats_us"] for r in tls[0]]
    assert ats == sorted(ats), "aligned order must be monotone " \
        "across a mid-file clock restart"
    assert ats[1] == pytest.approx(60_005.0)


def test_legacy_file_without_clock_header_still_loads(tmp_path):
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _span(0, "iteration", 50.0, 10.0, it=0),
    ])
    segs = obs_dist.rank_segments(d)
    (s,) = segs[0]
    assert s["aligned"] is False and s["t0_us"] == 0.0
    tls = obs_dist.aligned_timelines(d)
    assert tls[0][0]["ats_us"] == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# collective decomposition
# ---------------------------------------------------------------------------


def _two_rank_dir(tmp_path):
    """rank 1 enters the barrier 40us late; transfer itself takes
    10us. Aligned clocks (offsets already zero)."""
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=0.0),
        _clock(0, t0_us=0.0, restart=False, offset_us=0.0),
        _span(0, "iteration", 0.0, 200.0, depth=0, it=0),
        _span(0, "phase:wait", 80.0, 60.0, depth=1, it=0),
        _span(0, "coll:barrier", 100.0, 50.0, depth=2, seq=0, tag="x"),
    ])
    _w(os.path.join(d, "events_rank1.jsonl"), [
        _clock(1, t0_us=0.0),
        _clock(1, t0_us=0.0, restart=False, offset_us=0.0),
        _span(1, "iteration", 0.0, 200.0, depth=0, it=0),
        _span(1, "phase:remesh", 10.0, 130.0, depth=1, it=0),
        _span(1, "coll:barrier", 140.0, 10.0, depth=2, seq=0, tag="x"),
    ])
    return d


def test_straggler_lag_vs_transfer_decomposition(tmp_path):
    d = _two_rank_dir(tmp_path)
    tls = obs_dist.aligned_timelines(d)
    (inst,) = obs_dist.collective_instances(tls)
    assert inst["name"] == "coll:barrier"
    assert inst["world"] == 2
    assert inst["straggler"] == 1
    assert inst["lag_us"] == pytest.approx(40.0)   # 140 - 100
    assert inst["transfer_us"] == pytest.approx(10.0)  # 150 - 140
    comm = obs_dist.decompose_collectives(tls)
    ph = comm["phases"]["coll:barrier"]
    assert ph["worst_rank"] == 1
    assert ph["lag_s"] == pytest.approx(40e-6)
    assert ph["transfer_s"] == pytest.approx(10e-6)
    # rank 0 sat 50us inside the barrier; rank 1 arrived 40us late
    assert comm["per_rank"][0]["wait_s"] == pytest.approx(50e-6)
    assert comm["per_rank"][0]["skew_s"] == pytest.approx(0.0)
    assert comm["per_rank"][1]["skew_s"] == pytest.approx(40e-6)


def test_collectives_matched_by_seq_not_wallclock(tmp_path):
    # rank 1 missed seq 0 entirely (e.g. joined late): seq matching
    # must NOT pair rank 0's seq-0 with rank 1's seq-1.
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=0.0),
        _span(0, "coll:barrier", 10.0, 5.0, seq=0),
        _span(0, "coll:barrier", 100.0, 5.0, seq=1),
    ])
    _w(os.path.join(d, "events_rank1.jsonl"), [
        _clock(1, t0_us=0.0),
        _span(1, "coll:barrier", 12.0, 5.0, seq=1),
    ])
    insts = obs_dist.collective_instances(
        obs_dist.aligned_timelines(d)
    )
    worlds = {i["seq"]: i["world"] for i in insts}
    assert worlds == {0: 1, 1: 2}


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------


def test_critical_path_names_gating_rank_and_phase(tmp_path):
    d = _two_rank_dir(tmp_path)
    tls = obs_dist.aligned_timelines(d)
    rows = obs_dist.critical_path(tls)
    assert rows, "expected critical-path rows"
    head = rows[0]
    # the segment up to the barrier is gated by rank 1 (last entrant),
    # which was inside phase:remesh at the segment midpoint (70us)
    assert head["it"] == 0
    assert head["rank"] == 1
    assert head["gate"] == "coll:barrier"
    assert head["phase"] == "phase:remesh"
    assert head["dur_us"] == pytest.approx(140.0)
    # the iteration tail after the barrier exit belongs to someone
    assert rows[-1]["gate"] == "iteration_end"


def test_critical_path_single_rank_degenerates(tmp_path):
    d = str(tmp_path)
    _w(os.path.join(d, "events_rank0.jsonl"), [
        _clock(0, t0_us=0.0),
        _span(0, "iteration", 0.0, 100.0, it=0),
        _span(0, "phase:remesh", 10.0, 80.0, depth=1, it=0),
    ])
    rows = obs_dist.critical_path(obs_dist.aligned_timelines(d))
    assert len(rows) == 1
    assert rows[0]["rank"] == 0
    assert rows[0]["phase"] == "phase:remesh"


# ---------------------------------------------------------------------------
# merged Perfetto trace + render
# ---------------------------------------------------------------------------


def test_merged_trace_applies_clock_shift(tmp_path):
    d = str(tmp_path)
    for rank, (t0, off) in enumerate([(0.0, 0.0), (100.0, -30.0)]):
        doc = dict(
            traceEvents=[
                dict(ph="M", pid=rank, name="process_name",
                     args=dict(name=f"rank{rank}")),
                dict(ph="X", pid=rank, tid=1, name="s", ts=10.0,
                     dur=5.0),
            ],
            clock=dict(rank=rank, t0_us=t0, offset_us=off),
        )
        with open(os.path.join(d, f"trace_rank{rank}.json"),
                  "w") as f:
            json.dump(doc, f)
    out = obs_dist.write_merged_trace(d)
    assert out and out.endswith("trace_merged.json")
    with open(out) as f:
        merged = json.load(f)
    ts = {e["pid"]: e["ts"] for e in merged["traceEvents"]
          if e["ph"] == "X"}
    assert ts[0] == pytest.approx(10.0)
    assert ts[1] == pytest.approx(80.0)  # 10 + 100 - 30
    meta = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == 2 and "ts" not in meta[0]


def test_render_dist_sections(tmp_path):
    d = _two_rank_dir(tmp_path)
    text = obs_report.render_dist(d)
    for want in ("clock alignment", "per-rank aligned timelines",
                 "collective decomposition", "critical path",
                 "coll:barrier", "trace_merged.json"):
        assert want in text, f"missing section {want!r}"
    # no trace_rank*.json fixtures here -> merged trace not written
    assert not os.path.exists(os.path.join(d, "trace_merged.json"))
    doc = obs_report.dist_summary(d)
    assert doc["world"] == 2
    assert doc["collectives"]["phases"]["coll:barrier"]["calls"] == 1


# ---------------------------------------------------------------------------
# real tracer integration: clock headers, chaos rendering unchanged
# ---------------------------------------------------------------------------


def test_tracer_writes_clock_header_and_offset(tmp_path):
    d = str(tmp_path)
    tr = obs_trace.Tracer(d, rank=0)
    with tr.span("iteration", it=0):
        pass
    tr.set_clock_offset(123.5, err_us=2.0, rounds=5)
    tr.flush()
    segs = obs_dist.rank_segments(d)
    (s,) = segs[0]
    assert s["aligned"] is True
    assert s["offset_us"] == pytest.approx(123.5)
    assert s["rounds"] == 5
    assert s["t0_us"] > 0
    # the chrome doc carries the clock for the merged-trace writer
    with open(os.path.join(d, "trace_rank0.json")) as f:
        doc = json.load(f)
    assert doc["clock"]["offset_us"] == pytest.approx(123.5)
    # single-rank timeline loaders must not see clock records
    tl = obs_report.load_timeline(d)
    assert all(r.get("type") != "clock" for r in tl)


def test_resumed_tracer_appends_fresh_clock_segment(tmp_path):
    d = str(tmp_path)
    tr = obs_trace.Tracer(d, rank=0)
    with tr.span("iteration", it=0):
        pass
    tr.flush()
    tr2 = obs_trace.Tracer(d, rank=0)  # resume: same file, appended
    with tr2.span("iteration", it=1):
        pass
    tr2.set_clock_offset(-7.0)
    tr2.flush()
    segs = obs_dist.rank_segments(d)
    assert len(segs[0]) == 2
    assert segs[0][1]["offset_us"] == pytest.approx(-7.0)
    tls = obs_dist.aligned_timelines(d)
    ats = [r["ats_us"] for r in tls[0] if r.get("type") == "span"
           and r["name"] == "iteration"]
    assert ats == sorted(ats)


def test_chaos_report_unchanged_by_clock_records(tmp_path):
    d = str(tmp_path)
    tr = obs_trace.Tracer(d, rank=0)
    tr.event("fault_injected", kind="kill", it=1)
    tr.flush()
    tl = obs_report.load_timeline(d)
    assert tl and tl[0]["name"] == "fault_injected"
    summary = obs_report.chaos_summary(d)
    assert summary["ranks"]
    assert "fault_injected" in obs_report.render_chaos(d)


# ---------------------------------------------------------------------------
# compile_s capture (PR-8 cold-cache caveat)
# ---------------------------------------------------------------------------


def test_compile_s_captured_per_entry_point():
    import jax.numpy as jnp

    from parmmg_tpu.obs import costs as obs_costs
    from parmmg_tpu.obs import metrics as obs_metrics

    obs_metrics.registry().reset()
    col = obs_costs.CostCollector()
    fn = jax.jit(lambda x: jnp.sin(x) * 2.0)
    col.capture("unit_sin", fn, (jnp.ones((8,)),))
    total = col.total_compile_s()
    assert total > 0.0, "lower+compile wall must be recorded"
    g = obs_metrics.registry().gauge("compile_s/unit_sin")
    assert g.value == pytest.approx(total, rel=1e-6)
    # a second shape variant accumulates
    col.capture("unit_sin", fn, (jnp.ones((16,)),))
    assert col.total_compile_s() > total
    obs_metrics.registry().reset()
