"""M0 tests: Medit I/O, mesh core, adjacency, edges, quality, compaction."""

import numpy as np
import pytest

import jax.numpy as jnp

from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.core.mesh import FACE_VERTS, Mesh, compact, tet_volumes
from parmmg_tpu.io import medit
from parmmg_tpu.ops import quality


def load_cube(cube_mesh_path, cube_met_path=None):
    return medit.load_mesh(cube_mesh_path, cube_met_path, dtype=jnp.float64)


def test_read_cube(cube_mesh_path):
    raw = medit.read_mesh(cube_mesh_path)
    assert raw.verts.shape == (12, 3)
    assert raw.tets.shape == (12, 4)
    assert raw.trias.shape[0] > 0
    assert raw.tets.min() == 0 and raw.tets.max() == 11


def test_read_sol(cube_met_path):
    vals, types = medit.read_sol(cube_met_path)
    assert types == [medit.SOL_SCALAR]
    assert vals.shape == (12, 1)
    assert np.allclose(vals, 0.5)


def test_roundtrip(tmp_path, cube_mesh_path, cube_met_path):
    m = load_cube(cube_mesh_path, cube_met_path)
    out = tmp_path / "out.mesh"
    medit.save_mesh(m, str(out))
    raw2 = medit.read_mesh(str(out))
    raw1 = medit.read_mesh(cube_mesh_path)
    np.testing.assert_allclose(raw1.verts, raw2.verts)
    np.testing.assert_array_equal(raw1.tets, raw2.tets)
    np.testing.assert_array_equal(raw1.trefs, raw2.trefs)
    np.testing.assert_array_equal(raw1.trias, raw2.trias)


def test_volumes_positive(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    vol = np.asarray(tet_volumes(m))
    tm = np.asarray(m.tmask)
    assert (vol[tm] > 0).all()
    # cube example covers the unit cube
    assert np.isclose(vol[tm].sum(), 1.0)


def brute_adjacency(tets):
    """O(n^2)-ish reference adjacency via dict."""
    faces = {}
    nt = len(tets)
    adja = -np.ones((nt, 4), np.int64)
    for t in range(nt):
        for f in range(4):
            key = tuple(sorted(tets[t, FACE_VERTS[f]]))
            if key in faces:
                t2, f2 = faces.pop(key)
                adja[t, f] = 4 * t2 + f2
                adja[t2, f2] = 4 * t + f
            else:
                faces[key] = (t, f)
    return adja


def test_adjacency_matches_bruteforce(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    m = adjacency.build_adjacency(m)
    tm = np.asarray(m.tmask)
    tets = np.asarray(m.tet)[tm]
    expect = brute_adjacency(tets)
    got = np.asarray(m.adja)[tm]
    np.testing.assert_array_equal(got, expect)


def test_adjacency_ignores_dead_slots(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    # kill one tet; its neighbors should become boundary faces
    tmask = np.asarray(m.tmask).copy()
    live = np.nonzero(tmask)[0]
    kill = live[3]
    tmask[kill] = False
    m2 = m.replace(tmask=jnp.asarray(tmask))
    m2 = adjacency.build_adjacency(m2)
    adja = np.asarray(m2.adja)
    assert (adja[kill] == -1).all()
    assert not np.any(adja // 4 == kill)


def test_unique_edges(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    edges, emask, t2e, n_unique = adjacency.unique_edges(m, ecap=200)
    em = np.asarray(emask)
    e = np.asarray(edges)[em]
    # brute force unique edges
    tets = np.asarray(m.tet)[np.asarray(m.tmask)]
    from parmmg_tpu.core.mesh import EDGE_VERTS

    s = set()
    for t in tets:
        for a, b in t[EDGE_VERTS]:
            s.add((min(a, b), max(a, b)))
    got = set(map(tuple, e))
    assert got == s
    assert int(n_unique) == len(s)
    # tet2edge maps back to correct pairs
    t2e_np = np.asarray(t2e)
    tm = np.asarray(m.tmask)
    for t in np.nonzero(tm)[0]:
        for k, (a, b) in enumerate(np.asarray(m.tet)[t][EDGE_VERTS]):
            eid = t2e_np[t, k]
            assert eid >= 0
            assert tuple(np.asarray(edges)[eid]) == (min(a, b), max(a, b))


def test_quality_unit(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    q = np.asarray(quality.tet_quality(m))
    tm = np.asarray(m.tmask)
    assert (q[tm] > 0.0).all() and (q[tm] <= 1.0).all()


def test_quality_regular_tet_is_one():
    # regular tetrahedron
    verts = np.array(
        [
            [1, 1, 1],
            [1, -1, -1],
            [-1, -1, 1],
            [-1, 1, -1],
        ],
        np.float64,
    )
    m = Mesh.from_numpy(verts, np.array([[0, 1, 2, 3]]), dtype=jnp.float64)
    q = float(quality.tet_quality(m)[0])
    assert q == pytest.approx(1.0, rel=1e-12)
    # aniso identity metric gives the same score
    met6 = np.tile(np.array([1.0, 0, 0, 1.0, 0, 1.0]), (4, 1))
    m6 = Mesh.from_numpy(
        verts, np.array([[0, 1, 2, 3]]), met=met6, dtype=jnp.float64
    )
    q6 = float(quality.tet_quality(m6)[0])
    assert q6 == pytest.approx(q, rel=1e-10)


def test_quality_histogram(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    h = quality.quality_histogram(m)
    assert int(h.ne) == 12
    assert int(h.counts.sum()) == 12
    assert 0 < float(h.qmin) <= float(h.qavg) <= float(h.qmax) <= 1.0
    s = quality.format_histogram(h)
    assert "12 elements" in s


def test_compact(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    vol0 = np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum()
    tmask = np.asarray(m.tmask).copy()
    live = np.nonzero(tmask)[0]
    tmask[live[::3]] = False  # kill every 3rd tet
    killed_vol = np.asarray(tet_volumes(m))[live[::3]].sum()
    m2 = m.replace(tmask=jnp.asarray(tmask))
    m3 = compact(m2)
    # counts shrank, volumes preserved
    assert int(m3.ntet) == tmask.sum()
    vol3 = np.asarray(tet_volumes(m3))[np.asarray(m3.tmask)].sum()
    assert np.isclose(vol3, vol0 - killed_vol)
    # valid slots are a prefix
    tm3 = np.asarray(m3.tmask)
    assert tm3[: tmask.sum()].all() and not tm3[tmask.sum():].any()
    # triangles still reference live vertices with same coordinates
    d = m3.to_numpy()
    assert d["trias"].max() < len(d["verts"])


def test_distributed_wave_read(wave_shard_paths):
    raw = medit.read_mesh(wave_shard_paths[0])
    assert raw.face_comms is not None
    ncomm = len(raw.face_comms)
    assert ncomm >= 1
    for color, loc, glob in raw.face_comms:
        assert 0 <= color < 4
        assert len(loc) == len(glob)
        assert loc.min() >= 0 and loc.max() < len(raw.trias)


def test_distributed_roundtrip(tmp_path, wave_shard_paths):
    raw = medit.read_mesh(wave_shard_paths[1])
    m = medit.raw_to_mesh(raw)
    out = tmp_path / "wave.out.mesh"
    medit.save_mesh(m, str(out), face_comms=raw.face_comms)
    raw2 = medit.read_mesh(str(out))
    assert raw2.face_comms is not None
    assert len(raw2.face_comms) == len(raw.face_comms)
    for (c1, l1, g1), (c2, l2, g2) in zip(raw.face_comms, raw2.face_comms):
        assert c1 == c2
        np.testing.assert_array_equal(l1, l2)
        np.testing.assert_array_equal(g1, g2)


def test_seg_broadcast_matches_scatter_reference():
    """seg_broadcast / seg_broadcast_multi against the scatter+gather
    definition, for every op used in the kernels (add/min/max/or)."""
    import numpy as np

    from parmmg_tpu.ops import common

    rng = np.random.default_rng(5)
    n = 4096
    gid = np.sort(rng.integers(0, n // 3, n)).astype(np.int32)
    newgrp = jnp.asarray(np.concatenate([[True], gid[1:] != gid[:-1]]))
    vals_f = jnp.asarray(rng.normal(size=n).astype(np.float32))
    vals_i = jnp.asarray(rng.integers(0, 16, n).astype(np.int32))

    def ref(v, op, neutral):
        acc = np.full(n, neutral, np.asarray(v).dtype)
        for i in range(n):
            acc[gid[i]] = op(acc[gid[i]], np.asarray(v)[i])
        return acc[gid]

    cases = [
        (vals_f, jnp.add, 0.0, np.add),
        (vals_f, jnp.minimum, np.inf, np.minimum),
        (vals_f, jnp.maximum, -np.inf, np.maximum),
        (vals_i, jnp.bitwise_or, 0, np.bitwise_or),
    ]
    # exercise BOTH lowerings: the platform-native one and the
    # segmented-scan path the TPU uses (forced via the platform probe)
    import unittest.mock as _mock

    for force_scan in (False, True):
        with _mock.patch.object(common, "_split_scatter_cols",
                                lambda: force_scan):
            for v, jop, neu, nop in cases:
                got = np.asarray(common.seg_broadcast(v, newgrp, jop, neu))
                np.testing.assert_allclose(got, ref(v, nop, neu), rtol=1e-4,
                                           atol=1e-6)

    # the fused variant agrees with per-part calls, on both lowerings
    parts = [
        (vals_i, jnp.add, 0),
        (vals_i, jnp.minimum, 2**30),
        (vals_i, jnp.maximum, -1),
    ]
    for force_scan in (False, True):
        with _mock.patch.object(common, "_split_scatter_cols",
                                lambda: force_scan):
            multi = common.seg_broadcast_multi(newgrp, parts)
            for got, (v, op, neu) in zip(multi, parts):
                np.testing.assert_array_equal(
                    np.asarray(got),
                    np.asarray(common.seg_broadcast(v, newgrp, op, neu)),
                )

    # single-element groups and one big group are edge cases of the scans
    allnew = jnp.ones(n, bool)
    np.testing.assert_array_equal(
        np.asarray(common.seg_broadcast(vals_i, allnew, jnp.add, 0)),
        np.asarray(vals_i),
    )
    onegrp = jnp.zeros(n, bool).at[0].set(True)
    np.testing.assert_array_equal(
        np.asarray(common.seg_broadcast(vals_i, onegrp, jnp.add, 0)),
        np.full(n, int(np.asarray(vals_i).sum())),
    )
