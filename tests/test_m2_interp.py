"""M2 tests: barycentric coords, point location walk, interpolation.

Mirrors the intent of the reference's location/interpolation CI tests
(`cmake/testing/pmmg_tests.cmake:215-241` field interpolation and
`:598-625` locate scenarios incl. exhaustive fallback), run on device.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parmmg_tpu.core import adjacency
from parmmg_tpu.core.mesh import Mesh
from parmmg_tpu.ops import interp, locate
from parmmg_tpu.utils import gen


@pytest.fixture(scope="module")
def cube8():
    return gen.unit_cube_mesh(8, dtype=jnp.float64, perturb=0.15)


def test_kuhn_mesh_valid():
    m = gen.unit_cube_mesh(4, dtype=jnp.float64)
    from parmmg_tpu.core.mesh import tet_volumes

    vol = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vol > 0).all()
    assert np.isclose(vol.sum(), 1.0)
    # every interior face matched
    adja = np.asarray(m.adja)[np.asarray(m.tmask)]
    nbnd = (adja < 0).sum()
    assert nbnd == 2 * 6 * 4 * 4  # 2 trias per cell face * 6 sides * n^2


def test_barycoords_unit_tet():
    c = jnp.array(
        [[[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1]]], dtype=jnp.float64
    )
    p = jnp.array([[0.25, 0.25, 0.25]])
    lam = locate.tet_barycoords(c, p)
    np.testing.assert_allclose(
        np.asarray(lam)[0], [0.25, 0.25, 0.25, 0.25], atol=1e-14
    )
    # vertex reproduces indicator
    lam = locate.tet_barycoords(c, jnp.array([[0.0, 0.0, 0.0]]))
    np.testing.assert_allclose(np.asarray(lam)[0], [1, 0, 0, 0], atol=1e-14)
    # outside: negative coordinate on the far side
    lam = locate.tet_barycoords(c, jnp.array([[-0.5, 0.2, 0.2]]))
    assert np.asarray(lam)[0].min() < 0


def test_walk_locates_interior_points(cube8):
    rng = np.random.default_rng(3)
    pts = jnp.asarray(rng.uniform(0.05, 0.95, (500, 3)))
    res = locate.locate_points(cube8, pts)
    assert bool(jnp.all(res.found))
    # containing tet reproduces the point from its barycoords
    c = cube8.vert[cube8.tet[res.tet]]
    rec = jnp.einsum("qk,qkd->qd", res.bary, c)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(pts), atol=1e-10)


def test_exhaustive_fallback_outside_point(cube8):
    pts = jnp.asarray([[1.5, 0.5, 0.5], [0.5, 0.5, 0.5]])
    res = locate.locate_points(cube8, pts)
    # outside point not walkable but gets a closest element with clamped
    # simplex coords (reference closest-point fallback, barycoord_pmmg.c:324)
    assert not bool(res.found[0])
    assert bool(res.found[1])
    lam = np.asarray(res.bary)
    assert (lam >= 0).all()
    np.testing.assert_allclose(lam.sum(1), 1.0, atol=1e-12)


def test_interp_linear_field_exact(cube8):
    # P1 interpolation reproduces affine fields exactly
    a = np.array([0.3, -1.2, 2.0])
    b = 0.7
    v = np.asarray(cube8.vert)
    ls = (v @ a + b)[:, None]
    old = cube8.replace(ls=jnp.asarray(ls))
    rng = np.random.default_rng(7)
    pts = jnp.asarray(rng.uniform(0.02, 0.98, (300, 3)))
    res = locate.locate_points(old, pts)
    _, ls_q, _, _ = interp.interp_at(old, res.tet, res.bary)
    expect = np.asarray(pts) @ a + b
    np.testing.assert_allclose(np.asarray(ls_q)[:, 0], expect, atol=1e-10)


def test_interp_constant_metric_iso(cube8):
    old = cube8.replace(met=jnp.full((cube8.pcap, 1), 0.37, jnp.float64))
    pts = jnp.asarray(np.random.default_rng(0).uniform(0.1, 0.9, (50, 3)))
    res = locate.locate_points(old, pts)
    met_q, _, _, _ = interp.interp_at(old, res.tet, res.bary)
    np.testing.assert_allclose(np.asarray(met_q), 0.37, atol=1e-12)


def test_interp_constant_metric_aniso(cube8):
    m6 = np.array([4.0, 0.5, 0.0, 9.0, 0.0, 16.0])
    met = np.tile(m6, (cube8.pcap, 1))
    old = cube8.replace(met=jnp.asarray(met))
    pts = jnp.asarray(np.random.default_rng(1).uniform(0.1, 0.9, (50, 3)))
    res = locate.locate_points(old, pts)
    met_q, _, _, _ = interp.interp_at(old, res.tet, res.bary)
    np.testing.assert_allclose(
        np.asarray(met_q), np.tile(m6, (50, 1)), atol=1e-9
    )


def test_interp_mesh_driver(cube8):
    """interp_metrics_and_fields maps new-mesh vertices through the old
    snapshot: smooth iso metric field interpolates within field bounds."""
    v = np.asarray(cube8.vert)
    h = (0.05 + 0.1 * v[:, 0] + 0.05 * v[:, 1])[:, None]
    old = cube8.replace(met=jnp.asarray(h))
    new = gen.unit_cube_mesh(5, dtype=jnp.float64, perturb=0.1)
    new, res = interp.interp_metrics_and_fields(new, old)
    got = np.asarray(new.met)[np.asarray(new.vmask)]
    assert got.min() >= 0.05 - 1e-9
    assert got.max() <= 0.2 + 1e-9
    # interior points located strictly
    assert np.asarray(res.found).mean() > 0.9


def test_required_vertices_keep_values(cube8):
    from parmmg_tpu.core import tags

    new = gen.unit_cube_mesh(5, dtype=jnp.float64)
    vtag = np.asarray(new.vtag).copy()
    vtag[7] |= tags.REQUIRED
    met0 = np.asarray(new.met).copy()
    met0[7] = 123.0
    new = new.replace(vtag=jnp.asarray(vtag), met=jnp.asarray(met0))
    old = cube8.replace(met=jnp.full((cube8.pcap, 1), 0.5, jnp.float64))
    new, _ = interp.interp_metrics_and_fields(new, old)
    met = np.asarray(new.met)
    assert met[7, 0] == 123.0
    assert np.allclose(met[:7, 0], 0.5)


def test_locate_after_adapt(cube_mesh_path):
    """End-to-end M2: adapt the reference cube then re-interpolate its
    metric from the pre-adaptation snapshot (the parmmglib1 inner-loop
    pattern, reference src/libparmmg1.c:829)."""
    from parmmg_tpu.io import medit
    from parmmg_tpu.models import adapt as adapt_mod

    old = medit.load_mesh(cube_mesh_path, dtype=jnp.float64)
    old = adjacency.build_adjacency(old)
    old = old.replace(met=jnp.full((old.pcap, 1), 0.3, jnp.float64))

    opts = adapt_mod.AdaptOptions(niter=1, max_sweeps=4, hsiz=0.3)
    new, _ = adapt_mod.adapt(old, opts)
    new, res = interp.interp_metrics_and_fields(new, old)
    met = np.asarray(new.met)[np.asarray(new.vmask)]
    np.testing.assert_allclose(met, 0.3, atol=1e-9)


def test_surface_locate_and_interp_beats_volume_path():
    """`PMMG_locatePointBdy` role (reference `src/locate_pmmg.c:587`):
    interpolating a *surface* metric for boundary points from the old
    boundary triangulation must not be polluted by interior values the
    way the volume walk is."""
    from parmmg_tpu.core import tags
    from parmmg_tpu.ops import analysis

    old = gen.unit_cube_mesh(4, dtype=jnp.float64)
    old = analysis.mark_boundary(old)
    # metric: 0.1 on the boundary, 0.4 inside
    bdy_v = (np.asarray(old.vtag) & tags.BDY) != 0
    met = np.full((old.pcap, 1), 0.4)
    met[bdy_v] = 0.1
    old = old.replace(met=jnp.asarray(met), met_set=True)

    # query points: tria barycenters nudged INWARD — the situation on a
    # curved surface, where a refined boundary vertex lies inside the
    # old polyhedral boundary
    smask = analysis.surf_tria_mask(old)
    sm_np = np.asarray(smask)
    tr = np.asarray(old.tria)[sm_np]
    bc = np.asarray(old.vert)[tr].mean(axis=1)
    nrm, _, _ = analysis.tria_normals(old)
    nrm = np.asarray(nrm)[sm_np]
    delta = 0.05
    pts = jnp.asarray(bc - delta * nrm)

    # volume path
    res = locate.locate_points(old, pts)
    met_v, _, _, _ = interp.interp_at(old, res.tet, res.bary)
    # surface path
    bres = locate.bdy_locate(old, smask, pts)
    met_s, _, _, _ = interp.interp_at_tria(old, bres.tria, bres.bary)

    err_v = np.abs(np.asarray(met_v)[:, 0] - 0.1)
    err_s = np.abs(np.asarray(met_s)[:, 0] - 0.1)
    assert err_s.max() < 1e-12          # exact: all 3 sources on surface
    assert err_v.max() > 0.01           # volume blends interior 0.4
    # the nearest surface point is the barycenter delta away
    d = np.asarray(bres.dist)
    assert np.allclose(d, delta, atol=1e-6)


def test_interp_dispatch_uses_surface_for_bdy_vertices():
    """interp_metrics_and_fields routes BDY-tagged vertices through the
    boundary triangulation (`src/interpmesh_pmmg.c:535-643` dispatch)."""
    from parmmg_tpu.core import tags
    from parmmg_tpu.ops import analysis

    old = gen.unit_cube_mesh(3, dtype=jnp.float64)
    old = analysis.mark_boundary(old)
    bdy_v = (np.asarray(old.vtag) & tags.BDY) != 0
    met = np.full((old.pcap, 1), 0.4)
    met[bdy_v] = 0.1
    old = old.replace(met=jnp.asarray(met), met_set=True)

    # "new" mesh: same geometry, shifted boundary queries via a finer cube
    new = gen.unit_cube_mesh(5, dtype=jnp.float64)
    new = analysis.mark_boundary(new)
    new, _ = interp.interp_metrics_and_fields(new, old)
    met_n = np.asarray(new.met)[:, 0]
    nb = (np.asarray(new.vtag) & tags.BDY) != 0
    nreq = (np.asarray(new.vtag) & tags.REQUIRED) == 0
    sel = nb & nreq & np.asarray(new.vmask)
    assert np.abs(met_n[sel] - 0.1).max() < 1e-9


def test_interp_stacked_rescue_keeps_surface_values():
    """A boundary vertex whose volume walk fails (nudged outside the old
    shard) must keep its surface-path interpolation — the exhaustive
    volume rescue may not overwrite it with interior-blended values."""
    from parmmg_tpu.core import tags
    from parmmg_tpu.ops import analysis
    from parmmg_tpu.parallel.distribute import split_mesh, unstack_mesh

    mesh = gen.unit_cube_mesh(4, dtype=jnp.float64)
    tm = np.asarray(mesh.tmask)
    bary = np.asarray(mesh.vert)[np.asarray(mesh.tet)].mean(axis=1)
    part = np.where(bary[:, 0] > 0.5, 1, 0)
    part[~tm] = -1
    stacked, _ = split_mesh(mesh, part, 2)
    shards = [analysis.analyze(m) for m in unstack_mesh(stacked)]
    # metric: 0.1 on the true surface, 0.4 inside
    olds = []
    for m in shards:
        bdy = ((np.asarray(m.vtag) & tags.BDY) != 0) & (
            (np.asarray(m.vtag) & tags.PARBDY) == 0
        )
        met = np.full((m.pcap, 1), 0.4)
        met[bdy] = 0.1
        olds.append(m.replace(met=jnp.asarray(met), met_set=True))
    old = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *olds)

    # new = same shards, with one true-surface vertex of shard 0 nudged
    # OUTSIDE the old domain so its walk cannot succeed
    news = []
    moved = None
    for s, m in enumerate(olds):
        v = np.asarray(m.vert).copy()
        if s == 0:
            vt = np.asarray(m.vtag)
            vm = np.asarray(m.vmask)
            cand = np.nonzero(
                vm & ((vt & tags.BDY) != 0) & ((vt & tags.PARBDY) == 0)
                & ((vt & tags.REQUIRED) == 0)
            )[0]
            moved = cand[0]
            # push along the outward normal of the unit cube surface
            p = v[moved]
            outward = np.where(p > 0.5, 1.0, np.where(p < 0.5, -1.0, 0.0))
            on_face = (np.abs(p) < 1e-9) | (np.abs(p - 1.0) < 1e-9)
            v[moved] = p + 0.05 * outward * on_face
        news.append(m.replace(vert=jnp.asarray(v),
                              met=jnp.asarray(np.ones((m.pcap, 1)))))
    new = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *news)

    from parmmg_tpu.ops import interp

    out = interp.interp_stacked(new, old)
    got = float(np.asarray(out.met)[0, moved, 0])
    # surface value, not the 0.4-blended interior rescue
    assert abs(got - 0.1) < 1e-6, got


def test_bdy_locate_cone_wedge_no_cross_ridge():
    """Near a feature line both sides are equally near, and raw distance
    can hand a query to the tria ACROSS the ridge; with query normals
    the wedge discipline (`PMMG_locatePointInCone/InWedge` role,
    reference src/locate_pmmg.c:209-384) keeps it on its own side.

    Fixture: two square sheets meeting at a 90-degree ridge along the
    x-axis — A horizontal (normal +z), B vertical (normal +y). The query
    sits a hair below plane A right at the ridge (discretization sag),
    geometrically CLOSER to B."""
    import jax.numpy as jnp

    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.ops import locate

    verts = np.array([
        [0, 0, 0], [1, 0, 0],          # ridge
        [0, 0.5, 0], [1, 0.5, 0],      # sheet A (z=0, y>0)
        [0, 0, -0.5], [1, 0, -0.5],    # sheet B (y=0, z<0)
    ], np.float64)
    trias = np.array(
        [[0, 1, 3], [0, 3, 2], [0, 1, 5], [0, 5, 4]], np.int32
    )
    mesh = Mesh.from_numpy(verts, np.zeros((0, 4), np.int32), trias=trias)
    smask = mesh.trmask

    # belongs to A (normal +z) but is nearer to B
    pts = jnp.asarray(np.array([[0.5, 0.0004, -0.001]]), mesh.dtype)
    plain = locate.bdy_locate(mesh, smask, pts, window=8)
    assert int(plain.tria[0]) in (2, 3), "fixture no longer reproduces"

    nq = jnp.asarray(np.array([[0.0, 0.0, 1.0]]), mesh.dtype)
    guided = locate.bdy_locate(mesh, smask, pts, window=8, normals=nq)
    assert int(guided.tria[0]) in (0, 1), (
        "wedge discipline failed to keep the query on its own side"
    )
    # far from the ridge the penalty changes nothing
    far = jnp.asarray(np.array([[0.5, 0.3, 0.002]]), mesh.dtype)
    a = locate.bdy_locate(mesh, smask, far, window=8)
    b = locate.bdy_locate(mesh, smask, far, window=8, normals=nq)
    assert int(a.tria[0]) == int(b.tria[0])
