"""Analytic golden-quality gates (SURVEY §4 implication).

The reference CI passes on exit code (remesh completed + conformity,
`cmake/testing/pmmg_tests.cmake:30-50`); these gates hold the output to
EXTERNAL yardsticks instead: unit-mesh edge-length concentration for a
constant metric, predicted element-count bands, minimum-quality floors,
and surface fidelity against the analytic geometry the mesh discretizes.
The reference binary itself cannot be built here (BASELINE.md: its
Mmg/Metis are ExternalProject downloads, no network egress), so analytic
truths replace golden files.
"""

import numpy as np
import pytest

from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.ops import quality
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube_mesh


@pytest.fixture(scope="module")
def cube_uniform():
    """Unit cube adapted to constant hsiz=0.1 — the adaptation_example0
    CI configuration class (uniform size map)."""
    mesh = unit_cube_mesh(6)
    out, info = adapt(
        mesh, AdaptOptions(hsiz=0.1, niter=2, max_sweeps=10, hgrad=None)
    )
    return out, info


def test_uniform_hsiz_edge_length_concentration(cube_uniform):
    """For a constant metric h, a unit mesh has metric edge lengths
    concentrated in [1/sqrt(2), sqrt(2)] (Mmg's LSHRT/LLONG band): at
    least 90% of edges must land inside, and the mean must sit within
    10% of 1."""
    out, _ = cube_uniform
    m = adjacency.build_adjacency(out)
    edges, emask, _, _ = adjacency.unique_edges(m, int(m.tcap * 1.7) + 64)
    e = np.asarray(edges)[np.asarray(emask)]
    p = np.asarray(out.vert)
    ell = np.linalg.norm(p[e[:, 0]] - p[e[:, 1]], axis=1) / 0.1
    frac_unit = ((ell >= 1 / np.sqrt(2)) & (ell <= np.sqrt(2))).mean()
    assert frac_unit >= 0.90, f"only {frac_unit:.1%} unit edges"
    # refinement overshoots slightly (splits lead, collapses lag): the
    # mean settles a little under 1
    assert 0.80 <= float(ell.mean()) <= 1.25, float(ell.mean())


def test_uniform_hsiz_element_count_band(cube_uniform):
    """Element count must land in the analytic band: a unit cube filled
    with regular tets of edge h contains 6*sqrt(2)/h^3 elements
    (regular-tet volume h^3/(6*sqrt(2))); unstructured packing and the
    refinement overshoot put real meshes within a [0.5, 3]x band."""
    out, _ = cube_uniform
    ne = int(out.ntet)
    ideal = 6.0 * np.sqrt(2.0) / 0.1**3
    assert 0.5 * ideal <= ne <= 3.0 * ideal, (ne, ideal)


def test_uniform_hsiz_quality_floor(cube_uniform):
    """Minimum and mean quality floors for the uniform cube workload —
    the qualhisto gate the reference only prints (quality_pmmg.c:156)."""
    out, _ = cube_uniform
    h = quality.quality_histogram(out)
    assert float(h.qmin) > 0.2, float(h.qmin)
    assert float(h.qavg) > 0.6, float(h.qavg)
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)


def test_flat_faces_stay_flat(cube_uniform):
    """Surface fidelity vs the analytic geometry: every boundary vertex
    of the adapted unit cube must lie exactly on one of the six planes
    (flat faces: hausd controls only curved surfaces, so the gate is
    machine precision scaled)."""
    out, _ = cube_uniform
    vm = np.asarray(out.vmask)
    vt = np.asarray(out.vtag)
    p = np.asarray(out.vert)
    bdy = vm & ((vt & tags.BDY) != 0)
    bp = p[bdy]
    on_face = (np.abs(bp) < 1e-6) | (np.abs(bp - 1.0) < 1e-6)
    assert on_face.any(axis=1).all(), "boundary vertex left the surface"
    # total volume exact to f32 accumulation error
    from parmmg_tpu.core.mesh import tet_volumes

    vol = np.asarray(tet_volumes(out), np.float64)[
        np.asarray(out.tmask)
    ].sum()
    assert vol == pytest.approx(1.0, rel=1e-5), vol


@pytest.mark.slow
def test_bench_scale_quality_gate():
    """Quality floor at the BENCH default workload (cube n=10 ->
    hsiz=0.05, ~94k tets) so kernel perf work cannot silently trade
    output quality — the reference reads its qualhisto at every scale
    (src/quality_pmmg.c:156). Gates the round-2 recorded figures
    (qmin 0.15254, qavg 0.81026) with a little slack."""
    from parmmg_tpu.utils.gen import unit_cube_mesh as ucm

    est = int(12.0 / 0.05**3)
    mesh = ucm(10, tcap=int(est * 1.9), pcap=max(int(est * 0.45), 4096),
               fcap=max(int(est * 0.30), 4096))
    out, _ = adapt(mesh, AdaptOptions(
        niter=1, hsiz=0.05, max_sweeps=12, hgrad=None
    ))
    h = quality.quality_histogram(out)
    ne = int(out.ntet)
    assert ne > 60000, f"workload too small to be the gate: {ne}"
    # the single worst element jitters between equally-valid winner
    # sets (observed 0.141-0.153 across selection-order changes), so the
    # gate reads the histogram like the reference does: a hard floor,
    # a thin worst-bin tail, and the average
    assert float(h.qmin) >= 0.12, f"bench-scale qmin regressed: {h}"
    worst_frac = float(h.counts[0]) / ne
    assert worst_frac <= 1e-4, f"bench-scale quality tail grew: {h}"
    assert float(h.qavg) >= 0.78, f"bench-scale qavg regressed: {h}"


@pytest.mark.slow
def test_large_scale_quality_gate():
    """Quality floor at the BENCH large workload (cube n=12 ->
    hsiz=0.04, ~200k+ tets), so scale/perf work cannot silently trade
    the large-mesh histogram (round-4 verdict: the n=12 record carried
    a known 0.04-class sliver with nothing gating it). Floor: the
    round-5 tree reproducibly lands qmin=0.0725 here (CPU,
    deterministic; the sliver survives polish unchanged at
    polish_sweeps=4 — it needs an insertion, which polish forbids), so
    the floor is set at 0.06: tight enough that the round-4-era
    0.04-class sliver would FAIL, with the tail-mass and average
    asserts carrying the real discipline — the reference itself never
    gates qmin at all, it only prints the histogram
    (src/quality_pmmg.c:156-369)."""
    from parmmg_tpu.utils.gen import unit_cube_mesh as ucm

    est = int(12.0 / 0.04**3)
    mesh = ucm(12, tcap=int(est * 1.9), pcap=max(int(est * 0.45), 4096),
               fcap=max(int(est * 0.30), 4096))
    out, _ = adapt(mesh, AdaptOptions(
        niter=1, hsiz=0.04, max_sweeps=12, hgrad=None
    ))
    h = quality.quality_histogram(out)
    ne = int(out.ntet)
    assert ne > 150000, f"workload too small to be the gate: {ne}"
    assert float(h.qmin) >= 0.06, f"large-scale qmin regressed: {h}"
    worst_frac = float(h.counts[0]) / ne
    assert worst_frac <= 1e-4, f"large-scale quality tail grew: {h}"
    assert float(h.qavg) >= 0.78, f"large-scale qavg regressed: {h}"
    # 0.04-class tail mass (round 6): the r4-era sliver sat in the
    # [0.04, 0.08) class where the 0.2-wide worst bin above cannot see
    # a mass shift — gate the fine-binned cumulative tail so a
    # population of near-slivers cannot hide under a passing qmin.
    # Round-6 tree measures 0 elements below 0.08 and 2 below 0.16 at
    # this workload (qmin 0.0928); the bounds leave generous headroom
    # for selection jitter while still failing a sliver POPULATION.
    h25 = quality.quality_histogram(out, nbins=25)
    fine = np.asarray(h25.counts, np.int64)
    assert int(fine[:2].sum()) <= 5, (
        f"sub-0.08 sliver class repopulated: {fine[:6]}"
    )
    assert int(fine[:4].sum()) <= 1e-3 * ne, (
        f"sub-0.16 tail mass grew: {fine[:6]}"
    )
