"""M3 tests: SFC partitioning, distribution, communicators, halo exchange.

Exercises the distributed path on 8 virtual CPU devices the way the
reference CI exercises MPI with oversubscribed ranks (SURVEY.md §4):
partition the cube, build communicators, verify chkcomm invariants and
collective-reduced quality histograms match the centralized run.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.core.mesh import Mesh
from parmmg_tpu.ops import quality
from parmmg_tpu.parallel import chkcomm, comm, distribute, partition, shard
from parmmg_tpu.utils import gen

NDEV = 8


@pytest.fixture(scope="module")
def dmesh():
    assert jax.device_count() >= NDEV
    return shard.device_mesh(NDEV)


@pytest.fixture(scope="module")
def cube():
    return gen.unit_cube_mesh(6, dtype=jnp.float64, perturb=0.1)


@pytest.fixture(scope="module")
def parts(cube):
    return np.asarray(partition.sfc_partition(cube, NDEV))


@pytest.fixture(scope="module")
def sharded(cube, parts):
    return distribute.split_mesh(cube, parts, NDEV)


def test_partition_balanced(cube, parts):
    tm = np.asarray(cube.tmask)
    assert (parts[tm] >= 0).all() and (parts[tm] < NDEV).all()
    assert (parts[~tm] == -1).all()
    counts = np.bincount(parts[tm], minlength=NDEV)
    ne = tm.sum()
    assert counts.min() >= ne // NDEV - 1
    assert counts.max() <= -(-ne // NDEV) + 1


def test_partition_weighted(cube):
    # heavy weights on one region shift the cuts
    bc = np.asarray(jnp.mean(cube.vert[cube.tet], axis=1))
    w = np.where(bc[:, 0] < 0.5, 10.0, 1.0).astype(np.float32)
    part = np.asarray(
        partition.sfc_partition(cube, 4, weights=jnp.asarray(w))
    )
    tm = np.asarray(cube.tmask)
    wsum = np.array([w[tm][part[tm] == s].sum() for s in range(4)])
    assert wsum.max() / wsum.min() < 1.5


def test_partition_metric_weights(cube):
    """Metric-aware weights (the PMMG_computeWgt role, reference
    src/metis_pmmg.c:280) balance the PREDICTED output elements: under a
    localized-refinement metric the weighted cut gives the refined
    corner fewer tets NOW so shards stay balanced after the splits."""
    import jax.numpy as jnp

    # sharp refinement in one corner: h 10x smaller -> ~1000x density
    hv = np.full(cube.pcap, 0.2, np.float64)
    vert = np.asarray(cube.vert)
    hv[np.linalg.norm(vert - 0.15, axis=1) < 0.3] = 0.02
    # iso metric stores the size h directly (metric_det -> 1/h^6)
    m = cube.replace(met=jnp.asarray(hv[:, None], cube.dtype), met_set=True)
    w = np.asarray(partition.metric_weights(m))
    tm = np.asarray(cube.tmask)
    assert (w[tm] > 0).all() and (w[~tm] == 0).all()
    part = np.asarray(partition.sfc_partition(m, 4, weights=jnp.asarray(w)))
    wsum = np.array([w[tm][part[tm] == s].sum() for s in range(4)])
    # predicted-element balance good...
    assert wsum.max() / wsum.min() < 1.5
    # ...which REQUIRES a skewed tet-count balance (the refined corner
    # holds most of the predicted weight in far fewer current tets)
    counts = np.bincount(part[tm], minlength=4)
    assert counts.max() > 1.5 * counts.min()


def test_split_covers_mesh(cube, parts, sharded):
    stacked, c = sharded
    per = distribute.unstack_mesh(stacked)
    assert sum(int(m.ntet) for m in per) == int(cube.ntet)
    # true-boundary trias partition exactly; interface (PARBDY+NOSURF)
    # trias are extra per-shard materializations
    nreal = 0
    for m in per:
        trtag = np.asarray(m.trtag)[np.asarray(m.trmask)]
        pure_par = ((trtag & tags.PARBDY) != 0) & ((trtag & tags.NOSURF) != 0)
        nreal += int((~pure_par).sum())
    assert nreal == int(cube.ntria)
    # every shard mesh is individually valid
    from parmmg_tpu.utils.conformity import check_mesh

    for m in per:
        rep = check_mesh(m)
        assert rep.ok, str(rep)


def test_parbdy_tags(sharded):
    stacked, c = sharded
    per = distribute.unstack_mesh(stacked)
    l2g = np.asarray(c.l2g)
    # count shards holding each gid
    from collections import Counter

    cnt = Counter()
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        cnt.update(l2g[s][vm].tolist())
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        vt = np.asarray(m.vtag)
        for l in np.nonzero(vm)[0]:
            g = l2g[s, l]
            if cnt[g] > 1:
                assert vt[l] & tags.PARBDY, (s, l, g)
            else:
                assert not (vt[l] & tags.PARBDY)


def test_owner_unique(sharded):
    stacked, c = sharded
    l2g = np.asarray(c.l2g)
    owner = np.asarray(c.owner)
    per = distribute.unstack_mesh(stacked)
    nglob = l2g.max() + 1
    owns = np.zeros(nglob, int)
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        owns[l2g[s][vm & owner[s]]] += 1
    assert (owns == 1).all()


def test_chkcomm_invariants(sharded, dmesh):
    stacked, c = sharded
    st = shard.put_sharded(stacked, dmesh)
    rep = chkcomm.assert_comm_ok(st, c, dmesh)
    assert rep["max_coord_err"] == 0.0


def test_chkcomm_detects_corruption(sharded, dmesh):
    stacked, c = sharded
    # corrupt one interface vertex coordinate on shard 0
    idx0 = np.asarray(c.comm_idx)[0]
    slots = idx0[idx0 >= 0]
    assert len(slots)
    v = np.asarray(stacked.vert).copy()
    v[0, slots[0]] += 0.123
    bad = stacked.replace(vert=jnp.asarray(v))
    rep = chkcomm.check_node_comm(shard.put_sharded(bad, dmesh), c, dmesh)
    assert rep["max_coord_err"] > 0.1


def test_halo_sum_degree(sharded, dmesh):
    """Summing per-copy vertex tet-degrees across shards must reproduce
    the global vertex degree for interface vertices."""
    stacked, c = sharded
    per = distribute.unstack_mesh(stacked)
    l2g = np.asarray(c.l2g)

    def body(blk, comm_blk):
        m = shard._squeeze(blk)
        ci = comm_blk[0]
        deg = jnp.zeros(m.pcap, jnp.int32).at[m.tet.reshape(-1)].add(
            jnp.repeat(m.tmask.astype(jnp.int32), 4), mode="drop"
        )
        tot = comm.halo_sum(deg, ci)
        return tot[None]

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(P(shard.AXIS), P(shard.AXIS)),
            out_specs=P(shard.AXIS),
        )
    )
    tot = np.asarray(
        f(shard.put_sharded(stacked, dmesh), c.comm_idx)
    )
    # global degrees
    nglob = l2g.max() + 1
    gdeg = np.zeros(nglob, int)
    for s, m in enumerate(per):
        tm = np.asarray(m.tmask)
        t = np.asarray(m.tet)[tm]
        np.add.at(gdeg, l2g[s][t].reshape(-1), 1)
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        for l in np.nonzero(vm)[0]:
            assert tot[s, l] == gdeg[l2g[s, l]], (s, l)


def test_halo_min_max_or(sharded, dmesh):
    stacked, c = sharded
    l2g = np.asarray(c.l2g)

    def body(blk, comm_blk, l2g_blk):
        m = shard._squeeze(blk)
        ci = comm_blk[0]
        g = l2g_blk[0]
        sid = jax.lax.axis_index(shard.AXIS).astype(jnp.int32)
        vals = jnp.where(m.vmask, sid, 10**6)
        mn = comm.halo_min(vals, ci)
        bits = jnp.where(m.vmask, jnp.int32(1) << sid, 0)
        ored = comm.halo_or(bits, ci)
        return mn[None], ored[None]

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=dmesh,
            in_specs=(P(shard.AXIS),) * 3,
            out_specs=(P(shard.AXIS),) * 2,
        )
    )
    mn, ored = f(shard.put_sharded(stacked, dmesh), c.comm_idx, c.l2g)
    mn, ored = np.asarray(mn), np.asarray(ored)
    # min over copies = lowest shard id holding the vertex = owner shard
    per = distribute.unstack_mesh(stacked)
    holders = {}
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        for l in np.nonzero(vm)[0]:
            holders.setdefault(l2g[s, l], []).append(s)
    for s, m in enumerate(per):
        vm = np.asarray(m.vmask)
        for l in np.nonzero(vm)[0]:
            hs = holders[l2g[s, l]]
            assert mn[s, l] == min(hs)
            assert ored[s, l] == sum(1 << h for h in set(hs))


def test_sharded_histogram_matches_global(cube, sharded, dmesh):
    stacked, c = sharded
    hg = quality.quality_histogram(cube)
    hs = shard.sharded_quality_histogram(
        shard.put_sharded(stacked, dmesh), dmesh
    )
    assert int(hs.ne) == int(hg.ne)
    np.testing.assert_allclose(float(hs.qmin), float(hg.qmin), rtol=1e-12)
    np.testing.assert_allclose(float(hs.qmax), float(hg.qmax), rtol=1e-12)
    np.testing.assert_allclose(float(hs.qavg), float(hg.qavg), rtol=1e-12)
    np.testing.assert_array_equal(
        np.asarray(hs.counts), np.asarray(hg.counts)
    )
    assert int(hs.worst_shard) >= 0


def test_merge_roundtrip(cube, sharded):
    stacked, c = sharded
    back = distribute.merge_shards(stacked, c)
    assert int(back.ntet) == int(cube.ntet)
    assert int(back.npoin) == int(cube.npoin)
    assert int(back.ntria) == int(cube.ntria)
    # same total volume and quality histogram
    from parmmg_tpu.core.mesh import tet_volumes

    v0 = float(
        np.asarray(tet_volumes(cube))[np.asarray(cube.tmask)].sum()
    )
    v1 = float(
        np.asarray(tet_volumes(back))[np.asarray(back.tmask)].sum()
    )
    np.testing.assert_allclose(v0, v1, rtol=1e-12)
    h0, h1 = quality.quality_histogram(cube), quality.quality_histogram(back)
    np.testing.assert_array_equal(np.asarray(h0.counts), np.asarray(h1.counts))


def test_renumber_sfc(cube):
    m = partition.renumber_sfc(cube)
    assert int(m.ntet) == int(cube.ntet)
    s0 = {tuple(sorted(t)) for t in np.asarray(cube.tet)[np.asarray(cube.tmask)].tolist()}
    s1 = {tuple(sorted(t)) for t in np.asarray(m.tet)[np.asarray(m.tmask)].tolist()}
    assert s0 == s1


def test_parbdybdy_tria_roundtrip():
    """An input boundary tria lying ON an inter-shard interface face must
    come back from split+merge exactly once, with its original tags — no
    duplication, no leaked REQUIRED/NOSURF (reference PMMG_parbdyTria /
    updateTag discipline, src/tag_pmmg.c:646)."""
    from parmmg_tpu.core import adjacency, tags
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(4)
    mesh = adjacency.build_adjacency(mesh)
    # force a partition cut and plant a tria on an interior face that the
    # cut turns into an interface face
    part = np.asarray(jax.device_get(partition.sfc_partition(mesh, 2)))
    adja = np.asarray(mesh.adja)
    tet = np.asarray(mesh.tet)
    tmask = np.asarray(mesh.tmask)
    nb = adja // 4
    ifc = (adja >= 0) & tmask[:, None] & (part[np.maximum(nb, 0)] != part[:, None])
    t, f = np.argwhere(ifc)[0]
    from parmmg_tpu.core.mesh import FACE_VERTS
    tri = tet[t, FACE_VERTS[f]]
    ntr0 = int(mesh.ntria)
    tria = np.asarray(mesh.tria).copy()
    trmask = np.asarray(mesh.trmask).copy()
    trtag = np.asarray(mesh.trtag).copy()
    trref = np.asarray(mesh.trref).copy()
    assert ntr0 < tria.shape[0], "need tria headroom"
    tria[ntr0] = tri
    trmask[ntr0] = True
    trtag[ntr0] = tags.BDY
    trref[ntr0] = 7
    mesh2 = mesh.replace(
        tria=jnp.asarray(tria), trmask=jnp.asarray(trmask),
        trtag=jnp.asarray(trtag), trref=jnp.asarray(trref),
    )
    stacked, comm = distribute.split_mesh(mesh2, part, 2)
    back = distribute.merge_shards(stacked, comm)
    bt = np.asarray(back.tria)[np.asarray(back.trmask)]
    btag = np.asarray(back.trtag)[np.asarray(back.trmask)]
    bref = np.asarray(back.trref)[np.asarray(back.trmask)]
    tgt = set(map(tuple, [sorted(tri.tolist())]))
    hits = [i for i, tr in enumerate(bt) if tuple(sorted(tr.tolist())) in tgt]
    assert len(hits) == 1, f"tria must appear exactly once, got {len(hits)}"
    i = hits[0]
    assert bref[i] == 7
    assert btag[i] & (tags.REQUIRED | tags.NOSURF | tags.PARBDY | tags.PARBDYBDY) == 0
    assert btag[i] & tags.BDY
    assert int(back.ntria) == ntr0 + 1


def test_chkcomm_face_edge_invariants(sharded, dmesh):
    """Face/edge-communicator geometric checks pass on a clean split
    (`PMMG_check_extFaceComm` / `_extEdgeComm` roles, reference
    `src/chkcomm_pmmg.c:1027,605`)."""
    stacked, c = sharded
    st = shard.put_sharded(stacked, dmesh)
    rep = chkcomm.check_face_edge_comm(st, c, dmesh)
    assert rep["face_count_bad"] == 0
    assert rep["max_face_bc_err"] <= 1e-12
    assert rep["max_edge_mid_err"] <= 1e-12
    assert rep["edge_tag_mismatch"] == 0


def test_chkcomm_detects_face_corruption(sharded, dmesh):
    """A displaced interface-tria copy must trip the barycenter check."""
    stacked, c = sharded
    trtag0 = np.asarray(stacked.trtag)[0]
    trmask0 = np.asarray(stacked.trmask)[0]
    pp = (
        ((trtag0 & tags.PARBDY) != 0)
        & ((trtag0 & tags.NOSURF) != 0)
        & ((trtag0 & tags.PARBDYBDY) == 0)
        & trmask0
    )
    f = np.nonzero(pp)[0]
    assert len(f)
    # move one vertex of one interface tria on shard 0 only — its copy on
    # the peer shard keeps the true position, so the two barycenters split
    tri = np.asarray(stacked.tria)[0, f[0]]
    v = np.asarray(stacked.vert).copy()
    v[0, tri[0]] += 0.2
    bad = stacked.replace(vert=jnp.asarray(v))
    rep = chkcomm.check_face_edge_comm(
        shard.put_sharded(bad, dmesh), c, dmesh
    )
    assert rep["max_face_bc_err"] > 0.01
