"""Worker for the 2-process multi-host test (test_m10_multihost.py).

Each process owns 4 of the 8 CPU devices; the shard_map collectives
(halo all_to_all, psum reductions) cross the process boundary over the
coordination service — the same code path that rides DCN between TPU
slices. Run only via the test, which sets the PMMGTPU_* env contract."""

import sys


def adapt_main():
    """End-to-end `adapt_stacked_input` under the multi-controller
    runtime (or single-process with PMMGTPU_SPMD_SWEEPS=1, which runs
    the IDENTICAL SPMD sweep programs — the bit-for-bit reference run).
    niter=2 exercises a full displacement+migration round between the
    iterations; the merged output is digested so the test can compare
    the 2-process and 1-process results exactly. The reference analog is
    its CI matrix running the whole driver under `mpiexec -np {1,2,...}`
    (cmake/testing/pmmg_tests.cmake:30-38)."""
    import hashlib

    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    import numpy as np

    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.ops import quality
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    if multi:
        assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    # identical replicated host prep on every process
    mesh = unit_cube_mesh(4)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)

    out, comm2, info = adapt_stacked_input(
        st, comm,
        DistOptions(hsiz=0.2, niter=2, max_sweeps=4, nparts=8,
                    min_shard_elts=8),
    )
    merged = merge_adapted(out, comm2)
    d = jax.device_get(merged)
    h = hashlib.sha256()
    for name in ("vert", "vmask", "tet", "tmask", "tria", "trmask",
                 "tref", "trref", "vtag", "trtag"):
        h.update(np.ascontiguousarray(np.asarray(getattr(d, name))).tobytes())
    qh = quality.quality_histogram(merged)
    print(
        f"ADAPT_DIGEST {h.hexdigest()} ne={int(qh.ne)} "
        f"qmin={float(qh.qmin):.9f} qavg={float(qh.qavg):.9f} "
        f"status={int(info['status'])}",
        flush=True,
    )


def failsafe_main():
    """Multi-host fail-safe workload for test_m10's kill/resume tests.

    Runs `adapt_stacked_input` (8 shards over however many processes
    the PMMGTPU_* env describes) with a sharded, barrier-committed
    checkpoint directory (PMMGTPU_CKPT_DIR) and the collective watchdog
    armed (PMMGTPU_WATCHDOG seconds). Rank-targeted PARMMG_FAULTS kill
    exactly one worker mid-run; the survivor's next heartbeat converts
    the silent loss into PeerLostError and this worker exits with
    failsafe.PEER_LOST_EXIT_CODE (a resume-refusal exits with
    MISMATCH_EXIT_CODE). A clean run prints ADAPT_DIGEST exactly like
    `adapt_main`, so kill+resume can be compared bit for bit against an
    uninterrupted run."""
    import hashlib
    import os

    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.ops import quality
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    ckdir = os.environ.get("PMMGTPU_CKPT_DIR") or None
    watchdog = float(os.environ.get("PMMGTPU_WATCHDOG", "60"))
    stall = os.environ.get("PMMGTPU_STALL_DUMP")
    if stall:
        # whole-run stall tripwire: dump every thread's Python stack
        # and exit if the run wedges — the collective watchdog bounds
        # the COORDINATION collectives, but a desync inside the mesh
        # collectives themselves can only be diagnosed post-hoc
        import faulthandler

        faulthandler.dump_traceback_later(float(stall), exit=True)

    # identical replicated host prep on every process
    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    opts = DistOptions(
        hsiz=0.32, niter=2, max_sweeps=4, nparts=8, min_shard_elts=8,
        hgrad=None, polish_sweeps=0, checkpoint_dir=ckdir,
        watchdog_timeout=watchdog if multi else None,
        # PMMGTPU_VALIDATE=full arms the collective-lockstep ledger
        # (the chaos --desync rung); default stays the cheap device
        # checks
        validate=os.environ.get("PMMGTPU_VALIDATE") or "basic",
    )
    try:
        out, comm2, info = adapt_stacked_input(st, comm, opts)
    except failsafe.CollectiveDivergenceError as e:
        # the ledger proved a desynced collective schedule — EVERY rank
        # raises this at the same boundary (before PeerLostError: it is
        # a subclass, and the distinct exit code is the point)
        print(f"COLL_DIVERGENCE rank={jax.process_index()}: {e}",
              flush=True)
        os._exit(failsafe.DIVERGENCE_EXIT_CODE)
    except failsafe.PreemptionError as e:
        # graceful SIGTERM path: the harness committed a checkpoint at
        # the iteration boundary before raising — exit through the
        # same code the hard kill uses so the chaos matrix sees one
        # typed preemption family
        print(f"PREEMPTED rank={jax.process_index()}: {e}", flush=True)
        os._exit(failsafe.KILL_EXIT_CODE)
    except failsafe.PeerLostError as e:
        print(f"PEER_LOST rank={jax.process_index()}: {e}", flush=True)
        # the stuck watchdog thread cannot be joined; a clean interpreter
        # shutdown would hang on it — exit hard, the checkpoint survives
        os._exit(failsafe.PEER_LOST_EXIT_CODE)
    except failsafe.CheckpointMismatchError as e:
        print(f"CKPT_MISMATCH rank={jax.process_index()}: {e}",
              flush=True)
        os._exit(failsafe.MISMATCH_EXIT_CODE)
    except failsafe.CheckpointIOError as e:
        # store I/O failed past its bounded retries: typed exit so the
        # harness can tell a durability problem from a crash
        print(f"CKPT_IO rank={jax.process_index()}: {e}", flush=True)
        os._exit(failsafe.CKPT_IO_EXIT_CODE)
    merged = merge_adapted(out, comm2)
    d = jax.device_get(merged)
    h = hashlib.sha256()
    for name in ("vert", "vmask", "tet", "tmask", "tria", "trmask",
                 "tref", "trref", "vtag", "trtag"):
        h.update(np.ascontiguousarray(np.asarray(getattr(d, name))).tobytes())
    qh = quality.quality_histogram(merged)
    print(
        f"ADAPT_DIGEST {h.hexdigest()} ne={int(qh.ne)} "
        f"qmin={float(qh.qmin):.9f} qavg={float(qh.qavg):.9f} "
        f"status={int(info['status'])}",
        flush=True,
    )


def elastic_main():
    """Elastic fleet workload (tools/fleet.py launches this as one rank
    of an autoscaling world — the chaos harness's elastic rung).

    Differences from `failsafe_main`: the shard count follows the
    CURRENT device pool (`nparts = jax.device_count()`, so a reformed
    world re-cuts the checkpoint through `_elastic_recut`), the elastic
    coordinator is armed via the PMMGTPU_ELASTIC_* env the fleet sets,
    and two more typed exits join the family: REFORM_EXIT_CODE (90, a
    survivor of a world-agreed reformation asking to be relaunched)
    and the UnreformableWorldError refusal (88 — the world cannot
    shrink any further). A completed run prints ADAPT_DIGEST with the
    merged mesh's quality so the harness can gate the elastic finish
    against a fixed-world reference."""
    import hashlib
    import os

    from parmmg_tpu.parallel import multihost

    multi = multihost.init_from_env()

    import jax
    import numpy as np

    from parmmg_tpu import failsafe
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.ops import quality
    from parmmg_tpu.parallel import elastic
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    ckdir = os.environ.get("PMMGTPU_CKPT_DIR") or None
    watchdog = float(os.environ.get("PMMGTPU_WATCHDOG", "60"))
    niter = int(os.environ.get("PMMGTPU_ELASTIC_NITER", "4"))
    rank = jax.process_index()

    # identical replicated host prep on every process of THIS epoch;
    # the shard count follows the epoch's device pool, so a reformed
    # world resumes its checkpoint through the elastic re-cut
    ndev = jax.device_count()
    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, ndev)))
    st, comm = split_mesh(mesh, part, ndev)
    opts = DistOptions(
        hsiz=0.32, niter=niter, max_sweeps=3, nparts=ndev,
        min_shard_elts=8, hgrad=None, polish_sweeps=0,
        checkpoint_dir=ckdir,
        watchdog_timeout=watchdog if multi else None,
        validate=os.environ.get("PMMGTPU_VALIDATE") or "basic",
    )
    try:
        out, comm2, info = adapt_stacked_input(st, comm, opts)
    except failsafe.WorldReformError as e:
        print(f"WORLD_REFORM rank={rank}: {e}", flush=True)
        os._exit(failsafe.REFORM_EXIT_CODE)
    except failsafe.CollectiveDivergenceError as e:
        print(f"COLL_DIVERGENCE rank={rank}: {e}", flush=True)
        os._exit(failsafe.DIVERGENCE_EXIT_CODE)
    except failsafe.PreemptionError as e:
        # elastic departure / SIGTERM: checkpoint committed first
        print(f"PREEMPTED rank={rank}: {e}", flush=True)
        os._exit(failsafe.KILL_EXIT_CODE)
    except failsafe.PeerLostError as e:
        print(f"PEER_LOST rank={rank}: {e}", flush=True)
        os._exit(failsafe.PEER_LOST_EXIT_CODE)
    except elastic.UnreformableWorldError as e:
        print(f"UNREFORMABLE rank={rank}: {e}", flush=True)
        os._exit(failsafe.MISMATCH_EXIT_CODE)
    except failsafe.CheckpointMismatchError as e:
        print(f"CKPT_MISMATCH rank={rank}: {e}", flush=True)
        os._exit(failsafe.MISMATCH_EXIT_CODE)
    except failsafe.CheckpointIOError as e:
        print(f"CKPT_IO rank={rank}: {e}", flush=True)
        os._exit(failsafe.CKPT_IO_EXIT_CODE)
    merged = merge_adapted(out, comm2)
    d = jax.device_get(merged)
    h = hashlib.sha256()
    for name in ("vert", "vmask", "tet", "tmask", "tria", "trmask",
                 "tref", "trref", "vtag", "trtag"):
        h.update(np.ascontiguousarray(np.asarray(getattr(d, name))).tobytes())
    qh = quality.quality_histogram(merged)
    print(
        f"ADAPT_DIGEST {h.hexdigest()} ne={int(qh.ne)} "
        f"qmin={float(qh.qmin):.9f} qavg={float(qh.qavg):.9f} "
        f"status={int(info['status'])}",
        flush=True,
    )


def main():
    if "--adapt" in sys.argv:
        return adapt_main()
    if "--failsafe" in sys.argv:
        return failsafe_main()
    if "--elastic" in sys.argv:
        return elastic_main()
    # the package __init__ auto-initializes the multi-controller
    # runtime from the PMMGTPU_* env (before any backend touch) — the
    # same path `python -m parmmg_tpu` takes under a process launcher
    from parmmg_tpu.parallel import multihost

    assert multihost.init_from_env(), "PMMGTPU_* env not set"

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from parmmg_tpu.ops import quality
    from parmmg_tpu.parallel import comm as comm_mod
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.parallel.shard import (
        AXIS, device_mesh, sharded_quality_histogram,
    )
    from parmmg_tpu.utils.gen import unit_cube_mesh

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    # identical host-side prep on every process (replicated determinism)
    mesh = unit_cube_mesh(4)
    np_global = int(mesh.npoin)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)

    dmesh = device_mesh(8)
    stg = multihost.put_sharded_global(st, dmesh)
    cidx = multihost.put_sharded_global(comm.comm_idx, dmesh)
    owner = multihost.put_sharded_global(comm.owner, dmesh)

    # 1. global vertex count: sum of owned-vertex indicators, psum'd
    #    across shards (and processes)
    # 2. interface multiplicity: halo_sum of ones on every live vertex
    #    must agree with the local copy count implied by comm_idx
    def body(blk, cidx_blk, owner_blk):
        m = jax.tree_util.tree_map(lambda a: a[0], blk)
        ones = m.vmask.astype(jnp.float32)
        mult = comm_mod.halo_sum(ones, cidx_blk[0], AXIS)
        owned = jnp.sum(jnp.where(owner_blk[0] & m.vmask, 1.0, 0.0))
        total = jax.lax.psum(owned, AXIS)
        chks = jax.lax.psum(jnp.sum(mult * ones), AXIS)
        return total, chks

    total, chks = jax.jit(
        jax.shard_map(
            body, mesh=dmesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P()),
        )
    )(stg, cidx, owner)
    total = float(jax.device_get(total))
    chks = float(jax.device_get(chks))
    assert total == float(np_global), (total, np_global)
    # expected halo multiplicity checksum, from host connectivity: a
    # vertex held by c shards reads back c on each of its c copies
    # (non-interface vertices keep their own 1)
    vg = np.asarray(st.vglob)
    vm = np.asarray(st.vmask)
    cnt = np.bincount(vg[vm].astype(np.int64))
    expected = float(np.sum(np.where(cnt > 1, cnt * cnt, cnt)))
    assert chks == expected, (chks, expected)

    # gather_stacked: the cross-process allgather that feeds replicated
    # host phases must reproduce the host-side stacked arrays exactly
    back = multihost.gather_stacked(stg)
    np.testing.assert_array_equal(
        np.asarray(back.vglob), np.asarray(st.vglob)
    )
    np.testing.assert_array_equal(
        np.asarray(back.tet), np.asarray(st.tet)
    )

    h = sharded_quality_histogram(stg, dmesh)
    ne = int(jax.device_get(h.ne))
    qmin = float(jax.device_get(h.qmin))
    qavg = float(jax.device_get(h.qavg))
    assert ne == int(mesh.ntet), (ne, int(mesh.ntet))

    print(
        f"MULTIHOST_OK proc={jax.process_index()} total={total} "
        f"chks={chks} ne={ne} qmin={qmin:.6f} qavg={qavg:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
