"""M14: multi-host fail-safe — the in-process half.

Unit coverage for the subsystems the 2-process harness
(test_m10_multihost.py, tools/fault_smoke.py --multihost) exercises end
to end, kept subprocess-free so tier-1 can afford them:

- device-resident validation (`failsafe.stacked_status` /
  `PhaseValidator.check_sharded`): equivalence with the gathered
  vmapped validator on the same corrupted meshes, and the zero-host-
  gather contract (no `multihost.gather_stacked`, only the tiny status
  table fetched, computation clean under the
  `lint.contracts.no_host_transfers` guard);
- the sharded checkpointer's layout, digests, rank-slice round trip
  and world-size refusal (two in-process Checkpointer instances
  standing in for two ranks — the commit barrier is injected);
- checkpoint GC (`AdaptOptions.checkpoint_keep`);
- rank-targeted fault grammar (``kill@rank1``) and the ``sigterm``
  fault kind's checkpoint-then-PreemptionError path, resumed to a
  bit-identical result;
- the collective watchdog (`multihost.run_with_watchdog`) converting a
  hang into `PeerLostError` while passing values and real errors
  through.
"""

import os
import signal
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parmmg_tpu import failsafe
from parmmg_tpu.core.tags import ReturnStatus
from parmmg_tpu.lint import contracts
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.parallel import multihost
from parmmg_tpu.parallel.distribute import split_mesh
from parmmg_tpu.parallel.partition import sfc_partition
from parmmg_tpu.parallel.shard import device_mesh, put_sharded
from parmmg_tpu.utils.gen import unit_cube_mesh

C_OPTS = dict(hsiz=0.45, niter=3, max_sweeps=3, hgrad=None,
              polish_sweeps=0)


@pytest.fixture(scope="module")
def stacked8():
    mesh = unit_cube_mesh(2)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    return st


def _corruptions(st):
    """(name, corrupted stacked mesh, expected nonzero status column)
    triples — NaN coords, inverted tet, out-of-range connectivity (the
    per-shard overflow-truncation signature)."""
    nan = st.replace(vert=st.vert.at[2, 0].set(jnp.nan))
    # swapping two vertices of a live tet flips its orientation
    t0 = st.tet[5, 0]
    inv = st.replace(
        tet=st.tet.at[5, 0].set(t0[jnp.asarray([1, 0, 2, 3])])
    )
    oob = st.replace(tet=st.tet.at[3, 0, 0].set(10 ** 6))
    return [("nan", nan, 0), ("inverted", inv, 2), ("oob", oob, 3)]


# ---------------------------------------------------------------------------
# device-resident validator
# ---------------------------------------------------------------------------


def test_stacked_status_equals_gathered_validator(stacked8):
    """The psum-reduced device status must agree, per shard and per
    counter, with the gathered vmapped validator on the same corrupted
    meshes — and both validators must agree on raise/pass."""
    dm = device_mesh(8)
    clean = np.asarray(
        jax.device_get(failsafe.stacked_status(put_sharded(stacked8, dm),
                                               dm))
    )
    assert clean.shape == (8, len(failsafe.STATUS_COLS))
    assert not clean.any()
    v = failsafe.PhaseValidator(level="basic", every=1)
    v.check(stacked8, 0)
    v.check_sharded(put_sharded(stacked8, dm), dm, 0)
    for name, bad, col in _corruptions(stacked8):
        dev = np.asarray(
            jax.device_get(failsafe.stacked_status(put_sharded(bad, dm),
                                                   dm))
        )
        host = np.asarray(jax.device_get(
            jax.vmap(failsafe._sanity_counts)(bad)
        ))
        np.testing.assert_array_equal(dev, host, err_msg=name)
        assert dev[:, col].sum() >= 1, (name, dev)
        with pytest.raises(failsafe.NumericalError):
            v.check(bad, 0)
        with pytest.raises(failsafe.NumericalError, match="per-shard"):
            v.check_sharded(put_sharded(bad, dm), dm, 0)


def test_basic_sharded_validation_no_host_gather(stacked8, monkeypatch):
    """``validate="basic"`` on the SPMD path performs ZERO host gathers:
    `multihost.gather_stacked` is never called, the only explicit fetch
    is the [D, 4] status table, and the computation runs clean under
    the runtime transfer guard (`lint.contracts.no_host_transfers` —
    load-bearing on accelerator backends, where an implicit D2H sync
    raises; the CPU backend's arrays are host-resident so only the
    structural assertions bite here)."""
    dm = device_mesh(8)
    stg = put_sharded(stacked8, dm)

    def no_gather(tree):
        raise AssertionError(
            "validate='basic' must not gather the mesh to host"
        )

    monkeypatch.setattr(multihost, "gather_stacked", no_gather)
    fetched = []
    real_get = jax.device_get

    def counting_get(x):
        fetched.append(np.asarray(real_get(x)).size)
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    v = failsafe.PhaseValidator(level="basic", every=1)
    with contracts.no_host_transfers():
        v.check_sharded(stg, dm, 0)
    assert fetched, "the status table must be fetched"
    assert max(fetched) <= 8 * len(failsafe.STATUS_COLS), fetched
    # cadence / level gates hold for the sharded path too
    failsafe.PhaseValidator(level="off").check_sharded(stg, dm, 0)
    failsafe.PhaseValidator(level="basic", every=2).check_sharded(
        stg, dm, 0
    )


# ---------------------------------------------------------------------------
# sharded checkpointer (two in-process "ranks")
# ---------------------------------------------------------------------------


def test_sharded_checkpoint_roundtrip_and_refusals(tmp_path, stacked8):
    opts = AdaptOptions(hsiz=0.35, niter=2)
    ck = str(tmp_path / "ck")
    barriers = []
    ranks = [
        failsafe.Checkpointer(ck, opts, "distributed", rank=r, world=2,
                              barrier=barriers.append)
        for r in (0, 1)
    ]
    aux = {"hausd": np.asarray([0.01, 0.02])}
    # rank 1 commits first: the manifest must still come from rank 0
    for c in (ranks[1], ranks[0]):
        c.save(0, {"mesh": stacked8}, history=[{"iter": 0}], emult=1.7,
               meta={"icap": 4}, aux_arrays=aux)
    assert sorted(os.listdir(ck)) == [
        "ckpt_00000.json", "ckpt_00000.proc0.npz", "ckpt_00000.proc1.npz",
    ]
    # two-phase commit: each rank passes the data + commit barriers
    assert barriers == ["ckpt-data-0", "ckpt-commit-0"] * 2
    import json

    with open(os.path.join(ck, "ckpt_00000.json")) as f:
        doc = json.load(f)
    assert doc["world"] == 2 and sorted(doc["digests"]) == ["0", "1"]
    # per-rank digests verify against the published shard files
    for r in (0, 1):
        with np.load(os.path.join(ck, f"ckpt_00000.proc{r}.npz")) as z:
            arrs = {k: z[k] for k in z.files}
        assert failsafe._digest_arrays(arrs) == doc["digests"][str(r)]
    rs = ranks[0].load()
    assert rs is not None and rs.it == 0 and rs.emult == 1.7
    for name in ("vert", "tet", "vmask", "tmask", "vglob", "met"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rs.mesh, name)),
            np.asarray(jax.device_get(getattr(stacked8, name))),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        rs.meta["aux_arrays"]["hausd"], aux["hausd"]
    )
    # ELASTIC resume: a 1-process load of the 2-process checkpoint
    # digest-verifies both shard files and re-concatenates the
    # replicated host state bit for bit (world size is a resource
    # layout, not a trajectory option)
    single = failsafe.Checkpointer(ck, opts, "distributed", rank=0,
                                   world=1, barrier=lambda t: None)
    el = single.load()
    assert el is not None and el.source_world == 2
    for name in ("vert", "tet", "vmask", "tmask", "vglob", "met"):
        np.testing.assert_array_equal(
            np.asarray(getattr(el.mesh, name)),
            np.asarray(jax.device_get(getattr(stacked8, name))),
            err_msg=f"elastic {name}",
        )
    # the hard refusal remains ONLY for a trajectory-options mismatch
    other = failsafe.Checkpointer(
        ck, AdaptOptions(hsiz=0.2, niter=2), "distributed", rank=0,
        world=2, barrier=lambda t: None,
    )
    with pytest.raises(failsafe.CheckpointMismatchError, match="hsiz"):
        other.load()


def test_checkpoint_gc_keep(tmp_path, stacked8):
    opts = AdaptOptions(hsiz=0.35)
    for keep, want in ((2, [1, 2]), (1, [2])):
        ck = str(tmp_path / f"keep{keep}")
        c = failsafe.Checkpointer(ck, opts, "distributed", keep=keep,
                                  rank=0, world=1)
        for it in range(3):
            c.save(it, {"mesh": stacked8}, history=[], emult=1.6)
        assert c._known() == want, (keep, sorted(os.listdir(ck)))
        # no orphan npz survives its pruned manifest
        npz = sorted(f for f in os.listdir(ck) if f.endswith(".npz"))
        assert npz == [f"ckpt_{i:05d}.npz" for i in want]
    # the harness wires AdaptOptions.checkpoint_keep through
    fs = failsafe.harness(
        AdaptOptions(checkpoint_keep=5,
                     checkpoint_dir=str(tmp_path / "h")),
        driver="centralized",
    )
    assert fs.ckpt.keep == 5


# ---------------------------------------------------------------------------
# rank-targeted faults + sigterm preemption
# ---------------------------------------------------------------------------


def test_fault_plan_rank_grammar():
    plan = failsafe.FaultPlan.parse(
        "it1:remesh:kill@rank1,it0:post:sigterm"
    )
    assert [(f.it, f.phase, f.kind, f.rank) for f in plan.faults] == [
        (1, "remesh", "kill", 1), (0, "post", "sigterm", None),
    ]
    # this test process is jax process 0: a rank-1 fault is not ours
    assert not plan.faults[0].mine and plan.faults[1].mine
    # firing the rank-1 kill at its boundary is a no-op here
    state = unit_cube_mesh(2)
    out = plan.fire(1, "remesh", state)
    assert out is state and not plan.faults[0].fired
    assert not plan.take(1, "remesh", "kill")
    # a rank-0 kill IS ours (kill_mode=raise so the test survives)
    mine = failsafe.FaultPlan.parse("it0:remesh:kill@rank0",
                                    kill_mode="raise")
    with pytest.raises(failsafe.PreemptionError):
        mine.fire(0, "remesh", state)
    for bad in ("it0:remesh:kill@r1", "it0:remesh:kill@rankx",
                "it0:remesh:kill@"):
        with pytest.raises(ValueError):
            failsafe.FaultPlan.parse(bad)


def test_sigterm_checkpoints_then_exits_and_resumes(tmp_path):
    """The preemption path end to end, in process: an injected SIGTERM
    mid-iteration sets the harness flag, the driver commits the
    iteration's checkpoint and raises PreemptionError; resuming
    reproduces the uninterrupted run; the previous SIGTERM disposition
    is restored."""
    prev = signal.getsignal(signal.SIGTERM)
    ref, ref_info = adapt(unit_cube_mesh(2), AdaptOptions(**C_OPTS))

    def key(m, info):
        h = info["qual_out"]
        return (
            int(np.asarray(jax.device_get(m.vmask)).sum()),
            int(np.asarray(jax.device_get(m.tmask)).sum()),
            tuple(int(x) for x in np.asarray(jax.device_get(h.counts))),
        )

    ck = str(tmp_path / "ck")
    with pytest.raises(failsafe.PreemptionError, match="checkpointed"):
        adapt(unit_cube_mesh(2),
              AdaptOptions(faults="it1:remesh:sigterm", **C_OPTS),
              checkpoint_dir=ck)
    assert signal.getsignal(signal.SIGTERM) == prev
    assert any(f.endswith(".json") for f in os.listdir(ck))
    assert not [f for f in os.listdir(ck) if ".tmp." in f]
    res, res_info = adapt(unit_cube_mesh(2), AdaptOptions(**C_OPTS),
                          checkpoint_dir=ck)
    assert res_info["status"] == ReturnStatus.SUCCESS
    assert key(res, res_info) == key(ref, ref_info)


# ---------------------------------------------------------------------------
# collective watchdog
# ---------------------------------------------------------------------------


def test_watchdog_converts_hang_to_peer_lost():
    with pytest.raises(failsafe.PeerLostError, match="did not complete"):
        multihost.run_with_watchdog(
            lambda: threading.Event().wait(), tag="hung", timeout=0.3,
        )
    # values and real errors pass through un-wrapped
    assert multihost.run_with_watchdog(lambda: 42, timeout=5.0) == 42
    assert multihost.run_with_watchdog(lambda: 43) == 43  # no thread
    with pytest.raises(ValueError, match="boom"):
        multihost.run_with_watchdog(
            lambda: (_ for _ in ()).throw(ValueError("boom")),
            timeout=5.0,
        )


def test_heartbeat_noop_without_world_or_timeout(tmp_path):
    # single process: barrier and heartbeat return immediately
    multihost.barrier("t", timeout=0.1)
    fs = failsafe.harness(AdaptOptions(), driver="centralized")
    assert fs.watchdog is None
    fs.heartbeat(0)  # no timeout configured -> no collective
