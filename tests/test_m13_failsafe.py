"""M13: fail-safe layer — graded failure, checkpoint/resume, fault
injection (`parmmg_tpu.failsafe`, the `failed_handling` /
checkpoint-restart role of reference `src/libparmmg1.c:970-1011`).

Covers the acceptance matrix: for each injected fault class (NaN,
capacity overflow, forced retrace, simulated preemption) x each driver
(centralized, distributed), the run returns a documented ReturnStatus
with a conformal, saveable mesh and a ``failure`` entry in
info.history — never an unhandled exception or a truncated file. Plus
the previously-untested LOWFAILURE snapshot-rollback branch of
`models/distributed._iteration_loop` (now the shared validator path),
kill-and-resume equivalence, and fingerprint-mismatch refusal.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from parmmg_tpu import failsafe
from parmmg_tpu.core.tags import ReturnStatus
from parmmg_tpu.io import medit
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.models.distributed import (
    DistOptions,
    adapt_distributed,
    merge_adapted,
)
from parmmg_tpu.parallel.distribute import unstack_mesh
from parmmg_tpu.utils.conformity import check_mesh
from parmmg_tpu.utils.gen import unit_cube_mesh

# KEEP IN SYNC with failsafe_worker.OPTS (fingerprint compatibility)
C_OPTS = dict(hsiz=0.35, niter=2, max_sweeps=4, hgrad=None,
              polish_sweeps=0)
D_OPTS = dict(hsiz=0.32, niter=2, max_sweeps=4, nparts=2,
              min_shard_elts=8, hgrad=None, polish_sweeps=0)


def _key(mesh, info):
    """Mesh counts + quality-histogram fingerprint of a result."""
    h = info["qual_out"]
    return (
        int(np.asarray(jax.device_get(mesh.vmask)).sum()),
        int(np.asarray(jax.device_get(mesh.tmask)).sum()),
        tuple(int(x) for x in np.asarray(jax.device_get(h.counts))),
    )


def _failures(info):
    return [r for r in info["history"] if "failure" in r]


# ---------------------------------------------------------------------------
# FaultPlan grammar + validator unit coverage (cheap, no adapt run)
# ---------------------------------------------------------------------------


def test_fault_plan_grammar():
    plan = failsafe.FaultPlan.parse(
        "it1:remesh:nan, it2:migrate:overflow,it1:post:kill"
    )
    assert [(f.it, f.phase, f.kind) for f in plan.faults] == [
        (1, "remesh", "nan"), (2, "migrate", "overflow"),
        (1, "post", "kill"),
    ]
    assert plan.take(2, "migrate", "overflow")
    assert not plan.take(2, "migrate", "overflow")  # fires once
    for bad in ("1:remesh:nan", "it1:bogus:nan", "it1:remesh:bogus",
                "it1:remesh"):
        with pytest.raises(ValueError):
            failsafe.FaultPlan.parse(bad)


def test_validator_catches_poison_and_cadence():
    m = unit_cube_mesh(2)
    v = failsafe.PhaseValidator(level="basic", every=1)
    v.check(m, 0)  # clean mesh passes
    bad = m.replace(vert=m.vert.at[0].set(float("nan")))
    with pytest.raises(failsafe.NumericalError, match="non-finite"):
        v.check(bad, 0)
    # cadence: iteration 0 of every=2 is not due; level off never is
    failsafe.PhaseValidator(level="basic", every=2).check(bad, 0)
    failsafe.PhaseValidator(level="off").check(bad, 0)
    # full level runs the host conformity check too
    v_full = failsafe.PhaseValidator(level="full", every=1)
    v_full.check(m, 0)


def test_options_fingerprint_resume_safe_fields():
    a = AdaptOptions(hsiz=0.3, niter=2)
    fp_a, _ = failsafe.options_fingerprint(a)
    # niter / verbose / checkpointing knobs are resume-safe
    assert failsafe.options_fingerprint(
        AdaptOptions(hsiz=0.3, niter=7, verbose=2,
                     checkpoint_dir="/x")
    )[0] == fp_a
    # trajectory knobs are not
    assert failsafe.options_fingerprint(
        AdaptOptions(hsiz=0.25, niter=2)
    )[0] != fp_a


# ---------------------------------------------------------------------------
# atomic writes (satellite: io/medit tmp + os.replace)
# ---------------------------------------------------------------------------


def test_save_mesh_atomic_no_truncation(tmp_path, monkeypatch):
    m = unit_cube_mesh(2)
    path = str(tmp_path / "out.mesh")
    medit.save_mesh(m, path)
    before = open(path).read()

    calls = []
    orig = medit._fmt_block

    def boom(f, name, *a, **kw):
        calls.append(name)
        if name == "Tetrahedra":
            raise IOError("injected mid-write failure")
        return orig(f, name, *a, **kw)

    monkeypatch.setattr(medit, "_fmt_block", boom)
    with pytest.raises(IOError, match="injected"):
        medit.save_mesh(m, path)
    monkeypatch.setattr(medit, "_fmt_block", orig)
    # the failed write left neither a truncated target nor temp litter
    assert open(path).read() == before
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_save_meshb_atomic_no_truncation(tmp_path):
    m = unit_cube_mesh(2)
    path = str(tmp_path / "out.meshb")
    medit.save_mesh(m, path)
    m2 = medit.load_mesh(path)
    assert int(m2.ntet) == int(m.ntet)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


# ---------------------------------------------------------------------------
# centralized driver: fault matrix + checkpoint/resume
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ref_centralized():
    out, info = adapt(unit_cube_mesh(3), AdaptOptions(**C_OPTS))
    assert info["status"] == ReturnStatus.SUCCESS
    return _key(out, info)


@pytest.mark.parametrize("fault,expect", [
    ("it1:remesh:nan", ReturnStatus.LOWFAILURE),
    ("it0:remesh:overflow", ReturnStatus.SUCCESS),
])
def test_fault_matrix_centralized(tmp_path, fault, expect):
    out, info = adapt(
        unit_cube_mesh(3), AdaptOptions(faults=fault, **C_OPTS)
    )
    assert info["status"] == expect
    assert _failures(info), "absorbed fault must leave a history entry"
    assert check_mesh(out, check_boundary=False).ok
    medit.save_mesh(out, str(tmp_path / "out.mesh"))  # saveable


def test_checkpoint_resume_equivalence_centralized(tmp_path,
                                                   ref_centralized):
    ck = str(tmp_path / "ck")
    # partial run (one iteration), then resume with the full budget:
    # niter is a resume-safe option by design
    adapt(unit_cube_mesh(3),
          AdaptOptions(**dict(C_OPTS, niter=1)), checkpoint_dir=ck)
    assert sorted(os.listdir(ck)) == ["ckpt_00000.json",
                                      "ckpt_00000.npz"]
    out, info = adapt(unit_cube_mesh(3), AdaptOptions(**C_OPTS),
                      checkpoint_dir=ck)
    assert info["status"] == ReturnStatus.SUCCESS
    assert _key(out, info) == ref_centralized
    # a mismatched options fingerprint REFUSES to resume with a clear
    # error naming the differing field (same checkpoint dir)
    with pytest.raises(failsafe.CheckpointMismatchError, match="hsiz"):
        adapt(unit_cube_mesh(3),
              AdaptOptions(**dict(C_OPTS, hsiz=0.3)), checkpoint_dir=ck)


def test_kill_and_resume_centralized(tmp_path, ref_centralized):
    """In-process preemption (kill_mode="raise" — BaseException, no
    driver can absorb it) at the it0 boundary, then resume: the resumed
    run must reproduce the uninterrupted run bit for bit."""
    ck = str(tmp_path / "ck")
    plan = failsafe.FaultPlan.parse("it0:post:kill", kill_mode="raise")
    with pytest.raises(failsafe.PreemptionError):
        adapt(unit_cube_mesh(3),
              AdaptOptions(faults=plan, **C_OPTS), checkpoint_dir=ck)
    # the kill fired AFTER the atomic checkpoint commit
    assert any(f.endswith(".json") for f in os.listdir(ck))
    assert not [f for f in os.listdir(ck) if ".tmp." in f]
    out, info = adapt(unit_cube_mesh(3), AdaptOptions(**C_OPTS),
                      checkpoint_dir=ck)
    assert info["status"] == ReturnStatus.SUCCESS
    assert _key(out, info) == ref_centralized


@pytest.mark.slow  # subprocess jax startup; tier-1 covers the
# in-process preemption above, and tools/fault_smoke.py (the
# tools/check.sh gate) runs this exact scenario end to end
def test_kill_and_resume_subprocess(tmp_path, ref_centralized):
    """Genuine preemption: a subprocess is os._exit()ed mid-run by the
    PARMMG_FAULTS plan; the checkpoint directory must hold a complete
    (atomically published) checkpoint that this process resumes into
    the same final mesh as the uninterrupted run."""
    ck = str(tmp_path / "ck")
    worker = os.path.join(os.path.dirname(__file__),
                          "failsafe_worker.py")
    env = dict(os.environ, PARMMG_FAULTS="it0:post:kill",
               JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, worker, ck], env=env,
        capture_output=True, text=True, timeout=1500,
    )
    assert p.returncode == failsafe.KILL_EXIT_CODE, (
        p.returncode, p.stdout[-2000:], p.stderr[-2000:],
    )
    assert not [f for f in os.listdir(ck) if ".tmp." in f]
    out, info = adapt(unit_cube_mesh(3), AdaptOptions(**C_OPTS),
                      checkpoint_dir=ck)
    assert info["status"] == ReturnStatus.SUCCESS
    assert _key(out, info) == ref_centralized


# ---------------------------------------------------------------------------
# distributed driver: fault matrix + rollback + kill/resume
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ref_distributed():
    st, comm, info = adapt_distributed(
        unit_cube_mesh(3), DistOptions(**D_OPTS)
    )
    assert info["status"] == ReturnStatus.SUCCESS
    return _key(st, info)


def test_lowfailure_rollback_returns_conformal_snapshot(tmp_path):
    """The snapshot-rollback branch of `_iteration_loop` (previously an
    untested except-branch): a NaN injected into iteration 1 must roll
    the state back to the iteration-0 result — conformal, saveable —
    and grade the run LOWFAILURE, never raise."""
    st, comm, info = adapt_distributed(
        unit_cube_mesh(3),
        DistOptions(faults="it1:remesh:nan", **D_OPTS),
    )
    assert info["status"] == ReturnStatus.LOWFAILURE
    fails = _failures(info)
    assert fails and "non-finite" in fails[-1]["failure"]
    for s, m in enumerate(unstack_mesh(st)):
        rep = check_mesh(m, check_boundary=False)
        assert rep.ok, f"shard {s}: {rep}"
    merged = merge_adapted(st, comm)
    assert check_mesh(merged, check_boundary=False).ok
    medit.save_mesh_distributed(st, comm, str(tmp_path / "out.mesh"))
    assert os.path.exists(str(tmp_path / "out.0.mesh"))


def test_fault_overflow_distributed_migrate(tmp_path):
    """Injected slot-capacity undershoot at the migrate boundary drives
    the REAL CapacityError raise site in parallel/migrate.py and the
    real grow-and-retry consumer in the driver."""
    st, comm, info = adapt_distributed(
        unit_cube_mesh(3),
        DistOptions(faults="it0:migrate:overflow", **D_OPTS),
    )
    assert info["status"] == ReturnStatus.SUCCESS
    fails = _failures(info)
    assert fails and fails[0].get("error") == "CapacityError"
    assert fails[0].get("recovered")
    for m in unstack_mesh(st):
        assert check_mesh(m, check_boundary=False).ok


def test_kill_and_resume_distributed(tmp_path, ref_distributed):
    """Preemption at the it0 boundary of the distributed driver +
    resume from DistOptions.checkpoint_dir reproduces the uninterrupted
    run (the module's reference fixture)."""
    ref = ref_distributed
    ck = str(tmp_path / "ck")
    plan = failsafe.FaultPlan.parse("it0:post:kill", kill_mode="raise")
    with pytest.raises(failsafe.PreemptionError):
        adapt_distributed(
            unit_cube_mesh(3),
            DistOptions(faults=plan, checkpoint_dir=ck, **D_OPTS),
        )
    assert any(f.endswith(".json") for f in os.listdir(ck))
    st, comm, info = adapt_distributed(
        unit_cube_mesh(3), DistOptions(checkpoint_dir=ck, **D_OPTS)
    )
    assert info["status"] == ReturnStatus.SUCCESS
    assert _key(st, info) == ref


# --- retrace faults LAST: their recovery clears the in-process compile
# cache, so every adapt after them would recompile from scratch --------


def test_fault_retrace_centralized(tmp_path):
    out, info = adapt(
        unit_cube_mesh(3),
        AdaptOptions(faults="it1:remesh:retrace", **C_OPTS),
    )
    assert info["status"] == ReturnStatus.SUCCESS
    assert any(r.get("error") == "RetraceError"
               for r in _failures(info))
    assert check_mesh(out, check_boundary=False).ok
    medit.save_mesh(out, str(tmp_path / "out.mesh"))


def test_fault_retrace_distributed():
    """Injected transient-XLA error: recovered by clear-caches + retry."""
    st, comm, info = adapt_distributed(
        unit_cube_mesh(3),
        DistOptions(faults="it0:remesh:retrace", **D_OPTS),
    )
    assert info["status"] == ReturnStatus.SUCCESS
    assert any(r.get("error") == "RetraceError"
               for r in _failures(info))
    for m in unstack_mesh(st):
        assert check_mesh(m, check_boundary=False).ok
