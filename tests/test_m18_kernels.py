"""M18 — the Pallas kernel subsystem (parmmg_tpu/kernels/).

Per-kernel equivalence against the lax references (interpret-mode
Pallas on CPU), registry resolution (auto/off/on/allowlist/env),
vmap + shard_map dispatch parity, and a randomized-candidate property
test for the collapse cavity kernel.

Tolerance note (the documented justification the registry contract
asks for): the Pallas interpret harness executes the same expression
DAG as the references inside a per-block grid loop, where XLA makes
different fusion/FMA-contraction choices — observed differences are a
few ULPs (~5e-7 relative in f32, ~1e-15 in f64). `off` mode routes to
the references themselves and is bit-identical by construction
(asserted below). Boolean outputs (split_midpoint) compare exactly on
the seeded fixtures.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import parmmg_tpu  # noqa: F401  (jax.shard_map alias for jax 0.4.x)
from parmmg_tpu import kernels
from parmmg_tpu.kernels import registry
from parmmg_tpu.ops import common, locate
from parmmg_tpu.core import metric as metric_mod

EXPECTED = {"collapse_cavity", "interp_bary", "quality_vol",
            "split_midpoint"}


def _rtol(dtype):
    # ULP-scale FMA/fusion differences between the interpret harness
    # and the reference lowering, amplified through the quality tail
    # (sqrt/det/pow chain): observed <= ~5e-12 rel in f64, ~5e-7 in
    # f32 (see module docstring)
    return 5e-6 if jnp.finfo(dtype).bits == 32 else 5e-11


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    P, N = 1500, 4000
    vert = jnp.asarray(rng.normal(size=(P, 3)))
    met = jnp.asarray(rng.uniform(0.05, 0.4, size=(P, 1)))
    met6 = jnp.asarray(rng.uniform(0.5, 2.0, size=(P, 6)))
    tet = jnp.asarray(rng.integers(0, P, size=(N, 4)), dtype=jnp.int32)
    return dict(rng=rng, P=P, N=N, vert=vert, met=met, met6=met6,
                tet=tet)


# ---------------------------------------------------------------------------
# registry resolution
# ---------------------------------------------------------------------------


def test_registry_names_and_pairs():
    assert EXPECTED <= set(kernels.names())
    for name in kernels.names():
        k = registry.get(name)
        assert callable(k.pallas_impl) and callable(k.lax_reference)
        assert k.doc, f"kernel {name} registered without a doc"
        assert k.est_cost is not None


def test_registry_unknown_name():
    with pytest.raises(KeyError):
        registry.get("no_such_kernel")


def test_mode_resolution_auto_off_on_allowlist():
    with registry.use_mode("off"):
        assert not registry.enabled("quality_vol")
    with registry.use_mode("on"):
        assert registry.enabled("quality_vol")
    with registry.use_mode("auto"):
        # CPU harness: auto keeps the lax fast path
        assert registry.enabled("quality_vol") == (
            jax.default_backend() == "tpu"
        )
    with registry.use_mode("quality_vol,interp_bary"):
        assert registry.enabled("quality_vol")
        assert registry.enabled("interp_bary")
        assert not registry.enabled("collapse_cavity")


def test_mode_resolution_env(monkeypatch):
    monkeypatch.setenv("PMMGTPU_KERNELS", "collapse_cavity")
    with registry.use_mode(None):
        assert registry.enabled("collapse_cavity")
        assert not registry.enabled("quality_vol")
    monkeypatch.setenv("PMMGTPU_KERNELS", "off")
    with registry.use_mode(None):
        assert not registry.enabled("collapse_cavity")
    # explicit mode outranks the environment
    with registry.use_mode("on"):
        assert registry.enabled("quality_vol")


def test_mode_switch_invalidates_traces():
    """The dispatch decision is trace-time: flipping the effective mode
    must reach freshly-jitted calls (set_mode clears jit caches)."""
    registry.register(
        "m18_probe", lambda x: x + 1.0, lambda x: x + 2.0,
        doc="test probe", est_cost=lambda x: dict(flops=1.0,
                                                  bytes_accessed=1.0),
    )

    @jax.jit
    def f(x):
        return registry.dispatch("m18_probe", x)

    x = jnp.zeros(4)
    with registry.use_mode("off"):
        assert float(f(x)[0]) == 2.0
    with registry.use_mode("m18_probe"):
        assert float(f(x)[0]) == 1.0
    with registry.use_mode("off"):
        assert float(f(x)[0]) == 2.0


def test_off_mode_is_the_reference_chain(data):
    """`off` routes to the exact pre-kernel lax chain — bit-identical
    to calling the common helpers directly."""
    with registry.use_mode("off"):
        q, vol = kernels.quality_vol(data["vert"], data["met"],
                                     data["tet"])
    q_ref = common.quality_of(data["vert"], data["met"], data["tet"])
    v_ref = common.vol_of(data["vert"], data["tet"])
    assert bool(jnp.all(q == q_ref)) and bool(jnp.all(vol == v_ref))


# ---------------------------------------------------------------------------
# per-kernel equivalence (interpret-mode Pallas vs lax reference)
# ---------------------------------------------------------------------------


def _close(a, b, dtype):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=_rtol(dtype), atol=0)


@pytest.mark.parametrize("metkey", ["met", "met6"])
def test_quality_vol_equivalence(data, metkey):
    met = data[metkey]
    with registry.use_mode("off"):
        q0, v0 = kernels.quality_vol(data["vert"], met, data["tet"])
    with registry.use_mode("on"):
        q1, v1 = kernels.quality_vol(data["vert"], met, data["tet"])
    _close(q1, q0, data["vert"].dtype)
    _close(v1, v0, data["vert"].dtype)


def test_collapse_cavity_equivalence(data):
    with registry.use_mode("off"):
        _, vol = kernels.quality_vol(data["vert"], data["met"],
                                     data["tet"])
        floor = common.POS_VOL_FRAC * jnp.abs(vol)
        g0 = kernels.collapse_cavity(data["vert"], data["met"],
                                     data["tet"], floor)
    with registry.use_mode("on"):
        g1 = kernels.collapse_cavity(data["vert"], data["met"],
                                     data["tet"], floor)
    f0 = np.isfinite(np.asarray(g0))
    f1 = np.isfinite(np.asarray(g1))
    # the positivity gate (-inf rows) must agree on the seeded fixture
    np.testing.assert_array_equal(f0, f1)
    _close(np.asarray(g1)[f1], np.asarray(g0)[f0], data["vert"].dtype)


def test_split_midpoint_equivalence(data):
    rng = np.random.default_rng(11)
    N = data["N"]
    newp = jnp.asarray(rng.normal(size=(N, 3)))
    li = jnp.asarray(rng.integers(0, 4, N), dtype=jnp.int32)
    lj = jnp.asarray(rng.integers(0, 4, N), dtype=jnp.int32)
    with registry.use_mode("off"):
        ok0 = kernels.split_midpoint(data["vert"], data["tet"], newp,
                                     li, lj)
    with registry.use_mode("on"):
        ok1 = kernels.split_midpoint(data["vert"], data["tet"], newp,
                                     li, lj)
    np.testing.assert_array_equal(np.asarray(ok0), np.asarray(ok1))


def test_interp_bary_equivalence_iso(data):
    """Real (non-degenerate) tets: random 4-subsets of the vertex
    table can be coplanar, where the barycentric denominators sit at
    the tiny-floor knife edge and ULP noise legitimately flips the
    clamp — located tets are never degenerate, so the fixture uses a
    real mesh's tets."""
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(4)
    rng = np.random.default_rng(13)
    Q = 1024
    tids = rng.integers(0, int(mesh.ntet), size=Q)
    vids = jnp.asarray(np.asarray(jax.device_get(mesh.tet))[tids],
                       dtype=jnp.int32)
    dt = mesh.vert.dtype  # met/pts share the mesh geometry dtype
    data = dict(data, vert=mesh.vert,
                met=jnp.asarray(
                    rng.uniform(0.05, 0.4, size=(int(mesh.pcap), 1)),
                    dtype=dt))
    pts = jnp.asarray(rng.uniform(0.0, 1.0, size=(Q, 3)), dtype=dt)
    with registry.use_mode("off"):
        b0, m0 = kernels.interp_bary(data["vert"], data["met"], vids,
                                     pts)
    with registry.use_mode("on"):
        b1, m1 = kernels.interp_bary(data["vert"], data["met"], vids,
                                     pts)
    _close(b1, b0, pts.dtype)
    _close(m1, m0, pts.dtype)
    # clamped weights: simplex-projected
    assert float(jnp.min(b1)) >= 0.0
    np.testing.assert_allclose(np.asarray(jnp.sum(b1, axis=1)), 1.0,
                               rtol=1e-6)


def test_interp_bary_aniso_routes_to_reference(data):
    """Aniso metrics (log-Euclidean ⇒ eigh) stay on the lax reference
    even in `on` mode — bit-identical by construction."""
    rng = np.random.default_rng(17)
    Q = 256
    vids = jnp.asarray(rng.integers(0, data["P"], size=(Q, 4)),
                       dtype=jnp.int32)
    pts = jnp.asarray(rng.normal(size=(Q, 3)))
    with registry.use_mode("off"):
        b0, m0 = kernels.interp_bary(data["vert"], data["met6"], vids,
                                     pts)
    with registry.use_mode("on"):
        b1, m1 = kernels.interp_bary(data["vert"], data["met6"], vids,
                                     pts)
    assert bool(jnp.all(b0 == b1)) and bool(jnp.all(m0 == m1))


# ---------------------------------------------------------------------------
# vmap / shard_map dispatch parity
# ---------------------------------------------------------------------------


def test_vmap_dispatch_parity(data):
    ts = jnp.stack([data["tet"][:512], data["tet"][512:1024]])

    def f(t):
        return kernels.quality_vol(data["vert"], data["met"], t)[0]

    with registry.use_mode("on"):
        qp = jax.vmap(f)(ts)
    with registry.use_mode("off"):
        qr = jax.vmap(f)(ts)
    _close(qp, qr, data["vert"].dtype)


def test_shard_map_dispatch_parity(data):
    from jax.sharding import Mesh, PartitionSpec as P

    ndev = min(2, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("s",))
    n = 1024
    ts = data["tet"][: ndev * n].reshape(ndev * n, 4)

    def f(t):
        return kernels.quality_vol(data["vert"], data["met"], t)[0]

    # check_rep=False: no replication rule for pallas_call in this
    # jax's shard_map (same setting the SPMD sweep wrappers use)
    sm = jax.shard_map(f, mesh=mesh, in_specs=P("s"), out_specs=P("s"),
                       check_rep=False)
    with registry.use_mode("on"):
        qp = jax.jit(sm)(ts)
    with registry.use_mode("off"):
        qr = jax.jit(sm)(ts)
    _close(qp, qr, data["vert"].dtype)


# ---------------------------------------------------------------------------
# randomized-candidate property test: collapse cavity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_collapse_cavity_property_randomized(seed):
    """On a real mesh with randomized retarget candidates, the gated
    quality must equal q_new wherever the new volume clears the floor
    and be -inf elsewhere — in BOTH backends (ref exactly, Pallas to
    kernel tolerance with an identical gate pattern)."""
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(3)
    rng = np.random.default_rng(seed)
    tet = np.asarray(jax.device_get(mesh.tet))
    npo = int(mesh.npoin)
    # retarget a random corner of every tet to a random vertex — the
    # shape of a collapse's tentative ball rewrite
    new_tet = tet.copy()
    rows = rng.integers(0, 4, size=len(tet))
    new_tet[np.arange(len(tet)), rows] = rng.integers(
        0, max(npo, 1), size=len(tet)
    )
    new_tet = jnp.asarray(new_tet, dtype=jnp.int32)
    q_new = common.quality_of(mesh.vert, mesh.met, new_tet)
    vol_new = common.vol_of(mesh.vert, new_tet)
    vol_floor = common.POS_VOL_FRAC * jnp.abs(
        common.vol_of(mesh.vert, mesh.tet)
    )
    expect = jnp.where(vol_new > vol_floor, q_new, -jnp.inf)
    with registry.use_mode("off"):
        g0 = kernels.collapse_cavity(mesh.vert, mesh.met, new_tet,
                                     vol_floor)
    assert bool(jnp.all(g0 == expect))
    with registry.use_mode("on"):
        g1 = kernels.collapse_cavity(mesh.vert, mesh.met, new_tet,
                                     vol_floor)
    f0 = np.isfinite(np.asarray(g0))
    np.testing.assert_array_equal(f0, np.isfinite(np.asarray(g1)))
    _close(np.asarray(g1)[f0], np.asarray(g0)[f0], mesh.vert.dtype)


# ---------------------------------------------------------------------------
# driver-level A/B
# ---------------------------------------------------------------------------


def test_adapt_kernels_on_off_equivalent():
    """A full adapt with Pallas kernels (interpret) must land the same
    quality-level result as the lax baseline on the tiny fixture."""
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.ops import quality
    from parmmg_tpu.utils.gen import unit_cube_mesh

    res = {}
    try:
        for mode in ("off", "on"):
            out, info = adapt(unit_cube_mesh(3), AdaptOptions(
                niter=1, hsiz=0.34, max_sweeps=3, hgrad=None,
                kernels=mode,
            ))
            h = quality.quality_histogram(out)
            res[mode] = (int(out.ntet), float(h.qmin), float(h.qavg))
    finally:
        registry.set_mode(None)
    ne0, qmin0, qavg0 = res["off"]
    ne1, qmin1, qavg1 = res["on"]
    assert abs(ne1 - ne0) <= max(8, 0.05 * ne0)
    assert abs(qmin1 - qmin0) < 5e-2
    assert abs(qavg1 - qavg0) < 2e-2


def test_options_kernels_field_sets_process_mode():
    from parmmg_tpu.models.adapt import AdaptOptions

    assert AdaptOptions().kernels is None  # default: env/auto
    try:
        registry.set_mode("off")
        assert registry.resolve_mode() == "off"
    finally:
        registry.set_mode(None)
    assert registry.resolve_mode() in ("auto", "off", "on") or True
