"""Test harness: simulate an 8-device mesh on CPU, mirroring how the
reference exercises its distributed path with oversubscribed mpiexec ranks
(SURVEY.md §4).

The surrounding environment pins JAX to a single-chip TPU tunnel (an `axon`
PJRT plugin registered by sitecustomize at interpreter start, with
JAX_PLATFORMS=axon). jax initializes *every* registered backend factory on
first use regardless of JAX_PLATFORMS, so to keep tests hermetic and offline
we deregister the accelerator factories before any backend exists, then pin
the CPU platform with 8 virtual devices and x64 for exact geometry checks."""

import os

# silence the cpu_aot_loader pseudo-feature ERROR spam (see cache note
# below); must be set before jax/xla load
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

# Pallas registers its Mosaic lowering rules for platform "tpu" at
# import time and REFUSES when "tpu" is no longer a known platform —
# import it while the accelerator factories are still registered (the
# kernels subsystem runs interpret-mode Pallas on CPU in this suite).
# Importing only registers lowerings; it does not initialize a backend.
import jax.experimental.pallas  # noqa: F401, E402
from jax.experimental.pallas import tpu as _pltpu  # noqa: F401, E402

assert not _xb._default_backend, "conftest must run before jax backend init"
for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Persistent compile cache for the CPU suite: OPT-IN ONLY
# (PARMMG_CPU_CACHE=1). The round-2-era executable (de)serialization
# crash DOES reproduce on this jaxlib (re-measured PR 1): a cold run
# that only WRITES cache entries completes its tests cleanly, while the
# next warm run ABORTS (SIGABRT in jax Array._value) executing a
# deserialized executable — both with the previously committed blobs
# and with blobs freshly written by this very jaxlib. Cold compiles are
# slower but stable, and stability is what the tier-1 gate measures.
if os.environ.get("PARMMG_CPU_CACHE"):
    _cache = os.path.join(os.path.dirname(__file__), ".jax_cache_cpu")
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 1)

import pathlib  # noqa: E402

import pytest  # noqa: E402

REF_EX0 = pathlib.Path("/root/reference/libexamples/adaptation_example0")
REF_EX1 = pathlib.Path("/root/reference/libexamples/adaptation_example1")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running gate (bench-scale workloads)"
    )


# ---------------------------------------------------------------------------
# reference-fixture synthesis
#
# The reference checkout (/root/reference) is not mounted in every
# environment. The fixture tests assert cube-GENERIC properties (12/12
# two-slab unit cube, scalar met 0.5, structural communicator records),
# so when the reference files are absent we synthesize equivalent
# fixtures with the package's own writers: a unit cube as two stacked
# Freudenthal-split slabs (12 vertices, 12 positively-oriented tets,
# full boundary triangulation) and a 4-shard x-sliced "wave" with
# ParallelTriangleCommunicators records.
# ---------------------------------------------------------------------------

import itertools  # noqa: E402

import numpy as np  # noqa: E402


def _freudenthal_box(vid):
    """6-tet Kuhn split of a box; `vid[(i,j,k)]` -> vertex id. The six
    path tets share the main diagonal, so stacked boxes conform."""
    tets = []
    for perm in itertools.permutations(range(3)):
        p = [0, 0, 0]
        path = [tuple(p)]
        for ax in perm:
            p[ax] = 1
            path.append(tuple(p))
        tets.append([vid[q] for q in path])
    return tets


def _orient_positive(verts, tets):
    tets = np.asarray(tets, np.int32)
    c = verts[tets]
    vol = np.einsum(
        "ti,ti->t",
        np.cross(c[:, 1] - c[:, 0], c[:, 2] - c[:, 0]),
        c[:, 3] - c[:, 0],
    )
    flip = vol < 0
    tets[flip] = tets[flip][:, [0, 1, 3, 2]]
    return tets


def _boundary_trias(tets):
    from parmmg_tpu.core.mesh import FACE_VERTS

    seen = {}
    for tet in np.asarray(tets):
        for f in range(4):
            tri = tuple(int(v) for v in tet[FACE_VERTS[f]])
            seen.setdefault(tuple(sorted(tri)), []).append(tri)
    return np.asarray(
        [v[0] for v in seen.values() if len(v) == 1], np.int32
    ).reshape(-1, 3)


def _tria_plane_refs(verts, trias):
    """Stable per-face references: 1..6 for the axis-aligned bounding
    planes, 0 elsewhere."""
    refs = np.zeros(len(trias), np.int32)
    lo, hi = verts.min(axis=0), verts.max(axis=0)
    for r, (ax, val) in enumerate(
        [(2, lo[2]), (2, hi[2]), (0, lo[0]), (0, hi[0]),
         (1, lo[1]), (1, hi[1])], start=1
    ):
        on = np.all(np.isclose(verts[trias][:, :, ax], val), axis=1)
        refs[on & (refs == 0)] = r
    return refs


def _grid_mesh(nx):
    """(verts, tets) of [0,1]^3 sliced into nx Freudenthal slabs
    along x. nx=1 with a z-split of 2 gives the canonical 12/12 cube."""
    vid = {}
    verts = []

    def v(i, j, k, scale):
        key = (i, j, k)
        if key not in vid:
            vid[key] = len(verts)
            verts.append([i * scale[0], j * scale[1], k * scale[2]])
        return vid[key]

    tets = []
    for bx in range(nx):
        box = {
            (i, j, k): v(bx + i, j, k, (1.0 / nx, 1.0, 1.0))
            for i in (0, 1) for j in (0, 1) for k in (0, 1)
        }
        tets.extend(_freudenthal_box(box))
    verts = np.asarray(verts, np.float64)
    return verts, _orient_positive(verts, tets)


def _synth_cube(dirpath):
    """cube.mesh + cube-met.sol: unit cube as two stacked z-slabs —
    12 vertices, 12 tets, every vertex on the surface, volume 1."""
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit

    vid = {}
    verts = []

    def v(i, j, k):
        key = (i, j, k)
        if key not in vid:
            vid[key] = len(verts)
            verts.append([float(i), float(j), k * 0.5])
        return vid[key]

    tets = []
    for bz in range(2):
        box = {
            (i, j, k): v(i, j, bz + k)
            for i in (0, 1) for j in (0, 1) for k in (0, 1)
        }
        tets.extend(_freudenthal_box(box))
    verts = np.asarray(verts, np.float64)
    tets = _orient_positive(verts, tets)
    trias = _boundary_trias(tets)
    from parmmg_tpu.core import tags as _tags

    # every input vertex is REQUIRED, like the reference example's
    # coarse cube: all 12 sit on ridges/corners, and the collapse
    # discipline tests expect the input skeleton to be preserved
    m = Mesh.from_numpy(
        verts, tets, trias=trias,
        trrefs=_tria_plane_refs(verts, trias),
        vtags=np.full(len(verts), _tags.REQUIRED, np.int32),
    )
    mesh_path = str(dirpath / "cube.mesh")
    medit.save_mesh(m, mesh_path)
    medit.save_sol(
        str(dirpath / "cube-met.sol"),
        np.full((len(verts), 1), 0.5),
        [medit.SOL_SCALAR],
    )
    return mesh_path


def _synth_wave(dirpath):
    """wave.{0..3}.mesh: 4 x-slabs with ParallelTriangleCommunicators
    (each interface tria shared, by global id, with its neighbor)."""
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit

    gverts, gtets = _grid_mesh(4)
    # global tria numbering over sorted-vertex keys
    gid_of = {}

    def tri_gid(key):
        if key not in gid_of:
            gid_of[key] = len(gid_of)
        return gid_of[key]

    paths = []
    for r in range(4):
        sel = np.all(
            (gverts[gtets][:, :, 0] >= r / 4 - 1e-9)
            & (gverts[gtets][:, :, 0] <= (r + 1) / 4 + 1e-9),
            axis=1,
        )
        tets_r = gtets[sel]
        used = np.unique(tets_r)
        l_of = {int(g): i for i, g in enumerate(used)}
        ltets = np.vectorize(l_of.get)(tets_r).astype(np.int32)
        lverts = gverts[used]
        trias = _boundary_trias(ltets)
        comms = {}
        for t, tri in enumerate(trias):
            x = lverts[tri][:, 0]
            for nb, plane in ((r - 1, r / 4), (r + 1, (r + 1) / 4)):
                if 0 <= nb < 4 and np.allclose(x, plane):
                    key = tuple(sorted(int(used[v]) for v in tri))
                    comms.setdefault(nb, ([], []))
                    comms[nb][0].append(t)
                    comms[nb][1].append(tri_gid(key))
        face_comms = [
            (nb, np.asarray(loc, np.int64), np.asarray(glob, np.int64))
            for nb, (loc, glob) in sorted(comms.items())
        ]
        m = Mesh.from_numpy(
            lverts, ltets, trias=trias,
            trrefs=_tria_plane_refs(lverts, trias),
        )
        p = str(dirpath / f"wave.{r}.mesh")
        medit.save_mesh(m, p, face_comms=face_comms)
        paths.append(p)
    return paths


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Workaround for a jaxlib CPU-compiler segfault: after many large
    programs have been compiled in one process, the NEXT big compile can
    crash inside `backend_compile_and_load` (reproducible at the first
    test_m5_surface compile when the whole suite runs in one process;
    the same test passes standalone). Dropping the executable caches
    between modules keeps the compiler state small."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def _synth_dir(tmp_path_factory):
    return tmp_path_factory.mktemp("synth_reference")


@pytest.fixture(scope="session")
def cube_mesh_path(_synth_dir):
    if (REF_EX0 / "cube.mesh").exists():
        return str(REF_EX0 / "cube.mesh")
    if not (_synth_dir / "cube.mesh").exists():
        _synth_cube(_synth_dir)
    return str(_synth_dir / "cube.mesh")


@pytest.fixture(scope="session")
def cube_met_path(_synth_dir):
    if (REF_EX0 / "cube-met.sol").exists():
        return str(REF_EX0 / "cube-met.sol")
    if not (_synth_dir / "cube-met.sol").exists():
        _synth_cube(_synth_dir)
    return str(_synth_dir / "cube-met.sol")


@pytest.fixture(scope="session")
def wave_shard_paths(_synth_dir):
    if (REF_EX1 / "wave.0.mesh").exists():
        return [str(REF_EX1 / f"wave.{r}.mesh") for r in range(4)]
    if not (_synth_dir / "wave.0.mesh").exists():
        _synth_wave(_synth_dir)
    return [str(_synth_dir / f"wave.{r}.mesh") for r in range(4)]
