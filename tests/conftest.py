"""Test harness: simulate an 8-device mesh on CPU, mirroring how the
reference exercises its distributed path with oversubscribed mpiexec ranks
(SURVEY.md §4).

The surrounding environment pins JAX to a single-chip TPU tunnel (an `axon`
PJRT plugin registered by sitecustomize at interpreter start, with
JAX_PLATFORMS=axon). jax initializes *every* registered backend factory on
first use regardless of JAX_PLATFORMS, so to keep tests hermetic and offline
we deregister the accelerator factories before any backend exists, then pin
the CPU platform with 8 virtual devices and x64 for exact geometry checks."""

import os

# silence the cpu_aot_loader pseudo-feature ERROR spam (see cache note
# below); must be set before jax/xla load
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

assert not _xb._default_backend, "conftest must run before jax backend init"
for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Persistent compile cache for the CPU suite (round-5): the round-2-era
# segfault in executable (de)serialization no longer reproduces on this
# tree — measured warm adapt 50.4 s -> 6.1 s (8x). The cpu_aot_loader
# logs a noisy per-load "machine feature +prefer-no-scatter not
# supported" ERROR; those are XLA's own scheduling pseudo-features on a
# same-machine cache, not real ISA features, so the loads are safe —
# TF_CPP_MIN_LOG_LEVEL=3 (set above, before jax import) silences them.
# PARMMG_NO_CPU_CACHE=1 restores the uncached behavior.
if not os.environ.get("PARMMG_NO_CPU_CACHE"):
    _cache = os.path.join(os.path.dirname(__file__), ".jax_cache_cpu")
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 1)

import pathlib  # noqa: E402

import pytest  # noqa: E402

REF_EX0 = pathlib.Path("/root/reference/libexamples/adaptation_example0")
REF_EX1 = pathlib.Path("/root/reference/libexamples/adaptation_example1")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running gate (bench-scale workloads)"
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Workaround for a jaxlib CPU-compiler segfault: after many large
    programs have been compiled in one process, the NEXT big compile can
    crash inside `backend_compile_and_load` (reproducible at the first
    test_m5_surface compile when the whole suite runs in one process;
    the same test passes standalone). Dropping the executable caches
    between modules keeps the compiler state small."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def cube_mesh_path():
    return str(REF_EX0 / "cube.mesh")


@pytest.fixture(scope="session")
def cube_met_path():
    return str(REF_EX0 / "cube-met.sol")


@pytest.fixture(scope="session")
def wave_shard_paths():
    return [str(REF_EX1 / f"wave.{r}.mesh") for r in range(4)]
