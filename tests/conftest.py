"""Test harness: simulate an 8-device mesh on CPU, mirroring how the
reference exercises its distributed path with oversubscribed mpiexec ranks
(SURVEY.md §4).

The surrounding environment pins JAX to a single-chip TPU tunnel (an `axon`
PJRT plugin registered by sitecustomize at interpreter start, with
JAX_PLATFORMS=axon). jax initializes *every* registered backend factory on
first use regardless of JAX_PLATFORMS, so to keep tests hermetic and offline
we deregister the accelerator factories before any backend exists, then pin
the CPU platform with 8 virtual devices and x64 for exact geometry checks."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

assert not _xb._default_backend, "conftest must run before jax backend init"
for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# NOTE: jax_compilation_cache_dir is deliberately NOT set — this
# jaxlib's executable (de)serialization segfaults on the CPU backend
# (observed in both the write path and get_executable_and_time), so the
# persistent compile cache is unsafe here.

import pathlib  # noqa: E402

import pytest  # noqa: E402

REF_EX0 = pathlib.Path("/root/reference/libexamples/adaptation_example0")
REF_EX1 = pathlib.Path("/root/reference/libexamples/adaptation_example1")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running gate (bench-scale workloads)"
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Workaround for a jaxlib CPU-compiler segfault: after many large
    programs have been compiled in one process, the NEXT big compile can
    crash inside `backend_compile_and_load` (reproducible at the first
    test_m5_surface compile when the whole suite runs in one process;
    the same test passes standalone). Dropping the executable caches
    between modules keeps the compiler state small."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def cube_mesh_path():
    return str(REF_EX0 / "cube.mesh")


@pytest.fixture(scope="session")
def cube_met_path():
    return str(REF_EX0 / "cube-met.sol")


@pytest.fixture(scope="session")
def wave_shard_paths():
    return [str(REF_EX1 / f"wave.{r}.mesh") for r in range(4)]
