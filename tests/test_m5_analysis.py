"""Surface analysis tests: ridge/corner detection, normals, synthesis.

Models the reference's analysis acceptance criteria: the cube's 12 edges
are dihedral ridges and its 8 corners singular (`MMG5_setdhd`/`MMG5_singul`
semantics re-derived, reference `src/analys_pmmg.c:2001,1679`), while a
smooth sphere has no features at the default 45-degree threshold.
"""

import numpy as np
import pytest

from parmmg_tpu.core import tags
from parmmg_tpu.ops import analysis
from parmmg_tpu.utils.gen import unit_ball_mesh, unit_cube_mesh


@pytest.fixture(scope="module")
def cube():
    return analysis.analyze(unit_cube_mesh(3))


def test_cube_ridges_are_the_12_edges(cube):
    ed = np.asarray(cube.edtag)
    em = np.asarray(cube.edmask)
    ridge = ((ed & tags.RIDGE) != 0) & em
    # n=3: each of the 12 cube edges is 3 segments
    assert ridge.sum() == 36
    # every ridge edge segment lies on a cube edge: two coordinates at {0,1}
    ev = np.asarray(cube.edge)[ridge]
    pts = np.asarray(cube.vert)[ev].reshape(-1, 3)
    on_extreme = (np.abs(pts) < 1e-9) | (np.abs(pts - 1.0) < 1e-9)
    assert (on_extreme.sum(axis=1) >= 2).all()


def test_cube_corners(cube):
    vt = np.asarray(cube.vtag)
    vm = np.asarray(cube.vmask)
    corner = ((vt & tags.CORNER) != 0) & vm
    assert corner.sum() == 8
    pts = np.asarray(cube.vert)[corner]
    on_extreme = (np.abs(pts) < 1e-9) | (np.abs(pts - 1.0) < 1e-9)
    assert (on_extreme.sum(axis=1) == 3).all()


def test_cube_ridge_vertices(cube):
    vt = np.asarray(cube.vtag)
    vm = np.asarray(cube.vmask)
    ridge_v = ((vt & tags.RIDGE) != 0) & vm
    # 12 edges x 2 interior verts + 8 corners
    assert ridge_v.sum() == 32
    # feature vertices are also boundary
    assert ((vt[ridge_v] & tags.BDY) != 0).all()


def test_sphere_has_no_features():
    m = analysis.analyze(unit_ball_mesh(6))
    ed = np.asarray(m.edtag)
    em = np.asarray(m.edmask)
    vt = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    assert (((ed & tags.RIDGE) != 0) & em).sum() == 0
    assert (((vt & tags.CORNER) != 0) & vm).sum() == 0


def test_ref_change_edges():
    # cube face refs differ side-to-side, so cube edges are REF edges too
    m = analysis.analyze(unit_cube_mesh(2))
    ed = np.asarray(m.edtag)
    em = np.asarray(m.edmask)
    ref = ((ed & tags.REF) != 0) & em
    assert ref.sum() == 24  # 12 edges x 2 segments at n=2


def test_vertex_normals_point_outward():
    m = analysis.analyze(unit_ball_mesh(6))
    vn = np.asarray(analysis.vertex_normals(m))
    vm = np.asarray(m.vmask)
    bdy = ((np.asarray(m.vtag) & tags.BDY) != 0) & vm
    p = np.asarray(m.vert)[bdy]
    n = vn[bdy]
    # outward radial: normal aligns with position on the sphere
    r = p / np.linalg.norm(p, axis=1, keepdims=True)
    dots = np.sum(n * r, axis=1)
    assert dots.min() > 0.7
    # interior verts get zero normal
    inte = vm & ~bdy
    assert np.abs(vn[inte]).max() == 0.0


def test_tria_normals_oriented_regardless_of_winding():
    m = unit_cube_mesh(2)
    # scramble tria winding
    tria = np.asarray(m.tria).copy()
    trmask = np.asarray(m.trmask)
    flip = np.arange(len(tria)) % 2 == 0
    tria[flip] = tria[flip][:, [1, 0, 2]]
    m = m.replace(tria=m.tria.at[:].set(tria))
    unit, area, ok = analysis.tria_normals(m)
    unit = np.asarray(unit)
    ok = np.asarray(ok) & trmask
    # every z=0-face tria normal must point to -z despite winding
    c = np.asarray(m.vert)[tria]
    on_bottom = ok & np.all(np.abs(c[..., 2]) < 1e-9, axis=1)
    assert on_bottom.sum() > 0
    assert (unit[on_bottom][:, 2] < -0.99).all()


def test_synthesize_missing_trias():
    import jax.numpy as jnp

    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.utils.gen import unit_cube

    raw = unit_cube(2)
    m = Mesh.from_numpy(raw["verts"], raw["tets"])  # no trias given
    m = analysis.analyze(m)
    # 6 faces x 2*n^2 trias
    assert int(m.ntria) == 48
    # idempotent: re-running does not duplicate
    m2 = analysis.analyze(m)
    assert int(m2.ntria) == 48
    # and the synthesized cube still gets its 12 ridge edges (here each
    # edge is 2 segments)
    ed = np.asarray(m2.edtag)
    em = np.asarray(m2.edmask)
    assert (((ed & tags.RIDGE) != 0) & em).sum() == 24


def test_internal_interface_not_fake_ridged():
    """A flat internal material interface (trias with two owner tets of
    different refs) must get consistently oriented normals — per-tria
    arbitrary owner choice would make neighbors antiparallel and tag the
    whole flat interface as ridges/corners, freezing it solid."""
    import jax.numpy as jnp

    from parmmg_tpu.core.mesh import FACE_VERTS, Mesh
    from parmmg_tpu.utils.gen import unit_cube

    raw = unit_cube(2)
    verts, tets = raw["verts"], raw["tets"]
    bary_z = verts[tets].mean(axis=1)[:, 2]
    trefs = np.where(bary_z < 0.5, 1, 2)
    # internal trias: tet faces lying in the z=0.5 plane
    fv = tets[:, FACE_VERTS].reshape(-1, 3)
    on_mid = np.all(np.abs(verts[fv][:, :, 2] - 0.5) < 1e-12, axis=1)
    mid = np.unique(np.sort(fv[on_mid], axis=1), axis=0)
    trias = np.concatenate([raw["trias"], mid])
    trrefs = np.concatenate(
        [raw["trrefs"], np.full(len(mid), 9, np.int64)]
    )
    m = Mesh.from_numpy(verts, tets, trefs=trefs, trias=trias,
                        trrefs=trrefs)
    m = analysis.analyze(m)
    vt = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    # the interface's interior vertex (center of the cube face plane,
    # (0.5,0.5,0.5)) must be neither CORNER nor RIDGE
    center = np.all(np.abs(np.asarray(m.vert) - 0.5) < 1e-12, axis=1) & vm
    assert center.sum() == 1
    assert (vt[center] & (tags.CORNER | tags.RIDGE)) == 0
    # but it is a REF-surface vertex (internal interface detected)
    assert (vt[center] & tags.BDY) != 0


def test_nonmanifold_fan_detection():
    import jax.numpy as jnp

    from parmmg_tpu.core.mesh import Mesh

    # two tets sharing face (0,1,3), plus a dangling tria on edge (0,1):
    # that edge is then in 3+ surface trias -> non-manifold fan
    verts = np.array(
        [
            [0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1],
            [0, -1, 0], [0.5, 0.3, -1.0],
        ],
        float,
    )
    tets = np.array([[0, 1, 2, 3], [0, 1, 3, 4]])
    m = Mesh.from_numpy(verts, tets, trias=np.array([[0, 1, 5]]))
    m = analysis.analyze(m)
    ed = np.asarray(m.edtag)
    em = np.asarray(m.edmask)
    ev = np.asarray(m.edge)
    nom = ((ed & tags.NOM) != 0) & em
    keys = {tuple(sorted(e)) for e in ev[nom]}
    assert (0, 1) in keys
