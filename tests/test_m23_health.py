"""M23: the run-health observatory — unit-length edge telemetry,
termination verdicts, live status endpoint.

Coverage of round 12 (`obs/health.py`, the `quality` length-stats
additions, `service/status.py` run endpoint):

- edge-length histogram exactness: device-side `mesh_length_stats`
  against an independent numpy reference on the tiny fixture (same
  metric-length formula, the reference's exact `bd[9]` bins);
- sharded-vs-central parity: the jit(shard_map)+psum world reduction
  equals the vmapped host merge bit-for-bit;
- the verdict matrix: converged / stalled (forced ``max_sweeps=1``) /
  oscillating (seeded split<->collapse churn) / budget_exhausted;
- NaN / empty-set formatter safety (the divide-by-ne=0 family);
- the live run endpoint: ``run_status_text`` over HTTP per the m21
  scrape pattern, run-state gauges included.
"""

import math
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parmmg_tpu.core import adjacency, metric as metric_mod
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.obs import health, metrics as obs_metrics
from parmmg_tpu.ops import quality
from parmmg_tpu.utils.gen import unit_cube_mesh


def _prepared_mesh(n=2, perturb=0.12):
    """Tiny fixture with a nontrivial iso metric so lengths spread
    across bins."""
    mesh = unit_cube_mesh(n, perturb=perturb, seed=3)
    # graded sizes: h in [0.18, 0.55] by x-coordinate
    h = 0.18 + 0.37 * mesh.vert[:, 0:1]
    return mesh.replace(met=jnp.asarray(h, mesh.vert.dtype))


# ---------------------------------------------------------------------------
# edge-length histogram exactness vs numpy
# ---------------------------------------------------------------------------


def test_length_stats_match_numpy_reference():
    mesh = _prepared_mesh()
    ls = quality.mesh_length_stats(mesh)

    ecap = int(mesh.tcap * 1.7) + 64
    edges, emask, _, _ = adjacency.unique_edges(mesh, ecap)
    e = np.asarray(jax.device_get(edges))
    m = np.asarray(jax.device_get(emask))
    vert = np.asarray(jax.device_get(mesh.vert))
    met = np.asarray(jax.device_get(mesh.met))

    p0, p1 = vert[e[:, 0]], vert[e[:, 1]]
    h0, h1 = met[e[:, 0], 0], met[e[:, 1], 0]
    d = np.linalg.norm(p1 - p0, axis=-1)
    # the iso metric length formula (metric.edge_length_iso)
    ln = d * 0.5 * (1.0 / h0 + 1.0 / h1)
    ln = ln[m]
    assert ln.size == int(ls.nedge) > 0

    assert np.isclose(float(ls.lmin), ln.min())
    assert np.isclose(float(ls.lmax), ln.max())
    assert np.isclose(float(ls.lavg), ln.mean())
    lshrt, llong = metric_mod.LSHRT, metric_mod.LLONG
    assert int(ls.n_small) == int((ln < lshrt).sum())
    assert int(ls.n_large) == int((ln > llong).sum())
    assert int(ls.n_unit) == int(
        ((ln >= lshrt) & (ln <= llong)).sum()
    )
    assert np.isclose(
        quality.in_band_fraction(ls),
        ((ln >= lshrt) & (ln <= llong)).mean(),
    )
    # the reference's exact bd[9] bin bounds
    bd = np.array([0.0, 0.3, 0.6, lshrt, 0.9, 1.3, llong, 2.0, 5.0])
    want = np.zeros(bd.size + 1, int)
    for k, c in zip(np.searchsorted(bd, ln), np.ones_like(ln, int)):
        want[k] += c
    got = np.asarray(jax.device_get(ls.counts))
    assert got.tolist() == want.tolist()
    assert got.sum() == ln.size


def test_length_stats_doc_json_safe_and_consistent():
    mesh = _prepared_mesh()
    ls = quality.mesh_length_stats(mesh)
    doc = quality.length_stats_doc(ls)
    import json

    json.dumps(doc)  # strictly serializable
    assert doc["nedge"] == int(ls.nedge)
    assert doc["n_small"] + doc["n_unit"] + doc["n_large"] \
        == doc["nedge"]
    assert sum(doc["counts"]) == doc["nedge"]
    assert doc["in_band"] == round(quality.in_band_fraction(ls), 6)


def test_empty_length_stats_formats_without_nan_or_div0():
    mesh = _prepared_mesh()
    ecap = int(mesh.tcap * 1.7) + 64
    edges, emask, _, _ = adjacency.unique_edges(mesh, ecap)
    ls = quality.length_stats(mesh, edges, jnp.zeros_like(emask))
    assert int(ls.nedge) == 0
    text = quality.format_length_stats(ls)
    assert "--" in text and "nan" not in text and "inf" not in text
    doc = quality.length_stats_doc(ls)
    assert doc["lmin"] is None and doc["lmax"] is None
    assert doc["lavg"] == 0.0  # sum over max(nedge, 1): finite
    assert doc["in_band"] == 0.0
    # the post-mortem renderer is None-safe too
    assert "--" in health.render_length_doc(doc)


def test_format_histogram_safe_on_empty_and_nonfinite():
    h = quality.QualityHisto(
        ne=jnp.int32(0), qmin=jnp.inf, qmax=-jnp.inf,
        qavg=jnp.nan, worst_elt=jnp.int32(-1), nbad=jnp.int32(0),
        ninverted=jnp.int32(0), counts=jnp.zeros(5, jnp.int32),
        worst_shard=jnp.int32(-1),
    )
    text = quality.format_histogram(h)
    assert "nan" not in text and "inf" not in text
    assert "--" in text
    assert "0.00 %" in text  # percentages divide by max(ne, 1)


# ---------------------------------------------------------------------------
# sharded vs central merge parity
# ---------------------------------------------------------------------------


def test_sharded_length_stats_match_stacked_merge():
    from parmmg_tpu.parallel import shard as shard_mod
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition

    mesh = _prepared_mesh(n=3)
    nparts = 4
    part = np.asarray(jax.device_get(sfc_partition(mesh, nparts)))
    stacked, _comm = split_mesh(mesh, part, nparts)

    dmesh = shard_mod.device_mesh(nparts)
    world = shard_mod.sharded_length_stats(stacked, dmesh)

    ecap = int(stacked.tet.shape[1] * 1.7) + 64
    per_shard = jax.vmap(
        lambda m: quality.mesh_length_stats(m, ecap)
    )(stacked)
    merged = quality.merge_stacked_length_stats(per_shard)

    assert int(world.nedge) == int(merged.nedge) > 0
    assert float(world.lmin) == pytest.approx(float(merged.lmin))
    assert float(world.lmax) == pytest.approx(float(merged.lmax))
    assert float(world.lavg) == pytest.approx(float(merged.lavg))
    for f in ("n_small", "n_large", "n_unit"):
        assert int(getattr(world, f)) == int(getattr(merged, f))
    assert jax.device_get(world.counts).tolist() \
        == jax.device_get(merged.counts).tolist()


# ---------------------------------------------------------------------------
# the verdict matrix
# ---------------------------------------------------------------------------


def _rec(it, sw, nsplit=0, ncollapse=0, nswap=0, ne=1000,
         n_unique=500, n_active=100, capped=False, **kw):
    r = dict(iter=it, sweep=sw, nsplit=nsplit, ncollapse=ncollapse,
             nswap=nswap, nmoved=0, ne=ne, np=300, n_unique=n_unique,
             n_active=n_active, capped=capped)
    r.update(kw)
    return r


def test_verdict_converged_by_driver_rule():
    hist = [_rec(0, 0, nsplit=200), _rec(0, 1, nsplit=2)]
    v = health.assess(hist, converge_frac=0.005)
    assert v["verdict"] == "converged"
    assert v["sweeps"] == 2 and v["iterations"] == 1


def test_verdict_converged_by_drained_frontier():
    hist = [_rec(0, 0, nsplit=200),
            _rec(0, 1, nsplit=50, n_active=0, skipped=True)]
    v = health.assess(hist)
    assert v["verdict"] == "converged"
    assert "drained" in v["reason"]


def test_verdict_stalled_on_forced_single_sweep():
    # one capped sweep with real work: no convergence, no decay
    # evidence — must be stalled, never converged
    hist = [_rec(0, 0, nsplit=300, capped=True)]
    v = health.assess(hist, max_sweeps=1)
    assert v["verdict"] == "stalled"


def test_verdict_oscillating_on_seeded_churn():
    # seeded split<->collapse thrash: sweep k's splits undone by sweep
    # k+1's collapses, sustained across the window
    hist = [
        _rec(0, 0, nsplit=100, ncollapse=5),
        _rec(0, 1, nsplit=8, ncollapse=95),
        _rec(0, 2, nsplit=90, ncollapse=10),
        _rec(0, 3, nsplit=12, ncollapse=88),
        _rec(0, 4, nsplit=85, ncollapse=9, capped=True),
    ]
    v = health.assess(hist, max_sweeps=5)
    assert v["verdict"] == "oscillating"
    assert v["churn"]["sustained"] is True
    assert v["churn"]["max_score"] > health.CHURN_MIN_FRACTION


def test_verdict_budget_exhausted_on_decay():
    hist = [
        _rec(0, 0, nsplit=400),
        _rec(0, 1, nsplit=250),
        _rec(0, 2, nsplit=120, capped=True),
    ]
    v = health.assess(hist, max_sweeps=3)
    assert v["verdict"] == "budget_exhausted"


def test_verdict_empty_history_is_stalled():
    v = health.assess([])
    assert v["verdict"] == "stalled"
    assert v["sweeps"] == 0


def test_forced_stall_end_to_end_not_converged():
    # the acceptance criterion: a real max_sweeps=1 run must be judged
    # stalled by the driver's own exit emit
    obs_metrics.registry().reset()
    health.run_state().reset()
    _out, info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(hsiz=0.35, niter=1, max_sweeps=1, hgrad=None,
                     polish_sweeps=0),
    )
    assert info["health"]["verdict"] == "stalled"
    assert info["health"]["verdict"] in health.VERDICTS
    # and every sweep record carried the unit-band fraction
    recs = [r for r in info["history"] if "nsplit" in r]
    assert recs and all("in_band" in r for r in recs)
    assert health.history_in_band(info["history"]) is not None


def test_drain_curve_eta():
    recs = [_rec(0, k, n_active=400 - 100 * k) for k in range(4)]
    d = health.drain_curve(recs)
    assert d["series"] == [0.8, 0.6, 0.4, 0.2]
    assert d["eta_sweeps"] == pytest.approx(1.0)
    # flat series: not draining
    flat = health.drain_curve([_rec(0, k) for k in range(3)])
    assert flat["eta_sweeps"] is None


def test_churn_scores_pairwise():
    recs = [
        _rec(0, 0, nsplit=100, ncollapse=0),
        _rec(0, 1, nsplit=0, ncollapse=100),
        _rec(1, 0, nsplit=50),  # new iteration: pair not scored
    ]
    s = health.churn_scores(recs)
    assert len(s) == 1 and s[0] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# live run endpoint (m21 scrape pattern over run_status_text)
# ---------------------------------------------------------------------------


def test_run_status_http_endpoint_scrapes():
    from parmmg_tpu.service import StatusServer, run_status_text

    obs_metrics.registry().reset()
    health.run_state().reset()
    obs_metrics.record_sweep(dict(
        nsplit=7, ncollapse=3, nswap=1, nmoved=2, n_active=40,
        n_unique=100, in_band=0.625, iter=0, sweep=0, ne=100, np=30,
    ))
    health.run_state().update(phase="sweeps", iteration=0,
                              driver="centralized")
    status = StatusServer(render=run_status_text, port=0).start()
    try:
        base = f"http://{status.host}:{status.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            assert r.status == 200
            assert "version=0.0.4" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "parmmg_ops_split_accepted 7" in body
        assert "parmmg_sweeps 1" in body
        assert 'parmmg_run_phase{phase="sweeps"} 1' in body
        assert "parmmg_len_in_band 0.625" in body
        # len/in_band must appear exactly once per exposition (one
        # sample line; the other match is its # TYPE header)
        samples = [ln for ln in body.splitlines()
                   if ln.startswith("parmmg_len_in_band ")]
        assert len(samples) == 1
        assert "parmmg_run_heartbeat_age_s" in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert r.read() == b"ok\n"
    finally:
        status.close()
        obs_metrics.registry().reset()
        health.run_state().reset()


def test_serve_run_from_env_contract(monkeypatch):
    from parmmg_tpu.service import serve_run_from_env

    monkeypatch.delenv("PMMGTPU_STATUS_PORT", raising=False)
    assert serve_run_from_env() is None
    monkeypatch.setenv("PMMGTPU_STATUS_PORT", "0")
    health.run_state().reset()
    srv = serve_run_from_env()
    try:
        assert srv is not None and srv.port > 0
        st = health.run_state().snapshot()
        assert st["status_port"] == srv.port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ).read().decode()
        assert "parmmg_run_phase" in body
    finally:
        if srv is not None:
            srv.close()
        health.run_state().reset()


def test_run_state_note_sweep_tracks_drain():
    rs = health.RunState()
    for k in range(4):
        rs.note_sweep(dict(sweep=k, in_band=0.5 + 0.1 * k,
                           n_active=400 - 100 * k, n_unique=500))
    snap = rs.snapshot()
    assert snap["sweep"] == 3
    assert snap["in_band"] == pytest.approx(0.8)
    assert snap["active_fraction"] == pytest.approx(0.2)
    assert snap["drain_eta_sweeps"] == pytest.approx(1.0)
    assert snap["heartbeat_age_s"] >= 0.0


# ---------------------------------------------------------------------------
# emit + post-mortem reconstruction round trip
# ---------------------------------------------------------------------------


def test_emit_and_health_summary_round_trip(tmp_path):
    from parmmg_tpu.obs import report as obs_report
    from parmmg_tpu.obs import trace as obs_trace

    d = str(tmp_path)
    tr = obs_trace.Tracer(d)
    hist = [
        _rec(0, 0, nsplit=400, in_band=0.41),
        _rec(0, 1, nsplit=2, in_band=0.83),
    ]
    mesh = _prepared_mesh()
    doc = quality.length_stats_doc(quality.mesh_length_stats(mesh))
    verdict = health.assess(hist)
    health.emit_run_health(hist, length_doc=doc, verdict=verdict,
                           tracer=tr)
    tr.flush()

    s = obs_report.health_summary(d)
    assert s["verdict"]["verdict"] == "converged"
    assert s["length"]["nedge"] == doc["nedge"]
    assert s["in_band"] == pytest.approx(0.83)
    assert len(s["history"]) == 2
    text = obs_report.render_health(d)
    for want in ("verdict: converged", "UNIT EDGE LENGTHS",
                 "sweep history", "drain curve"):
        assert want in text, (want, text)
    # reassessment path: a dir whose verdict event is missing
    d2 = str(tmp_path / "partial")
    tr2 = obs_trace.Tracer(d2)
    health.emit_run_health(hist, tracer=tr2)
    tr2.flush()
    s2 = obs_report.health_summary(d2)
    assert s2["verdict"]["verdict"] == "converged"
    assert s2["verdict"]["reassessed"] is True


def test_format_history_rows_single_formatter():
    hist = [_rec(0, 0, nsplit=12, in_band=0.5, capped=True)]
    text = health.format_history_rows(hist)
    assert "split=    12" in text
    assert "band=" in text and "CAP" in text


def test_history_event_cap_bounds_rows():
    hist = [_rec(0, k, nsplit=1) for k in range(
        health.HISTORY_EVENT_CAP + 40)]
    rows = health._compact_rows(health.sweep_records(hist))
    assert len(rows) == health.HISTORY_EVENT_CAP + 40
    # the emit path truncates (covered via the event payload shape)
    dropped = max(len(rows) - health.HISTORY_EVENT_CAP, 0)
    assert dropped == 40
