"""M20: notice-driven elastic autoscaling — the in-process half.

Unit coverage for what `tools/chaos_smoke.py --elastic` drives end to
end through `tools/fleet.py` (a real 2-rank world absorbing a
preemption notice, shrinking to 1, growing back on the capacity
signal):

- the store-backed membership protocol (`parallel.elastic`): manifest
  publish/read, per-rank reform requests, exit acks, epoch discovery;
- the coordinator's boundary poll: notice→shrink and capacity→grow
  decisions, the typed per-role exit errors (departure = preemption
  family, survivor = `WorldReformError`→exit 90), the
  `UnreformableWorldError` refusal below the minimum world;
- world-transition observability: `world_shrink`/`world_grow` events
  with downtime seconds, and the `obs_report --chaos` world-size
  timeline section;
- the capacity-restored signal trio (file / callback / programmatic),
  symmetric to the preemption-notice sources, including the
  auto-unlatch of cancelled polled sources (the PR-11 notice bugfix:
  a cancelled maintenance event must stop forcing per-iteration
  commits and leave a ``preempt_notice_cleared`` record);
- driver-level elastic GROW: `_resume_stacked` re-cuts onto more
  shards with the frontier reset to all-active and the cached comm
  capacity dropped, and (slow) a full grow-under-way run through
  `adapt_distributed` — reform raised mid-run, resumed at the larger
  layout, quality within the m9-class gate.
"""

import json
import os

import jax
import numpy as np
import pytest

from parmmg_tpu import failsafe
from parmmg_tpu.core.tags import ReturnStatus
from parmmg_tpu.io import ckpt_store
from parmmg_tpu.models.distributed import (
    DistOptions,
    _resume_stacked,
    adapt_distributed,
    merge_adapted,
)
from parmmg_tpu.obs import report as obs_report, trace as obs_trace
from parmmg_tpu.parallel import elastic, multihost
from parmmg_tpu.parallel.distribute import split_mesh
from parmmg_tpu.parallel.partition import sfc_partition
from parmmg_tpu.utils.gen import unit_cube_mesh

C_OPTS = dict(hsiz=0.45, niter=2, max_sweeps=2, hgrad=None,
              polish_sweeps=0)


@pytest.fixture(autouse=True)
def _clean_signals():
    yield
    multihost.clear_preemption_notice()
    multihost.set_preemption_callback(None)
    multihost.clear_capacity_signal()
    multihost.set_capacity_callback(None)
    elastic._NOTED_EPOCHS.clear()


def _mem_store(name):
    ckpt_store.memory_bucket(name).clear()
    return ckpt_store.make_store(f"mem://{name}", None)


def _events(dirpath, name=None):
    recs = []
    for fn in os.listdir(dirpath):
        if not fn.startswith("events_rank"):
            continue
        with open(os.path.join(dirpath, fn)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("type") == "event" and (
                    name is None or rec.get("name") == name
                ):
                    recs.append(rec)
    return recs


# ---------------------------------------------------------------------------
# store-backed membership protocol
# ---------------------------------------------------------------------------


def test_manifest_reform_ack_roundtrip():
    store = _mem_store("m20-proto")
    assert elastic.latest_epoch(store) is None
    assert elastic.read_manifest(store, 0) is None
    doc = elastic.publish_manifest(store, 0, world=2, members=[0, 1],
                                   target_world=2, reason="launch")
    assert elastic.read_manifest(store, 0) == doc
    elastic.publish_manifest(store, 1, world=1, members=[0],
                             target_world=2, reason="shrink")
    assert elastic.latest_epoch(store) == 1
    # per-rank reform records never conflict; corrupt ones are skipped
    assert elastic.reform_records(store, 0) == []
    store.put_json(elastic.REFORM_FMT.format(0, 1),
                   dict(epoch=0, rank=1, kind="shrink", ts=10.0))
    store.put(elastic.REFORM_FMT.format(0, 0), b"{not json")
    recs = elastic.reform_records(store, 0)
    assert len(recs) == 1 and recs[0]["rank"] == 1
    # acks: best-effort, newest ts wins, absent -> None
    assert elastic.last_ack_ts(store, 0) is None
    elastic.write_exit_ack(store, 0, 1, "departing", "shrink")
    elastic.write_exit_ack(store, 0, 0, "survivor", "shrink")
    ts = elastic.last_ack_ts(store, 0)
    assert ts is not None and ts > 0


def test_fleet_manifest_matches_worker_protocol(tmp_path):
    """The jax-free supervisor half (tools/fleet.py) writes manifests
    the worker-side coordinator reads — one format, two writers."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "parmmg_fleet", os.path.join(root, "tools", "fleet.py")
    )
    fleet = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet)

    ck = str(tmp_path / "ck")
    fleet.publish_manifest(ck, 3, members=[0, 2], target=2,
                           reason="grow: capacity restored")
    store = ckpt_store.make_store(ck, None)
    doc = elastic.read_manifest(store, 3)
    assert doc is not None
    assert doc["world"] == 2 and doc["members"] == [0, 2]
    assert doc["target_world"] == 2 and doc["epoch"] == 3
    assert elastic.latest_epoch(store) == 3
    # and the fleet can read back the worker's reform records
    store.put_json(elastic.REFORM_FMT.format(3, 0),
                   dict(epoch=3, rank=0, kind="grow", ts=1.0))
    assert fleet.reform_kinds(ck, 3) == {"grow"}


# ---------------------------------------------------------------------------
# coordinator decisions
# ---------------------------------------------------------------------------


def test_coordinator_disarmed_without_env(monkeypatch):
    monkeypatch.delenv("PMMGTPU_ELASTIC", raising=False)
    assert elastic.coordinator_from_env(_mem_store("m20-off")) is None
    assert elastic.coordinator_from_env(None) is None


def test_poll_without_signals_is_noop():
    store = _mem_store("m20-noop")
    c = elastic.ElasticCoordinator(store, epoch=0, rank=0, world=1,
                                   target_world=1)
    assert c.poll(0) is None
    assert elastic.reform_records(store, 0) == []


def test_notice_driven_shrink_decision():
    store = _mem_store("m20-shrink")
    c1 = elastic.ElasticCoordinator(store, epoch=0, rank=1, world=2,
                                    target_world=2)
    multihost.request_preemption_notice("maintenance event")
    d = c1.poll(1)
    assert d is not None and d.kind == "shrink"
    assert d.departing == (1,) and d.new_world == 1 and d.old_world == 2
    # the departure record is durable BEFORE the vote returned
    recs = elastic.reform_records(store, 0)
    assert [r["kind"] for r in recs] == ["shrink"]
    # per-role exits: the noticed rank leaves via the preemption
    # family, a survivor via the typed reform error (exit 90)
    assert isinstance(c1.error_for(d), failsafe.PreemptionError)
    c0 = elastic.ElasticCoordinator(store, epoch=0, rank=0, world=2,
                                    target_world=2)
    err = c0.error_for(d)
    assert isinstance(err, failsafe.WorldReformError)
    assert err.kind == "shrink" and err.new_world == 1
    # sealed exits leave acks; the decision is cached (poll is
    # idempotent once agreed)
    c1.ack_exit(d)
    c0.ack_exit(d)
    assert elastic.last_ack_ts(store, 0) is not None
    assert c1.poll(2) is d


def test_capacity_driven_grow_decision():
    store = _mem_store("m20-grow")
    c = elastic.ElasticCoordinator(store, epoch=2, rank=0, world=1,
                                   target_world=2)
    assert c.poll(0) is None          # no capacity signal yet
    multihost.request_capacity_restored("pool refilled")
    d = c.poll(1)
    assert d is not None and d.kind == "grow"
    assert (d.old_world, d.new_world) == (1, 2) and d.departing == ()
    err = c.error_for(d)
    assert isinstance(err, failsafe.WorldReformError)
    assert err.kind == "grow"
    # a world already AT target never grows on the signal
    store2 = _mem_store("m20-grow-at-target")
    c2 = elastic.ElasticCoordinator(store2, epoch=0, rank=0, world=1,
                                    target_world=1)
    assert c2.poll(0) is None
    assert elastic.reform_records(store2, 0) == []


def test_capacity_grow_is_batched_to_target():
    """One capacity-restored vote grows STRAIGHT to the target world:
    1 -> 4 is one reformation (one barrier + checkpoint + relaunch),
    not three single-step ones."""
    store = _mem_store("m20-batch-grow")
    c = elastic.ElasticCoordinator(store, epoch=0, rank=0, world=1,
                                   target_world=4)
    multihost.request_capacity_restored("pool refilled")
    d = c.poll(0)
    assert d is not None and d.kind == "grow"
    assert (d.old_world, d.new_world) == (1, 4) and d.departing == ()


def test_unreformable_world_refusal():
    store = _mem_store("m20-refuse")
    c = elastic.ElasticCoordinator(store, epoch=0, rank=0, world=1,
                                   target_world=1, min_world=1)
    multihost.request_preemption_notice("last rank preempted")
    with pytest.raises(elastic.UnreformableWorldError, match="minimum"):
        c.poll(0)


def test_agree_flags_single_process_identity():
    assert multihost.agree_flags(0) == 0
    assert multihost.agree_flags(3) == 3
    assert multihost.agree_flags(True) == 1


# ---------------------------------------------------------------------------
# capacity-signal sources + notice auto-unlatch (the PR-11 bugfix)
# ---------------------------------------------------------------------------


def test_capacity_signal_sources(tmp_path, monkeypatch):
    monkeypatch.delenv("PMMGTPU_CAPACITY_FILE", raising=False)
    assert not multihost.capacity_restored()
    # 1. marker file: present arms, removed auto-clears
    cap = tmp_path / "capacity"
    monkeypatch.setenv("PMMGTPU_CAPACITY_FILE", str(cap))
    assert not multihost.capacity_restored()
    cap.write_text("")
    assert multihost.capacity_restored()
    cap.unlink()
    assert not multihost.capacity_restored()
    # 2. callback probe, same auto-unlatch semantics
    state = {"up": True}
    multihost.set_capacity_callback(lambda: state["up"])
    assert multihost.capacity_restored()
    state["up"] = False
    assert not multihost.capacity_restored()
    # 3. explicit request is sticky until cleared
    multihost.request_capacity_restored("programmatic")
    state["up"] = False
    assert multihost.capacity_restored()
    multihost.clear_capacity_signal()
    assert not multihost.capacity_restored()


def test_cancelled_notice_stops_forcing_and_leaves_trace(tmp_path,
                                                         monkeypatch):
    """The satellite bugfix: a notice latched from a POLLED source
    (drain file / callback) auto-clears when the source cancels,
    emitting ``preempt_notice_cleared`` — so a cancelled maintenance
    event stops forcing per-iteration commits. Explicit requests stay
    sticky."""
    tr = obs_trace.Tracer(str(tmp_path / "obs"), costs=False, rank=0)
    prev = obs_trace.install(tr)
    try:
        drain = tmp_path / "drain"
        monkeypatch.setenv("PMMGTPU_PREEMPT_FILE", str(drain))
        drain.write_text("")
        assert multihost.preemption_notice()
        drain.unlink()
        # cancelled: the latch drops on the next poll, with a trace
        assert not multihost.preemption_notice()
        assert not multihost.preemption_notice()   # stays clear
        names = [e["name"] for e in _events(str(tmp_path / "obs"))]
        assert "preempt_notice" in names
        assert "preempt_notice_cleared" in names
        # explicit requests survive source silence until cleared
        multihost.request_preemption_notice("platform glue")
        assert multihost.preemption_notice()
        multihost.clear_preemption_notice()
        assert not multihost.preemption_notice()
    finally:
        obs_trace.install(prev)
        tr.flush()


def test_cancelled_notice_driver_level(tmp_path):
    """Driver-level regression: a notice that cancels after one
    boundary forces exactly ONE out-of-cadence commit — before the
    fix the latch survived cancellation and every later iteration
    committed too."""
    fired = {"n": 0}

    def probe():
        # truthy exactly once: the maintenance event is cancelled
        # before the next iteration boundary polls again
        fired["n"] += 1
        return fired["n"] == 1

    multihost.set_preemption_callback(probe)
    try:
        ck = tmp_path / "ck"
        from parmmg_tpu.models.adapt import AdaptOptions, adapt

        out, info = adapt(
            unit_cube_mesh(2),
            AdaptOptions(checkpoint_every=50, **C_OPTS),
            checkpoint_dir=str(ck),
        )
        assert info["status"] == ReturnStatus.SUCCESS
        names = sorted(os.listdir(ck))
        assert "ckpt_00000.json" in names, names
        assert "ckpt_00001.json" not in names, (
            "cancelled notice kept forcing commits", names,
        )
    finally:
        multihost.set_preemption_callback(None)
        multihost.clear_preemption_notice()


# ---------------------------------------------------------------------------
# world-transition observability
# ---------------------------------------------------------------------------


def test_transition_events_with_downtime(tmp_path):
    store = _mem_store("m20-trans")
    elastic.publish_manifest(store, 0, world=2, members=[0, 1],
                             target_world=2, reason="launch")
    elastic.write_exit_ack(store, 0, 0, "survivor", "shrink")
    elastic.write_exit_ack(store, 0, 1, "departing", "shrink")
    elastic.publish_manifest(store, 1, world=1, members=[0],
                             target_world=2,
                             reason="shrink: members [1] departed")
    elastic.publish_manifest(store, 2, world=2, members=[0, 2],
                             target_world=2,
                             reason="grow: capacity restored")
    tr = obs_trace.Tracer(str(tmp_path / "obs"), costs=False, rank=0)
    prev = obs_trace.install(tr)
    try:
        c1 = elastic.ElasticCoordinator(store, epoch=1, rank=0,
                                        world=1, target_world=2)
        assert c1.note_transition() == "world_shrink"
        assert c1.note_transition() is None     # idempotent per epoch
        c2 = elastic.ElasticCoordinator(store, epoch=2, rank=0,
                                        world=2, target_world=2)
        assert c2.note_transition() == "world_grow"
        # epoch 0 has no predecessor: no event
        elastic._NOTED_EPOCHS.clear()
        c0 = elastic.ElasticCoordinator(store, epoch=0, rank=0,
                                        world=2, target_world=2)
        assert c0.note_transition() is None
    finally:
        obs_trace.install(prev)
        tr.flush()
    shr = _events(str(tmp_path / "obs"), "world_shrink")
    gro = _events(str(tmp_path / "obs"), "world_grow")
    assert len(shr) == 1 and len(gro) == 1
    assert shr[0]["args"]["old"] == 2 and shr[0]["args"]["new"] == 1
    assert gro[0]["args"]["old"] == 1 and gro[0]["args"]["new"] == 2
    # downtime measured from the previous epoch's last ack (shrink)
    # or its manifest ts (grow: the world-1 epoch left no acks here)
    assert float(shr[0]["args"]["downtime_s"]) >= 0.0
    assert float(gro[0]["args"]["downtime_s"]) >= 0.0


def test_chaos_report_world_timeline(tmp_path):
    obs = tmp_path / "obs"
    obs.mkdir()
    lines = [
        dict(type="event", name="preempt_notice", ts_us=10, rank=0,
             args=dict(reason="drain")),
        dict(type="event", name="world_reform", ts_us=20, rank=0,
             args=dict(kind="shrink", epoch=0, old=2, new=1,
                       departing=[1])),
        dict(type="event", name="checkpoint_commit", ts_us=30, rank=0,
             args=dict(it=1, mode="sync")),
        dict(type="event", name="world_shrink", ts_us=5, rank=0,
             args=dict(old=2, new=1, epoch=1, downtime_s=3.25,
                       reason="shrink: members [1] departed")),
        dict(type="event", name="world_grow", ts_us=9, rank=0,
             args=dict(old=1, new=2, epoch=2, downtime_s=2.5,
                       reason="grow: capacity restored")),
    ]
    with open(obs / "events_rank0.jsonl", "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    s = obs_report.chaos_summary(str(obs))
    tl = s["world_timeline"]
    assert [t["name"] for t in tl] == ["world_shrink", "world_grow"]
    assert tl[0]["downtime_s"] == 3.25 and tl[0]["epoch"] == 1
    # the chain tags world events with their own role
    roles = {c["name"]: c["role"] for c in s["ranks"][0]["chain"]}
    assert roles["world_reform"] == "world"
    assert roles["world_shrink"] == "world"
    text = obs_report.render_chaos(str(obs))
    assert "world-size timeline" in text
    assert "world_shrink  world 2 -> 1, downtime 3.250s" in text
    assert "world_grow  world 1 -> 2, downtime 2.500s" in text


# ---------------------------------------------------------------------------
# driver-level elastic grow
# ---------------------------------------------------------------------------


def test_resume_stacked_grow_resets_frontier_and_comm(tmp_path):
    """`_resume_stacked` on a shard-count change (the grow direction):
    the state is re-cut, the checkpointed frontier carry is dropped
    (the resumed sweeps start from the exact all-active frontier) and
    the cached comm capacity is discarded so `rebuild_comm` re-derives
    `icap` for the new layout. An unchanged count keeps all three."""
    mesh = unit_cube_mesh(2)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 2)))
    st, _comm = split_mesh(mesh, part, 2)
    ntet_live = int(np.asarray(jax.device_get(st.tmask)).sum())
    fr = np.zeros((2, st.vert.shape[1]), bool)
    fr[:, :3] = True

    def resume_state():
        # fresh snapshot per call: the re-cut path donates its input
        # buffers (exactly like a real resume, which owns its arrays)
        return failsafe.ResumeState(
            it=0, meshes={"mesh": failsafe.snapshot(st)}, history=[],
            emult=1.6,
            meta=dict(icap=16, aux_arrays=dict(frontier=fr)),
            source_world=2,
        )

    # unchanged layout: everything carried through
    same, icap, fr0 = _resume_stacked(
        resume_state(), DistOptions(nparts=2, **C_OPTS)
    )
    assert same.vert.shape[0] == 2 and icap == 16
    np.testing.assert_array_equal(np.asarray(fr0), fr)
    # grow 2 -> 4: re-cut, frontier all-active (None), icap re-derived
    grown, icap4, fr4 = _resume_stacked(
        resume_state(), DistOptions(nparts=4, min_shard_elts=8,
                                    **C_OPTS)
    )
    assert grown.vert.shape[0] == 4
    assert icap4 is None and fr4 is None
    # the re-cut conserves the mesh: same live totals, owners rebuilt
    assert int(np.asarray(jax.device_get(grown.tmask)).sum()) \
        == ntet_live


@pytest.mark.slow
def test_driver_grow_under_way(tmp_path, monkeypatch):
    """Grow UNDER WAY through the public driver: a world-1 run with
    elasticity armed and restored capacity below its target commits
    its epoch and raises the typed WorldReformError mid-run; the
    relaunched larger layout resumes through the elastic re-cut and
    finishes with comm/owner rebuilt, `icap` re-derived and the
    quality histogram inside the m9-class gate."""
    from parmmg_tpu.ops import quality
    from parmmg_tpu.utils.conformity import check_mesh

    spec = "mem://m20-driver-grow"
    store = _mem_store("m20-driver-grow")
    elastic.publish_manifest(store, 0, world=1, members=[0],
                             target_world=2, reason="launch")
    monkeypatch.setenv("PMMGTPU_ELASTIC", "1")
    monkeypatch.setenv("PMMGTPU_ELASTIC_EPOCH", "0")
    monkeypatch.setenv("PMMGTPU_ELASTIC_TARGET", "2")
    multihost.request_capacity_restored("test grow")
    opts2 = DistOptions(nparts=2, min_shard_elts=8,
                        checkpoint_store=spec, **C_OPTS)
    with pytest.raises(failsafe.WorldReformError) as ei:
        adapt_distributed(unit_cube_mesh(2), opts2)
    assert ei.value.kind == "grow"
    names = sorted(store.list())
    assert any(n.startswith("ckpt_") and n.endswith(".json")
               for n in names), names
    assert any(n.startswith("elastic_ack_e00000") for n in names)

    # "relaunch" at the grown layout: shard count follows the larger
    # device pool, the checkpoint re-cuts through _elastic_recut
    monkeypatch.delenv("PMMGTPU_ELASTIC")
    multihost.clear_capacity_signal()
    opts4 = DistOptions(nparts=4, min_shard_elts=8,
                        checkpoint_store=spec, **C_OPTS)
    st, comm, info = adapt_distributed(unit_cube_mesh(2), opts4)
    assert info["status"] == ReturnStatus.SUCCESS
    assert st.vert.shape[0] == 4
    assert comm is not None and comm.icap > 0
    assert comm.owner.shape[0] == 4          # owner table per shard
    merged = merge_adapted(st, comm)
    assert check_mesh(merged, check_boundary=False).ok
    h = quality.quality_histogram(merged)
    assert float(h.qmin) > 0.2, float(h.qmin)   # the m9 small gate
