"""M16: unified observability layer (parmmg_tpu.obs).

Covers the tentpole contracts:
- span nesting/ordering and Chrome-trace-event structural validity
  (loads via ``json``, required keys per event, containment on one
  thread track);
- JSONL durability (event lines are on disk the moment they are
  emitted — the hard-kill timeline guarantee);
- per-rank metrics merge (counters summed, gauges per rank,
  histograms folded);
- counter EXACTNESS on a tiny adapt run: the ops counters equal the
  driver-reported history sums bit for bit;
- injected faults land in the event timeline;
- the disabled path is measurably near-free (the <2% bench-overhead
  acceptance bound, enforced here as a per-call ceiling).
"""

import json
import os
import time

import pytest

from parmmg_tpu.obs import metrics as obs_metrics
from parmmg_tpu.obs import report as obs_report
from parmmg_tpu.obs import trace as obs_trace


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """One tiny traced adapt run shared by the structural tests:
    (trace dir, output mesh, info dict)."""
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    d = str(tmp_path_factory.mktemp("obs_run"))
    tr = obs_trace.Tracer(d)
    obs_metrics.registry().reset()
    out, info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(hsiz=0.5, niter=1, max_sweeps=3, hgrad=None,
                     polish_sweeps=0),
        tracer=tr,
    )
    return d, out, info


# --- span mechanics -------------------------------------------------------


def test_span_nesting_and_ordering(tmp_path):
    tr = obs_trace.Tracer(str(tmp_path))
    with tr.span("outer"):
        with tr.span("mid", it=1):
            with tr.span("inner"):
                pass
        with tr.span("mid2"):
            pass
    tr.flush()
    doc = json.load(open(tmp_path / "trace_rank0.json"))
    spans = {e["name"]: e for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert set(spans) == {"outer", "mid", "mid2", "inner"}

    def contains(a, b):  # a strictly contains b on the time axis
        return (a["ts"] <= b["ts"]
                and a["ts"] + a["dur"] >= b["ts"] + b["dur"])

    assert contains(spans["outer"], spans["mid"])
    assert contains(spans["outer"], spans["mid2"])
    assert contains(spans["mid"], spans["inner"])
    assert not contains(spans["mid"], spans["mid2"])
    # ordering: mid ends before mid2 starts
    assert spans["mid"]["ts"] + spans["mid"]["dur"] <= spans["mid2"]["ts"]
    # span args survive the export
    assert spans["mid"]["args"]["it"] == 1
    # the JSONL mirror records explicit depths
    depths = {
        r["name"]: r["depth"]
        for r in obs_report.load_timeline(str(tmp_path))
        if r["type"] == "span"
    }
    assert depths == {"outer": 0, "mid": 1, "mid2": 1, "inner": 2}


def test_chrome_trace_required_keys(traced_run):
    d, _, _ = traced_run
    with open(os.path.join(d, "trace_rank0.json")) as f:
        doc = json.load(f)  # structural validity: plain json loads it
    events = doc["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans, "traced adapt produced no spans"
    for e in events:
        for key in ("name", "ph", "pid", "tid"):
            assert key in e, (key, e)
        if e["ph"] != "M":
            assert "ts" in e
        if e["ph"] == "X":
            assert "dur" in e and e["dur"] >= 0
    names = {e["name"] for e in spans}
    # the driver span tree: root -> phases -> iteration -> sweep
    for want in ("adapt", "phase:analysis", "phase:sweeps", "iteration"):
        assert want in names, (want, sorted(names))
    assert any(n.startswith("remesh_sweeps") or n.startswith("sweep")
               for n in names)


def test_jsonl_event_durable_before_flush(tmp_path):
    """Instant events hit the disk when emitted, NOT at flush: the
    guarantee that lets an os._exit'ed worker leave its fault in the
    timeline (asserted end to end by tools/fault_smoke.py)."""
    tr = obs_trace.Tracer(str(tmp_path))
    tr.event("fault_injected", kind="kill", it=0)
    # no flush() — read what is already on disk
    recs = obs_report.load_timeline(str(tmp_path))
    assert [r["name"] for r in recs if r["type"] == "event"] == [
        "fault_injected"
    ]
    assert recs[0]["args"]["kind"] == "kill"


# --- metrics --------------------------------------------------------------


def test_metrics_rank_merge():
    r0 = obs_metrics.MetricsRegistry()
    r1 = obs_metrics.MetricsRegistry()
    r0.counter("ops/split_accepted").inc(10)
    r1.counter("ops/split_accepted").inc(32)
    r0.gauge("sweep_active_fraction").set(0.25)
    r1.gauge("sweep_active_fraction").set(0.75)
    r0.histogram("ckpt/op_seconds").observe(0.1)
    r0.histogram("ckpt/op_seconds").observe(0.3)
    r1.histogram("ckpt/op_seconds").observe(0.2)
    r0.snapshot(0)
    r1.snapshot(0)
    merged = obs_metrics.merge_rank_docs(
        [r0.to_doc(rank=0), r1.to_doc(rank=1)]
    )
    assert merged["world"] == 2 and merged["ranks"] == [0, 1]
    assert merged["counters"]["ops/split_accepted"] == 42
    g = merged["gauges"]["sweep_active_fraction"]
    assert g["per_rank"] == {"0": 0.25, "1": 0.75} and g["max"] == 0.75
    h = merged["histograms"]["ckpt/op_seconds"]
    assert h["count"] == 3
    assert h["min"] == pytest.approx(0.1)
    assert h["max"] == pytest.approx(0.3)
    assert h["mean"] == pytest.approx(0.2)
    assert set(merged["series"]) == {"0", "1"}


def test_metrics_rank_files_roundtrip(tmp_path):
    r0 = obs_metrics.MetricsRegistry()
    r0.counter("sweeps").inc(7)
    r0.write(str(tmp_path), rank=0)
    r1 = obs_metrics.MetricsRegistry()
    r1.counter("sweeps").inc(5)
    r1.write(str(tmp_path), rank=1)
    merged = obs_metrics.merge_dir(str(tmp_path))
    assert merged["world"] == 2
    assert merged["counters"]["sweeps"] == 12


def test_counter_exactness_vs_driver_history(traced_run):
    """Acceptance: `ops/*_accepted` equals the driver-reported op
    totals — the registry records the SAME history rows the driver
    returns, via one shared record_sweep definition."""
    d, _, info = traced_run
    hist = [r for r in info["history"] if "nsplit" in r]
    assert hist, "driver reported no sweep rows"
    merged = obs_metrics.merge_dir(d)
    c = merged["counters"]
    assert c["ops/split_accepted"] == sum(r["nsplit"] for r in hist)
    assert c["ops/collapse_accepted"] == sum(r["ncollapse"] for r in hist)
    assert c["ops/swap_accepted"] == sum(r["nswap"] for r in hist)
    assert c["ops/smooth_moved"] == sum(r["nmoved"] for r in hist)
    assert c["sweeps"] == len(hist)


# --- events from the failsafe layer ---------------------------------------


def test_fault_events_in_timeline(tmp_path):
    from parmmg_tpu.core.tags import ReturnStatus
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    d = str(tmp_path / "obs")
    out, info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(hsiz=0.5, niter=1, max_sweeps=2, hgrad=None,
                     polish_sweeps=0, faults="it0:remesh:nan"),
        tracer=obs_trace.Tracer(d),
    )
    assert info["status"] == ReturnStatus.LOWFAILURE
    events = [r for r in obs_report.load_timeline(d)
              if r["type"] == "event"]
    names = [e["name"] for e in events]
    assert "fault_injected" in names and "rollback" in names
    fault = next(e for e in events if e["name"] == "fault_injected")
    assert fault["args"]["kind"] == "nan"
    # timeline ordering: the injection precedes the rollback
    assert names.index("fault_injected") < names.index("rollback")
    # and the report renders the failure timeline from the same files
    text = obs_report.render(d)
    assert "fault_injected" in text and "rollback" in text


def test_report_renders_traced_run(traced_run):
    d, _, info = traced_run
    s = obs_report.summarize(d)
    assert s["n_spans"] > 0
    assert s["ops"]["sweeps"] == len(
        [r for r in info["history"] if "nsplit" in r]
    )
    text = obs_report.render(d)
    for section in ("phase breakdown", "operators", "checkpoint I/O",
                    "recompiles", "failure timeline"):
        assert section in text, section


# --- disabled path --------------------------------------------------------


def test_disabled_tracer_is_default_and_inert(tmp_path, monkeypatch):
    monkeypatch.delenv("PMMGTPU_TRACE", raising=False)
    assert not obs_trace.from_env().enabled
    null = obs_trace.NullTracer()
    with null.span("x", a=1) as s:
        pass
    assert s is null.span("y")  # one shared no-op context manager
    null.event("e")
    null.flush()
    assert list(tmp_path.iterdir()) == []  # no files, ever


def test_disabled_span_overhead_guard():
    """Measured guard for the <2% disabled-overhead acceptance bound:
    a disabled span must cost well under 5 µs per call (the drivers
    enter a handful per SWEEP, each of which costs milliseconds even
    on the tiniest fixture — so this ceiling implies far below 2%)."""
    null = obs_trace.NullTracer()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with null.span("hot"):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"disabled span costs {per_call * 1e6:.2f} µs"


def test_env_contract_parses_profile_flag(tmp_path, monkeypatch):
    d = str(tmp_path / "t")
    monkeypatch.setenv("PMMGTPU_TRACE", d)
    tr = obs_trace.from_env()
    assert tr.enabled and tr.dir == d
    tr.flush()
    # dir[,profile]: the flag must parse; the capture window itself is
    # backend-dependent and degrades to host-only tracing on CPU
    monkeypatch.setenv("PMMGTPU_TRACE", str(tmp_path / "t2") + ",profile")
    tr2 = obs_trace.from_env()
    assert tr2.enabled
    tr2.flush()


def test_chaos_postmortem_chain_and_file_order(tmp_path):
    """The chaos section (round 11): per-rank fault -> detection ->
    recovery chains must preserve FILE order (a resumed run appends to
    the same rank file with a restarted clock — a ts sort would
    interleave the two runs), name the injected fault, and merge the
    surviving metrics snapshots."""
    d = str(tmp_path / "obs")
    # run 1: a fault, a detection, a commit — then a hard death (no
    # flush beyond the per-line JSONL writes)
    tr = obs_trace.Tracer(d, rank=0)
    tr.event("fault_injected", kind="kill", phase="post", it=1,
             where="it1:post@rank0")
    tr.event("sigterm_received")
    tr.event("checkpoint_commit", it=1, mode="sync", seconds=0.1)
    # run 2 (the resume): fresh tracer, restarted clock, same file
    tr2 = obs_trace.Tracer(d, rank=0)
    tr2.event("resume", it=1, source_world=2, world=1)
    tr2.flush()
    # a second rank with its own timeline + metrics
    tr3 = obs_trace.Tracer(d, rank=1)
    tr3.event("peer_lost", status="injected")
    tr3.flush()

    tls = obs_report.rank_timelines(d)
    assert sorted(tls) == [0, 1]
    names0 = [r["name"] for r in tls[0] if r.get("type") == "event"]
    # file order: the resume (restarted clock, ts ~0) stays LAST
    assert names0 == ["fault_injected", "sigterm_received",
                      "checkpoint_commit", "resume"]

    s = obs_report.chaos_summary(d)
    assert s["world"] == 2
    assert s["ranks"][0]["faults"] == [
        dict(kind="kill", where="it1:post@rank0")
    ]
    roles0 = [(c["role"], c["name"]) for c in s["ranks"][0]["chain"]]
    assert roles0 == [
        ("fault", "fault_injected"), ("detect", "sigterm_received"),
        ("recover", "checkpoint_commit"), ("recover", "resume"),
    ]
    assert [(c["role"], c["name"]) for c in s["ranks"][1]["chain"]] \
        == [("detect", "peer_lost")]

    text = obs_report.render_chaos(d)
    assert "chaos post-mortem" in text
    assert "injected: kill @ it1:post@rank0" in text
    assert "-- rank 0" in text and "-- rank 1" in text
    assert "recover  resume" in text
    assert "detect   peer_lost" in text


def test_chaos_postmortem_tolerates_killed_rank_without_metrics(
        tmp_path):
    """A hard-killed rank leaves ONLY its JSONL (no metrics snapshot):
    the post-mortem must still render, reporting the asymmetry."""
    d = str(tmp_path / "obs")
    tr = obs_trace.Tracer(d, rank=0)
    tr.event("fault_injected", kind="ioerror", phase="ckpt", op="put",
             store_op=3)
    # no flush: simulates os._exit — metrics_rank0.json never written
    s = obs_report.chaos_summary(d)
    assert s["world"] == 1 and s["metrics_ranks"] == 0
    assert s["ranks"][0]["faults"][0]["kind"] == "ioerror"
    assert "store op 3" in s["ranks"][0]["faults"][0]["where"]
    assert "injected: ioerror" in obs_report.render_chaos(d)
