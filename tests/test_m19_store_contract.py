"""M19: the parametrized store-contract suite + GCS adapter.

ONE suite, run identically against every checkpoint-store backend —
`LocalFSStore`, `ObjectStore` (``mem://`` semantics) and the new
`GCSStore` speaking real HTTP to the hermetic fake server
(``tests/fake_gcs.py``) — replacing the per-backend copies that used
to live in test_m15:

- put/get/list/delete/publish roundtrip + atomicity semantics;
- bounded retry with DETERMINISTIC seeded backoff (same seed → same
  recorded delay schedule on every backend);
- transient faults absorbed within the retry budget, persistent
  faults escalating to the typed `CheckpointIOError`;
- the ``slowio``/per-op-timeout leg via the shared `FaultPlan` hook;
- Checkpointer-level publish atomicity: a failed manifest publish
  leaves data objects that are NOT a checkpoint (no commit token →
  `load` returns None).

Plus the GCS-only taxonomy matrix (429-with-Retry-After, 500, stall
timeout, truncated body, 401/404/412 terminal subtypes, pagination,
``if-generation-match`` conditional publish, auth providers, the
``gs://`` spec) and the PR-5-NOTE regression: npz corruption is now
the typed `CheckpointCorruptionError` (still a ValueError for the
fall-back-to-previous path, and a `CheckpointIOError` so an escape
maps onto exit code 89).
"""

import os
import time

import numpy as np
import pytest

from fake_gcs import FakeGCS
from parmmg_tpu import failsafe
from parmmg_tpu.io import ckpt_store
from parmmg_tpu.io.ckpt_store import (
    CheckpointAuthError,
    CheckpointCorruptionError,
    CheckpointIOError,
    CheckpointNotFoundError,
    CheckpointPreconditionError,
    CheckpointStore,
    LocalFSStore,
    ObjectStore,
    TransientStoreError,
)
from parmmg_tpu.io.gcs import (
    GCSStore,
    classify_http_status,
    resolve_token_provider,
)
from parmmg_tpu.models.adapt import AdaptOptions
from parmmg_tpu.utils.gen import unit_cube_mesh

BACKENDS = ("localfs", "mem", "gcs")


@pytest.fixture(scope="module")
def gcs_server():
    srv = FakeGCS()
    srv.start()
    yield srv
    srv.stop()


class _Backend:
    """One backend under contract test: a store factory plus a
    backend-appropriate transient/persistent fault injector (fault_cb
    for the in-process stores, real HTTP faults for GCS)."""

    def __init__(self, name, factory, server=None):
        self.name = name
        self.factory = factory
        self.server = server
        self._cb_faults = {}

    def store(self, **kw) -> CheckpointStore:
        kw.setdefault("attempts", 3)
        kw.setdefault("backoff", 0.0)
        return self.factory(self, kw)

    # fault_cb shared by the in-process backends
    def _fault_cb(self, op, name, timeout):
        n = self._cb_faults.get(op, 0)
        if n != 0:
            if n > 0:
                self._cb_faults[op] = n - 1
            raise OSError(f"injected transient {op} failure")

    def inject(self, op: str, times: int = 1) -> None:
        """`times` transient failures on the next ops of kind `op`
        (-1 = every attempt, the persistent-fault leg). GCS maps store
        ops onto their HTTP requests."""
        if self.server is None:
            cur = self._cb_faults.get(op, 0)
            self._cb_faults[op] = -1 if times < 0 else cur + times
            return
        http_op = {"put": "upload", "publish": "upload", "get": "get",
                   "list": "list", "delete": "delete"}[op]
        self.server.inject(http_op, status=503,
                           times=10_000 if times < 0 else times)

    def clear(self) -> None:
        self._cb_faults.clear()
        if self.server is not None:
            self.server.clear_faults()


@pytest.fixture(params=BACKENDS)
def backend(request, tmp_path, gcs_server):
    name = request.param
    if name == "localfs":
        be = _Backend(name, lambda self, kw: LocalFSStore(
            str(tmp_path / "store"), fault_cb=self._fault_cb, **kw))
    elif name == "mem":
        bucket: dict = {}
        be = _Backend(name, lambda self, kw: ObjectStore(
            bucket, fault_cb=self._fault_cb, **kw))
    else:
        gcs_server.objects.clear()
        gcs_server.clear_faults()
        gcs_server.reset_counts()
        be = _Backend(
            name,
            lambda self, kw: GCSStore(
                "contract", "pre", endpoint=gcs_server.base_url,
                token_provider=None, fault_cb=self._fault_cb, **kw),
            server=gcs_server,
        )
    yield be
    be.clear()


# ---------------------------------------------------------------------------
# the shared contract
# ---------------------------------------------------------------------------


def test_contract_roundtrip(backend):
    st = backend.store()
    assert st.list() == []
    st.put("a.npz", b"alpha")
    st.put("b.json", b"{}")
    st.publish("manifest.json", b"commit-token")
    assert st.list() == ["a.npz", "b.json", "manifest.json"]
    assert st.get("a.npz") == b"alpha"
    assert st.get("manifest.json") == b"commit-token"
    # overwrite is whole-object
    st.put("a.npz", b"alpha2")
    assert st.get("a.npz") == b"alpha2"
    # publish republishes cleanly (same-name commit token, e.g. a
    # re-published epoch after a lost response)
    st.publish("manifest.json", b"commit-token-2")
    assert st.get("manifest.json") == b"commit-token-2"
    st.delete("a.npz")
    assert st.list() == ["b.json", "manifest.json"]
    # missing objects: typed missing-object error on get, success on
    # delete (concurrent-GC tolerance)
    with pytest.raises(FileNotFoundError):
        st.get("a.npz")
    st.delete("a.npz")


def test_contract_transient_fault_absorbed(backend):
    st = backend.store(attempts=4)
    backend.inject("put", times=2)
    st.put("x.npz", b"payload")           # 2 failures < 4 attempts
    assert st.get("x.npz") == b"payload"
    backend.inject("get", times=1)
    assert st.get("x.npz") == b"payload"


def test_contract_persistent_fault_typed_abort(backend):
    st = backend.store(attempts=2)
    backend.inject("put", times=-1)
    with pytest.raises(CheckpointIOError):
        st.put("y.npz", b"data")
    backend.clear()
    st.put("y.npz", b"data")              # backend healthy again
    assert st.get("y.npz") == b"data"


def test_contract_retry_determinism(backend, monkeypatch):
    """The same seed replays the exact backoff schedule on every
    backend — the property every chaos assertion leans on."""
    from parmmg_tpu.utils import retry as retry_mod

    def delays_for(seed):
        recorded = []

        def spying_retry(fn, **kw):
            kw["sleep"] = recorded.append
            return retry_mod.retry(fn, **kw)

        monkeypatch.setattr(ckpt_store, "retry", spying_retry)
        st = backend.store(attempts=4, backoff=0.01, jitter=0.5,
                           seed=seed)
        backend.inject("put", times=3)
        st.put(f"det-{seed}.npz", b"d")
        backend.clear()
        return recorded

    a = delays_for(7)
    b = delays_for(7)
    assert len(a) == 3 and a == b
    assert delays_for(8) != a
    for k, d in enumerate(a):
        assert 0.01 * 2 ** k <= d <= 0.01 * 2 ** k * 1.5


def test_contract_slowio_trips_per_op_timeout(backend):
    """The shared FaultPlan ``ckpt`` hook drives the per-op watchdog on
    every backend: one slowio fault converts into timeout → retry, a
    persistent burst escalates to the typed abort."""
    plan = failsafe.FaultPlan.parse("it0:ckpt:slowio")
    st = backend.store(attempts=2, timeout=0.2)
    st.fault_cb = plan.io_fault
    t0 = time.perf_counter()
    st.put("slow.npz", b"data")
    assert time.perf_counter() - t0 >= 0.2
    assert st.get("slow.npz") == b"data"
    plan2 = failsafe.FaultPlan(
        [failsafe.Fault(it, "ckpt", "slowio") for it in range(20)]
    )
    st2 = backend.store(attempts=2, timeout=0.2)
    st2.fault_cb = plan2.io_fault
    with pytest.raises(CheckpointIOError, match="timeout|attempts"):
        st2.put("slow2.npz", b"data")


def test_contract_checkpointer_publish_atomicity(backend):
    """Data objects without the commit token are NOT a checkpoint:
    a persistently failing manifest publish leaves `load` → None, and
    a later healthy save commits normally."""
    opts = AdaptOptions(hsiz=0.45, niter=2)
    mesh = unit_cube_mesh(2)
    st = backend.store(attempts=2)
    c = failsafe.Checkpointer(None, opts, "centralized", rank=0,
                              world=1, store=st)
    backend.inject("publish", times=-1)
    with pytest.raises(CheckpointIOError):
        c.save(0, {"mesh": mesh}, history=[], emult=1.6)
    backend.clear()
    assert c.load() is None
    c.save(1, {"mesh": mesh}, history=[{"iter": 1}], emult=1.7)
    rs = c.load()
    assert rs is not None and rs.it == 1 and rs.emult == 1.7
    np.testing.assert_array_equal(
        np.asarray(rs.mesh.vert), np.asarray(mesh.vert)
    )


# ---------------------------------------------------------------------------
# GCS-only: the HTTP retry-status taxonomy + protocol details
# ---------------------------------------------------------------------------


@pytest.fixture
def gcs(gcs_server):
    gcs_server.objects.clear()
    gcs_server.clear_faults()
    gcs_server.reset_counts()

    def make(**kw):
        kw.setdefault("attempts", 3)
        kw.setdefault("backoff", 0.0)
        return GCSStore("bkt", "ck", endpoint=gcs_server.base_url,
                        token_provider=None, **kw)

    yield gcs_server, make
    gcs_server.clear_faults()


def test_gcs_status_taxonomy_mapping():
    """The status → exception table, standalone."""
    for status in (408, 429, 500, 502, 503, 599):
        e = classify_http_status(status, "op")
        assert isinstance(e, TransientStoreError), status
    e = classify_http_status(429, "op", retry_after="7")
    assert e.retry_after == 7.0
    assert classify_http_status(429, "op",
                                retry_after="nonsense").retry_after is None
    for status, typ in ((401, CheckpointAuthError),
                        (403, CheckpointAuthError),
                        (404, CheckpointNotFoundError),
                        (412, CheckpointPreconditionError),
                        (400, CheckpointIOError)):
        e = classify_http_status(status, "op")
        assert type(e) is typ, (status, type(e))
        assert isinstance(e, CheckpointIOError)
    # terminal members are refused by the retry predicate; transient
    # and timeout members are retried
    assert not ckpt_store._retryable(classify_http_status(401, "x"))
    assert not ckpt_store._retryable(classify_http_status(412, "x"))
    assert not ckpt_store._retryable(classify_http_status(404, "x"))
    assert ckpt_store._retryable(classify_http_status(500, "x"))
    assert ckpt_store._retryable(
        ckpt_store.CheckpointTimeoutError("t"))
    assert isinstance(classify_http_status(404, "x"), FileNotFoundError)


def test_gcs_429_retry_after_floors_backoff(gcs, monkeypatch):
    """A 429 with Retry-After is retried, and the server's hint FLOORS
    the seeded delay (deterministic, never below the hint)."""
    from parmmg_tpu.utils import retry as retry_mod

    srv, make = gcs
    recorded = []

    def spying_retry(fn, **kw):
        kw["sleep"] = recorded.append
        return retry_mod.retry(fn, **kw)

    monkeypatch.setattr(ckpt_store, "retry", spying_retry)
    st = make(attempts=3, backoff=0.01)
    st.put("a", b"1")
    recorded.clear()
    srv.inject("get", status=429, retry_after=3, times=1)
    assert st.get("a") == b"1"
    assert recorded and recorded[0] >= 3.0


def test_gcs_500_retry_and_budget(gcs):
    srv, make = gcs
    st = make(attempts=3)
    srv.inject("upload", status=500, times=2)
    st.put("b", b"2")                      # recovered within budget
    srv.inject("upload", status=500, times=3)
    with pytest.raises(CheckpointIOError, match="attempts"):
        st.put("c", b"3")


def test_gcs_stall_trips_timeout_then_recovers(gcs):
    srv, make = gcs
    st = make(attempts=2, http_timeout=0.3)
    st.put("s", b"stall-me")
    srv.inject("get", stall=1.2, times=1)
    t0 = time.perf_counter()
    assert st.get("s") == b"stall-me"
    assert time.perf_counter() - t0 >= 0.3


def test_gcs_truncated_body_retried(gcs):
    srv, make = gcs
    st = make(attempts=3)
    payload = b"x" * 4096
    st.put("t", payload)
    srv.inject("get", truncate=0.5, times=1)
    assert st.get("t") == payload


def test_gcs_terminal_statuses_not_retried(gcs):
    srv, make = gcs
    st = make(attempts=5)
    st.put("z", b"1")
    srv.reset_counts()
    srv.inject("get", status=401, times=10)
    with pytest.raises(CheckpointAuthError):
        st.get("z")
    assert srv.request_count("get") == 1   # terminal: ONE attempt
    srv.clear_faults()
    with pytest.raises(FileNotFoundError):
        st.get("missing")
    srv.reset_counts()
    srv.inject("upload", status=412, times=10)
    with pytest.raises(CheckpointPreconditionError):
        st.publish("m.json", b"tok")
    assert srv.request_count("upload") == 1


def test_gcs_conditional_publish_generation_conflict(gcs):
    """The if-generation-match commit token: a publisher whose
    generation snapshot went stale (concurrent publisher won) gets the
    typed 412 instead of silently overwriting the winner."""
    srv, make = gcs
    st = make()
    st.publish("m.json", b"epoch-1")       # create (generation 0 match)
    gen = st._generation("m.json")
    assert gen > 0
    st.publish("m.json", b"epoch-2")       # re-publish advances
    assert st.get("m.json") == b"epoch-2"
    # stale-generation conditional write: the raw conflict surface
    with pytest.raises(CheckpointPreconditionError):
        st._put("m.json", b"stale-writer", generation_match=gen)
    assert st.get("m.json") == b"epoch-2"  # winner kept


def test_gcs_list_pagination(gcs):
    srv, make = gcs
    srv.page_size = 2
    try:
        st = make()
        names = [f"obj{i:02d}" for i in range(5)]
        for n in names:
            st.put(n, n.encode())
        assert st.list() == names
    finally:
        srv.page_size = 1000


def test_gcs_auth_token_and_providers(monkeypatch):
    srv = FakeGCS(require_token="sekrit")
    base = srv.start()
    try:
        ok = GCSStore("b", endpoint=base, attempts=2, backoff=0.0,
                      token_provider=lambda: "sekrit")
        ok.put("x", b"1")
        assert ok.get("x") == b"1"
        bad = GCSStore("b", endpoint=base, attempts=2, backoff=0.0,
                       token_provider=None)
        with pytest.raises(CheckpointAuthError):
            bad.get("x")
        # env provider reads PMMGTPU_GCS_TOKEN per call
        monkeypatch.setenv("PMMGTPU_GCS_TOKEN", "sekrit")
        envd = GCSStore("b", endpoint=base, attempts=2, backoff=0.0)
        assert envd.get("x") == b"1"
        # resolution rules: explicit mode wins; non-Google endpoint
        # without a token defaults to anonymous
        monkeypatch.setenv("PMMGTPU_GCS_AUTH", "anon")
        assert resolve_token_provider(base) is None
        monkeypatch.setenv("PMMGTPU_GCS_AUTH", "env")
        prov = resolve_token_provider(base)
        assert prov is not None and prov() == "sekrit"
        monkeypatch.setenv("PMMGTPU_GCS_AUTH", "bogus")
        with pytest.raises(ValueError, match="PMMGTPU_GCS_AUTH"):
            resolve_token_provider(base)
        monkeypatch.delenv("PMMGTPU_GCS_AUTH")
        monkeypatch.delenv("PMMGTPU_GCS_TOKEN")
        assert resolve_token_provider(base) is None
    finally:
        srv.stop()


def test_gcs_make_store_spec(gcs, monkeypatch):
    srv, make = gcs
    monkeypatch.setenv("PMMGTPU_GCS_ENDPOINT", srv.base_url)
    monkeypatch.setenv("PMMGTPU_CKPT_ATTEMPTS", "5")
    st = ckpt_store.make_store("gs://specbkt/some/prefix", None)
    assert isinstance(st, GCSStore)
    assert st.bucket == "specbkt" and st.prefix == "some/prefix/"
    assert st.attempts == 5
    st.put("via-spec", b"ok")
    assert st.get("via-spec") == b"ok"
    with pytest.raises(ValueError, match="bucket"):
        GCSStore.from_url("gs://")


def test_gcs_checkpointer_world2_roundtrip(gcs):
    """The full sharded-checkpoint protocol over real HTTP: two
    in-process ranks share the fake bucket, the rank-0 manifest digests
    verify, and an elastic world-1 reader re-concatenates."""
    import jax

    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition

    srv, make = gcs
    opts = AdaptOptions(hsiz=0.35, niter=2)
    mesh = unit_cube_mesh(2)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st8, _ = split_mesh(mesh, part, 8)
    ranks = [
        failsafe.Checkpointer(None, opts, "distributed", rank=r,
                              world=2, barrier=lambda t: None,
                              store=make())
        for r in (0, 1)
    ]
    for c in ranks:
        c.save(0, {"mesh": st8}, history=[{"iter": 0}], emult=1.7)
    assert sorted(n for n in srv.objects) == [
        "ck/ckpt_00000.json", "ck/ckpt_00000.proc0.npz",
        "ck/ckpt_00000.proc1.npz",
    ]
    rdr = failsafe.Checkpointer(None, opts, "distributed", rank=0,
                                world=1, barrier=lambda t: None,
                                store=make())
    rs = rdr.load()
    assert rs is not None and rs.source_world == 2 and rs.it == 0
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(rs.mesh.vert)),
        np.asarray(jax.device_get(st8.vert)),
    )


# ---------------------------------------------------------------------------
# PR-5 NOTE regression: npz corruption is typed
# ---------------------------------------------------------------------------


def test_npz_corruption_typed_taxonomy():
    for garbage in (b"not-a-zip-at-all", b"PK\x03\x04torn"):
        with pytest.raises(CheckpointCorruptionError) as ei:
            ckpt_store.npz_arrays(garbage)
        # both halves of the contract: ValueError keeps the loader's
        # fall-back-to-previous catch working, CheckpointIOError maps
        # an escape onto the typed exit (89)
        assert isinstance(ei.value, ValueError)
        assert isinstance(ei.value, CheckpointIOError)
    # a flipped byte mid-payload (CRC damage) classifies the same way
    blob = bytearray(ckpt_store.npz_bytes({"a": np.arange(64)}))
    blob[len(blob) // 2] ^= 0xFF
    with pytest.raises(CheckpointCorruptionError):
        ckpt_store.npz_arrays(bytes(blob))
    # corruption is TERMINAL for the store retry envelope: re-reading
    # rotten bytes cannot help
    assert not ckpt_store._retryable(CheckpointCorruptionError("x"))


def test_npz_corruption_falls_back_to_previous_epoch(tmp_path):
    """Driver-visible half of the regression: a corrupted NEWEST npz
    makes `Checkpointer.load` fall back to the previous committed
    epoch deliberately (typed corruption inside, not a bare
    ValueError bubbling up)."""
    opts = AdaptOptions(hsiz=0.45, niter=3)
    mesh = unit_cube_mesh(2)
    ck = str(tmp_path / "ck")
    c = failsafe.Checkpointer(ck, opts, "centralized", rank=0, world=1)
    for it in (0, 1):
        c.save(it, {"mesh": mesh}, history=[{"iter": it}], emult=1.6)
    path = os.path.join(ck, "ckpt_00001.npz")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    rs = c.load()
    assert rs is not None and rs.it == 0
