"""M11: the JAX-invariant linter (parmmg_tpu.lint) + runtime contracts.

Fixture-file tests: every rule has a known-bad snippet that must fire
(by ID) and a known-good/suppressed variant that must not.  The
analyzer half is pure AST — the fixtures are written to tmp_path and
linted in-process.
"""

import textwrap

import pytest

from parmmg_tpu.lint import run_lint
from parmmg_tpu.lint.rules import RULES


def lint(tmp_path, src, name="mod.py"):
    (tmp_path / name).write_text(textwrap.dedent(src))
    return run_lint([str(tmp_path)], root=str(tmp_path))


def rule_ids(findings):
    return {f.rule for f in findings}


HEADER = """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import lru_cache, partial
"""


def test_rule_catalog_size():
    # acceptance: >= 8 implemented rules, each with a stable PML id
    assert len(RULES) >= 8
    assert all(r.startswith("PML") for r in RULES)


# --- PML001 host-sync ----------------------------------------------------


def test_pml001_device_get_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        y = jnp.sum(x)
        return jax.device_get(y)
    """)
    assert "PML001" in rule_ids(out)


def test_pml001_item_and_numpy_fire(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        a = x.item()
        b = np.asarray(x)
        return a, b
    """)
    assert sum(f.rule == "PML001" for f in out) == 2


def test_pml001_host_code_clean(tmp_path):
    # not jit-reachable: numpy syncs on host code are fine
    out = lint(tmp_path, HEADER + """
    def host(x):
        return np.asarray(x).item()
    """)
    assert "PML001" not in rule_ids(out)


# --- PML002 traced bool --------------------------------------------------


def test_pml002_if_on_traced_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """)
    assert "PML002" in rule_ids(out)


def test_pml002_static_argnames_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @partial(jax.jit, static_argnames=("flag",))
    def f(x, flag):
        if flag:
            return x
        return -x
    """)
    assert "PML002" not in rule_ids(out)


def test_pml002_is_none_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x, y=None):
        if y is None:
            return x
        return x + y
    """)
    assert "PML002" not in rule_ids(out)


def test_pml002_interprocedural_taint(tmp_path):
    # taint flows through the call into the helper's parameter
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def entry(x):
        return helper(x * 2)

    def helper(y):
        if y > 0:
            return y
        return -y
    """)
    bad = [f for f in out if f.rule == "PML002"]
    assert bad and "helper" in bad[0].func


# --- PML003 traced loop --------------------------------------------------


def test_pml003_for_over_traced_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        total = 0
        for v in x:
            total = total + v
        return total
    """)
    assert "PML003" in rule_ids(out)


def test_pml003_static_range_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        for k in range(4):
            x = x + k
        return x
    """)
    assert "PML003" not in rule_ids(out)


# --- PML004 inline jit ---------------------------------------------------


def test_pml004_inline_jit_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    def g(f, x):
        return jax.jit(f)(x)
    """)
    assert "PML004" in rule_ids(out)


def test_pml004_module_level_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return x
    """)
    assert "PML004" not in rule_ids(out)


def test_pml004_memoized_factory_clean(tmp_path):
    # @lru_cache factories are the sanctioned fix, not a violation
    out = lint(tmp_path, HEADER + """
    @lru_cache(maxsize=8)
    def make(key):
        def body(x):
            return x * key
        return jax.jit(body)
    """)
    assert "PML004" not in rule_ids(out)


# --- PML005 missing donation --------------------------------------------


def test_pml005_mesh_without_donate_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(mesh):
        return mesh
    """)
    assert "PML005" in rule_ids(out)


def test_pml005_donating_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @partial(jax.jit, donate_argnums=0)
    def f(mesh):
        return mesh
    """)
    assert "PML005" not in rule_ids(out)


def test_pml005_partial_wrap_assignment(tmp_path):
    # the `name = partial(jax.jit, ...)(impl)` module-level idiom
    out = lint(tmp_path, HEADER + """
    def _impl(mesh, k):
        return mesh

    wrapped = partial(jax.jit, static_argnames=("k",))(_impl)
    """)
    assert "PML005" in rule_ids(out)


# --- PML006 dtype widening ----------------------------------------------


def test_pml006_float64_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    def f(x):
        return x.astype(jnp.float64)
    """)
    assert "PML006" in rule_ids(out)


def test_pml006_host_numpy_clean(tmp_path):
    # host-side numpy int64 (sort keys etc.) is fine
    out = lint(tmp_path, HEADER + """
    def f(x):
        return np.asarray(x, np.int64)
    """)
    assert "PML006" not in rule_ids(out)


# --- PML007 dynamic shapes ----------------------------------------------


def test_pml007_boolean_mask_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return x[x > 0]
    """)
    assert "PML007" in rule_ids(out)


def test_pml007_nonzero_fires_unique_sized_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        a = jnp.nonzero(x)
        b = jnp.unique(x, size=4)
        return a, b
    """)
    assert sum(f.rule == "PML007" for f in out) == 1


def test_pml007_three_arg_where_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x, mask):
        return jnp.where(mask, x, 0.0)
    """)
    assert "PML007" not in rule_ids(out)


# --- PML008 print under trace -------------------------------------------


def test_pml008_print_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        print("tracing", x)
        return x
    """)
    assert "PML008" in rule_ids(out)


# --- PML009 arange dtype -------------------------------------------------


def test_pml009_arange_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return jnp.arange(x.shape[0])
    """)
    assert "PML009" in rule_ids(out)


def test_pml009_arange_with_dtype_clean(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return jnp.arange(x.shape[0], dtype=jnp.int32)
    """)
    assert "PML009" not in rule_ids(out)


# --- PML010 host clock under trace ---------------------------------------


def test_pml010_host_clock_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    import time

    @jax.jit
    def f(x):
        t0 = time.perf_counter()
        return jnp.sum(x) + 0 * t0
    """)
    assert "PML010" in rule_ids(out)


def test_pml010_time_time_via_helper_fires(tmp_path):
    # interprocedural: a helper REACHED from a jit entry point is
    # jit-reachable code too
    out = lint(tmp_path, HEADER + """
    import time

    def helper(x):
        return jnp.sum(x) * time.time()

    @jax.jit
    def f(x):
        return helper(x)
    """)
    assert "PML010" in rule_ids(out)


def test_pml010_host_code_clean(tmp_path):
    # host-side timing (bench loops, tools) is exactly where host
    # clocks belong — no finding outside jit-reachable code
    out = lint(tmp_path, HEADER + """
    import time

    def bench(fn, x):
        t0 = time.perf_counter()
        fn(x)
        return time.perf_counter() - t0
    """)
    assert "PML010" not in rule_ids(out)


def test_pml010_suppressible(tmp_path):
    out = lint(tmp_path, HEADER + """
    import time

    @jax.jit
    def f(x):
        t0 = time.time()  # parmmg-lint: disable=PML010
        return jnp.sum(x) + 0 * t0
    """)
    assert "PML010" not in rule_ids(out)


# --- suppressions --------------------------------------------------------


def test_suppression_same_line(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return jax.device_get(x)  # parmmg-lint: disable=PML001 -- why
    """)
    assert "PML001" not in rule_ids(out)


def test_suppression_previous_line(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        # parmmg-lint: disable=PML001
        return jax.device_get(x)
    """)
    assert "PML001" not in rule_ids(out)


def test_suppression_def_scope(tmp_path):
    out = lint(tmp_path, HEADER + """
    # parmmg-lint: disable=PML008
    @jax.jit
    def f(x):
        print("a")
        print("b")
        return x
    """)
    assert "PML008" not in rule_ids(out)


def test_suppression_file_level(tmp_path):
    out = lint(tmp_path, """
    # parmmg-lint: disable-file=PML006
    import jax.numpy as jnp

    def f(x):
        return x.astype(jnp.float64)
    """)
    assert "PML006" not in rule_ids(out)


def test_suppression_wrong_rule_still_fires(tmp_path):
    out = lint(tmp_path, HEADER + """
    @jax.jit
    def f(x):
        return jax.device_get(x)  # parmmg-lint: disable=PML008
    """)
    assert "PML001" in rule_ids(out)


# --- repo gate -----------------------------------------------------------


def test_repo_is_lint_clean():
    """Acceptance: the committed tree lints clean (all findings fixed
    or explicitly suppressed)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = run_lint(
        [os.path.join(root, "parmmg_tpu"), os.path.join(root, "tools")],
        root=root,
    )
    assert out == [], "\n".join(f.format() for f in out)


# --- runtime contracts ---------------------------------------------------


def test_contracts_mesh_ok_and_corruption_caught():
    import jax

    from parmmg_tpu.core import adjacency
    from parmmg_tpu.lint import contracts as C
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = adjacency.build_adjacency(unit_cube_mesh(3))
    rep = C.assert_mesh_ok(m)
    assert all(v == 0 for v in rep.values())

    bad = m.replace(tet=m.tet.at[0, 0].set(10 ** 6))
    with pytest.raises(C.MeshContractError) as ei:
        C.assert_mesh_ok(bad)
    assert ei.value.report["tet_conn_bad"] == 1

    bad2 = m.replace(adja=m.adja.at[0, 0].set(-5))
    with pytest.raises(C.MeshContractError) as ei:
        C.assert_mesh_ok(bad2)
    assert ei.value.report["adja_sentinel_bad"] == 1


def test_contracts_report_is_jittable():
    import jax

    from parmmg_tpu.lint import contracts as C
    from parmmg_tpu.utils.gen import unit_cube_mesh

    rep = jax.jit(C.mesh_invariant_report)(unit_cube_mesh(3))
    assert int(rep["tet_conn_bad"]) == 0


def test_contracts_owner_consistency():
    from types import SimpleNamespace

    import jax.numpy as jnp

    from parmmg_tpu.lint import contracts as C

    comm = SimpleNamespace(
        l2g=jnp.asarray([[0, 1, 2, -1], [1, 2, 3, -1]], jnp.int32),
        owner=jnp.asarray(
            [[True, True, True, False], [False, False, True, False]]
        ),
        comm_idx=jnp.asarray(
            [[[-1, -1], [1, 2]], [[0, 1], [-1, -1]]], jnp.int32
        ),
        counts=jnp.asarray([[0, 2], [2, 0]], jnp.int32),
    )
    rep = C.assert_comm_ok(comm)
    assert all(v == 0 for v in rep.values())

    # two owners for gid 1 -> owner_bad
    comm.owner = comm.owner.at[1, 0].set(True)
    with pytest.raises(C.MeshContractError) as ei:
        C.assert_comm_ok(comm)
    assert ei.value.report["owner_bad"] == 1


def test_retrace_counter_and_budget():
    import jax
    import jax.numpy as jnp

    from parmmg_tpu.lint import contracts as C

    counter = C.RetraceCounter()
    with counter:
        with counter.phase("warm"):
            f = jax.jit(lambda x: x * 2)
            f(jnp.ones(3))
        with counter.phase("steady", budget=0):
            f(jnp.ones(3))  # cache hit: within budget
    assert counter.counts.get("warm", 0) >= 1
    assert counter.counts.get("steady", 0) == 0

    with pytest.raises(C.RetraceBudgetExceeded):
        with counter, counter.phase("strict", budget=0):
            jax.jit(lambda x: x * 7)(jnp.ones(6))


# --- PML011 Pallas kernel registration hygiene ----------------------------


def lint_kernels(tmp_path, src, name="mod.py"):
    import textwrap as _tw

    kdir = tmp_path / "kernels"
    kdir.mkdir(exist_ok=True)
    (kdir / name).write_text(_tw.dedent(src))
    return run_lint([str(tmp_path)], root=str(tmp_path))


def test_pml011_register_without_lax_reference_fires(tmp_path):
    out = lint_kernels(tmp_path, HEADER + """
    def register(*a, **k): ...
    def _p(x): return x
    register("orphan_kernel", _p)
    """)
    assert "PML011" in rule_ids(out)


def test_pml011_paired_registration_clean(tmp_path):
    out = lint_kernels(tmp_path, HEADER + """
    def register(*a, **k): ...
    def _p(x): return x
    def _r(x): return x
    register("good_kernel", _p, _r)
    register("kw_kernel", pallas_impl=_p, lax_reference=_r)
    """)
    assert "PML011" not in rule_ids(out)


def test_pml011_numpy_in_kernel_body_fires(tmp_path):
    out = lint_kernels(tmp_path, HEADER + """
    def my_kernel(x_ref, o_ref):
        o_ref[...] = np.sum(x_ref[...])
    """)
    assert "PML011" in rule_ids(out)


def test_pml011_f64_constant_in_kernel_body_fires(tmp_path):
    out = lint_kernels(tmp_path, HEADER + """
    def my_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...].astype("float64")
    """)
    assert "PML011" in rule_ids(out)


def test_pml011_silent_outside_kernels_package(tmp_path):
    (tmp_path / "plain.py").write_text(textwrap.dedent(HEADER + """
    def register(*a, **k): ...
    def _p(x): return x
    register("orphan_kernel", _p)
    def my_kernel(x_ref, o_ref):
        o_ref[...] = np.sum(x_ref[...])
    """))
    out = run_lint([str(tmp_path)], root=str(tmp_path))
    assert "PML011" not in rule_ids(out)


def test_pml011_kernel_body_clean_jnp(tmp_path):
    out = lint_kernels(tmp_path, HEADER + """
    def ok_kernel(x_ref, o_ref):
        o_ref[...] = jnp.sum(x_ref[...] * 2.0)
    """)
    assert "PML011" not in rule_ids(out)


# --- PML012–016 SPMD divergence rules ------------------------------------


SPMD_HEADER = HEADER + """
    import os
    from parmmg_tpu.parallel import multihost
"""


def test_spmd_rules_in_catalog():
    for rid in ("PML012", "PML013", "PML014", "PML015", "PML016"):
        assert rid in RULES, rid
    assert len(RULES) >= 16


def test_pml012_rank_guarded_collective_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def publish():
        if jax.process_index() == 0:
            multihost.barrier("manifest")
    """)
    assert "PML012" in rule_ids(out)


def test_pml012_interprocedural_rank_taint(tmp_path):
    # the taint crosses the helper's return; the early return makes
    # the barrier fall-through-dominated by the rank branch
    out = lint(tmp_path, SPMD_HEADER + """
    def rank_of():
        return jax.process_index()

    def publish():
        r = rank_of()
        if r != 0:
            return
        multihost.barrier("manifest")
    """)
    fs = [f for f in out if f.rule == "PML012"]
    assert fs, rule_ids(out)
    # the finding carries its taint chain (origin -> guard)
    assert fs[0].chain and "process_index" in fs[0].chain[0]


def test_pml012_env_rank_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def vote():
        if os.environ.get("PMMGTPU_PROC_ID") == "0":
            multihost.agree_flags(1, tag="vote")
    """)
    assert "PML012" in rule_ids(out)


def test_pml012_world_uniform_guard_clean(tmp_path):
    # process_count is world-UNIFORM: every rank takes the same branch,
    # so the canonical is_multiprocess() guard must not fire
    out = lint(tmp_path, SPMD_HEADER + """
    def maybe_sync():
        if jax.process_count() > 1:
            multihost.barrier("sync")
    """)
    assert "PML012" not in rule_ids(out)


def test_pml012_suppressible(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def publish():
        if jax.process_index() == 0:
            # parmmg-lint: disable=PML012 -- peers wait at the commit barrier
            multihost.barrier("manifest")
    """)
    assert "PML012" not in rule_ids(out)


def test_pml013_set_iteration_into_collective_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def exchange():
        tags = {"a", "b"}
        for t in tags:
            multihost.barrier(t)
    """)
    assert "PML013" in rule_ids(out)


def test_pml013_unsorted_listdir_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def replay(d):
        return [os.path.join(d, n) for n in os.listdir(d)]
    """)
    assert "PML013" in rule_ids(out)


def test_pml013_sorted_listdir_clean(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def replay(d):
        return [os.path.join(d, n) for n in sorted(os.listdir(d))]
    """)
    assert "PML013" not in rule_ids(out)


def test_pml014_module_rng_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    import random

    def backoff(attempt):
        return 0.1 * attempt * (1 + random.random())
    """)
    assert "PML014" in rule_ids(out)


def test_pml014_wall_clock_seed_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    import time

    def make_seed():
        seed = int(time.time())
        return seed
    """)
    assert "PML014" in rule_ids(out)


def test_pml014_seeded_rng_clean(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    import random

    def backoff(attempt):
        rng = random.Random(7)
        return 0.1 * attempt * (1 + rng.random())
    """)
    assert "PML014" not in rule_ids(out)


def test_pml015_blocking_io_in_window_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def commit(store, path):
        multihost.barrier("data")
        store.put(path, b"x")
        multihost.barrier("commit")
    """)
    assert "PML015" in rule_ids(out)


def test_pml015_watchdogged_io_clean(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def commit(store, path):
        multihost.barrier("data")
        multihost.run_with_watchdog(
            lambda: store.put(path, b"x"), "publish", 5.0)
        multihost.barrier("commit")
    """)
    assert "PML015" not in rule_ids(out)


def test_pml015_interprocedural_io_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def write_side(path):
        with open(path, "w") as f:
            f.write("x")

    def commit(path):
        multihost.barrier("data")
        write_side(path)
        multihost.barrier("commit")
    """)
    fs = [f for f in out if f.rule == "PML015"]
    assert fs and fs[0].chain, rule_ids(out)


def test_pml016_typed_raise_between_collectives_fires(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def commit(ok):
        multihost.barrier("data")
        if not ok:
            raise ValueError("bad manifest")
        multihost.barrier("commit")
    """)
    assert "PML016" in rule_ids(out)


def test_pml016_divergence_taxonomy_exempt(tmp_path):
    # raising the peer-loss/divergence family IS the typed conversion
    # the rule wants — exempt by name
    out = lint(tmp_path, SPMD_HEADER + """
    from parmmg_tpu.failsafe import CollectiveDivergenceError

    def commit(ok):
        multihost.barrier("data")
        if not ok:
            raise CollectiveDivergenceError("schedules diverged")
        multihost.barrier("commit")
    """)
    assert "PML016" not in rule_ids(out)


def test_pml016_suppressible(tmp_path):
    out = lint(tmp_path, SPMD_HEADER + """
    def commit(ok):
        multihost.barrier("data")
        if not ok:
            # parmmg-lint: disable=PML016 -- peers are watchdog-bounded
            raise ValueError("bad manifest")
        multihost.barrier("commit")
    """)
    assert "PML016" not in rule_ids(out)


def test_cli_json_artifact(tmp_path):
    import json

    from parmmg_tpu.lint.cli import main as lint_main

    (tmp_path / "clean.py").write_text("def f():\n    return 1\n")
    art = tmp_path / "findings.json"
    rc = lint_main(["--json", str(art), "--root", str(tmp_path),
                    str(tmp_path)])
    assert rc == 0
    doc = json.loads(art.read_text())
    assert doc["count"] == 0 and doc["findings"] == []
    assert "PML016" in doc["rules"]


# --- collective-lockstep ledger ------------------------------------------


def test_ledger_hash_determinism_and_divergence():
    from parmmg_tpu.lint import contracts as c

    a, b = c.CollectiveLedger(), c.CollectiveLedger()
    for led in (a, b):
        led.record("barrier", 0, "hb:iteration:0")
        led.record("agree_flags", 0, "reform:0")
    # identical schedules -> identical digests on every rank
    assert a.digest == b.digest and a.count == b.count == 2
    # one phantom collective -> the digests part ways
    b.record("desync-fault", -1, "it1:comm@rank1")
    assert a.digest != b.digest
    # the digest fits the int32 psum lane with room for sum-of-squares
    assert 0 <= a.digest < (1 << 12)


def test_ledger_record_hook_unarmed_is_noop():
    from parmmg_tpu.lint import contracts as c

    c.uninstall_ledger()
    assert c.ledger() is None
    c.record_collective("barrier", 0, "t")   # validate="basic" path
    assert c.ledger() is None
    # verify is equally inert with no ledger installed
    c.verify_ledger(0)


def test_ledger_install_uninstall_cycle():
    from parmmg_tpu.lint import contracts as c

    led = c.install_ledger()
    try:
        assert c.install_ledger() is led     # idempotent, no reset
        c.record_collective("barrier", 0, "t")
        assert led.count == 1 and led.last == "barrier#0"
        # single-process verify is a no-op (no collective to compare)
        c.verify_ledger(0)
    finally:
        c.uninstall_ledger()
    assert c.ledger() is None


def test_harness_arms_ledger_only_under_full_validation():
    from types import SimpleNamespace

    from parmmg_tpu import failsafe
    from parmmg_tpu.lint import contracts as c

    basic = failsafe.harness(
        SimpleNamespace(validate="basic", validate_every=1), "test")
    try:
        assert c.ledger() is None            # zero-overhead contract
    finally:
        basic.finish()

    full = failsafe.harness(
        SimpleNamespace(validate="full", validate_every=1), "test")
    try:
        assert c.ledger() is not None
        full.verify_collectives(0)           # single-process: no raise
    finally:
        full.finish()
    assert c.ledger() is None                # finish() disarms


def test_desync_fault_poisons_ledger():
    from parmmg_tpu import failsafe
    from parmmg_tpu.lint import contracts as c

    led = c.install_ledger()
    try:
        before = led.digest
        plan = failsafe.FaultPlan.parse("it1:comm:desync")
        assert plan.fire(1, "comm", None) is None   # state untouched
        assert led.count == 1 and led.digest != before
        assert plan.faults[0].fired
    finally:
        c.uninstall_ledger()


def test_desync_fault_pairing_is_exclusive():
    from parmmg_tpu import failsafe

    for bad in ("it1:comm:kill", "it1:remesh:desync", "it0:ckpt:desync"):
        with pytest.raises(ValueError):
            failsafe.FaultPlan.parse(bad)
    plan = failsafe.FaultPlan.parse("it1:comm:desync@rank1")
    f = plan.faults[0]
    assert (f.it, f.phase, f.kind, f.rank) == (1, "comm", "desync", 1)
