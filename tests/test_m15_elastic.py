"""M15: elastic resume + durable checkpoint I/O — the in-process half.

Unit coverage for what the chaos harness (tools/chaos_smoke.py) and the
multihost smoke's elastic leg (tools/fault_smoke.py --multihost phase D,
test_m10's slow subprocess matrix) exercise end to end:

- `utils.retry.retry`: deterministic seeded jitter, retry_on filtering,
  attempt exhaustion, the on_retry hook;
- `io.ckpt_store`: spec resolution + the multi-rank shard-put /
  newest-epoch-get fault legs (the per-backend put/get/list/delete/
  publish/retry/slowio contract moved to the parametrized suite in
  tests/test_m19_store_contract.py, which runs it identically against
  LocalFSStore, ObjectStore and GCSStore-on-fake-server);
- elastic `Checkpointer.load`: an N-rank manifest re-concatenated under
  world sizes 1/3/4 bit for bit, digest verification retained, the
  fingerprint refusal retained (m14 keeps the same-world coverage);
- rank-scoped GC: rank r prunes only its own proc files, rank 0 the
  manifests + stale ranks; concurrent deletes tolerated;
- async snapshot staging: stage returns before the epoch is committed,
  the NEXT stage commits the previous epoch only, writer failures
  surface typed at the commit point, `overlap_s` accounts hidden wall
  time, and the preemption path drains synchronously;
- the proactive preemption notice (file / callback / injected
  ``preempt-notice`` fault) forcing an out-of-cadence checkpoint.

The world matrix here is load-level and shrink-biased (2→{1,3,4});
the GROW direction — `_resume_stacked` re-cut, grow-under-way through
the driver, and the notice→shrink / capacity→grow supervisor protocol
— lives in tests/test_m20_elastic_world.py, with the process-level
fleet story in tools/chaos_smoke.py --elastic.
"""

import os
import signal
import time

import jax
import numpy as np
import pytest

from parmmg_tpu import failsafe
from parmmg_tpu.core.tags import ReturnStatus
from parmmg_tpu.io import ckpt_store
from parmmg_tpu.io.ckpt_store import (
    CheckpointIOError,
    LocalFSStore,
    ObjectStore,
)
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.parallel import multihost
from parmmg_tpu.parallel.distribute import split_mesh
from parmmg_tpu.parallel.partition import sfc_partition
from parmmg_tpu.utils.gen import unit_cube_mesh
from parmmg_tpu.utils.retry import retry

C_OPTS = dict(hsiz=0.45, niter=2, max_sweeps=2, hgrad=None,
              polish_sweeps=0)


@pytest.fixture(scope="module")
def stacked8():
    mesh = unit_cube_mesh(2)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 8)))
    st, comm = split_mesh(mesh, part, 8)
    return st


@pytest.fixture(autouse=True)
def _clear_preempt_notice():
    yield
    multihost.clear_preemption_notice()
    multihost.set_preemption_callback(None)


def _mesh_equal(got, want, names=("vert", "tet", "vmask", "tmask",
                                  "vglob", "met")):
    for name in names:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(getattr(got, name))),
            np.asarray(jax.device_get(getattr(want, name))),
            err_msg=name,
        )


# ---------------------------------------------------------------------------
# utils.retry.retry
# ---------------------------------------------------------------------------


def test_retry_deterministic_jitter_and_filtering():
    def delays_for(seed):
        delays = []

        def boom():
            raise OSError("x")

        with pytest.raises(OSError):
            retry(boom, attempts=4, backoff=0.01, jitter=0.5, seed=seed,
                  retry_on=OSError, sleep=delays.append)
        return delays

    a, b = delays_for(7), delays_for(7)
    assert a == b and len(a) == 3          # seeded stream replays
    assert delays_for(8) != a              # and actually depends on it
    # exponential envelope: base*2^k <= d < base*2^k*(1+jitter)
    for k, d in enumerate(a):
        assert 0.01 * 2 ** k <= d <= 0.01 * 2 ** k * 1.5

    # non-matching exceptions propagate on the first attempt
    calls = []

    def typeerr():
        calls.append(1)
        raise TypeError("no")

    with pytest.raises(TypeError):
        retry(typeerr, attempts=5, backoff=0.0, retry_on=OSError)
    assert len(calls) == 1
    # success passes through; on_retry sees each failed attempt
    seen = []
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("flake")
        return "ok"

    assert retry(flaky, attempts=4, backoff=0.0, retry_on=OSError,
                 on_retry=lambda e, k: seen.append(k)) == "ok"
    assert seen == [0, 1]


# ---------------------------------------------------------------------------
# store resolution + fault matrix
# ---------------------------------------------------------------------------


def test_make_store_specs(tmp_path):
    s = ckpt_store.make_store(None, str(tmp_path / "a"))
    assert isinstance(s, LocalFSStore) and s.dir.endswith("a")
    s = ckpt_store.make_store("file://" + str(tmp_path / "b"), None)
    assert isinstance(s, LocalFSStore) and s.dir.endswith("b")
    s = ckpt_store.make_store(str(tmp_path / "c"), None)
    assert isinstance(s, LocalFSStore)
    m1 = ckpt_store.make_store("mem://m15-spec", None)
    m2 = ckpt_store.make_store("mem://m15-spec", None)
    assert isinstance(m1, ObjectStore) and m1.bucket is m2.bucket
    inst = ObjectStore({})
    assert ckpt_store.make_store(inst, None) is inst
    with pytest.raises(ValueError, match="resolve"):
        ckpt_store.make_store(None, None)


def _two_ranks(opts, store_factory):
    """Two in-process 'ranks' sharing one bucket (the m14 pattern)."""
    return [
        failsafe.Checkpointer(
            None, opts, "distributed", rank=r, world=2,
            barrier=lambda t: None, store=store_factory(r),
        )
        for r in (0, 1)
    ]


def test_sharded_fault_legs(stacked8):
    """Multi-rank shard-file faults the parametrized m19 contract
    cannot express (it drives ONE store; these need two ranks sharing
    a bucket): a persistent shard-put failure leaves an epoch that is
    never resumable, and an unreadable NEWEST epoch falls back to the
    previous committed one silently."""
    opts = AdaptOptions(hsiz=0.35, niter=2)

    # --- persistent shard-put failure: typed abort; the incomplete
    # epoch is never resumable. (The in-process stand-in barrier is a
    # no-op, so rank 0's manifest does land here — in a real world the
    # data barrier holds it back; either way the missing shard file
    # disqualifies the epoch at load time.)
    bucket2: dict = {}

    def cb2(op, name, timeout):
        if op == "put" and name.endswith(".proc1.npz"):
            raise OSError("store down")

    ranks2 = _two_ranks(opts, lambda r: ObjectStore(
        bucket2, attempts=2, backoff=0.0, fault_cb=cb2))
    ranks2[0].save(0, {"mesh": stacked8}, history=[], emult=1.6)
    with pytest.raises(CheckpointIOError, match="2 attempts"):
        ranks2[1].save(0, {"mesh": stacked8}, history=[], emult=1.6)
    assert "ckpt_00000.proc1.npz" not in bucket2
    with pytest.warns(UserWarning, match="starting fresh"):
        assert ranks2[0].load() is None

    # --- get failure on the newest checkpoint: fall back to previous -
    bucket4: dict = {}
    arm = {"on": False}

    def cb4(op, name, timeout):
        if arm["on"] and op == "get" and "00001" in name \
                and name.endswith(".npz"):
            raise OSError("flaky read")

    ranks4 = _two_ranks(opts, lambda r: ObjectStore(
        bucket4, attempts=2, backoff=0.0, fault_cb=cb4))
    for it in (0, 1):
        for c in ranks4:
            c.save(it, {"mesh": stacked8}, history=[], emult=1.6)
    arm["on"] = True
    # newest epoch unreadable -> SILENT fallback to the previous
    # committed one (keep=2 retains both); no refusal, no warning
    rs = ranks4[0].load()
    assert rs is not None and rs.it == 0
    arm["on"] = False
    assert ranks4[0].load().it == 1


# ---------------------------------------------------------------------------
# elastic load
# ---------------------------------------------------------------------------


def test_elastic_load_world_matrix(tmp_path, stacked8):
    """A 2-rank manifest loads under world sizes 1, 3 and 4 with the
    re-concatenated state bit-identical to the source, the source world
    recorded, and digest verification still armed."""
    opts = AdaptOptions(hsiz=0.35, niter=2)
    ck = str(tmp_path / "ck")
    writers = [
        failsafe.Checkpointer(ck, opts, "distributed", rank=r, world=2,
                              barrier=lambda t: None)
        for r in (0, 1)
    ]
    aux = {"hausd": np.asarray([0.01, 0.02])}
    for c in writers:
        c.save(0, {"mesh": stacked8}, history=[{"iter": 0}], emult=1.7,
               meta={"icap": 4}, aux_arrays=aux)
    for world in (1, 3, 4):
        rdr = failsafe.Checkpointer(ck, opts, "distributed", rank=0,
                                    world=world, barrier=lambda t: None)
        rs = rdr.load()
        assert rs is not None and rs.source_world == 2, world
        assert rs.it == 0 and rs.emult == 1.7
        _mesh_equal(rs.mesh, stacked8)
        np.testing.assert_array_equal(
            rs.meta["aux_arrays"]["hausd"], aux["hausd"]
        )
    # digest verification retained on the elastic path: corrupt one
    # source shard file -> the (only) checkpoint is rejected
    path = os.path.join(ck, "ckpt_00000.proc1.npz")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    single = failsafe.Checkpointer(ck, opts, "distributed", rank=0,
                                   world=1, barrier=lambda t: None)
    with pytest.warns(UserWarning, match="starting fresh"):
        assert single.load() is None


# ---------------------------------------------------------------------------
# rank-scoped GC
# ---------------------------------------------------------------------------


def test_gc_prunes_only_own_rank_files(tmp_path, stacked8):
    opts = AdaptOptions(hsiz=0.35, niter=4)
    ck = str(tmp_path / "ck")
    ranks = [
        failsafe.Checkpointer(ck, opts, "distributed", rank=r, world=2,
                              keep=1, barrier=lambda t: None)
        for r in (0, 1)
    ]
    # two committed epochs, but only rank 1 runs its GC: rank 0's old
    # files (manifest is rank 0's to prune) must survive
    for it in (0, 1):
        for c in ranks:
            c.save(it, {"mesh": stacked8}, history=[], emult=1.6)
        # undo the automatic prune of epoch `it` to re-drive it manually
    names = sorted(os.listdir(ck))
    # both ranks pruned after commit: only epoch 1 remains
    assert names == ["ckpt_00001.json", "ckpt_00001.proc0.npz",
                     "ckpt_00001.proc1.npz"], names
    # re-create a stale epoch and prune from ONE rank only
    for c in ranks:
        c.save(2, {"mesh": stacked8}, history=[], emult=1.6)
    stale = [
        ("ckpt_00001.json", b"{}"), ("ckpt_00001.proc0.npz", b"x"),
        ("ckpt_00001.proc1.npz", b"x"),
        ("ckpt_00001.proc7.npz", b"x"),     # elastic leftover rank
    ]
    for name, data in stale:
        with open(os.path.join(ck, name), "wb") as f:
            f.write(data)
    ranks[1]._prune()
    names = set(os.listdir(ck))
    assert "ckpt_00001.proc1.npz" not in names       # own file: pruned
    assert {"ckpt_00001.json", "ckpt_00001.proc0.npz",
            "ckpt_00001.proc7.npz"} <= names         # not rank 1's
    ranks[0]._prune()
    names = set(os.listdir(ck))
    # rank 0 owns the manifest, its own proc file, and stale ranks
    assert not any(n.startswith("ckpt_00001.") for n in names), names
    # concurrent-delete tolerance: deleting a missing object succeeds
    ranks[0].store.delete("ckpt_00001.proc0.npz")


# ---------------------------------------------------------------------------
# async staging
# ---------------------------------------------------------------------------


class _SlowStore(ObjectStore):
    """ObjectStore whose npz puts stall (manifest publishes stay fast),
    standing in for slow durable media under async staging."""

    def __init__(self, bucket, delay):
        super().__init__(bucket, attempts=1, backoff=0.0)
        self.delay = delay

    def _put(self, name, data):
        if name.endswith(".npz"):
            time.sleep(self.delay)
        super()._put(name, data)


def test_async_staging_blocks_on_previous_epoch_only(stacked8):
    bucket: dict = {}
    opts = AdaptOptions(hsiz=0.35, niter=4)
    c = failsafe.Checkpointer(None, opts, "distributed", rank=0,
                              world=1, store=_SlowStore(bucket, 0.4))
    meshes = {"mesh": stacked8}
    t0 = time.perf_counter()
    c.stage(0, meshes, history=[], emult=1.6)
    assert time.perf_counter() - t0 < 0.3       # snapshot only, no put
    assert "ckpt_00000.json" not in bucket      # epoch 0 not yet durable
    time.sleep(0.6)                             # "compute" overlaps I/O
    t0 = time.perf_counter()
    c.stage(1, meshes, history=[], emult=1.6)   # commits epoch 0 first
    stage1_block = time.perf_counter() - t0
    assert "ckpt_00000.json" in bucket          # previous epoch durable
    assert "ckpt_00001.json" not in bucket      # current still staged
    assert stage1_block < 0.3                   # epoch 0 was already done
    c.drain()
    assert "ckpt_00001.json" in bucket
    # the writer's 0.4 s npz put was hidden behind the 0.6 s compute
    assert c.overlap_s >= 0.3, c.overlap_s
    # both epochs readable
    assert c.load().it == 1


def test_async_writer_failure_surfaces_typed_at_commit(stacked8):
    def cb(op, name, timeout):
        if op == "put":
            raise OSError("store down")

    c = failsafe.Checkpointer(
        None, AdaptOptions(hsiz=0.35), "distributed", rank=0, world=1,
        store=ObjectStore({}, attempts=2, backoff=0.0, fault_cb=cb),
    )
    c.stage(0, {"mesh": stacked8}, history=[], emult=1.6)
    with pytest.raises(CheckpointIOError, match="attempts"):
        c.drain()
    # the failed epoch is cleared: drain is idempotent afterwards
    c.drain()


def test_preemption_drains_staged_epoch(tmp_path, stacked8):
    """The SIGTERM contract under async staging: once the harness sees
    preempt_requested, save() commits synchronously — the process never
    exits with checkpoint state in flight."""
    opts = AdaptOptions(
        hsiz=0.35, niter=2, checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_async=True,
    )
    fs = failsafe.harness(opts, driver="distributed")
    assert fs.async_staging and fs.ckpt is not None
    prev = signal.getsignal(signal.SIGTERM)
    fs.arm_preemption()
    try:
        fs.save(0, {"mesh": stacked8}, history=[], emult=1.6,
                force=True)
        os.kill(os.getpid(), signal.SIGTERM)
        assert fs.preempt_requested
        fs.save(1, {"mesh": stacked8}, history=[], emult=1.6,
                force=True)
        # both epochs committed: nothing in flight after the save
        names = sorted(os.listdir(tmp_path / "ck"))
        assert "ckpt_00000.json" in names and "ckpt_00001.json" in names
        fs.finish()     # idempotent
    finally:
        fs.disarm_preemption()
    assert signal.getsignal(signal.SIGTERM) == prev
    assert fs.ckpt_overlap_s >= 0.0


# ---------------------------------------------------------------------------
# proactive preemption notice
# ---------------------------------------------------------------------------


def test_preempt_notice_sources(tmp_path, monkeypatch):
    fs = failsafe.harness(
        AdaptOptions(checkpoint_dir=str(tmp_path / "ck")),
        driver="centralized",
    )
    assert not fs.preempt_notice()
    # 1. drain file
    drain = tmp_path / "drain"
    monkeypatch.setenv("PMMGTPU_PREEMPT_FILE", str(drain))
    assert not fs.preempt_notice()
    drain.write_text("")
    assert fs.preempt_notice()
    multihost.clear_preemption_notice()
    drain.unlink()
    assert not fs.preempt_notice()
    # 2. callback probe (latched on first truthy return)
    hits = {"n": 0}

    def probe():
        hits["n"] += 1
        return hits["n"] >= 2

    multihost.set_preemption_callback(probe)
    assert not fs.preempt_notice()
    assert fs.preempt_notice() and fs.preempt_notice()
    multihost.set_preemption_callback(None)
    multihost.clear_preemption_notice()
    # 3. the injected fault kind latches it at a phase boundary
    plan = failsafe.FaultPlan.parse("it0:remesh:preempt-notice")
    plan.fire(0, "remesh", unit_cube_mesh(2))
    assert fs.preempt_notice()
    # no checkpointer -> nothing to commit proactively -> never pending
    bare = failsafe.harness(AdaptOptions(), driver="centralized")
    assert not bare.preempt_notice()


def test_preempt_notice_forces_out_of_cadence_checkpoint(tmp_path):
    """Driver-level: with checkpoint_every far beyond niter, an
    injected maintenance notice still commits a checkpoint at its
    iteration boundary — and the run completes normally (the notice is
    proactive, not terminal)."""
    ck = tmp_path / "ck"
    out, info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(faults="it0:remesh:preempt-notice",
                     checkpoint_every=50, **C_OPTS),
        checkpoint_dir=str(ck),
    )
    assert info["status"] == ReturnStatus.SUCCESS
    names = sorted(os.listdir(ck))
    assert "ckpt_00000.json" in names, names
    # the latched notice is process-global: clear it so the control
    # run below really runs notice-free
    multihost.clear_preemption_notice()
    # without the notice the same cadence writes nothing
    ck2 = tmp_path / "ck2"
    adapt(unit_cube_mesh(2),
          AdaptOptions(checkpoint_every=50, **C_OPTS),
          checkpoint_dir=str(ck2))
    assert not ck2.exists() or not os.listdir(ck2)


# ---------------------------------------------------------------------------
# driver-level elastic re-cut + store plumbing (subprocess-free)
# ---------------------------------------------------------------------------


def test_driver_resumes_from_object_store(tmp_path):
    """`checkpoint_store` plumbs through the centralized driver: a run
    killed mid-flight through a mem:// bucket resumes from it
    bit-identically (the chaos harness covers the LocalFS path)."""
    spec = "mem://m15-driver"
    ckpt_store.memory_bucket("m15-driver").clear()
    ref, ref_info = adapt(unit_cube_mesh(2), AdaptOptions(**C_OPTS))

    def key(m, info):
        h = info["qual_out"]
        return (
            int(np.asarray(jax.device_get(m.vmask)).sum()),
            int(np.asarray(jax.device_get(m.tmask)).sum()),
            tuple(int(x) for x in np.asarray(jax.device_get(h.counts))),
        )

    with pytest.raises(failsafe.PreemptionError):
        adapt(unit_cube_mesh(2),
              AdaptOptions(checkpoint_store=spec,
                           faults=failsafe.FaultPlan.parse(
                               "it1:post:kill", kill_mode="raise"),
                           **C_OPTS))
    bucket = ckpt_store.memory_bucket("m15-driver")
    assert any(n.endswith(".json") for n in bucket)
    res, res_info = adapt(
        unit_cube_mesh(2),
        AdaptOptions(checkpoint_store=spec, **C_OPTS),
    )
    assert res_info["status"] == ReturnStatus.SUCCESS
    assert key(res, res_info) == key(ref, ref_info)


@pytest.mark.slow
def test_elastic_resume_1_to_2_ranks(tmp_path):
    """The 1→2 elastic direction (2→1 is fault_smoke --multihost phase
    D / the m10 matrix): a single-controller run (all 8 devices,
    PMMGTPU_SPMD_SWEEPS=1) killed mid-run leaves a world-1 manifest; a
    2-process world resumes from that SAME manifest and must converge
    to the digest of an uninterrupted run at the target world size."""
    import socket
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")

    def base_env(extra):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        for k in ("PMMGTPU_COORDINATOR", "PMMGTPU_NUM_PROCS",
                  "PMMGTPU_PROC_ID"):
            env.pop(k, None)
        env.update(JAX_PLATFORMS="cpu", PYTHONPATH=root,
                   PYTHONFAULTHANDLER="1")
        env.update(extra)
        return env

    def run_single(extra):
        env = base_env(dict(
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
            PMMGTPU_SPMD_SWEEPS="1", **extra,
        ))
        p = subprocess.run(
            [sys.executable, worker, "--failsafe"], env=env, cwd=root,
            capture_output=True, text=True, timeout=1200,
        )
        return p.returncode, p.stdout + p.stderr

    def run_pair(tag, extra):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs, logs = [], []
        for pid in (0, 1):
            env = base_env(dict(
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
                PMMGTPU_NUM_PROCS="2", PMMGTPU_PROC_ID=str(pid),
                PMMGTPU_WATCHDOG="300", **extra,
            ))
            lp = tmp_path / f"{tag}{pid}.log"
            logs.append(lp)
            procs.append(subprocess.Popen(
                [sys.executable, worker, "--failsafe"], env=env,
                stdout=open(lp, "w"), stderr=subprocess.STDOUT,
                cwd=root,
            ))
        try:
            rcs = [p.wait(timeout=1200) for p in procs]
        finally:
            for p in procs:
                p.kill()
        return rcs, [lp.read_text() for lp in logs]

    def digests(text):
        return [ln for ln in text.splitlines()
                if ln.startswith("ADAPT_DIGEST")]

    # uninterrupted reference at the TARGET world size (2 processes)
    rcs, logs = run_pair("ref", {})
    assert rcs == [0, 0], (rcs, logs[0][-2000:], logs[1][-2000:])
    ref = digests(logs[0])
    assert ref and digests(logs[1]) == ref

    # world-1 run killed after its first committed epoch
    ck = str(tmp_path / "ck")
    rc, out = run_single({
        "PMMGTPU_CKPT_DIR": ck, "PARMMG_FAULTS": "it0:post:kill",
    })
    assert rc == failsafe.KILL_EXIT_CODE, (rc, out[-2000:])
    names = sorted(os.listdir(ck))
    assert "ckpt_00000.json" in names and "ckpt_00000.npz" in names, (
        names
    )

    # 2-process elastic resume from the world-1 manifest
    rcs, logs = run_pair("resume", {"PMMGTPU_CKPT_DIR": ck})
    assert rcs == [0, 0], (rcs, logs[0][-2000:], logs[1][-2000:])
    assert digests(logs[0]) == ref and digests(logs[1]) == ref, (
        digests(logs[0]), ref,
    )


@pytest.mark.slow
def test_elastic_recut_to_different_shard_count(tmp_path):
    """A distributed checkpoint written at 4 shards resumes at nparts=8
    through the merge + SFC re-cut path: the run completes with a
    conformal mesh (bit-identity is only promised for an unchanged
    shard count — covered by the subprocess legs)."""
    from parmmg_tpu.models.distributed import DistOptions, adapt_distributed
    from parmmg_tpu.utils.conformity import check_mesh
    from parmmg_tpu.models.distributed import merge_adapted

    ck = str(tmp_path / "ck")
    opts4 = DistOptions(nparts=4, min_shard_elts=8, checkpoint_dir=ck,
                        faults=failsafe.FaultPlan.parse(
                            "it0:post:kill", kill_mode="raise"),
                        **C_OPTS)
    with pytest.raises(failsafe.PreemptionError):
        adapt_distributed(unit_cube_mesh(3), opts4)
    assert any(n.endswith(".json") for n in os.listdir(ck))
    opts8 = DistOptions(nparts=8, min_shard_elts=8, checkpoint_dir=ck,
                        **C_OPTS)
    st, comm, info = adapt_distributed(unit_cube_mesh(3), opts8)
    assert st.vert.shape[0] == 8
    assert info["status"] == ReturnStatus.SUCCESS
    merged = merge_adapted(st, comm)
    assert check_mesh(merged, check_boundary=False).ok
