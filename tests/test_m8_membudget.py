"""Memory-budget tests (the `zaldy_pmmg.c` per-process budget role)."""

import numpy as np
import pytest

from parmmg_tpu.models.adapt import (
    AdaptOptions, adapt, ensure_capacity, estimate_mesh_bytes,
)
from parmmg_tpu.utils.gen import unit_cube_mesh


def test_budget_blocks_growth():
    m = unit_cube_mesh(4, headroom=1.05)
    # budget below what any refinement growth would need
    tiny = estimate_mesh_bytes(m, m.pcap, m.tcap, m.fcap, m.ecap) / 1e6
    opts = AdaptOptions(hsiz=0.05, niter=1, max_sweeps=2,
                        mem_budget_mb=tiny * 1.01)
    with pytest.raises(RuntimeError, match="memory budget"):
        adapt(m, opts)


def test_budget_allows_within():
    m = unit_cube_mesh(3)
    opts = AdaptOptions(hsiz=0.3, niter=1, max_sweeps=3,
                        mem_budget_mb=500.0)
    out, _ = adapt(m, opts)
    assert int(out.ntet) > 0


def test_distributed_budget_degrades_to_lowfailure():
    from parmmg_tpu.core.tags import ReturnStatus
    from parmmg_tpu.models.distributed import DistOptions, adapt_distributed

    m = unit_cube_mesh(4)
    tiny = estimate_mesh_bytes(m, m.pcap, m.tcap, m.fcap, m.ecap) / 1e6
    opts = DistOptions(hsiz=0.06, niter=1, max_sweeps=2, nparts=2,
                       min_shard_elts=8, mem_budget_mb=tiny * 0.6)
    stacked, comm, info = adapt_distributed(m, opts)
    # the iteration loop degrades the budget failure to LOWFAILURE and
    # returns the last conformal snapshot (here: the distributed input)
    assert info["status"] == ReturnStatus.LOWFAILURE
    assert int(np.asarray(stacked.tmask).sum()) > 0
