"""M10: multi-host (multi-process) collectives — the DCN scaling axis.

The reference runs one MPI rank per node and exchanges over the
network (`mpirun -np N parmmg`); here two OS processes each own 4 of 8
CPU devices and the shard_map collectives (halo all_to_all, psum)
cross the process boundary through JAX's multi-controller runtime —
the exact code path that rides DCN between TPU slices
(`parallel/multihost.py`). This is a REAL multi-process run, not a
simulation: the two workers coordinate over gRPC and each executes
only its addressable half of the global program."""

import os
import subprocess
import sys

import pytest


def test_init_from_env_validates_rank_and_world(monkeypatch):
    """Bugfix coverage: a malformed PMMGTPU_PROC_ID / NUM_PROCS must
    raise a typed MultihostConfigError BEFORE touching
    jax.distributed.initialize (which would block forever waiting for
    a rank that can never dial in)."""
    from parmmg_tpu.parallel import multihost

    monkeypatch.setattr(multihost, "_INITIALIZED", False)
    monkeypatch.setenv("PMMGTPU_COORDINATOR", "127.0.0.1:1")
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "2")
    monkeypatch.setenv("PMMGTPU_PROC_ID", "2")
    with pytest.raises(multihost.MultihostConfigError,
                       match="out of range"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_PROC_ID", "-1")
    with pytest.raises(multihost.MultihostConfigError,
                       match="out of range"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "zebra")
    with pytest.raises(multihost.MultihostConfigError,
                       match="integers"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "0")
    monkeypatch.setenv("PMMGTPU_PROC_ID", "0")
    with pytest.raises(multihost.MultihostConfigError,
                       match="positive"):
        multihost.init_from_env()
    monkeypatch.delenv("PMMGTPU_PROC_ID")
    with pytest.raises(multihost.MultihostConfigError,
                       match="incomplete"):
        multihost.init_from_env()
    assert not multihost._INITIALIZED


@pytest.mark.slow
def test_two_process_collectives(tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")

    # ephemeral coordinator port: a hardcoded one collides with
    # lingering workers from aborted runs or parallel test sessions
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def env_for(pid):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=root,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
        )
        return env

    procs = []
    logs = []
    for pid in (0, 1):
        log = open(tmp_path / f"proc{pid}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env_for(pid),
            stdout=log, stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        for p in procs:
            assert p.wait(timeout=900) == 0, (
                (tmp_path / "proc0.log").read_text()
                + (tmp_path / "proc1.log").read_text()
            )
    finally:
        for log in logs:
            log.close()
        for p in procs:
            p.kill()

    lines = []
    for pid in (0, 1):
        text = (tmp_path / f"proc{pid}.log").read_text()
        ok = [ln for ln in text.splitlines() if "MULTIHOST_OK" in ln]
        assert ok, text
        lines.append(ok[0])
    # both controllers computed identical replicated reductions
    strip = [
        " ".join(t for t in ln.split() if not t.startswith("proc="))
        for ln in lines
    ]
    assert strip[0] == strip[1], lines


@pytest.mark.slow
def test_two_process_adaptation_matches_single_process(tmp_path):
    """The FULL driver under two controllers: `adapt_stacked_input`
    (niter=2, including one interface-displacement + migration round)
    runs with its sweep programs genuinely SPMD over the 8 devices of
    both processes, and the merged output must be BIT-IDENTICAL
    (sha256 over every entity array) to a single-process run of the
    same SPMD programs. The reference analog: its entire CI matrix runs
    the driver under `mpiexec -np {1,2,4,6,8}`
    (cmake/testing/pmmg_tests.cmake:30-38)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def base_env(ndev):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
            PYTHONPATH=root,
        )
        return env

    # single-process reference: same SPMD sweep programs, one controller
    ref_env = base_env(8)
    ref_env["PMMGTPU_SPMD_SWEEPS"] = "1"
    ref = subprocess.run(
        [sys.executable, worker, "--adapt"], env=ref_env, cwd=root,
        capture_output=True, text=True, timeout=1200,
    )
    assert ref.returncode == 0, ref.stdout + ref.stderr
    ref_line = [ln for ln in ref.stdout.splitlines()
                if ln.startswith("ADAPT_DIGEST")]
    assert ref_line, ref.stdout + ref.stderr

    procs = []
    logs = []
    for pid in (0, 1):
        env = base_env(4)
        env.update(
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
        )
        log = open(tmp_path / f"adapt{pid}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, worker, "--adapt"], env=env,
            stdout=log, stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        for p in procs:
            assert p.wait(timeout=1200) == 0, (
                (tmp_path / "adapt0.log").read_text()
                + (tmp_path / "adapt1.log").read_text()
            )
    finally:
        for log in logs:
            log.close()
        for p in procs:
            p.kill()

    for pid in (0, 1):
        text = (tmp_path / f"adapt{pid}.log").read_text()
        ok = [ln for ln in text.splitlines()
              if ln.startswith("ADAPT_DIGEST")]
        assert ok, text
        assert ok[0] == ref_line[0], (
            f"proc {pid} diverged:\n  2-proc: {ok[0]}\n"
            f"  1-proc: {ref_line[0]}"
        )


def _run_failsafe_pair(tmp_path, tag, extra_env, timeout=1200):
    """Two coordinated `multihost_worker.py --failsafe` processes (4
    CPU devices each); returns (exit codes, log texts)."""
    import socket

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, logs = [], []
    for pid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=root,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
        )
        env.update(extra_env)
        lp = tmp_path / f"{tag}{pid}.log"
        logs.append(lp)
        procs.append(subprocess.Popen(
            [sys.executable, worker, "--failsafe"], env=env,
            stdout=open(lp, "w"), stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        rcs = [p.wait(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            p.kill()
    return rcs, [lp.read_text() for lp in logs]


def _digests(text):
    return [ln for ln in text.splitlines()
            if ln.startswith("ADAPT_DIGEST")]


@pytest.mark.slow
def test_two_process_kill_resume_sharded_checkpoint(tmp_path):
    """The multi-host fail-safe acceptance path, subprocess-real:

    1. an uninterrupted 2-process run fixes the reference digest;
    2. the same run with ``it0:post:kill@rank1`` and a checkpoint dir:
       rank 1 must die with KILL_EXIT_CODE only AFTER the sharded
       checkpoint's barrier-committed manifest (layout + digests
       verified here), and rank 0's collective watchdog must convert
       the silent peer loss into PeerLostError
       (PEER_LOST_EXIT_CODE) instead of hanging;
    3. an ELASTIC single-process resume of the 2-process checkpoint
       (PMMGTPU_SPMD_SWEEPS=1 — the identical SPMD sweep programs on
       one controller) completes bit-identically to (1);
    4. a 2-process resume completes bit-identically to (1).

    The reference analog: per-rank restart state + MPI_Barrier'd
    checkpoint I/O in the node-scale runs of RR-9307."""
    import json
    import shutil

    from parmmg_tpu import failsafe

    rcs, logs = _run_failsafe_pair(
        tmp_path, "ref", {"PMMGTPU_WATCHDOG": "300"}
    )
    assert rcs == [0, 0], logs[0][-2000:] + logs[1][-2000:]
    ref = _digests(logs[0])
    assert ref and _digests(logs[1]) == ref

    ck = tmp_path / "ck"
    rcs, logs = _run_failsafe_pair(tmp_path, "kill", {
        "PMMGTPU_CKPT_DIR": str(ck),
        "PMMGTPU_WATCHDOG": "60",
        "PARMMG_FAULTS": "it0:post:kill@rank1",
    })
    assert rcs[1] == failsafe.KILL_EXIT_CODE, (rcs, logs[1][-2000:])
    assert rcs[0] == failsafe.PEER_LOST_EXIT_CODE, (rcs, logs[0][-2000:])
    assert "PEER_LOST" in logs[0]
    # barrier-committed sharded layout: manifest + one data file per
    # rank, no temp litter, digests verifying
    names = sorted(os.listdir(ck))
    assert names == ["ckpt_00000.json", "ckpt_00000.proc0.npz",
                     "ckpt_00000.proc1.npz"], names
    with open(ck / "ckpt_00000.json") as f:
        doc = json.load(f)
    assert doc["world"] == 2 and doc["sharded"] == ["mesh"]
    import numpy as np

    for r in (0, 1):
        with np.load(ck / f"ckpt_00000.proc{r}.npz") as z:
            arrs = {k: z[k] for k in z.files}
        assert failsafe._digest_arrays(arrs) == doc["digests"][str(r)]

    # elastic resume: a 1-process run (all 8 devices on one
    # controller, same SPMD sweep programs) re-concatenates the 2-rank
    # shard files and continues to the SAME digest — against a COPY of
    # the checkpoint so phase 4's 2-process resume sees the original
    ck1 = tmp_path / "ck_elastic"
    shutil.copytree(ck, ck1)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=root, PMMGTPU_CKPT_DIR=str(ck1),
        PMMGTPU_SPMD_SWEEPS="1",
    )
    p = subprocess.run(
        [sys.executable,
         os.path.join(root, "tests", "multihost_worker.py"),
         "--failsafe"],
        env=env, capture_output=True, text=True, timeout=1200, cwd=root,
    )
    assert p.returncode == 0, (
        p.returncode, p.stdout[-2000:], p.stderr[-2000:],
    )
    assert _digests(p.stdout) == ref, (_digests(p.stdout), ref)

    rcs, logs = _run_failsafe_pair(tmp_path, "resume", {
        "PMMGTPU_CKPT_DIR": str(ck), "PMMGTPU_WATCHDOG": "300",
    })
    assert rcs == [0, 0], logs[0][-2000:] + logs[1][-2000:]
    assert _digests(logs[0]) == ref and _digests(logs[1]) == ref, (
        _digests(logs[0]), ref,
    )
