"""M10: multi-host (multi-process) collectives — the DCN scaling axis.

The reference runs one MPI rank per node and exchanges over the
network (`mpirun -np N parmmg`); here two OS processes each own 4 of 8
CPU devices and the shard_map collectives (halo all_to_all, psum)
cross the process boundary through JAX's multi-controller runtime —
the exact code path that rides DCN between TPU slices
(`parallel/multihost.py`). This is a REAL multi-process run, not a
simulation: the two workers coordinate over gRPC and each executes
only its addressable half of the global program."""

import os
import subprocess
import sys

import pytest


def test_init_from_env_validates_rank_and_world(monkeypatch):
    """Bugfix coverage: a malformed PMMGTPU_PROC_ID / NUM_PROCS must
    raise a typed MultihostConfigError BEFORE touching
    jax.distributed.initialize (which would block forever waiting for
    a rank that can never dial in)."""
    from parmmg_tpu.parallel import multihost

    monkeypatch.setattr(multihost, "_INITIALIZED", False)
    monkeypatch.setenv("PMMGTPU_COORDINATOR", "127.0.0.1:1")
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "2")
    monkeypatch.setenv("PMMGTPU_PROC_ID", "2")
    with pytest.raises(multihost.MultihostConfigError,
                       match="out of range"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_PROC_ID", "-1")
    with pytest.raises(multihost.MultihostConfigError,
                       match="out of range"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "zebra")
    with pytest.raises(multihost.MultihostConfigError,
                       match="integers"):
        multihost.init_from_env()
    monkeypatch.setenv("PMMGTPU_NUM_PROCS", "0")
    monkeypatch.setenv("PMMGTPU_PROC_ID", "0")
    with pytest.raises(multihost.MultihostConfigError,
                       match="positive"):
        multihost.init_from_env()
    monkeypatch.delenv("PMMGTPU_PROC_ID")
    with pytest.raises(multihost.MultihostConfigError,
                       match="incomplete"):
        multihost.init_from_env()
    assert not multihost._INITIALIZED


@pytest.mark.slow
def test_two_process_collectives(tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")

    # ephemeral coordinator port: a hardcoded one collides with
    # lingering workers from aborted runs or parallel test sessions
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def env_for(pid):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=root,
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
        )
        return env

    procs = []
    logs = []
    for pid in (0, 1):
        log = open(tmp_path / f"proc{pid}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env_for(pid),
            stdout=log, stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        for p in procs:
            assert p.wait(timeout=900) == 0, (
                (tmp_path / "proc0.log").read_text()
                + (tmp_path / "proc1.log").read_text()
            )
    finally:
        for log in logs:
            log.close()
        for p in procs:
            p.kill()

    lines = []
    for pid in (0, 1):
        text = (tmp_path / f"proc{pid}.log").read_text()
        ok = [ln for ln in text.splitlines() if "MULTIHOST_OK" in ln]
        assert ok, text
        lines.append(ok[0])
    # both controllers computed identical replicated reductions
    strip = [
        " ".join(t for t in ln.split() if not t.startswith("proc="))
        for ln in lines
    ]
    assert strip[0] == strip[1], lines


@pytest.mark.slow
def test_two_process_adaptation_matches_single_process(tmp_path):
    """The FULL driver under two controllers: `adapt_stacked_input`
    (niter=2, including one interface-displacement + migration round)
    runs with its sweep programs genuinely SPMD over the 8 devices of
    both processes, and the merged output must be BIT-IDENTICAL
    (sha256 over every entity array) to a single-process run of the
    same SPMD programs. The reference analog: its entire CI matrix runs
    the driver under `mpiexec -np {1,2,4,6,8}`
    (cmake/testing/pmmg_tests.cmake:30-38)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def base_env(ndev):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
            PYTHONPATH=root,
        )
        return env

    # single-process reference: same SPMD sweep programs, one controller
    ref_env = base_env(8)
    ref_env["PMMGTPU_SPMD_SWEEPS"] = "1"
    ref = subprocess.run(
        [sys.executable, worker, "--adapt"], env=ref_env, cwd=root,
        capture_output=True, text=True, timeout=1200,
    )
    assert ref.returncode == 0, ref.stdout + ref.stderr
    ref_line = [ln for ln in ref.stdout.splitlines()
                if ln.startswith("ADAPT_DIGEST")]
    assert ref_line, ref.stdout + ref.stderr

    procs = []
    logs = []
    for pid in (0, 1):
        env = base_env(4)
        env.update(
            PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
            PMMGTPU_NUM_PROCS="2",
            PMMGTPU_PROC_ID=str(pid),
        )
        log = open(tmp_path / f"adapt{pid}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, worker, "--adapt"], env=env,
            stdout=log, stderr=subprocess.STDOUT, cwd=root,
        ))
    try:
        for p in procs:
            assert p.wait(timeout=1200) == 0, (
                (tmp_path / "adapt0.log").read_text()
                + (tmp_path / "adapt1.log").read_text()
            )
    finally:
        for log in logs:
            log.close()
        for p in procs:
            p.kill()

    for pid in (0, 1):
        text = (tmp_path / f"adapt{pid}.log").read_text()
        ok = [ln for ln in text.splitlines()
              if ln.startswith("ADAPT_DIGEST")]
        assert ok, text
        assert ok[0] == ref_line[0], (
            f"proc {pid} diverged:\n  2-proc: {ok[0]}\n"
            f"  1-proc: {ref_line[0]}"
        )


@pytest.mark.slow
def test_multi_rank_chaos_matrix(tmp_path):
    """The hand-written 2-process kill/peer-lost/resume legs are
    subsumed by the generated rank-targeted chaos matrix
    (``tools/chaos_smoke.py --world 2``): seeded schedules aim
    kill / broadcast-sigterm / peer-lost / ckpt-store faults —
    including commit-window kills BETWEEN the two manifest barriers —
    at random ranks of a real coordinated world, assert every rank
    exits typed (86/87/88/89 family, zero hangs, zero untyped
    tracebacks), resume killed worlds bit-identically (elastic 2→1
    included on odd seeds), and require a complete per-rank
    post-mortem (JSONL timeline + metrics merge via
    ``tools/obs_report.py --chaos``) for every seed.

    The sharded-checkpoint layout/digest details stay covered
    non-generated by `tools/fault_smoke.py --multihost` (a check.sh
    stage); the reference analog is per-rank restart state +
    MPI_Barrier'd checkpoint I/O in the node-scale runs of RR-9307."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "chaos_smoke.py"),
         "--world", "2", "--seeds", "1", "--seed-base", "0"],
        env=env, capture_output=True, text=True, timeout=2400,
        cwd=root,
    )
    assert p.returncode == 0, (
        p.returncode, p.stdout[-3000:], p.stderr[-2000:],
    )
    assert "terminated typed" in p.stdout, p.stdout[-2000:]
