"""Level-set discretization tests (`-ls` mode — a capability the
reference's CLI accepts but gates off at `src/libparmmg.c:73-76`; here it
is actually provided)."""

import numpy as np
import pytest

import jax.numpy as jnp

from parmmg_tpu.core import adjacency
from parmmg_tpu.core.mesh import Mesh, tet_volumes
from parmmg_tpu.models.levelset import REF_IN, REF_ISO, REF_OUT, discretize_levelset
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube


def sphere_case(n=4, r=0.3):
    raw = unit_cube(n)
    ls = np.linalg.norm(raw["verts"] - 0.5, axis=1) - r
    m = Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"], ls=ls[:, None], dtype=jnp.float64,
    )
    return m


def test_levelset_split_conformal_and_volume_exact():
    out = discretize_levelset(sphere_case())
    out = adjacency.build_adjacency(out)
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    vol = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert vol.sum() == pytest.approx(1.0, rel=1e-6)
    assert vol.min() > 0


def test_levelset_refs_and_isosurface():
    out = discretize_levelset(sphere_case())
    d = out.to_numpy()
    vol = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    refs = d["trefs"]
    assert set(np.unique(refs)) == {REF_IN, REF_OUT}
    v_in = vol[refs == REF_IN].sum()
    true_v = 4 / 3 * np.pi * 0.3**3
    # coarse-mesh piecewise-linear approximation of the ball volume
    assert 0.4 * true_v < v_in < 1.3 * true_v
    # isosurface trias exist, sit between differently-signed regions
    iso = d["trrefs"] == REF_ISO
    assert iso.sum() > 50
    p = d["verts"][np.unique(d["trias"][iso])]
    rr = np.linalg.norm(p - 0.5, axis=1)
    assert rr.max() < 0.3 + 1e-9  # cut points never outside the ball
    # every vertex of an iso tria lies on the linear-interpolated zero set
    ls = d["ls"][:, 0]
    assert np.abs(ls[np.unique(d["trias"][iso])]).max() < 1e-12


def test_levelset_plane_exact():
    # plane z=0.5: inside volume must be exactly half the cube (n=4 has
    # a vertex layer exactly at z=0.5, so snapping reuses it)
    raw = unit_cube(4)
    ls = raw["verts"][:, 2] - 0.5
    m = Mesh.from_numpy(raw["verts"], raw["tets"], trias=raw["trias"],
                        trrefs=raw["trrefs"], ls=ls[:, None],
                        dtype=jnp.float64)
    out = discretize_levelset(m)
    out = adjacency.build_adjacency(out)
    assert conformity.check_mesh(out).ok
    # plane hits mesh vertices exactly: snapped, no new points
    assert int(out.npoin) == len(raw["verts"])
    vol = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    refs = out.to_numpy()["trefs"]
    assert vol[refs == REF_IN].sum() == pytest.approx(0.5, rel=1e-9)


def test_levelset_then_adapt():
    from parmmg_tpu.models.adapt import AdaptOptions, adapt

    out = discretize_levelset(sphere_case())
    adapted, _ = adapt(out, AdaptOptions(hsiz=0.2, niter=1, max_sweeps=4))
    assert conformity.check_mesh(adapted).ok
    d = adapted.to_numpy()
    # the isosurface survives adaptation as a REF-change interface
    assert (d["trrefs"] == REF_ISO).sum() > 20
