"""PR-17 shard-local dispatch + closed-loop load balancing tests.

Three contracts:

1. **Shard-local unfused bit-equivalence** — above `UNFUSED_TCAP` the
   sharded path runs `_sweep_body` only over the shards each process
   owns (`_remesh_phase_shardlocal`); the result must be DIGEST-IDENTICAL
   to the replicated vmapped engine it replaced (`_remesh_phase_local`),
   including the frontier carry and the per-sweep history records, and
   `_remesh_phase_global` must route the above-cap case to it (forced
   via a `UNFUSED_TCAP = 0` monkeypatch, the PARMMG_UNFUSED_TCAP=0
   override's effect).

2. **BalancePolicy unit matrix** — band trigger, hysteresis low-water
   re-arm, displace-then-recut escalation, min-interval throttle and
   the no-telemetry fallback, on synthetic history rows.

3. **Skewed-demand driver** — a deliberately imbalanced initial cut
   driven through `adapt_stacked_input` with the balancer on conserves
   live tets and ends with the imbalance back inside the band.
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import parmmg_tpu.models.adapt as adapt_mod
from parmmg_tpu.core import adjacency
from parmmg_tpu.models.adapt import AdaptOptions, prepare_metric
from parmmg_tpu.models.distributed import (
    DistOptions,
    _remesh_phase_local,
    _remesh_phase_shardlocal,
    adapt_stacked_input,
    merge_adapted,
    remesh_phase,
)
from parmmg_tpu.ops import analysis
from parmmg_tpu.parallel.distribute import (
    assign_global_ids, rebuild_comm, split_mesh,
)
from parmmg_tpu.parallel.migrate import (
    BalancePolicy, measured_shard_work, resolve_balance_band,
)
from parmmg_tpu.parallel.partition import sfc_partition
from parmmg_tpu.utils.gen import unit_cube_mesh


def _digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _stacked_fixture(nparts=2, n=3, hsiz=0.25):
    mesh = unit_cube_mesh(n)
    mesh = adjacency.build_adjacency(mesh)
    mesh = analysis.analyze(mesh)
    # max_sweeps=3: the digest comparison only needs BOTH engines to
    # run the same (unconverged) sweep schedule, and every unfused
    # sweep pays per-op compiles — tier-1 time is compile-dominated
    opts = AdaptOptions(hsiz=hsiz, hgrad=None, niter=1, max_sweeps=3,
                        verbose=0)
    mesh = prepare_metric(mesh, opts, int(mesh.tcap * 1.6) + 64)
    part = np.asarray(jax.device_get(sfc_partition(mesh, nparts)))
    st, _ = split_mesh(mesh, part, nparts)
    st = assign_global_ids(st)
    st = jax.vmap(adjacency.build_adjacency)(st)
    return st, opts


# ---------------------------------------------------------------------------
# 1. shard-local unfused dispatch: bit-equivalence to the replicated engine
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("frontier", [True, False],
                         ids=["frontier", "full-table"])
def test_shardlocal_bit_equivalent_to_replicated(frontier):
    """`_remesh_phase_shardlocal` (per-shard `_sweep_body`, per-shard
    frontier staleness) must produce the BIT-IDENTICAL mesh, frontier
    carry and sweep records as the replicated vmapped engine on the
    same stacked input — the digest assertion the fallback swap rests
    on."""
    st, opts = _stacked_fixture()
    opts.frontier = frontier

    def run(fn):
        hist = []
        s, fr = fn(st, opts, [1.6], hist, 0, 0.01, fr0=None)
        return s, fr, hist

    sa, fra, ha = run(_remesh_phase_shardlocal)
    sb, frb, hb = run(_remesh_phase_local)
    assert _digest(sa) == _digest(sb), "mesh digests diverge"
    if frontier:
        np.testing.assert_array_equal(np.asarray(fra), np.asarray(frb))
    else:
        assert fra is None and frb is None
    cols = ("sweep", "nsplit", "ncollapse", "nswap", "nmoved",
            "imbalance", "shard_ne")
    assert [{k: r.get(k) for k in cols} for r in ha] == \
           [{k: r.get(k) for k in cols} for r in hb]


@pytest.mark.slow
def test_global_dispatch_routes_above_tcap_to_shardlocal(monkeypatch):
    """With `UNFUSED_TCAP` forced to 0 (every mesh is "above cap") and
    SPMD dispatch selected, `remesh_phase` must route through the
    shard-local engine and still match the replicated result — the
    integration seam of the fallback replacement."""
    monkeypatch.setattr(adapt_mod, "UNFUSED_TCAP", 0)
    monkeypatch.setenv("PMMGTPU_SPMD_SWEEPS", "1")
    st, opts = _stacked_fixture(n=3)
    hist = []
    sa, fra = remesh_phase(st, opts, [1.6], hist, 0, 0.01, fr0=None)
    sb, frb = _remesh_phase_local(st, opts, [1.6], [], 0, 0.01,
                                  fr0=None)
    assert _digest(sa) == _digest(sb)
    np.testing.assert_array_equal(np.asarray(fra), np.asarray(frb))
    assert hist, "no sweep records"


# ---------------------------------------------------------------------------
# 2. BalancePolicy unit matrix
# ---------------------------------------------------------------------------


def _rows(it, work, active=None):
    d = len(work)
    return dict(iter=it, shard_ne=list(work),
                shard_active=list(active) if active is not None
                else [1.0] * d)


def test_policy_in_band_never_fires():
    p = BalancePolicy(1.5)
    for it in range(5):
        out = p.evaluate([_rows(it, [100, 100, 100, 100])], it)
        assert out["action"] is None
        assert out["reason"] == "in-band"
        assert out["imbalance"] == 1.0


def test_policy_no_telemetry():
    p = BalancePolicy(1.5)
    out = p.evaluate([], 0)
    assert out["action"] is None and out["reason"] == "no-telemetry"
    # failure records for the iteration do not count as telemetry
    out = p.evaluate([dict(iter=0, failure="boom", shard_ne=[1, 2])], 0)
    assert out["reason"] == "no-telemetry"


def test_policy_hysteresis_hold_between_low_water_and_band():
    p = BalancePolicy(2.0)  # low_water = 1.5
    out = p.evaluate([_rows(0, [170, 100, 100, 100])], 0)  # imb ~1.45
    assert out["reason"] == "in-band"
    out = p.evaluate([_rows(1, [180, 100, 100, 100])], 1)  # imb 1.5+
    assert out["action"] is None
    assert out["reason"] == "hysteresis-hold"


def test_policy_displace_then_recut_escalation():
    p = BalancePolicy(1.5, min_interval=2)
    skew = [400, 100, 100, 100]  # imb 2.29
    out0 = p.evaluate([_rows(0, skew)], 0)
    assert out0["action"] == "displace"
    # inside min_interval: throttled even though still out of band
    out1 = p.evaluate([_rows(1, skew)], 1)
    assert out1["action"] is None and out1["reason"] == "throttled"
    # past the interval and still above band: escalate to the re-cut
    out2 = p.evaluate([_rows(2, skew)], 2)
    assert out2["action"] == "recut"
    assert out2["reason"] == "band-exceeded-again"


def test_policy_low_water_rearm_resets_escalation():
    p = BalancePolicy(1.5, min_interval=1)
    skew = [400, 100, 100, 100]
    assert p.evaluate([_rows(0, skew)], 0)["action"] == "displace"
    # back in band: strikes reset
    assert p.evaluate([_rows(1, [100] * 4)], 1)["reason"] == "in-band"
    # next excursion starts over at displace, not recut
    assert p.evaluate([_rows(2, skew)], 2)["action"] == "displace"


def test_measured_work_weights_by_active_fraction():
    """The policy reads MEASURED work: a shard full of converged (zero
    active fraction) cells contributes nothing even if its element
    count dominates."""
    rows = [dict(iter=3, shard_ne=[1000, 100],
                 shard_active=[0.0, 0.5])]
    work = measured_shard_work(rows, 3)
    assert work == [0.0, 50.0]
    # all-zero active: element counts are the fallback signal
    rows = [dict(iter=3, shard_ne=[1000, 100],
                 shard_active=[0.0, 0.0])]
    assert measured_shard_work(rows, 3) == [1000.0, 100.0]
    # multiple sweeps of one iteration accumulate
    rows = [_rows(4, [10, 20]), _rows(4, [30, 40])]
    assert measured_shard_work(rows, 4) == [40.0, 60.0]
    assert measured_shard_work(rows, 5) is None


def test_resolve_balance_band_knobs(monkeypatch):
    monkeypatch.delenv("PMMGTPU_BALANCE_BAND", raising=False)
    assert resolve_balance_band(DistOptions()) == 1.5  # default on
    assert resolve_balance_band(DistOptions(balance_band=2.25)) == 2.25
    assert resolve_balance_band(DistOptions(balance_band=0.0)) is None
    monkeypatch.setenv("PMMGTPU_BALANCE_BAND", "1.8")
    assert resolve_balance_band(DistOptions()) == 1.8
    monkeypatch.setenv("PMMGTPU_BALANCE_BAND", "-1")
    assert resolve_balance_band(DistOptions()) is None


def test_balance_band_excluded_from_fingerprint():
    """A resume may widen or narrow the band without invalidating the
    checkpointed mesh — resource-layout knob discipline."""
    from parmmg_tpu.failsafe import _FINGERPRINT_EXCLUDE

    assert "balance_band" in _FINGERPRINT_EXCLUDE


# ---------------------------------------------------------------------------
# 3. skewed-demand driver: conservation + band re-entry
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_skewed_demand_rebalances_into_band():
    """A deliberately skewed initial cut (one shard owning most of the
    mesh) driven through the balancing loop must conserve live tets
    across migrations and end with the live-tet imbalance back inside
    the band."""
    band = 1.5
    nparts = 4
    mesh = unit_cube_mesh(3)
    chunks = np.asarray(jax.device_get(sfc_partition(mesh, 2 * nparts)))
    part = np.where(chunks < nparts + 1, 0, chunks - nparts)
    st, comm = split_mesh(mesh, part, nparts)
    ne0 = np.asarray(jax.device_get(st.tmask.sum(axis=1)))
    imb0 = float(ne0.max()) / max(float(ne0.mean()), 1.0)
    assert imb0 > band, f"fixture not skewed ({imb0:.3f})"

    opts = DistOptions(hsiz=0.32, niter=2, max_sweeps=3, nparts=nparts,
                       min_shard_elts=8, hgrad=None, polish_sweeps=0,
                       balance_band=band)
    out, comm2, info = adapt_stacked_input(st, comm, opts)

    ne = np.asarray(jax.device_get(out.tmask.sum(axis=1)))
    merged = merge_adapted(out, comm2)
    assert int(ne.sum()) == int(merged.ntet), "live tets not conserved"
    imb_final = float(ne.max()) / max(float(ne.mean()), 1.0)
    assert imb_final <= band, \
        f"final imbalance {imb_final:.3f} outside band {band}"
    assert int(info["status"]) == 0
