"""Cross-shard surface analysis: features split by a shard interface are
recovered by the gid-keyed normal exchange (`PMMG_setdhd` role,
reference `src/analys_pmmg.c:2001`)."""

import numpy as np
import pytest

import jax

from parmmg_tpu.core import tags
from parmmg_tpu.ops import analysis
from parmmg_tpu.parallel.distribute import split_mesh, unstack_mesh
from parmmg_tpu.parallel.partition import sfc_partition
from parmmg_tpu.utils.gen import unit_cube_mesh


def ridge_gid_pairs(shards):
    """Global (deduped) set of ridge segments over all shards."""
    out = set()
    for m in shards:
        ed = np.asarray(m.edtag)
        em = np.asarray(m.edmask)
        ev = np.asarray(m.edge)
        vg = np.asarray(m.vglob)
        sel = em & ((ed & tags.RIDGE) != 0)
        for a, b in ev[sel]:
            ga, gb = int(vg[a]), int(vg[b])
            assert ga >= 0 and gb >= 0
            out.add((min(ga, gb), max(ga, gb)))
    return out


def test_cross_shard_ridges_recovered():
    n = 4
    mesh = unit_cube_mesh(n)  # NOT pre-analyzed: distributed-input shape
    # partition along the diagonal plane y=z: the interface CONTAINS the
    # cube edges (y=0,z=0) and (y=1,z=1), so each of their segments has
    # its two adjacent boundary trias (faces y=0 and z=0, resp. y=1/z=1)
    # on DIFFERENT shards — exactly the case per-shard dihedral
    # detection cannot see
    tm = np.asarray(mesh.tmask)
    bary = np.asarray(mesh.vert)[np.asarray(mesh.tet)].mean(axis=1)
    part = np.where(bary[:, 1] > bary[:, 2], 1, 0)
    part[~tm] = -1
    stacked, comm = split_mesh(mesh, part, 2)
    shards = [analysis.analyze(m) for m in unstack_mesh(stacked)]

    before = ridge_gid_pairs(shards)
    total = 12 * n  # 12 cube edges x n segments
    # the partition must actually split some cube edges across shards,
    # otherwise this test exercises nothing
    assert len(before) < total

    shards = analysis.cross_shard_features(shards)
    after = ridge_gid_pairs(shards)
    assert len(after) == total
    # corner count: globally the 8 cube corners (deduped by gid)
    corners = set()
    for m in shards:
        vt = np.asarray(m.vtag)
        vm = np.asarray(m.vmask)
        vg = np.asarray(m.vglob)
        for i in np.nonzero(vm & ((vt & tags.CORNER) != 0))[0]:
            corners.add(int(vg[i]))
    assert len(corners) == 8


def test_cross_shard_noop_on_smooth_sphere():
    from parmmg_tpu.utils.gen import unit_ball_mesh

    mesh = unit_ball_mesh(6)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 4)))
    stacked, comm = split_mesh(mesh, part, 4)
    shards = [analysis.analyze(m) for m in unstack_mesh(stacked)]
    shards = analysis.cross_shard_features(shards)
    assert len(ridge_gid_pairs(shards)) == 0


def test_cross_shard_singul_no_spurious_corner():
    """`PMMG_singul` role (reference `src/analys_pmmg.c:1679`): a ridge
    line crossing the interface transversally looks like a line END
    (local degree 1) on each side; the global classification must NOT
    freeze the crossing vertex as a corner."""
    n = 4
    mesh = unit_cube_mesh(n)
    tm = np.asarray(mesh.tmask)
    bary = np.asarray(mesh.vert)[np.asarray(mesh.tet)].mean(axis=1)
    part = np.where(bary[:, 0] > 0.5, 1, 0)  # split plane x=0.5
    part[~tm] = -1
    stacked, comm = split_mesh(mesh, part, 2)
    shards = [analysis.analyze(m) for m in unstack_mesh(stacked)]
    shards = analysis.cross_shard_features(shards)

    # globally exactly the 8 cube corners — in particular NOT the points
    # where the 4 x-direction cube edges pierce the x=0.5 interface
    corners = {}
    for m in shards:
        vt = np.asarray(m.vtag)
        vm = np.asarray(m.vmask)
        vg = np.asarray(m.vglob)
        v = np.asarray(m.vert)
        for i in np.nonzero(vm & ((vt & tags.CORNER) != 0))[0]:
            corners[int(vg[i])] = v[i]
    pos = np.array(list(corners.values()))
    assert len(corners) == 8, pos
    # every corner is a true cube corner (all coords in {0,1})
    assert np.all(np.isin(np.round(pos, 6), [0.0, 1.0]))
