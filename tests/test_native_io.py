"""Native tokenizer: build + parity with the pure-Python fallback."""

import re

import pytest

from parmmg_tpu.io import native_io

CUBE = "/root/reference/libexamples/adaptation_example0/cube.mesh"


def test_native_tokenizer_parity():
    if not native_io.available():
        pytest.skip("native tokenizer not built (no g++?)")
    with open(CUBE) as f:
        text = f.read()
    py = re.compile(r"#.*").sub(" ", text).split()
    assert native_io.tokenize(CUBE) == py
