"""Native tokenizer: build + parity with the pure-Python fallback."""

import re

import pytest

from parmmg_tpu.io import native_io

CUBE = "/root/reference/libexamples/adaptation_example0/cube.mesh"


def test_native_tokenizer_parity():
    if not native_io.available():
        pytest.skip("native tokenizer not built (no g++?)")
    with open(CUBE) as f:
        text = f.read()
    py = re.compile(r"#.*").sub(" ", text).split()
    assert native_io.tokenize(CUBE) == py


def test_capi_adapt_file(tmp_path):
    """The C-ABI shim target: `api.adapt_file` runs load -> adapt -> save
    and returns the graded status (the Fortran-surface role of
    `API_functionsf_pmmg.c`; `native/parmmg_capi.c` calls exactly this)."""
    import os

    from parmmg_tpu import api
    from parmmg_tpu.io import medit
    from parmmg_tpu.utils import conformity

    ref = "/root/reference/libexamples/adaptation_example0/cube.mesh"
    if not os.path.exists(ref):
        import pytest

        pytest.skip("reference fixture not available")
    out = str(tmp_path / "capi.mesh")
    rc = api.adapt_file(ref, "", out, 0.25, 1, 1)
    assert rc == 0
    m = medit.load_mesh(out)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)
