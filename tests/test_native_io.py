"""Native tokenizer: build + parity with the pure-Python fallback."""

import re

import pytest

from parmmg_tpu.io import native_io

def test_native_tokenizer_parity(cube_mesh_path):
    # conftest fixture: the reference cube when /root/reference is
    # mounted, else the synthesized equivalent — the hardcoded
    # reference path made this fail (not skip) on hermetic machines
    # once the native tokenizer auto-built
    if not native_io.available():
        pytest.skip("native tokenizer not built (no g++?)")
    with open(cube_mesh_path) as f:
        text = f.read()
    py = re.compile(r"#.*").sub(" ", text).split()
    assert native_io.tokenize(cube_mesh_path) == py


def test_capi_adapt_file(tmp_path):
    """The C-ABI shim target: `api.adapt_file` runs load -> adapt -> save
    and returns the graded status (the Fortran-surface role of
    `API_functionsf_pmmg.c`; `native/parmmg_capi.c` calls exactly this)."""
    import os

    from parmmg_tpu import api
    from parmmg_tpu.io import medit
    from parmmg_tpu.utils import conformity

    ref = "/root/reference/libexamples/adaptation_example0/cube.mesh"
    if not os.path.exists(ref):
        import pytest

        pytest.skip("reference fixture not available")
    out = str(tmp_path / "capi.mesh")
    rc = api.adapt_file(ref, "", out, 0.25, 1, 1)
    assert rc == 0
    m = medit.load_mesh(out)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)


def test_capi_staged_arrays_roundtrip():
    """Drive the staged-arrays C ABI end-to-end through ctypes: stage a
    cube from raw buffers (1-based connectivity like the reference API),
    adapt, and read the result back — the foreign-caller workflow of
    `PMMG_Init_parMesh` + `PMMG_Set_*` + `PMMG_parmmglib_centralized` +
    `PMMG_Get_*` (reference `src/API_functions_pmmg.c`)."""
    import ctypes
    import os

    import numpy as np

    from parmmg_tpu.api import Param
    from parmmg_tpu.utils.gen import unit_cube

    so = os.path.join(os.path.dirname(__file__), "..", "native",
                      "libparmmg_capi.so")
    if not os.path.exists(so):
        pytest.skip("libparmmg_capi.so not built")
    lib = ctypes.CDLL(so)
    C = ctypes
    dp, ip = C.POINTER(C.c_double), C.POINTER(C.c_int)
    lib.pmmgtpu_init.restype = C.c_void_p
    lib.pmmgtpu_init.argtypes = [C.c_int]
    lib.pmmgtpu_free.argtypes = [C.c_void_p]
    lib.pmmgtpu_set_vertices.argtypes = [C.c_void_p, dp, ip, C.c_int]
    lib.pmmgtpu_set_tetrahedra.argtypes = [C.c_void_p, ip, ip, C.c_int]
    lib.pmmgtpu_set_triangles.argtypes = [C.c_void_p, ip, ip, C.c_int]
    lib.pmmgtpu_set_metric.argtypes = [C.c_void_p, dp, C.c_int, C.c_int]
    lib.pmmgtpu_set_iparameter.argtypes = [C.c_void_p, C.c_int, C.c_int]
    lib.pmmgtpu_set_dparameter.argtypes = [C.c_void_p, C.c_int, C.c_double]
    lib.pmmgtpu_run.argtypes = [C.c_void_p]
    lib.pmmgtpu_get_meshsize.argtypes = [C.c_void_p, ip, ip, ip]
    lib.pmmgtpu_get_vertices.argtypes = [C.c_void_p, dp, ip, C.c_int]
    lib.pmmgtpu_get_tetrahedra.argtypes = [C.c_void_p, ip, ip, C.c_int]
    lib.pmmgtpu_get_metric.argtypes = [C.c_void_p, dp, C.c_int, C.c_int]
    h = lib.pmmgtpu_init(1)
    assert h

    raw = unit_cube(3)
    verts = np.ascontiguousarray(raw["verts"], np.float64)
    tets = np.ascontiguousarray(raw["tets"] + 1, np.int32)
    trias = np.ascontiguousarray(raw["trias"] + 1, np.int32)
    trrefs = np.ascontiguousarray(raw["trrefs"], np.int32)

    def ptr(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    H = ctypes.c_void_p(h)
    assert lib.pmmgtpu_set_vertices(
        H, ptr(verts, ctypes.c_double), None, len(verts)) == 0
    assert lib.pmmgtpu_set_tetrahedra(
        H, ptr(tets, ctypes.c_int), None, len(tets)) == 0
    assert lib.pmmgtpu_set_triangles(
        H, ptr(trias, ctypes.c_int), ptr(trrefs, ctypes.c_int),
        len(trias)) == 0
    met = np.full((len(verts), 1), 0.25, np.float64)
    assert lib.pmmgtpu_set_metric(
        H, ptr(met, ctypes.c_double), len(verts), 1) == 0
    assert lib.pmmgtpu_set_iparameter(
        H, int(Param.IPARAM_niter), 1) == 0
    assert lib.pmmgtpu_set_dparameter(
        H, int(Param.DPARAM_hsiz), 0.25) == 0

    assert lib.pmmgtpu_run(H) == 0

    np_o, ne_o, nt_o = ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
    assert lib.pmmgtpu_get_meshsize(
        H, ctypes.byref(np_o), ctypes.byref(ne_o), ctypes.byref(nt_o)) == 0
    assert ne_o.value > len(tets), "adaptation did not refine"

    vout = np.empty((np_o.value, 3), np.float64)
    vref = np.empty(np_o.value, np.int32)
    tout = np.empty((ne_o.value, 4), np.int32)
    tref = np.empty(ne_o.value, np.int32)
    mout = np.empty((np_o.value, 1), np.float64)
    assert lib.pmmgtpu_get_vertices(
        H, ptr(vout, ctypes.c_double), ptr(vref, ctypes.c_int),
        np_o.value) == 0
    assert lib.pmmgtpu_get_tetrahedra(
        H, ptr(tout, ctypes.c_int), ptr(tref, ctypes.c_int),
        ne_o.value) == 0
    assert lib.pmmgtpu_get_metric(
        H, ptr(mout, ctypes.c_double), np_o.value, 1) == 0
    # 1-based connectivity referencing the returned vertex block
    assert tout.min() >= 1 and tout.max() <= np_o.value
    assert np.isfinite(vout).all() and (mout > 0).all()
    assert lib.pmmgtpu_free(H) == 0
