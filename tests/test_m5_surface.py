"""Boundary adaptation tests: the surface can be coarsened, refined and
smoothed while staying within the Hausdorff bound — the capability of
Mmg's boundary operators (`MMG5_colver` bdy path / `movbdyregpt` /
`MMG5_BezierTgt` midpoints) that the reference forwards with `-hausd`,
plus the `-nosurf` freeze mode."""

import numpy as np
import pytest

from parmmg_tpu.core import tags
from parmmg_tpu.models.adapt import AdaptOptions, adapt
from parmmg_tpu.ops import quality
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_ball_mesh

HAUSD = 0.05


def surface_radii(m):
    vm = np.asarray(m.vmask)
    vt = np.asarray(m.vtag)
    bdy = ((vt & tags.BDY) != 0) & vm
    return np.linalg.norm(np.asarray(m.vert)[bdy], axis=1)


def test_ball_coarsen_boundary():
    """Coarsening a sphere must remove surface vertices (768 input
    boundary trias cannot satisfy h=0.45) while keeping every surviving
    surface vertex within hausd of the unit sphere."""
    m = unit_ball_mesh(8)
    ntria_in = int(m.ntria)
    out, _ = adapt(m, AdaptOptions(hsiz=0.45, niter=1, max_sweeps=8,
                                   hausd=HAUSD))
    assert int(out.ntet) < 3072 * 0.75
    assert int(out.ntria) < ntria_in * 0.8  # the boundary itself coarsened
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    r = surface_radii(out)
    assert r.min() > 1.0 - HAUSD and r.max() < 1.0 + HAUSD


def test_ball_refine_keeps_curvature():
    """Refining a sphere splits boundary edges with curvature-corrected
    midpoints: new surface points stay near radius 1, not on the chords
    (plain midpoints would sag to ~0.976 at this size)."""
    m = unit_ball_mesh(6)
    out, _ = adapt(m, AdaptOptions(hsiz=0.22, niter=1, max_sweeps=6,
                                   hausd=HAUSD))
    assert int(out.ntet) > 1296 * 2
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    r = surface_radii(out)
    assert r.min() > 0.985
    h = quality.quality_histogram(out)
    assert float(h.qmin) > 0.01


@pytest.mark.parametrize("hsiz", [0.45, 0.2])
def test_nosurf_freezes_boundary(hsiz):
    """-nosurf: the boundary surface must be exactly preserved, under
    both coarsening and refinement."""
    from parmmg_tpu.ops import analysis

    # analyze a fresh copy for the before-snapshot (analysis kernels
    # donate their input buffers)
    bdy_in = np.sort(
        np.round(surface_radii(analysis.analyze(unit_ball_mesh(6))), 12)
    )
    assert len(bdy_in) > 0
    m = unit_ball_mesh(6)
    tri_in = int(m.ntria)
    out, _ = adapt(
        m, AdaptOptions(hsiz=hsiz, niter=1, max_sweeps=6, nosurf=True)
    )
    assert int(out.ntria) == tri_in  # no boundary tria created/destroyed
    bdy_out = np.sort(np.round(surface_radii(out), 12))
    assert len(bdy_out) == len(bdy_in)
    np.testing.assert_allclose(np.asarray(bdy_out), np.asarray(bdy_in))
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)


def test_cube_ridges_preserved_under_coarsening():
    """Coarsening the cube must keep its 12 edges straight and its 8
    corners in place (ridge/corner discipline of tag_pmmg.c)."""
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(6)  # 1296 tets, h~0.17
    out, _ = adapt(m, AdaptOptions(hsiz=0.4, niter=1, max_sweeps=8,
                                   hausd=HAUSD))
    assert int(out.ntet) < 1296 * 0.6
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    vm = np.asarray(out.vmask)
    vt = np.asarray(out.vtag)
    p = np.asarray(out.vert)
    # corners still present and exactly at the cube corners
    corner = ((vt & tags.CORNER) != 0) & vm
    assert corner.sum() == 8
    cp = p[corner]
    assert np.allclose(np.sort(cp, axis=0)[:4], 0.0, atol=1e-12)
    assert np.allclose(np.sort(cp, axis=0)[4:], 1.0, atol=1e-12)
    # ridge vertices still lie exactly on cube edges (two coords at {0,1})
    ridge = ((vt & tags.RIDGE) != 0) & vm & ~corner
    rp = p[ridge]
    on_ext = (np.abs(rp) < 1e-9) | (np.abs(rp - 1.0) < 1e-9)
    assert (on_ext.sum(axis=1) >= 2).all()
    # boundary vertices still on the unit-cube surface
    bdy = ((vt & tags.BDY) != 0) & vm
    bp = p[bdy]
    face = (np.abs(bp) < 1e-9) | (np.abs(bp - 1.0) < 1e-9)
    assert (face.any(axis=1)).all()
    # total volume preserved (flat faces: surface ops are in-plane)
    from parmmg_tpu.core.mesh import tet_volumes

    vol = np.asarray(tet_volumes(out), np.float64)[
        np.asarray(out.tmask)
    ].sum()
    # f32 mesh: per-tet volumes carry f32 rounding; the sum is exact to
    # ~n*eps_f32, not 1e-9
    assert vol == pytest.approx(1.0, rel=1e-6)


def test_opnbdy_preserves_internal_sheet():
    """-opnbdy: an internal same-ref tria sheet (a baffle with an open
    rim inside the volume) survives adaptation as real surface — the
    reference's opnbdy_peninsula/island CI class
    (cmake/testing/pmmg_tests.cmake:152-165; tag special case
    src/tag_pmmg.c:267). The sheet keeps its area, the rim stays a
    feature line, and the mesh remains conformal."""
    from parmmg_tpu.core.mesh import FACE_VERTS, Mesh
    from parmmg_tpu.utils import gen

    n = 4
    raw = gen.unit_cube(n)
    verts, tets = raw["verts"], raw["tets"]
    fv = tets[:, FACE_VERTS].reshape(-1, 3)
    c = verts[fv]                                     # [F,3,3]
    onplane = np.all(np.abs(c[:, :, 2] - 0.5) < 1e-9, axis=1)
    half = c[:, :, 0].max(axis=1) <= 0.5 + 1e-9       # peninsula: x<=1/2
    sheet = np.unique(np.sort(fv[onplane & half], axis=1), axis=0)
    assert len(sheet) == 2 * (n // 2) * n             # sanity: 2 tria/cell
    trias = np.concatenate([raw["trias"], sheet])
    trrefs = np.concatenate(
        [raw["trrefs"], np.full(len(sheet), 9, np.int32)]
    )
    mesh = Mesh.from_numpy(verts, tets, trias=trias, trrefs=trrefs,
                           headroom=3.0)

    out, _ = adapt(mesh, AdaptOptions(
        hsiz=0.15, niter=1, opnbdy=True, hgrad=None, max_sweeps=8,
    ))

    trmask = np.asarray(out.trmask)
    opn = trmask & ((np.asarray(out.trtag) & tags.OPNBDY) != 0)
    assert opn.any(), "sheet trias vanished"
    tri = np.asarray(out.tria)[opn]
    v = np.asarray(out.vert)
    ar = 0.5 * np.linalg.norm(np.cross(
        v[tri[:, 1]] - v[tri[:, 0]], v[tri[:, 2]] - v[tri[:, 0]]
    ), axis=1)
    assert abs(ar.sum() - 0.5) < 0.05, f"sheet area drifted: {ar.sum()}"
    # the sheet stayed flat (z == 0.5 within hausd) and inside its half
    sverts = np.unique(tri)
    assert np.abs(v[sverts, 2] - 0.5).max() < 0.02
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)


def test_opnbdy_mixed_winding_no_fake_ridges():
    """Medit does not guarantee orientation for internal trias: a sheet
    with alternating winding must not read as wall-to-wall fake ridges
    (the dihedral test between two OPNBDY trias is winding-independent).
    """
    from parmmg_tpu.core.mesh import FACE_VERTS, Mesh
    from parmmg_tpu.ops import analysis
    from parmmg_tpu.utils import gen

    n = 4
    raw = gen.unit_cube(n)
    verts, tets = raw["verts"], raw["tets"]
    fv = tets[:, FACE_VERTS].reshape(-1, 3)
    c = verts[fv]
    onplane = np.all(np.abs(c[:, :, 2] - 0.5) < 1e-9, axis=1)
    half = c[:, :, 0].max(axis=1) <= 0.5 + 1e-9
    sheet = np.unique(np.sort(fv[onplane & half], axis=1), axis=0)
    # scramble winding: flip every other tria
    sheet[::2] = sheet[::2, ::-1]
    trias = np.concatenate([raw["trias"], sheet])
    trrefs = np.concatenate(
        [raw["trrefs"], np.full(len(sheet), 9, np.int32)]
    )
    mesh = Mesh.from_numpy(verts, tets, trias=trias, trrefs=trrefs)
    mesh = analysis.analyze(mesh, opnbdy=True)

    # no RIDGE feature edge strictly interior to the flat sheet
    ed = np.asarray(mesh.edge)
    live = np.asarray(mesh.edmask) & (
        (np.asarray(mesh.edtag) & tags.RIDGE) != 0
    )
    v = np.asarray(mesh.vert)
    eps = 1e-6
    interior = (
        (np.abs(v[:, 2] - 0.5) < eps)
        & (v[:, 0] > eps) & (v[:, 0] < 0.5 - eps)
        & (v[:, 1] > eps) & (v[:, 1] < 1 - eps)
    )
    bad = live & interior[ed[:, 0]] & interior[ed[:, 1]]
    assert not bad.any(), (
        f"{int(bad.sum())} fake ridges inside a flat mixed-winding sheet"
    )
