"""M1 tests: batched remeshing operators + single-shard adaptation.

Mirrors the reference's CI approach (cube adaptation at fixed sizes,
pass = conformity + quality, SURVEY.md §4) with golden-invariant checks:
exact volume conservation, conforming topology, metric convergence."""

import numpy as np
import pytest

import jax.numpy as jnp

from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.core.mesh import compact, tet_volumes
from parmmg_tpu.io import medit
from parmmg_tpu.models import adapt
from parmmg_tpu.ops import analysis, collapse, quality, smooth, split, swap
from parmmg_tpu.utils import conformity

ECAP = 40000


def load_cube(path, hsiz=None, features=True):
    m = medit.load_mesh(path, dtype=jnp.float64)
    m = m.with_capacity(4000, 16000, 4000, 64)
    m = analysis.analyze(m, features=features)
    if hsiz is not None:
        m = m.replace(met=jnp.full((m.pcap, 1), hsiz, m.dtype))
    return m


def total_volume(m):
    return float(np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum())


def edges_of(m):
    return adjacency.unique_edges(m, ECAP)


def test_boundary_marking(cube_mesh_path):
    m = load_cube(cube_mesh_path)
    vt = np.asarray(m.vtag)[np.asarray(m.vmask)]
    # every cube vertex lies on the surface
    assert ((vt & tags.BDY) != 0).all()


def test_split_conserves_volume(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=0.2)
    for _ in range(4):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, st = split.split_long_edges(m, e, em, t2e)
    assert int(m.ntet) > 40
    assert total_volume(m) == pytest.approx(1.0, abs=1e-12)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)


def test_split_respects_required(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=0.2)
    # freeze everything via REQUIRED on all vertices? splits are edge-based;
    # use PARBDY on all vertices to freeze all edges
    m = m.replace(vtag=jnp.where(m.vmask, m.vtag | tags.PARBDY, m.vtag))
    e, em, t2e, _ = edges_of(m)
    m2, st = split.split_long_edges(m, e, em, t2e)
    assert int(st.nsplit) == 0


def test_collapse_conserves(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=0.2)
    for _ in range(5):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, _ = split.split_long_edges(m, e, em, t2e)
    ne_fine = int(m.ntet)
    # now coarsen: larger target size makes edges short
    m = m.replace(met=jnp.full((m.pcap, 1), 0.45, m.dtype))
    removed = 0
    for _ in range(5):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, st = collapse.collapse_short_edges(m, e, em, t2e)
        removed += int(st.ncollapse)
    assert removed > 0
    assert int(m.ntet) < ne_fine
    assert total_volume(m) == pytest.approx(1.0, abs=1e-12)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)


def test_collapse_never_touches_boundary(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=5.0)  # everything "short"
    nb0 = int(((np.asarray(m.vtag) & tags.BDY) != 0)[np.asarray(m.vmask)].sum())
    for _ in range(3):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, _ = collapse.collapse_short_edges(m, e, em, t2e)
    vm = np.asarray(m.vmask)
    nb1 = int(((np.asarray(m.vtag) & tags.BDY) != 0)[vm].sum())
    assert nb1 == nb0  # interior-only collapses
    assert total_volume(m) == pytest.approx(1.0, abs=1e-12)


def test_smooth_keeps_volume_and_validity(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=0.25)
    for _ in range(4):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, _ = split.split_long_edges(m, e, em, t2e)
    v0 = total_volume(m)
    for _ in range(3):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, st = smooth.smooth_vertices(m, e, em)
    # interior-only smoothing preserves the domain exactly
    assert total_volume(m) == pytest.approx(v0, rel=1e-12)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)


def test_swap_sweeps_safe(cube_mesh_path):
    m = load_cube(cube_mesh_path, hsiz=0.25)
    for _ in range(5):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, _ = split.split_long_edges(m, e, em, t2e)
    v0 = total_volume(m)
    for _ in range(2):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, _ = swap.swap_32(m, e, em, t2e)
        m = adjacency.build_adjacency(compact(m))
        e, em, t2e, _ = edges_of(m)
        m, _ = swap.swap_23(m, e, em)
    assert total_volume(m) == pytest.approx(v0, rel=1e-12)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)


def test_adapt_uniform(cube_mesh_path):
    m = medit.load_mesh(cube_mesh_path, dtype=jnp.float64)
    opts = adapt.AdaptOptions(niter=2, max_sweeps=10, hsiz=0.22, hgrad=None)
    m2, info = adapt.adapt(m, opts)
    rep = conformity.check_mesh(m2)
    assert rep.ok, str(rep)
    assert total_volume(m2) == pytest.approx(1.0, abs=1e-12)
    assert int(m2.ntet) > 150  # refined well beyond the 12 input tets
    assert float(info["qual_out"].qmin) > 0.15
    # metric convergence: most edges near unit length
    e, em, t2e, _ = adjacency.unique_edges(m2, int(m2.tcap * 1.6) + 64)
    ls = quality.length_stats(m2, e, em)
    assert float(ls.n_unit) / float(ls.nedge) > 0.6
    assert float(ls.lmax) < 3.0


def test_adapt_with_metric_file(cube_mesh_path, cube_met_path):
    # reference example: cube with constant 0.5 metric prescribed in sol
    m = medit.load_mesh(cube_mesh_path, cube_met_path, dtype=jnp.float64)
    opts = adapt.AdaptOptions(niter=1, max_sweeps=8, hgrad=None)
    m2, info = adapt.adapt(m, opts)
    rep = conformity.check_mesh(m2)
    assert rep.ok, str(rep)
    assert total_volume(m2) == pytest.approx(1.0, abs=1e-12)
    assert int(m2.ntet) >= 12


def test_adapt_noinsert_nomove(cube_mesh_path):
    m = medit.load_mesh(cube_mesh_path, dtype=jnp.float64)
    opts = adapt.AdaptOptions(
        niter=1, max_sweeps=3, hsiz=0.1, hgrad=None,
        noinsert=True, nomove=True, noswap=True,
    )
    m2, info = adapt.adapt(m, opts)
    # no insertion, no move, no swap, nothing to collapse: mesh unchanged
    assert int(m2.ntet) == 12
    assert int(m2.npoin) == 12


def test_split_feature_edge_reversed_rows(cube_mesh_path):
    """Feature edges stored as (hi, lo) must split into both halves
    (regression: the append used the canonical hi endpoint instead of the
    stored row's own second vertex). Feature detection is off so the
    planted edge is the only feature edge."""
    m = load_cube(cube_mesh_path, hsiz=0.2, features=False)
    # pick a real tet edge and store it hi-before-lo as a feature edge
    e, em, t2e, _ = edges_of(m)
    eid = int(np.nonzero(np.asarray(em))[0][0])
    a, b = (int(v) for v in np.asarray(e)[eid])
    ed = np.asarray(m.edge).copy()
    edm = np.asarray(m.edmask).copy()
    edt = np.asarray(m.edtag).copy()
    ed[0] = (b, a)  # reversed storage order
    edm[0] = True
    edt[0] = tags.RIDGE
    m = m.replace(
        edge=jnp.asarray(ed), edmask=jnp.asarray(edm), edtag=jnp.asarray(edt)
    )
    # the feature edge must win its arena eventually (longer diagonals
    # split first) — 15 sweeps is plenty for the cube at hsiz=0.2
    for _ in range(15):
        m = compact(m)
        e, em, t2e, _ = edges_of(m)
        m, st = split.split_long_edges(m, e, em, t2e)
        if int(m.nedge) > 1:
            break
    ed2 = np.asarray(m.edge)[np.asarray(m.edmask)]
    assert len(ed2) >= 2
    # the halves must still cover both original endpoints and chain
    # through shared midpoints (connectivity of the feature line)
    ends = ed2.reshape(-1).tolist()
    assert a in ends and b in ends
    from collections import Counter

    deg = Counter(ends)
    odd = [v for v, d in deg.items() if d % 2 == 1]
    assert sorted(odd) == sorted([a, b])  # a simple path from a to b


def test_split_respects_required_triangles(cube_mesh_path):
    """Edges of REQUIRED triangles are frozen even without a required
    feature edge covering them (RequiredTriangles discipline)."""
    m = load_cube(cube_mesh_path, hsiz=0.2)
    m = m.replace(
        trtag=jnp.where(m.trmask, m.trtag | tags.REQUIRED, m.trtag)
    )
    tria0 = np.asarray(m.tria)[np.asarray(m.trmask)]
    e, em, t2e, _ = edges_of(m)
    m2, st = split.split_long_edges(m, e, em, t2e)
    # interior edges may split, but every original boundary tria survives
    tria2 = np.asarray(m2.tria)[np.asarray(m2.trmask)]
    s0 = {tuple(sorted(t)) for t in tria0.tolist()}
    s2 = {tuple(sorted(t)) for t in tria2.tolist()}
    assert s0 == s2


def test_unfused_sweep_path_matches(monkeypatch):
    """Above UNFUSED_TCAP the sweep runs per-op instead of as one fused
    program (whole-program XLA scheduling costs hours at large shapes on
    TPU while per-op compiles cost seconds). Both dispatch paths run the
    identical per-sweep math, so the final mesh and the per-sweep stats
    must agree exactly."""
    import parmmg_tpu.models.adapt as A
    from parmmg_tpu.utils.gen import unit_cube_mesh

    opts = A.AdaptOptions(hsiz=0.18, niter=1, max_sweeps=6, hgrad=None)
    fused_out, fused_info = A.adapt(unit_cube_mesh(4), opts)

    monkeypatch.setattr(A, "UNFUSED_TCAP", 64)
    out, info = A.adapt(unit_cube_mesh(4), opts)
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    assert int(out.ntet) > 500
    h = quality.quality_histogram(out)
    assert float(h.qavg) > 0.7
    assert len(info["history"]) >= 2  # one record per sweep

    # path equivalence: same sweep count, same per-sweep stats, same
    # final entity counts
    keys = ("nsplit", "ncollapse", "nswap", "ne", "np")
    f_hist = [tuple(r[k] for k in keys) for r in fused_info["history"]]
    u_hist = [tuple(r[k] for k in keys) for r in info["history"]]
    assert f_hist == u_hist
    assert int(out.ntet) == int(fused_out.ntet)
    assert int(out.npoin) == int(fused_out.npoin)
