"""Round-6/round-8 active-set (frontier) sweep tests.

Equivalence discipline: frontier sweeps gate candidate generation on the
one-ring closure of the previous sweep's changes and rebuild analysis
tables incrementally — the RESULT must match full-table sweeps on the
seeded cube workload (same element count, quality histogram and
conformity within fp jitter), on both the fused and unfused dispatch
paths AND on the distributed drivers (round 8: per-shard frontier
through the vmapped/SPMD sweeps, remapped through migration). The
incremental rebuilds (`update_adjacency`, `merge_unique_edges`) must be
bit-exact against their full counterparts, including their overflow
fallbacks — `merge_unique_edges` across ARBITRARY randomized
split/collapse/swap delta schedules, not just append-only ones.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import parmmg_tpu.models.adapt as adapt_mod
from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.core.mesh import compact
from parmmg_tpu.models.adapt import (
    AdaptOptions, Frontier, adapt, default_mem_budget_mb, remesh_sweep,
)
from parmmg_tpu.ops import quality, swap
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube_mesh


def _copy(m):
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, m
    )


def _run(frontier, unfused, monkeypatch):
    # n=5 cube: adaptive enough to exercise every operator phase while
    # keeping tier-1 time down (compile count, not rows, dominates)
    if unfused:
        monkeypatch.setattr(adapt_mod, "UNFUSED_TCAP", 0)
    mesh = unit_cube_mesh(5)
    opts = AdaptOptions(hsiz=0.12, niter=1, max_sweeps=10, hgrad=None,
                        frontier=frontier)
    out, info = adapt(mesh, opts)
    h = quality.quality_histogram(out)
    return out, info, h


@pytest.mark.parametrize("unfused", [False, True],
                         ids=["fused", "unfused"])
def test_frontier_full_table_equivalence(monkeypatch, unfused):
    """Active-set sweeps must reproduce the full-table result on the
    seeded cube workload: same invariants (ne, qmin/qavg within fp
    tolerance, conformity histogram) on both dispatch paths."""
    out_f, info_f, h_f = _run(True, unfused, monkeypatch)
    out_t, info_t, h_t = _run(False, unfused, monkeypatch)
    ne_f, ne_t = int(out_f.ntet), int(out_t.ntet)
    assert abs(ne_f - ne_t) <= max(0.02 * ne_t, 16), (ne_f, ne_t)
    assert float(h_f.qmin) == pytest.approx(float(h_t.qmin), abs=0.05)
    assert float(h_f.qavg) == pytest.approx(float(h_t.qavg), abs=0.02)
    # conformity histogram: both conformal, same 5-bin quality shape
    assert conformity.check_mesh(out_f).ok
    assert conformity.check_mesh(out_t).ok
    cf = np.asarray(h_f.counts, np.float64) / max(ne_f, 1)
    ct = np.asarray(h_t.counts, np.float64) / max(ne_t, 1)
    assert np.abs(cf - ct).max() < 0.05, (cf, ct)
    # the frontier run reports a (weakly) shrinking active fraction
    saf = [r["n_active"] / max(r["n_unique"], 1)
           for r in info_f["history"]]
    assert saf, "history missing n_active"
    assert all(0.0 <= x <= 1.0 for x in saf)


def test_noop_frontier_sweep_is_identity():
    """A sweep offered an EMPTY frontier over clean tables must do
    nothing: no ops, mesh arrays bit-identical — the converged
    verification-sweep fast path."""
    mesh = unit_cube_mesh(4)
    out, _ = adapt(mesh, AdaptOptions(hsiz=0.2, niter=1, max_sweeps=8,
                                      hgrad=None))
    out = compact(out)
    ecap = int(out.tcap * 1.6) + 64
    edges, emask, t2e, nu = adjacency.unique_edges(out, ecap)
    out = adjacency.build_adjacency(out)
    fr = Frontier(
        changed=jnp.zeros(out.pcap, bool),
        dirty=jnp.int32(0),
        tables=(edges, emask, t2e, jnp.asarray(nu, jnp.int32)),
        adja_ok=jnp.bool_(True),
    )
    ref = _copy(out)
    out2, st, fr2 = remesh_sweep(out, ecap, phase_skip=False, frontier=fr)
    assert int(st.nsplit) == 0 and int(st.ncollapse) == 0
    assert int(st.nswap) == 0 and int(st.nmoved) == 0
    assert int(st.n_active) == 0
    np.testing.assert_array_equal(np.asarray(out2.vert),
                                  np.asarray(ref.vert))
    np.testing.assert_array_equal(np.asarray(out2.tet),
                                  np.asarray(ref.tet))
    np.testing.assert_array_equal(np.asarray(out2.tmask),
                                  np.asarray(ref.tmask))
    # successor frontier stays drained and clean
    assert int(jnp.sum(fr2.changed.astype(jnp.int32))) == 0
    assert int(fr2.dirty) == 0


def _jittered_cube(n=5, seed=0, amp=0.35):
    """Structured cube with deterministically jittered interior vertices
    — quality incentives make 2-3 swaps fire (the pristine cube has
    none)."""
    mesh = compact(unit_cube_mesh(n))
    v = np.asarray(mesh.vert).copy()
    vm = np.asarray(mesh.vmask)
    vt = np.asarray(mesh.vtag)
    interior = vm & ((vt & tags.BDY) == 0)
    rng = np.random.default_rng(seed)
    v[interior] += rng.uniform(-amp, amp, v[interior].shape) / n
    return mesh.replace(vert=jnp.asarray(v, mesh.vert.dtype))


def test_update_adjacency_exact():
    """Incremental face rematch == full rebuild after a real 2-3 swap
    pass, including the K-overflow fallback, and is a no-op on an empty
    frontier."""
    mesh = _jittered_cube()
    m0 = adjacency.build_adjacency(mesh)
    ref0 = np.asarray(m0.adja).copy()
    K = m0.tcap * 4
    m_all = adjacency.update_adjacency(
        _copy(m0), jnp.ones(m0.pcap, bool), K=K
    )
    np.testing.assert_array_equal(ref0, np.asarray(m_all.adja))
    m_none = adjacency.update_adjacency(
        _copy(m0), jnp.zeros(m0.pcap, bool), K=K
    )
    np.testing.assert_array_equal(ref0, np.asarray(m_none.adja))

    ecap = int(m0.tcap * 1.7) + 64
    edges, emask, _, _ = adjacency.unique_edges(m0, ecap)
    m1, st = swap.swap_23(_copy(m0), edges, emask)
    assert int(st.nswap23) > 0, "workload produced no 2-3 swaps"
    full = adjacency.build_adjacency(_copy(m1))
    incr = adjacency.update_adjacency(_copy(m1), st.changed_v, K=K)
    np.testing.assert_array_equal(np.asarray(full.adja),
                                  np.asarray(incr.adja))
    # K too small for the frontier -> exact via the full-rebuild fallback
    fall = adjacency.update_adjacency(_copy(m1), st.changed_v, K=8)
    np.testing.assert_array_equal(np.asarray(full.adja),
                                  np.asarray(fall.adja))


def _assert_table_equiv(m1, tab_incr, tab_full):
    """Semantic table equality: same live edge SET, same live count,
    and every live tet's t2e row references the same vertex pairs (slot
    NUMBERING may differ — the merge reclaims tombstoned slots, the
    full rebuild assigns sorted-dense ids)."""
    e_i, em_i, t2e_i, nu_i = tab_incr
    e_f, em_f, t2e_f, nu_f = tab_full
    assert int(nu_i) == int(nu_f)
    set_i = {tuple(r) for r in np.asarray(e_i)[np.asarray(em_i)]}
    set_f = {tuple(r) for r in np.asarray(e_f)[np.asarray(em_f)]}
    assert set_i == set_f
    Ei, Ti = np.asarray(e_i), np.asarray(t2e_i)
    Ef, Tf = np.asarray(e_f), np.asarray(t2e_f)
    live = np.nonzero(np.asarray(m1.tmask))[0]
    assert (Ti[live] >= 0).all() and (Tf[live] >= 0).all()
    np.testing.assert_array_equal(Ei[Ti[live]], Ef[Tf[live]])
    # dead tets carry no stale references
    dead = np.nonzero(~np.asarray(m1.tmask))[0]
    assert (Ti[dead] == -1).all()


def test_merge_unique_edges_exact():
    """General incremental merge after a 2-3 swap pass (the old
    append-only case) matches the full re-sort — edge set, live count,
    per-row pairs — including the K-overflow fallback."""
    mesh = _jittered_cube(seed=1)
    m0 = adjacency.build_adjacency(mesh)
    ecap = int(m0.tcap * 1.7) + 64
    edges, emask, t2e, nu = adjacency.unique_edges(m0, ecap)
    m1, st = swap.swap_23(_copy(m0), edges, emask)
    assert int(st.nswap23) > 0
    tab_i = adjacency.merge_unique_edges(
        m1, st.changed_v, edges, emask, t2e, nu, K=m0.tcap
    )
    _assert_table_equiv(m1, tab_i, adjacency.unique_edges(m1, ecap))
    # K-overflow fallback stays exact
    _, _, _, nu_k = adjacency.merge_unique_edges(
        m1, st.changed_v, edges, emask, t2e, nu, K=2
    )
    assert int(nu_k) == int(adjacency.unique_edges(m1, ecap)[3])


def test_merge_unique_edges_delta_schedule():
    """PROPERTY: the merge is exact across a randomized schedule of
    split/collapse/swap deltas — the cases the append-only extension
    could not express (edge deletions, tombstoned slots, slot reuse).
    Each delta applies a REAL operator pass under a random active gate
    (stable numbering, no compaction — appending ops run before killing
    ops, the same packing discipline the sweep's in-body compaction
    points enforce), accumulates the operators' changed_v union, and
    compares the single merged table against the full re-sort."""
    from parmmg_tpu.ops import collapse as collapse_mod
    from parmmg_tpu.ops import split as split_mod

    def fresh_tables(m, ecap):
        # valid current-topology tables for FEEDING the next operator;
        # the merge under test still runs from the original tab0 +
        # accumulated changed set
        return adjacency.unique_edges(m, ecap)

    rng = np.random.default_rng(7)
    for trial in range(3):
        mesh = _jittered_cube(n=4, seed=10 + trial)
        # mixed random metric: fine spots make splits fire, coarse
        # spots make collapses fire — the delta mix the merge must
        # absorb in one pass
        met = rng.uniform(0.08, 0.6, (mesh.pcap, 1))
        mesh = mesh.replace(
            met=jnp.asarray(met, mesh.vert.dtype), met_set=True
        )
        m = adjacency.build_adjacency(mesh)
        ecap = int(m.tcap * 1.9) + 64
        tab0 = adjacency.unique_edges(m, ecap)
        changed = jnp.zeros(m.pcap, bool)
        # appending ops (split / 2-3 swap) target the live-count cursor
        # and must precede killing ops (collapse / 3-2 swap) when no
        # compaction runs in between
        appenders = [x for x in ("split", "swap23")
                     if rng.random() < 0.7]
        killers = [x for x in ("collapse", "swap32")
                   if rng.random() < 0.7] or ["collapse"]
        applied = []
        for op in (
            list(rng.permutation(appenders)) if appenders else []
        ) + list(rng.permutation(killers)):
            act = jnp.asarray(
                rng.random(m.pcap) < rng.uniform(0.3, 1.0), bool
            )
            e, em, t2, _ = fresh_tables(m, ecap)
            if op == "split":
                m, st = split_mod.split_long_edges(
                    m, e, em, t2, active=act
                )
                n_op, chg = int(st.nsplit), st.changed_v
            elif op == "collapse":
                m, st = collapse_mod.collapse_short_edges(
                    m, e, em, t2, hausd=0.05, active=act
                )
                n_op, chg = int(st.ncollapse), st.changed_v
            elif op == "swap23":
                m, st = swap.swap_23(m, e, em, active=act)
                n_op, chg = int(st.nswap23), st.changed_v
            else:
                m, st = swap.swap_32(m, e, em, t2, active=act)
                n_op, chg = int(st.nswap32), st.changed_v
            changed = changed | chg
            applied.append((op, n_op))
        assert any(n for _, n in applied), applied
        tab_i = adjacency.merge_unique_edges(
            m, changed, *tab0, K=m.tcap
        )
        _assert_table_equiv(m, tab_i, adjacency.unique_edges(m, ecap))


# ---------------------------------------------------------------------------
# round 8: the frontier carry through the distributed drivers
# ---------------------------------------------------------------------------


_DIST_BASE = dict(nparts=2, niter=2, hsiz=0.25, max_sweeps=6,
                  min_shard_elts=16, hgrad=None)


def _dist_run(frontier, **kw):
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed,
    )

    base = dict(_DIST_BASE)
    base.update(kw)
    st, comm, info = adapt_distributed(
        unit_cube_mesh(4), DistOptions(frontier=frontier, **base)
    )
    return st, comm, info


@pytest.fixture(scope="module")
def dist_frontier():
    return _dist_run(True)


def test_distributed_frontier_runs_and_reports(dist_frontier):
    """Frontier-on distributed adaptation: green loop, conformal merged
    output, and every sweep record carries the active-set telemetry
    (world active_fraction + per-shard fractions)."""
    from parmmg_tpu.models.distributed import merge_adapted

    st, comm, info = dist_frontier
    assert info["status"] == tags.ReturnStatus.SUCCESS
    merged = merge_adapted(st, comm)
    assert conformity.check_mesh(merged).ok
    recs = [r for r in info["history"] if "n_unique" in r]
    assert recs
    for r in recs:
        assert 0.0 <= r["active_fraction"] <= 1.0
        assert len(r["shard_active"]) == 2


@pytest.mark.slow
def test_distributed_frontier_full_equivalence(dist_frontier):
    """Frontier on/off must produce the same adapted mesh class on the
    distributed driver: same element count (tight), quality histogram
    and conformity — the driver-level extension of the single-shard
    equivalence discipline."""
    from parmmg_tpu.models.distributed import merge_adapted

    st_f, comm_f, _ = dist_frontier
    st_t, comm_t, _ = _dist_run(False)
    m_f = merge_adapted(st_f, comm_f)
    m_t = merge_adapted(st_t, comm_t)
    ne_f, ne_t = int(m_f.ntet), int(m_t.ntet)
    assert abs(ne_f - ne_t) <= max(0.02 * ne_t, 16), (ne_f, ne_t)
    h_f = quality.quality_histogram(m_f)
    h_t = quality.quality_histogram(m_t)
    assert float(h_f.qmin) == pytest.approx(float(h_t.qmin), abs=0.05)
    assert float(h_f.qavg) == pytest.approx(float(h_t.qavg), abs=0.02)
    cf = np.asarray(h_f.counts, np.float64) / max(ne_f, 1)
    ct = np.asarray(h_t.counts, np.float64) / max(ne_t, 1)
    assert np.abs(cf - ct).max() < 0.05, (cf, ct)


def test_distributed_noop_phase_identity(dist_frontier):
    """A drained carry makes the converged distributed remesh phase the
    IDENTITY: bit-identical stacked arrays, one zero-op `skipped`
    record, and the carry stays drained — the converged fast path the
    round-8 bench measures."""
    from parmmg_tpu.models.distributed import DistOptions, remesh_phase

    st, _, _ = dist_frontier
    opts = DistOptions(frontier=True, **_DIST_BASE)
    hist: list = []
    zeros = jnp.zeros((st.vert.shape[0], st.vert.shape[1]), bool)
    ref = _copy(st)
    out, fr2 = remesh_phase(st, opts, [1.6], hist, 9, 0.01, fr0=zeros)
    assert len(hist) == 1 and hist[0].get("skipped")
    assert hist[0]["nsplit"] + hist[0]["ncollapse"] + hist[0]["nswap"] == 0
    assert hist[0]["n_active"] == 0
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jnp.sum(fr2.astype(jnp.int32))) == 0


@pytest.mark.slow
def test_distributed_spmd_frontier(monkeypatch, dist_frontier):
    """The SPMD (`shard_map`) dispatch with per-shard frontier state
    (shard-varying staleness conds) must reproduce the vmapped path's
    result on the same workload — the single-controller equivalence
    run the multi-process path shares its program with."""
    from parmmg_tpu.models.distributed import merge_adapted

    monkeypatch.setenv("PMMGTPU_SPMD_SWEEPS", "1")
    st_s, comm_s, info_s = _dist_run(True)
    assert info_s["status"] == tags.ReturnStatus.SUCCESS
    m_s = merge_adapted(st_s, comm_s)
    assert conformity.check_mesh(m_s).ok
    st_l, comm_l, _ = dist_frontier
    m_l = merge_adapted(st_l, comm_l)
    assert int(m_s.ntet) == int(m_l.ntet)
    h_s = quality.quality_histogram(m_s)
    h_l = quality.quality_histogram(m_l)
    assert float(h_s.qmin) == pytest.approx(float(h_l.qmin), abs=1e-6)


def test_frontier_remap_through_migration_exact():
    """The gid-keyed frontier remap is EXACT through a real
    displacement + migration + compaction + retag round: a vertex is
    active on its (possibly new) owner iff its gid was in the encoded
    active set — bit-equal against a host recomputation."""
    from parmmg_tpu.models.distributed import grow_stacked
    from parmmg_tpu.parallel import migrate as mig
    from parmmg_tpu.parallel.distribute import (
        assign_global_ids, rebuild_comm, split_mesh,
    )
    from parmmg_tpu.parallel.partition import sfc_partition

    mesh = adjacency.build_adjacency(unit_cube_mesh(4))
    part = np.asarray(jax.device_get(sfc_partition(mesh, 2)))
    st, _ = split_mesh(mesh, part, 2)
    st = assign_global_ids(st)
    comm = rebuild_comm(st)
    st = jax.vmap(adjacency.build_adjacency)(st)
    color = mig.displace_colors(st, comm, 2, round_id=0, layers=2)
    cnts = np.asarray(jax.device_get(mig.migration_counts(st, color, 2)))
    assert cnts.sum() > 0, "displacement moved nothing"

    rng = np.random.default_rng(3)
    chg = jnp.asarray(rng.random(st.vmask.shape) < 0.3, bool) & st.vmask
    keys = mig.frontier_gid_keys(st, chg)
    want = set(
        np.asarray(st.vglob)[np.asarray(chg)].tolist()
    )

    st2 = grow_stacked(
        st,
        pcap=st.vert.shape[1] * 2, tcap=st.tet.shape[1] * 2,
        fcap=st.tria.shape[1] * 2, ecap=st.edge.shape[1] * 2,
    )
    color = jnp.pad(
        color, ((0, 0), (0, st2.tet.shape[1] - color.shape[1])),
        constant_values=-1,
    )
    moved = mig.migrate(st2, color, 2, int(cnts.max()) + 8)
    moved = jax.vmap(compact)(moved)
    st3, _ = mig.retag_interfaces(moved)

    got = np.asarray(jax.device_get(
        mig.frontier_from_gid_keys(st3, keys)
    ))
    g3 = np.asarray(st3.vglob)
    vm3 = np.asarray(st3.vmask)
    exp = np.zeros_like(got)
    exp[vm3] = np.isin(g3[vm3], sorted(want))
    np.testing.assert_array_equal(got, exp)


def test_mem_budget_autoderived():
    """VERDICT coverage row 3: an unset mem_budget_mb derives from the
    device's reported memory (CPU fallback: /proc/meminfo) instead of
    running unbounded; float('inf') opts out."""
    derived = default_mem_budget_mb()
    assert derived is None or derived > 0
    mesh = unit_cube_mesh(3)
    out, info = adapt(mesh, AdaptOptions(hsiz=0.3, niter=1, max_sweeps=3))
    assert int(out.ntet) > 0
    if derived is not None:
        assert info["mem_budget_mb"] == pytest.approx(derived, rel=0.5)
    out2, info2 = adapt(unit_cube_mesh(3), AdaptOptions(
        hsiz=0.3, niter=1, max_sweeps=3, mem_budget_mb=float("inf")
    ))
    assert info2["mem_budget_mb"] == float("inf")
