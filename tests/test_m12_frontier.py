"""Round-6 active-set (frontier) sweep tests.

Equivalence discipline: frontier sweeps gate candidate generation on the
one-ring closure of the previous sweep's changes and rebuild analysis
tables incrementally — the RESULT must match full-table sweeps on the
seeded cube workload (same element count, quality histogram and
conformity within fp jitter), on both the fused and unfused dispatch
paths. The incremental rebuilds (`update_adjacency`,
`append_unique_edges`) must be bit-exact against their full
counterparts, including their overflow fallbacks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import parmmg_tpu.models.adapt as adapt_mod
from parmmg_tpu.core import adjacency, tags
from parmmg_tpu.core.mesh import compact
from parmmg_tpu.models.adapt import (
    AdaptOptions, Frontier, adapt, default_mem_budget_mb, remesh_sweep,
)
from parmmg_tpu.ops import quality, swap
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube_mesh


def _copy(m):
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, m
    )


def _run(frontier, unfused, monkeypatch):
    # n=5 cube: adaptive enough to exercise every operator phase while
    # keeping tier-1 time down (compile count, not rows, dominates)
    if unfused:
        monkeypatch.setattr(adapt_mod, "UNFUSED_TCAP", 0)
    mesh = unit_cube_mesh(5)
    opts = AdaptOptions(hsiz=0.12, niter=1, max_sweeps=10, hgrad=None,
                        frontier=frontier)
    out, info = adapt(mesh, opts)
    h = quality.quality_histogram(out)
    return out, info, h


@pytest.mark.parametrize("unfused", [False, True],
                         ids=["fused", "unfused"])
def test_frontier_full_table_equivalence(monkeypatch, unfused):
    """Active-set sweeps must reproduce the full-table result on the
    seeded cube workload: same invariants (ne, qmin/qavg within fp
    tolerance, conformity histogram) on both dispatch paths."""
    out_f, info_f, h_f = _run(True, unfused, monkeypatch)
    out_t, info_t, h_t = _run(False, unfused, monkeypatch)
    ne_f, ne_t = int(out_f.ntet), int(out_t.ntet)
    assert abs(ne_f - ne_t) <= max(0.02 * ne_t, 16), (ne_f, ne_t)
    assert float(h_f.qmin) == pytest.approx(float(h_t.qmin), abs=0.05)
    assert float(h_f.qavg) == pytest.approx(float(h_t.qavg), abs=0.02)
    # conformity histogram: both conformal, same 5-bin quality shape
    assert conformity.check_mesh(out_f).ok
    assert conformity.check_mesh(out_t).ok
    cf = np.asarray(h_f.counts, np.float64) / max(ne_f, 1)
    ct = np.asarray(h_t.counts, np.float64) / max(ne_t, 1)
    assert np.abs(cf - ct).max() < 0.05, (cf, ct)
    # the frontier run reports a (weakly) shrinking active fraction
    saf = [r["n_active"] / max(r["n_unique"], 1)
           for r in info_f["history"]]
    assert saf, "history missing n_active"
    assert all(0.0 <= x <= 1.0 for x in saf)


def test_noop_frontier_sweep_is_identity():
    """A sweep offered an EMPTY frontier over clean tables must do
    nothing: no ops, mesh arrays bit-identical — the converged
    verification-sweep fast path."""
    mesh = unit_cube_mesh(4)
    out, _ = adapt(mesh, AdaptOptions(hsiz=0.2, niter=1, max_sweeps=8,
                                      hgrad=None))
    out = compact(out)
    ecap = int(out.tcap * 1.6) + 64
    edges, emask, t2e, nu = adjacency.unique_edges(out, ecap)
    out = adjacency.build_adjacency(out)
    fr = Frontier(
        changed=jnp.zeros(out.pcap, bool),
        dirty=jnp.int32(0),
        tables=(edges, emask, t2e, jnp.asarray(nu, jnp.int32)),
        adja_ok=jnp.bool_(True),
    )
    ref = _copy(out)
    out2, st, fr2 = remesh_sweep(out, ecap, phase_skip=False, frontier=fr)
    assert int(st.nsplit) == 0 and int(st.ncollapse) == 0
    assert int(st.nswap) == 0 and int(st.nmoved) == 0
    assert int(st.n_active) == 0
    np.testing.assert_array_equal(np.asarray(out2.vert),
                                  np.asarray(ref.vert))
    np.testing.assert_array_equal(np.asarray(out2.tet),
                                  np.asarray(ref.tet))
    np.testing.assert_array_equal(np.asarray(out2.tmask),
                                  np.asarray(ref.tmask))
    # successor frontier stays drained and clean
    assert int(jnp.sum(fr2.changed.astype(jnp.int32))) == 0
    assert int(fr2.dirty) == 0


def _jittered_cube(n=5, seed=0, amp=0.35):
    """Structured cube with deterministically jittered interior vertices
    — quality incentives make 2-3 swaps fire (the pristine cube has
    none)."""
    mesh = compact(unit_cube_mesh(n))
    v = np.asarray(mesh.vert).copy()
    vm = np.asarray(mesh.vmask)
    vt = np.asarray(mesh.vtag)
    interior = vm & ((vt & tags.BDY) == 0)
    rng = np.random.default_rng(seed)
    v[interior] += rng.uniform(-amp, amp, v[interior].shape) / n
    return mesh.replace(vert=jnp.asarray(v, mesh.vert.dtype))


def test_update_adjacency_exact():
    """Incremental face rematch == full rebuild after a real 2-3 swap
    pass, including the K-overflow fallback, and is a no-op on an empty
    frontier."""
    mesh = _jittered_cube()
    m0 = adjacency.build_adjacency(mesh)
    ref0 = np.asarray(m0.adja).copy()
    K = m0.tcap * 4
    m_all = adjacency.update_adjacency(
        _copy(m0), jnp.ones(m0.pcap, bool), K=K
    )
    np.testing.assert_array_equal(ref0, np.asarray(m_all.adja))
    m_none = adjacency.update_adjacency(
        _copy(m0), jnp.zeros(m0.pcap, bool), K=K
    )
    np.testing.assert_array_equal(ref0, np.asarray(m_none.adja))

    ecap = int(m0.tcap * 1.7) + 64
    edges, emask, _, _ = adjacency.unique_edges(m0, ecap)
    m1, st = swap.swap_23(_copy(m0), edges, emask)
    assert int(st.nswap23) > 0, "workload produced no 2-3 swaps"
    full = adjacency.build_adjacency(_copy(m1))
    incr = adjacency.update_adjacency(_copy(m1), st.changed_v, K=K)
    np.testing.assert_array_equal(np.asarray(full.adja),
                                  np.asarray(incr.adja))
    # K too small for the frontier -> exact via the full-rebuild fallback
    fall = adjacency.update_adjacency(_copy(m1), st.changed_v, K=8)
    np.testing.assert_array_equal(np.asarray(full.adja),
                                  np.asarray(fall.adja))


def test_append_unique_edges_exact():
    """Incremental edge-table extension after a 2-3 swap pass matches
    the full re-sort: same edge set, same n_unique, and every live
    tet2edge row references the same vertex pair."""
    mesh = _jittered_cube(seed=1)
    m0 = adjacency.build_adjacency(mesh)
    ecap = int(m0.tcap * 1.7) + 64
    edges, emask, t2e, nu = adjacency.unique_edges(m0, ecap)
    m1, st = swap.swap_23(_copy(m0), edges, emask)
    assert int(st.nswap23) > 0
    e_i, em_i, t2e_i, nu_i = adjacency.append_unique_edges(
        m1, st.changed_v, edges, emask, t2e, nu, K=m0.tcap
    )
    e_f, em_f, t2e_f, nu_f = adjacency.unique_edges(m1, ecap)
    assert int(nu_i) == int(nu_f)
    set_i = {tuple(r) for r in np.asarray(e_i)[np.asarray(em_i)]}
    set_f = {tuple(r) for r in np.asarray(e_f)[np.asarray(em_f)]}
    assert set_i == set_f
    Ei, Ti = np.asarray(e_i), np.asarray(t2e_i)
    Ef, Tf = np.asarray(e_f), np.asarray(t2e_f)
    live = np.nonzero(np.asarray(m1.tmask))[0]
    assert (Ti[live] >= 0).all() and (Tf[live] >= 0).all()
    np.testing.assert_array_equal(Ei[Ti[live]], Ef[Tf[live]])
    # K-overflow fallback stays exact
    _, _, _, nu_k = adjacency.append_unique_edges(
        m1, st.changed_v, edges, emask, t2e, nu, K=2
    )
    assert int(nu_k) == int(nu_f)


def test_mem_budget_autoderived():
    """VERDICT coverage row 3: an unset mem_budget_mb derives from the
    device's reported memory (CPU fallback: /proc/meminfo) instead of
    running unbounded; float('inf') opts out."""
    derived = default_mem_budget_mb()
    assert derived is None or derived > 0
    mesh = unit_cube_mesh(3)
    out, info = adapt(mesh, AdaptOptions(hsiz=0.3, niter=1, max_sweeps=3))
    assert int(out.ntet) > 0
    if derived is not None:
        assert info["mem_budget_mb"] == pytest.approx(derived, rel=0.5)
    out2, info2 = adapt(unit_cube_mesh(3), AdaptOptions(
        hsiz=0.3, niter=1, max_sweeps=3, mem_budget_mb=float("inf")
    ))
    assert info2["mem_budget_mb"] == float("inf")
