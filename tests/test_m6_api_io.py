"""M6 tests: public API, CLI, distributed I/O round-trip, VTK, aniso
gradation — the reference's API/IO acceptance style (manual setter
round-trips, distributed-output rerun pairs; SURVEY §4 tiers 1-2,
`cmake/testing/pmmg_tests.cmake:173-208,324-591`)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from parmmg_tpu.api import Param, ParMesh, ReturnStatus
from parmmg_tpu.core import tags
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube


def test_api_manual_io_roundtrip():
    """Manual setter/getter round-trip + centralized run (the
    `adaptation_example0/sequential_IO/manual_IO/main.c` flow)."""
    raw = unit_cube(3)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(raw["verts"]), ne=len(raw["tets"]),
                     nt=len(raw["trias"]))
    assert pm.set_vertices(raw["verts"]) == ReturnStatus.SUCCESS
    assert pm.set_tetrahedra(raw["tets"]) == ReturnStatus.SUCCESS
    assert pm.set_triangles(raw["trias"], raw["trrefs"]) == ReturnStatus.SUCCESS
    pm.set_metric_sols(np.full(len(raw["verts"]), 0.25))
    pm.set_dparameter(Param.DPARAM_hgrad, 1.3)
    pm.set_iparameter(Param.IPARAM_niter, 1)
    assert pm.get_iparameter(Param.IPARAM_niter) == 1
    assert pm.get_dparameter(Param.DPARAM_hgrad) == 1.3
    assert pm.parmmglib_centralized() == ReturnStatus.SUCCESS
    npo, ne, nt, na = pm.get_mesh_size()
    assert ne > 162  # refined beyond the input
    verts, vrefs = pm.get_vertices()
    tets, trefs = pm.get_tetrahedra()
    assert verts.shape == (npo, 3) and tets.shape == (ne, 4)
    met = pm.get_metric_sols()
    assert met.shape[0] == npo


def test_api_required_entities_survive():
    raw = unit_cube(2)
    pm = ParMesh()
    pm.set_vertices(raw["verts"])
    pm.set_tetrahedra(raw["tets"])
    pm.set_triangles(raw["trias"], raw["trrefs"])
    pm.set_corner(0)
    pm.set_required_vertex(13)  # center vertex of n=2 cube
    pm.set_metric_sols(np.full(len(raw["verts"]), 0.6))
    pm.set_iparameter(Param.IPARAM_niter, 1)
    assert pm.parmmglib_centralized() == ReturnStatus.SUCCESS
    verts, _ = pm.get_vertices()
    # the required center vertex must still exist at its position
    center = raw["verts"][13]
    d = np.linalg.norm(verts - center, axis=1)
    assert d.min() < 1e-12


def test_cli_adapts_cube(tmp_path):
    from parmmg_tpu.__main__ import main
    from parmmg_tpu.io import medit

    raw = unit_cube(2)
    from parmmg_tpu.core.mesh import Mesh

    src = str(tmp_path / "cube.mesh")
    medit.save_mesh(Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"]), src)
    out = str(tmp_path / "cube.o.mesh")
    rc = main([src, "-hsiz", "0.3", "-niter", "1", "-v", "0",
               "-out", out])
    assert rc == 0
    m = medit.load_mesh(out)
    assert int(m.ntet) > 48
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)
    # metric written next to it
    assert os.path.exists(str(tmp_path / "cube.o.sol"))


def test_distributed_io_checkpoint_loop(tmp_path):
    """adapt -> save distributed -> reload -> chkcomm -> re-adapt ->
    merge: the reference's rerun-from-distributed-output CI pairs
    (`pmmg_tests.cmake:173-208`)."""
    from parmmg_tpu.io import medit
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.parallel import chkcomm
    from parmmg_tpu.parallel.shard import device_mesh
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(4)
    opts = DistOptions(hsiz=0.2, niter=1, max_sweeps=4, nparts=4,
                       min_shard_elts=8)
    stacked, comm, _ = adapt_distributed(mesh, opts)
    path = str(tmp_path / "ckpt.mesh")
    medit.save_mesh_distributed(stacked, comm, path, with_met=True)
    for r in range(4):
        assert os.path.exists(str(tmp_path / f"ckpt.{r}.mesh"))

    stacked2, comm2 = medit.load_mesh_distributed(
        path, 4, metpath=str(tmp_path / "ckpt.sol")
    )
    chkcomm.assert_comm_ok(stacked2, comm2, device_mesh(4), tol=1e-6)
    # the PARBDY|NOSURF discipline of synthetic interface trias must
    # survive the round trip (else they come back as plain REQUIRED
    # surface and freeze permanently — advisor round-2 medium finding)
    import numpy as np

    from parmmg_tpu.core import tags as tg

    tt0 = np.asarray(stacked.trtag)
    tt1 = np.asarray(stacked2.trtag)
    syn0 = np.asarray(stacked.trmask) & tg.pure_interface_tria(tt0)
    syn1 = np.asarray(stacked2.trmask) & tg.pure_interface_tria(tt1)
    assert syn0.sum() > 0, "expected synthetic interface trias in ckpt"
    assert syn1.sum(axis=1).tolist() == syn0.sum(axis=1).tolist()
    # continue adapting from the checkpoint
    out, comm3, _ = adapt_stacked_input(
        stacked2, comm2,
        DistOptions(hsiz=0.2, niter=1, max_sweeps=3, nparts=4),
    )
    chkcomm.assert_comm_ok(out, comm3, device_mesh(4), tol=1e-6)
    merged = merge_adapted(out, comm3)
    rep = conformity.check_mesh(merged)
    assert rep.ok, str(rep)
    # merged output must not retain interface pseudo-boundary trias:
    # every surviving tria is a real boundary face (exactly one owner tet)
    from parmmg_tpu.core.adjacency import build_adjacency

    madj = build_adjacency(merged)
    adja = np.asarray(madj.adja)
    tm = np.asarray(madj.tmask)
    bdry_faces = ((adja < 0) & tm[:, None]).sum()
    ntria = int(np.asarray(merged.trmask).sum())
    assert ntria <= bdry_faces, (
        f"{ntria} trias > {bdry_faces} boundary faces: interior "
        "pseudo-boundary trias leaked through the checkpoint"
    )


def test_vtu_roundtrip(tmp_path):
    from parmmg_tpu.io import vtk
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(2)
    p = str(tmp_path / "cube.vtu")
    vtk.save_vtu(m, p)
    m2 = vtk.load_vtu(p)
    assert int(m2.ntet) == int(m.ntet)
    assert int(m2.ntria) == int(m.ntria)
    d1, d2 = m.to_numpy(), m2.to_numpy()
    np.testing.assert_allclose(d1["verts"], d2["verts"])
    np.testing.assert_array_equal(d1["tets"], d2["tets"])


def test_pvtu_output(tmp_path):
    from parmmg_tpu.io import vtk
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    import jax

    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 4)))
    stacked, comm = split_mesh(mesh, part, 4)
    p = str(tmp_path / "out.pvtu")
    vtk.save_pvtu(stacked, comm, p)
    assert os.path.exists(p)
    for s in range(4):
        assert os.path.exists(str(tmp_path / f"out_{s}.vtu"))
    text = open(p).read()
    assert "PUnstructuredGrid" in text and "out_3.vtu" in text


def test_aniso_gradation_bounds_ratio():
    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core import metric as mm
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(6)
    v = np.asarray(m.vert)
    hx = np.where(np.abs(v[:, 0] - 0.5) < 0.1, 0.02, 0.5)
    met = np.zeros((m.pcap, 6))
    met[:, 0] = 1 / hx**2
    met[:, 3] = met[:, 5] = 1 / 0.3**2
    mesh = m.replace(met=jnp.asarray(met), met_set=True)
    edges, emask, _, _ = adjacency.unique_edges(mesh, 20000)
    g = np.asarray(
        mm.gradate_aniso(mesh.vert, mesh.met, edges, emask, hgrad=1.3)
    )
    a = np.asarray(edges[:, 0])
    b = np.asarray(edges[:, 1])
    em = np.asarray(emask)
    hx_g = 1 / np.sqrt(g[:, 0])
    r = np.maximum(hx_g[a[em]], hx_g[b[em]]) / np.minimum(
        hx_g[a[em]], hx_g[b[em]]
    )
    # gradation bounds growth per unit METRIC length: ratio <= hgrad^l
    # (Alauzet gradation; a shock-crossing edge many unit-lengths long
    # legitimately spans a large ratio). Allow 2x slack for the
    # fixed-iteration Jacobi approximation.
    gj = jnp.asarray(g)
    l = np.asarray(
        mm.edge_length(
            mesh.vert[edges[:, 0]], mesh.vert[edges[:, 1]],
            gj[edges[:, 0]], gj[edges[:, 1]],
        )
    )[em]
    viol = r / 1.3 ** np.maximum(l, 1e-9)
    assert viol.max() < 2.0
    # and the ungraded h-field (ratio 25 across one cell) got smoothed
    before = np.maximum(hx[a[em]], hx[b[em]]) / np.minimum(
        hx[a[em]], hx[b[em]]
    )
    assert r.max() < 0.9 * before.max()
    # result stays SPD
    det = np.asarray(mm.metric_det(jnp.asarray(g)))[np.asarray(m.vmask)]
    assert det.min() > 0


def test_aniso_adapt_converges():
    """Aniso metric end-to-end: adapt with a stretched metric, bounded
    element count and valid mesh (the torus-shock class of the
    reference CI, scaled down)."""
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(3)
    met = np.zeros((m.pcap, 6))
    met[:, 0] = 1 / 0.5**2   # coarse in x
    met[:, 3] = 1 / 0.15**2  # fine in y
    met[:, 5] = 1 / 0.5**2
    mesh = m.replace(met=jnp.asarray(met), met_set=True)
    out, info = adapt(mesh, AdaptOptions(niter=1, max_sweeps=6, hgrad=1.3))
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    ne = int(out.ntet)
    assert 100 < ne < 3000
    # anisotropy realized: mean edge length ratio y-vs-x below 0.8
    d = out.to_numpy()
    tets = d["tets"]
    p = d["verts"]
    from parmmg_tpu.core.mesh import EDGE_VERTS

    ev = tets[:, EDGE_VERTS].reshape(-1, 2)
    e = p[ev[:, 1]] - p[ev[:, 0]]
    span = np.abs(e)
    assert span[:, 1].mean() < 0.8 * span[:, 0].mean()
