"""M6 tests: public API, CLI, distributed I/O round-trip, VTK, aniso
gradation — the reference's API/IO acceptance style (manual setter
round-trips, distributed-output rerun pairs; SURVEY §4 tiers 1-2,
`cmake/testing/pmmg_tests.cmake:173-208,324-591`)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from parmmg_tpu.api import Param, ParMesh, ReturnStatus
from parmmg_tpu.core import tags
from parmmg_tpu.utils import conformity
from parmmg_tpu.utils.gen import unit_cube


def test_api_manual_io_roundtrip():
    """Manual setter/getter round-trip + centralized run (the
    `adaptation_example0/sequential_IO/manual_IO/main.c` flow)."""
    raw = unit_cube(3)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(raw["verts"]), ne=len(raw["tets"]),
                     nt=len(raw["trias"]))
    assert pm.set_vertices(raw["verts"]) == ReturnStatus.SUCCESS
    assert pm.set_tetrahedra(raw["tets"]) == ReturnStatus.SUCCESS
    assert pm.set_triangles(raw["trias"], raw["trrefs"]) == ReturnStatus.SUCCESS
    pm.set_metric_sols(np.full(len(raw["verts"]), 0.25))
    pm.set_dparameter(Param.DPARAM_hgrad, 1.3)
    pm.set_iparameter(Param.IPARAM_niter, 1)
    assert pm.get_iparameter(Param.IPARAM_niter) == 1
    assert pm.get_dparameter(Param.DPARAM_hgrad) == 1.3
    assert pm.parmmglib_centralized() == ReturnStatus.SUCCESS
    npo, ne, nt, na = pm.get_mesh_size()
    assert ne > 162  # refined beyond the input
    verts, vrefs = pm.get_vertices()
    tets, trefs = pm.get_tetrahedra()
    assert verts.shape == (npo, 3) and tets.shape == (ne, 4)
    met = pm.get_metric_sols()
    assert met.shape[0] == npo
    # centralized global numbering: contiguous 0..np-1 (a single-shard
    # run never fills Mesh.vglob; the getter must not surface its -1s)
    vg = pm.get_vertex_glonum()
    assert vg.shape == (npo,) and vg[0] == 0 and vg[-1] == npo - 1
    tg = pm.get_triangle_glonum()
    assert len(tg) == nt and (tg >= 0).all()


def test_api_required_entities_survive():
    raw = unit_cube(2)
    pm = ParMesh()
    pm.set_vertices(raw["verts"])
    pm.set_tetrahedra(raw["tets"])
    pm.set_triangles(raw["trias"], raw["trrefs"])
    pm.set_corner(0)
    pm.set_required_vertex(13)  # center vertex of n=2 cube
    pm.set_metric_sols(np.full(len(raw["verts"]), 0.6))
    pm.set_iparameter(Param.IPARAM_niter, 1)
    assert pm.parmmglib_centralized() == ReturnStatus.SUCCESS
    verts, _ = pm.get_vertices()
    # the required center vertex must still exist at its position
    center = raw["verts"][13]
    d = np.linalg.norm(verts - center, axis=1)
    assert d.min() < 1e-12


def test_cli_adapts_cube(tmp_path):
    from parmmg_tpu.__main__ import main
    from parmmg_tpu.io import medit

    raw = unit_cube(2)
    from parmmg_tpu.core.mesh import Mesh

    src = str(tmp_path / "cube.mesh")
    medit.save_mesh(Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"]), src)
    out = str(tmp_path / "cube.o.mesh")
    rc = main([src, "-hsiz", "0.3", "-niter", "1", "-v", "0",
               "-out", out])
    assert rc == 0
    m = medit.load_mesh(out)
    assert int(m.ntet) > 48
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)
    # metric written next to it
    assert os.path.exists(str(tmp_path / "cube.o.sol"))


def test_distributed_io_checkpoint_loop(tmp_path):
    """adapt -> save distributed -> reload -> chkcomm -> re-adapt ->
    merge: the reference's rerun-from-distributed-output CI pairs
    (`pmmg_tests.cmake:173-208`)."""
    from parmmg_tpu.io import medit
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, adapt_stacked_input, merge_adapted,
    )
    from parmmg_tpu.parallel import chkcomm
    from parmmg_tpu.parallel.shard import device_mesh
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(4)
    opts = DistOptions(hsiz=0.2, niter=1, max_sweeps=4, nparts=4,
                       min_shard_elts=8)
    stacked, comm, _ = adapt_distributed(mesh, opts)
    path = str(tmp_path / "ckpt.mesh")
    medit.save_mesh_distributed(stacked, comm, path, with_met=True)
    for r in range(4):
        assert os.path.exists(str(tmp_path / f"ckpt.{r}.mesh"))

    stacked2, comm2 = medit.load_mesh_distributed(
        path, 4, metpath=str(tmp_path / "ckpt.sol")
    )
    chkcomm.assert_comm_ok(stacked2, comm2, device_mesh(4), tol=1e-6)
    # the PARBDY|NOSURF discipline of synthetic interface trias must
    # survive the round trip (else they come back as plain REQUIRED
    # surface and freeze permanently — advisor round-2 medium finding)
    import numpy as np

    from parmmg_tpu.core import tags as tg

    tt0 = np.asarray(stacked.trtag)
    tt1 = np.asarray(stacked2.trtag)
    syn0 = np.asarray(stacked.trmask) & tg.pure_interface_tria(tt0)
    syn1 = np.asarray(stacked2.trmask) & tg.pure_interface_tria(tt1)
    assert syn0.sum() > 0, "expected synthetic interface trias in ckpt"
    assert syn1.sum(axis=1).tolist() == syn0.sum(axis=1).tolist()
    # continue adapting from the checkpoint
    out, comm3, _ = adapt_stacked_input(
        stacked2, comm2,
        DistOptions(hsiz=0.2, niter=1, max_sweeps=3, nparts=4),
    )
    chkcomm.assert_comm_ok(out, comm3, device_mesh(4), tol=1e-6)
    merged = merge_adapted(out, comm3)
    rep = conformity.check_mesh(merged)
    assert rep.ok, str(rep)
    # merged output must not retain interface pseudo-boundary trias:
    # every surviving tria is a real boundary face (exactly one owner tet)
    from parmmg_tpu.core.adjacency import build_adjacency

    madj = build_adjacency(merged)
    adja = np.asarray(madj.adja)
    tm = np.asarray(madj.tmask)
    bdry_faces = ((adja < 0) & tm[:, None]).sum()
    ntria = int(np.asarray(merged.trmask).sum())
    assert ntria <= bdry_faces, (
        f"{ntria} trias > {bdry_faces} boundary faces: interior "
        "pseudo-boundary trias leaked through the checkpoint"
    )


def test_meshb_roundtrip(tmp_path):
    """Binary Medit (.meshb/.solb): byte-for-byte content parity with
    the ASCII path — same sections, same tags, same metric (reference
    reads/writes binary wherever ASCII is handled, the bin/iswp branches
    of src/inout_pmmg.c:88-105,239-330). At 10M tets an ASCII mesh is a
    ~2 GB parse, so binary is the scale path."""
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit

    raw = unit_cube(3)
    nv = len(raw["verts"])
    vtags = np.zeros(nv, np.int32)
    vtags[[0, 3]] |= tags.CORNER | tags.REQUIRED
    vtags[[5, 9]] |= tags.REQUIRED
    edges = np.array([[0, 1], [1, 2]], np.int32)
    edtags = np.array([tags.RIDGE, tags.REQUIRED | tags.RIDGE], np.int32)
    mesh = Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"], vtags=vtags,
        edges=edges, edtags=edtags,
        met=np.full((len(raw["verts"]), 1), 0.3),
    )
    pa = str(tmp_path / "cube.mesh")
    pb = str(tmp_path / "cube.meshb")
    medit.save_mesh(mesh, pa)
    medit.save_mesh(mesh, pb)
    medit.save_met(mesh, str(tmp_path / "cube.sol"))
    medit.save_met(mesh, str(tmp_path / "cube.solb"))
    assert not medit.is_binary_file(pa)
    assert medit.is_binary_file(pb)
    ra = medit.read_mesh(pa)
    rb = medit.read_mesh(pb)
    # binary is bit-exact against the saved arrays; ASCII rounds at %.15g
    np.testing.assert_array_equal(rb.verts, mesh.to_numpy()["verts"])
    np.testing.assert_allclose(ra.verts, rb.verts, rtol=1e-14)
    np.testing.assert_array_equal(rb.tets, ra.tets)
    np.testing.assert_array_equal(rb.trias, ra.trias)
    np.testing.assert_array_equal(rb.trrefs, ra.trrefs)
    np.testing.assert_array_equal(rb.corners, ra.corners)
    np.testing.assert_array_equal(rb.req_verts, ra.req_verts)
    # non-empty id sections actually exercise the binary encoding
    # (review r5: the 0-based write bug passed a corner-less fixture)
    assert len(rb.corners) == 2 and set(rb.corners) == {0, 3}
    assert set(rb.req_verts) == {5, 9}
    np.testing.assert_array_equal(rb.ridges, ra.ridges)
    assert len(rb.ridges) == 2
    np.testing.assert_array_equal(rb.req_edges, ra.req_edges)
    assert len(rb.req_edges) == 1
    sa, ta = medit.read_sol(str(tmp_path / "cube.sol"))
    sb, tb = medit.read_sol(str(tmp_path / "cube.solb"))
    assert ta == tb
    np.testing.assert_allclose(sb, sa, rtol=1e-14)


def test_distributed_checkpoint_binary(tmp_path):
    """The distributed checkpoint loop closes in BINARY: save_.meshb
    shards with communicator records (codes 70-73, the reference's own
    binary communicator encoding, src/inout_pmmg.c:137-142 — whose
    WRITER the reference never implemented, src/libparmmg_tools.c:884),
    reload, chkcomm, and interface discipline intact."""
    from parmmg_tpu.io import medit
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed,
    )
    from parmmg_tpu.parallel import chkcomm
    from parmmg_tpu.parallel.shard import device_mesh
    from parmmg_tpu.utils.gen import unit_cube_mesh

    mesh = unit_cube_mesh(4)
    opts = DistOptions(hsiz=0.2, niter=1, max_sweeps=4, nparts=4,
                       min_shard_elts=8)
    stacked, comm, _ = adapt_distributed(mesh, opts)
    path = str(tmp_path / "ckpt.meshb")
    medit.save_mesh_distributed(stacked, comm, path, with_met=True)
    for r in range(4):
        shard = str(tmp_path / f"ckpt.{r}.meshb")
        assert os.path.exists(shard)
        assert medit.is_binary_file(shard)
        assert os.path.exists(str(tmp_path / f"ckpt.{r}.solb"))

    stacked2, comm2 = medit.load_mesh_distributed(
        path, 4, metpath=str(tmp_path / "ckpt.solb")
    )
    chkcomm.assert_comm_ok(stacked2, comm2, device_mesh(4), tol=1e-6)
    from parmmg_tpu.core import tags as tg

    tt0 = np.asarray(stacked.trtag)
    tt1 = np.asarray(stacked2.trtag)
    syn0 = np.asarray(stacked.trmask) & tg.pure_interface_tria(tt0)
    syn1 = np.asarray(stacked2.trmask) & tg.pure_interface_tria(tt1)
    assert syn0.sum() > 0, "expected synthetic interface trias in ckpt"
    assert syn1.sum(axis=1).tolist() == syn0.sum(axis=1).tolist()
    # metric survived the .solb round trip (save writes live rows in
    # slot order, the loader fills a fresh prefix — row-aligned)
    m0 = np.asarray(stacked.met)
    m1 = np.asarray(stacked2.met)
    vm = np.asarray(stacked.vmask)
    for s in range(4):
        nlive = int(vm[s].sum())
        assert np.allclose(m1[s, :nlive, 0], m0[s][vm[s]][:, 0])


def test_vtu_roundtrip(tmp_path):
    from parmmg_tpu.io import vtk
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(2)
    p = str(tmp_path / "cube.vtu")
    vtk.save_vtu(m, p)
    m2 = vtk.load_vtu(p)
    assert int(m2.ntet) == int(m.ntet)
    assert int(m2.ntria) == int(m.ntria)
    d1, d2 = m.to_numpy(), m2.to_numpy()
    np.testing.assert_allclose(d1["verts"], d2["verts"])
    np.testing.assert_array_equal(d1["tets"], d2["tets"])


def test_pvtu_output(tmp_path):
    from parmmg_tpu.io import vtk
    from parmmg_tpu.parallel.distribute import split_mesh
    from parmmg_tpu.parallel.partition import sfc_partition
    from parmmg_tpu.utils.gen import unit_cube_mesh

    import jax

    mesh = unit_cube_mesh(3)
    part = np.asarray(jax.device_get(sfc_partition(mesh, 4)))
    stacked, comm = split_mesh(mesh, part, 4)
    p = str(tmp_path / "out.pvtu")
    vtk.save_pvtu(stacked, comm, p)
    assert os.path.exists(p)
    for s in range(4):
        assert os.path.exists(str(tmp_path / f"out_{s}.vtu"))
    text = open(p).read()
    assert "PUnstructuredGrid" in text and "out_3.vtu" in text


def test_aniso_gradation_bounds_ratio():
    from parmmg_tpu.core import adjacency
    from parmmg_tpu.core import metric as mm
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(6)
    v = np.asarray(m.vert)
    hx = np.where(np.abs(v[:, 0] - 0.5) < 0.1, 0.02, 0.5)
    met = np.zeros((m.pcap, 6))
    met[:, 0] = 1 / hx**2
    met[:, 3] = met[:, 5] = 1 / 0.3**2
    mesh = m.replace(met=jnp.asarray(met), met_set=True)
    edges, emask, _, _ = adjacency.unique_edges(mesh, 20000)
    g = np.asarray(
        mm.gradate_aniso(mesh.vert, mesh.met, edges, emask, hgrad=1.3)
    )
    a = np.asarray(edges[:, 0])
    b = np.asarray(edges[:, 1])
    em = np.asarray(emask)
    hx_g = 1 / np.sqrt(g[:, 0])
    r = np.maximum(hx_g[a[em]], hx_g[b[em]]) / np.minimum(
        hx_g[a[em]], hx_g[b[em]]
    )
    # gradation bounds growth per unit METRIC length: ratio <= hgrad^l
    # (Alauzet gradation; a shock-crossing edge many unit-lengths long
    # legitimately spans a large ratio). Allow 2x slack for the
    # fixed-iteration Jacobi approximation.
    gj = jnp.asarray(g)
    l = np.asarray(
        mm.edge_length(
            mesh.vert[edges[:, 0]], mesh.vert[edges[:, 1]],
            gj[edges[:, 0]], gj[edges[:, 1]],
        )
    )[em]
    viol = r / 1.3 ** np.maximum(l, 1e-9)
    assert viol.max() < 2.0
    # and the ungraded h-field (ratio 25 across one cell) got smoothed
    before = np.maximum(hx[a[em]], hx[b[em]]) / np.minimum(
        hx[a[em]], hx[b[em]]
    )
    assert r.max() < 0.9 * before.max()
    # result stays SPD
    det = np.asarray(mm.metric_det(jnp.asarray(g)))[np.asarray(m.vmask)]
    assert det.min() > 0


def test_aniso_adapt_converges():
    """Aniso metric end-to-end: adapt with a stretched metric, bounded
    element count and valid mesh (the torus-shock class of the
    reference CI, scaled down)."""
    from parmmg_tpu.models.adapt import AdaptOptions, adapt
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(3)
    met = np.zeros((m.pcap, 6))
    met[:, 0] = 1 / 0.5**2   # coarse in x
    met[:, 3] = 1 / 0.15**2  # fine in y
    met[:, 5] = 1 / 0.5**2
    mesh = m.replace(met=jnp.asarray(met), met_set=True)
    out, info = adapt(mesh, AdaptOptions(niter=1, max_sweeps=6, hgrad=1.3))
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    ne = int(out.ntet)
    assert 100 < ne < 3000
    # anisotropy realized: mean edge length ratio y-vs-x below 0.8
    d = out.to_numpy()
    tets = d["tets"]
    p = d["verts"]
    from parmmg_tpu.core.mesh import EDGE_VERTS

    ev = tets[:, EDGE_VERTS].reshape(-1, 2)
    e = p[ev[:, 1]] - p[ev[:, 0]]
    span = np.abs(e)
    assert span[:, 1].mean() < 0.8 * span[:, 0].mean()


def test_cli_fields_roundtrip(tmp_path):
    """-field end-to-end: load a solution field, interpolate it through
    adaptation, save `<out>.fields.sol` (the reference CI field family,
    `pmmg_tests.cmake:215-241` + `src/parmmg.c:292-433`)."""
    from parmmg_tpu.__main__ import main
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit

    raw = unit_cube(2)
    src = str(tmp_path / "cube.mesh")
    medit.save_mesh(Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"]), src)
    # scalar field = x coordinate (linear: midpoint interpolation exact)
    # plus a constant 3-vector field
    fld = str(tmp_path / "phys.sol")
    vals = np.concatenate(
        [raw["verts"][:, :1],
         np.tile([1.0, 2.0, 3.0], (len(raw["verts"]), 1))], axis=1,
    )
    medit.save_sol(fld, vals, [medit.SOL_SCALAR, medit.SOL_VECTOR])
    out = str(tmp_path / "cube.o.mesh")
    rc = main([src, "-hsiz", "0.3", "-niter", "1", "-v", "0",
               "-field", fld, "-out", out])
    assert rc == 0
    fout = str(tmp_path / "cube.o.fields.sol")
    assert os.path.exists(fout)
    fvals, ftypes = medit.read_sol(fout)
    assert ftypes == [medit.SOL_SCALAR, medit.SOL_VECTOR]
    m = medit.load_mesh(out)
    d = m.to_numpy()
    assert fvals.shape[0] == d["verts"].shape[0]
    # the x-coordinate field tracks the vertices through remeshing
    assert np.abs(fvals[:, 0] - d["verts"][:, 0]).max() < 1e-3
    assert np.allclose(fvals[:, 1:4], [1.0, 2.0, 3.0], atol=1e-6)


def test_cli_val_and_noout(tmp_path, capsys):
    from parmmg_tpu.__main__ import main
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit

    assert main(["-val"]) == 0
    assert "Default parameters" in capsys.readouterr().out

    raw = unit_cube(2)
    src = str(tmp_path / "cube.mesh")
    medit.save_mesh(Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"]), src)
    out = str(tmp_path / "cube.o.mesh")
    rc = main([src, "-hsiz", "0.3", "-niter", "1", "-v", "0",
               "-noout", "-out", out])
    assert rc == 0
    assert not os.path.exists(out)


def test_implied_aniso_metric_unit_lengths():
    """-A implied tensor: on a uniform mesh the LS fit must give ~unit
    metric length to the existing edges (MMG3D_doSol_ani role)."""
    from parmmg_tpu.core import metric as mm
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(3, perturb=0.15)
    met = mm.implied_aniso_metric(m.vert, m.tet, m.tmask, m.pcap)
    from parmmg_tpu.core.mesh import EDGE_VERTS

    ev = np.asarray(m.tet)[np.asarray(m.tmask)][:, EDGE_VERTS].reshape(-1, 2)
    p = np.asarray(m.vert)
    l_m = np.asarray(mm.edge_length_aniso(
        jnp.asarray(p[ev[:, 0]]), jnp.asarray(p[ev[:, 1]]),
        jnp.asarray(np.asarray(met)[ev[:, 0]]),
        jnp.asarray(np.asarray(met)[ev[:, 1]]),
    ))
    assert 0.6 < np.median(l_m) < 1.5
    # SPD everywhere
    det = np.asarray(mm.metric_det(met))[np.asarray(m.vmask)]
    assert det.min() > 0


def test_cli_aniso_flag(tmp_path):
    """-A without a metric file adapts under the implied tensor metric."""
    from parmmg_tpu.__main__ import main
    from parmmg_tpu.core.mesh import Mesh
    from parmmg_tpu.io import medit
    from parmmg_tpu.utils import conformity

    raw = unit_cube(3)
    src = str(tmp_path / "cube.mesh")
    medit.save_mesh(Mesh.from_numpy(
        raw["verts"], raw["tets"], trias=raw["trias"],
        trrefs=raw["trrefs"]), src)
    out = str(tmp_path / "cube.o.mesh")
    rc = main([src, "-A", "-niter", "1", "-v", "0", "-out", out])
    assert rc == 0
    m = medit.load_mesh(out)
    rep = conformity.check_mesh(m)
    assert rep.ok, str(rep)
    # tensor metric written (9 columns per tensor line in medit = sym 6)
    sol = str(tmp_path / "cube.o.sol")
    vals, types = medit.read_sol(sol)
    assert types == [medit.SOL_TENSOR]


def test_parsop_local_params(tmp_path):
    """parsop local parameters: per-reference hmin/hmax clamps and the
    per-tria-ref hausd table (`PMMG_parsop`,
    reference `src/libparmmg_tools.c:573`)."""
    from parmmg_tpu.io import parsop
    from parmmg_tpu.models.adapt import (
        AdaptOptions, local_hausd_table, prepare_metric,
    )
    from parmmg_tpu.utils.gen import unit_cube_mesh

    pf = tmp_path / "cube.mmg3d"
    pf.write_text(
        "Parameters\n2\n"
        "1 Triangles 0.05 0.15 0.002\n"
        "2 Triangles 0.05 0.5  0.02\n"
    )
    lps = parsop.parse_local_params(str(pf))
    assert len(lps) == 2 and lps[0].elt == "triangle"
    assert parsop.default_param_file(str(tmp_path / "cube.mesh")) == str(pf)

    m = unit_cube_mesh(2)
    opts = AdaptOptions(hsiz=0.4, local_params=lps, hgrad=None)
    m2 = prepare_metric(m, opts, int(m.tcap * 1.7) + 64)
    met = np.asarray(m2.met)[:, 0]
    tr = np.asarray(m.tria)[np.asarray(m.trmask)]
    trref = np.asarray(m.trref)[np.asarray(m.trmask)]
    v_ref1 = np.unique(tr[trref == 1])
    assert np.all(met[v_ref1] <= 0.15 + 1e-12)
    # vertices on no local-param face keep the global size
    on_face = np.zeros(m.pcap, bool)
    on_face[np.unique(tr[(trref == 1) | (trref == 2)])] = True
    free = np.asarray(m.vmask) & ~on_face
    assert np.allclose(met[free], 0.4)

    table = local_hausd_table(m, opts, 0.01)
    t = np.asarray(table)
    assert t[1] == 0.002 and t[2] == 0.02 and t[3] == 0.01


def test_hgradreq_required_sizes_win():
    """-hgradreq: required vertices act as immutable gradation sources."""
    from parmmg_tpu.core import adjacency, metric as mm
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(3)
    h = np.full((m.pcap, 1), 0.1)
    h[0] = 0.9  # a required vertex with a much coarser prescribed size
    fixed = np.zeros(m.pcap, bool)
    fixed[0] = True
    edges, emask, _, _ = adjacency.unique_edges(m, int(m.tcap * 1.7) + 64)
    # plain gradation (gradsiz) would shrink the coarse prescription
    # toward its fine neighbors...
    g0 = np.asarray(mm.gradate_iso(
        m.vert, jnp.asarray(h), edges, emask, hgrad=1.2,
    ))
    assert g0[0, 0] < 0.3
    # ...the -hgradreq pass keeps required sizes immutable
    g = np.asarray(mm.gradate_iso(
        m.vert, jnp.asarray(h), edges, emask, hgrad=1.2,
        fixed=jnp.asarray(fixed),
    ))
    assert g[0, 0] == pytest.approx(0.9)    # required size wins
    assert np.allclose(g[1:, 0][g[1:, 0] > 0], 0.1)  # others untouched


def test_distributed_aniso_adapt():
    """Aniso tensor metric through the distributed driver (VERDICT: the
    reference CI torus-shock family runs multi-rank)."""
    import jax

    # this jaxlib's CPU compiler can segfault on the next BIG compile
    # after many in one process (conftest note); this is the first
    # vmapped-driver compile after 14 compile-heavy tests
    jax.clear_caches()
    from parmmg_tpu.models.distributed import (
        DistOptions, adapt_distributed, merge_adapted,
    )
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(3)
    met = np.zeros((m.pcap, 6))
    met[:, 0] = 1 / 0.5**2
    met[:, 3] = 1 / 0.15**2
    met[:, 5] = 1 / 0.5**2
    mesh = m.replace(met=jnp.asarray(met), met_set=True)
    stacked, comm, info = adapt_distributed(
        mesh, DistOptions(niter=1, max_sweeps=4, nparts=2,
                          min_shard_elts=8, hgrad=1.3)
    )
    out = merge_adapted(stacked, comm)
    rep = conformity.check_mesh(out)
    assert rep.ok, str(rep)
    d = out.to_numpy()
    from parmmg_tpu.core.mesh import EDGE_VERTS

    ev = d["tets"][:, EDGE_VERTS].reshape(-1, 2)
    e = d["verts"][ev[:, 1]] - d["verts"][ev[:, 0]]
    span = np.abs(e)
    assert span[:, 1].mean() < 0.85 * span[:, 0].mean()


def test_global_numbering_and_owner_getters():
    """The distributed-output contract (VERDICT Missing #6): triangle
    global numbering (`PMMG_Compute_trianglesGloNum` role, reference
    src/libparmmg.c:464) and node-communicator owner getters
    (`PMMG_Get_NodeCommunicator_owners`, src/libparmmg.h:2499)."""
    from parmmg_tpu.api import Param, ParMesh
    from parmmg_tpu.core import tags as T
    from parmmg_tpu.utils.gen import unit_cube

    raw = unit_cube(4)
    pm = ParMesh(nparts=2)
    pm.set_mesh_size(np_=len(raw["verts"]), ne=len(raw["tets"]),
                     nt=len(raw["trias"]))
    pm.set_vertices(raw["verts"])
    pm.set_tetrahedra(raw["tets"])
    pm.set_triangles(raw["trias"], raw["trrefs"])
    pm.set_iparameter(Param.IPARAM_niter, 1)
    pm.set_iparameter(Param.IPARAM_globalNum, 1)
    pm.set_dparameter(Param.DPARAM_hsiz, 0.3)
    pm.opts.min_shard_elts = 8
    pm.opts.max_sweeps = 4
    assert pm.parmmglib_centralized() == 0

    # vertex gids: every live vertex numbered, interface ids shared
    vg = pm.get_vertex_glonum()
    assert len(vg) == 2 and all((g >= 0).all() for g in vg)
    allg = np.concatenate(vg)
    # total distinct ids == merged vertex count (each interface vertex
    # counted once)
    assert len(np.unique(allg)) == len(pm.get_vertices()[0])

    # triangle gids: contiguous over distinct true-surface trias;
    # synthetic interface trias are -1
    tg = pm.get_triangle_glonum()
    cat = np.concatenate(tg)
    real = cat[cat >= 0]
    assert len(real) > 0
    assert real.max() == len(np.unique(real)) - 1
    # replicas of one physical tria never disagree: count of distinct
    # ids equals the merged mesh's tria count
    assert len(np.unique(real)) == len(pm.get_triangles()[0])

    # owners: lowest shard owns; counts consistent
    own = pm.get_node_communicator_owners()
    ranks0, gids0, nuni, ntot = own[0]
    assert ntot >= nuni > 0
    assert ((ranks0 == 0) | (ranks0 == 1)).all()
    # a vertex shared by shards 0 and 1 is owned by 0
    shared = np.intersect1d(gids0, own[1][1])
    r_by_gid = {g: r for g, r in zip(gids0, ranks0)}
    assert all(r_by_gid[g] == 0 for g in shared)


def test_gradate_from_required_semantics():
    """MMG3D_gradsizreq: propagation FROM required entities only — a
    no-op without required vertices; caps neighbors of a fine required
    size; leaves far vertices untouched."""
    from parmmg_tpu.core import adjacency, metric as mm
    from parmmg_tpu.utils.gen import unit_cube_mesh

    m = unit_cube_mesh(4)
    h = np.full((m.pcap, 1), 0.5)
    edges, emask, _, _ = adjacency.unique_edges(m, int(m.tcap * 1.7) + 64)

    # no required vertices: exact no-op (a plain gradation would relax)
    req0 = np.zeros(m.pcap, bool)
    g0 = np.asarray(mm.gradate_from_required(
        m.vert, jnp.asarray(h), edges, emask, jnp.asarray(req0),
        hgrad=1.3,
    ))
    assert np.array_equal(g0, h)

    # a finer required size at corner 0 caps its neighborhood; the cap
    # relaxes away at the hgradreq ratio and the far corner is untouched
    h[0] = 0.3
    req = np.zeros(m.pcap, bool)
    req[0] = True
    g = np.asarray(mm.gradate_from_required(
        m.vert, jnp.asarray(h), edges, emask, jnp.asarray(req),
        hgrad=1.3,
    ))
    assert g[0, 0] == pytest.approx(0.3)
    a, b = np.asarray(edges[:, 0]), np.asarray(edges[:, 1])
    em = np.asarray(emask)
    nbr = np.unique(np.concatenate([b[(a == 0) & em], a[(b == 0) & em]]))
    assert g[nbr, 0].max() < 0.45         # capped near the source
    far = np.linalg.norm(np.asarray(m.vert) - np.asarray(m.vert)[0],
                         axis=1) > 1.5
    far &= np.asarray(m.vmask)
    assert np.allclose(g[far, 0], 0.5)    # untouched far away


@pytest.mark.parametrize("flags", [
    ["-optim"],
    ["-optimLES"],
    ["-noinsert"],
    ["-noswap"],
    ["-nomove"],
    ["-nosurf"],
    ["-hsiz", "0.35"],
    ["-hausd", "0.002"],
    ["-hsiz", "0.35", "-hgrad", "1.1"],
    ["-nr"],
    ["-ar", "30"],
    ["-A"],
    ["-hsiz", "0.35", "-hgradreq", "1.2"],
], ids=lambda f: " ".join(f))
def test_cli_option_sweep(tmp_path, flags):
    """Option matrix on a curved (ball) mesh — the reference CI's sphere
    option sweep (`cmake/testing/pmmg_tests.cmake:71-150`), pass
    criterion = exit code like the reference."""
    import jax

    from parmmg_tpu.__main__ import main
    from parmmg_tpu.io import medit
    from parmmg_tpu.utils.gen import unit_ball_mesh

    # each flag combo compiles its own programs anyway; dropping the
    # executable caches first keeps the jaxlib CPU compiler state small
    # (its documented crash mode is the NEXT big compile after many —
    # see conftest._clear_jax_caches_between_modules)
    jax.clear_caches()
    src = str(tmp_path / "ball.mesh")
    medit.save_mesh(unit_ball_mesh(4), src)
    rc = main([src, "-niter", "1", "-v", "0", "-noout", *flags])
    assert rc == 0
