"""Tet-tet adjacency and unique-edge extraction via sort-based matching.

Functional equivalent of Mmg's `MMG3D_hashTetra` (called by the reference at
`src/libparmmg1.c:733`), re-designed for XLA: instead of a serial hash table,
faces/edges are canonicalized, lexicographically sorted, and matched between
equal neighbors — O(n log n) fully on device, static shapes throughout.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import EDGE_VERTS, FACE_VERTS, Mesh

# numpy, not jnp: an import-time jnp constant becomes a leaked tracer
# if the module is first imported under an active trace (see the
# SENT_U32 note in ops/common.py)
_BIG = np.int32(2**30)


def _sort3(a, b, c):
    lo = jnp.minimum(jnp.minimum(a, b), c)
    hi = jnp.maximum(jnp.maximum(a, b), c)
    mid = a + b + c - lo - hi
    return lo, mid, hi


# parmmg-lint: disable=PML005 -- rebuilds adja only; warm/profile harnesses and tests reuse the input mesh
@jax.jit
def build_adjacency(mesh: Mesh) -> Mesh:
    """Fill `mesh.adja`: adja[t,f] = 4*t2+f2 for the tet face glued to (t,f),
    -1 for boundary faces. Masked tets get all -1 and never match. Faces
    shared by 3+ tets (invalid input) are left unmatched (-1) rather than
    silently mis-paired; `utils.conformity.check_mesh` reports them.

    When vertex ids fit the packed-key bound, the (b,c) columns collapse
    into one uint32 key, cutting the sort from 3 comparator columns to 2
    (see ops.common.pack_ok)."""
    from ..ops import common as _common

    tc = mesh.tcap
    tet = mesh.tet
    # face vertex triples, canonically sorted; dead slots get unique sentinels
    fv = tet[:, FACE_VERTS]  # [TC, 4, 3]
    a, b, c = _sort3(fv[..., 0], fv[..., 1], fv[..., 2])
    slot = jnp.arange(tc * 4, dtype=jnp.int32).reshape(tc, 4)
    dead = ~mesh.tmask[:, None]
    a = jnp.where(dead, _BIG, a).reshape(-1)
    if _common.pack_ok(mesh.pcap, 2):
        s = jnp.uint32(mesh.pcap + 1)
        bc = b.astype(jnp.uint32) * s + c.astype(jnp.uint32)
        bc = jnp.where(dead, slot.astype(jnp.uint32), bc).reshape(-1)
        order = jnp.lexsort((bc, a)).astype(jnp.int32)
        sa, sbc = a[order], bc[order]
        eq_next = (sa[:-1] == sa[1:]) & (sbc[:-1] == sbc[1:])
    else:
        b = jnp.where(dead, slot, b).reshape(-1)
        c = jnp.where(dead, slot, c).reshape(-1)
        order = jnp.lexsort((c, b, a)).astype(jnp.int32)
        sa, sb, sc = a[order], b[order], c[order]
        eq_next = (
            (sa[:-1] == sa[1:]) & (sb[:-1] == sb[1:]) & (sc[:-1] == sc[1:])
        )
    eq_next = jnp.concatenate([eq_next, jnp.zeros(1, bool)])
    eq_prev = jnp.concatenate([jnp.zeros(1, bool), eq_next[:-1]])
    # pair only runs of exactly 2 equal faces; longer runs are invalid
    not_mid = ~(eq_next & eq_prev)  # not the middle of a 3+-run
    pair2 = eq_next & not_mid & jnp.roll(not_mid, -1)  # i pairs with i+1
    partner = jnp.where(
        pair2,
        jnp.roll(order, -1),
        jnp.where(jnp.roll(pair2, 1), jnp.roll(order, 1), -1),
    )
    adja_flat = jnp.full(tc * 4, -1, jnp.int32).at[order].set(partner)
    return mesh.replace(adja=adja_flat.reshape(tc, 4))


# parmmg-lint: disable=PML005 -- pure query (edge table); every caller keeps using the mesh
@partial(jax.jit, static_argnames=("ecap",))
def unique_edges(mesh: Mesh, ecap: int):
    """Extract unique undirected edges of the valid tets.

    Returns (edges [ecap,2] int32 vertex pairs (lo,hi), emask [ecap] bool,
    tet2edge [TC,6] int32 edge-slot id per local tet edge, -1 on dead tets,
    n_unique scalar int32 = true number of unique edges). If
    n_unique > ecap, edges beyond the cap were dropped (their tet2edge
    entries are -1) — callers must check and re-run with a larger cap.
    `ecap = 6*tcap` is always safe; ~1.3*tcap suffices for well-connected
    tet meshes (~1.19 edges/tet asymptotically)."""
    from ..ops import common as _common

    tc = mesh.tcap
    ev = mesh.tet[:, EDGE_VERTS]  # [TC, 6, 2]
    lo = jnp.minimum(ev[..., 0], ev[..., 1])
    hi = jnp.maximum(ev[..., 0], ev[..., 1])
    dead = jnp.broadcast_to(~mesh.tmask[:, None], (tc, 6))
    order, newgrp, live_sorted, slo, shi = _common.sorted_pair_groups(
        lo.reshape(-1), hi.reshape(-1), dead.reshape(-1), mesh.pcap
    )
    # unique edge id per sorted position (0-based over all groups incl. dead)
    gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    # edge arrays: scatter first member of each live group
    first = newgrp & live_sorted
    edges = jnp.zeros((ecap, 2), jnp.int32)
    emask = jnp.zeros(ecap, bool)
    # group representatives have unique gids; non-first/dead rows AND
    # overflow representatives (gid >= ecap, the documented retry path)
    # get distinct OOB sentinels so the unique-indices promise holds
    tgt = _common.unique_oob(
        first & (gid < ecap), gid.astype(jnp.int32), ecap
    )
    kw = dict(mode="drop", unique_indices=True)
    edges = edges.at[tgt, 0].set(slo.astype(jnp.int32), **kw)
    edges = edges.at[tgt, 1].set(shi.astype(jnp.int32), **kw)
    emask = emask.at[tgt].set(True, **kw)
    # tet->edge map
    t2e_flat = jnp.full(tc * 6, -1, jnp.int32)
    val = jnp.where(live_sorted & (gid < ecap), gid, -1).astype(jnp.int32)
    t2e_flat = t2e_flat.at[order].set(val, unique_indices=True)
    n_unique = jnp.sum((newgrp & live_sorted).astype(jnp.int32))
    return edges, emask, t2e_flat.reshape(tc, 6), n_unique


def boundary_faces(mesh: Mesh):
    """Mask [TC,4] of faces with no neighbor (requires fresh adjacency)."""
    return (mesh.adja < 0) & mesh.tmask[:, None]
