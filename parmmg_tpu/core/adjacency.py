"""Tet-tet adjacency and unique-edge extraction via sort-based matching.

Functional equivalent of Mmg's `MMG3D_hashTetra` (called by the reference at
`src/libparmmg1.c:733`), re-designed for XLA: instead of a serial hash table,
faces/edges are canonicalized, lexicographically sorted, and matched between
equal neighbors — O(n log n) fully on device, static shapes throughout.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import EDGE_VERTS, FACE_VERTS, Mesh

# numpy, not jnp: an import-time jnp constant becomes a leaked tracer
# if the module is first imported under an active trace (see the
# SENT_U32 note in ops/common.py)
_BIG = np.int32(2**30)


def _sort3(a, b, c):
    lo = jnp.minimum(jnp.minimum(a, b), c)
    hi = jnp.maximum(jnp.maximum(a, b), c)
    mid = a + b + c - lo - hi
    return lo, mid, hi


# parmmg-lint: disable=PML005 -- rebuilds adja only; warm/profile harnesses and tests reuse the input mesh
@jax.jit
def build_adjacency(mesh: Mesh) -> Mesh:
    """Fill `mesh.adja`: adja[t,f] = 4*t2+f2 for the tet face glued to (t,f),
    -1 for boundary faces. Masked tets get all -1 and never match. Faces
    shared by 3+ tets (invalid input) are left unmatched (-1) rather than
    silently mis-paired; `utils.conformity.check_mesh` reports them.

    When vertex ids fit the packed-key bound, the (b,c) columns collapse
    into one uint32 key, cutting the sort from 3 comparator columns to 2
    (see ops.common.pack_ok)."""
    from ..ops import common as _common

    tc = mesh.tcap
    tet = mesh.tet
    # face vertex triples, canonically sorted; dead slots get unique sentinels
    fv = tet[:, FACE_VERTS]  # [TC, 4, 3]
    a, b, c = _sort3(fv[..., 0], fv[..., 1], fv[..., 2])
    slot = jnp.arange(tc * 4, dtype=jnp.int32).reshape(tc, 4)
    dead = ~mesh.tmask[:, None]
    a = jnp.where(dead, _BIG, a).reshape(-1)
    if _common.pack_ok(mesh.pcap, 2):
        s = jnp.uint32(mesh.pcap + 1)
        bc = b.astype(jnp.uint32) * s + c.astype(jnp.uint32)
        bc = jnp.where(dead, slot.astype(jnp.uint32), bc).reshape(-1)
        order = jnp.lexsort((bc, a)).astype(jnp.int32)
        sa, sbc = a[order], bc[order]
        eq_next = (sa[:-1] == sa[1:]) & (sbc[:-1] == sbc[1:])
    else:
        b = jnp.where(dead, slot, b).reshape(-1)
        c = jnp.where(dead, slot, c).reshape(-1)
        order = jnp.lexsort((c, b, a)).astype(jnp.int32)
        sa, sb, sc = a[order], b[order], c[order]
        eq_next = (
            (sa[:-1] == sa[1:]) & (sb[:-1] == sb[1:]) & (sc[:-1] == sc[1:])
        )
    eq_next = jnp.concatenate([eq_next, jnp.zeros(1, bool)])
    eq_prev = jnp.concatenate([jnp.zeros(1, bool), eq_next[:-1]])
    # pair only runs of exactly 2 equal faces; longer runs are invalid
    not_mid = ~(eq_next & eq_prev)  # not the middle of a 3+-run
    pair2 = eq_next & not_mid & jnp.roll(not_mid, -1)  # i pairs with i+1
    partner = jnp.where(
        pair2,
        jnp.roll(order, -1),
        jnp.where(jnp.roll(pair2, 1), jnp.roll(order, 1), -1),
    )
    adja_flat = jnp.full(tc * 4, -1, jnp.int32).at[order].set(partner)
    return mesh.replace(adja=adja_flat.reshape(tc, 4))


# parmmg-lint: disable=PML005 -- pure query (edge table); every caller keeps using the mesh
@partial(jax.jit, static_argnames=("ecap",))
def unique_edges(mesh: Mesh, ecap: int):
    """Extract unique undirected edges of the valid tets.

    Returns (edges [ecap,2] int32 vertex pairs (lo,hi), emask [ecap] bool,
    tet2edge [TC,6] int32 edge-slot id per local tet edge, -1 on dead tets,
    n_unique scalar int32 = true number of unique edges). If
    n_unique > ecap, edges beyond the cap were dropped (their tet2edge
    entries are -1) — callers must check and re-run with a larger cap.
    `ecap = 6*tcap` is always safe; ~1.3*tcap suffices for well-connected
    tet meshes (~1.19 edges/tet asymptotically)."""
    from ..ops import common as _common

    tc = mesh.tcap
    ev = mesh.tet[:, EDGE_VERTS]  # [TC, 6, 2]
    lo = jnp.minimum(ev[..., 0], ev[..., 1])
    hi = jnp.maximum(ev[..., 0], ev[..., 1])
    dead = jnp.broadcast_to(~mesh.tmask[:, None], (tc, 6))
    order, newgrp, live_sorted, slo, shi = _common.sorted_pair_groups(
        lo.reshape(-1), hi.reshape(-1), dead.reshape(-1), mesh.pcap
    )
    # unique edge id per sorted position (0-based over all groups incl. dead)
    gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    # edge arrays: scatter first member of each live group
    first = newgrp & live_sorted
    edges = jnp.zeros((ecap, 2), jnp.int32)
    emask = jnp.zeros(ecap, bool)
    # group representatives have unique gids; non-first/dead rows AND
    # overflow representatives (gid >= ecap, the documented retry path)
    # get distinct OOB sentinels so the unique-indices promise holds
    tgt = _common.unique_oob(
        first & (gid < ecap), gid.astype(jnp.int32), ecap
    )
    kw = dict(mode="drop", unique_indices=True)
    edges = edges.at[tgt, 0].set(slo.astype(jnp.int32), **kw)
    edges = edges.at[tgt, 1].set(shi.astype(jnp.int32), **kw)
    emask = emask.at[tgt].set(True, **kw)
    # tet->edge map
    t2e_flat = jnp.full(tc * 6, -1, jnp.int32)
    val = jnp.where(live_sorted & (gid < ecap), gid, -1).astype(jnp.int32)
    t2e_flat = t2e_flat.at[order].set(val, unique_indices=True)
    n_unique = jnp.sum((newgrp & live_sorted).astype(jnp.int32))
    return edges, emask, t2e_flat.reshape(tc, 6), n_unique


def boundary_faces(mesh: Mesh):
    """Mask [TC,4] of faces with no neighbor (requires fresh adjacency)."""
    return (mesh.adja < 0) & mesh.tmask[:, None]


# ---------------------------------------------------------------------------
# incremental (frontier-compacted) rebuilds — rounds 6 and 8
#
# Both functions share one contract with the frontier sweeps
# (models/adapt.py): the existing table was computed on the SAME
# vertex/tet numbering (no compaction since), and `changed_v` marks
# every vertex of every tet row created, deleted, or rewritten since
# the table was built (the operators' `changed_v` stats guarantee this:
# a modified tet marks all of its vertices). It follows that a face or
# edge whose pairing/membership could have changed has ALL its vertices
# in `changed_v` — both sides of a stale face pairing share the 3 face
# vertices of the modified side — so only those rows are recomputed,
# gathered into a fixed-K compacted stream (static shape) and merged
# into the previous table. Overflowing frontiers fall back to the full
# rebuild via `lax.cond`, so the result is always exact. Round 8
# generalized the edge-table path from append-only extension to a full
# delta merge (`merge_unique_edges`: tombstone + slot reclamation), so
# collapse/split/swap churn no longer forces the full re-sort.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("K",), donate_argnums=0)
def update_adjacency(mesh: Mesh, changed_v: jax.Array, K: int) -> Mesh:
    """Incrementally refresh `mesh.adja` for a frontier of changed
    vertices: re-match only faces whose 3 vertices are all in
    `changed_v`, at K-compacted sort size instead of 4*TC (see the
    contract note above). More than `K` hot faces -> full
    `build_adjacency`."""
    from ..ops import common as _common

    tc = mesh.tcap

    def _full(m):
        return build_adjacency(m)

    def _incr(m):
        fv = m.tet[:, FACE_VERTS]                      # [TC,4,3]
        a, b, c = _sort3(fv[..., 0], fv[..., 1], fv[..., 2])
        hot = (
            changed_v[fv[..., 0]] & changed_v[fv[..., 1]]
            & changed_v[fv[..., 2]] & m.tmask[:, None]
        ).reshape(-1)
        # compact hot faces into the K stream (scan + scatter, no sort)
        rank = jnp.cumsum(hot.astype(jnp.int32)) - 1
        tgt = _common.unique_oob(hot & (rank < K), rank, K)
        slot = jnp.full(K, -1, jnp.int32).at[tgt].set(
            jnp.arange(tc * 4, dtype=jnp.int32), mode="drop",
            unique_indices=True,
        )
        valid = slot >= 0
        src = jnp.maximum(slot, 0)
        ka = jnp.where(valid, a.reshape(-1)[src], _BIG)
        if _common.pack_ok(m.pcap, 2):
            s = jnp.uint32(m.pcap + 1)
            bc = (
                b.reshape(-1)[src].astype(jnp.uint32) * s
                + c.reshape(-1)[src].astype(jnp.uint32)
            )
            bc = jnp.where(valid, bc, jnp.arange(K, dtype=jnp.uint32))
            order = jnp.lexsort((bc, ka)).astype(jnp.int32)
            sa, sbc = ka[order], bc[order]
            eq_next = (sa[:-1] == sa[1:]) & (sbc[:-1] == sbc[1:])
        else:
            kb = jnp.where(valid, b.reshape(-1)[src],
                           jnp.arange(K, dtype=jnp.int32))
            kc = jnp.where(valid, c.reshape(-1)[src],
                           jnp.arange(K, dtype=jnp.int32))
            order = jnp.lexsort((kc, kb, ka)).astype(jnp.int32)
            sa, sb, sc = ka[order], kb[order], kc[order]
            eq_next = (
                (sa[:-1] == sa[1:]) & (sb[:-1] == sb[1:])
                & (sc[:-1] == sc[1:])
            )
        eq_next = jnp.concatenate([eq_next, jnp.zeros(1, bool)])
        eq_prev = jnp.concatenate([jnp.zeros(1, bool), eq_next[:-1]])
        not_mid = ~(eq_next & eq_prev)
        pair2 = eq_next & not_mid & jnp.roll(not_mid, -1)
        gslot = slot[order]                            # global face slots
        partner = jnp.where(
            pair2,
            jnp.roll(gslot, -1),
            jnp.where(jnp.roll(pair2, 1), jnp.roll(gslot, 1), -1),
        )
        # every hot face gets its new pairing (or -1: became boundary);
        # cold faces keep their rows — their partner cannot have changed
        adja_flat = m.adja.reshape(-1).at[
            _common.unique_oob(gslot >= 0, gslot, tc * 4)
        ].set(partner, mode="drop", unique_indices=True)
        adja = jnp.where(
            m.tmask[:, None], adja_flat.reshape(tc, 4), -1
        )
        return m.replace(adja=adja)

    n_hot = jnp.sum(
        (
            changed_v[mesh.tet[:, FACE_VERTS]].all(axis=-1)
            & mesh.tmask[:, None]
        ).astype(jnp.int32)
    )
    return jax.lax.cond(n_hot > K, _full, _incr, mesh)


# parmmg-lint: disable=PML005 -- table query/update only: the caller keeps using the mesh; the big tables are rebuilt functionally inside a lax.cond (donation would be dropped by the cond anyway)
@partial(jax.jit, static_argnames=("K",))
def merge_unique_edges(
    mesh: Mesh,
    changed_v: jax.Array,
    edges: jax.Array,
    emask: jax.Array,
    t2e: jax.Array,
    n_unique,
    K: int,
):
    """GENERAL incremental merge of a `unique_edges` table after
    arbitrary topology deltas with STABLE numbering (no compaction since
    the table was built): split bisections, collapse deletions and both
    swap flavors may have rewritten, appended or killed tets in the hot
    region, as long as `changed_v` covers every vertex of every tet row
    created, deleted or rewritten since the build (the operators'
    `changed_v` contract — see the module note above). Replaces the
    former append-only extension (`append_unique_edges`), which bailed
    to a full re-sort on any edge deletion.

    The delta is applied as tombstone + slot reclamation:

      * hot tets (all 4 vertices in `changed_v`, live) are gathered into
        a K-compacted stream and their 6 edges recomputed and matched
        against the live table;
      * every pre-existing edge slot is kept alive iff some live tet
        still references it — cold live tets via their (unchanged) `t2e`
        rows, hot tets via the fresh matches. A destroyed edge's slot is
        tombstoned (`emask` cleared) in the same pass;
      * unmatched hot pairs are deduplicated among themselves and each
        representative takes a reclaimed (tombstoned or never-used)
        slot, so tombstones never accumulate — the compaction is the
        slot free-list itself and the table needs no separate cursor;
      * dead tets' `t2e` rows are cleared; hot live rows are rewritten;
        cold rows are untouched (their references cannot have changed).

    Exactness: recomputing a hot-but-unmodified tet re-matches its old
    slots, and an edge with any endpoint outside `changed_v` belongs
    only to unmodified tets (a modified tet marks ALL its vertices), so
    its slot keeps cold references and survives untouched. Falls back to
    the exact full re-sort via `lax.cond` when the hot stream overflows
    K or the worst-case fresh count could overflow the capacity.
    Returns (edges, emask, t2e, n_unique) with `n_unique` = live edge
    count (int32)."""
    from ..ops import common as _common

    tc = mesh.tcap
    ecap = edges.shape[0]
    hot_t = (
        changed_v[mesh.tet].all(axis=-1) & mesh.tmask
    )

    def _full(_):
        e, em, t2, nu = unique_edges(mesh, ecap)
        return e, em, t2, jnp.asarray(nu, jnp.int32)

    def _incr(_):
        # dead tets lose their rows; cold live rows are authoritative
        cold = mesh.tmask & ~hot_t
        t2e_base = jnp.where(mesh.tmask[:, None], t2e, -1)
        # surviving references from OUTSIDE the hot region: one linear
        # scatter-add over the cold rows (no sort — the whole point)
        cold_idx = jnp.where(
            cold[:, None] & (t2e >= 0), t2e, ecap
        ).astype(jnp.int32)
        cnt = jnp.zeros(ecap, jnp.int32).at[cold_idx.reshape(-1)].add(
            1, mode="drop"
        )
        # K-compacted hot stream: recompute each hot tet's 6 edges
        rank = jnp.cumsum(hot_t.astype(jnp.int32)) - 1
        tgt = _common.unique_oob(hot_t & (rank < K), rank, K)
        tslot = jnp.full(K, -1, jnp.int32).at[tgt].set(
            jnp.arange(tc, dtype=jnp.int32), mode="drop",
            unique_indices=True,
        )
        valid = tslot >= 0
        ev = mesh.tet[jnp.maximum(tslot, 0)][:, EDGE_VERTS]  # [K,6,2]
        lo = jnp.minimum(ev[..., 0], ev[..., 1]).reshape(-1)
        hi = jnp.maximum(ev[..., 0], ev[..., 1]).reshape(-1)
        live = jnp.broadcast_to(valid[:, None], (K, 6)).reshape(-1)
        # match against the LIVE pre-merge slots (tombstoned/stale rows
        # never match); a matched slot is referenced hot, so it survives
        q = jnp.stack(
            [jnp.where(live, lo, -1), jnp.where(live, hi, -1)], axis=1
        )
        old_keys = jnp.where(emask[:, None], edges, -1)
        eid = _common.match_rows(old_keys, q, bound=mesh.pcap)
        matched = live & (eid >= 0)
        cnt = cnt.at[jnp.where(matched, eid, ecap)].add(1, mode="drop")
        # tombstone: a pre-existing slot lives iff still referenced
        alive_old = emask & (cnt > 0)
        # fresh pairs: dedup among themselves; live groups sort ahead of
        # the shared dead sentinel, so their gids are dense
        fresh = live & (eid < 0)
        order, newgrp, live_s, slo, shi = _common.sorted_pair_groups(
            lo, hi, ~fresh, mesh.pcap
        )
        gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
        first = newgrp & live_s
        # reclaimed-slot map: slot_of[j] = the j-th free slot (free =
        # tombstoned this merge or never used). The fallback predicate
        # guarantees n_new <= free count, so every representative lands.
        free = ~alive_old
        free_pos = jnp.cumsum(free.astype(jnp.int32)) - 1
        slot_of = jnp.full(ecap, ecap, jnp.int32).at[
            _common.unique_oob(free, free_pos, ecap)
        ].set(jnp.arange(ecap, dtype=jnp.int32), mode="drop",
              unique_indices=True)
        rep_slot = slot_of[jnp.clip(gid, 0, ecap - 1)]
        rep_tgt = _common.unique_oob(
            first & (rep_slot < ecap), rep_slot, ecap
        )
        kw = dict(mode="drop", unique_indices=True)
        edges_out = edges.at[rep_tgt, 0].set(slo.astype(jnp.int32), **kw)
        edges_out = edges_out.at[rep_tgt, 1].set(shi.astype(jnp.int32),
                                                 **kw)
        emask_out = alive_old.at[rep_tgt].set(True, **kw)
        # per-row final edge slot: matched -> surviving old slot, fresh
        # -> its group's reclaimed slot (sorted gids back to row order)
        gid_rows = jnp.zeros(K * 6, jnp.int32).at[order].set(
            gid, unique_indices=True
        )
        eid_final = jnp.where(
            fresh, slot_of[jnp.clip(gid_rows, 0, ecap - 1)], eid
        )
        eid_final = jnp.where(
            live & (eid_final >= 0) & (eid_final < ecap), eid_final, -1
        ).astype(jnp.int32)
        t2e_out = _common.scatter_rows(
            t2e_base, _common.unique_oob(valid, tslot, tc),
            eid_final.reshape(K, 6), unique=True,
        )
        # int32 even under x64 (jnp.sum promotes): the frontier conds
        # demand identical branch dtypes against the stored int32 tables
        return edges_out, emask_out, t2e_out, jnp.sum(
            emask_out.astype(jnp.int32)
        ).astype(jnp.int32)

    n_hot = jnp.sum(hot_t.astype(jnp.int32))
    # worst case each hot tet introduces 6 fresh edges; free slots are
    # at least ecap - n_unique (live count), so this bound also covers
    # the reclaimed-slot placement above
    fallback = (n_hot > K) | (
        jnp.asarray(n_unique, jnp.int32) + 6 * n_hot > ecap
    )
    return jax.lax.cond(fallback, _full, _incr, 0)
