from . import adjacency, mesh, metric, tags  # noqa: F401
