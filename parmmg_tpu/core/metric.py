"""Metric-tensor algebra: iso/aniso sizes, metric lengths, means, gradation.

Covers the metric math the reference delegates to Mmg (length/quality in a
metric, `MMG5_interp4barintern`-style log-Euclidean tensor interpolation)
plus metric construction from target sizes (`MMG3D_Set_constantSize` /
`MMG3D_doSol` analogs used at reference `src/libparmmg.c:155-166`).

An isotropic metric is stored as the size h itself ([...,1]); the implied
tensor is (1/h^2) I. Anisotropic metrics are 6-vectors (m11,m12,m13,m22,
m23,m33) of an SPD 3x3 tensor M; the metric length of edge e is
sqrt(e^T M e), and the unit-mesh goal is length 1 for every edge.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# unit-edge thresholds of the "unit mesh" framework (standard in the
# anisotropic remeshing literature): split above SQRT2, collapse below
# 1/SQRT2 — same role as Mmg's long/short edge bounds.
LLONG = math.sqrt(2.0)
LSHRT = 1.0 / math.sqrt(2.0)


def sym6_to_mat(m6: jax.Array) -> jax.Array:
    """[...,6] -> [...,3,3] symmetric."""
    m11, m12, m13, m22, m23, m33 = jnp.moveaxis(m6, -1, 0)
    row0 = jnp.stack([m11, m12, m13], -1)
    row1 = jnp.stack([m12, m22, m23], -1)
    row2 = jnp.stack([m13, m23, m33], -1)
    return jnp.stack([row0, row1, row2], -2)


def mat_to_sym6(m: jax.Array) -> jax.Array:
    return jnp.stack(
        [m[..., 0, 0], m[..., 0, 1], m[..., 0, 2], m[..., 1, 1], m[..., 1, 2], m[..., 2, 2]],
        -1,
    )


def iso_to_sym6(h: jax.Array) -> jax.Array:
    """[...,1] iso size -> [...,6] tensor (1/h^2) I."""
    lam = 1.0 / (h[..., 0] ** 2)
    z = jnp.zeros_like(lam)
    return jnp.stack([lam, z, z, lam, z, lam], -1)


def edge_length_iso(p0, p1, h0, h1, eps=1e-30):
    """Metric length of edge under iso sizes at endpoints: the standard
    harmonic-style approximation  |e| * (1/h0 + 1/h1) / 2  (exact for the
    linear-interpolated 1/h integrand)."""
    d = jnp.linalg.norm(p1 - p0, axis=-1)
    return d * 0.5 * (1.0 / jnp.maximum(h0[..., 0], eps) + 1.0 / jnp.maximum(h1[..., 0], eps))


def edge_length_aniso(p0, p1, m0, m1):
    """Metric length under endpoint tensors: average of the two endpoint
    measures, ( sqrt(e^T M0 e) + sqrt(e^T M1 e) ) / 2."""
    e = p1 - p0
    M0, M1 = sym6_to_mat(m0), sym6_to_mat(m1)
    q0 = jnp.einsum("...i,...ij,...j->...", e, M0, e)
    q1 = jnp.einsum("...i,...ij,...j->...", e, M1, e)
    return 0.5 * (jnp.sqrt(jnp.maximum(q0, 0.0)) + jnp.sqrt(jnp.maximum(q1, 0.0)))


def edge_length(p0, p1, met0, met1):
    if met0.shape[-1] == 1:
        return edge_length_iso(p0, p1, met0, met1)
    return edge_length_aniso(p0, p1, met0, met1)


def _sym_eigh(m6: jax.Array):
    return jnp.linalg.eigh(sym6_to_mat(m6))


def log_sym6(m6: jax.Array, eps=1e-30) -> jax.Array:
    w, v = _sym_eigh(m6)
    lw = jnp.log(jnp.maximum(w, eps))
    return mat_to_sym6(jnp.einsum("...ik,...k,...jk->...ij", v, lw, v))


def exp_sym6(m6: jax.Array) -> jax.Array:
    w, v = _sym_eigh(m6)
    return mat_to_sym6(jnp.einsum("...ik,...k,...jk->...ij", v, jnp.exp(w), v))


def interp_metric(mets: jax.Array, bary: jax.Array) -> jax.Array:
    """Barycentric metric interpolation at a point.

    mets: [..., K, C] endpoint metrics (C = 1 or 6), bary: [..., K] weights.
    Iso: harmonic-in-1/h (linear in 1/h, consistent with edge_length_iso).
    Aniso: log-Euclidean mean, the smooth SPD-preserving analog of the
    reference's `MMG5_interp4barintern` path (`src/interpmesh_pmmg.c:247`).
    """
    if mets.shape[-1] == 1:
        inv = jnp.sum(bary[..., None] / jnp.maximum(mets, 1e-30), axis=-2)
        return 1.0 / jnp.maximum(inv, 1e-30)
    logs = log_sym6(mets)
    mean = jnp.sum(bary[..., None] * logs, axis=-2)
    return exp_sym6(mean)


def metric_det(met: jax.Array) -> jax.Array:
    """det(M): [...,1] iso -> h^-6 ; [...,6] aniso -> det of tensor."""
    if met.shape[-1] == 1:
        return 1.0 / jnp.maximum(met[..., 0] ** 6, 1e-30)
    m11, m12, m13, m22, m23, m33 = jnp.moveaxis(met, -1, 0)
    return (
        m11 * (m22 * m33 - m23 * m23)
        - m12 * (m12 * m33 - m23 * m13)
        + m13 * (m12 * m23 - m22 * m13)
    )


def constant_iso_metric(npoints_cap: int, hsiz: float, dtype=jnp.float32):
    """`-hsiz` constant-size metric (MMG3D_Set_constantSize analog)."""
    return jnp.full((npoints_cap, 1), hsiz, dtype)


def implied_iso_metric(vert, tet, tmask, pcap, clip=(1e-30, 1e30)):
    """Per-vertex size implied by the current mesh: mean length of incident
    edges (the `MMG3D_doSol` analog used for `-optim` mode)."""
    from .mesh import EDGE_VERTS

    ev = tet[:, EDGE_VERTS]  # [T,6,2]
    p0 = vert[ev[..., 0]]
    p1 = vert[ev[..., 1]]
    d = jnp.linalg.norm(p1 - p0, axis=-1)  # [T,6]
    d = jnp.where(tmask[:, None], d, 0.0)
    w = jnp.where(tmask[:, None], jnp.ones_like(d), 0.0)
    acc = jnp.zeros(pcap, vert.dtype)
    cnt = jnp.zeros(pcap, vert.dtype)
    for k in (0, 1):
        acc = acc.at[ev[..., k].reshape(-1)].add(d.reshape(-1), mode="drop")
        cnt = cnt.at[ev[..., k].reshape(-1)].add(w.reshape(-1), mode="drop")
    h = acc / jnp.maximum(cnt, 1.0)
    h = jnp.where(cnt > 0, h, 1.0)
    return jnp.clip(h, *clip)[:, None]


def implied_aniso_metric(vert, tet, tmask, pcap, ratio_max: float = 4.0):
    """Per-vertex tensor implied by the current mesh (`-A` without a
    metric file: Mmg's `MMG3D_doSol_ani` role, forwarded by the reference
    at `src/libparmmg_tools.c:142` via `PMMG_IPARAM_anisosize`).

    Least-squares fit of M so that every incident tet edge has unit
    metric length (e^T M e = 1): accumulate the normal equations
    N = sum r r^T, rhs = sum r with r(e) the sym6 quadratic-form row,
    solve per vertex, then project to SPD with eigenvalues clamped to a
    `ratio_max` band around the isotropic implied size."""
    from .mesh import EDGE_VERTS

    ev = tet[:, EDGE_VERTS].reshape(-1, 2)  # [6T, 2]
    live = jnp.repeat(tmask, 6)
    e = vert[ev[:, 1]] - vert[ev[:, 0]]
    ex, ey, ez = e[:, 0], e[:, 1], e[:, 2]
    # sym6 order (m11, m12, m13, m22, m23, m33)
    r = jnp.stack(
        [ex * ex, 2 * ex * ey, 2 * ex * ez, ey * ey, 2 * ey * ez, ez * ez],
        axis=-1,
    )
    rr = r[:, :, None] * r[:, None, :]  # [6T, 6, 6]
    w = live.astype(vert.dtype)
    N = jnp.zeros((pcap, 6, 6), vert.dtype)
    rhs = jnp.zeros((pcap, 6), vert.dtype)
    for k in (0, 1):
        idx = jnp.where(live, ev[:, k], pcap)
        N = N.at[idx].add(rr * w[:, None, None], mode="drop")
        rhs = rhs.at[idx].add(r * w[:, None], mode="drop")
    # ridge regularization keeps rank-deficient stars (boundary fans,
    # vertices with <6 distinct edge directions) solvable
    tr = jnp.trace(N, axis1=-2, axis2=-1)
    N = N + (1e-6 * jnp.maximum(tr, 1e-30) / 6.0)[:, None, None] * jnp.eye(
        6, dtype=vert.dtype
    )
    m6 = jnp.linalg.solve(N, rhs[..., None])[..., 0]
    # SPD projection, eigenvalues within ratio_max of the iso implied size
    h_iso = implied_iso_metric(vert, tet, tmask, pcap)[:, 0]
    lam_mid = 1.0 / jnp.maximum(h_iso, 1e-30) ** 2
    lo = lam_mid / ratio_max**2
    hi = lam_mid * ratio_max**2
    wv, v = _sym_eigh(m6)
    wv = jnp.clip(wv, lo[:, None], hi[:, None])
    out = mat_to_sym6(jnp.einsum("...ik,...k,...jk->...ij", v, wv, v))
    return jnp.where(
        jnp.isfinite(out).all(-1, keepdims=True), out,
        iso_to_sym6(h_iso[:, None]),
    )


def apply_hbounds(met: jax.Array, hmin: float | None, hmax: float | None):
    """Clamp metric sizes into [hmin, hmax] (iso: clamp h; aniso: clamp
    eigenvalues into [hmax^-2, hmin^-2])."""
    if hmin is None and hmax is None:
        return met
    hmin = 0.0 if hmin is None else hmin
    hmax = jnp.inf if hmax is None else hmax
    if met.shape[-1] == 1:
        return jnp.clip(met, hmin, hmax)
    w, v = _sym_eigh(met)
    lo = jnp.where(jnp.isinf(hmax), 0.0, 1.0 / hmax**2)
    hi = jnp.where(hmin <= 0.0, jnp.inf, 1.0 / jnp.maximum(hmin, 1e-30) ** 2)
    w = jnp.clip(w, lo, hi)
    return mat_to_sym6(jnp.einsum("...ik,...k,...jk->...ij", v, w, v))


def gradate_iso(
    vert, met, edges, emask, niter: int = 20, hgrad: float = 1.3,
    fixed=None,
):
    """Metric gradation: limit the ratio of sizes across each edge so that
    h grows at most geometrically with metric distance (Mmg's `-hgrad`;
    reference forwards it at `src/libparmmg_tools.c` -hgrad). Iterative
    edge relaxation: h_b <- min(h_b, h_a + (hgrad-1) * l_ab_euclid).

    `fixed` ([PC] bool, optional) marks vertices whose size must not be
    modified — the propagation *from required entities* mode of
    `-hgradreq` (Mmg `MMG3D_gradsizreq`): pass the REQUIRED vertex mask
    and the required sizes win while everything else relaxes."""
    loghg = jnp.log(hgrad)

    def body(_, h):
        a, b = edges[:, 0], edges[:, 1]
        d = jnp.linalg.norm(vert[b] - vert[a], axis=-1)
        ha, hb = h[a, 0], h[b, 0]
        # cap each end by the other end grown along the edge
        cap_b = ha * jnp.exp(loghg * d / jnp.maximum(ha, 1e-30))
        cap_a = hb * jnp.exp(loghg * d / jnp.maximum(hb, 1e-30))
        nb = jnp.where(emask, jnp.minimum(hb, cap_b), hb)
        na = jnp.where(emask, jnp.minimum(ha, cap_a), ha)
        h = h.at[b, 0].min(nb, mode="drop")
        h = h.at[a, 0].min(na, mode="drop")
        if fixed is not None:
            h = jnp.where(fixed[:, None], met, h)
        return h

    return jax.lax.fori_loop(0, niter, body, met)


def gradate_from_required(
    vert, met, edges, emask, req, niter: int = 20, hgrad: float = 1.3
):
    """`-hgradreq` (Mmg `MMG3D_gradsizreq`): sizes propagate FROM
    required vertices only — required sizes are authoritative and cap
    their (transitive) neighborhoods at the hgradreq ratio; vertices
    with no required entity in reach are untouched (with no required
    vertices at all this is a no-op, unlike a plain gradation pass).

    Implementation: an auxiliary field g starts at the required sizes
    (+inf elsewhere) and relaxes along edges like gradate_iso; the final
    size is min(h, g) off the required set. Aniso metrics propagate
    their smallest directional size and are scaled finer by the
    violation factor (scalar cap, conservative like gradate_aniso)."""
    a, b = edges[:, 0], edges[:, 1]
    d = jnp.linalg.norm(vert[b] - vert[a], axis=-1)
    loghg = jnp.log(hgrad)
    inf = jnp.asarray(jnp.inf, vert.dtype)
    if met.shape[-1] == 1:
        h = met[:, 0]
    else:
        # smallest directional size: 1/sqrt(lambda_max)
        w, _ = _sym_eigh(met)
        h = 1.0 / jnp.sqrt(jnp.maximum(w[..., -1], 1e-30))
    g0 = jnp.where(req, h, inf)

    def body(_, g):
        ga, gb = g[a], g[b]
        cap_b = jnp.where(
            jnp.isfinite(ga),
            ga * jnp.exp(loghg * d / jnp.maximum(ga, 1e-30)), inf,
        )
        cap_a = jnp.where(
            jnp.isfinite(gb),
            gb * jnp.exp(loghg * d / jnp.maximum(gb, 1e-30)), inf,
        )
        g = g.at[b].min(jnp.where(emask, cap_b, inf), mode="drop")
        g = g.at[a].min(jnp.where(emask, cap_a, inf), mode="drop")
        return g

    g = jax.lax.fori_loop(0, niter, body, g0)
    reached = jnp.isfinite(g) & ~req
    if met.shape[-1] == 1:
        capped = jnp.minimum(met[:, 0], g)
        return jnp.where(reached, capped, met[:, 0])[:, None]
    # aniso: scale the tensor finer by (h/g)^2 where the cap is violated
    f = jnp.where(reached & (g < h), (h / jnp.maximum(g, 1e-30)) ** 2, 1.0)
    return met * f[:, None]


def _max_geneig(M: jax.Array, G: jax.Array) -> jax.Array:
    """Largest generalized eigenvalue lambda of G v = lambda M v for
    batched SPD 3x3 M: eigvals of L^-1 G L^-T with M = L L^T."""
    L = jnp.linalg.cholesky(M)
    Z = jax.lax.linalg.triangular_solve(
        L, G, left_side=True, lower=True, transpose_a=False
    )
    Y = jax.lax.linalg.triangular_solve(
        L, jnp.swapaxes(Z, -1, -2), left_side=True, lower=True,
        transpose_a=False,
    )
    w = jnp.linalg.eigvalsh(0.5 * (Y + jnp.swapaxes(Y, -1, -2)))
    return w[..., -1]


def gradate_aniso(
    vert, met, edges, emask, niter: int = 8, hgrad: float = 1.3,
    fixed=None,
):
    """Anisotropic metric gradation (the `-hgrad` control Mmg applies via
    `MMG3D_gradsiz_ani`; the reference forwards hgrad for aniso runs at
    `src/libparmmg_tools.c`). Log-space capping along edges:

    For edge (a,b), the metric seen from a grown along the edge is
    G_a = M_a * hgrad^(-2 l_ab) (all sizes coarsened by hgrad^l, l = the
    metric length of the edge). If M_b is coarser than G_a in any
    direction — largest generalized eigenvalue f = lam_max(M_b^-1 G_a)
    exceeds 1 — M_b is scaled up (made finer) by f. The scalar cap makes
    the bound direction-uniform (slightly conservative vs Mmg's
    per-direction simultaneous reduction) but keeps the combine over
    concurrent neighbor updates a scatter-max, which is what the TPU
    needs. Jacobi-iterated to propagate across the mesh.
    """
    loghg = jnp.log(hgrad)
    a, b = edges[:, 0], edges[:, 1]
    pcap = met.shape[0]
    e = vert[b] - vert[a]

    def body(_, m6):
        Ma = sym6_to_mat(m6[a])
        Mb = sym6_to_mat(m6[b])
        la = jnp.sqrt(jnp.maximum(
            jnp.einsum("...i,...ij,...j->...", e, Ma, e), 0.0
        ))
        lb = jnp.sqrt(jnp.maximum(
            jnp.einsum("...i,...ij,...j->...", e, Mb, e), 0.0
        ))
        Ga = Ma * jnp.exp(-2.0 * la * loghg)[..., None, None]
        Gb = Mb * jnp.exp(-2.0 * lb * loghg)[..., None, None]
        fb = _max_geneig(Mb, Ga)   # how much finer b must get
        fa = _max_geneig(Ma, Gb)
        logfb = jnp.log(jnp.maximum(fb, 1.0))
        logfa = jnp.log(jnp.maximum(fa, 1.0))
        ok = emask
        logf = jnp.zeros(pcap, m6.dtype)
        logf = logf.at[jnp.where(ok, b, pcap)].max(
            jnp.where(jnp.isfinite(logfb), logfb, 0.0), mode="drop"
        )
        logf = logf.at[jnp.where(ok, a, pcap)].max(
            jnp.where(jnp.isfinite(logfa), logfa, 0.0), mode="drop"
        )
        out = m6 * jnp.exp(logf)[:, None]
        if fixed is not None:
            out = jnp.where(fixed[:, None], met, out)
        return out

    return jax.lax.fori_loop(0, niter, body, met)
