"""Flat structure-of-arrays tetrahedral mesh on device.

TPU-native replacement for the reference's pointer-based mesh data model
(`PMMG_Grp` wrapping `MMG5_Mesh`/`MMG5_Sol`, reference
`src/libparmmgtypes.h:286-307`). Where Mmg stores linked entity arrays with
1-based indices, EOK flags and side xpoint/xtetra structures, we store fixed
capacity, 0-based flat arrays with validity masks — the shape XLA needs for
batched kernels. Capacities are static (recompile on growth-bucket change);
live counts are dynamic scalars derived from masks.

Conventions:
 - vertex/tet/tria/edge slots are valid iff the corresponding mask bit is set;
   invalid slots may contain arbitrary data and must never be dereferenced
   unmasked.
 - `tet[:, i]` is the vertex opposite to local face `i` (standard simplex
   numbering, same convention the reference inherits from Mmg).
 - `adja[t, f] = 4*t2 + f2` encodes that face `f` of tet `t` is glued to face
   `f2` of tet `t2`; `-1` marks a boundary (or unmatched) face. This is the
   flat analog of Mmg's `adja` built by `MMG3D_hashTetra`.
 - metric `met` has 1 component (isotropic size h) or 6 (upper-triangular
   symmetric 3x3 anisotropic metric, order m11,m12,m13,m22,m23,m33 — matching
   the Medit SolAtVertices symmetric-tensor layout).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import tags

# local face f of a tet is the triple of vertex slots != f, oriented so that
# the normal points outward for a positively oriented tet.
FACE_VERTS = np.array(
    [[1, 2, 3], [0, 3, 2], [0, 1, 3], [0, 2, 1]], dtype=np.int32
)
# the 6 edges of a tet as local vertex-slot pairs.
EDGE_VERTS = np.array(
    [[0, 1], [0, 2], [0, 3], [1, 2], [1, 3], [2, 3]], dtype=np.int32
)


def _pad2(a: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full((cap,) + a.shape[1:], fill, dtype=a.dtype)
    out[: a.shape[0]] = a
    return out


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Mesh:
    """One shard's worth of mesh, as a JAX pytree of fixed-capacity arrays."""

    # vertices
    vert: jax.Array   # [PC, 3] float coords
    vref: jax.Array   # [PC] int32 reference
    vtag: jax.Array   # [PC] int32 tag bitfield (tags.py)
    vmask: jax.Array  # [PC] bool validity
    # tetrahedra
    tet: jax.Array    # [TC, 4] int32 vertex ids
    tref: jax.Array   # [TC] int32
    tmask: jax.Array  # [TC] bool
    adja: jax.Array   # [TC, 4] int32, 4*neighbor+face or -1
    # boundary triangles
    tria: jax.Array   # [FC, 3] int32 vertex ids
    trref: jax.Array  # [FC] int32
    trtag: jax.Array  # [FC] int32
    trmask: jax.Array  # [FC] bool
    # feature edges (ridges / required edges)
    edge: jax.Array   # [EC, 2] int32 vertex ids
    edref: jax.Array  # [EC] int32
    edtag: jax.Array  # [EC] int32
    edmask: jax.Array  # [EC] bool
    # vertex-attached solutions
    met: jax.Array    # [PC, 1|6] metric (all-ones when unset)
    ls: jax.Array     # [PC, 0|1] level-set
    disp: jax.Array   # [PC, 0|3] displacement
    fields: jax.Array  # [PC, K] concatenated user fields
    # global vertex id (-1 = no global identity yet, e.g. vertices created
    # by remeshing before the next global-numbering pass). Carried inside
    # the mesh so compaction renumbers it consistently — the role of the
    # reference's global node numbering (src/libparmmg.c:923)
    vglob: jax.Array = None  # [PC] int32
    field_ncomp: Tuple[int, ...] = dataclasses.field(
        default=(), metadata=dict(static=True)
    )

    def __post_init__(self):
        # a None data leaf would give this pytree a different treedef than
        # from_numpy-built meshes (None = empty subtree), silently breaking
        # tree_map/stacking — fail fast instead
        if self.vglob is None:
            raise TypeError(
                "Mesh.vglob is required (int32 [PC], -1 where unset); "
                "build meshes via Mesh.from_numpy or pass vglob explicitly"
            )
    # whether `met` holds a user-prescribed metric (vs. the all-ones fill);
    # an explicit flag, not value sniffing — a legitimate uniform h=1.0
    # metric must not be mistaken for "unset"
    met_set: bool = dataclasses.field(default=False, metadata=dict(static=True))

    # --- capacities (static) ---------------------------------------------
    @property
    def pcap(self) -> int:
        return self.vert.shape[0]

    @property
    def tcap(self) -> int:
        return self.tet.shape[0]

    @property
    def fcap(self) -> int:
        return self.tria.shape[0]

    @property
    def ecap(self) -> int:
        return self.edge.shape[0]

    @property
    def dtype(self):
        return self.vert.dtype

    # --- dynamic counts ---------------------------------------------------
    @property
    def npoin(self) -> jax.Array:
        return jnp.sum(self.vmask.astype(jnp.int32))

    @property
    def ntet(self) -> jax.Array:
        return jnp.sum(self.tmask.astype(jnp.int32))

    @property
    def ntria(self) -> jax.Array:
        return jnp.sum(self.trmask.astype(jnp.int32))

    @property
    def nedge(self) -> jax.Array:
        return jnp.sum(self.edmask.astype(jnp.int32))

    @property
    def aniso(self) -> bool:
        return self.met.shape[1] == 6

    # --- constructors -----------------------------------------------------
    @staticmethod
    def from_numpy(
        verts: np.ndarray,
        tets: np.ndarray,
        *,
        vrefs: np.ndarray | None = None,
        trefs: np.ndarray | None = None,
        trias: np.ndarray | None = None,
        trrefs: np.ndarray | None = None,
        edges: np.ndarray | None = None,
        edrefs: np.ndarray | None = None,
        vtags: np.ndarray | None = None,
        trtags: np.ndarray | None = None,
        edtags: np.ndarray | None = None,
        met: np.ndarray | None = None,
        ls: np.ndarray | None = None,
        disp: np.ndarray | None = None,
        fields: np.ndarray | None = None,
        field_ncomp: Tuple[int, ...] = (),
        vglob: np.ndarray | None = None,
        pcap: int | None = None,
        tcap: int | None = None,
        fcap: int | None = None,
        ecap: int | None = None,
        headroom: float = 1.5,
        dtype=jnp.float32,
    ) -> "Mesh":
        """Build a device Mesh from 0-based numpy arrays, padding to capacity.

        `headroom` sizes capacities relative to current counts so remeshing
        has room to grow before a host-side rebucket (the capacity-planning
        analog of the reference's memory budgeting in `src/zaldy_pmmg.c`).
        """
        npo, nte = len(verts), len(tets)
        trias = np.zeros((0, 3), np.int32) if trias is None else trias
        edges = np.zeros((0, 2), np.int32) if edges is None else edges
        ntr, ned = len(trias), len(edges)

        def cap(n, c, lo=8):
            return int(c) if c is not None else max(lo, int(np.ceil(n * headroom)))

        pc, tc = cap(npo, pcap), cap(nte, tcap)
        fc, ec = cap(ntr, fcap, lo=8), cap(ned, ecap, lo=8)

        def ints(n, given):
            if given is None:
                return np.zeros(n, np.int32)
            out = np.asarray(given, np.int32)
            if out.shape[0] != n:
                raise ValueError(
                    f"attribute length {out.shape[0]} != entity count {n}"
                )
            return out

        verts = np.asarray(verts, np.float64)
        mcomp = 1 if met is None else np.asarray(met).reshape(npo, -1).shape[1]
        if mcomp not in (1, 6):
            raise ValueError(f"metric must have 1 or 6 components, got {mcomp}")
        met_np = (
            np.ones((npo, 1)) if met is None else np.asarray(met, np.float64).reshape(npo, mcomp)
        )
        ls_np = np.zeros((npo, 0)) if ls is None else np.asarray(ls, np.float64).reshape(npo, -1)
        disp_np = (
            np.zeros((npo, 0)) if disp is None else np.asarray(disp, np.float64).reshape(npo, -1)
        )
        f_np = (
            np.zeros((npo, 0))
            if fields is None
            else np.asarray(fields, np.float64).reshape(npo, -1)
        )

        mesh = Mesh(
            vert=jnp.asarray(_pad2(verts, pc, 0.0), dtype),
            vref=jnp.asarray(_pad2(ints(npo, vrefs), pc, 0)),
            vtag=jnp.asarray(_pad2(ints(npo, vtags), pc, 0)),
            vmask=jnp.asarray(_pad2(np.ones(npo, bool), pc, False)),
            tet=jnp.asarray(_pad2(np.asarray(tets, np.int32), tc, 0)),
            tref=jnp.asarray(_pad2(ints(nte, trefs), tc, 0)),
            tmask=jnp.asarray(_pad2(np.ones(nte, bool), tc, False)),
            adja=jnp.full((tc, 4), -1, jnp.int32),
            tria=jnp.asarray(_pad2(np.asarray(trias, np.int32), fc, 0)),
            trref=jnp.asarray(_pad2(ints(ntr, trrefs), fc, 0)),
            trtag=jnp.asarray(_pad2(ints(ntr, trtags), fc, 0)),
            trmask=jnp.asarray(_pad2(np.ones(ntr, bool), fc, False)),
            edge=jnp.asarray(_pad2(np.asarray(edges, np.int32), ec, 0)),
            edref=jnp.asarray(_pad2(ints(ned, edrefs), ec, 0)),
            edtag=jnp.asarray(_pad2(ints(ned, edtags), ec, 0)),
            edmask=jnp.asarray(_pad2(np.ones(ned, bool), ec, False)),
            met=jnp.asarray(_pad2(met_np, pc, 1.0), dtype),
            ls=jnp.asarray(_pad2(ls_np, pc, 0.0), dtype),
            disp=jnp.asarray(_pad2(disp_np, pc, 0.0), dtype),
            fields=jnp.asarray(_pad2(f_np, pc, 0.0), dtype),
            vglob=jnp.asarray(
                _pad2(
                    np.full(npo, -1, np.int32)
                    if vglob is None
                    else np.asarray(vglob, np.int32),
                    pc,
                    -1,
                )
            ),
            field_ncomp=tuple(field_ncomp),
            met_set=met is not None,
        )
        return mesh

    def with_metric(self, met) -> "Mesh":
        """Attach a user metric (marks it as prescribed for the adapter)."""
        met = jnp.asarray(met, self.dtype)
        if met.shape[0] != self.pcap:
            raise ValueError(
                f"metric rows {met.shape[0]} != vertex capacity {self.pcap}"
            )
        return dataclasses.replace(self, met=met, met_set=True)

    # --- host-side extraction --------------------------------------------
    def to_numpy(self) -> dict:
        """Pull valid entities to host as compact 0-based numpy arrays.

        Vertex ids in tets/trias/edges are renumbered to the compacted
        vertex order (the host analog of the reference's `PMMG_packParMesh`).
        """
        vmask = np.asarray(self.vmask)
        tmask = np.asarray(self.tmask)
        trmask = np.asarray(self.trmask)
        edmask = np.asarray(self.edmask)
        new_id = np.cumsum(vmask) - 1  # old slot -> compact id
        out = dict(
            verts=np.asarray(self.vert)[vmask],
            vrefs=np.asarray(self.vref)[vmask],
            vtags=np.asarray(self.vtag)[vmask],
            tets=new_id[np.asarray(self.tet)[tmask]],
            trefs=np.asarray(self.tref)[tmask],
            trias=new_id[np.asarray(self.tria)[trmask]],
            trrefs=np.asarray(self.trref)[trmask],
            trtags=np.asarray(self.trtag)[trmask],
            edges=new_id[np.asarray(self.edge)[edmask]],
            edrefs=np.asarray(self.edref)[edmask],
            edtags=np.asarray(self.edtag)[edmask],
            met=np.asarray(self.met)[vmask],
            ls=np.asarray(self.ls)[vmask],
            disp=np.asarray(self.disp)[vmask],
            fields=np.asarray(self.fields)[vmask],
            vglob=np.asarray(self.vglob)[vmask],
            field_ncomp=self.field_ncomp,
        )
        return out

    # --- capacity management ---------------------------------------------
    def with_capacity(
        self,
        pcap: int | None = None,
        tcap: int | None = None,
        fcap: int | None = None,
        ecap: int | None = None,
    ) -> "Mesh":
        """Grow (never shrink below live data) capacities, host-side."""
        pc = max(self.pcap, pcap or 0)
        tc = max(self.tcap, tcap or 0)
        fc = max(self.fcap, fcap or 0)
        ec = max(self.ecap, ecap or 0)

        def grow(a, cap, fill):
            a = np.asarray(a)
            if a.shape[0] == cap:
                return jnp.asarray(a)
            return jnp.asarray(_pad2(a, cap, fill))

        return dataclasses.replace(
            self,
            vert=grow(self.vert, pc, 0.0),
            vref=grow(self.vref, pc, 0),
            vtag=grow(self.vtag, pc, 0),
            vmask=grow(self.vmask, pc, False),
            tet=grow(self.tet, tc, 0),
            tref=grow(self.tref, tc, 0),
            tmask=grow(self.tmask, tc, False),
            adja=grow(self.adja, tc, -1),
            tria=grow(self.tria, fc, 0),
            trref=grow(self.trref, fc, 0),
            trtag=grow(self.trtag, fc, 0),
            trmask=grow(self.trmask, fc, False),
            edge=grow(self.edge, ec, 0),
            edref=grow(self.edref, ec, 0),
            edtag=grow(self.edtag, ec, 0),
            edmask=grow(self.edmask, ec, False),
            met=grow(self.met, pc, 1.0),
            ls=grow(self.ls, pc, 0.0),
            disp=grow(self.disp, pc, 0.0),
            fields=grow(self.fields, pc, 0.0),
            vglob=grow(self.vglob, pc, -1),
        )

    def replace(self, **kw) -> "Mesh":
        return dataclasses.replace(self, **kw)


def tet_coords(mesh: Mesh) -> jax.Array:
    """[TC, 4, 3] coordinates of each tet's vertices (garbage where masked)."""
    return mesh.vert[mesh.tet]


def tet_volumes(mesh: Mesh) -> jax.Array:
    """Signed volumes of all tet slots ([TC], garbage where masked)."""
    c = tet_coords(mesh)
    d1, d2, d3 = c[:, 1] - c[:, 0], c[:, 2] - c[:, 0], c[:, 3] - c[:, 0]
    return jnp.einsum("ti,ti->t", jnp.cross(d1, d2), d3) / 6.0


def _compact_impl(mesh: Mesh, aux):
    """Shared compaction core; `aux` is an optional [PC] auxiliary
    vertex array (e.g. the frontier mask) remapped through the same
    renumbering (dropped vertices fall away, fill = zeros)."""
    # drop vertices not referenced by any valid tet/tria/edge and not REQUIRED
    pc = mesh.pcap
    used = jnp.zeros(pc, bool)
    used = used.at[mesh.tet.reshape(-1)].max(
        jnp.repeat(mesh.tmask, 4), mode="drop"
    )
    used = used.at[mesh.tria.reshape(-1)].max(
        jnp.repeat(mesh.trmask, 3), mode="drop"
    )
    used = used.at[mesh.edge.reshape(-1)].max(
        jnp.repeat(mesh.edmask, 2), mode="drop"
    )
    keep_v = mesh.vmask & (used | ((mesh.vtag & tags.REQUIRED) != 0))

    vpos = jnp.cumsum(keep_v.astype(jnp.int32)) - 1  # new id per old slot
    vnew = jnp.where(keep_v, vpos, 0).astype(jnp.int32)

    from ..ops import common as _common

    vidx = _common.unique_oob(keep_v, vpos, pc)  # dead -> distinct OOB

    def scat_v(a, fill):
        out = jnp.full_like(a, fill)
        return _common.scatter_rows(out, vidx, a, unique=True)

    def compact_ent(conn, mask, extras, fills):
        n = conn.shape[0]
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        idx = _common.unique_oob(mask, pos, n)
        new_conn = _common.scatter_rows(
            jnp.zeros_like(conn), idx, vnew[conn], unique=True
        )
        new_mask = jnp.zeros_like(mask).at[idx].set(
            mask, mode="drop", unique_indices=True
        )
        new_extras = tuple(
            _common.scatter_rows(jnp.full_like(e, f), idx, e, unique=True)
            for e, f in zip(extras, fills)
        )
        return new_conn, new_mask, new_extras

    tet, tmask, (tref,) = compact_ent(mesh.tet, mesh.tmask, (mesh.tref,), (0,))
    tria, trmask, (trref, trtag) = compact_ent(
        mesh.tria, mesh.trmask, (mesh.trref, mesh.trtag), (0, 0)
    )
    edge, edmask, (edref, edtag) = compact_ent(
        mesh.edge, mesh.edmask, (mesh.edref, mesh.edtag), (0, 0)
    )

    aux_out = None if aux is None else _common.scatter_rows(
        jnp.zeros_like(aux), vidx, aux, unique=True
    )
    return aux_out, mesh.replace(
        vert=scat_v(mesh.vert, 0.0),
        vref=scat_v(mesh.vref, 0),
        vtag=scat_v(mesh.vtag, 0),
        vmask=scat_v(keep_v, False),
        met=scat_v(mesh.met, 1.0),
        ls=scat_v(mesh.ls, 0.0),
        disp=scat_v(mesh.disp, 0.0),
        fields=scat_v(mesh.fields, 0.0),
        vglob=scat_v(mesh.vglob, -1),
        tet=tet,
        tmask=tmask,
        tref=tref,
        adja=jnp.full_like(mesh.adja, -1),
        tria=tria,
        trmask=trmask,
        trref=trref,
        trtag=trtag,
        edge=edge,
        edmask=edmask,
        edref=edref,
        edtag=edtag,
    )


@partial(jax.jit, donate_argnums=0)
def compact(mesh: Mesh) -> Mesh:
    """Compact valid entities to array prefixes and drop unreferenced
    vertices.

    Masked-compaction analog of the reference's pack step
    (`PMMG_packParMesh`, `src/libparmmg1.c:195`): scan-based renumbering
    in place of Mmg's serial in-place repacking.
    """
    return _compact_impl(mesh, None)[1]


@partial(jax.jit, donate_argnums=(0, 1))
def compact_aux(mesh: Mesh, aux: jax.Array):
    """`compact` that also remaps an auxiliary [PC] per-vertex array
    (the frontier active mask) through the same vertex renumbering.
    Returns (mesh, aux)."""
    aux_out, out = _compact_impl(mesh, aux)
    return out, aux_out
