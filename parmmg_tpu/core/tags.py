"""Entity tag bitfield and status constants.

TPU-native analog of the MG_* tag discipline used by the reference
(ParMmg `src/tag_pmmg.c:39-180` and the Mmg tag bits it manipulates).
Tags are carried as an int32 bitfield per vertex / triangle / tet-face so
that masked, vectorized kernels can test them with bitwise ops instead of
pointer-chased xpoint/xtetra side structures.
"""

from __future__ import annotations

import enum

# --- entity tag bits (vertices, edges, triangles) -------------------------
NOTAG = 0
REF = 1 << 0        # reference edge/vertex (feature line)
BDY = 1 << 1        # on the geometric boundary
RIDGE = 1 << 2      # ridge (sharp dihedral angle) entity
REQUIRED = 1 << 3   # required: must not be modified by remeshing
CORNER = 1 << 4     # corner vertex (singular point)
NOM = 1 << 5        # non-manifold entity
GEO = RIDGE         # alias: geometric ridge
PARBDY = 1 << 6     # on an inter-shard (parallel) interface — frozen
PARBDYBDY = 1 << 7  # parallel interface that is also a true boundary
OLDPARBDY = 1 << 8  # was a parallel interface at the previous iteration
NOSURF = 1 << 9     # required only because parallel, not user-required
#                     (internal-only: input readers never set it; split
#                     adds it, merge strips it together with the
#                     REQUIRED it marks as split-added)
OVERLAP = 1 << 10   # belongs to a halo/ghost overlap region
OPNBDY = 1 << 11    # open-boundary tria: internal surface with the same
#                     tet ref on both sides, preserved and adapted as a
#                     real surface in -opnbdy mode (the MG_OPNBDY role;
#                     reference PMMG_IPARAM_opnbdy, src/libparmmg.h:64,
#                     tag special case src/tag_pmmg.c:267)

# A vertex with any of these cannot be moved by smoothing:
IMMOVABLE = REQUIRED | CORNER | PARBDY
# A vertex with any of these cannot be deleted by collapse:
UNCOLLAPSIBLE = REQUIRED | CORNER | PARBDY | NOM


def pure_interface_tria(trtag):
    """Bool mask: tria is a *synthetic* parallel-interface face
    (PARBDY|NOSURF without PARBDYBDY) — an interior face of the global
    mesh materialized as frozen pseudo-boundary by the split, to be
    stripped again at merge. Works on numpy and jnp int arrays; the one
    definition shared by the checkpoint writer, the merge, and tests."""
    return (
        ((trtag & PARBDY) != 0)
        & ((trtag & NOSURF) != 0)
        & ((trtag & PARBDYBDY) == 0)
    )


class ReturnStatus(enum.IntEnum):
    """Graded failure model, mirroring the reference semantics
    (PMMG_SUCCESS / PMMG_LOWFAILURE / PMMG_STRONGFAILURE,
    reference `src/libparmmgtypes.h:45-66`): LOWFAILURE means the mesh is
    still conformal and savable; STRONGFAILURE means it is unusable."""

    SUCCESS = 0
    LOWFAILURE = 1
    STRONGFAILURE = 2


class RedistributionMode(enum.IntEnum):
    """Repartitioning strategies (reference `src/libparmmgtypes.h:173-228`)."""

    IFC_DISPLACEMENT = 0  # advancing-front interface displacement (default)
    GRAPH = 1             # graph/SFC-based repartitioning
    NONE = 2


class APIDistrib(enum.IntEnum):
    """Distributed-API input mode (faces or nodes interface description)."""

    UNSET = 0
    FACES = 1
    NODES = 2
