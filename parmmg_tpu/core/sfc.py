"""Space-filling-curve (Morton) keys for spatial seeding and partitioning.

The reference partitions via Metis k-way graph partitioning
(`src/metis_pmmg.c`, `PMMG_part_meshElts2metis:1271`); the TPU-native design
replaces the graph library with Morton keys of tet barycenters + a prefix-sum
split into contiguous key ranges — fully on device, no host graph build.
The same keys provide cache-friendly renumbering (the Scotch role,
reference `src/libparmmg1.c:468-535`) and walk-seed locality for point
location (`src/locate_pmmg.c` warm starts under USE_POINTMAP).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MORTON_BITS = 10  # 10 bits/axis -> 30-bit keys, fits int32


def _spread3(x: jax.Array) -> jax.Array:
    """Spread the low 10 bits of x so consecutive bits land 3 apart."""
    x = x & 0x3FF
    x = (x | (x << 16)) & 0x030000FF
    x = (x | (x << 8)) & 0x0300F00F
    x = (x | (x << 4)) & 0x030C30C3
    x = (x | (x << 2)) & 0x09249249
    return x


def morton3d(ix: jax.Array, iy: jax.Array, iz: jax.Array) -> jax.Array:
    """Interleave three 10-bit integer coords into a 30-bit Morton key."""
    return (
        _spread3(ix.astype(jnp.int32))
        | (_spread3(iy.astype(jnp.int32)) << 1)
        | (_spread3(iz.astype(jnp.int32)) << 2)
    )


def quantize(pts: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """[...,3] float coords -> [...,3] integer grid coords in [0, 2^10)."""
    scale = (2.0**MORTON_BITS - 1.0) / jnp.maximum(hi - lo, 1e-30)
    q = (pts - lo) * scale
    return jnp.clip(q.astype(jnp.int32), 0, 2**MORTON_BITS - 1)


def morton_keys(pts: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """[...] int32 Morton key of each point within the box [lo, hi]."""
    q = quantize(pts, lo, hi)
    return morton3d(q[..., 0], q[..., 1], q[..., 2])
