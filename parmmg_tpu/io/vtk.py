"""VTK XML output: centralized `.vtu` and distributed `.pvtu`.

Role of the reference's C++ VTK layer (`src/inoutcpp_pmmg.cpp`:
`PMMG_loadVtuMesh_centralized:44`, `PMMG_savePvtuMesh:84`, built on
Mmg's VTK templates under `#ifdef USE_VTK`). The reference links the VTK
library; here the XML is emitted directly (ASCII appended-data-free
format) so the capability has no external dependency. Metric / level-set
/ displacement / user fields are written as PointData, matching what the
reference forwards to `MMG5_saveVtkMesh`.
"""

from __future__ import annotations

import os

import numpy as np

from ..core.mesh import Mesh

_VTK_TETRA = 10
_VTK_TRIANGLE = 5


def _data_array(f, name: str, arr: np.ndarray, indent: str = "        "):
    arr = np.asarray(arr)
    ncomp = 1 if arr.ndim == 1 else arr.shape[1]
    if arr.dtype.kind in "iu":
        typ, fmt = "Int64", "%d"
    else:
        typ, fmt = "Float64", "%.15g"
    f.write(
        f'{indent}<DataArray type="{typ}" Name="{name}" '
        f'NumberOfComponents="{ncomp}" format="ascii">\n'
    )
    np.savetxt(f, arr.reshape(-1, ncomp), fmt=fmt)
    f.write(f"{indent}</DataArray>\n")


def _point_data_fields(d) -> list:
    """(name, array) PointData entries from a to_numpy dict."""
    out = []
    met = d["met"]
    if met.size:
        out.append(("metric" if met.shape[1] > 1 else "h", met))
    if d["ls"].shape[1]:
        out.append(("ls", d["ls"]))
    if d["disp"].shape[1]:
        out.append(("disp", d["disp"]))
    if d["fields"].shape[1]:
        off = 0
        for k, nc in enumerate(d["field_ncomp"]):
            out.append((f"field{k}", d["fields"][:, off:off + nc]))
            off += nc
    out.append(("ref", d["vrefs"]))
    return out


def save_vtu(mesh: Mesh, path: str) -> None:
    """Write one shard/mesh as an ASCII `.vtu` unstructured grid
    (tetra cells + boundary-triangle cells, like Mmg's VTK writer)."""
    d = mesh.to_numpy()
    npts = len(d["verts"])
    cells = [d["tets"], d["trias"]]
    ctypes = np.concatenate([
        np.full(len(d["tets"]), _VTK_TETRA, np.int64),
        np.full(len(d["trias"]), _VTK_TRIANGLE, np.int64),
    ])
    crefs = np.concatenate([d["trefs"], d["trrefs"]])
    conn = np.concatenate([c.reshape(-1) for c in cells])
    sizes = np.concatenate([
        np.full(len(d["tets"]), 4, np.int64),
        np.full(len(d["trias"]), 3, np.int64),
    ])
    offsets = np.cumsum(sizes)
    ncell = len(ctypes)
    with open(path, "w") as f:
        f.write('<?xml version="1.0"?>\n')
        f.write(
            '<VTKFile type="UnstructuredGrid" version="0.1" '
            'byte_order="LittleEndian">\n  <UnstructuredGrid>\n'
        )
        f.write(
            f'    <Piece NumberOfPoints="{npts}" NumberOfCells="{ncell}">\n'
        )
        f.write("      <Points>\n")
        _data_array(f, "Points", d["verts"])
        f.write("      </Points>\n      <Cells>\n")
        _data_array(f, "connectivity", conn)
        _data_array(f, "offsets", offsets)
        _data_array(f, "types", ctypes)
        f.write("      </Cells>\n      <PointData>\n")
        for name, arr in _point_data_fields(d):
            _data_array(f, name, arr)
        f.write("      </PointData>\n      <CellData>\n")
        _data_array(f, "ref", crefs)
        f.write("      </CellData>\n    </Piece>\n")
        f.write("  </UnstructuredGrid>\n</VTKFile>\n")


def save_pvtu(stacked: Mesh, comm, path: str) -> None:
    """Parallel `.pvtu` master file + one `.vtu` piece per shard
    (`PMMG_savePvtuMesh` role, reference `src/inoutcpp_pmmg.cpp:84`)."""
    from ..parallel.distribute import unstack_mesh

    base, ext = os.path.splitext(path)
    if ext != ".pvtu":
        base = path
    shards = unstack_mesh(stacked)
    pieces = []
    for s, m in enumerate(shards):
        piece = f"{os.path.basename(base)}_{s}.vtu"
        save_vtu(m, os.path.join(os.path.dirname(path) or ".", piece))
        pieces.append(piece)
    d0 = shards[0].to_numpy()
    with open(base + ".pvtu", "w") as f:
        f.write('<?xml version="1.0"?>\n')
        f.write(
            '<VTKFile type="PUnstructuredGrid" version="0.1" '
            'byte_order="LittleEndian">\n'
            '  <PUnstructuredGrid GhostLevel="0">\n'
        )
        f.write("    <PPoints>\n")
        f.write(
            '      <PDataArray type="Float64" Name="Points" '
            'NumberOfComponents="3"/>\n'
        )
        f.write("    </PPoints>\n    <PPointData>\n")
        for name, arr in _point_data_fields(d0):
            a = np.asarray(arr)
            nc = 1 if a.ndim == 1 else a.shape[1]
            typ = "Int64" if a.dtype.kind in "iu" else "Float64"
            f.write(
                f'      <PDataArray type="{typ}" Name="{name}" '
                f'NumberOfComponents="{nc}"/>\n'
            )
        f.write("    </PPointData>\n    <PCellData>\n")
        f.write(
            '      <PDataArray type="Int64" Name="ref" '
            'NumberOfComponents="1"/>\n'
        )
        f.write("    </PCellData>\n")
        for piece in pieces:
            f.write(f'    <Piece Source="{piece}"/>\n')
        f.write("  </PUnstructuredGrid>\n</VTKFile>\n")


def load_vtu(path: str) -> Mesh:
    """Read an ASCII `.vtu` written by `save_vtu` (or a compatible ASCII
    file) back into a Mesh — the `PMMG_loadVtuMesh_centralized` role.
    Only the inline-ASCII subset is supported (the writer's own format:
    checkpoint parity, not a general VTK reader)."""
    import xml.etree.ElementTree as ET

    root = ET.parse(path).getroot()
    piece = root.find(".//Piece")

    def arr_of(parent, name):
        for da in parent.iter("DataArray"):
            if da.get("Name") == name:
                flat = np.array(da.text.split(), dtype=np.float64)
                nc = int(da.get("NumberOfComponents", "1"))
                return flat.reshape(-1, nc) if nc > 1 else flat
        return None

    pts = arr_of(piece.find("Points"), "Points")
    cells = piece.find("Cells")
    conn = arr_of(cells, "connectivity").astype(np.int64)
    types = arr_of(cells, "types").astype(np.int64)
    offsets = arr_of(cells, "offsets").astype(np.int64)
    starts = np.concatenate([[0], offsets[:-1]])
    tets, trias = [], []
    for t, s, e in zip(types, starts, offsets):
        if t == _VTK_TETRA:
            tets.append(conn[s:e])
        elif t == _VTK_TRIANGLE:
            trias.append(conn[s:e])
    pd = piece.find("PointData")
    met = None
    if pd is not None:
        m = arr_of(pd, "metric")
        h = arr_of(pd, "h")
        met = m if m is not None else (h[:, None] if h is not None else None)
    return Mesh.from_numpy(
        pts,
        np.array(tets, np.int64).reshape(-1, 4),
        trias=(np.array(trias, np.int64).reshape(-1, 3) if trias else None),
        met=met,
    )
