"""Mmg local-parameter files (`<mesh>.mmg3d`).

The reference forwards these via `PMMG_parsop` (`src/libparmmg_tools.c:573`)
to `MMG3D_parsop`: a text file holding per-reference hmin/hmax/hausd
overrides, applied to the entities carrying that reference.

Format (Mmg's, case-insensitive keywords)::

    Parameters
    <n>
    <ref> <Vertex|Triangle|Tetrahedron> <hmin> <hmax> <hausd>
"""

from __future__ import annotations

import os
from typing import NamedTuple, Tuple


class LocalParam(NamedTuple):
    ref: int
    elt: str        # "vertex" | "triangle" | "tetrahedron"
    hmin: float
    hmax: float
    hausd: float


_ELT_ALIASES = {
    "vertex": "vertex", "vertices": "vertex",
    "triangle": "triangle", "triangles": "triangle",
    "tetrahedron": "tetrahedron", "tetrahedra": "tetrahedron",
    "tetra": "tetrahedron",
}


def parse_local_params(path: str) -> Tuple[LocalParam, ...]:
    """Parse a `.mmg3d` local-parameter file (MMG3D_parsop grammar)."""
    with open(path) as f:
        toks = []
        for line in f:
            line = line.split("#")[0]
            toks.extend(line.split())
    i = 0
    while i < len(toks) and toks[i].lower() != "parameters":
        i += 1
    if i >= len(toks):
        raise ValueError(f"no Parameters section in {path}")
    i += 1
    n = int(toks[i])
    i += 1
    out = []
    for _ in range(n):
        ref = int(toks[i])
        elt = _ELT_ALIASES.get(toks[i + 1].lower())
        if elt is None:
            raise ValueError(
                f"unknown local-parameter entity {toks[i + 1]!r} in {path}"
            )
        hmin, hmax, hausd = (float(t) for t in toks[i + 2 : i + 5])
        out.append(LocalParam(ref, elt, hmin, hmax, hausd))
        i += 5
    return tuple(out)


def default_param_file(meshpath: str) -> str | None:
    """The `<mesh>.mmg3d` file MMG3D_parsop looks for next to the mesh,
    falling back to `DEFAULT.mmg3d` in the same directory."""
    root = os.path.splitext(meshpath)[0]
    for cand in (root + ".mmg3d",
                 os.path.join(os.path.dirname(meshpath) or ".",
                              "DEFAULT.mmg3d")):
        if os.path.exists(cand):
            return cand
    return None
