"""Pluggable, durable checkpoint storage backends.

The failsafe `Checkpointer` used to call ``np.savez`` straight onto a
shared POSIX filesystem — one hard-wired backend, no retry story, no
way to exercise an I/O failure deterministically. This module splits
the storage contract out into a small :class:`CheckpointStore`
interface (put / get / list / delete + an **atomic publish token**)
with two implementations:

- :class:`LocalFSStore` — the previous behavior: same-directory temp
  file + ``os.replace`` (`io.medit.atomic_replace`) + directory fsync,
  so a reader sees old-complete or new-complete, never a torn file;
- :class:`ObjectStore` — modeled on GCS object semantics: there is
  **no rename**, but every single-object put is atomic (readers see
  whole old or whole new object), so commit ordering comes entirely
  from the *manifest-last* publish discipline the checkpointer already
  follows — the manifest object IS the commit token. The backend is a
  plain mutable mapping of ``name -> bytes`` (`memory_bucket` serves
  shared in-process buckets via ``mem://<name>`` specs), so the GCS
  failure surface — transient 5xx, slow writes, lost manifests — is
  reproducible in tests without a cloud dependency.

Every public operation is wrapped in bounded retry with exponential
backoff + deterministic (seeded) jitter (`utils.retry.retry`) and an
optional per-operation timeout (a daemon-thread watchdog — blocking
POSIX I/O cannot be cancelled, only abandoned). Exhausted retries
raise :class:`CheckpointIOError` (an ``OSError``, so pre-existing
broad handlers keep working).

Deterministic fault injection: stores accept a ``fault_cb(op, name,
timeout)`` hook invoked before every raw attempt; the failsafe
`FaultPlan` wires its ``ckpt``-phase faults (``ioerror`` raises,
``slowio`` outsleeps the per-op timeout) through it, so each
retry/abort path is testable byte for byte.

Env contract (read by :func:`make_store` for the default store):

  PMMGTPU_CKPT_ATTEMPTS  bounded retry attempts per op (default 4)
  PMMGTPU_CKPT_BACKOFF   base backoff seconds (default 0.05, doubling)
  PMMGTPU_CKPT_TIMEOUT   per-operation timeout seconds (default none)
"""

from __future__ import annotations

import io as _io
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..utils.retry import retry


class CheckpointIOError(OSError):
    """A checkpoint-store operation failed after its bounded retries
    (or timed out). Typed so the drivers/harness can map it onto the
    graded-failure ladder (`failsafe.CKPT_IO_EXIT_CODE`) instead of an
    untyped traceback.

    Subtypes form the TERMINAL half of the retry-status taxonomy: a
    raw store attempt raising any `CheckpointIOError` subtype (other
    than the timeout, which the retry envelope itself produces) is NOT
    re-attempted — retrying a bad credential or a lost conditional
    write cannot help and only delays the caller's typed exit."""


class CheckpointTimeoutError(CheckpointIOError):
    """A single store operation exceeded its per-op timeout."""


class CheckpointAuthError(CheckpointIOError):
    """The store rejected our credentials (HTTP 401/403). Terminal:
    no number of retries fixes a bad/expired token or missing bucket
    ACL — fail typed and let the operator rotate the credential."""


class CheckpointNotFoundError(CheckpointIOError, FileNotFoundError):
    """The named object does not exist (HTTP 404). Also a
    `FileNotFoundError`, so every pre-existing missing-object path
    (load's fall-back-to-previous, delete's concurrent-GC tolerance)
    handles a remote store identically to a local directory."""


class CheckpointPreconditionError(CheckpointIOError):
    """A conditional write lost its precondition (HTTP 412: the
    ``if-generation-match`` guard on a manifest publish saw a
    concurrent writer). Terminal for THIS attempt — the commit token
    was taken by another publisher, and blindly overwriting it would
    un-commit their epoch."""


class CheckpointCorruptionError(CheckpointIOError, ValueError):
    """A checkpoint payload is structurally corrupt (npz/zip CRC or
    container damage — a torn object, bit rot). Also a ``ValueError``
    so the loader's established fall-back-to-previous-epoch catch
    keeps working; as a `CheckpointIOError` it maps onto exit code 89
    when it escapes every fallback."""


def _call_with_timeout(fn, timeout: float, what: str):
    """Run `fn` bounded by `timeout` seconds on a daemon thread.

    Blocking filesystem/network I/O cannot be cancelled from Python;
    on timeout the worker is abandoned (daemon) and
    :class:`CheckpointTimeoutError` raised — the retry layer above then
    re-attempts the operation fresh."""
    box: dict = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as e:
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(
        target=_run, name=f"parmmg-ckpt-io:{what}", daemon=True
    )
    t.start()
    if not done.wait(timeout):
        raise CheckpointTimeoutError(
            f"checkpoint op {what} exceeded its {timeout:.1f}s timeout"
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")


class TransientStoreError(OSError):
    """A retryable backend failure (HTTP 408/429/5xx, a truncated or
    timed-out transport, a dropped connection). Carries the optional
    server-provided ``retry_after`` hint in seconds, which the seeded
    backoff honors as a floor on the next delay
    (`utils.retry.retry`)."""

    def __init__(self, message: str, status: Optional[int] = None,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


def _retryable(exc: BaseException) -> bool:
    """Transient store failures worth re-attempting: timeouts and
    OSErrors that are NOT (a) a plain missing object (retrying a
    FileNotFoundError cannot help and only delays the caller's
    fallback-to-previous-checkpoint path) or (b) a typed TERMINAL
    member of the `CheckpointIOError` taxonomy (auth rejection,
    precondition loss, corruption)."""
    if isinstance(exc, CheckpointTimeoutError):
        return True
    if isinstance(exc, CheckpointIOError):
        return False
    return isinstance(exc, OSError) and not isinstance(
        exc, FileNotFoundError
    )


class CheckpointStore:
    """Abstract durable key/value store for checkpoint artifacts.

    Subclasses implement the raw primitives ``_put/_get/_list/_delete``
    over flat names (no directories); this base class supplies the
    retry/backoff/timeout/fault-injection envelope. The one semantic
    every backend must honor: :meth:`put` (and therefore
    :meth:`publish`) is atomic per object — a reader never observes a
    partially written object. ``publish`` is put with COMMIT-TOKEN
    meaning: the checkpoint protocol writes every data object first and
    publishes the manifest last, so the manifest's existence is the
    transaction's commit record on any backend, rename-capable or not.
    """

    def __init__(self, *, attempts: int = 4, backoff: float = 0.05,
                 jitter: float = 0.5, seed: int = 0,
                 timeout: Optional[float] = None,
                 fault_cb: Optional[Callable] = None):
        self.attempts = max(int(attempts), 1)
        self.backoff = float(backoff)
        self.jitter = float(jitter)
        self.seed = seed
        self.timeout = timeout
        self.fault_cb = fault_cb

    # -- raw primitives (subclass responsibility) -----------------------
    def _put(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def _get(self, name: str) -> bytes:
        raise NotImplementedError

    def _list(self) -> List[str]:
        raise NotImplementedError

    def _delete(self, name: str) -> None:
        raise NotImplementedError

    def _publish(self, name: str, data: bytes) -> None:
        """Raw commit-token put. Defaults to a plain `_put`; backends
        with conditional-write support override it (the GCS adapter's
        ``if-generation-match`` put) so the manifest-last commit token
        stays atomic under concurrent publishers."""
        self._put(name, data)

    # -- retry/timeout/fault envelope -----------------------------------
    def _op(self, op: str, name: str, fn):
        what = f"{op}:{name}" if name else op

        def raw():
            # the fault hook runs INSIDE the timed region: a `slowio`
            # fault must trip the per-op watchdog exactly like a
            # genuinely stalled backend would
            if self.fault_cb is not None:
                self.fault_cb(op, name, self.timeout)
            return fn()

        def attempt():
            if self.timeout is not None:
                return _call_with_timeout(raw, self.timeout, what)
            return raw()

        reg = obs_metrics.registry()
        t0 = time.perf_counter()
        try:
            return retry(
                attempt,
                attempts=self.attempts,
                backoff=self.backoff,
                jitter=self.jitter,
                seed=self.seed,
                retry_on=_retryable,
                on_retry=lambda e, k: reg.counter("ckpt/retries").inc(),
            )
        except FileNotFoundError:
            raise
        except CheckpointTimeoutError as e:
            raise CheckpointIOError(
                f"checkpoint {what} failed after {self.attempts} "
                f"attempts: {e}"
            ) from e
        except CheckpointIOError:
            # terminal taxonomy member (auth / precondition /
            # corruption): already typed — propagate unchanged so the
            # caller can tell WHY, not just that I/O failed
            raise
        except OSError as e:
            raise CheckpointIOError(
                f"checkpoint {what} failed after {self.attempts} "
                f"attempts: {e}"
            ) from e
        finally:
            # always-on store telemetry (a counter bump + a float — the
            # I/O it measures dwarfs it): op count + latency, rendered
            # by tools/obs_report.py as the checkpoint I/O table
            reg.counter("ckpt/ops").inc()
            reg.histogram("ckpt/op_seconds").observe(
                time.perf_counter() - t0
            )

    # -- public surface --------------------------------------------------
    def put(self, name: str, data: bytes) -> None:
        """Atomically store `data` under `name` (whole-object put)."""
        self._op("put", name, lambda: self._put(name, bytes(data)))
        obs_metrics.registry().counter("ckpt/put_bytes").inc(len(data))

    def publish(self, name: str, data: bytes) -> None:
        """Atomic commit-token put — identical durability to
        :meth:`put`; named separately because the checkpoint protocol's
        correctness hangs on this object landing LAST (and backends
        with conditional writes guard it against concurrent
        publishers — see `_publish`)."""
        self._op("publish", name, lambda: self._publish(name, bytes(data)))
        obs_metrics.registry().counter("ckpt/put_bytes").inc(len(data))

    def get(self, name: str) -> bytes:
        data = self._op("get", name, lambda: self._get(name))
        obs_metrics.registry().counter("ckpt/get_bytes").inc(len(data))
        return data

    def list(self) -> List[str]:
        return self._op("list", "", self._list)

    def delete(self, name: str) -> None:
        """Best-effort delete. An already-missing object is success —
        concurrent GC on a shared backend (another rank pruning, a
        lifecycle rule) must not fail the caller."""

        def _del():
            try:
                self._delete(name)
            except FileNotFoundError:
                pass

        self._op("delete", name, _del)

    # -- small JSON control records (elastic membership manifests) -------
    def put_json(self, name: str, doc: dict) -> None:
        """Store a small JSON control document (an elastic membership
        manifest, reform request or exit ack) — same atomic whole-object
        semantics as :meth:`put`."""
        self.put(name, json.dumps(doc, sort_keys=True,
                                  default=str).encode())

    def publish_json(self, name: str, doc: dict) -> None:
        """Commit-token JSON put (conditional on backends that support
        it — the membership manifest of one elastic epoch must have
        exactly one writer win)."""
        self.publish(name, json.dumps(doc, sort_keys=True,
                                      default=str).encode())

    def get_json(self, name: str) -> dict:
        """Read a JSON control document; a structurally broken payload
        surfaces as the typed :class:`CheckpointCorruptionError` (a
        torn or foreign object must not crash the reform protocol
        untyped)."""
        data = self.get(name)
        try:
            return json.loads(data.decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"corrupt JSON control record {name!r}: {e}"
            ) from e


class LocalFSStore(CheckpointStore):
    """POSIX-directory store — the original checkpoint layout.

    Atomicity via same-directory temp + ``os.replace``
    (`io.medit.atomic_replace`), durability via a directory fsync after
    every publish (`io.medit.fsync_dir`): the commit record must not
    sit in a dying host's page cache while the barrier releases the
    other ranks."""

    def __init__(self, dirpath: str, **kw):
        super().__init__(**kw)
        self.dir = dirpath

    def _put(self, name: str, data: bytes) -> None:
        from .medit import atomic_replace, fsync_dir

        os.makedirs(self.dir, exist_ok=True)
        with atomic_replace(os.path.join(self.dir, name), "wb") as f:
            f.write(data)
        fsync_dir(self.dir)

    def _get(self, name: str) -> bytes:
        with open(os.path.join(self.dir, name), "rb") as f:
            return f.read()

    def _list(self) -> List[str]:
        try:
            return sorted(os.listdir(self.dir))
        except FileNotFoundError:
            return []

    def _delete(self, name: str) -> None:
        os.unlink(os.path.join(self.dir, name))


class ObjectStore(CheckpointStore):
    """Object-store semantics (modeled on GCS): no rename exists, but a
    single-object put is atomic — readers see the whole old object or
    the whole new one. The manifest-last discipline of the checkpoint
    protocol therefore carries the entire commit semantics, with no
    filesystem tricks to lean on. The backing `bucket` is any mutable
    ``name -> bytes`` mapping (an in-process dict from
    :func:`memory_bucket`, or an adapter over a real object-store
    client's blob API)."""

    def __init__(self, bucket: Dict[str, bytes], **kw):
        super().__init__(**kw)
        self.bucket = bucket
        # one lock per store: dict mutation is atomic under the GIL but
        # real adapters may not be; the raw ops stay tiny so the lock
        # cost is irrelevant next to serialization
        self._lock = threading.Lock()

    def _put(self, name: str, data: bytes) -> None:
        with self._lock:
            self.bucket[name] = bytes(data)

    def _get(self, name: str) -> bytes:
        with self._lock:
            try:
                return self.bucket[name]
            except KeyError:
                raise FileNotFoundError(name) from None

    def _list(self) -> List[str]:
        with self._lock:
            return sorted(self.bucket)

    def _delete(self, name: str) -> None:
        with self._lock:
            try:
                del self.bucket[name]
            except KeyError:
                raise FileNotFoundError(name) from None


# shared in-process object buckets, keyed by name — lets two in-process
# "ranks" (tests) or a driver + a verifier share one simulated bucket
_MEM_BUCKETS: Dict[str, Dict[str, bytes]] = {}
_MEM_LOCK = threading.Lock()


def memory_bucket(name: str) -> Dict[str, bytes]:
    """The shared in-process bucket registered under `name` (created on
    first use). Contents do NOT survive the process — ``mem://`` stores
    exercise the object-store code paths and fault matrix, not real
    durability."""
    with _MEM_LOCK:
        return _MEM_BUCKETS.setdefault(name, {})


def _env_retry_kw() -> dict:
    kw: dict = {}
    att = os.environ.get("PMMGTPU_CKPT_ATTEMPTS")
    if att:
        kw["attempts"] = int(att)
    back = os.environ.get("PMMGTPU_CKPT_BACKOFF")
    if back:
        kw["backoff"] = float(back)
    tmo = os.environ.get("PMMGTPU_CKPT_TIMEOUT")
    if tmo:
        kw["timeout"] = float(tmo)
    return kw


def make_store(spec, dirpath: Optional[str] = None,
               fault_cb: Optional[Callable] = None) -> CheckpointStore:
    """Resolve a checkpoint store from an options spec.

    - a :class:`CheckpointStore` instance passes through (its
      `fault_cb` is armed when unset);
    - ``"mem://<bucket>"`` — shared in-process :class:`ObjectStore`;
    - ``"gs://<bucket>[/<prefix>]"`` — real GCS via the stdlib-HTTP
      adapter (`io.gcs.GCSStore`; endpoint/auth per the PMMGTPU_GCS_*
      env contract documented there);
    - ``"file://<dir>"`` or a plain path string — :class:`LocalFSStore`
      rooted there;
    - ``None`` — :class:`LocalFSStore` over `dirpath` (the
      ``checkpoint_dir`` default).

    Retry/backoff/timeout knobs come from the PMMGTPU_CKPT_* env
    contract (module docstring)."""
    if isinstance(spec, CheckpointStore):
        if spec.fault_cb is None:
            spec.fault_cb = fault_cb
        return spec
    kw = _env_retry_kw()
    kw["fault_cb"] = fault_cb
    if isinstance(spec, str):
        if spec.startswith("mem://"):
            return ObjectStore(memory_bucket(spec[6:]), **kw)
        if spec.startswith("gs://"):
            from .gcs import GCSStore

            return GCSStore.from_url(spec, **kw)
        if spec.startswith("file://"):
            return LocalFSStore(spec[7:], **kw)
        return LocalFSStore(spec, **kw)
    if spec is None and dirpath is not None:
        return LocalFSStore(dirpath, **kw)
    raise ValueError(
        f"cannot resolve a checkpoint store from spec {spec!r} "
        "(want a CheckpointStore, 'mem://<bucket>', 'file://<dir>', a "
        "path, or a checkpoint_dir)"
    )


def npz_bytes(arrays: Dict) -> bytes:
    """Serialize an array dict to npz bytes (the store-facing half of
    the old direct-``np.savez``-to-file path)."""
    import numpy as np

    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def npz_arrays(data: bytes) -> Dict:
    """Deserialize npz bytes back to an eager {name: ndarray} dict.
    Corrupt payloads (zip CRC/structure failures) surface as the typed
    :class:`CheckpointCorruptionError` — still a ``ValueError``, so the
    checkpoint loader's fall-back-to-previous path catches them
    uniformly, and a `CheckpointIOError`, so an escape past every
    fallback maps onto exit code 89 instead of an untyped crash."""
    import zipfile
    import zlib

    import numpy as np

    try:
        with np.load(_io.BytesIO(data)) as z:
            return {k: z[k] for k in z.files}
    except (zipfile.BadZipFile, zlib.error, ValueError, OSError) as e:
        # BadZipFile (container), zlib.error (deflate stream), and
        # np.load's own ValueError/OSError flavors on mangled bytes:
        # all mean "this is not the npz we wrote"
        raise CheckpointCorruptionError(
            f"corrupt npz payload: {e}"
        ) from e
