from . import ckpt_store, medit  # noqa: F401
