from . import medit  # noqa: F401
