"""Medit (.mesh/.sol) ASCII I/O, centralized and distributed.

Behavioral counterpart of the reference's `src/inout_pmmg.c`:
 - centralized load/save (`PMMG_loadMesh_centralized:488`,
   `PMMG_saveMesh_centralized:847`) for whole meshes plus met/ls/disp/fields
   sol files;
 - distributed per-shard files `name.<rank>.mesh` carrying the parallel
   interface as `ParallelCommunicator{Vertices,Triangles}` keywords with
   (local id, global id, comm index) triples
   (`PMMG_loadCommunicator:74`, `PMMG_saveMesh_distributed:798`).

Implementation is tokenizer-based numpy (vectorized reshape per section), not
a translation of the reference's fscanf loops. An optional C++ tokenizer for
very large files lives in `native/` and is used transparently when built.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import os
import re
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..core import tags
from ..core.mesh import Mesh

_COMMENT_RE = re.compile(r"#[^\n]*")


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so just-published renames (`atomic_replace`)
    are durable, not merely visible: the checkpoint commit protocol
    must not let a barrier release other ranks while this rank's
    rename still sits in the page cache of a host about to lose power.
    Best-effort — platforms that refuse O_RDONLY on directories are
    silently skipped (rename ordering still gives crash atomicity,
    just not power-loss durability)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# unique per-call tmp suffix: a pid alone is not enough once the
# checkpoint store retries a timed-out write — the abandoned attempt's
# thread may still hold the old tmp file open, and two writers on one
# tmp path would interleave into garbage that os.replace then publishes
_TMP_SEQ = itertools.count()


@contextlib.contextmanager
def atomic_replace(path: str, mode: str = "w"):
    """Write-then-rename file publication: the payload goes to a
    same-directory temp file and appears at `path` only via
    ``os.replace`` after a successful close (+fsync), so a killed run
    can never leave a truncated mesh/sol/checkpoint behind — a reader
    sees either the old complete file or the new complete file. Every
    writer in this module (and the failsafe checkpointer) publishes
    through this."""
    tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_SEQ)}"
    f = open(tmp, mode)
    try:
        yield f
    except BaseException:
        f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    else:
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)

# Medit sol type codes
SOL_SCALAR = 1
SOL_VECTOR = 2
SOL_TENSOR = 3
_SOL_NCOMP = {SOL_SCALAR: 1, SOL_VECTOR: 3, SOL_TENSOR: 6}

# sections: name -> (columns, has_ref)
_ENT_SECTIONS = {
    "Vertices": (3, True),
    "Tetrahedra": (4, True),
    "Triangles": (3, True),
    "Edges": (2, True),
    "Quadrilaterals": (4, True),
    "Corners": (1, False),
    "RequiredVertices": (1, False),
    "RequiredTriangles": (1, False),
    "RequiredEdges": (1, False),
    "Ridges": (1, False),
    "Normals": (3, False),
    "Tangents": (3, False),
    "NormalAtVertices": (2, False),
    "TangentAtVertices": (2, False),
}


def _tokenize(path: str) -> List[str]:
    from . import native_io

    if not os.path.exists(path):  # uniform error across both backends
        raise FileNotFoundError(f"mesh file not found: {path}")
    if native_io.available():
        return native_io.tokenize(path)
    with open(path) as f:
        text = f.read()
    return _COMMENT_RE.sub(" ", text).split()


@dataclasses.dataclass
class RawMesh:
    """Host-side parsed mesh, 0-based indices."""

    verts: np.ndarray
    vrefs: np.ndarray
    tets: np.ndarray
    trefs: np.ndarray
    trias: np.ndarray
    trrefs: np.ndarray
    edges: np.ndarray
    edrefs: np.ndarray
    corners: np.ndarray
    req_verts: np.ndarray
    req_trias: np.ndarray
    req_edges: np.ndarray
    ridges: np.ndarray
    # distributed interface info (None for centralized files)
    # list per communicator: (color, local_ids, global_ids)
    face_comms: List[Tuple[int, np.ndarray, np.ndarray]] | None = None
    node_comms: List[Tuple[int, np.ndarray, np.ndarray]] | None = None


# --------------------------------------------------------------------------
# binary Medit (.meshb / .solb)
#
# GMF container (libMeshb): int32 cookie 1 (16777216 when byte-swapped),
# int32 version, then keyword records [code, NulPos, payload] where NulPos
# is the byte offset of the NEXT record — unknown sections are skipped by
# seeking to it, exactly how the reference reader walks these files
# (`PMMG_loadCommunicators`, src/inout_pmmg.c:259-299). Version 2 (float64
# coords, int32 ints/positions) is what Mmg writes and what we write; the
# reader also accepts version 1 (float32) and 3 (int64 positions).
# Communicator sections use the reference's own binary codes 70-73
# (src/inout_pmmg.c:137-142,270-278). NOTE the reference can only READ
# binary communicators — its writer errors out ("Binary file format not
# yet implemented for communicators", src/libparmmg_tools.c:884); here
# both directions work, so the distributed checkpoint loop closes in
# binary as well.
# --------------------------------------------------------------------------

_KWD_CODES = {
    "Dimension": 3,
    "Vertices": 4,
    "Edges": 5,
    "Triangles": 6,
    "Quadrilaterals": 7,
    "Tetrahedra": 8,
    "Corners": 13,
    "Ridges": 14,
    "RequiredVertices": 15,
    "RequiredEdges": 16,
    "RequiredTriangles": 17,
    "NormalAtVertices": 20,
    "End": 54,
    "Tangents": 59,
    "Normals": 60,
    "TangentAtVertices": 61,
    "SolAtVertices": 62,
    # ParMmg extension codes (reference src/inout_pmmg.c:137-142)
    "ParallelTriangleCommunicators": 70,
    "ParallelVertexCommunicators": 71,
    "ParallelCommunicatorTriangles": 72,
    "ParallelCommunicatorVertices": 73,
}
_KWD_NAMES = {v: k for k, v in _KWD_CODES.items()}


def is_binary_file(path: str) -> bool:
    """Sniff the GMF binary cookie (int32 1, either endianness) — the
    role of the reference's extension dispatch in `MMG3D_openMesh`, but
    content-based so misnamed files still load."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"mesh file not found: {path}")
    with open(path, "rb") as f:
        head = f.read(4)
    if len(head) < 4:
        return False
    v = int(np.frombuffer(head, "<i4")[0])
    return v in (1, 16777216)


class _BinReader:
    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.buf = f.read()
        self.path = path
        cookie = int(np.frombuffer(self.buf, "<i4", 1)[0])
        if cookie == 1:
            self.end = "<"
        elif cookie == 16777216:
            self.end = ">"
        else:
            raise ValueError(f"{path}: not a GMF binary file")
        self.ver = int(np.frombuffer(self.buf, self.end + "i4", 1, 4)[0])
        if self.ver not in (1, 2, 3):
            raise ValueError(
                f"{path}: unsupported GMF version {self.ver} "
                "(1-3 readable, 2 written)"
            )
        self.real = self.end + ("f4" if self.ver == 1 else "f8")
        self.int = self.end + "i4"
        self.pos_t = self.end + ("i8" if self.ver >= 3 else "i4")
        self.off = 8

    def ints(self, n):
        out = np.frombuffer(self.buf, self.int, n, self.off).astype(np.int64)
        self.off += 4 * n
        return out

    def int1(self):
        return int(self.ints(1)[0])

    def pos(self):
        v = int(np.frombuffer(self.buf, self.pos_t, 1, self.off)[0])
        self.off += np.dtype(self.pos_t).itemsize
        return v

    def table(self, cnt, ncols_real=0, ncols_int=0):
        """cnt rows of (reals..., ints...) -> float64 [cnt, ncols] array
        (the ASCII sections parse to float64 too, so the shared assembly
        code sees identical input)."""
        rdt = np.dtype(self.real)
        dt = np.dtype(
            ([("r", rdt, (ncols_real,))] if ncols_real else [])
            + ([("i", self.int, (ncols_int,))] if ncols_int else [])
        )
        arr = np.frombuffer(self.buf, dt, cnt, self.off)
        self.off += dt.itemsize * cnt
        parts = []
        if ncols_real:
            parts.append(arr["r"].astype(np.float64))
        if ncols_int:
            parts.append(arr["i"].astype(np.float64))
        return np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _read_sections_binary(path: str):
    r = _BinReader(path)
    data: Dict[str, np.ndarray] = {}
    comm_heads: Dict[str, np.ndarray] = {}
    comm_items: Dict[str, np.ndarray] = {}
    dim = 3
    n = len(r.buf)
    while r.off + 4 <= n:
        code = r.int1()
        if code == 54:  # End
            break
        nxt = r.pos()
        name = _KWD_NAMES.get(code)
        if name is None or name in ("End",):
            if nxt <= 0 or nxt <= r.off:
                # a broken skip chain means a truncated/corrupt file —
                # surface it rather than returning a partial mesh
                raise ValueError(
                    f"{path}: malformed GMF record chain at byte "
                    f"{r.off} (keyword code {code}, next={nxt}) — "
                    "truncated or corrupt binary mesh"
                )
            r.off = nxt
            continue
        if name == "Dimension":
            dim = r.int1()
        elif name in _ENT_SECTIONS:
            cols, has_ref = _ENT_SECTIONS[name]
            if name == "Vertices":
                cols = dim
            cnt = r.int1()
            if name in ("Vertices", "Normals", "Tangents"):
                data[name] = r.table(
                    cnt, ncols_real=cols, ncols_int=1 if has_ref else 0
                )
            else:
                data[name] = r.table(
                    cnt, ncols_int=cols + (1 if has_ref else 0)
                )
        elif name in (
            "ParallelTriangleCommunicators",
            "ParallelVertexCommunicators",
        ):
            cnt = r.int1()
            comm_heads[name] = (
                r.ints(cnt * 2).reshape(cnt, 2)
            )
        elif name in (
            "ParallelCommunicatorTriangles",
            "ParallelCommunicatorVertices",
        ):
            head_kw = (
                "ParallelTriangleCommunicators"
                if "Triangles" in name
                else "ParallelVertexCommunicators"
            )
            if head_kw not in comm_heads:
                raise ValueError(
                    f"{path}: section {name} appears before its header "
                    f"section {head_kw}"
                )
            ntot = int(comm_heads[head_kw][:, 1].sum())
            comm_items[name] = r.ints(ntot * 3).reshape(ntot, 3)
        elif name == "SolAtVertices":
            # skip: sols live in their own files; tolerate embedding
            r.off = nxt
        else:
            r.off = nxt
        if nxt > 0:
            r.off = nxt  # trust the skip chain over our own arithmetic
    return data, comm_heads, comm_items, dim


class _BinWriter:
    """GMF version-2 writer (float64 reals, int32 ints/positions —
    what Mmg's `MMG3D_saveMesh` emits for .meshb). Writes to a temp
    file; `end()` publishes it atomically (`atomic_replace`
    discipline), `abort()` discards it — the target path is never
    observable half-written."""

    def __init__(self, path: str):
        self.path = path
        self.tmp = f"{path}.tmp.{os.getpid()}"
        self.f = open(self.tmp, "wb")
        self.f.write(np.array([1, 2], "<i4").tobytes())

    def _i4(self, *vals):
        self.f.write(np.array(vals, "<i4").tobytes())

    def section(self, name: str, payload: bytes, head: Sequence[int]):
        """[code, NulPos, head ints..., payload]."""
        code = _KWD_CODES[name]
        here = self.f.tell()
        nxt = here + 8 + 4 * len(head) + len(payload)
        if nxt > 2**31 - 1:
            raise ValueError(
                "mesh too large for GMF version 2 int32 positions "
                f"(section {name} would end at byte {nxt}); write ASCII "
                "or shard the mesh"
            )
        self._i4(code, nxt, *head)
        self.f.write(payload)

    def end(self):
        self._i4(54, 0)
        self.f.flush()
        os.fsync(self.f.fileno())
        self.f.close()
        os.replace(self.tmp, self.path)

    def abort(self):
        if not self.f.closed:
            self.f.close()
        try:
            os.unlink(self.tmp)
        except OSError:
            pass


def _rows_bytes(arr_i: np.ndarray, refs: np.ndarray | None,
                one_based: bool) -> bytes:
    body = arr_i.astype(np.int32) + (1 if one_based else 0)
    if refs is not None:
        body = np.concatenate(
            [body, refs.astype(np.int32)[:, None]], axis=1
        )
    return np.ascontiguousarray(body, "<i4").tobytes()


def _save_mesh_binary(
    path: str,
    d: Dict[str, np.ndarray],
    comm_sections,
) -> None:
    # the writer stages into a temp file (atomic_replace discipline):
    # neither an exception nor a kill can leave a truncated .meshb at
    # `path` — a later load would sniff the valid cookie and then fail
    # mid-chain
    w = _BinWriter(path)
    try:
        _save_mesh_binary_inner(w, d, comm_sections)
    except BaseException:
        w.abort()
        raise


def _save_mesh_binary_inner(
    w: "_BinWriter",
    d: Dict[str, np.ndarray],
    comm_sections,
) -> None:
    w.section("Dimension", b"", [3])
    verts = np.zeros(
        len(d["verts"]), np.dtype([("xyz", "<f8", (3,)), ("ref", "<i4")])
    )
    verts["xyz"] = d["verts"]
    verts["ref"] = d["vrefs"]
    w.section("Vertices", verts.tobytes(), [len(verts)])
    for name, key, rkey in (
        ("Tetrahedra", "tets", "trefs"),
        ("Triangles", "trias", "trrefs"),
        ("Edges", "edges", "edrefs"),
    ):
        if len(d[key]):
            w.section(
                name, _rows_bytes(d[key], d[rkey], True), [len(d[key])]
            )
    for name, ids in d["idsections"]:
        if len(ids):
            w.section(
                name, _rows_bytes(ids[:, None], None, True), [len(ids)]
            )
    for kw_head, kw_items, remapped in comm_sections:
        w.section(
            kw_head,
            np.ascontiguousarray(
                [[c, len(loc)] for c, loc, _ in remapped], "<i4"
            ).tobytes(),
            [len(remapped)],
        )
        items = np.concatenate(
            [
                np.stack(
                    [
                        np.asarray(loc, np.int64) + 1,
                        np.asarray(glob, np.int64),
                        np.full(len(loc), icomm, np.int64),
                    ],
                    axis=1,
                )
                for icomm, (c, loc, glob) in enumerate(remapped)
            ]
        )
        w.section(kw_items, np.ascontiguousarray(items, "<i4").tobytes(), [])
    w.end()


def read_mesh(path: str) -> RawMesh:
    if is_binary_file(path):
        data, comm_heads, comm_items, dim = _read_sections_binary(path)
        return _assemble_raw(data, comm_heads, comm_items, dim, path)
    toks = _tokenize(path)
    n = len(toks)
    i = 0
    data: Dict[str, np.ndarray] = {}
    comm_heads: Dict[str, np.ndarray] = {}
    comm_items: Dict[str, np.ndarray] = {}
    dim = 3
    while i < n:
        kw = toks[i]
        i += 1
        if kw == "End":
            break
        if kw == "MeshVersionFormatted":
            i += 1
        elif kw == "Dimension":
            dim = int(toks[i])
            i += 1
        elif kw in _ENT_SECTIONS:
            cols, has_ref = _ENT_SECTIONS[kw]
            if kw == "Vertices":
                cols = dim
            cnt = int(toks[i])
            i += 1
            w = cols + (1 if has_ref else 0)
            arr = np.array(toks[i : i + cnt * w], dtype=np.float64).reshape(cnt, w)
            i += cnt * w
            data[kw] = arr
        elif kw in (
            "ParallelTriangleCommunicators",
            "ParallelVertexCommunicators",
        ):
            cnt = int(toks[i])
            i += 1
            arr = np.array(toks[i : i + cnt * 2], dtype=np.int64).reshape(cnt, 2)
            i += cnt * 2
            comm_heads[kw] = arr  # columns: color, nitem
        elif kw in (
            "ParallelCommunicatorTriangles",
            "ParallelCommunicatorVertices",
        ):
            head_kw = (
                "ParallelTriangleCommunicators"
                if "Triangles" in kw
                else "ParallelVertexCommunicators"
            )
            if head_kw not in comm_heads:
                raise ValueError(
                    f"{path}: section {kw} appears before its header "
                    f"section {head_kw}"
                )
            head = comm_heads[head_kw]
            ntot = int(head[:, 1].sum())
            arr = np.array(toks[i : i + ntot * 3], dtype=np.int64).reshape(ntot, 3)
            i += ntot * 3
            comm_items[kw] = arr  # columns: idx_loc, idx_glob, icomm
        else:
            raise ValueError(f"unhandled Medit keyword {kw!r} in {path}")
    return _assemble_raw(data, comm_heads, comm_items, dim, path)


def _assemble_raw(
    data: Dict[str, np.ndarray],
    comm_heads: Dict[str, np.ndarray],
    comm_items: Dict[str, np.ndarray],
    dim: int,
    path: str,
) -> RawMesh:
    """Section dicts -> RawMesh: the shared back half of the ASCII and
    binary readers (sections carry identical content in both forms)."""

    def ent(kw, cols):
        if kw not in data:
            return (
                np.zeros((0, cols), np.int32),
                np.zeros(0, np.int32),
            )
        a = data[kw]
        return a[:, :cols].astype(np.int64).astype(np.int32) - 1, a[:, cols].astype(
            np.int32
        )

    verts = data.get("Vertices", np.zeros((0, dim + 1)))
    tets, trefs = ent("Tetrahedra", 4)
    trias, trrefs = ent("Triangles", 3)
    edges, edrefs = ent("Edges", 2)

    def ids(kw):
        if kw not in data:
            return np.zeros(0, np.int32)
        return data[kw][:, 0].astype(np.int64).astype(np.int32) - 1

    def build_comms(head_kw, item_kw):
        if head_kw not in comm_heads:
            return None
        head = comm_heads[head_kw]
        if item_kw not in comm_items:
            raise ValueError(
                f"{path}: header section {head_kw} present but item "
                f"section {item_kw} missing"
            )
        items = comm_items[item_kw]
        out = []
        for icomm in range(head.shape[0]):
            sel = items[:, 2] == icomm
            out.append(
                (
                    int(head[icomm, 0]),
                    items[sel, 0].astype(np.int32) - 1,
                    items[sel, 1].astype(np.int32),
                )
            )
        return out

    return RawMesh(
        verts=verts[:, :dim].astype(np.float64),
        vrefs=verts[:, dim].astype(np.int32),
        tets=tets,
        trefs=trefs,
        trias=trias,
        trrefs=trrefs,
        edges=edges,
        edrefs=edrefs,
        corners=ids("Corners"),
        req_verts=ids("RequiredVertices"),
        req_trias=ids("RequiredTriangles"),
        req_edges=ids("RequiredEdges"),
        ridges=ids("Ridges"),
        face_comms=build_comms(
            "ParallelTriangleCommunicators", "ParallelCommunicatorTriangles"
        ),
        node_comms=build_comms(
            "ParallelVertexCommunicators", "ParallelCommunicatorVertices"
        ),
    )


def read_sol(path: str) -> Tuple[np.ndarray, List[int]]:
    """Read SolAtVertices: returns (values [n, sum(ncomp)], type codes)."""
    if is_binary_file(path):
        r = _BinReader(path)
        n = len(r.buf)
        while r.off + 4 <= n:
            code = r.int1()
            if code == 54:
                break
            nxt = r.pos()
            if code == _KWD_CODES["Dimension"]:
                r.int1()
            elif code == _KWD_CODES["SolAtVertices"]:
                nv = r.int1()
                nsol = r.int1()
                types = [int(t) for t in r.ints(nsol)]
                width = sum(_SOL_NCOMP[t] for t in types)
                vals = r.table(nv, ncols_real=width)
                return vals, types
            if nxt > 0:
                r.off = nxt
        raise ValueError(f"no SolAtVertices section in {path}")
    toks = _tokenize(path)
    i = 0
    n = len(toks)
    while i < n and toks[i] != "SolAtVertices":
        if toks[i] == "Dimension":
            i += 1
        i += 1
    if i >= n:
        raise ValueError(f"no SolAtVertices section in {path}")
    i += 1
    nv = int(toks[i])
    i += 1
    nsol = int(toks[i])
    i += 1
    types = [int(toks[i + k]) for k in range(nsol)]
    i += nsol
    width = sum(_SOL_NCOMP[t] for t in types)
    vals = np.array(toks[i : i + nv * width], dtype=np.float64).reshape(nv, width)
    return vals, types


def raw_to_mesh(raw: RawMesh, met: np.ndarray | None = None, **kw) -> Mesh:
    """Assemble a device Mesh from a RawMesh, deriving tag bits from the
    required/corner/ridge sections (the role of `MMG3D_Set_requiredVertex`
    et al. in the reference API)."""
    npo = len(raw.verts)
    vtags = np.zeros(npo, np.int32)
    vtags[raw.req_verts] |= tags.REQUIRED
    vtags[raw.corners] |= tags.CORNER | tags.REQUIRED
    trtags = np.zeros(len(raw.trias), np.int32)
    trtags[raw.req_trias] |= tags.REQUIRED
    edtags = np.zeros(len(raw.edges), np.int32)
    edtags[raw.req_edges] |= tags.REQUIRED
    edtags[raw.ridges] |= tags.RIDGE
    return Mesh.from_numpy(
        raw.verts,
        raw.tets,
        vrefs=raw.vrefs,
        trefs=raw.trefs,
        trias=raw.trias,
        trrefs=raw.trrefs,
        edges=raw.edges,
        edrefs=raw.edrefs,
        vtags=vtags,
        trtags=trtags,
        edtags=edtags,
        met=met,
        **kw,
    )


def load_mesh(path: str, metpath: str | None = None, **kw) -> Mesh:
    """Centralized load: mesh file plus optional metric sol file."""
    raw = read_mesh(path)
    met = None
    if metpath is not None:
        if not os.path.exists(metpath):
            raise FileNotFoundError(f"metric sol file not found: {metpath}")
        vals, types = read_sol(metpath)
        if types[0] not in (SOL_SCALAR, SOL_TENSOR):
            raise ValueError("metric sol must be scalar or symmetric tensor")
        met = vals[:, : _SOL_NCOMP[types[0]]]  # first solution only
    return raw_to_mesh(raw, met=met, **kw)


def _fmt_block(f, name: str, arr: np.ndarray, refs: np.ndarray | None, one_based):
    cnt = arr.shape[0]
    if cnt == 0:
        return
    f.write(f"\n{name}\n{cnt}\n")
    if arr.dtype.kind in "iu":
        body = arr + (1 if one_based else 0)
        if refs is not None:
            body = np.concatenate([body, refs[:, None]], axis=1)
        np.savetxt(f, body, fmt="%d")
    else:
        cols = ["%.15g"] * arr.shape[1]
        if refs is not None:
            body = np.concatenate([arr, refs[:, None].astype(np.float64)], axis=1)
            np.savetxt(f, body, fmt=" ".join(cols + ["%d"]))
        else:
            np.savetxt(f, arr, fmt=" ".join(cols))


def save_mesh(
    mesh: Mesh,
    path: str,
    *,
    face_comms: Sequence[Tuple[int, np.ndarray, np.ndarray]] | None = None,
    node_comms: Sequence[Tuple[int, np.ndarray, np.ndarray]] | None = None,
    binary: bool | None = None,
) -> None:
    """Write a (centralized or per-shard) Medit file. `binary=None`
    dispatches on the extension like the reference (`.meshb` → binary,
    `MMG3D_openMesh` extension rule)."""
    if binary is None:
        binary = os.path.splitext(path)[1] in (".meshb", ".solb")
    d = mesh.to_numpy()
    vt = d["vtags"]
    # 0-based id sections, derived once for both encodings
    corners = np.nonzero(vt & tags.CORNER)[0]
    req = np.nonzero(
        ((vt & tags.REQUIRED) != 0) & ((vt & tags.CORNER) == 0)
    )[0]
    ridges = np.nonzero(d["edtags"] & tags.RIDGE)[0]
    req_ed = np.nonzero(d["edtags"] & tags.REQUIRED)[0]
    # pure synthetic interface trias are excluded: their REQUIRED is
    # split-added and restored from the face-comm sections on load;
    # PARBDYBDY (real-surface) interface trias stay listed here, which
    # is what lets the loader tell the two kinds apart
    req_tr = np.nonzero(
        ((d["trtags"] & tags.REQUIRED) != 0)
        & ~tags.pure_interface_tria(d["trtags"])
    )[0]
    d["idsections"] = [
        ("Corners", corners),
        ("RequiredVertices", req),
        ("Ridges", ridges),
        ("RequiredEdges", req_ed),
        ("RequiredTriangles", req_tr),
    ]
    # communicator local ids are mesh slot ids; entity sections are
    # written in compacted numbering, so remap through the same maps
    tr_live = np.asarray(mesh.trmask)
    v_live = np.asarray(mesh.vmask)
    tr_new = np.cumsum(tr_live) - 1
    v_new = np.cumsum(v_live) - 1
    comm_sections = []
    for kw_head, kw_items, comms, live, renum in (
        ("ParallelTriangleCommunicators", "ParallelCommunicatorTriangles",
         face_comms, tr_live, tr_new),
        ("ParallelVertexCommunicators", "ParallelCommunicatorVertices",
         node_comms, v_live, v_new),
    ):
        if not comms:
            continue
        remapped = []
        for color, loc, glob in comms:
            loc = np.asarray(loc)
            if not live[loc].all():
                raise ValueError(
                    f"communicator (color {color}) references deleted "
                    f"entities; cannot save"
                )
            remapped.append((color, renum[loc], np.asarray(glob)))
        comm_sections.append((kw_head, kw_items, remapped))

    if binary:
        _save_mesh_binary(path, d, comm_sections)
        return
    with atomic_replace(path, "w") as f:
        f.write("MeshVersionFormatted 2\n\nDimension 3\n")
        _fmt_block(f, "Vertices", d["verts"], d["vrefs"], True)
        _fmt_block(f, "Tetrahedra", d["tets"], d["trefs"], True)
        _fmt_block(f, "Triangles", d["trias"], d["trrefs"], True)
        _fmt_block(f, "Edges", d["edges"], d["edrefs"], True)
        for name, ids in d["idsections"]:
            _fmt_block(f, name, ids[:, None] + 1, None, False)
        for kw_head, kw_items, remapped in comm_sections:
            f.write(f"\n{kw_head}\n{len(remapped)}\n")
            for color, loc, glob in remapped:
                f.write(f"{color} {len(loc)}\n")
            f.write(f"\n{kw_items}\n")
            for icomm, (color, loc, glob) in enumerate(remapped):
                for l, g in zip(loc, glob):
                    f.write(f"{l + 1} {g} {icomm}\n")
        f.write("\nEnd\n")


def save_sol(
    path: str, values: np.ndarray, types: Sequence[int], dim: int = 3,
    binary: bool | None = None,
) -> None:
    values = np.asarray(values)
    if binary is None:
        binary = os.path.splitext(path)[1] in (".meshb", ".solb")
    if binary:
        w = _BinWriter(path)
        try:
            w.section("Dimension", b"", [dim])
            payload = (
                np.array(types, "<i4").tobytes()
                + np.ascontiguousarray(values, "<f8").tobytes()
            )
            w.section(
                "SolAtVertices", payload, [values.shape[0], len(types)]
            )
            w.end()
        except BaseException:
            w.abort()
            raise
        return
    with atomic_replace(path, "w") as f:
        f.write(f"MeshVersionFormatted 2\n\nDimension {dim}\n\nSolAtVertices\n")
        f.write(f"{values.shape[0]}\n{len(types)} {' '.join(map(str, types))}\n")
        np.savetxt(f, values, fmt="%.15g")
        f.write("\nEnd\n")


def save_met(mesh: Mesh, path: str) -> None:
    d = mesh.to_numpy()
    t = SOL_TENSOR if mesh.aniso else SOL_SCALAR
    save_sol(path, d["met"], [t])


_NCOMP_SOL = {v: k for k, v in _SOL_NCOMP.items()}


def save_fields(mesh: Mesh, path: str) -> None:
    """Save the interpolated solution fields (`-field` output, the
    `PMMG_saveAllSols_centralized` role, reference `src/parmmg.c:433`)."""
    d = mesh.to_numpy()
    types = [_NCOMP_SOL[nc] for nc in d["field_ncomp"]]
    save_sol(path, d["fields"], types)


def load_fields(path: str):
    """Read a solution-fields sol file: (values [n, sum(ncomp)], ncomp
    tuple) for Mesh.from_numpy's fields/field_ncomp."""
    vals, types = read_sol(path)
    return vals, tuple(_SOL_NCOMP[t] for t in types)


def shard_filename(path: str, rank: int) -> str:
    """`name.mesh -> name.<rank>.mesh` (reference `PMMG_insert_rankIndex:387`)."""
    base, ext = os.path.splitext(path)
    return f"{base}.{rank}{ext}"


def met_filename(path: str) -> str:
    """Metric sol name next to a mesh path, with the encoding following
    the mesh encoding (`.meshb` -> `.solb`, like the reference's metout
    naming) — the one definition shared by the CLI and distributed
    writers."""
    base, ext = os.path.splitext(path)
    return base + (".solb" if ext == ".meshb" else ".sol")


def save_mesh_distributed(stacked: Mesh, comm, path: str,
                          with_met: bool = False) -> None:
    """Write per-shard `name.<rank>.mesh` files with the parallel
    interface as `ParallelVertexCommunicators` sections — the
    distributed-output path of the reference
    (`PMMG_saveMesh_distributed`, `src/inout_pmmg.c:798`). The node
    tables come from the live `ShardComm` (colors = neighbor shard ids,
    global ids from `l2g`), so a later `load_mesh_distributed` restores
    an equivalent ShardComm: the checkpoint/resume loop of SURVEY §5."""
    from ..parallel.distribute import unstack_mesh

    comm_idx = np.asarray(comm.comm_idx)
    counts = np.asarray(comm.counts)
    l2g = np.asarray(comm.l2g)
    D = comm_idx.shape[0]
    for s, m in enumerate(unstack_mesh(stacked)):
        node_comms = []
        for r in range(D):
            c = int(counts[s, r])
            if r == s or c == 0:
                continue
            loc = comm_idx[s, r, :c]
            node_comms.append((r, loc, l2g[s][loc]))
        # Interface trias carrying a split-added NOSURF (both the pure
        # synthetic ones and real-surface PARBDYBDY replicas) are persisted
        # as face-comm sections so a reloaded run restores the
        # MG_PARBDY/MG_NOSURF distinction — Medit's RequiredTriangles alone
        # cannot carry it and the resumed run would otherwise freeze these
        # faces as plain REQUIRED surface (reference stores its face
        # communicators the same way, `src/inout_pmmg.c:798`). The loader
        # tells the kinds apart by RequiredTriangles membership: pure
        # synthetic trias are excluded from it (see save_mesh), PARBDYBDY
        # ones stay in.
        trtag_s = np.asarray(m.trtag)
        syn = (
            np.asarray(m.trmask)
            & ((trtag_s & tags.PARBDY) != 0)
            & ((trtag_s & tags.NOSURF) != 0)
        )
        tria_ids = np.nonzero(syn)[0]
        face_comms = []
        if len(tria_ids):
            member = np.zeros((l2g.shape[1], D), bool)
            for r in range(D):
                c = int(counts[s, r])
                if r != s and c:
                    member[comm_idx[s, r, :c], r] = True
            tv = np.asarray(m.tria)[tria_ids]
            in_r = member[tv].all(axis=1)  # [K, D]
            # the neighbor sharing all three vertices (exists by
            # construction: a synthetic tria is a tet face between
            # exactly two shards); argmax falls back to 0 harmlessly —
            # the loader unions the lists and ignores colors
            color = np.argmax(in_r, axis=1)
            for r in np.unique(color):
                sel = color == r
                face_comms.append(
                    (int(r), tria_ids[sel], np.zeros(int(sel.sum()), np.int64))
                )
        save_mesh(m, shard_filename(path, s), node_comms=node_comms,
                  face_comms=face_comms or None)
        if with_met:
            save_met(m, met_filename(shard_filename(path, s)))


def load_mesh_distributed(path: str, nparts: int, metpath: str | None = None,
                          **kw):
    """Read per-shard `name.<rank>.mesh` files (+ optional per-shard
    metric sols) and rebuild (stacked Mesh, ShardComm) — the reference's
    `PMMG_loadMesh_distributed` + communicator build
    (`src/inout_pmmg.c:440`, `src/libparmmg.c:206-314`)."""
    from ..parallel.distribute import stack_loaded_shards

    raws = [read_mesh(shard_filename(path, s)) for s in range(nparts)]
    stacked, comm = stack_loaded_shards(raws, **kw)
    if metpath is not None:
        import jax.numpy as jnp

        mets = []
        for s in range(nparts):
            vals, types = read_sol(shard_filename(metpath, s))
            ncomp = _SOL_NCOMP[types[0]]
            met = np.ones((stacked.met.shape[1], ncomp))
            met[: len(vals)] = vals[:, :ncomp]
            mets.append(met)
        stacked = stacked.replace(
            met=jnp.asarray(np.stack(mets), stacked.vert.dtype),
            met_set=True,
        )
    return stacked, comm
