"""Real Google Cloud Storage adapter behind the `CheckpointStore`
interface — the production durability backend the `ObjectStore`
semantics (single-object atomic put, manifest-last commit) were
modeled on.

Pure stdlib HTTP (`urllib`) against the GCS JSON/upload API; no cloud
SDK dependency, so the adapter runs anywhere the container runs and is
testable hermetically against the in-repo fake server
(``tests/fake_gcs.py``). Three pieces:

- **typed retry-status taxonomy** (:func:`classify_http_status`): the
  HTTP failure surface is split into RETRYABLE (408 request timeout,
  429 rate limit — with the server's ``Retry-After`` hint honored as a
  floor inside the seeded backoff of `utils.retry.retry` — and every
  5xx, plus transport-level failures: refused/dropped connections,
  truncated bodies, socket timeouts) raised as
  `io.ckpt_store.TransientStoreError`, and TERMINAL statuses raised as
  `CheckpointIOError` subtypes the retry envelope refuses to retry:

  ======  ==========================================================
  status  outcome
  ======  ==========================================================
  408     retry (request timeout)
  429     retry, ``Retry-After`` floors the next seeded delay
  5xx     retry (server fault)
  401/403 `CheckpointAuthError` — rotate the credential, not retry
  404     `CheckpointNotFoundError` (also ``FileNotFoundError``)
  412     `CheckpointPreconditionError` — lost conditional write
  other   `CheckpointIOError`
  ======  ==========================================================

- **pluggable auth** (:func:`resolve_token_provider`): a zero-arg
  callable returning a bearer token or ``None`` (anonymous). Built-in
  providers: the ``PMMGTPU_GCS_TOKEN`` env token (read per request, so
  an external refresher can rotate it), the GCE metadata server
  (cached until shortly before expiry), and anonymous (the fake
  server / public buckets).

- **conditional commit tokens**: `publish` routes through an
  ``if-generation-match`` put — the object's current generation is
  read and the upload is accepted only if it still holds (generation 0
  = "only create"). Under concurrent publishers exactly one manifest
  write wins; the loser gets the typed 412 instead of silently
  un-committing the winner's epoch.

Env contract (all optional):

  PMMGTPU_GCS_ENDPOINT  API base URL (default
                        ``https://storage.googleapis.com``; point it
                        at a fake/emulator for hermetic runs)
  PMMGTPU_GCS_TOKEN     static OAuth2 bearer token (env auth mode)
  PMMGTPU_GCS_AUTH      ``env`` | ``metadata`` | ``anon`` — forces an
                        auth mode; default: ``env`` when a token is
                        set, ``metadata`` against the real Google
                        endpoint, ``anon`` against anything else
  PMMGTPU_GCS_METADATA  metadata-server base URL override (tests)

Retry attempts/backoff/per-op timeout ride the shared PMMGTPU_CKPT_*
contract through `ckpt_store.make_store` (``gs://bucket/prefix``
specs resolve here); the fault-injection hook (`FaultPlan.io_fault`,
the ``ckpt`` fault phase) applies unchanged through the base class's
retry envelope.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, List, Optional

from .ckpt_store import (
    CheckpointAuthError,
    CheckpointIOError,
    CheckpointNotFoundError,
    CheckpointPreconditionError,
    CheckpointStore,
    TransientStoreError,
)

DEFAULT_ENDPOINT = "https://storage.googleapis.com"
_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """Seconds from a ``Retry-After`` header (delta-seconds form; the
    HTTP-date form is ignored rather than parsed against a wall clock
    the seeded backoff must not depend on)."""
    if not value:
        return None
    try:
        return max(float(value), 0.0)
    except ValueError:
        return None


def classify_http_status(status: int, what: str,
                         retry_after: Optional[str] = None,
                         detail: str = "") -> OSError:
    """The typed retry-status taxonomy: map an HTTP failure status to
    the exception the store attempt should raise (returned, not
    raised, so the mapping is unit-testable standalone)."""
    msg = f"GCS {what}: HTTP {status}"
    if detail:
        msg += f": {detail}"
    if status in (408, 429) or 500 <= status < 600:
        return TransientStoreError(
            msg, status=status,
            retry_after=_parse_retry_after(retry_after),
        )
    if status in (401, 403):
        return CheckpointAuthError(
            f"{msg} — credential rejected (check PMMGTPU_GCS_TOKEN / "
            "PMMGTPU_GCS_AUTH and the bucket ACL)"
        )
    if status == 404:
        return CheckpointNotFoundError(msg)
    if status == 412:
        return CheckpointPreconditionError(
            f"{msg} — conditional write lost its if-generation-match "
            "guard (a concurrent publisher committed first)"
        )
    return CheckpointIOError(msg)


# ---------------------------------------------------------------------------
# auth-token providers
# ---------------------------------------------------------------------------


def env_token_provider() -> Optional[str]:
    """The PMMGTPU_GCS_TOKEN bearer token, read per request so an
    external refresher can rotate the env var without a restart."""
    return os.environ.get("PMMGTPU_GCS_TOKEN") or None


class MetadataTokenProvider:
    """GCE/Cloud-TPU metadata-server token, cached until 60 s before
    its advertised expiry (the standard refresh discipline)."""

    def __init__(self, url: Optional[str] = None,
                 http_timeout: float = 5.0):
        self.url = url or os.environ.get(
            "PMMGTPU_GCS_METADATA"
        ) or _METADATA_URL
        self.http_timeout = http_timeout
        self._token: Optional[str] = None
        self._expiry = 0.0

    def __call__(self) -> Optional[str]:
        now = time.monotonic()
        if self._token is not None and now < self._expiry:
            return self._token
        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.http_timeout
            ) as resp:
                doc = json.loads(resp.read().decode())
        except (OSError, ValueError, http.client.HTTPException) as e:
            raise CheckpointAuthError(
                f"GCS metadata-server token fetch failed ({e}); set "
                "PMMGTPU_GCS_TOKEN or PMMGTPU_GCS_AUTH=anon"
            ) from e
        self._token = doc.get("access_token")
        self._expiry = now + float(doc.get("expires_in", 0)) - 60.0
        return self._token


def resolve_token_provider(
    endpoint: str,
) -> Optional[Callable[[], Optional[str]]]:
    """Auth mode per the env contract: explicit ``PMMGTPU_GCS_AUTH``
    wins; otherwise a set token means env auth, the real Google
    endpoint means metadata auth, and anything else (a fake server, an
    emulator) defaults to anonymous."""
    mode = (os.environ.get("PMMGTPU_GCS_AUTH") or "").strip().lower()
    if mode in ("anon", "anonymous", "none"):
        return None
    if mode == "env":
        return env_token_provider
    if mode == "metadata":
        return MetadataTokenProvider()
    if mode:
        raise ValueError(
            f"PMMGTPU_GCS_AUTH={mode!r} not one of env|metadata|anon"
        )
    if os.environ.get("PMMGTPU_GCS_TOKEN"):
        return env_token_provider
    if endpoint.rstrip("/") == DEFAULT_ENDPOINT:
        return MetadataTokenProvider()
    return None


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


class GCSStore(CheckpointStore):
    """Checkpoint store over a real GCS bucket (JSON/upload API).

    Every raw primitive is ONE bounded HTTP request whose failure is
    classified by :func:`classify_http_status` — the base class's
    retry/timeout/fault envelope then drives the retryable half
    (seeded backoff, ``Retry-After`` floors) and propagates the
    terminal half typed. Object names are flat (the checkpoint
    protocol's contract) under an optional ``prefix/``."""

    def __init__(self, bucket: str, prefix: str = "", *,
                 endpoint: Optional[str] = None,
                 token_provider=None,
                 http_timeout: Optional[float] = None, **kw):
        super().__init__(**kw)
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        if self.prefix:
            self.prefix += "/"
        self.endpoint = (
            endpoint
            or os.environ.get("PMMGTPU_GCS_ENDPOINT")
            or DEFAULT_ENDPOINT
        ).rstrip("/")
        self.token_provider = (
            token_provider if token_provider is not None
            else resolve_token_provider(self.endpoint)
        )
        # socket-level deadline: the per-op watchdog (self.timeout)
        # ABANDONS a stalled request thread; this bound makes the
        # abandoned request itself die instead of holding a connection
        # forever
        self.http_timeout = float(
            http_timeout if http_timeout is not None
            else (self.timeout or 20.0)
        )

    @classmethod
    def from_url(cls, url: str, **kw) -> "GCSStore":
        """``gs://bucket[/prefix]`` → a configured store (the
        `ckpt_store.make_store` entry point)."""
        rest = url[5:] if url.startswith("gs://") else url
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"no bucket in GCS url {url!r}")
        return cls(bucket, prefix, **kw)

    def __repr__(self) -> str:
        return (f"GCSStore(gs://{self.bucket}/{self.prefix} "
                f"via {self.endpoint})")

    # -- HTTP plumbing ---------------------------------------------------
    def _request(self, method: str, url: str, what: str,
                 data: Optional[bytes] = None,
                 headers: Optional[dict] = None) -> bytes:
        req = urllib.request.Request(url, data=data, method=method)
        if self.token_provider is not None:
            tok = self.token_provider()
            if tok:
                req.add_header("Authorization", f"Bearer {tok}")
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(
                req, timeout=self.http_timeout
            ) as resp:
                body = resp.read()
                want = resp.headers.get("Content-Length")
                if want is not None and len(body) != int(want):
                    raise TransientStoreError(
                        f"GCS {what}: truncated body "
                        f"({len(body)}/{want} bytes)"
                    )
                return body
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read(200).decode("utf-8", "replace")
            except OSError:
                pass
            raise classify_http_status(
                e.code, what, retry_after=e.headers.get("Retry-After"),
                detail=detail,
            ) from None
        except urllib.error.URLError as e:
            raise TransientStoreError(
                f"GCS {what}: connection failed: {e.reason}"
            ) from e
        except (http.client.HTTPException, socket.timeout,
                TimeoutError, ConnectionError) as e:
            # IncompleteRead (a truncated body detected by the client),
            # reset connections, socket deadlines: all transient
            raise TransientStoreError(
                f"GCS {what}: transport error: {e!r}"
            ) from e

    def _obj_url(self, name: str, **params) -> str:
        quoted = urllib.parse.quote(self.prefix + name, safe="")
        url = f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{quoted}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    # -- raw primitives --------------------------------------------------
    def _put(self, name: str, data: bytes,
             generation_match: Optional[int] = None) -> None:
        params = {"uploadType": "media", "name": self.prefix + name}
        if generation_match is not None:
            params["ifGenerationMatch"] = str(generation_match)
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?"
               + urllib.parse.urlencode(params))
        self._request(
            "POST", url, f"put {name!r}", data=bytes(data),
            headers={"Content-Type": "application/octet-stream"},
        )

    def _generation(self, name: str) -> int:
        """Current generation of `name`, or 0 when absent — exactly the
        ``ifGenerationMatch`` value meaning "only create"."""
        try:
            body = self._request(
                "GET", self._obj_url(name, fields="generation"),
                f"stat {name!r}",
            )
        except FileNotFoundError:
            return 0
        return int(json.loads(body).get("generation", 0))

    def _publish(self, name: str, data: bytes) -> None:
        """Conditional commit-token put: re-reads the generation on
        every attempt (a retried publish whose first upload landed but
        whose response was lost sees its OWN new generation and
        overwrites idempotently; a genuine concurrent publisher
        surfaces as the typed 412)."""
        self._put(name, data, generation_match=self._generation(name))

    def _get(self, name: str) -> bytes:
        return self._request(
            "GET", self._obj_url(name, alt="media"), f"get {name!r}"
        )

    def _list(self) -> List[str]:
        names: List[str] = []
        token: Optional[str] = None
        base = f"{self.endpoint}/storage/v1/b/{self.bucket}/o"
        while True:
            params = {"fields": "items(name),nextPageToken"}
            if self.prefix:
                params["prefix"] = self.prefix
            if token:
                params["pageToken"] = token
            doc = json.loads(self._request(
                "GET", base + "?" + urllib.parse.urlencode(params),
                "list",
            ))
            for item in doc.get("items") or ():
                n = item.get("name", "")
                if n.startswith(self.prefix):
                    names.append(n[len(self.prefix):])
            token = doc.get("nextPageToken")
            if not token:
                return sorted(names)

    def _delete(self, name: str) -> None:
        self._request("DELETE", self._obj_url(name), f"delete {name!r}")
