"""ctypes bridge to the optional C++ tokenizer in native/medit_tok.cpp.

The reference's I/O layer is native C (`src/inout_pmmg.c`); here only the
hot tokenization loop is native — parsing/assembly stays in numpy. Falls
back silently to the pure-Python tokenizer when the shared library has not
been built (see native/build.sh)."""

from __future__ import annotations

import ctypes
import os
from typing import List

_LIB = None
_TRIED = False


def _lib_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "native", "libmedit_tok.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        # first use: try a quiet in-tree build (g++ is part of the
        # supported toolchain); any failure falls back to pure Python
        import subprocess

        try:
            subprocess.run(
                ["sh", os.path.join(os.path.dirname(path), "build.sh")],
                capture_output=True, timeout=120, check=False,
            )
        except Exception:
            pass
    if os.path.exists(path):
        try:
            lib = ctypes.CDLL(path)
            lib.medit_tokenize.restype = ctypes.c_void_p
            lib.medit_tokenize.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.medit_free.argtypes = [ctypes.c_void_p]
            _LIB = lib
        except OSError:
            _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def tokenize(path: str) -> List[str]:
    lib = _load()
    n = ctypes.c_long(0)
    buf = lib.medit_tokenize(path.encode(), ctypes.byref(n))
    if not buf:
        raise IOError(f"native tokenizer failed on {path}")
    try:
        raw = ctypes.string_at(buf, n.value)
    finally:
        lib.medit_free(buf)
    return raw.decode().split("\x00")
