"""CLI: `python -m parmmg_tpu input.mesh [-sol met.sol] [options] [-out out.mesh]`.

The `parmmg` executable role (reference `src/parmmg.c:60` with the flag
set of `PMMG_parsar`, `src/libparmmg_tools.c:108-163`), on the TPU
framework: load → adapt (single-shard or distributed over -nparts
shards) → save, printing the reference-style quality histograms.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m parmmg_tpu",
        description="TPU-native parallel tetrahedral remesher "
        "(capability parity with the ParMmg CLI)",
    )
    p.add_argument("input", nargs="?", default=None,
                   help="input .mesh (Medit ASCII)")
    p.add_argument("-out", "-o", dest="out", default=None,
                   help="output mesh name (default <input>.o.mesh)")
    p.add_argument("-sol", "-met", dest="sol", default=None,
                   help="metric .sol file")
    p.add_argument("-field", dest="field", default=None,
                   help="solution-field .sol to interpolate from the "
                   "input onto the adapted mesh")
    p.add_argument("-noout", action="store_true",
                   help="do not write the output mesh")
    p.add_argument("-val", dest="print_val", action="store_true",
                   help="print the default parameter values and exit")
    p.add_argument("-v", dest="verbose", type=int, default=1,
                   help="verbosity level")
    p.add_argument("-m", dest="mem", type=float, default=None,
                   help="memory budget in MB per shard for mesh arrays")
    # remeshing controls (Mmg-forwarded flags)
    p.add_argument("-hsiz", type=float, default=None,
                   help="constant target edge size")
    p.add_argument("-hmin", type=float, default=None)
    p.add_argument("-hmax", type=float, default=None)
    p.add_argument("-hgrad", type=float, default=None,
                   help="size gradation ratio (<=0 disables)")
    p.add_argument("-hausd", type=float, default=None,
                   help="Hausdorff bound for boundary approximation")
    p.add_argument("-ar", dest="angle", type=float, default=45.0,
                   help="ridge-detection dihedral angle (degrees)")
    p.add_argument("-nr", dest="no_angle", action="store_true",
                   help="disable angle detection")
    p.add_argument("-hgradreq", type=float, default=None,
                   help="gradation ratio propagated from required "
                   "entities (<=0 disables)")
    p.add_argument("-optim", action="store_true",
                   help="keep mesh-implied sizes, only improve quality")
    p.add_argument("-optimLES", dest="optim_les", action="store_true",
                   help="strong mesh optimization for LES computations "
                   "(iso only)")
    p.add_argument("-A", dest="aniso", action="store_true",
                   help="enable anisotropy (without metric file)")
    p.add_argument("-nofem", action="store_true",
                   help="do not force a finite-element mesh (accepted "
                   "for parity; the batched operators never create the "
                   "non-FE configurations Mmg must repair)")
    p.add_argument("-rn", dest="renumber", action="store_true",
                   help="Morton-order renumbering for locality (the "
                   "reference's Scotch renumbering role)")
    p.add_argument("-noinsert", action="store_true")
    p.add_argument("-noswap", action="store_true")
    p.add_argument("-nomove", action="store_true")
    p.add_argument("-nofrontier", dest="nofrontier", action="store_true",
                   help="disable active-set (frontier) sweeps: full-table "
                        "candidate generation every sweep on every driver "
                        "(the A/B baseline for the frontier speedup; "
                        "frontier sweeps are exact-fallback-guarded and on "
                        "by default, distributed included)")
    p.add_argument("-nosurf", action="store_true",
                   help="freeze the boundary surface exactly")
    p.add_argument("-opnbdy", action="store_true",
                   help="preserve open internal boundaries (same-ref "
                        "internal trias) as adapted surface")
    # parallel controls
    p.add_argument("-niter", type=int, default=3,
                   help="outer remesh-repartition iterations")
    p.add_argument("-nparts", type=int, default=1,
                   help="number of shards (devices); 1 = single-chip")
    p.add_argument("-nobalance", dest="nobalancing", action="store_true",
                   help="disable interface displacement between iterations")
    p.add_argument("-balance", dest="balance_band", type=float,
                   default=None,
                   help="closed-loop balance band: measured work "
                        "imbalance (max/mean) above this forces a full "
                        "re-cut, with hysteresis (default 1.5, env "
                        "PMMGTPU_BALANCE_BAND; <= 0 disables)")
    p.add_argument("-nlayers", dest="ifc_layers", type=int, default=2,
                   help="interface-displacement advancing-front depth")
    p.add_argument("-groups-ratio", dest="grps_ratio", type=float,
                   default=2.0, help="max shard imbalance before SFC recut")
    p.add_argument("-mesh-size", dest="mesh_size", type=int, default=None,
                   help="remesher target size (maps to the per-shard "
                        "pre-split growth floor)")
    p.add_argument("-pure-partitioning", action="store_true",
                   help="partition + save only, no remeshing")
    p.add_argument("-distributed-output", dest="dist_out",
                   action="store_true",
                   help="save per-shard name.<rank>.mesh files")
    p.add_argument("-centralized-output", dest="cent_out",
                   action="store_true")
    p.add_argument("-distributed-input", dest="dist_in",
                   action="store_true",
                   help="input is per-shard name.<rank>.mesh files")
    p.add_argument("-ls", type=float, nargs="?", const=0.0, default=None,
                   help="level-set discretization at the given isovalue")
    p.add_argument("-ckpt", dest="ckpt", default=None,
                   help="checkpoint directory or store spec "
                        "(mem://bucket, file://dir); a compatible "
                        "checkpoint found there RESUMES the run — "
                        "elastically across world sizes")
    p.add_argument("-ckpt-every", dest="ckpt_every", type=int, default=1,
                   help="checkpoint cadence in outer iterations")
    p.add_argument("-ckpt-async", dest="ckpt_async", action="store_true",
                   help="stage checkpoints on a background writer "
                        "(blocks only on the previous epoch's commit)")
    return p


def print_default_values() -> None:
    """`-val`: print the default parameters (PMMG_defaultValues role,
    reference `src/libparmmg_tools.c`)."""
    from .models.distributed import DistOptions

    d = DistOptions()
    print("\nDefault parameters values:")
    print("\n** Generic options")
    print(f"verbosity (-v)          : {d.verbose}")
    print("\n** Parameters")
    print(f"niter (-niter)          : {d.niter}")
    print(f"nparts (-nparts)        : {d.nparts}")
    print(f"ifc layers (-nlayers)   : {d.ifc_layers}")
    print(f"groups ratio            : {d.grps_ratio}")
    from .parallel.migrate import BALANCE_BAND_DEFAULT

    print(f"balance band (-balance) : {d.balance_band or BALANCE_BAND_DEFAULT}")
    print(f"angle detection (-ar)   : {d.angle}")
    print(f"hgrad (-hgrad)          : {d.hgrad}")
    print(f"hgradreq (-hgradreq)    : {d.hgradreq or 'off'}")
    print("hausd (-hausd)          : 0.01 x bounding-box diagonal")
    print("hmin / hmax             : off")
    print(f"max sweeps per iter     : {d.max_sweeps}")
    print(f"memory budget (-m)      : {d.mem_budget_mb or 'unlimited'}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.print_val:
        print_default_values()
        return 0
    if args.input is None:
        build_parser().error("an input mesh is required")

    import numpy as np

    from .io import medit
    from .models.adapt import adapt
    from .models.distributed import (
        DistOptions,
        adapt_distributed,
        adapt_stacked_input,
        merge_adapted,
    )
    from .ops import quality
    from .utils.timing import Timers

    timers = Timers(enabled=args.verbose >= 1)
    out = args.out or (os.path.splitext(args.input)[0] + ".o.mesh")
    angle = None if args.no_angle else args.angle
    hgrad = (
        None if (args.hgrad is not None and args.hgrad <= 0)
        else (args.hgrad if args.hgrad is not None else 1.3)
    )
    hgradreq = (
        None if (args.hgradreq is None or args.hgradreq <= 0)
        else args.hgradreq
    )

    # local-parameter file (`PMMG_parsop`, reference
    # `src/libparmmg_tools.c:573`): <mesh>.mmg3d / DEFAULT.mmg3d
    from .io import parsop

    local_params = ()
    pf = parsop.default_param_file(args.input)
    if pf is not None:
        local_params = parsop.parse_local_params(pf)
        if args.verbose >= 1:
            print(f"  %% {pf}: {len(local_params)} local parameter(s)")

    opts = DistOptions(
        niter=args.niter,
        hsiz=args.hsiz, hmin=args.hmin, hmax=args.hmax,
        hgrad=hgrad, hgradreq=hgradreq, hausd=args.hausd, angle=angle,
        optim=args.optim or args.optim_les, optim_les=args.optim_les,
        aniso=args.aniso, nofem=args.nofem,
        local_params=local_params,
        noinsert=args.noinsert, noswap=args.noswap,
        nomove=args.nomove, nosurf=args.nosurf, opnbdy=args.opnbdy,
        verbose=args.verbose,
        mem_budget_mb=args.mem,
        nparts=args.nparts,
        nobalancing=args.nobalancing,
        balance_band=args.balance_band,
        ifc_layers=args.ifc_layers,
        grps_ratio=args.grps_ratio,
        frontier=not args.nofrontier,
    )
    if args.ckpt:
        # durable checkpoint/resume (failsafe layer): a path selects
        # the POSIX store, mem://&friends an object store spec
        if "://" in args.ckpt and not args.ckpt.startswith("file://"):
            opts.checkpoint_store = args.ckpt
        else:
            opts.checkpoint_dir = args.ckpt[7:] \
                if args.ckpt.startswith("file://") else args.ckpt
        opts.checkpoint_every = args.ckpt_every
        opts.checkpoint_async = args.ckpt_async
    if args.mesh_size:
        # the reference's remesher target size (-mesh-size,
        # PMMG_REMESHER_TARGET_MESH_SIZE role): per-shard growth floor
        opts.min_shard_elts = args.mesh_size

    fields = field_ncomp = None
    if args.field:
        if args.dist_in:
            # capability parity: the reference prints the same error
            # (`src/parmmg.c:300`)
            print("  ## Error: Distributed fields input not yet "
                  "implemented.", file=sys.stderr)
            return 1
        fields, field_ncomp = medit.load_fields(args.field)

    with timers.phase("input"):
        if args.dist_in:
            stacked, comm = medit.load_mesh_distributed(
                args.input, args.nparts, metpath=args.sol
            )
            mesh = None
        elif args.ls is not None:
            # in ls mode the sol file IS the level-set (reference
            # `src/parmmg.c:241-307` routing)
            raw = medit.read_mesh(args.input)
            ls = None
            if args.sol:
                vals, _types = medit.read_sol(args.sol)
                ls = vals[:, :1]
            mesh = medit.raw_to_mesh(raw, ls=ls)
        elif args.input.endswith(".vtu"):
            # input format sniffing (reference `src/parmmg.c:157-210`)
            from .io import vtk as vtk_io

            mesh = vtk_io.load_vtu(args.input)
        else:
            mesh = medit.load_mesh(args.input, args.sol)
        if fields is not None:
            # uniform attach for every centralized input format (the
            # fields sol is independent of the mesh file format)
            import jax.numpy as jnp

            npo = int(mesh.npoin)
            if len(fields) != npo:
                print(f"  ## Error: -field has {len(fields)} entries "
                      f"for {npo} vertices.", file=sys.stderr)
                return 1
            pad = np.zeros((mesh.pcap, fields.shape[1]))
            pad[:npo] = fields
            mesh = mesh.replace(
                fields=jnp.asarray(pad, mesh.dtype),
                field_ncomp=tuple(field_ncomp),
            )

    if args.ls is not None:
        try:
            from .models.levelset import discretize_levelset
        except ImportError:
            # capability parity with the reference, which gates -ls off
            # (`src/libparmmg.c:73-76`: "level-set discretization is not
            # yet available with parallel remeshing")
            print("  ## Error: level-set discretization is not yet "
                  "available with parallel remeshing. Exit program.",
                  file=sys.stderr)
            return 1
        with timers.phase("level-set"):
            if mesh is None:
                print("level-set mode requires centralized input",
                      file=sys.stderr)
                return 1
            mesh = discretize_levelset(mesh, isovalue=args.ls)

    if args.renumber and mesh is not None:
        from .core.adjacency import build_adjacency
        from .parallel.partition import renumber_sfc

        with timers.phase("renumbering"):
            mesh = build_adjacency(renumber_sfc(mesh))

    if args.pure_partitioning:
        import jax

        from .parallel.distribute import split_mesh
        from .parallel.partition import sfc_partition

        with timers.phase("partitioning"):
            part = np.asarray(
                jax.device_get(sfc_partition(mesh, args.nparts))
            )
            stacked, comm = split_mesh(mesh, part, args.nparts)
        with timers.phase("output"):
            medit.save_mesh_distributed(stacked, comm, out,
                                        with_met=mesh.met_set)
        timers.report()
        return 0

    with timers.phase("remeshing"):
        if args.dist_in:
            stacked, comm, info = adapt_stacked_input(stacked, comm, opts)
            mesh_out = None
        elif args.nparts > 1:
            stacked, comm, info = adapt_distributed(mesh, opts)
            mesh_out = None
        else:
            # DistOptions extends AdaptOptions: the single-shard driver
            # just ignores the redistribution fields
            mesh_out, info = adapt(mesh, opts)

    if args.verbose >= 1:
        print(quality.format_histogram(info["qual_in"],
                                       "INPUT MESH QUALITY"))
        print(quality.format_histogram(info["qual_out"],
                                       "OUTPUT MESH QUALITY"))
        if mesh_out is not None:
            # edge-length histogram (PMMG_prilen role)
            from .core import adjacency as adj

            m_l = adj.build_adjacency(mesh_out)
            ecap_l = int(m_l.tcap * 1.7) + 64
            e_l, em_l, _, _ = adj.unique_edges(m_l, ecap_l)
            print(quality.format_length_stats(
                quality.length_stats(m_l, e_l, em_l)
            ))

    if args.noout:
        timers.report()
        return 0

    with timers.phase("output"):
        # output mode follows the input mode unless overridden: distributed
        # input defaults to distributed output, centralized input to
        # centralized, -distributed-output/-centralized-output force
        # (reference `PMMG_IPARAM_distributedOutput` + parsar discipline)
        distributed_out = not args.cent_out and (args.dist_out or args.dist_in)
        vtk = out.endswith((".vtu", ".pvtu"))
        if distributed_out and mesh_out is None:
            if vtk:
                from .io import vtk as vtk_io

                vtk_io.save_pvtu(stacked, comm, out)
            else:
                medit.save_mesh_distributed(stacked, comm, out,
                                            with_met=True)
        elif distributed_out:
            # single-part run asked for distributed output: one rank file
            if vtk:
                from .io import vtk as vtk_io

                if out.endswith(".pvtu"):
                    # a .pvtu is an XML index over .vtu pieces — write the
                    # piece plus the one-piece index, not raw vtu content
                    # under a .pvtu name
                    import jax
                    import jax.numpy as jnp

                    stacked1 = jax.tree_util.tree_map(
                        lambda a: jnp.asarray(a)[None], mesh_out
                    )
                    vtk_io.save_pvtu(stacked1, None, out)
                else:
                    vtk_io.save_vtu(mesh_out, medit.shard_filename(out, 0))
            else:
                medit.save_mesh(mesh_out, medit.shard_filename(out, 0))
                medit.save_met(
                    mesh_out, medit.met_filename(medit.shard_filename(out, 0))
                )
        else:
            if mesh_out is None:
                mesh_out = merge_adapted(stacked, comm)
            if vtk:
                from .io import vtk as vtk_io

                vtk_io.save_vtu(mesh_out, out)
            else:
                medit.save_mesh(mesh_out, out)
                medit.save_met(mesh_out, medit.met_filename(out))
        # interpolated solution fields (`-field` round trip, reference
        # `src/parmmg.c:433`)
        if args.field and not vtk:
            if distributed_out and mesh_out is None:
                # per-shard fields next to the per-shard meshes, so the
                # numbering matches what was actually written (the
                # reference cannot write distributed fields at all)
                from .parallel.distribute import unstack_mesh

                for r, shard in enumerate(unstack_mesh(stacked)):
                    medit.save_fields(
                        shard,
                        os.path.splitext(medit.shard_filename(out, r))[0]
                        + ".fields.sol",
                    )
            else:
                if mesh_out is None:
                    mesh_out = merge_adapted(stacked, comm)
                medit.save_fields(
                    mesh_out, os.path.splitext(out)[0] + ".fields.sol"
                )
    timers.report()
    return 0


def _main_traced(argv=None) -> int:
    """CLI entry: run `main` and flush the process tracer afterwards,
    so the top-level Timers spans that close AFTER the driver's own
    flush (remeshing/output) still make it into the Chrome trace —
    the JSONL log has them either way (per-line flush). The typed
    checkpoint failures keep their documented exit codes here (the
    same contract the chaos workers honor): 88 = resume refusal,
    89 = checkpoint I/O abort (store retries exhausted, credential
    rejected, corrupt payload past every fallback)."""
    from . import failsafe
    from .io.ckpt_store import CheckpointIOError

    try:
        return main(argv)
    except failsafe.CheckpointMismatchError as e:
        print(f"parmmg_tpu: {e}", file=sys.stderr)
        return failsafe.MISMATCH_EXIT_CODE
    except CheckpointIOError as e:
        print(f"parmmg_tpu: {type(e).__name__}: {e}", file=sys.stderr)
        return failsafe.CKPT_IO_EXIT_CODE
    except failsafe.WorldReformError as e:
        # an elastic survivor under a fleet supervisor: 90 = "relaunch
        # me in the reformed world" (checkpoint committed)
        print(f"parmmg_tpu: {e}", file=sys.stderr)
        return failsafe.REFORM_EXIT_CODE
    finally:
        from .obs import trace as obs_trace

        obs_trace.get_tracer().flush()


if __name__ == "__main__":
    sys.exit(_main_traced())
