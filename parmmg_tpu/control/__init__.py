"""Closed-loop run control: spend the telemetry, don't just report it.

PRs 14-15 built post-mortem explainability — typed verdicts, drain
curves with ETA-to-empty, churn scores, `len/in_band` on every sweep
record. This package promotes that telemetry from reporting to
*control*: a :class:`RunGovernor` rides both drivers' sweep and
iteration boundaries, early-terminates a run the moment the rolling
`health.assess` would call it oscillating or stalled (refunding the
remaining sweep budget instead of burning it), caps the sweep loop at
the drain-curve ETA, and shortens `niter` when the frontier projects
drained. Every decision is a `control_decision` tracer event rendered
by ``obs_report --control`` — control never acts silently.

Off by default: arm with ``PMMGTPU_GOVERN=1`` or
``AdaptOptions(govern=True)``. The default stays off because an early
stop legitimately changes the result trajectory, and the tree's
equivalence gates (frontier on/off, chaos resume bit-identity, kernel
A/B) compare governor-free arms.
"""

from .governor import (  # noqa: F401
    GOVERN_ENV,
    IN_BAND_SLOPE_MIN,
    MIN_EVIDENCE_SWEEPS,
    RunGovernor,
    resolve_governor,
)

__all__ = [
    "GOVERN_ENV", "IN_BAND_SLOPE_MIN", "MIN_EVIDENCE_SWEEPS",
    "RunGovernor", "resolve_governor",
]
