"""The run governor: verdict-driven early termination and self-tuning
budgets at sweep/iteration boundaries.

Both drivers call :meth:`RunGovernor.check_sweep` after every operator
sweep and :meth:`RunGovernor.check_iteration` after every outer
iteration. The governor judges the SAME rolling-window
:func:`obs.health.assess` that post-mortem re-assessment uses
(``GOVERN_WINDOW``), so an in-run stop and a killed-run post-mortem
can never disagree on identical history rows. Three decision kinds:

- ``early_stop`` — the rolling verdict is ``oscillating`` or
  ``stalled`` with at least ``MIN_EVIDENCE_SWEEPS`` sweeps of evidence
  this iteration: the phase stops, the remaining sweep budget is
  refunded (counter ``control/refunded_sweeps``), and the final
  ``info["health"]`` carries the typed early-stop verdict. The stop is
  REFUSED (a ``hold`` decision) while ``len/in_band`` is still
  improving faster than ``IN_BAND_SLOPE_MIN`` per sweep — control
  never trades quality it can still see accruing.
- ``tune_budget`` — the frontier drain curve projects empty in fewer
  sweeps than the remaining budget: the sweep loop is capped at
  ETA + ``ETA_MARGIN`` and the difference refunded.
- ``shorten_niter`` — the frontier projects drained across iterations
  (a fully-skipped drained phase, or an iteration that performed zero
  operator work): the remaining outer iterations are dropped. An
  ``early_stop`` also ends the outer loop — the same metric would
  re-oscillate next iteration.

Every decision is emitted as a ``control_decision`` tracer event
(rendered by ``obs_report --control``); nothing here acts silently.
The governor holds NO device state and reads only the replicated host
history, so its decisions are identical on every rank of a
distributed world.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence

from ..obs import health as obs_health
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

__all__ = [
    "GOVERN_ENV", "IN_BAND_SLOPE_MIN", "MIN_EVIDENCE_SWEEPS",
    "ETA_MARGIN", "RunGovernor", "resolve_governor",
]

# master switch (AdaptOptions.govern=None defers here): "1"/"on" arms
# the governor, anything else leaves the drivers exactly as before
GOVERN_ENV = "PMMGTPU_GOVERN"

# an oscillating/stalled verdict is only acted on after this many
# sweeps of the current iteration — one flat sweep is not evidence
MIN_EVIDENCE_SWEEPS = 4

# refuse an early stop while in_band improves faster than this per
# sweep (PMMGTPU_GOVERN_SLOPE overrides): the run is still buying
# unit-length conformity with its budget
IN_BAND_SLOPE_MIN = 1e-3

# sweeps kept above the drain ETA when capping the budget — the linear
# extrapolation is optimistic on convex tails
ETA_MARGIN = 2


def _truthy(val: str) -> bool:
    return val.strip().lower() not in ("", "0", "off", "false", "no")


def resolve_governor(opts) -> Optional["RunGovernor"]:
    """The driver-side constructor: ``opts.govern`` when set, else the
    ``PMMGTPU_GOVERN`` env; returns None (no control) when unarmed."""
    armed = getattr(opts, "govern", None)
    if armed is None:
        armed = _truthy(os.environ.get(GOVERN_ENV, ""))
    if not armed:
        return None
    return RunGovernor(
        converge_frac=float(getattr(opts, "converge_frac", 0.005)),
    )


class RunGovernor:
    """Closed-loop controller over one driver run. Stateful: it
    accumulates decisions and refunds so :meth:`finalize` can fold
    them into the run's final health verdict."""

    def __init__(
        self,
        converge_frac: float = 0.005,
        window: Optional[int] = None,
        min_slope: Optional[float] = None,
        min_evidence: int = MIN_EVIDENCE_SWEEPS,
    ):
        if window is None:
            window = int(os.environ.get(
                "PMMGTPU_GOVERN_WINDOW", obs_health.GOVERN_WINDOW))
        if min_slope is None:
            min_slope = float(os.environ.get(
                "PMMGTPU_GOVERN_SLOPE", IN_BAND_SLOPE_MIN))
        self.converge_frac = converge_frac
        self.window = max(int(window), 2)
        self.min_slope = float(min_slope)
        self.min_evidence = int(min_evidence)
        self.refunded = 0
        self.decisions: List[dict] = []
        self.stop_info: Optional[dict] = None
        self._held_iters: set = set()

    # -- decision plumbing --------------------------------------------

    def _decide(self, action: str, **args) -> dict:
        d = dict(action=action, **args)
        self.decisions.append(d)
        obs_trace.emit_event("control_decision", **d)
        return d

    def _refund(self, n: int) -> None:
        if n > 0:
            self.refunded += n
            obs_metrics.registry().counter(
                "control/refunded_sweeps").inc(n)

    # -- sweep boundary -----------------------------------------------

    def check_sweep(self, history: Sequence[dict], it: int,
                    sweep: int, budget: int) -> dict:
        """Judge the run after sweep `sweep` (0-based) of iteration
        `it` against the current `budget`. Returns the decision dict;
        callers break the sweep loop on ``action == "early_stop"`` and
        adopt ``d["budget"]`` on ``action == "tune_budget"``."""
        done = sweep + 1
        tail = [r for r in obs_health.sweep_records(history)
                if r.get("iter", 0) == it]
        if len(tail) >= self.min_evidence and done < budget:
            verdict = obs_health.assess(
                history, converge_frac=self.converge_frac,
                max_sweeps=None, window=self.window)
            if verdict["verdict"] in ("oscillating", "stalled"):
                slope = obs_health.in_band_slope(
                    history, window=self.window)
                if slope is not None and slope > self.min_slope:
                    # quality still accruing: refuse the stop, once
                    # per iteration so a long hold doesn't spam
                    if it not in self._held_iters:
                        self._held_iters.add(it)
                        return self._decide(
                            "hold", it=it, sweep=done,
                            verdict=verdict["verdict"],
                            in_band_slope=round(slope, 6),
                            reason="in_band still improving "
                                   f"({slope:.2%}/sweep)")
                    return dict(action=None)
                refund = budget - done
                self._refund(refund)
                self.stop_info = dict(
                    verdict=verdict["verdict"],
                    reason=verdict["reason"], it=it, sweep=done,
                    refunded_sweeps=refund)
                return self._decide(
                    "early_stop", it=it, sweep=done,
                    verdict=verdict["verdict"], refunded=refund,
                    in_band_slope=None if slope is None
                    else round(slope, 6),
                    reason=verdict["reason"])
        # drain-ETA budget cap: only the current iteration's frontier
        # telemetry projects this loop's remaining work
        eta = obs_health.drain_curve(tail)["eta_sweeps"]
        if eta is not None:
            cap = done + int(math.ceil(eta)) + ETA_MARGIN
            if cap < budget:
                self._refund(budget - cap)
                return self._decide(
                    "tune_budget", it=it, sweep=done, budget=cap,
                    was=budget, eta_sweeps=eta,
                    reason=f"drain ETA {eta} sweeps caps budget "
                           f"{budget} -> {cap}")
        return dict(action=None)

    # -- iteration boundary -------------------------------------------

    def check_iteration(self, history: Sequence[dict], it: int,
                        niter: int) -> bool:
        """After iteration `it` (0-based) completed: True ends the
        outer loop (remaining iterations dropped)."""
        if it + 1 >= niter:
            return False
        if self.stop_info is not None:
            self._decide(
                "shorten_niter", it=it, niter=niter,
                reason="early-stop verdict "
                       f"'{self.stop_info['verdict']}' ends the run")
            return True
        tail = [r for r in obs_health.sweep_records(history)
                if r.get("iter", 0) == it]
        if not tail:
            return False
        last = tail[-1]
        drained = last.get("n_active", None) == 0 and last.get("skipped")
        idle = all(
            obs_health._ops(r) == 0 and not r.get("nmoved", 0)
            for r in tail)
        if drained or idle:
            self._decide(
                "shorten_niter", it=it, niter=niter,
                reason="frontier projects drained"
                if drained else "iteration performed zero operator "
                                "work",
            )
            return True
        return False

    # -- run end ------------------------------------------------------

    def finalize(self, verdict: dict) -> dict:
        """Fold the governor's outcome into the run's final health
        verdict (the dict that rides ``info["health"]`` and the
        ``health:verdict`` event)."""
        if self.stop_info is not None:
            verdict["verdict"] = self.stop_info["verdict"]
            verdict["reason"] = (
                "governor early stop: " + self.stop_info["reason"])
            verdict["early_stop"] = True
        verdict["control"] = dict(
            decisions=len(self.decisions),
            refunded_sweeps=self.refunded,
            window=self.window,
        )
        return verdict
