"""Distributed iterative remesh-repartition driver — the engine.

TPU-native counterpart of the reference's core runtime
(`PMMG_parmmglib1`, reference `src/libparmmg1.c:550-896`): the mesh is
partitioned into shards, each shard's interior is remeshed with frozen
(PARBDY) interfaces by batched operator sweeps, metrics/fields are
re-interpolated from a pre-remesh snapshot, communicator tables are
rebuilt, and interfaces are displaced so frozen bands become interior at
the next iteration.

Re-design notes (vs the reference's per-rank group loop):
 - all shards share one set of static capacities, so the per-shard remesh
   is ONE vmapped sweep over the leading shard axis — under `jit` with a
   sharded leading axis every device remeshes its shard simultaneously
   (the role of each MPI rank calling `MMG5_mmg3d1_delone` on its own
   groups, without host-side divergence).
 - communicator rebuild does not need the reference's face-vertex hash
   remap (`PMMG_update_face2intInterfaceTetra`, `src/libparmmg1.c:361`):
   interface vertices are frozen and carry persistent global ids in
   `Mesh.vglob`, which `compact()` renumbers consistently, so tables are
   re-derived by matching gids (sorted order both sides).
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache, partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import adjacency, tags
from ..core.mesh import Mesh, compact, compact_aux
from ..failsafe import CapacityError
from ..obs import (
    costs as obs_costs,
    health as obs_health,
    metrics as obs_metrics,
    trace as obs_trace,
)
from ..ops import analysis, interp, quality
from ..parallel.distribute import (
    ShardComm,
    assign_global_ids,
    merge_shards,
    rebuild_comm,
    split_mesh,
    unstack_mesh,
)
from ..parallel import partition as partition_mod
from ..parallel.partition import sfc_partition
from .adapt import (
    AdaptOptions,
    Frontier,
    adapt as adapt_single,
    estimate_target_ntet,
    pad_changed,
    prepare_metric,
    remesh_sweep,
    resolve_hausd,
    run_sweep_loop,
    stacked_frontier,
)


# ---------------------------------------------------------------------------
# stacked-mesh utilities (leading axis = shard)
# ---------------------------------------------------------------------------

def stacked_counts(st: Mesh) -> tuple[int, int, int, int]:
    """Max live counts across shards (capacity planning is per the largest
    shard, since capacities are uniform)."""
    return (
        int(jnp.max(jnp.sum(st.vmask, axis=1))),
        int(jnp.max(jnp.sum(st.tmask, axis=1))),
        int(jnp.max(jnp.sum(st.trmask, axis=1))),
        int(jnp.max(jnp.sum(st.edmask, axis=1))),
    )


def grow_stacked(
    st: Mesh,
    pcap: int | None = None,
    tcap: int | None = None,
    fcap: int | None = None,
    ecap: int | None = None,
) -> Mesh:
    """Grow capacities of a stacked mesh (pads axis 1, host-side) by
    delegating to the single source of truth, `Mesh.with_capacity`, per
    shard and restacking."""
    grown = [
        m.with_capacity(pcap, tcap, fcap, ecap) for m in unstack_mesh(st)
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *grown)


def _presize_for_target(st: Mesh, opts: AdaptOptions | None = None) -> Mesh:
    """Pre-size capacities for the predicted unit mesh (per-shard max) so
    the sweep compiles once per growth bucket at most. Skipped when the
    predicted size would blow the per-shard memory budget (presize is an
    optimization; real growth failures raise inside the iterations and
    degrade to LOWFAILURE)."""
    ests = [estimate_target_ntet(m) for m in unstack_mesh(st)]
    est_ne = int(max(ests) * 1.35) + 64
    if est_ne > st.tet.shape[1]:
        want = (
            max(st.vert.shape[1], est_ne // 5 + 64),
            est_ne,
            max(st.tria.shape[1], est_ne // 4 + 64),
            max(st.edge.shape[1], est_ne // 16 + 64),
        )
        if opts is not None:
            from .adapt import _check_budget

            try:
                _check_budget(st, opts, *want)
            except RuntimeError:
                return st
        st = grow_stacked(st, *want)
    return st


def ensure_capacity_stacked(st: Mesh, opts: AdaptOptions) -> Mesh:
    """Stacked analog of `models.adapt.ensure_capacity` (the reference's
    memory-budget role, `src/zaldy_pmmg.c`): grow when any shard crosses
    the utilization trigger."""
    npo, nte, ntr, ned = stacked_counts(st)
    g = opts.grow_factor

    def target(n, cap):
        if n > opts.grow_trigger * cap:
            return max(int(n * g) + 8, int(cap * g))
        return cap

    caps = (
        st.vert.shape[1], st.tet.shape[1], st.tria.shape[1], st.edge.shape[1]
    )
    want = (
        target(npo, caps[0]),
        target(nte, caps[1]),
        target(ntr, caps[2]),
        target(ned, caps[3]),
    )
    if want != caps:
        from .adapt import _check_budget

        # per-shard budget (uniform capacities = uniform per-shard cost)
        _check_budget(st, opts, *want)
        st = grow_stacked(st, *want)
    return st


# ---------------------------------------------------------------------------
# stacked remesh phase (one outer iteration's operator sweeps)
# ---------------------------------------------------------------------------

def _vsweep(st: Mesh, ecap: int, opts: AdaptOptions, hausd: float,
            frontier: Optional[Frontier] = None):
    from .adapt import UNFUSED_TCAP, _sweep_body

    # same fused/unfused dispatch as the single-shard engine: above
    # UNFUSED_TCAP TOTAL capacity, whole-program XLA scheduling costs
    # hours (PERF_NOTES round 4). The vmapped program's shapes scale
    # with nparts * per-shard tcap, so the guard compares the BATCHED
    # size. Vmapping the plain body keeps each constituent op its own
    # (batched) compiled program, since the inner jits remain compile
    # boundaries under vmap.
    total = st.tet.shape[0] * st.tet.shape[1]
    unfused = total > UNFUSED_TCAP
    body = _sweep_body if unfused else remesh_sweep
    kw = dict(
        ecap=ecap,
        noinsert=opts.noinsert,
        noswap=opts.noswap,
        nomove=opts.nomove,
        nosurf=opts.nosurf,
        hausd=hausd,
        # per-shard growth predicates are batched under vmap: the skip
        # would lower to select (both branches run) on the fused path
        # and is inexpressible on the unfused one — disabled so both
        # distributed paths stay result-equivalent (the single-shard
        # engine keeps it; a global cross-shard growth decision would
        # need the split phase and tail in separate vmapped calls)
        phase_skip=False,
    )
    if frontier is None:
        return jax.vmap(partial(body, fused=not unfused, **kw))(st)
    # frontier sweeps (round 8): `changed` and the cached tables are
    # per-shard (batched), while `dirty`/`adja_ok` ride HOST-SHARED
    # scalars (in_axes=None) — an unbatched predicate keeps the
    # table-staleness lax.conds real conditionals under vmap instead of
    # both-branches selects. fused=True on the unfused dispatch too:
    # the frontier conds there wrap only table rebuilds
    # (compact/unique_edges-class programs, which compile in seconds at
    # any shape) while the operator kernels remain their own inner-jit
    # compile boundaries under eager vmap.
    fr_axes = Frontier(
        changed=0, dirty=None, tables=(0, 0, 0, 0), adja_ok=None,
    )
    return jax.vmap(
        lambda m, fr: body(m, fused=True, frontier=fr, **kw),
        in_axes=(0, fr_axes),
    )(st, frontier)


def _use_spmd_sweeps() -> bool:
    """SPMD sweep dispatch: automatic under a multi-controller runtime
    (the sweeps are the dominant cost — they must actually distribute
    across processes), opt-in single-process via PMMGTPU_SPMD_SWEEPS=1
    (used by the multihost equivalence test to produce the bit-identical
    single-process reference run)."""
    import os

    if os.environ.get("PMMGTPU_SPMD_SWEEPS"):
        return True
    from ..parallel import multihost

    return multihost.is_multiprocess()


@lru_cache(maxsize=32)
def _spmd_sweep_fn(dmesh, ecap, noinsert, noswap, nomove, nosurf,
                   frontier=False):
    """One fused SPMD sweep program per (device mesh, capacity, flag)
    key. Memoized: building jit(shard_map(...)) inside `sweep_fn` made
    every sweep retrace from scratch (parmmg-lint PML004). `hausd` stays
    an OPERAND (replicated spec), not part of the key — it may be a
    traced per-reference table from `local_hausd_table`.

    With `frontier=True` the program additionally takes/returns a
    per-shard `Frontier` (sharded like the mesh). Inside `shard_map`
    every device runs its OWN program instance, so the frontier's
    `dirty`/`adja_ok` scalars are shard-varying and the table-staleness
    and no-candidate lax.conds branch PER DEVICE — a converged shard
    genuinely skips the rebuild/apply work its neighbors still pay for
    (the Omega_h compacted-candidate-stream discipline on the SPMD
    path)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.shard import AXIS, _squeeze, _unsqueeze

    if frontier:
        def body_fr(blk, hausd, frb):
            m = _squeeze(blk)
            fr = _squeeze(frb)
            m, stats, fro = remesh_sweep(
                m, ecap, noinsert=noinsert, noswap=noswap,
                nomove=nomove, nosurf=nosurf, hausd=hausd,
                fused=True, phase_skip=False, frontier=fr,
            )
            return (
                _unsqueeze(m),
                jax.tree_util.tree_map(lambda x: x[None], stats),
                _unsqueeze(fro),
            )

        # check_rep=False: this jax's shard_map has no replication rule
        # for pallas_call, which the sweep body reaches when the kernel
        # subsystem dispatches Pallas (every operand/output is
        # explicitly specced, so the check adds nothing here)
        return jax.jit(jax.shard_map(
            body_fr, mesh=dmesh, in_specs=(P(AXIS), P(), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS), P(AXIS)), check_rep=False,
        ))

    def body(blk, hausd):
        m = _squeeze(blk)
        m, stats = remesh_sweep(
            m, ecap, noinsert=noinsert, noswap=noswap,
            nomove=nomove, nosurf=nosurf, hausd=hausd,
            fused=True, phase_skip=False,
        )
        return _unsqueeze(m), jax.tree_util.tree_map(
            lambda x: x[None], stats
        )

    # check_rep=False: see body_fr above (pallas_call under shard_map)
    return jax.jit(jax.shard_map(
        body, mesh=dmesh, in_specs=(P(AXIS), P()),
        out_specs=(P(AXIS), P(AXIS)), check_rep=False,
    ))


def _rec_from_stats(s, stats) -> dict:
    """One host history record from per-shard SweepStats (device arrays
    on the vmapped path, gathered host numpy on the SPMD path):
    cross-shard aggregates like the legacy recs, plus the active-set
    telemetry — total candidates offered, the world active fraction and
    the per-shard fractions `obs.metrics`/`tools/obs_report.py`
    render."""
    def g(x):
        return np.asarray(jax.device_get(x))

    na = g(stats.n_active).astype(np.int64)
    nu = g(stats.n_unique).astype(np.int64)
    shard_ne = g(s.tmask).sum(axis=1).astype(np.int64)
    # unit-mesh telemetry: world sums of the per-shard band counts
    # (interface edges count once per owning shard — thin-band
    # approximation, see quality.reduce_length_stats)
    n_len_unit = int(g(stats.n_len_unit).astype(np.int64).sum())
    n_len_edges = int(g(stats.n_len_edges).astype(np.int64).sum())
    return dict(
        nsplit=int(g(stats.nsplit).sum()),
        ncollapse=int(g(stats.ncollapse).sum()),
        nswap=int(g(stats.nswap).sum()),
        nmoved=int(g(stats.nmoved).sum()),
        ne=int(shard_ne.sum()),
        np=int(g(s.vmask).sum()),
        n_unique=int(nu.max()),
        capped=bool(g(stats.split_capped).any()),
        n_active=int(na.sum()),
        active_fraction=round(
            float(na.sum()) / max(int(nu.sum()), 1), 6
        ),
        n_len_unit=n_len_unit,
        n_len_edges=n_len_edges,
        in_band=round(n_len_unit / max(n_len_edges, 1), 6),
        shard_active=[
            round(float(a) / max(int(u), 1), 4)
            for a, u in zip(na.tolist(), nu.tolist())
        ],
        # load-imbalance accounting: live tets per shard and the
        # max/mean factor (1.0 = perfectly even — the same shape as
        # the GRPS_RATIO rebalance trigger, so the report, the BENCH
        # record and the balance branch all speak one number)
        shard_ne=[int(x) for x in shard_ne.tolist()],
        imbalance=round(
            float(shard_ne.max()) / max(float(shard_ne.mean()), 1.0), 4
        ),
    )


def _drained_rec(st: Mesh, history: List[dict]) -> dict:
    """Synthetic zero-op record for a skipped (drained-frontier)
    converged sweep — same keys as a real record so every consumer
    (history sums, BENCH JSON series, `record_sweep`) stays uniform."""
    D = st.vert.shape[0]
    last_nu = 0
    for r in reversed(history):
        if r.get("n_unique"):
            last_nu = int(r["n_unique"])
            break
    # a drained sweep changes no edges: the unit-band fraction carries
    # forward from the last measured sweep
    last_band = None
    for r in reversed(history):
        if "in_band" in r:
            last_band = float(r["in_band"])
            break
    shard_ne = np.asarray(
        jax.device_get(jnp.sum(st.tmask, axis=1))
    ).astype(np.int64)
    rec = dict(
        nsplit=0, ncollapse=0, nswap=0, nmoved=0,
        ne=int(shard_ne.sum()),
        np=int(jax.device_get(jnp.sum(st.vmask))),
        n_unique=last_nu, capped=False, n_active=0,
        active_fraction=0.0, shard_active=[0.0] * D,
        shard_ne=[int(x) for x in shard_ne.tolist()],
        imbalance=round(
            float(shard_ne.max()) / max(float(shard_ne.mean()), 1.0), 4
        ),
        skipped=True,
    )
    if last_band is not None:
        rec["in_band"] = last_band
    return rec


def _frontier_stale(fr: Frontier, s: Mesh, ecap: int) -> bool:
    """Capacity growth or an edge-cap (emult) event changed the table
    shapes: the carried frontier must be re-seeded (changed masks
    survive — growth pads, ids are stable — but tables restart stale)."""
    return (
        fr.changed.shape[1] != s.vert.shape[1]
        or fr.tables[0].shape[1] != ecap
        or fr.tables[2].shape[1] != s.tet.shape[1]
    )


def _pad_changed1(changed, pcap: int):
    """Single-shard analog of `pad_changed`: [PC_old] -> [PC] (growth
    appends slots; ids are stable, the new tail is inactive)."""
    changed = jnp.asarray(changed, bool)
    pad = pcap - changed.shape[0]
    if pad > 0:
        changed = jnp.pad(changed, (0, pad))
    return changed


def _frontier_stale_shard(fr: Frontier, m: Mesh, ecap: int) -> bool:
    """Per-shard (unstacked) `_frontier_stale`: capacity growth or an
    edge-cap event changed this shard's table shapes."""
    return (
        fr.changed.shape[0] != m.vert.shape[0]
        or fr.tables[0].shape[0] != ecap
        or fr.tables[2].shape[0] != m.tet.shape[0]
    )


def _remesh_phase_shardlocal(
    st: Mesh, opts: AdaptOptions, emult: List[float], history: List[dict],
    it: int, hausd, fs=None, fr0=None, governor=None,
):
    """Above-UNFUSED_TCAP remesh phase with SHARD-LOCAL unfused
    dispatch: each process runs the per-op `_sweep_body` (fused=False —
    every constituent op its own compiled program, host-branched skips)
    only over the shards it OWNS under the global device mesh, then the
    world view is reassembled from local rows
    (`multihost.put_sharded_local_rows`) and replicated through the ONE
    `gather_stacked` collective per sweep that the SPMD path already
    pays. This replaces the former fallback where every process
    computed ALL shards through the replicated vmapped engine — compute
    that scaled with nparts exactly in the large-mesh regime sharding
    exists for.

    Owner/comm discipline is unchanged: host control flow stays
    replicated-deterministic because every decision (capacity growth,
    convergence, staleness RESETS) reads the gathered world state; the
    per-shard frontier staleness scalars stay shard-local concrete
    values as on the SPMD path (`_host_int` branches instead of
    `shard_map` conds — a converged shard skips its rebuilds without
    its neighbors paying). Per-sweep host records are world aggregates
    of the reassembled stats, so the sweep-loop exit is bit-identical
    on every process (the collective ledger stays in lockstep — one
    gather per sweep on every rank).

    Bit-equivalence to the replicated vmapped engine is digest-asserted
    by tests/test_m24_balance.py: a stricter staleness level is always
    exact and batched-vs-unbatched op parity holds (PR 7 property
    tests), so per-shard staleness may only ever run MORE exact
    rebuilds than the host-shared conservative max. Returns
    (stacked, changed | None) like `_remesh_phase_local`."""
    from ..parallel import multihost
    from ..parallel.shard import device_mesh, owned_shards
    from .adapt import _sweep_body, empty_frontier

    D = st.tet.shape[0]
    dmesh = device_mesh(D)
    multi = multihost.is_multiprocess()
    if multi:
        procs = {d.process_index for d in dmesh.devices.ravel().tolist()}
        if len(procs) != jax.process_count():
            # a process owning no shard of the D-device mesh cannot
            # contribute local rows (nor skip the gathers without
            # desyncing the ledger): fall back to the replicated
            # engine. Deterministic: dmesh is identical on every rank.
            return _remesh_phase_local(st, opts, emult, history, it,
                                       hausd, fr0=fr0,
                                       governor=governor)
    owned = owned_shards(dmesh)
    use_fr = bool(opts.frontier)
    frs: dict = {}
    wd = fs.watchdog if fs is not None else None
    tr = obs_trace.get_tracer()
    kw = dict(
        noinsert=opts.noinsert, noswap=opts.noswap, nomove=opts.nomove,
        nosurf=opts.nosurf, hausd=hausd,
        # phase skip disabled for result-equivalence across the
        # distributed dispatches (see _vsweep)
        phase_skip=False,
    )

    def sweep_fn(s, ecap):
        outs, stat_rows = [], []
        for i in owned:
            m = jax.tree_util.tree_map(lambda a, _i=i: a[_i], s)
            if use_fr:
                fr = frs.get(i)
                if fr is None or _frontier_stale_shard(fr, m, ecap):
                    if fr is not None:
                        # mid-loop growth: keep the changed mask,
                        # restart the tables stale (same discipline as
                        # the vmapped/SPMD engines)
                        chg = _pad_changed1(fr.changed, m.vert.shape[0])
                    elif fr0 is not None:
                        chg = _pad_changed1(
                            jnp.asarray(fr0, bool)[i], m.vert.shape[0]
                        )
                    else:
                        chg = None  # full frontier: exact full sweep
                    fr = empty_frontier(m, ecap)
                    if chg is not None:
                        fr = fr._replace(changed=chg)
                with tr.span("sweep_shard", it=it, shard=int(i)):
                    m, stats, fro = _sweep_body(
                        m, ecap, fused=False, frontier=fr, **kw
                    )
                frs[i] = fro
            else:
                with tr.span("sweep_shard", it=it, shard=int(i)):
                    m, stats = _sweep_body(m, ecap, fused=False, **kw)
            outs.append(m)
            stat_rows.append(stats)
        local = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)
        stats_l = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *stat_rows
        )
        if multi:
            sg = multihost.put_sharded_local_rows(local, dmesh)
            stg = multihost.put_sharded_local_rows(stats_l, dmesh)
            if fs is not None:
                # device-resident validation before the NaNs of a
                # poisoned shard could ride the allgather (same
                # discipline as the SPMD sweep path)
                fs.validate_sharded(sg, dmesh, it, phase="sweep")
            s2, stats_g = multihost.gather_stacked((sg, stg),
                                                   timeout=wd)
        else:
            s2, stats_g = local, stats_l
        return s2, _rec_from_stats(s2, stats_g)

    st = run_sweep_loop(
        st, opts, emult, history, it,
        ensure_fn=lambda s: ensure_capacity_stacked(s, opts),
        tcap_fn=lambda s: int(s.tet.shape[1]),
        sweep_fn=sweep_fn,
        governor=governor,
    )
    if not use_fr:
        return st, None
    pcap = st.vert.shape[1]
    if frs:
        loc = jnp.stack([
            _pad_changed1(frs[i].changed, pcap) for i in owned
        ])
        if multi:
            chg = multihost.gather_stacked(
                multihost.put_sharded_local_rows(loc, dmesh), timeout=wd
            )
        else:
            chg = loc
    else:
        chg = fr0 if fr0 is not None else jnp.ones((D, pcap), bool)
    return st, pad_changed(jnp.asarray(np.asarray(chg), bool), pcap)


def _remesh_phase_global(
    st: Mesh, opts: AdaptOptions, emult: List[float], history: List[dict],
    it: int, hausd, fs=None, fr0=None, governor=None,
):
    """Multi-process remesh phase: each sweep is ONE SPMD program over
    the global device mesh — with 2 processes owning 4 devices each, the
    per-shard sweeps execute on the devices of BOTH processes and any
    cross-shard collective rides the coordination transport (the DCN
    path), the role of each MPI rank running `MMG5_mmg3d1_delone` on its
    own groups (`src/libparmmg1.c:662-800`). Host control flow
    (capacity checks, convergence) is replicated-deterministic on every
    process, per the `parallel.multihost` contract: the stacked mesh is
    gathered back to host numpy after each sweep, so every other phase
    of `_one_iteration` runs unchanged.

    With `opts.frontier` the per-shard `Frontier` rides the sweep carry
    DEVICE-RESIDENT (sharded like the mesh, never gathered between
    sweeps); `dirty`/`adja_ok` are shard-varying, so each device's
    staleness conds branch independently — a converged shard stops
    paying for its neighbors' work. Returns (stacked, changed | None)
    like `_remesh_phase_local`."""
    from ..parallel import multihost
    from ..parallel.shard import device_mesh

    from .adapt import UNFUSED_TCAP

    D = st.tet.shape[0]
    if st.tet.shape[1] > UNFUSED_TCAP:
        # Above the compile-budget threshold the fused whole-sweep
        # program must not be built (whole-program XLA scheduling costs
        # hours at these shapes — PERF_NOTES r4); the per-op unfused
        # path cannot run inside one shard_map program, so dispatch the
        # shard-local unfused engine: each process sweeps only the
        # shards it owns and the world view is reassembled through one
        # gather per sweep (digest-identical to the replicated vmapped
        # engine it replaced — tests/test_m24_balance.py).
        return _remesh_phase_shardlocal(st, opts, emult, history, it,
                                        hausd, fs=fs, fr0=fr0,
                                        governor=governor)
    dmesh = device_mesh(D)
    use_fr = bool(opts.frontier)
    fr_cell: list = [None]
    wd = fs.watchdog if fs is not None else None

    def sweep_fn(s, ecap):
        sg = multihost.put_sharded_global(s, dmesh)
        if use_fr:
            fr = fr_cell[0]
            if fr is None or _frontier_stale(fr, s, ecap):
                if fr is not None:
                    # mid-loop growth: keep the changed masks (host
                    # round trip only on the rare capacity event)
                    chg = pad_changed(jnp.asarray(np.asarray(
                        multihost.gather_stacked(fr.changed, timeout=wd)
                    ), bool), s.vert.shape[1])
                elif fr0 is not None:
                    chg = pad_changed(
                        jnp.asarray(fr0, bool), s.vert.shape[1]
                    )
                else:
                    chg = None  # full frontier: exact full-table sweep
                fr = multihost.put_sharded_global(
                    stacked_frontier(
                        s, ecap, changed=chg, per_shard_state=True
                    ),
                    dmesh,
                )
            fn = _spmd_sweep_fn(
                dmesh, ecap, opts.noinsert, opts.noswap, opts.nomove,
                opts.nosurf, frontier=True,
            )
            # cost doc for the SPMD sweep program, joined by the report
            # with run_sweep_loop's "sweep" device span
            obs_costs.capture("sweep", fn, (sg, hausd, fr))
            out, stats, fro = fn(sg, hausd, fr)
            fr_cell[0] = fro
        else:
            fn = _spmd_sweep_fn(
                dmesh, ecap, opts.noinsert, opts.noswap, opts.nomove,
                opts.nosurf,
            )
            obs_costs.capture("sweep", fn, (sg, hausd))
            out, stats = fn(sg, hausd)
        if fs is not None:
            # device-resident validation (psum status inside the
            # shard_map): a poisoned shard is caught HERE, before its
            # NaNs ride the cross-process allgather below — and
            # validate="basic" costs one tiny device reduce, zero host
            # gathers of mesh arrays
            fs.validate_sharded(out, dmesh, it, phase="sweep")
        s2 = multihost.gather_stacked(out, timeout=wd)
        stats = multihost.gather_stacked(stats, timeout=wd)
        return s2, _rec_from_stats(s2, stats)

    st = run_sweep_loop(
        st, opts, emult, history, it,
        ensure_fn=lambda s: ensure_capacity_stacked(s, opts),
        tcap_fn=lambda s: int(s.tet.shape[1]),
        sweep_fn=sweep_fn,
        governor=governor,
    )
    if not use_fr:
        return st, None
    if fr_cell[0] is not None:
        chg = jnp.asarray(np.asarray(multihost.gather_stacked(
            fr_cell[0].changed, timeout=wd
        )), bool)
    else:
        chg = fr0 if fr0 is not None else jnp.ones(
            (D, st.vert.shape[1]), bool
        )
    return st, pad_changed(jnp.asarray(chg, bool), st.vert.shape[1])


def remesh_phase(
    st: Mesh, opts: AdaptOptions, emult: List[float], history: List[dict],
    it: int, hausd: float = 0.01, fs=None, fr0=None, governor=None,
):
    """Operator sweeps to convergence on every shard at once (vmapped) —
    the batched analog of the per-group `MMG5_mmg3d1_delone` calls in the
    reference loop body (`src/libparmmg1.c:662-800`). Control flow is the
    shared `run_sweep_loop` engine with cross-shard-aggregated stats.
    `fs` (a FailsafeHarness) arms the device-resident per-sweep
    validation on the SPMD path.

    `fr0` (with `opts.frontier`) is the iteration's carried active-set:
    per-shard [D, PC] bool vertex masks — what the previous iteration
    changed, remapped through migration, plus the interface bands the
    repartition unfroze. The first sweep gates on its one-ring closure
    (None = all-active, the exact full-table fallback); a DRAINED carry
    skips the sweep loop outright, because an empty-frontier sweep is
    the identity (the converged no-op fast path the round-8 bench
    measures). Returns (stacked, changed | None)."""
    if opts.frontier and fr0 is not None:
        n_act = int(jax.device_get(jnp.sum(fr0.astype(jnp.int32))))
        if n_act == 0:
            rec = _drained_rec(st, history)
            rec.update(iter=it, sweep=0)
            history.append(rec)
            obs_metrics.record_sweep(rec)
            if opts.verbose >= 2:
                print(
                    f"  it {it}: frontier drained — converged sweep "
                    "skipped", flush=True,
                )
            return st, fr0
    if _use_spmd_sweeps():
        return _remesh_phase_global(st, opts, emult, history, it, hausd,
                                    fs=fs, fr0=fr0, governor=governor)
    return _remesh_phase_local(st, opts, emult, history, it, hausd,
                               fr0=fr0, governor=governor)


def _remesh_phase_local(
    st: Mesh, opts: AdaptOptions, emult: List[float], history: List[dict],
    it: int, hausd, fr0=None, governor=None,
):
    """Single-process (vmapped) remesh phase. With `opts.frontier` the
    stacked Frontier is carried across sweeps with HOST-SHARED
    `dirty`/`adja_ok` (conservative max/all over shards — a stricter
    staleness level is always exact, and an unbatched predicate keeps
    the table conds real conditionals under vmap). Returns
    (stacked, changed | None)."""
    use_fr = bool(opts.frontier)
    fr_cell: list = [None]

    def sweep_fn(s, ecap):
        if use_fr:
            fr = fr_cell[0]
            if fr is None or _frontier_stale(fr, s, ecap):
                if fr is not None:
                    chg = pad_changed(fr.changed, s.vert.shape[1])
                elif fr0 is not None:
                    chg = pad_changed(
                        jnp.asarray(fr0, bool), s.vert.shape[1]
                    )
                else:
                    chg = None  # full frontier: exact full-table sweep
                fr = stacked_frontier(s, ecap, changed=chg)
            s, stats, fro = _vsweep(s, ecap, opts, hausd, frontier=fr)
            fr_cell[0] = fro._replace(
                dirty=jnp.int32(
                    int(jax.device_get(jnp.max(fro.dirty)))
                ),
                adja_ok=jnp.bool_(
                    bool(jax.device_get(jnp.all(fro.adja_ok)))
                ),
            )
        else:
            s, stats = _vsweep(s, ecap, opts, hausd)
        return s, _rec_from_stats(s, stats)

    st = run_sweep_loop(
        st, opts, emult, history, it,
        ensure_fn=lambda s: ensure_capacity_stacked(s, opts),
        tcap_fn=lambda s: int(s.tet.shape[1]),
        sweep_fn=sweep_fn,
        governor=governor,
    )
    if not use_fr:
        return st, None
    if fr_cell[0] is not None:
        chg = fr_cell[0].changed
    else:
        chg = fr0 if fr0 is not None else jnp.ones(
            (st.vert.shape[0], st.vert.shape[1]), bool
        )
    return st, pad_changed(jnp.asarray(chg, bool), st.vert.shape[1])


def interp_phase(st: Mesh, old: Mesh,
                 opts: AdaptOptions | None = None) -> Mesh:
    """Interpolation from the pre-remesh snapshot for ALL shards in one
    vmapped device call — `PMMG_interpMetricsAndFields`
    (`src/interpmesh_pmmg.c:663`; purely shard-local, see SURVEY §3.4).
    The rare walk failures are rescued host-side inside
    `interp.interp_stacked` (exhaustive closest-element per shard).
    The wedge threshold of the surface path follows the session's
    feature angle (-ar); -nr disables the demotion."""
    import math as _math

    if opts is None or opts.angle is None:
        cw = -1.0  # no feature detection: nothing counts as cross-ridge
    else:
        cw = _math.cos(_math.radians(opts.angle))
    # cost doc of the jitted all-shards locate+interp program, under
    # the same name as the phase:interp device span that times it
    obs_costs.capture(
        "phase:interp", interp._interp_all_shards, (st, old),
        dict(max_steps=64, surface=True, cos_wedge=cw),
    )
    return interp.interp_stacked(st, old, cos_wedge=cw)


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

# redistribution modes, reference src/libparmmgtypes.h:173-186
REDISTRIBUTION_GRAPH_BALANCING = 0
REDISTRIBUTION_IFC_DISPLACEMENT = 1


@dataclasses.dataclass
class DistOptions(AdaptOptions):
    """Distributed controls on top of the adaptation options (the
    redistribution rows of `PMMG_Param`, reference `src/libparmmg.h:54-90`:
    nobalancing, APImode, niter...)."""

    nparts: int = 8
    # -nobalance: skip the between-iteration resharding (interface
    # displacement); frozen bands then stay frozen for all niter
    nobalancing: bool = False
    # advancing-front displacement depth per iteration (reference
    # PMMG_MVIFCS_NLAYERS=2, src/parmmg.h:227)
    ifc_layers: int = 2
    # max shard-size imbalance (max/mean) before a rebalancing SFC
    # re-cut replaces the displaced partition. The reference's
    # PMMG_GRPS_RATIO=2.0 (src/parmmg.h:221) governs GROUP sizes, a much
    # finer granularity it can re-split at will; shard = device here, so
    # the guard gets more slack before it cancels a displacement whose
    # front movement is the whole point of the iteration
    grps_ratio: float = 2.5
    # between-iteration redistribution mode (reference
    # PMMG_REDISTRIBUTION_graph_balancing=0 / _ifc_displacement=1,
    # src/libparmmgtypes.h:173-186; default ifc_displacement like the
    # reference's PMMG_REDISTRIBUTION_mode). Graph mode recomputes a
    # fresh global weighted SFC cut each iteration (device-resident,
    # partition.stacked_graph_colors) instead of advancing fronts.
    repartitioning: int = REDISTRIBUTION_IFC_DISPLACEMENT
    check_comm: bool = False      # chkcomm assert each iteration (debug)
    # minimum elements per shard before distribution pays off — the group
    # sizing role of PMMG_howManyGroups / PMMG_GRPSPL_DISTR_TARGET
    # (reference src/grpsplit_pmmg.c:47, src/parmmg.h:218-227): a mesh
    # smaller than nparts*min_shard_elts is first grown single-shard so
    # frozen interfaces don't dominate the shards
    min_shard_elts: int = 256


def _elastic_recut(stacked: Mesh, opts: DistOptions) -> Mesh:
    """Elastic-resume re-cut: a checkpoint whose shard count no longer
    matches the current run's `opts.nparts` (the world resized past
    what re-laying the same shards over the devices can absorb) is
    merged back to one centralized mesh and re-partitioned through the
    ordinary SFC path — owner ranks come back from `rebuild_comm` over
    the persistent vglob ids, comm tables are rebuilt by the iteration
    loop exactly as after any re-cut. The trajectory from here on is
    that of the NEW layout (this is the operator's explicit choice;
    bit-identical resume holds only when the shard count is
    unchanged)."""
    stacked = assign_global_ids(stacked)
    merged = adjacency.build_adjacency(
        merge_shards(stacked, rebuild_comm(stacked))
    )
    part = np.asarray(jax.device_get(sfc_partition(
        merged, opts.nparts, partition_mod.metric_weights(merged)
    )))
    out, _comm = split_mesh(
        merged, part, opts.nparts, assume_adjacency=True,
        build_shard_adjacency=False,
    )
    return _presize_for_target(out, opts)


def _resume_stacked(resume, opts: DistOptions):
    """Common driver-side handling of a distributed ResumeState:
    elastic re-cut when the checkpointed shard count differs from the
    current layout (then the cached comm capacity is stale too, and the
    checkpointed frontier carry no longer maps onto the shards — it
    restarts full). Returns (stacked, icap, fr0)."""
    stacked = resume.mesh
    icap = resume.meta.get("icap")
    fr0 = resume.meta.get("aux_arrays", {}).get("frontier")
    if stacked.vert.shape[0] != opts.nparts:
        if opts.verbose >= 1:
            print(
                f"  ## elastic resume: re-cutting {stacked.vert.shape[0]}"
                f"-shard checkpoint onto {opts.nparts} shards",
                flush=True,
            )
        stacked = _elastic_recut(stacked, opts)
        icap = None
        fr0 = None
    return stacked, icap, fr0


def _finish_dist_info(stacked: Mesh, history: List[dict], h_in, fs,
                      status, opts: "DistOptions", driver: str,
                      governor=None) -> dict:
    """Common exit bookkeeping of both distributed entry points: the
    world quality histogram, the world edge-length histogram (per-shard
    unique edges merged like `merge_stacked_histograms` — the
    `PMMG_prilen` world totals), the obs.health termination verdict
    (folded with the run governor's outcome when one was armed) and
    its tracer emission. Returns the info dict."""
    h_out = quality.merge_stacked_histograms(
        jax.vmap(quality.quality_histogram)(stacked)
    )
    ecap = int(stacked.tet.shape[1] * 1.7) + 64
    len_out = quality.merge_stacked_length_stats(
        jax.vmap(lambda m: quality.mesh_length_stats(m, ecap))(stacked)
    )
    len_doc = quality.length_stats_doc(len_out)
    verdict = obs_health.assess(
        history, converge_frac=opts.converge_frac,
        max_sweeps=opts.max_sweeps, status=int(status),
    )
    if governor is not None:
        verdict = governor.finalize(verdict)
    obs_health.emit_run_health(
        history, length_doc=len_doc, verdict=verdict, driver=driver,
    )
    obs_health.run_state().update(
        phase="done", verdict=verdict["verdict"],
        in_band=len_doc["in_band"],
    )
    return dict(history=history, qual_in=h_in, qual_out=h_out,
                len_out=len_out, health=verdict,
                ckpt_overlap_s=round(fs.ckpt_overlap_s, 3),
                status=status)


@obs_trace.traced("adapt_distributed", driver="distributed")
def adapt_distributed(
    mesh: Mesh,
    opts: Optional[DistOptions] = None,
):
    """Adapt a centralized mesh on `opts.nparts` shards.

    Returns (stacked Mesh, ShardComm, info). Drives the reference's
    centralized entry semantics (`PMMG_parmmglib_centralized`,
    `src/libparmmg.c:1444`): preprocess → distribute → niter × [remesh
    with frozen interfaces → interpolate → rebuild comm] → global
    numbering. Use `merge_adapted` for the centralized-output path.

    With `opts.checkpoint_dir` set, each iteration is checkpointed
    atomically and a compatible checkpoint found at entry RESUMES the
    run past the preprocess/distribute preamble (see
    `parmmg_tpu.failsafe`).
    """
    from .. import failsafe

    opts = opts or DistOptions()
    if opts.kernels is not None:
        from ..kernels import registry as kernels_registry

        kernels_registry.set_mode(opts.kernels)
    nparts = opts.nparts
    fs = failsafe.harness(opts, driver="distributed")
    from .. import control as run_control

    gov = run_control.resolve_governor(opts)

    resume = fs.resume()
    if resume is not None:
        stacked, icap0, fr0 = _resume_stacked(resume, opts)
        history: List[dict] = resume.history
        h_in = failsafe._histo_from_json(resume.meta.get("qual_in"))
        hausd = resume.meta.get("hausd")
        if hausd is None and "hausd" in resume.meta.get("aux_arrays", {}):
            hausd = jnp.asarray(
                resume.meta["aux_arrays"]["hausd"], stacked.vert.dtype
            )
        if opts.verbose >= 1:
            print(
                f"  ## resuming from checkpoint: iteration {resume.it} "
                f"complete, continuing at {resume.it + 1}", flush=True,
            )
        stacked, comm, status = _iteration_loop(
            stacked, opts, hausd, history,
            icap0=icap0, fs=fs,
            start_it=resume.it + 1, emult0=resume.emult,
            ckpt_meta=dict(qual_in=resume.meta.get("qual_in")),
            fr0=fr0, governor=gov,
        )
        info = _finish_dist_info(
            stacked, history, h_in, fs, status, opts, "distributed",
            governor=gov,
        )
        return stacked, comm, info

    # --- preprocess (reference PMMG_preprocessMesh, src/libparmmg.c:128) --
    mesh = adjacency.build_adjacency(mesh)
    mesh = analysis.analyze(mesh, ang=opts.angle, opnbdy=opts.opnbdy)
    mesh = fs.fire(0, "analysis", mesh)
    ecap0 = int(mesh.tcap * 1.6) + 64
    mesh = prepare_metric(mesh, opts, ecap0)
    mesh = fs.fire(0, "metric", mesh)
    from .adapt import local_hausd_table

    hausd = local_hausd_table(mesh, opts, resolve_hausd(mesh, opts))
    h_in = quality.quality_histogram(mesh)

    # a mesh too small for nparts shards is grown single-shard first, so
    # interfaces stay a thin fraction of each shard (group sizing,
    # reference PMMG_howManyGroups, src/grpsplit_pmmg.c:47)
    while (
        int(mesh.ntet) < nparts * opts.min_shard_elts
        and not opts.noinsert
    ):
        # the pre-growth is an internal sub-run: it must not consume
        # the outer run's fault plan or write into its checkpoint dir
        pre_opts = dataclasses.replace(
            opts, niter=1, hgrad=None, checkpoint_dir=None,
            faults=failsafe.FaultPlan(),
        )
        ne_before = int(mesh.ntet)
        mesh, pre_info = adapt_single(mesh, pre_opts)
        if int(mesh.ntet) <= ne_before:  # metric is satisfied: stop
            break

    # --- distribute (reference PMMG_distribute_mesh) ----------------------
    # metric-aware weights: balance the PREDICTED output elements, so a
    # localized-refinement metric (torus-shock class) doesn't skew the
    # shards after the first iteration's splits (PMMG_computeWgt role)
    part = np.asarray(jax.device_get(sfc_partition(
        mesh, nparts, partition_mod.metric_weights(mesh)
    )))
    stacked, comm = split_mesh(
        mesh, part, nparts, build_shard_adjacency=False
    )
    stacked = _presize_for_target(stacked, opts)

    history = []
    stacked, comm, status = _iteration_loop(
        stacked, opts, hausd, history, fs=fs,
        ckpt_meta=dict(qual_in=failsafe._histo_to_json(h_in)),
        governor=gov,
    )
    info = _finish_dist_info(
        stacked, history, h_in, fs, status, opts, "distributed",
        governor=gov,
    )
    return stacked, comm, info


def _grow_stacked_for_recovery(st: Mesh, opts: DistOptions) -> Mesh:
    """Uniform geometric growth for the CapacityError grow-and-retry
    path of the iteration loop — budget-checked so a budget-bound run
    degrades (MemoryBudgetError → LOWFAILURE) instead of looping."""
    from .adapt import _check_budget

    g = max(float(opts.grow_factor), 1.2)
    want = (
        int(st.vert.shape[1] * g) + 8,
        int(st.tet.shape[1] * g) + 8,
        int(st.tria.shape[1] * g) + 8,
        int(st.edge.shape[1] * g) + 64,
    )
    _check_budget(st, opts, *want)
    return grow_stacked(st, *want)


def _publish_shard_gauges(st: Mesh) -> None:
    """Publish `work/imbalance` + per-shard live-tet gauges from the
    CURRENT stacked state. `record_sweep` only writes these when a
    distributed sweep record lands, so an iteration whose balancing
    moved cells AFTER the last sweep (or a drained early-converged
    iteration that records no sweep at all) would otherwise leave the
    gauges stale; the iteration boundary republishes them
    (last-write-wins Gauge semantics — the freshest state wins)."""
    ne = np.asarray(jax.device_get(jnp.sum(st.tmask, axis=1)))
    reg = obs_metrics.registry()
    imb = float(ne.max()) / max(float(ne.mean()), 1.0)
    reg.gauge("work/imbalance").set(round(imb, 4))
    for i, v in enumerate(ne.tolist()):
        reg.gauge(f"work/live_tets/shard{i}").set(float(v))


def _iteration_loop(stacked: Mesh, opts: DistOptions, hausd: float,
                    history: List[dict], icap0: int | None = None,
                    fs=None, start_it: int = 0, emult0: float | None = None,
                    ckpt_meta: dict | None = None, fr0=None,
                    governor=None):
    """The niter remesh/interpolate/rebalance iterations shared by the
    centralized (`adapt_distributed`) and distributed-input
    (`adapt_stacked_input`) entry points — the `PMMG_parmmglib1` body
    (`src/libparmmg1.c:636-896`). Returns (stacked, comm, status) with
    global ids assigned and comm tables rebuilt.

    Graded failure (`failed_handling`, `src/libparmmg1.c:970-1011` and
    `PMMG_SUCCESS/LOWFAILURE/STRONGFAILURE`, `src/libparmmgtypes.h:45-66`)
    via the failsafe harness `fs` (`parmmg_tpu.failsafe`): each
    iteration is validated at its boundary (the cadence-configurable
    validator replacing the old ad-hoc `_finite_ok` — the role of the
    reference's per-phase `MPI_Allreduce(ier, MIN)` agreement), rolled
    back to the iteration-start snapshot on failure (still a conformal,
    saveable mesh), retried with grown capacities (CapacityError) or
    cleared caches (RetraceError) up to `opts.recovery_attempts` times,
    and checkpointed atomically when `opts.checkpoint_dir` is set.
    Anything unrecovered degrades to LOWFAILURE; only an unusable
    initial state raises through (STRONGFAILURE is the caller's
    exception path). Every absorbed failure appends a ``failure`` entry
    to `history`.
    """
    from .. import failsafe
    from ..lint import contracts

    if fs is None:
        fs = failsafe.harness(opts, driver="distributed")
    tr = obs_trace.get_tracer()
    # one timebase for the world: estimate this rank's clock offset to
    # rank 0 (median-of-K barrier exchange) and persist it in the trace
    # clock header, so obs.dist can merge the rank timelines. A resumed
    # run re-enters here with a fresh tracer and a RESTARTED clock —
    # its new segment gets its own offset. Collective: every process
    # reaches this boundary before any iteration work.
    from ..parallel import multihost

    if tr.enabled or multihost.is_multiprocess():
        multihost.sync_tracer_clock(
            tr, timeout=getattr(opts, "watchdog_timeout", None)
        )
    nparts = opts.nparts
    emult = [emult0 if emult0 is not None else 1.6]
    icap = icap0
    comm = None
    # closed-loop balancer: band on the measured work imbalance with
    # hysteresis + a min re-cut interval (parallel.migrate). One policy
    # instance for the whole run — its state (strikes, last fire) IS
    # the hysteresis. `balance_band`/PMMGTPU_BALANCE_BAND <= 0 (or
    # -nobalance) disables it; the GRPS_RATIO count-based escape hatch
    # stays active either way.
    from ..parallel import migrate as migrate_mod

    _band = migrate_mod.resolve_balance_band(opts)
    policy = (migrate_mod.BalancePolicy(_band)
              if _band is not None and not opts.nobalancing
              and nparts > 1 else None)
    status = tags.ReturnStatus.SUCCESS
    last_good = fs.snapshot(stacked)
    it = start_it
    attempts = 0
    # active-set carry across iterations (opts.frontier): None = full
    # first frontier (exact full-table sweep); thereafter the per-shard
    # changed masks remapped through compaction and migration. Reset to
    # full on every rollback — the restored snapshot predates the carry.
    # `fr0` restores a CHECKPOINTED carry on resume, so a killed run's
    # continuation gates its sweeps exactly like the uninterrupted run
    # (bit-identical resume holds with the frontier on).
    fr_carry = None if fr0 is None else jnp.asarray(fr0, bool)
    # live status endpoint (PMMGTPU_STATUS_PORT contract): lazy import
    # keeps models free of a module-level service dependency
    from ..service import status as service_status

    status_srv = service_status.serve_run_from_env()
    fs.arm_preemption()
    try:
        while it < opts.niter:
            if fs.preempt_requested:
                # every rank sees the broadcast SIGTERM and raises here
                # together; a lone receiver's peers are bounded by the
                # next heartbeat barrier's watchdog
                # parmmg-lint: disable=PML016 -- peers are watchdog-bounded at the next heartbeat barrier (typed PeerLostError, not a hang)
                raise failsafe.PreemptionError(
                    f"SIGTERM received before iteration {it} — the "
                    "last committed checkpoint stands; resume to "
                    "continue"
                )
            # phase-boundary heartbeat: all processes must arrive
            # within the watchdog window or a silent peer loss becomes
            # a typed PeerLostError instead of a hang in the first
            # collective of the iteration (no-op single-process)
            fs.heartbeat(it)
            obs_health.run_state().update(
                iteration=it, phase="iteration", driver="distributed"
            )

            def _iteration(st, cm, ic, fr):
                st, cm, ic, fr = _one_iteration(
                    st, opts, hausd, history, it, cm, ic, emult, nparts,
                    fs=fs, fr=fr, policy=policy, governor=governor,
                )
                fs.validate(st, it, comm=cm, phase="iteration")
                return st, cm, ic, fr

            try:
                with tr.span("iteration", it=it):
                    if attempts:
                        # recovery re-entry: recompiles (grown shapes /
                        # cleared caches) land in a recovery phase,
                        # exempt from the steady retrace budgets
                        with contracts.budget_exempt("iteration-retry"):
                            stacked, comm, icap, fr_carry = _iteration(
                                stacked, comm, icap, fr_carry
                            )
                    else:
                        stacked, comm, icap, fr_carry = _iteration(
                            stacked, comm, icap, fr_carry
                        )
            except failsafe.CapacityError as e:
                history.append(dict(iter=it, phase="iteration",
                                    failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e)
                if last_good is None:
                    raise
                stacked = failsafe.snapshot(last_good)
                comm = None
                icap = None
                fr_carry = None
                if attempts < fs.attempts:
                    attempts += 1
                    try:
                        stacked = _grow_stacked_for_recovery(
                            stacked, opts
                        )
                    except failsafe.MemoryBudgetError as e2:
                        history.append(dict(iter=it, failure=str(e2),
                                            error=type(e2).__name__))
                        status = tags.ReturnStatus.LOWFAILURE
                        break
                    continue
                status = tags.ReturnStatus.LOWFAILURE
                break
            except failsafe.RetraceError as e:
                history.append(dict(iter=it, phase="iteration",
                                    failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e)
                if last_good is None:
                    raise
                stacked = failsafe.snapshot(last_good)
                comm = None
                icap = None
                fr_carry = None
                if attempts < fs.attempts:
                    attempts += 1
                    jax.clear_caches()
                    continue
                status = tags.ReturnStatus.LOWFAILURE
                break
            except failsafe.PeerLostError as e:
                # a dead peer cannot be rolled back around: the SPMD
                # world is broken, every further collective would hang.
                # Re-raise through the graded-degradation ladder — the
                # cure is checkpoint-backed restart, not LOWFAILURE
                # (which would run the post-loop collectives below)
                obs_trace.emit_event("peer_lost", it=int(it),
                                     error=str(e)[:200])
                raise
            except (FloatingPointError, ValueError, RuntimeError,
                    OverflowError) as e:
                # numeric/capacity/budget failures degrade gracefully;
                # programming errors (TypeError, trace errors, ...)
                # propagate — hiding them as LOWFAILURE would mask
                # defects
                history.append(dict(iter=it, failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e)
                if last_good is None:
                    raise
                stacked = failsafe.snapshot(last_good)
                status = tags.ReturnStatus.LOWFAILURE
                comm = None
                icap = None
                fr_carry = None
                break
            attempts = 0
            last_good = fs.snapshot(stacked)
            # boundary gauge publication BEFORE the snapshot row, so
            # the per-iteration series reflects the post-balancing
            # state, not the last sweep record (satellite fix: gauges
            # were only written by record_sweep)
            _publish_shard_gauges(stacked)
            if tr.enabled:
                obs_metrics.registry().snapshot(it)
            # collective-lockstep boundary: fire any scheduled comm
            # fault (the chaos desync poisons THIS rank's ledger), then
            # world-compare the collective-schedule digests under
            # validate="full" — a desynced rank becomes a typed
            # CollectiveDivergenceError on EVERY rank here, instead of
            # a one-sided watchdog timeout in some later collective.
            # Same placement contract as elastic_poll below: every rank
            # reaches this boundary unconditionally
            stacked = fs.fire(it, "comm", stacked)
            fs.verify_collectives(it)
            # elastic reform vote (world-agreed; a collective when
            # armed multi-process, so it sits at the SAME boundary on
            # every rank): a standing preemption notice becomes a
            # shrink, restored capacity below the target world a grow —
            # either way the epoch force-commits below before anyone
            # exits
            reform = fs.elastic_poll(it)
            if fs.ckpt is not None and (
                fs.ckpt.due(it) or fs.preempt_requested
                # a maintenance-event notice forces an out-of-cadence
                # checkpoint NOW, before the platform's SIGTERM lands
                or fs.preempt_notice() or reform is not None
            ):
                meta = dict(ckpt_meta or {})
                meta["icap"] = int(icap) if icap is not None else None
                aux = {}
                if isinstance(hausd, (int, float)):
                    meta["hausd"] = float(hausd)
                else:
                    aux["hausd"] = hausd
                if fr_carry is not None:
                    # the active-set carry is part of the trajectory:
                    # without it a resumed run would restart from the
                    # full frontier and gate its sweeps differently
                    # than the uninterrupted run
                    aux["frontier"] = fr_carry
                with tr.span("checkpoint", it=it):
                    fs.save(it, {"mesh": stacked}, history=history,
                            emult=emult[0], meta=meta, aux_arrays=aux,
                            force=True)
            if reform is not None:
                # the agreed reformation's checkpoint is committed
                # (drain any async-staged epoch first — the exit must
                # leave durable state, not a staged one); ack, then
                # leave through the unabsorbable typed path: the
                # departing rank exits the preemption family, the
                # survivors exit REFORM for the fleet to relaunch
                fs.finish()
                raise fs.elastic_exit(reform)
            if fs.preempt_requested:
                # preemption grace window: the iteration's (sharded,
                # barrier-committed) checkpoint is in place — exit via
                # the unabsorbable path, like the injected kill
                raise failsafe.PreemptionError(
                    f"SIGTERM received: iteration {it} checkpointed — "
                    "exiting for preemption; resume to continue"
                )
            stacked = fs.post_iteration(it, stacked, history)
            if governor is not None and governor.check_iteration(
                    history, it, opts.niter):
                it += 1
                break
            it += 1
    finally:
        fs.disarm_preemption()
        # async staging: commit any staged epoch before control leaves
        # the loop — every exit path ends with the queue drained
        fs.finish()
        if status_srv is not None:
            status_srv.close()

    stacked = assign_global_ids(stacked)
    comm = rebuild_comm(stacked, icap)
    return stacked, comm, status


@partial(jax.jit, donate_argnums=(0, 1))
def _compact_aux_stacked(st: Mesh, changed):
    """Stacked compact that remaps the per-shard frontier masks through
    the same vertex renumbering (the single-shard `compact_aux`,
    vmapped)."""
    return jax.vmap(compact_aux)(st, changed)


def _one_iteration(stacked, opts, hausd, history, it, comm, icap, emult,
                   nparts, fs=None, fr=None, policy=None, governor=None):
    if fs is None:
        from .. import failsafe

        fs = failsafe.harness(opts, driver="distributed")
    tr = obs_trace.get_tracer()
    # snapshot for interpolation (PMMG_update_oldGrps role,
    # src/grpsplit_pmmg.c:1224) — needs fresh adjacency for the walk
    old = jax.vmap(adjacency.build_adjacency)(stacked)

    obs_health.run_state().update(phase="remesh")
    with tr.span("phase:remesh", it=it):
        stacked, fr = remesh_phase(stacked, opts, emult, history, it,
                                   hausd, fs=fs, fr0=fr,
                                   governor=governor)
        if fr is not None:
            # the frontier carry survives the pack: compact_aux remaps
            # each shard's changed mask through the vertex renumbering
            stacked, fr = _compact_aux_stacked(stacked, fr)
        else:
            stacked = jax.vmap(compact)(stacked)
    obs_costs.record_hbm("remesh")
    stacked = fs.fire(it, "remesh", stacked)

    # interpolate metric + fields from the snapshot
    obs_health.run_state().update(phase="interp")
    with tr.device_span("phase:interp", it=it):
        stacked = interp_phase(stacked, old, opts)
    obs_costs.record_hbm("interp")
    stacked = fs.fire(it, "interp", stacked)

    if opts.check_comm:
        from ..parallel import chkcomm
        from ..parallel.shard import device_mesh

        # comm rebuild from persistent gids (replaces the reference's
        # face-hash remap at src/libparmmg1.c:361); outside this
        # debug check the tables are rebuilt where next consumed —
        # in the balancing branch and after the loop
        comm = rebuild_comm(stacked, icap)
        icap = comm.icap
        chkcomm.assert_comm_ok(
            stacked, comm, device_mesh(nparts), tol=1e-6
        )

    # --- load balancing / interface displacement ----------------------
    # (reference PMMG_loadBalancing, src/loadbalancing_pmmg.c:44, in
    # ifc-displacement mode src/moveinterfaces_pmmg.c:1306): per-tet
    # colors advance `ifc_layers` layers across interfaces under a
    # fixed priority permutation, so every band frozen this iteration
    # is interior in the next. DEVICE-FIRST path: front propagation +
    # halo agreement + fixed-slot exchange (`parallel.migrate`, the
    # PMMG_transfer_all_grps role) — the host only re-derives the
    # interface discipline from connectivity. The former global
    # merge+split survives solely as the GRPS_RATIO re-cut fallback.
    # Like the reference, the LAST iteration balances the OUTPUT mesh
    # with the graph cut regardless of the user mode
    # (src/libparmmg1.c:854-869: repartitioning is forced to
    # graph_balancing for the final PMMG_loadBalancing call).
    last = it == opts.niter - 1
    if not opts.nobalancing and nparts > 1:
        from ..parallel import migrate as migrate_mod
        from ..utils.retry import jit_retry

        # closed-loop balance decision (BalancePolicy): reads the
        # MEASURED per-shard work from this iteration's sweep records
        # (active-fraction-weighted live tets — what the sweeps
        # actually paid), not element counts alone. Host-deterministic
        # over the replicated history, so every rank computes the same
        # action and the forced re-cut below cannot desync the
        # collective ledger. The interface displacement itself stays
        # unconditional — it doubles as the unfreezing machinery that
        # makes frozen bands interior next iteration.
        decision = (policy.evaluate(history, it)
                    if policy is not None else None)
        force_recut = bool(decision
                           and decision.get("action") == "recut")
        t_bal = time.monotonic()
        stacked = fs.fire(it, "migrate", stacked)
        stacked = assign_global_ids(stacked)
        comm = rebuild_comm(stacked, icap)
        stacked = jax.vmap(adjacency.build_adjacency)(stacked)
        graph_mode = (
            last or opts.repartitioning == REDISTRIBUTION_GRAPH_BALANCING
        )
        if graph_mode:
            color = partition_mod.stacked_graph_colors(stacked, nparts)
        else:
            color = jit_retry(
                migrate_mod.displace_colors, stacked, comm, nparts,
                round_id=0, layers=opts.ifc_layers,
            )
        cnts = np.asarray(jax.device_get(
            migrate_mod.migration_counts(stacked, color, nparts)
        ))
        if cnts.max() > 0:
            # the front moved: reattach any component it pinched off
            # (the PMMG_check_reachability role) before committing. The
            # repair is host connectivity-only work, so it is gated on
            # actual movement — an idle front cannot strand anything.
            color = migrate_mod.fix_contiguity(stacked, color, nparts)
            cnts = np.asarray(jax.device_get(
                migrate_mod.migration_counts(stacked, color, nparts)
            ))
        fr_keys = None
        if fr is not None:
            # encode the active set as gid keys BEFORE the exchange:
            # last sweep's changed vertices, every vertex of a
            # migrating cell (its 1-ring context changes owner), and
            # the CURRENT interface bands — the displacement unfreezes
            # them, making them the next iteration's working set
            # (ParMmg's interface-displacement loop). The gid encoding
            # is immune to the growth/compaction/slot permutation of
            # the exchange below.
            par_pre = (stacked.vtag & tags.PARBDY) != 0
            fr_keys = migrate_mod.frontier_gid_keys(
                stacked,
                jnp.asarray(fr, bool) | par_pre
                | migrate_mod.migrating_vertices(stacked, color),
            )
        # migration telemetry: cells crossing shards and an estimated
        # wire payload (tet row + its 4 vertex rows + amortized
        # surface/edge freight — the _pack stream contents), so the
        # run report can attribute comm volume per iteration
        moved_cells = int(cnts.sum())
        if moved_cells:
            fsz = jnp.dtype(stacked.vert.dtype).itemsize
            per_tet = (4 * 4 + 4) + 4 * (3 * fsz + 3 * 4) + 16
            reg = obs_metrics.registry()
            reg.counter("migrate/cells_moved").inc(moved_cells)
            reg.counter("migrate/payload_bytes").inc(
                moved_cells * per_tet
            )
        shard_ne = np.asarray(
            jax.device_get(jnp.sum(stacked.tmask, axis=1))
        )
        new_ne = shard_ne - cnts.sum(axis=1) + cnts.sum(axis=0)
        # pre-balance imbalance for the tracer event: the policy's
        # work-weighted measure when telemetry exists, raw live-tet
        # skew otherwise
        imb_pre = (decision or {}).get("imbalance")
        if imb_pre is None:
            imb_pre = round(
                float(shard_ne.max()) / max(float(shard_ne.mean()), 1.0),
                4,
            )
        trigger = "graph" if graph_mode else "displacement"
        # GRPS_RATIO discipline (reference src/parmmg.h:218-227): when
        # accumulated displacement skews shard sizes past the ratio,
        # rebalance with a fresh SFC cut (host fallback). Ratio is
        # max-vs-mean: wall-clock is governed by the LARGEST shard.
        # The BalancePolicy forces the same escape hatch when the
        # MEASURED imbalance has sat above its band (hysteresis +
        # min-interval live in the policy, not here).
        if opts.verbose >= 2:
            print(f"  [balance] moved={int(cnts.sum())} "
                  f"new_ne={new_ne.tolist()}")
        if force_recut or (
                new_ne.max() > opts.grps_ratio * max(new_ne.mean(), 1.0)):
            trigger = "balance-policy" if force_recut else "grps_ratio"
            if opts.verbose >= 2:
                print(f"  [balance] full re-cut ({trigger})")
            stacked, comm = _rebalance_full(stacked, comm, nparts)
            icap = None
            stacked = _presize_for_target(stacked, opts)
            # the host merge+split rewrites every shard: restart the
            # next iteration from the exact full frontier
            fr = None if fr is None else jnp.ones(
                (nparts, stacked.vert.shape[1]), bool
            )
        elif cnts.max() > 0:
            slot_cap = int(cnts.max()) + 8
            if fs.faults.take(it, "migrate", "overflow"):
                # injected fault: undershoot the real slot capacity so
                # the genuine CapacityError raise site and the genuine
                # grow-and-retry recovery below are what run
                slot_cap = 1
            # headroom for incoming entities before the exchange
            pc = stacked.vert.shape[1]
            tc = stacked.tet.shape[1]
            fc = stacked.tria.shape[1]
            ec = stacked.edge.shape[1]
            shard_np = np.asarray(
                jax.device_get(jnp.sum(stacked.vmask, axis=1))
            )
            shard_nf = np.asarray(
                jax.device_get(jnp.sum(stacked.trmask, axis=1))
            )
            inc = cnts.sum(axis=0)
            need_t = int((shard_ne + inc).max())
            need_p = int((shard_np + 4 * inc).max())
            need_f = int((shard_nf + 2 * inc).max())
            if (need_t > 0.9 * tc or need_p > 0.9 * pc
                    or need_f > 0.9 * fc):
                stacked = grow_stacked(
                    stacked,
                    pcap=max(pc, int(need_p * 1.3) + 8),
                    tcap=max(tc, int(need_t * 1.3) + 8),
                    fcap=max(fc, int(need_f * 1.3) + 8),
                    ecap=max(ec, int(need_t * 0.5) + 64),
                )
                pad = stacked.tet.shape[1] - color.shape[1]
                if pad:
                    color = jnp.pad(
                        color, ((0, 0), (0, pad)), constant_values=-1
                    )
            # bounded grow-and-retry on the typed CapacityError
            # (reference reallocation ladder role): the error carries
            # the per-shard/per-entity overflow scalars, so each retry
            # is sized exactly; only repeated misses fall back to the
            # host full re-cut
            moved = None
            for att in range(3):
                try:
                    with tr.device_span("migrate_exchange", it=it):
                        moved = migrate_mod.migrate(
                            stacked, color, nparts, slot_cap
                        )
                    break
                except CapacityError as e:
                    history.append(dict(
                        iter=it, phase="migrate", failure=str(e),
                        error=type(e).__name__, recovered=True,
                    ))
                    obs_trace.emit_event(
                        "migrate_capacity_retry", it=int(it),
                        attempt=att,
                    )
                    if att == 2:
                        break
                    if e.counts is not None:
                        # pack-side slot undershoot: the true
                        # per-destination max is in the error
                        slot_cap = int(e.counts.max()) + 8
                    if e.overflow is not None:
                        # integrate-side shard overflow: grow each
                        # entity by its measured excess (+30%)
                        over = np.maximum(
                            np.asarray(e.overflow), 0
                        ).max(axis=0)
                        stacked = grow_stacked(
                            stacked,
                            pcap=stacked.vert.shape[1]
                            + int(over[0] * 1.3) + 8,
                            tcap=stacked.tet.shape[1]
                            + int(over[1] * 1.3) + 8,
                            fcap=stacked.tria.shape[1]
                            + int(over[2] * 1.3) + 8,
                            ecap=stacked.edge.shape[1]
                            + int(over[3] * 1.3) + 64,
                        )
                        pad = stacked.tet.shape[1] - color.shape[1]
                        if pad:
                            color = jnp.pad(
                                color, ((0, 0), (0, pad)),
                                constant_values=-1,
                            )
            if moved is None:
                # capacity estimates kept falling short: full re-cut
                # fallback (the pre-existing degradation)
                trigger = "capacity-recut"
                stacked, comm = _rebalance_full(stacked, comm, nparts)
                icap = None
                stacked = _presize_for_target(stacked, opts)
                fr = None if fr is None else jnp.ones(
                    (nparts, stacked.vert.shape[1]), bool
                )
            else:
                stacked = jax.vmap(compact)(moved)
                stacked, comm = migrate_mod.retag_interfaces(stacked)
                icap = comm.icap
                stacked = _presize_for_target(stacked, opts)
                if fr is not None:
                    # decode the carried gid set on the new owners and
                    # add the POST-exchange interface bands (the next
                    # frozen regions border this iteration's work)
                    par_post = (stacked.vtag & tags.PARBDY) != 0
                    fr = migrate_mod.frontier_from_gid_keys(
                        stacked, fr_keys
                    ) | par_post
        # migration cost + decision telemetry, first-class: wall spent
        # in the whole balancing block (color, contiguity repair,
        # exchange OR re-cut) and one `rebalance` event per iteration
        # that moved anything, carrying the before/after imbalance the
        # report's "balance decisions" line renders
        ne_post = np.asarray(
            jax.device_get(jnp.sum(stacked.tmask, axis=1))
        )
        imb_post = round(
            float(ne_post.max()) / max(float(ne_post.mean()), 1.0), 4
        )
        wall = time.monotonic() - t_bal
        reg = obs_metrics.registry()
        reg.histogram("migrate/wall_s").observe(wall)
        recut = trigger in ("balance-policy", "grps_ratio",
                            "capacity-recut")
        if moved_cells or recut:
            reg.counter("migrate/rebalances").inc()
            obs_trace.emit_event(
                "rebalance", it=int(it), trigger=trigger,
                imbalance_pre=float(imb_pre),
                imbalance_post=float(imb_post),
                cells=int(moved_cells), wall_s=round(wall, 4),
                reason=str((decision or {}).get("reason", "")),
            )
        obs_costs.record_hbm("migrate")

    return stacked, comm, icap, fr


def _rebalance_full(stacked: Mesh, comm: ShardComm, nparts: int):
    """Full SFC re-cut via host merge+split — the rare GRPS_RATIO
    fallback (the displaced partition skewed too far). Centralizes the
    mesh once; the steady-state path is `parallel.migrate`."""
    merged = adjacency.build_adjacency(merge_shards(stacked, comm))
    part = np.asarray(jax.device_get(sfc_partition(
        merged, nparts, partition_mod.metric_weights(merged)
    )))
    return split_mesh(
        merged, part, nparts, assume_adjacency=True,
        build_shard_adjacency=False,
    )


@obs_trace.traced("adapt_stacked_input", driver="distributed-input")
def adapt_stacked_input(
    stacked: Mesh,
    comm: Optional[ShardComm],
    opts: Optional[DistOptions] = None,
):
    """Adapt a mesh supplied already-distributed (per-shard stacked Mesh
    with PARBDY interface tags and `vglob` seeded on interface vertices)
    — the reference's distributed entry
    (`PMMG_parmmglib_distributed` + `PMMG_preprocessMesh_distributed`,
    `src/libparmmg.c:1519,206`). Use `parallel.distribute.
    stack_loaded_shards` / `io.medit.load_mesh_distributed` to build the
    input from per-rank files.

    Returns (stacked, comm, info) like `adapt_distributed`.
    """
    from .. import failsafe

    opts = opts or DistOptions()
    opts = dataclasses.replace(opts, nparts=stacked.vert.shape[0])
    fs = failsafe.harness(opts, driver="distributed-input")
    from .. import control as run_control

    gov = run_control.resolve_governor(opts)

    resume = fs.resume()
    if resume is not None:
        st, icap0, fr0 = _resume_stacked(resume, opts)
        history: List[dict] = resume.history
        h_in = failsafe._histo_from_json(resume.meta.get("qual_in"))
        hausd = resume.meta.get("hausd")
        if hausd is None and "hausd" in resume.meta.get("aux_arrays", {}):
            hausd = jnp.asarray(
                resume.meta["aux_arrays"]["hausd"], st.vert.dtype
            )
        st, comm, status = _iteration_loop(
            st, opts, hausd, history, icap0=icap0,
            fs=fs, start_it=resume.it + 1, emult0=resume.emult,
            ckpt_meta=dict(qual_in=resume.meta.get("qual_in")),
            fr0=fr0, governor=gov,
        )
        return st, comm, _finish_dist_info(
            st, history, h_in, fs, status, opts, "distributed-input",
            governor=gov,
        )

    # per-shard preprocess: adjacency + analysis + metric, then the
    # cross-shard feature agreement pass for surface edges split by an
    # interface (the reference's PMMG_analys with its PMMG_setdhd
    # exchange rounds, src/libparmmg.c:314 + src/analys_pmmg.c:2001)
    shards = []
    ecap0 = int(stacked.tet.shape[1] * 1.6) + 64
    for m in unstack_mesh(stacked):
        shards.append(analysis.analyze(m, ang=opts.angle, opnbdy=opts.opnbdy))
    if opts.angle is not None:
        shards = analysis.cross_shard_features(shards, ang=opts.angle)
    shards = [prepare_metric(m, opts, ecap0) for m in shards]
    fcaps = {m.fcap for m in shards}
    ecaps = {m.ecap for m in shards}
    if len(fcaps) > 1 or len(ecaps) > 1:  # analysis growth diverged
        fc, ec = max(fcaps), max(ecaps)
        shards = [m.with_capacity(fcap=fc, ecap=ec) for m in shards]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
    if opts.hausd is not None:
        hausd = float(opts.hausd)
    else:  # global bounding box across shards
        w = stacked.vmask[..., None]
        lo = jnp.min(jnp.where(w, stacked.vert, jnp.inf), axis=(0, 1))
        hi = jnp.max(jnp.where(w, stacked.vert, -jnp.inf), axis=(0, 1))
        diag = float(jax.device_get(jnp.linalg.norm(hi - lo)))
        hausd = 0.01 * (diag if diag > 0 else 1.0)
    if opts.local_params:
        from .adapt import local_hausd_table

        hausd = local_hausd_table(stacked, opts, hausd)
    h_in = quality.merge_stacked_histograms(
        jax.vmap(quality.quality_histogram)(stacked)
    )

    stacked = _presize_for_target(stacked, opts)
    history = []
    # the supplied comm's tables stay valid in shape (interfaces are
    # frozen, shared lists can only shrink): reuse its capacity so the
    # rebuilt tables keep a stable static shape across iterations
    stacked, comm, status = _iteration_loop(
        stacked, opts, hausd, history,
        icap0=comm.icap if comm is not None else None,
        fs=fs, ckpt_meta=dict(qual_in=failsafe._histo_to_json(h_in)),
        governor=gov,
    )
    info = _finish_dist_info(
        stacked, history, h_in, fs, status, opts, "distributed-input",
        governor=gov,
    )
    return stacked, comm, info


def merge_adapted(stacked: Mesh, comm: ShardComm) -> Mesh:
    """Centralized-output path: merge adapted shards into one Mesh
    (reference `PMMG_merge_parmesh`, `src/mergemesh_pmmg.c:1571`).
    Requires `assign_global_ids` to have run (adapt_distributed does)."""
    return merge_shards(stacked, comm)
