"""Distributed iterative remesh-repartition driver — the engine.

TPU-native counterpart of the reference's core runtime
(`PMMG_parmmglib1`, reference `src/libparmmg1.c:550-896`): the mesh is
partitioned into shards, each shard's interior is remeshed with frozen
(PARBDY) interfaces by batched operator sweeps, metrics/fields are
re-interpolated from a pre-remesh snapshot, communicator tables are
rebuilt, and interfaces are displaced so frozen bands become interior at
the next iteration.

Re-design notes (vs the reference's per-rank group loop):
 - all shards share one set of static capacities, so the per-shard remesh
   is ONE vmapped sweep over the leading shard axis — under `jit` with a
   sharded leading axis every device remeshes its shard simultaneously
   (the role of each MPI rank calling `MMG5_mmg3d1_delone` on its own
   groups, without host-side divergence).
 - communicator rebuild does not need the reference's face-vertex hash
   remap (`PMMG_update_face2intInterfaceTetra`, `src/libparmmg1.c:361`):
   interface vertices are frozen and carry persistent global ids in
   `Mesh.vglob`, which `compact()` renumbers consistently, so tables are
   re-derived by matching gids (sorted order both sides).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import adjacency, tags
from ..core.mesh import Mesh, compact
from ..ops import analysis, interp, quality
from ..parallel.distribute import (
    ShardComm,
    assign_global_ids,
    merge_shards,
    rebuild_comm,
    split_mesh,
    unstack_mesh,
)
from ..parallel.partition import sfc_partition
from .adapt import (
    AdaptOptions,
    adapt as adapt_single,
    estimate_target_ntet,
    prepare_metric,
    remesh_sweep,
)


# ---------------------------------------------------------------------------
# stacked-mesh utilities (leading axis = shard)
# ---------------------------------------------------------------------------

def stacked_counts(st: Mesh) -> tuple[int, int, int, int]:
    """Max live counts across shards (capacity planning is per the largest
    shard, since capacities are uniform)."""
    return (
        int(jnp.max(jnp.sum(st.vmask, axis=1))),
        int(jnp.max(jnp.sum(st.tmask, axis=1))),
        int(jnp.max(jnp.sum(st.trmask, axis=1))),
        int(jnp.max(jnp.sum(st.edmask, axis=1))),
    )


def grow_stacked(
    st: Mesh,
    pcap: int | None = None,
    tcap: int | None = None,
    fcap: int | None = None,
    ecap: int | None = None,
) -> Mesh:
    """Grow capacities of a stacked mesh (pads axis 1, host-side) by
    delegating to the single source of truth, `Mesh.with_capacity`, per
    shard and restacking."""
    grown = [
        m.with_capacity(pcap, tcap, fcap, ecap) for m in unstack_mesh(st)
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *grown)


def ensure_capacity_stacked(st: Mesh, opts: AdaptOptions) -> Mesh:
    """Stacked analog of `models.adapt.ensure_capacity` (the reference's
    memory-budget role, `src/zaldy_pmmg.c`): grow when any shard crosses
    the utilization trigger."""
    npo, nte, ntr, ned = stacked_counts(st)
    g = opts.grow_factor

    def target(n, cap):
        if n > opts.grow_trigger * cap:
            return max(int(n * g) + 8, int(cap * g))
        return cap

    caps = (
        st.vert.shape[1], st.tet.shape[1], st.tria.shape[1], st.edge.shape[1]
    )
    want = (
        target(npo, caps[0]),
        target(nte, caps[1]),
        target(ntr, caps[2]),
        target(ned, caps[3]),
    )
    if want != caps:
        st = grow_stacked(st, *want)
    return st


# ---------------------------------------------------------------------------
# stacked remesh phase (one outer iteration's operator sweeps)
# ---------------------------------------------------------------------------

def _vsweep(st: Mesh, ecap: int, opts: AdaptOptions):
    fn = partial(
        remesh_sweep,
        ecap=ecap,
        noinsert=opts.noinsert,
        noswap=opts.noswap,
        nomove=opts.nomove,
    )
    return jax.vmap(fn)(st)


def remesh_phase(
    st: Mesh, opts: AdaptOptions, emult: List[float], history: List[dict],
    it: int,
) -> Mesh:
    """Operator sweeps to convergence on every shard at once (vmapped) —
    the batched analog of the per-group `MMG5_mmg3d1_delone` calls in the
    reference loop body (`src/libparmmg1.c:662-800`)."""
    sweep = 0
    budget = opts.max_sweeps
    while sweep < budget:
        st = ensure_capacity_stacked(st, opts)
        ecap = int(st.tet.shape[1] * emult[0]) + 64
        st, stats = _vsweep(st, ecap, opts)
        n_unique = int(jnp.max(stats.n_unique))
        overflow = n_unique > ecap
        if overflow:
            emult[0] = max(
                emult[0] * 1.5,
                1.1 * n_unique / max(int(st.tet.shape[1]), 1),
            )
            if budget < opts.max_sweeps + 4:
                budget += 1
        rec = dict(
            iter=it,
            sweep=sweep,
            nsplit=int(jnp.sum(stats.nsplit)),
            ncollapse=int(jnp.sum(stats.ncollapse)),
            nswap=int(jnp.sum(stats.nswap)),
            nmoved=int(jnp.sum(stats.nmoved)),
            ne=int(jnp.sum(st.tmask)),
            np=int(jnp.sum(st.vmask)),
            capped=bool(jnp.any(stats.split_capped)),
        )
        history.append(rec)
        if opts.verbose >= 2:
            print(
                f"  [dist] it {it} sweep {sweep}: +{rec['nsplit']} "
                f"-{rec['ncollapse']} ~{rec['nswap']} mv{rec['nmoved']} "
                f"-> ne={rec['ne']}"
            )
        nops = rec["nsplit"] + rec["ncollapse"] + rec["nswap"]
        if (
            not rec["capped"]
            and not overflow
            and nops <= opts.converge_frac * max(rec["ne"], 1)
        ):
            break
        sweep += 1
    return st


def interp_phase(st: Mesh, old: Mesh) -> Mesh:
    """Per-shard interpolation from the pre-remesh snapshot —
    `PMMG_interpMetricsAndFields` (`src/interpmesh_pmmg.c:663`; purely
    shard-local, see SURVEY §3.4). Host loop over shards so the rare
    exhaustive-location fallback can compact its failed subset host-side
    (the walk itself is one batched device kernel per shard)."""
    news = unstack_mesh(st)
    olds = unstack_mesh(old)
    out = [
        interp.interp_metrics_and_fields(n, o)[0]
        for n, o in zip(news, olds)
    ]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *out)


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DistOptions(AdaptOptions):
    """Distributed controls on top of the adaptation options (the
    redistribution rows of `PMMG_Param`, reference `src/libparmmg.h:54-90`:
    nobalancing, APImode, niter...)."""

    nparts: int = 8
    nobalancing: bool = False     # -nobalance: skip interface displacement
    ifc_layers: int = 2           # advancing-front displacement depth
    check_comm: bool = False      # chkcomm assert each iteration (debug)
    # minimum elements per shard before distribution pays off — the group
    # sizing role of PMMG_howManyGroups / PMMG_GRPSPL_DISTR_TARGET
    # (reference src/grpsplit_pmmg.c:47, src/parmmg.h:218-227): a mesh
    # smaller than nparts*min_shard_elts is first grown single-shard so
    # frozen interfaces don't dominate the shards
    min_shard_elts: int = 256


def adapt_distributed(
    mesh: Mesh,
    opts: Optional[DistOptions] = None,
):
    """Adapt a centralized mesh on `opts.nparts` shards.

    Returns (stacked Mesh, ShardComm, info). Drives the reference's
    centralized entry semantics (`PMMG_parmmglib_centralized`,
    `src/libparmmg.c:1444`): preprocess → distribute → niter × [remesh
    with frozen interfaces → interpolate → rebuild comm] → global
    numbering. Use `merge_adapted` for the centralized-output path.
    """
    opts = opts or DistOptions()
    nparts = opts.nparts

    # --- preprocess (reference PMMG_preprocessMesh, src/libparmmg.c:128) --
    mesh = adjacency.build_adjacency(mesh)
    mesh = analysis.analyze(mesh)
    ecap0 = int(mesh.tcap * 1.6) + 64
    mesh = prepare_metric(mesh, opts, ecap0)
    h_in = quality.quality_histogram(mesh)

    # a mesh too small for nparts shards is grown single-shard first, so
    # interfaces stay a thin fraction of each shard (group sizing,
    # reference PMMG_howManyGroups, src/grpsplit_pmmg.c:47)
    while (
        int(mesh.ntet) < nparts * opts.min_shard_elts
        and not opts.noinsert
    ):
        pre_opts = dataclasses.replace(opts, niter=1, hgrad=None)
        ne_before = int(mesh.ntet)
        mesh, pre_info = adapt_single(mesh, pre_opts)
        if int(mesh.ntet) <= ne_before:  # metric is satisfied: stop
            break

    # --- distribute (reference PMMG_distribute_mesh) ----------------------
    part = np.asarray(jax.device_get(sfc_partition(mesh, nparts)))
    stacked, comm = split_mesh(mesh, part, nparts)

    # pre-size for the predicted unit mesh (per-shard max) so the sweep
    # compiles once per growth bucket at most
    ests = [
        estimate_target_ntet(m) for m in unstack_mesh(stacked)
    ]
    est_ne = int(max(ests) * 1.35) + 64
    if est_ne > stacked.tet.shape[1]:
        stacked = grow_stacked(
            stacked,
            pcap=max(stacked.vert.shape[1], est_ne // 5 + 64),
            tcap=est_ne,
            fcap=max(stacked.tria.shape[1], est_ne // 4 + 64),
            ecap=max(stacked.edge.shape[1], est_ne // 16 + 64),
        )

    history: List[dict] = []
    emult = [1.6]
    icap = None
    for it in range(opts.niter):
        # snapshot for interpolation (PMMG_update_oldGrps role,
        # src/grpsplit_pmmg.c:1224) — needs fresh adjacency for the walk
        old = jax.vmap(adjacency.build_adjacency)(stacked)

        stacked = remesh_phase(stacked, opts, emult, history, it)
        stacked = jax.vmap(compact)(stacked)

        # comm rebuild from persistent gids (replaces the reference's
        # face-hash remap at src/libparmmg1.c:361)
        comm = rebuild_comm(stacked, icap)
        icap = comm.icap  # keep table shape stable across iterations

        # interpolate metric + fields from the snapshot
        stacked = interp_phase(stacked, old)

        if opts.check_comm:
            from ..parallel import chkcomm
            from ..parallel.shard import device_mesh

            chkcomm.assert_comm_ok(
                stacked, comm, device_mesh(nparts), tol=1e-6
            )

    stacked = assign_global_ids(stacked)
    comm = rebuild_comm(stacked, icap)
    h_out = quality.merge_stacked_histograms(
        jax.vmap(quality.quality_histogram)(stacked)
    )
    info = dict(history=history, qual_in=h_in, qual_out=h_out)
    return stacked, comm, info


def merge_adapted(stacked: Mesh, comm: ShardComm) -> Mesh:
    """Centralized-output path: merge adapted shards into one Mesh
    (reference `PMMG_merge_parmesh`, `src/mergemesh_pmmg.c:1571`).
    Requires `assign_global_ids` to have run (adapt_distributed does)."""
    return merge_shards(stacked, comm)
