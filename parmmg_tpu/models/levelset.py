"""Level-set isovalue discretization (`-ls` mode).

The reference *gates this mode off* ("level-set discretization is not yet
available with parallel remeshing", `src/libparmmg.c:73-76`) while its CLI
accepts the flag (`src/parmmg.c:341-439` routes it). Here the capability
is actually provided, as one-time host-side preprocessing before
adaptation: every tetrahedron crossed by the isosurface {ls = isovalue}
is conformally split along it (marching-tetrahedra patterns with snapped
vertices), subdomain references are assigned by side, and the isosurface
is materialized as boundary triangles so the subsequent adaptation
preserves it (the role Mmg's mmg3d2 splitting plays for `mmg3d -ls`).

Conventions (Mmg's MG_MINUS/MG_PLUS/MG_ISO discipline):
 - tets with ls < isovalue get ref `ref_in` (default 3), ls > isovalue
   get `ref_out` (default 2);
 - isosurface triangles get ref `ref_iso` (default 10);
 - cut boundary triangles are split 2D-conformally and keep their ref.

Conformity across neighboring tets relies only on per-face information:
quads are triangulated along the diagonal through the smallest vertex id
and each convex sub-region is fan-triangulated from its smallest vertex,
so the two tets sharing a face always agree on its sub-triangulation.
"""

from __future__ import annotations

import numpy as np

from ..core import tags
from ..core.mesh import EDGE_VERTS, FACE_VERTS, Mesh

REF_IN = 3
REF_OUT = 2
REF_ISO = 10


def _tri_quad(q):
    """Triangulate cyclic quad [a,b,c,d] along the diagonal through its
    smallest vertex id (consistent for any viewer of the same quad)."""
    a, b, c, d = q
    if min(a, c) < min(b, d):
        return [(a, b, c), (a, c, d)]
    return [(b, c, d), (b, d, a)]


def _fan(faces):
    """Fan-triangulate a convex polyhedron given by triangulated faces:
    tets (m, tri) for every face triangle not containing the global-min
    vertex m."""
    verts = {v for f in faces for v in f}
    m = min(verts)
    out = []
    for f in faces:
        for tri in ([f] if len(f) == 3 else _tri_quad(f)):
            if m not in tri:
                out.append((m,) + tuple(tri))
    return out


def discretize_levelset(
    mesh: Mesh,
    isovalue: float = 0.0,
    ref_in: int = REF_IN,
    ref_out: int = REF_OUT,
    ref_iso: int = REF_ISO,
    snap_rel: float = 1e-6,
) -> Mesh:
    """Split `mesh` along {ls = isovalue}; returns a new conformal Mesh."""
    d = mesh.to_numpy()
    verts = d["verts"]
    tets = d["tets"]
    if d["ls"].shape[1] != 1:
        raise ValueError("level-set mode requires a scalar ls field")
    v = d["ls"][:, 0] - isovalue

    # snap: vertices within snap_rel of the level move onto it exactly
    # (collapses the degenerate cut patterns, Mmg's MMG3D_snpval_ls role)
    scale = max(float(np.abs(v).max()), 1e-300)
    v = np.where(np.abs(v) < snap_rel * scale, 0.0, v)
    sgn = np.sign(v).astype(np.int8)

    st = sgn[tets]                                   # [T,4]
    cut = (st.min(axis=1) < 0) & (st.max(axis=1) > 0)

    # cut points: one new vertex per sign-changing unique edge
    ev = tets[:, EDGE_VERTS].reshape(-1, 2)
    e_lo = np.minimum(ev[:, 0], ev[:, 1])
    e_hi = np.maximum(ev[:, 0], ev[:, 1])
    s_lo, s_hi = sgn[e_lo], sgn[e_hi]
    crossing = (s_lo.astype(int) * s_hi.astype(int)) < 0
    ce = np.unique(np.stack([e_lo[crossing], e_hi[crossing]], 1), axis=0)
    npo = len(verts)
    t = v[ce[:, 0]] / (v[ce[:, 0]] - v[ce[:, 1]])
    new_pts = verts[ce[:, 0]] + t[:, None] * (verts[ce[:, 1]] - verts[ce[:, 0]])

    def lerp(arr):
        return arr[ce[:, 0]] + t[:, None] * (arr[ce[:, 1]] - arr[ce[:, 0]])

    cut_id = {}
    for k, (a, b) in enumerate(ce):
        cut_id[(int(a), int(b))] = npo + k

    def cid(a, b):
        return cut_id[(min(a, b), max(a, b))]

    # --- split tets --------------------------------------------------------
    out_tets, out_refs = [], []
    iso_tris = []

    for ti in np.nonzero(~cut)[0]:
        out_tets.append(tuple(tets[ti]))
        s = st[ti]
        out_refs.append(ref_in if (s.min() < 0 or s.max() == 0) else ref_out)

    for ti in np.nonzero(cut)[0]:
        vv = [int(x) for x in tets[ti]]
        s = {x: int(sgn[x]) for x in vv}
        P = [x for x in vv if s[x] > 0]
        N = [x for x in vv if s[x] < 0]
        Z = [x for x in vv if s[x] == 0]

        regions = []  # (faces, ref)
        if len(P) == 1 and len(N) == 3:
            a, (n1, n2, n3) = P[0], N
            c1, c2, c3 = cid(a, n1), cid(a, n2), cid(a, n3)
            regions.append(([(a, c1, c2), (a, c2, c3), (a, c1, c3),
                             (c1, c2, c3)], ref_out))
            regions.append(([(n1, n2, n3), (c1, c2, c3),
                             (n1, n2, c2, c1), (n2, n3, c3, c2),
                             (n1, n3, c3, c1)], ref_in))
            iso_tris.append((c1, c2, c3))
        elif len(N) == 1 and len(P) == 3:
            a, (n1, n2, n3) = N[0], P
            c1, c2, c3 = cid(a, n1), cid(a, n2), cid(a, n3)
            regions.append(([(a, c1, c2), (a, c2, c3), (a, c1, c3),
                             (c1, c2, c3)], ref_in))
            regions.append(([(n1, n2, n3), (c1, c2, c3),
                             (n1, n2, c2, c1), (n2, n3, c3, c2),
                             (n1, n3, c3, c1)], ref_out))
            iso_tris.append((c1, c2, c3))
        elif len(P) == 2 and len(N) == 2:
            p1, p2 = P
            n1, n2 = N
            c11, c12 = cid(p1, n1), cid(p1, n2)
            c21, c22 = cid(p2, n1), cid(p2, n2)
            isoq = (c11, c21, c22, c12)
            regions.append(([(p1, c11, c12), (p2, c21, c22),
                             (p1, p2, c21, c11), (p1, p2, c22, c12),
                             isoq], ref_out))
            regions.append(([(n1, c11, c21), (n2, c12, c22),
                             (n1, n2, c12, c11), (n1, n2, c22, c21),
                             isoq], ref_in))
            iso_tris.extend(_tri_quad(isoq))
        elif len(P) == 1 and len(N) == 2 and len(Z) == 1:
            p, (n1, n2), z = P[0], N, Z[0]
            c1, c2 = cid(p, n1), cid(p, n2)
            regions.append(([(p, c1, c2), (p, c1, z), (p, c2, z),
                             (c1, c2, z)], ref_out))
            regions.append(([(n1, n2, z), (n1, z, c1), (n2, z, c2),
                             (n1, n2, c2, c1), (c1, c2, z)], ref_in))
            iso_tris.append((c1, c2, z))
        elif len(N) == 1 and len(P) == 2 and len(Z) == 1:
            p, (n1, n2), z = N[0], P, Z[0]
            c1, c2 = cid(p, n1), cid(p, n2)
            regions.append(([(p, c1, c2), (p, c1, z), (p, c2, z),
                             (c1, c2, z)], ref_in))
            regions.append(([(n1, n2, z), (n1, z, c1), (n2, z, c2),
                             (n1, n2, c2, c1), (c1, c2, z)], ref_out))
            iso_tris.append((c1, c2, z))
        elif len(P) == 1 and len(N) == 1 and len(Z) == 2:
            p, n = P[0], N[0]
            z1, z2 = Z
            c = cid(p, n)
            regions.append(([(p, c, z1), (p, c, z2), (p, z1, z2),
                             (c, z1, z2)], ref_out))
            regions.append(([(n, c, z1), (n, c, z2), (n, z1, z2),
                             (c, z1, z2)], ref_in))
            iso_tris.append((c, z1, z2))
        else:  # unreachable given cut criterion + snapping
            raise AssertionError(f"unclassified cut pattern P{P} N{N} Z{Z}")

        for faces, ref in regions:
            for tt in _fan(faces):
                out_tets.append(tt)
                out_refs.append(ref)

    all_pts = np.concatenate([verts, new_pts], axis=0)
    out_tets = np.asarray(out_tets, np.int64)
    out_refs = np.asarray(out_refs, np.int64)
    # orient positively; drop degenerate slivers from snapped geometry
    c = all_pts[out_tets]
    vol = np.einsum(
        "ti,ti->t",
        np.cross(c[:, 1] - c[:, 0], c[:, 2] - c[:, 0]), c[:, 3] - c[:, 0],
    ) / 6.0
    flip = vol < 0
    out_tets[flip] = out_tets[flip][:, [0, 1, 3, 2]]
    good = np.abs(vol) > 1e-30
    out_tets, out_refs = out_tets[good], out_refs[good]

    # --- boundary trias: keep uncut, split cut ones 2D-conformally ---------
    trias, trrefs, trtags = d["trias"], d["trrefs"], d["trtags"]
    out_tris, out_trefs, out_ttags = [], [], []
    for fi in range(len(trias)):
        tv = [int(x) for x in trias[fi]]
        s3 = [int(sgn[x]) for x in tv]
        if min(s3) >= 0 or max(s3) <= 0:  # uncut
            out_tris.append(tuple(tv))
            out_trefs.append(int(trrefs[fi]))
            out_ttags.append(int(trtags[fi]))
            continue
        P = [x for x in tv if sgn[x] > 0]
        N = [x for x in tv if sgn[x] < 0]
        Z = [x for x in tv if sgn[x] == 0]
        if len(Z) == 1:  # one cut edge through the zero vertex
            p, n, z = P[0], N[0], Z[0]
            cc = cid(p, n)
            subs = [(p, cc, z), (n, cc, z)]
        else:  # 1 vs 2: one tri + one quad
            if len(P) == 1:
                a, (b1, b2) = P[0], N
            else:
                a, (b1, b2) = N[0], P
            c1, c2 = cid(a, b1), cid(a, b2)
            subs = [(a, c1, c2)] + _tri_quad((b1, b2, c2, c1))
        for tri in subs:
            out_tris.append(tuple(tri))
            out_trefs.append(int(trrefs[fi]))
            out_ttags.append(int(trtags[fi]))
    # isosurface trias
    for tri in iso_tris:
        out_tris.append(tuple(tri))
        out_trefs.append(ref_iso)
        out_ttags.append(tags.BDY | tags.REF)

    # drop sub-trias whose owner sub-tet was discarded as a degenerate
    # sliver above: a boundary tria with no adjacent tet face would make
    # tria_normals fall back to stored winding and could misclassify the
    # patch during feature detection
    out_tris_a = np.asarray(out_tris, np.int64).reshape(-1, 3)
    out_trefs_a = np.asarray(out_trefs, np.int64)
    out_ttags_a = np.asarray(out_ttags, np.int64)
    if len(out_tris_a):
        from ..utils.rows import row_member

        fkeys = np.sort(
            out_tets[:, np.asarray(FACE_VERTS)].reshape(-1, 3), axis=1
        )
        keep = row_member(np.sort(out_tris_a, axis=1), fkeys)
        out_tris_a = out_tris_a[keep]
        out_trefs_a = out_trefs_a[keep]
        out_ttags_a = out_ttags_a[keep]

    # --- vertex data -------------------------------------------------------
    def cat(name, newvals):
        return np.concatenate([d[name], newvals], axis=0)

    ls_new = np.full((len(new_pts), 1), isovalue)
    met = cat("met", lerp(d["met"]))
    fields = cat("fields", lerp(d["fields"])) if d["fields"].shape[1] else None
    disp = cat("disp", lerp(d["disp"])) if d["disp"].shape[1] else None
    vtags = np.concatenate(
        [d["vtags"], np.zeros(len(new_pts), np.int32)]
    )

    return Mesh.from_numpy(
        all_pts, out_tets, trefs=out_refs,
        vrefs=cat("vrefs", np.zeros(len(new_pts), np.int32)),
        vtags=vtags,
        trias=out_tris_a,
        trrefs=out_trefs_a,
        trtags=out_ttags_a,
        edges=d["edges"], edrefs=d["edrefs"], edtags=d["edtags"],
        met=met,
        ls=np.concatenate([d["ls"] - 0.0, ls_new]),
        disp=disp, fields=fields, field_ncomp=d["field_ncomp"],
        dtype=mesh.dtype,
    )
