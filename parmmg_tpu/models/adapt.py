"""Single-shard adaptation driver: the batched analog of the Mmg kernel.

Where the reference runs the serial cavity remesher per group
(`MMG5_mmg3d1_delone` in the `PMMG_parmmglib1` loop, reference
`src/libparmmg1.c:636-896`), this driver runs Jacobi *sweeps* of the batched
operators — split long edges, collapse short ones, 3-2/2-3 swaps, smoothing
— until the mesh is a unit mesh for the metric. Control flow that decides
array capacities lives on the host (recompile-on-bucket-change); everything
else is one fused jitted sweep.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import adjacency, metric as metric_mod, tags
from ..core.mesh import Mesh, compact, compact_aux
from ..obs import (
    costs as obs_costs,
    health as obs_health,
    metrics as obs_metrics,
    trace as obs_trace,
)
from ..ops import analysis, collapse, common, quality, smooth, split, swap


@dataclasses.dataclass
class AdaptOptions:
    """Adaptation controls, mirroring the reference's parameter surface
    (`PMMG_Param` enum, reference `src/libparmmg.h:54-90` and CLI flags in
    `src/libparmmg_tools.c:108-163`)."""

    niter: int = 3              # outer iterations (PMMG_NITER default)
    max_sweeps: int = 12        # operator sweeps per iteration
    hsiz: Optional[float] = None    # constant target size (-hsiz)
    hmin: Optional[float] = None
    hmax: Optional[float] = None
    hgrad: Optional[float] = 1.3    # size gradation (-hgrad), None = off
    # Hausdorff bound for boundary modification (-hausd); None = auto,
    # 0.01 x bounding-box diagonal (the reference scales the mesh to a
    # unit box and uses hausd=0.01, MMG5_HAUSD default)
    hausd: Optional[float] = None
    # feature-detection dihedral angle in degrees (-ar); None = -nr
    # (no angle detection)
    angle: Optional[float] = 45.0
    optim: bool = False         # keep implied sizes (-optim)
    # -optimLES: strong optimization for LES — implies optim; iso only
    # (the reference rejects optimLES with an aniso metric)
    optim_les: bool = False
    # -A: anisotropy without a metric file (PMMG_IPARAM_anisosize,
    # reference `src/libparmmg_tools.c:142`): tensor metric implied by
    # the input mesh, or the given scalar sizes promoted to tensors
    aniso: bool = False
    # -nofem: allow non finite-element configurations. Accepted for call
    # parity; the batched operators never create the edge-connections Mmg
    # repairs in FEM mode, so there is nothing to relax (obviated).
    nofem: bool = False
    # -hgradreq: gradation ratio propagated FROM required entities (their
    # sizes win); None = off (Mmg MMG3D_gradsizreq role)
    hgradreq: Optional[float] = None
    # parsop local parameters: per-reference hmin/hmax/hausd overrides
    # (`PMMG_parsop`, reference `src/libparmmg_tools.c:573`)
    local_params: tuple = ()
    noinsert: bool = False      # -noinsert: no splits
    nosurf: bool = False        # -nosurf: freeze the boundary surface
    noswap: bool = False        # -noswap
    nomove: bool = False        # -nomove
    # -opnbdy: preserve open internal boundaries (same-ref internal
    # trias) as adapted surface (PMMG_IPARAM_opnbdy, reference
    # `src/libparmmg.h:64`; tag special case `src/tag_pmmg.c:267`)
    opnbdy: bool = False
    # convergence: stop sweeping when ops this sweep < frac * ntet
    converge_frac: float = 0.005
    # post-convergence polish: up to this many quality-only sweeps
    # (no insertion), keeping the best histogram — the convergence
    # threshold can strand a few hundred improving ops (a 0.10-class
    # sliver among ~94k tets) and single sweeps jitter the min
    # non-monotonically, so each result is kept only when
    # (qmin, -worst-bin, qavg) improves lexicographically
    polish_sweeps: int = 2
    # capacity management
    grow_trigger: float = 0.85
    grow_factor: float = 1.6
    # device-memory budget in MB for the mesh arrays (per shard in the
    # distributed driver) — the role of the reference's per-node memory
    # budget (`PMMG_parmesh_SetMemGloMax`, `src/zaldy_pmmg.c:53`; -m
    # flag / IPARAM_mem). None = derive from the device's reported
    # memory at adapt() entry (the reference auto-derives node RAM ÷
    # procs, `PMMG_parmesh_SetMemGloMax`); pass float("inf") for
    # genuinely unbounded. Exceeding it raises RuntimeError, which the
    # distributed loop degrades to LOWFAILURE with the last conformal
    # mesh.
    mem_budget_mb: Optional[float] = None
    # active-set (frontier) sweeps: each sweep records the vertices it
    # changed and the next sweep's candidate generation, analysis
    # rebuilds and apply phases address only entities near that
    # frontier (round 6). Round 8 extended the carry through the
    # distributed drivers too — per-shard frontier state through the
    # vmapped/SPMD sweeps, remapped through migration so cells crossing
    # a shard boundary arrive active on their new owner — so True is
    # the default EVERYWHERE (CLI -nofrontier / False = full-table
    # sweeps, the pre-frontier behavior kept as the A/B baseline).
    frontier: bool = True
    # closed-loop load balancing (distributed driver): band on the
    # MEASURED work imbalance (max/mean of per-shard active x live-tet
    # demand) past which the BalancePolicy fires — displacement first,
    # full re-cut on a repeat breach (parallel.migrate.BalancePolicy).
    # None = PMMGTPU_BALANCE_BAND env, else the conservative default
    # (1.5); <= 0 disables the policy (CLI -balance <band>, with
    # -balance 0 as the policy-only escape hatch; -nobalance still
    # switches off ALL between-iteration resharding). Excluded from the
    # checkpoint fingerprint like other resource-layout knobs.
    balance_band: Optional[float] = None
    # closed-loop run governor (parmmg_tpu.control): verdict-driven
    # early termination (oscillating/stalled under the rolling
    # health.assess window stops the phase and refunds the remaining
    # sweep budget, unless len/in_band is still improving), drain-ETA
    # budget capping and drained-frontier niter shortening, each
    # emitted as a control_decision event (obs_report --control).
    # None = PMMGTPU_GOVERN env (default off — equivalence gates
    # compare governor-free arms); True/False force it. Excluded from
    # the checkpoint fingerprint: arming control on a resume is
    # legitimate and must not refuse the checkpoint.
    govern: Optional[bool] = None
    # Pallas kernel subsystem selection (parmmg_tpu.kernels.registry):
    # None leaves the process mode alone (PMMGTPU_KERNELS env, default
    # "auto" = Pallas on TPU / lax elsewhere); "off" = lax references
    # everywhere (bit-identical A/B baseline), "on" = Pallas everywhere
    # (interpret=True off-TPU), or a csv allowlist of kernel names.
    # Applied process-wide at driver entry; an effective-mode change
    # drops warmed jit traces (the dispatch is baked in at trace time).
    kernels: Optional[str] = None
    # --- fail-safe layer (parmmg_tpu.failsafe) ---------------------------
    # phase-boundary validation level: "off" | "basic" (device
    # finiteness + positive orientation, one fused reduce) | "full"
    # (basic + host conformity + comm symmetry) — the cadence-
    # configurable validator replacing the old ad-hoc _finite_ok
    validate: str = "basic"
    validate_every: int = 1     # validation cadence in outer iterations
    # bounded grow-and-retry budget per iteration: on a CapacityError
    # the driver rolls back to the iteration-start snapshot, grows the
    # offending capacities and re-enters instead of raising; on an
    # (injected or real) transient retrace error it clears the compile
    # caches and re-enters. 0 disables recovery (failures degrade to
    # LOWFAILURE immediately).
    recovery_attempts: int = 2
    # atomic per-iteration checkpoints (mesh + metric + sweep state +
    # history + options fingerprint, tmp+os.replace) written here; on
    # the next run with the same directory a compatible checkpoint is
    # detected and the run RESUMES from it (a mismatched options
    # fingerprint refuses with CheckpointMismatchError)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1   # checkpoint cadence in outer iterations
    # pluggable checkpoint storage (io.ckpt_store): an explicit
    # CheckpointStore instance, "mem://<bucket>" (in-process object
    # store, GCS put semantics), "file://<dir>", or None = LocalFSStore
    # over checkpoint_dir. Retry/backoff/timeout knobs ride the
    # PMMGTPU_CKPT_* env contract.
    checkpoint_store: Optional[object] = None
    # async snapshot staging: device->host snapshot at the iteration
    # boundary, serialize+put on a background writer thread — the loop
    # blocks only at the commit of the PREVIOUS checkpoint, and the
    # preemption/exit paths drain the queue (env PMMGTPU_ASYNC_CKPT=1
    # flips it without re-plumbing)
    checkpoint_async: bool = False
    # checkpoint GC: retain only the last K committed checkpoints per
    # run, pruning older ckpt_* files after each successful commit (a
    # long run would otherwise accumulate every iteration's full mesh
    # on disk)
    checkpoint_keep: int = 2
    # collective watchdog (multi-process runs): seconds a phase-boundary
    # heartbeat / checkpoint barrier may block before a silent peer loss
    # is converted into a typed failsafe.PeerLostError instead of
    # hanging the survivors forever. None = no watchdog (single-process
    # runs need none; the barrier is then unbounded).
    watchdog_timeout: Optional[float] = None
    # deterministic fault injection: a failsafe.FaultPlan (or spec
    # string "it1:remesh:nan,..."); None reads the PARMMG_FAULTS env var
    faults: Optional[object] = None
    verbose: int = 0


class SweepStats(NamedTuple):
    nsplit: jax.Array
    ncollapse: jax.Array
    nswap: jax.Array
    nmoved: jax.Array
    n_unique: jax.Array
    split_capped: jax.Array
    n_active: jax.Array     # active edges offered to this sweep's ops
    # unit-mesh telemetry (ParMmg -prilen analog, health observatory):
    # edges of the sweep's exit tables whose metric length lands in
    # [LSHRT, LLONG], and the edge count they were measured over
    n_len_unit: jax.Array
    n_len_edges: jax.Array


def _len_band_counts(mesh: Mesh, edges, emask):
    """Device-side unit-band edge count over the sweep's exit tables:
    (n_in_band, n_edges), both int32. One `edge_length` pass — the same
    metric lengths the split/collapse gates consume — so it rides every
    dispatch path (fused while_loop, unfused host loop, vmap, shard_map)
    at one reduction's cost. In frontier mode the tables may carry
    pending level-1 deltas (telemetry-grade mid-run, exact at
    convergence when the tables are clean)."""
    p0, p1 = mesh.vert[edges[:, 0]], mesh.vert[edges[:, 1]]
    m0, m1 = mesh.met[edges[:, 0]], mesh.met[edges[:, 1]]
    l = metric_mod.edge_length(p0, p1, m0, m1)
    band = emask & (l >= metric_mod.LSHRT) & (l <= metric_mod.LLONG)
    return (
        jnp.sum(band.astype(jnp.int32)),
        jnp.sum(emask.astype(jnp.int32)),
    )


class Frontier(NamedTuple):
    """Per-sweep active-set state threaded through the sweep engines.

    `changed` is the RAW set of vertices the previous sweep changed
    (geometry beyond smooth.MOVE_TOL, or 1-ring topology); each op gates
    on its one-ring closure, computed against the current topology.
    `dirty` is the staleness LEVEL of the compaction/edge tables:
    0 = clean (reuse `tables` bit for bit), 1 = stable-numbering
    topology deltas since the rebuild (2-3 swap appends, plus any
    rewrites/tombstones that did not force a compaction — folded in by
    the general `adjacency.merge_unique_edges`, no compaction),
    2 = renumbering topology (a compaction with holes ran since the
    rebuild, permuting tet rows: full compact + re-sort). `tables` is
    the (edges, emask, t2e, n_unique) tuple of the last rebuild;
    `adja_ok` marks `mesh.adja` still valid for the CURRENT numbering
    (lets a converged sweep skip `build_adjacency`).

    On the distributed paths every leaf gains a leading shard axis (see
    `stacked_frontier`); `dirty`/`adja_ok` stay per-shard scalars under
    `shard_map` (shard-varying cond skips — a converged shard stops
    paying for its neighbors' work) and are host-shared conservative
    scalars under the vmapped dispatch (where a batched predicate would
    lower the skip to a both-branches select)."""

    changed: jax.Array      # [PC] bool
    dirty: jax.Array        # scalar int32 level (host int unfused)
    tables: tuple           # (edges [E,2], emask [E], t2e [TC,6], nu)
    adja_ok: jax.Array      # scalar bool


def empty_frontier(mesh: Mesh, ecap: int, full: bool = True) -> Frontier:
    """Initial frontier: every vertex active (`full`, the exact
    full-sweep fallback) or none; tables marked stale so the first
    sweep rebuilds them."""
    act = jnp.full(mesh.pcap, bool(full), bool)
    tables = (
        jnp.zeros((ecap, 2), jnp.int32),
        jnp.zeros(ecap, bool),
        jnp.full((mesh.tcap, 6), -1, jnp.int32),
        jnp.int32(0),
    )
    return Frontier(act, jnp.int32(2), tables, jnp.bool_(False))


def stacked_frontier(
    st: Mesh, ecap: int, changed=None, per_shard_state: bool = False,
) -> Frontier:
    """Stacked (leading shard axis) frontier for the distributed sweep
    engines: per-shard changed masks (default all-active — the exact
    full-sweep fallback) over stale tables.

    `per_shard_state=True` makes `dirty`/`adja_ok` per-shard [D] arrays
    (the SPMD `shard_map` layout, where each device branches on its own
    staleness); the default keeps them shared scalars (the vmapped
    layout — an unbatched predicate keeps the table conds real
    conditionals instead of both-branches selects)."""
    D, pc = st.vert.shape[0], st.vert.shape[1]
    chg = (
        jnp.ones((D, pc), bool) if changed is None
        else jnp.asarray(changed, bool)
    )
    tables = (
        jnp.zeros((D, ecap, 2), jnp.int32),
        jnp.zeros((D, ecap), bool),
        jnp.full((D, st.tet.shape[1], 6), -1, jnp.int32),
        jnp.zeros((D,), jnp.int32),
    )
    if per_shard_state:
        return Frontier(
            chg, jnp.full((D,), 2, jnp.int32), tables,
            jnp.zeros((D,), bool),
        )
    return Frontier(chg, jnp.int32(2), tables, jnp.bool_(False))


def pad_changed(changed, pcap: int):
    """Pad a stacked [D, PC_old] changed mask to a grown vertex capacity
    (growth appends slots, so vertex ids are stable and the new tail is
    inactive). Capacities never shrink (`Mesh.with_capacity`)."""
    pad = pcap - changed.shape[1]
    if pad > 0:
        changed = jnp.pad(changed, ((0, 0), (0, pad)))
    return changed


def _sweep_body(
    mesh: Mesh,
    ecap: int,
    noinsert: bool = False,
    noswap: bool = False,
    nomove: bool = False,
    nosurf: bool = False,
    hausd: float = 0.01,
    fused: bool = True,
    phase_skip: bool = True,
    frontier: Optional["Frontier"] = None,
):
    """One sweep: split → (collapse → swaps → smooth unless the sweep is
    split-dominant).

    Compaction (the batched `MMG3D_pack`/`PMMG_packParMesh` analog) runs
    before operators that allocate, so live entities form array prefixes.

    Phase-aware scheduling: while refinement is still bisecting
    globally-long edges wholesale (split > ntet/10 this sweep and not
    capacity-capped), the quality tail — collapse, swaps, smoothing,
    ~70% of sweep cost — is skipped via `lax.cond`: each bisection round
    halves edge lengths everywhere and the next sweep re-splits the same
    regions, so interleaved quality passes buy nothing until lengths
    approach the unit target. The serial kernel behaves the same way:
    `MMG5_mmg3d1_delone`'s early passes are insertion-dominant, quality
    effort ramps as `ns` falls (reference `src/libparmmg1.c:739`).

    Frontier mode (round 6): with `frontier=Frontier(...)` the sweep is
    ACTIVE-SET driven — candidate generation in every operator is gated
    on the one-ring closure of the previous sweep's changed vertices,
    the compaction + `unique_edges` rebuilds at the sweep boundaries are
    reused from `frontier.tables` when no topological op ran since they
    were computed (exact reuse: recomputing over an unchanged mesh
    returns the same tables bit for bit), and `build_adjacency` before
    the 2-3 swap is skipped while `frontier.adja_ok` holds. The sweep
    returns a third element, the successor Frontier. `frontier=None`
    (all legacy callers and the distributed/vmapped paths) is the exact
    pre-frontier full-table sweep.

    Called two ways: under the `remesh_sweep`/`remesh_sweeps` jit with
    `fused=True` (ONE fused device program — best runtime, but its XLA
    compile grows super-linearly with the array shapes: >2h on the TPU
    tunnel at ~850k-tet capacities) — the phase skip and the frontier
    reuse decisions are `lax.cond`s; or DIRECTLY with `fused=False` for
    large meshes, where each constituent op runs as its own jitted
    program and every skip is a host branch (measured: single ops
    compile in seconds even at 5M rows — the blowup is whole-program
    scheduling, not op codegen)."""
    fr = frontier is not None

    def _host_int(x):
        if isinstance(x, (bool, int)):
            # guarded by the isinstance above: x is a host scalar here
            return int(x)  # parmmg-lint: disable=PML002
        assert not isinstance(x, jax.core.Tracer), (
            "_sweep_body(fused=False) requires concrete frontier flags; "
            "under vmap/jit pass fused=True or frontier=None"
        )
        # intentional host sync: this IS the unfused host-side branch
        # (same discipline as the fused=False phase skip below)
        return int(jax.device_get(x))  # parmmg-lint: disable=PML001,PML002

    def _host_bool(x):
        return bool(_host_int(x))

    def _closure(m, base):
        return common.one_ring_closure(m.tet, m.tmask, base)

    if not fr:
        mesh = compact(mesh)
        edges, emask, t2e, n_unique = adjacency.unique_edges(mesh, ecap)
        act = None
        chg = None
        adja_ok = None
    else:
        act, dirty, tables_in, adja_ok = frontier
        # merge_unique_edges frontier-stream capacity: stable-numbering
        # sweeps touch a few % of tets; tcap//4 gives the incremental
        # path a 4x-smaller sort with a fallback that stays exact
        k_edge = max(64, mesh.tcap // 4)

        def _entry_fresh(m, a):
            # level 2: a renumbering compaction ran — compact + re-sort
            m, a = compact_aux(m, a)
            e, em, t2, nu = adjacency.unique_edges(m, ecap)
            # int32 under x64 too: the reuse branch passes the stored
            # int32 tables and lax.cond demands identical branch types
            return m, a, e, em, t2, jnp.asarray(nu, jnp.int32), jnp.bool_(False)

        def _entry_merge(m, a):
            # level 1: stable-numbering topology deltas (2-3 swap
            # appends and rewrites) — the mesh is still prefix-packed,
            # so skip the compaction and fold the delta into the cached
            # tables with the general incremental merge (tombstone +
            # slot reclamation; exact, overflow falls back to the full
            # sort)
            e, em, t2, nu = tables_in
            e, em, t2, nu = adjacency.merge_unique_edges(
                m, a, e, em, t2, nu, K=k_edge
            )
            return m, a, e, em, t2, nu, jnp.asarray(adja_ok, bool)

        def _entry_reuse(m, a):
            e, em, t2, nu = tables_in
            return m, a, e, em, t2, nu, jnp.asarray(adja_ok, bool)

        if fused:
            def _entry_dirty(m, a):
                return jax.lax.cond(
                    dirty >= 2, _entry_fresh, _entry_merge, m, a
                )

            mesh, act, edges, emask, t2e, n_unique, adja_ok = jax.lax.cond(
                dirty >= 1, _entry_dirty, _entry_reuse, mesh, act
            )
        else:
            lvl = _host_int(dirty)
            entry = (
                _entry_fresh if lvl >= 2
                else _entry_merge if lvl >= 1
                else _entry_reuse
            )
            mesh, act, edges, emask, t2e, n_unique, adja_ok = entry(
                mesh, act
            )
        chg = mesh.vmask & False   # varying zeros (shard_map discipline)

    if fr:
        g0 = _closure(mesh, act)
        n_active = jnp.sum(
            (emask & (g0[edges[:, 0]] | g0[edges[:, 1]])).astype(jnp.int32)
        ).astype(jnp.int32)
    else:
        g0 = None
        n_active = jnp.asarray(n_unique, jnp.int32)

    if not noinsert:
        mesh, s_split = split.split_long_edges(
            mesh, edges, emask, t2e, nosurf=nosurf, active=g0
        )
        if not fr:
            mesh = compact(mesh)
            edges, emask, t2e, nu = adjacency.unique_edges(mesh, ecap)
            n_unique = jnp.maximum(n_unique, nu)
        else:
            chg = chg | s_split.changed_v

            def _ps_fresh(m, aux):
                m, aux = compact_aux(m, aux)
                e, em, t2, nu = adjacency.unique_edges(m, ecap)
                return m, aux, e, em, t2, jnp.asarray(
                    jnp.maximum(n_unique, nu), jnp.int32
                )

            def _ps_reuse(m, aux):
                return m, aux, edges, emask, t2e, n_unique

            aux = jnp.stack([act, chg], axis=1)
            if fused:
                mesh, aux, edges, emask, t2e, n_unique = jax.lax.cond(
                    s_split.nsplit > 0, _ps_fresh, _ps_reuse, mesh, aux
                )
            elif _host_bool(s_split.nsplit > 0):
                mesh, aux, edges, emask, t2e, n_unique = _ps_fresh(mesh, aux)
            else:
                mesh, aux, edges, emask, t2e, n_unique = _ps_reuse(mesh, aux)
            act, chg = aux[:, 0], aux[:, 1]
        # split-dominant growth detection: while refinement is still
        # bisecting globally-long edges wholesale, collapse/swap/smooth
        # (~70% of sweep cost) buy nothing — the next sweep re-splits
        # the same regions. Quality ops resume once splitting tapers
        # (or capacity capped the sweep, where coarsening may free
        # room).
        growth = (
            (s_split.nsplit > jnp.maximum(64, mesh.ntet // 10))
            & ~s_split.capped
        )
    else:
        s_split = split.SplitStats(
            jnp.int32(0), jnp.int32(0), jnp.bool_(False),
            jnp.zeros(mesh.pcap, bool),
        )
        growth = jnp.bool_(False)

    def _quality_tail(mesh, edges, emask, t2e, n_unique, chg, adja_ok):
        av = act
        g = _closure(mesh, av | chg) if fr else None
        mesh, s_col = collapse.collapse_short_edges(
            mesh, edges, emask, t2e, hausd=hausd, nosurf=nosurf, active=g
        )
        if not fr:
            mesh = compact(mesh)
            edges, emask, t2e, nu = adjacency.unique_edges(mesh, ecap)
            n_unique = jnp.maximum(n_unique, nu)
        else:
            chg = chg | s_col.changed_v

            def _pc_fresh(m, aux):
                m, aux = compact_aux(m, aux)
                e, em, t2, nu = adjacency.unique_edges(m, ecap)
                return m, aux, e, em, t2, jnp.asarray(
                    jnp.maximum(n_unique, nu), jnp.int32
                )

            def _pc_reuse(m, aux):
                return m, aux, edges, emask, t2e, n_unique

            aux = jnp.stack([av, chg], axis=1)
            if fused:
                mesh, aux, edges, emask, t2e, n_unique = jax.lax.cond(
                    s_col.ncollapse > 0, _pc_fresh, _pc_reuse, mesh, aux
                )
            elif _host_bool(s_col.ncollapse > 0):
                mesh, aux, edges, emask, t2e, n_unique = _pc_fresh(mesh, aux)
            else:
                mesh, aux, edges, emask, t2e, n_unique = _pc_reuse(mesh, aux)
            av, chg = aux[:, 0], aux[:, 1]

        if not noswap:
            g2 = _closure(mesh, av | chg) if fr else None
            mesh, s_32 = swap.swap_32(mesh, edges, emask, t2e, active=g2)
            # swaps never delete vertices, so compact() keeps vertex ids
            # and the post-collapse edge list stays valid: swap_23 uses
            # it only for a conservative new-edge-exists check, and
            # smoothing below tolerates approximate neighborhoods (its
            # validity loop guards geometry) — two unique_edges re-sorts
            # (~1/3 of sweep sort cost) skipped
            if not fr:
                mesh = adjacency.build_adjacency(compact(mesh))
                mesh, s_23 = swap.swap_23(mesh, edges, emask)
                mesh = compact(mesh)
                adja_ok_out = None
            else:
                chg = chg | s_32.changed_v
                topo = (
                    (s_split.nsplit > 0) | (s_col.ncollapse > 0)
                    | (s_32.nswap32 > 0)
                )
                need = ~jnp.asarray(adja_ok, bool) | topo

                def _adj_fresh(m, aux):
                    m, aux = compact_aux(m, aux)
                    return adjacency.build_adjacency(m), aux

                def _adj_reuse(m, aux):
                    return m, aux

                aux = jnp.stack([av, chg], axis=1)
                if fused:
                    mesh, aux = jax.lax.cond(
                        need, _adj_fresh, _adj_reuse, mesh, aux
                    )
                elif _host_bool(need):
                    mesh, aux = _adj_fresh(mesh, aux)
                else:
                    mesh, aux = _adj_reuse(mesh, aux)
                av, chg = aux[:, 0], aux[:, 1]
                g3 = _closure(mesh, av | chg)
                mesh, s_23 = swap.swap_23(mesh, edges, emask, active=g3)
                chg = chg | s_23.changed_v
                # the legacy post-swap23 compact is elided: 2-3 swaps
                # append into the live prefix and delete no vertex, so
                # the data is already canonical. Instead of declaring
                # adja stale, the swapped faces (a K-compacted stream)
                # are re-matched in place — adja stays warm across the
                # converged tail, where swap+smooth sweeps dominate
                k_face = max(64, mesh.tcap // 2)

                def _adj_upd(m):
                    return adjacency.update_adjacency(
                        m, s_23.changed_v, K=k_face
                    )

                if fused:
                    mesh = jax.lax.cond(
                        s_23.nswap23 > 0, _adj_upd, lambda m: m, mesh
                    )
                elif _host_bool(s_23.nswap23 > 0):
                    mesh = _adj_upd(mesh)
                adja_ok_out = jnp.bool_(True)
            nswap = s_32.nswap32 + s_23.nswap23
            if fr:
                # staleness of the EXIT tables (built at the latest of
                # entry / post-split / post-collapse): only a 3-2 swap
                # leaves tet holes, making the pre-swap23 compact a real
                # row permutation that invalidates t2e (level 2). With
                # no 3-2 swaps that compact is the identity (split
                # appends packed, collapse was compacted in-sweep), so
                # the 2-3 swap deltas are a stable-numbering merge
                # (level 1) — the general merge_unique_edges absorbs
                # them at the next entry without a full re-sort.
                renum_tail = s_32.nswap32 > 0
                merge_tail = s_23.nswap23 > 0
        else:
            # varying zero (not a literal): under shard_map the cond
            # branches must agree on varying-ness too
            nswap = jnp.zeros_like(s_col.ncollapse)
            adja_ok_out = (
                jnp.asarray(adja_ok, bool)
                & (s_split.nsplit == 0) & (s_col.ncollapse == 0)
                if fr else None
            )
            if fr:
                # noswap: split/collapse deltas were folded into the
                # in-sweep rebuilds, nothing renumbered since — the exit
                # tables are current (varying False, shard_map
                # discipline)
                renum_tail = (s_col.ncollapse * 0) > 0
                merge_tail = (s_col.ncollapse * 0) > 0

        if not nomove:
            g4 = _closure(mesh, av | chg) if fr else None
            mesh, s_sm = smooth.smooth_vertices(
                mesh, edges, emask, nosurf=nosurf, active=g4
            )
            nmoved = s_sm.nmoved
            if fr:
                chg = chg | s_sm.changed_v
        else:
            nmoved = jnp.zeros_like(s_col.ncollapse)
        # int32 regardless of jax_enable_x64: the skip branch of the
        # phase cond emits int32 zeros and lax.cond requires identical
        # branch output types
        dirty_tail = (
            jnp.where(
                renum_tail, 2, jnp.where(merge_tail, 1, 0)
            ).astype(jnp.int32)
            if fr else None
        )
        return (
            mesh, jnp.asarray(s_col.ncollapse, jnp.int32),
            jnp.asarray(nswap, jnp.int32), jnp.asarray(nmoved, jnp.int32),
            n_unique, edges, emask, t2e, chg, adja_ok_out, dirty_tail,
        )

    # tail-skipped sweeps leave adja untouched: it stays valid only if
    # it was valid AND the split phase did nothing
    adja_skip = (
        jnp.asarray(adja_ok, bool) & (s_split.nsplit == 0) if fr else None
    )
    # the skipped tail leaves the POST-SPLIT tables (rebuilt inside the
    # split phase when nsplit > 0, reused otherwise) — current either
    # way, so the next entry reuses them instead of re-sorting (varying
    # int32 zero, shard_map discipline)
    dirty_skip = (
        (s_split.nsplit * 0).astype(jnp.int32)
        if fr else None
    )

    def _tail_skip(m, ed, em, te, nu, c, ak):
        return (m, zero_c, zero_c, zero_c, nu, ed, em, te, c, adja_skip,
                dirty_skip)

    if not phase_skip or noinsert:
        # distributed vmapped sweeps disable the skip on BOTH dispatch
        # paths: a per-shard predicate is batched under vmap, where
        # lax.cond lowers to select (both branches execute — no savings)
        # while the unfused path cannot branch on it at all; running the
        # tail unconditionally keeps the fused and unfused distributed
        # paths result-equivalent across the UNFUSED_TCAP threshold.
        # noinsert: growth is statically False (no splits) — no cond
        (mesh, ncollapse, nswap, nmoved, n_unique, edges, emask, t2e, chg,
         adja_ok, dirty_lvl) = _quality_tail(
            mesh, edges, emask, t2e, n_unique, chg, adja_ok
        )
    elif fused:
        # skip-branch zeros derived from varying data (zeros_like of the
        # split counter), not literals: under shard_map a literal
        # jnp.int32(0) is unvarying over the shard axis while the tail
        # branch outputs vary, and lax.cond rejects the branch-type
        # mismatch
        zero_c = (s_split.nsplit * 0).astype(jnp.int32)
        (mesh, ncollapse, nswap, nmoved, n_unique, edges, emask, t2e, chg,
         adja_ok, dirty_lvl) = jax.lax.cond(
            growth,
            _tail_skip,
            _quality_tail,
            mesh, edges, emask, t2e, n_unique, chg, adja_ok,
        )
    else:
        assert not isinstance(growth, jax.core.Tracer), (
            "_sweep_body(fused=False, phase_skip=True) requires a "
            "concrete growth predicate; under vmap/jit pass "
            "phase_skip=False (tail runs unconditionally) or fused=True"
        )
        zero_c = (s_split.nsplit * 0).astype(jnp.int32)
        # host-only branch: the assert above guarantees `growth` is
        # concrete here (fused=False runs un-traced), so the sync is
        # intentional — this IS the host-side phase skip
        if bool(jax.device_get(growth)):  # parmmg-lint: disable=PML001,PML002
            (mesh, ncollapse, nswap, nmoved, n_unique, edges, emask, t2e,
             chg, adja_ok, dirty_lvl) = _tail_skip(
                mesh, edges, emask, t2e, n_unique, chg, adja_ok
            )
        else:
            (mesh, ncollapse, nswap, nmoved, n_unique, edges, emask, t2e,
             chg, adja_ok, dirty_lvl) = _quality_tail(
                mesh, edges, emask, t2e, n_unique, chg, adja_ok
            )

    n_len_unit, n_len_edges = _len_band_counts(mesh, edges, emask)
    stats = SweepStats(
        nsplit=s_split.nsplit,
        ncollapse=ncollapse,
        nswap=nswap,
        nmoved=nmoved,
        n_unique=n_unique,
        split_capped=s_split.capped,
        n_active=n_active,
        n_len_unit=n_len_unit,
        n_len_edges=n_len_edges,
    )
    if not fr:
        return mesh, stats
    fr_out = Frontier(
        changed=chg, dirty=dirty_lvl,
        tables=(edges, emask, t2e, n_unique), adja_ok=adja_ok,
    )
    return mesh, stats, fr_out


# no donate_argnums: the host-side callers that reach this wrapper
# directly (_polish best-snapshot A/B, the fused/unfused
# path-equivalence test, warm_ops) all REUSE the input mesh after the
# call; the hot loop donates at the remesh_sweeps level instead
# parmmg-lint: disable=PML005
remesh_sweep = partial(
    jax.jit,
    static_argnames=(
        "ecap", "noinsert", "noswap", "nomove", "nosurf", "fused",
        "phase_skip",
    ),
)(_sweep_body)

# above this tet capacity the sweep runs UNFUSED (per-op programs +
# per-sweep host loop): whole-program XLA scheduling at such shapes
# costs hours on the tunnel, while per-op compiles cost seconds and
# the extra dispatch round trips (~115 ms each) are noise against the
# multi-second sweeps of meshes this size. Overridable so a cold-cache
# bench can force the cheap-to-compile per-op path (PARMMG_UNFUSED_TCAP=0).
UNFUSED_TCAP = int(os.environ.get("PARMMG_UNFUSED_TCAP", 600_000))


# history columns of remesh_sweeps: one int32 row per executed sweep
HIST_COLS = (
    "nsplit", "ncollapse", "nswap", "nmoved", "ne", "np", "n_unique",
    "capped", "n_active", "n_len_unit", "n_len_edges",
)


def _hist_row(stats: "SweepStats", ne, npo):
    """One int32 history row in HIST_COLS order — the single definition
    shared by the fused while_loop and the unfused per-sweep branch."""
    return jnp.stack([
        stats.nsplit, stats.ncollapse, stats.nswap, stats.nmoved,
        jnp.asarray(ne, jnp.int32), jnp.asarray(npo, jnp.int32),
        stats.n_unique, stats.split_capped.astype(jnp.int32),
        stats.n_active, stats.n_len_unit, stats.n_len_edges,
    ]).astype(jnp.int32)  # counters can arrive int64 under x64


@partial(
    jax.jit,
    static_argnames=(
        "ecap", "max_sweeps", "noinsert", "noswap", "nomove", "nosurf",
        "grow_trigger", "converge_frac", "frontier",
    ),
    donate_argnums=0,
)
def remesh_sweeps(
    mesh: Mesh,
    n_left,
    ecap: int,
    max_sweeps: int,
    noinsert: bool = False,
    noswap: bool = False,
    nomove: bool = False,
    nosurf: bool = False,
    hausd: float = 0.01,
    converge_frac: float = 0.005,
    grow_trigger: float = 0.85,
    frontier: bool = False,
):
    """Run up to `max_sweeps` fused sweeps in ONE device program.

    The per-sweep host round trip of the naive loop (dispatch + stats
    readback) costs more than a sweep's compute on a remote accelerator;
    here the sweep loop is a `lax.while_loop` that exits early when the
    mesh converged (ops below `converge_frac`) or when host intervention
    is needed: capacity growth crossing `grow_trigger`, a capped split,
    or unique-edge overflow. The host inspects the last history row to
    decide what to do next — the role split matches the reference, where
    `PMMG_parmmglib1` drives Mmg sweeps and only reallocation returns to
    the coordination layer (`src/libparmmg1.c:636-896`).

    `max_sweeps` is STATIC (fixes the history shape — pass the constant
    options value so the compile cache is keyed only on mesh shapes);
    `n_left` is the DYNAMIC remaining sweep budget of this call.

    With `frontier=True` (STATIC) the active-set state rides the
    while_loop carry: sweep k+1's candidate generation, table rebuilds
    and adjacency address only the one-ring closure of what sweep k
    changed. The initial frontier is full/stale, so the first sweep of
    each call is exactly the full-table sweep — re-entries after a
    capacity event restart from a full frontier (capacities changed
    shape anyway).

    Returns (mesh, hist [max_sweeps, len(HIST_COLS)] int32, n_done).
    """

    def body(state):
        m, fr, hist, k, _ = state
        if frontier:
            m, st, fr = remesh_sweep(
                m, ecap,
                noinsert=noinsert, noswap=noswap, nomove=nomove,
                nosurf=nosurf, hausd=hausd, frontier=fr,
            )
        else:
            m, st = remesh_sweep(
                m, ecap,
                noinsert=noinsert, noswap=noswap, nomove=nomove,
                nosurf=nosurf, hausd=hausd,
            )
        ne = m.ntet
        npo = m.npoin
        nops = st.nsplit + st.ncollapse + st.nswap
        overflow = st.n_unique > ecap
        near_cap = (
            (npo > grow_trigger * m.pcap)
            | (ne > grow_trigger * m.tcap)
            | (m.ntria > grow_trigger * m.fcap)
            | (m.nedge > grow_trigger * m.ecap)
        )
        converged = (
            ~st.split_capped
            & ~overflow
            & (nops <= converge_frac * jnp.maximum(ne, 1))
        )
        stop = converged | st.split_capped | overflow | near_cap
        row = _hist_row(st, ne, npo)
        hist = hist.at[k].set(row)
        return m, fr, hist, k + 1, stop

    def cond(state):
        _, _, _, k, stop = state
        return (k < jnp.minimum(max_sweeps, n_left)) & ~stop

    hist0 = jnp.zeros((max_sweeps, len(HIST_COLS)), jnp.int32)
    fr0 = empty_frontier(mesh, ecap) if frontier else None
    mesh, _, hist, n_done, _ = jax.lax.while_loop(
        cond, body, (mesh, fr0, hist0, jnp.int32(0), jnp.bool_(False))
    )
    return mesh, hist, n_done


def resolve_hausd(mesh: Mesh, opts: AdaptOptions) -> float:
    """-hausd value, defaulting to 0.01 x bounding-box diagonal (the
    reference applies Mmg's default hausd=0.01 on the unit-scaled mesh,
    `MMG5_scaleMesh` at `src/libparmmg1.c:727`)."""
    if opts.hausd is not None:
        return float(opts.hausd)
    lo = jnp.min(jnp.where(mesh.vmask[:, None], mesh.vert, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(mesh.vmask[:, None], mesh.vert, -jnp.inf), axis=0)
    diag = float(jax.device_get(jnp.linalg.norm(hi - lo)))
    return 0.01 * (diag if diag > 0 else 1.0)


def prepare_metric(mesh: Mesh, opts: AdaptOptions, ecap: int) -> Mesh:
    """Metric setup: constant size / implied size / bounds / gradation —
    the role of `MMG3D_Set_constantSize` / `MMG3D_doSol` / gradation in the
    reference preprocessing (`src/libparmmg.c:128-205`)."""
    if opts.optim_les and (opts.aniso or mesh.met.shape[1] == 6):
        raise ValueError("-optimLES is incompatible with an aniso metric "
                         "(reference parsar discipline)")
    met = mesh.met
    is_iso = met.shape[1] == 1
    if opts.hsiz is not None:
        met = metric_mod.constant_iso_metric(
            mesh.pcap, opts.hsiz, mesh.dtype
        )
    elif is_iso and opts.aniso and not mesh.met_set:
        # -A with no metric file: tensor metric implied by the mesh
        met = metric_mod.implied_aniso_metric(
            mesh.vert, mesh.tet, mesh.tmask, mesh.pcap
        ).astype(mesh.dtype)
        is_iso = False
    elif is_iso and (opts.optim or opts.optim_les or not mesh.met_set):
        # no prescribed metric: default to the implied sizes (like -optim)
        met = metric_mod.implied_iso_metric(
            mesh.vert, mesh.tet, mesh.tmask, mesh.pcap
        ).astype(mesh.dtype)
    if opts.aniso and met.shape[1] == 1:
        # -A alongside scalar sizes (hsiz / scalar sol): promote to tensors
        met = metric_mod.iso_to_sym6(met)
    met = metric_mod.apply_hbounds(met, opts.hmin, opts.hmax)
    met = _apply_local_hbounds(mesh, met, opts.local_params)
    mesh = mesh.replace(met=met, met_set=True)
    if opts.hgrad is not None or opts.hgradreq is not None:
        # honor unique_edges' overflow contract: retry with a larger cap
        # so gradation sees every edge
        while True:
            edges, emask, _, nu = adjacency.unique_edges(mesh, ecap)
            if int(nu) <= ecap:
                break
            ecap = int(int(nu) * 1.1) + 64
        gradate = (
            metric_mod.gradate_iso
            if met.shape[1] == 1
            else metric_mod.gradate_aniso
        )
        met = mesh.met
        # with -hgradreq active, required sizes are authoritative: the
        # plain gradation must not relax them either (MMG3D_gradsizreq:
        # "required sizes win")
        req = (
            ((mesh.vtag & tags.REQUIRED) != 0) & mesh.vmask
            if opts.hgradreq is not None else None
        )
        if opts.hgrad is not None:
            met = gradate(mesh.vert, met, edges, emask, hgrad=opts.hgrad,
                          fixed=req)
        if opts.hgradreq is not None:
            # second pass: propagation FROM required entities only
            # (a no-op when the mesh has none)
            met = metric_mod.gradate_from_required(
                mesh.vert, met, edges, emask, req, hgrad=opts.hgradreq
            )
        mesh = mesh.replace(met=met)
    return mesh


def _apply_local_hbounds(mesh: Mesh, met, local_params):
    """Per-reference hmin/hmax clamps from parsop local parameters,
    applied to the vertices of the entities carrying each reference
    (`MMG3D_parsop` semantics via `PMMG_parsop`,
    reference `src/libparmmg_tools.c:573`)."""
    for lp in local_params:
        if lp.elt == "vertex":
            sel = (mesh.vref == lp.ref) & mesh.vmask
        else:
            conn, refs, emask2 = (
                (mesh.tria, mesh.trref, mesh.trmask)
                if lp.elt == "triangle"
                else (mesh.tet, mesh.tref, mesh.tmask)
            )
            hit = (refs == lp.ref) & emask2
            sel = jnp.zeros(mesh.pcap, bool)
            sel = sel.at[
                jnp.where(hit[:, None], conn, mesh.pcap).reshape(-1)
            ].max(True, mode="drop")
        clamped = metric_mod.apply_hbounds(met, lp.hmin, lp.hmax)
        met = jnp.where(sel[:, None], clamped, met)
    return met


def local_hausd_table(mesh: Mesh, opts: AdaptOptions, hausd: float):
    """Per-tria-reference hausd lookup (refs inherit through remeshing, so
    a ref-indexed table stays valid as the mesh evolves). Returns the
    scalar unchanged when no local triangle hausd is set."""
    trs = [lp for lp in opts.local_params
           if lp.elt == "triangle" and lp.hausd > 0]
    if not trs:
        return hausd
    rmax = max(
        int(jax.device_get(jnp.max(jnp.where(mesh.trmask, mesh.trref, 0)))),
        max(lp.ref for lp in trs),
    )
    table = np.full(rmax + 1, hausd, np.float64)
    for lp in trs:
        table[lp.ref] = lp.hausd
    return jnp.asarray(table, mesh.dtype)


def estimate_target_ntet(mesh: Mesh) -> int:
    """Predicted element count of the unit mesh for the current metric:
    ne ≈ C * Σ_t vol(t) * sqrt(det M)|_t  (C ≈ 12 empirically for the
    batched operators). This is the capacity-planning analog of the
    reference's remesher target sizing (`PMMG_REMESHER_TARGET_MESH_SIZE`,
    reference `src/parmmg.h:209-212`)."""
    from ..core.mesh import tet_volumes

    vol = jnp.where(mesh.tmask, tet_volumes(mesh), 0.0)
    dens = metric_mod.metric_det(mesh.met)  # 1/h^6 iso
    dens_t = jnp.mean(jnp.sqrt(jnp.maximum(dens[mesh.tet], 0.0)), axis=1)
    est = 12.0 * jnp.sum(vol * dens_t)
    return int(jax.device_get(est)) + 1


def _counts(mesh: Mesh):
    return (
        int(mesh.npoin), int(mesh.ntet), int(mesh.ntria), int(mesh.nedge)
    )


def estimate_mesh_bytes(
    mesh: Mesh, pc: int, tc: int, fc: int, ec: int
) -> int:
    """Device bytes the mesh arrays would occupy at the given capacities
    (current per-slot byte rates scaled — the sizing arithmetic of
    `PMMG_setMeshSize_alloc`, `src/zaldy_pmmg.c:256`)."""
    fs = jnp.dtype(mesh.dtype).itemsize
    per_v = 3 * fs + 4 * 3 + 1 + (
        mesh.met.shape[-1] + mesh.ls.shape[-1] + mesh.disp.shape[-1]
        + mesh.fields.shape[-1]
    ) * fs + 4  # vert+vref/vtag/vglob+vmask+sols
    per_t = 4 * 4 + 4 + 1 + 4 * 4        # tet+tref+tmask+adja
    per_f = 3 * 4 + 4 + 4 + 1
    per_e = 2 * 4 + 4 + 4 + 1
    return pc * per_v + tc * per_t + fc * per_f + ec * per_e


def default_mem_budget_mb() -> Optional[float]:
    """Device-memory budget when `AdaptOptions.mem_budget_mb` is unset —
    the role of the reference's automatic per-process budget (node RAM
    divided by procs, `PMMG_parmesh_SetMemGloMax`, `src/zaldy_pmmg.c:53`
    when -m is absent): 90% of the device's reported `bytes_limit`
    (accelerator backends), else 90% of the host's MemAvailable (CPU
    backend, whose allocator draws from host RAM). None when neither is
    detectable (budget stays unbounded)."""
    dev = jax.devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit"
        )
        if limit:
            return 0.9 * float(limit) / 1e6
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return 0.9 * float(line.split()[1]) / 1e3  # kB -> MB
    except (OSError, ValueError, IndexError):
        pass
    return None


def _check_budget(mesh: Mesh, opts: AdaptOptions, pc, tc, fc, ec):
    if opts.mem_budget_mb is None:
        return
    need = estimate_mesh_bytes(mesh, pc, tc, fc, ec)
    if need > opts.mem_budget_mb * 1e6:
        from ..failsafe import MemoryBudgetError

        # typed (failsafe taxonomy): NOT recoverable by growing — the
        # distributed loop degrades it to LOWFAILURE, the centralized
        # driver raises it through (the budget is a caller contract)
        raise MemoryBudgetError(
            f"mesh memory budget exceeded: growth to caps "
            f"(p={pc}, t={tc}, f={fc}, e={ec}) needs "
            f"{need / 1e6:.1f} MB > budget {opts.mem_budget_mb} MB"
        )


def ensure_capacity(mesh: Mesh, opts: AdaptOptions) -> Mesh:
    """Host-side capacity planning (the reference's memory-budget role,
    `src/zaldy_pmmg.c`): grow arrays when utilization crosses the trigger
    so jitted sweeps keep headroom. Growth changes static shapes and hence
    recompiles — growth is geometric to bound recompilations. A
    configured memory budget caps growth (RuntimeError, degraded to
    LOWFAILURE by the distributed loop)."""
    npo, nte, ntr, ned = _counts(mesh)
    g = opts.grow_factor

    def target(n, cap):
        if n > opts.grow_trigger * cap:
            return max(int(n * g) + 8, int(cap * g))
        return cap

    pc = target(npo, mesh.pcap)
    tc = target(nte, mesh.tcap)
    fc = target(ntr, mesh.fcap)
    ec = target(ned, mesh.ecap)
    if (pc, tc, fc, ec) != (mesh.pcap, mesh.tcap, mesh.fcap, mesh.ecap):
        _check_budget(mesh, opts, pc, tc, fc, ec)
        mesh = mesh.with_capacity(pc, tc, fc, ec)
    return mesh


def _rec_in_band(rec: dict) -> dict:
    """Attach the unit-band edge fraction (`in_band`, the `len/in_band`
    telemetry scalar) to a HIST_COLS host record. Idempotent: a record
    that already carries `in_band` (distributed world sums) is left
    alone; one without the length columns gets nothing."""
    if "in_band" not in rec and "n_len_unit" in rec:
        rec["in_band"] = round(
            rec["n_len_unit"] / max(rec.get("n_len_edges", 0), 1), 6
        )
    return rec


def run_sweep_loop(
    state,
    opts: AdaptOptions,
    emult: List[float],
    history: List[dict],
    it: int,
    ensure_fn,
    tcap_fn,
    sweep_fn,
    governor=None,
):
    """Shared sweep-to-convergence engine for the single-shard and
    stacked (distributed) drivers: capacity growth between sweeps,
    unique-edge-cap overflow handling (with bounded budget extension so
    a late overflow cannot loop forever), history bookkeeping and the
    converge_frac stopping rule.

    `ensure_fn(state) -> state` grows capacities; `tcap_fn(state)` is the
    tet capacity governing the unique-edge cap; `sweep_fn(state, ecap) ->
    (state, rec)` runs one sweep and returns host-int stats with keys
    nsplit/ncollapse/nswap/nmoved/ne/np (aggregated over shards where
    applicable) plus n_unique (max) and capped (any).

    `governor` (a control.RunGovernor, or None) gets a control point
    after every sweep: an `early_stop` decision ends the loop with the
    remaining budget refunded; a `tune_budget` decision caps the
    budget at the frontier drain ETA. It reads only replicated host
    history, so governed distributed shards stay in lockstep.
    """
    tr = obs_trace.get_tracer()
    sweep = 0
    budget = opts.max_sweeps
    while sweep < budget:
        state = ensure_fn(state)
        ecap = int(tcap_fn(state) * emult[0]) + 64
        # device_span: the same named region shows up on the host track
        # of a jax.profiler capture, aligning this dispatch with the
        # XLA device trace
        with tr.device_span("sweep", it=it, sweep=sweep):
            state, rec = sweep_fn(state, ecap)
        _rec_in_band(rec)
        obs_metrics.record_sweep(rec)
        overflow = rec["n_unique"] > ecap
        if overflow:
            # unique_edges dropped overflow edges this sweep (its
            # documented contract): grow the cap and redo coverage
            emult[0] = max(
                emult[0] * 1.5,
                1.1 * rec["n_unique"] / max(tcap_fn(state), 1),
            )
            if budget < opts.max_sweeps + 4:
                budget += 1
        rec.update(iter=it, sweep=sweep)
        history.append(rec)
        if opts.verbose >= 2:
            # flush: these lines are the liveness signal stall watchdogs
            # key off (tools/scale_run.py) — block-buffered pipes would
            # starve the watchdog while sweeps progress
            print(
                f"  it {it} sweep {sweep}: +{rec['nsplit']} split "
                f"-{rec['ncollapse']} collapse {rec['nswap']} swap "
                f"{rec['nmoved']} moved -> ne={rec['ne']}",
                flush=True,
            )
        nops = rec["nsplit"] + rec["ncollapse"] + rec["nswap"]
        if (
            not rec["capped"]
            and not overflow
            and nops <= opts.converge_frac * max(rec["ne"], 1)
        ):
            break
        if governor is not None:
            d = governor.check_sweep(history, it, sweep, budget)
            if d["action"] == "early_stop":
                break
            if d["action"] == "tune_budget":
                budget = d["budget"]
        sweep += 1
    return state


def run_batched_sweep_loop(
    mesh: Mesh,
    opts: AdaptOptions,
    emult: List[float],
    history: List[dict],
    it: int,
    hausd: float,
    governor=None,
) -> Mesh:
    """Single-shard sweep engine on top of `remesh_sweeps`: each device
    call runs as many sweeps as it can; the host only intervenes for
    capacity growth / edge-cap overflow, then re-enters. Replaces one
    dispatch + stats readback PER SWEEP with one per capacity event.

    An armed `governor` needs host control points, so fused device
    calls are chunked to its rolling window; per chunk it may
    early-stop the loop (budget refunded) or cap the budget at the
    frontier drain ETA."""
    tr = obs_trace.get_tracer()
    budget = opts.max_sweeps
    done = 0
    fr = None
    while done < budget:
        mesh = ensure_capacity(mesh, opts)
        ecap = int(mesh.tcap * emult[0]) + 64
        chunk = budget - done if governor is None \
            else min(budget - done, governor.window)
        if mesh.tcap > UNFUSED_TCAP:
            # large mesh: one sweep per call, each op its own program
            # (fused whole-program compile takes hours at these shapes)
            if opts.frontier:
                # the frontier survives between unfused sweeps; a
                # capacity/edge-cap event changes the table shapes, so
                # restart from the full (exact fallback) frontier
                if (
                    fr is None
                    or fr.changed.shape[0] != mesh.pcap
                    or fr.tables[0].shape[0] != ecap
                    or fr.tables[2].shape[0] != mesh.tcap
                ):
                    fr = empty_frontier(mesh, ecap)
                with tr.device_span("sweep_unfused", it=it, sweep=done):
                    mesh, stats, fr = _sweep_body(
                        mesh, ecap, noinsert=opts.noinsert,
                        noswap=opts.noswap, nomove=opts.nomove,
                        nosurf=opts.nosurf, hausd=hausd, fused=False,
                        frontier=fr,
                    )
            else:
                with tr.device_span("sweep_unfused", it=it, sweep=done):
                    mesh, stats = _sweep_body(
                        mesh, ecap, noinsert=opts.noinsert,
                        noswap=opts.noswap, nomove=opts.nomove,
                        nosurf=opts.nosurf, hausd=hausd, fused=False,
                    )
            hist = _hist_row(stats, mesh.ntet, mesh.npoin)[None, :]
            n = 1
        else:
            # XLA cost attribution (obs.costs): captured once per shape
            # signature, only under a costs-armed tracer — the doc the
            # report joins with this device_span's measured mean
            obs_costs.capture(
                "remesh_sweeps", remesh_sweeps,
                (mesh, jnp.int32(chunk), ecap, opts.max_sweeps),
                dict(noinsert=opts.noinsert, noswap=opts.noswap,
                     nomove=opts.nomove, nosurf=opts.nosurf,
                     hausd=hausd, converge_frac=opts.converge_frac,
                     grow_trigger=opts.grow_trigger,
                     frontier=opts.frontier),
            )
            with tr.device_span("remesh_sweeps", it=it, sweep=done):
                mesh, hist, n_done = remesh_sweeps(
                    mesh, jnp.int32(chunk), ecap, opts.max_sweeps,
                    noinsert=opts.noinsert, noswap=opts.noswap,
                    nomove=opts.nomove, nosurf=opts.nosurf, hausd=hausd,
                    converge_frac=opts.converge_frac,
                    grow_trigger=opts.grow_trigger,
                    frontier=opts.frontier,
                )
            n = int(n_done)
            if n == 0:
                break
        import numpy as _np

        rows = _np.asarray(jax.device_get(hist))[:n]
        for i, row in enumerate(rows):
            rec = dict(zip(HIST_COLS, (int(x) for x in row)))
            rec["capped"] = bool(rec["capped"])
            _rec_in_band(rec)
            rec.update(iter=it, sweep=done + i)
            history.append(rec)
            obs_metrics.record_sweep(rec)
            if opts.verbose >= 2:
                act = rec["n_active"] / max(rec["n_unique"], 1)
                print(
                    f"  it {it} sweep {rec['sweep']}: +{rec['nsplit']} "
                    f"split -{rec['ncollapse']} collapse {rec['nswap']} "
                    f"swap {rec['nmoved']} moved -> ne={rec['ne']} "
                    f"(active {act:.0%})",
                    flush=True,
                )
        last = history[-1]
        overflow = last["n_unique"] > ecap
        if overflow:
            emult[0] = max(
                emult[0] * 1.5,
                1.1 * last["n_unique"] / max(mesh.tcap, 1),
            )
            if budget < opts.max_sweeps + 4:
                budget += 1
        done += n
        nops = last["nsplit"] + last["ncollapse"] + last["nswap"]
        if (
            not last["capped"]
            and not overflow
            and nops <= opts.converge_frac * max(last["ne"], 1)
        ):
            break
        if governor is not None and n > 0:
            d = governor.check_sweep(history, it, done - 1, budget)
            if d["action"] == "early_stop":
                break
            if d["action"] == "tune_budget":
                budget = d["budget"]
    return mesh


def _hist_key(h):
    """Lexicographic goodness of a quality histogram: floor first, then
    a thin worst bin, then the average."""
    return (float(h.qmin), -int(h.counts[0]), float(h.qavg))


def _polish(mesh: Mesh, opts: AdaptOptions, emult, hausd: float) -> Mesh:
    """Post-convergence quality-only polish (single-shard path).

    The convergence threshold (`converge_frac`) can stop the sweep loop
    with a few hundred improving collapse/swap/smooth ops still
    available — enough to strand one 0.10-class sliver in a ~94k-tet
    mesh. Runs up to `polish_sweeps` insertion-free sweeps (dispatched
    fused or per-op by the main loop's UNFUSED_TCAP rule) and keeps
    each result only if the histogram improves — the floor never
    regresses. The reference's
    serial kernel ends every wave with the same quality-only ops
    (`MMG5_mmg3d1_delone` final passes, `src/libparmmg1.c:739`)."""
    if opts.polish_sweeps <= 0 or (opts.noswap and opts.nomove):
        return mesh
    from ..ops import quality as quality_mod

    def snap(m):
        # the sweep ops donate their input buffers (compact & friends),
        # so the kept-best state must be a real copy
        return jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, m
        )

    best_h = quality_mod.quality_histogram(mesh)
    best = snap(mesh)
    cur = mesh
    ecap = int(mesh.tcap * emult[0]) + 64
    # dispatch mirrors the main loop's fused/unfused rule; results are
    # path-equivalent (see test_unfused_sweep_path_matches). Below
    # UNFUSED_TCAP the fused single-sweep program costs ONE dispatch
    # round trip (measured: the per-op path's ~25 round trips cost 62 s
    # of a 112 s n=12 bench run in a slow tunnel window) and keeps the
    # per-process compile count low on CPU (this jaxlib's CPU compiler
    # can segfault after many large compiles — conftest note). Above
    # the threshold the per-op path avoids the mega-compile, and the
    # dispatch overhead is noise against multi-second sweeps.
    unfused = mesh.tcap > UNFUSED_TCAP
    for _ in range(opts.polish_sweeps):
        cur, st = (_sweep_body if unfused else remesh_sweep)(
            cur, ecap, noinsert=True, noswap=opts.noswap,
            nomove=opts.nomove, nosurf=opts.nosurf, hausd=hausd,
            fused=not unfused, phase_skip=False,
        )
        h = quality_mod.quality_histogram(cur)
        nops = int(st.ncollapse) + int(st.nswap) + int(st.nmoved)
        if _hist_key(h) > _hist_key(best_h):
            best, best_h = snap(cur), h
        if nops == 0:
            break
    return best


def _grow_for_recovery(mesh: Mesh, opts: AdaptOptions) -> Mesh:
    """Uniform geometric growth for the CapacityError grow-and-retry
    path (the single-shard half of the reference's reallocation ladder):
    budget-checked, so a budget-bound run converts the retry into the
    documented MemoryBudgetError degradation instead of looping."""
    g = max(float(opts.grow_factor), 1.2)
    want = (
        int(mesh.pcap * g) + 8,
        int(mesh.tcap * g) + 8,
        int(mesh.fcap * g) + 8,
        int(mesh.ecap * g) + 64,
    )
    _check_budget(mesh, opts, *want)
    return mesh.with_capacity(*want)


@obs_trace.traced("adapt", driver="centralized")
def adapt(
    mesh: Mesh,
    opts: AdaptOptions | None = None,
    phase_hook=None,
    checkpoint_dir: Optional[str] = None,
):
    """Adapt `mesh` to its metric. Returns (mesh, info dict).

    Observability (`parmmg_tpu.obs`): the run is traced as a span tree
    (run → phase → iteration → sweep) on the process tracer — a
    `Tracer` passed via the extra ``tracer=`` keyword, else the
    ``PMMGTPU_TRACE=dir[,profile]`` environment contract, else the
    no-op NullTracer (the default: zero overhead). Sweep/op counters
    land in the `obs.metrics` registry either way, snapshotted per
    iteration.

    Host loop over `opts.niter` outer iterations of up to `max_sweeps`
    operator sweeps each, with capacity growth between sweeps — the
    single-shard skeleton that `PMMG_parmmglib1` wraps with migration and
    interpolation in the distributed driver.

    `phase_hook(name)`, when given, is called at each phase boundary
    (analysis / metric / input histogram / sweeps / finalize) — the
    attachment point for `lint.contracts.RetraceCounter` per-phase
    compile accounting and for external progress monitors.

    Fail-safe layer (`parmmg_tpu.failsafe`, the `failed_handling` role
    of reference `src/libparmmg1.c:970-1011`): each outer iteration is
    transactional — validated at its boundary per `opts.validate`,
    rolled back to the iteration-start snapshot on failure, retried with
    grown capacities (CapacityError) or cleared caches (RetraceError)
    up to `opts.recovery_attempts` times, and checkpointed atomically
    to `checkpoint_dir` (argument or `opts.checkpoint_dir`). A
    compatible checkpoint found there at entry RESUMES the run;
    `info["status"]` carries the graded outcome and every absorbed
    failure leaves a ``failure`` entry in `info["history"]`. Only
    `MemoryBudgetError` raises through — the memory budget is a caller
    contract, not a transient."""
    from .. import failsafe
    from ..lint import contracts

    opts = opts or AdaptOptions()
    if opts.kernels is not None:
        from ..kernels import registry as kernels_registry

        kernels_registry.set_mode(opts.kernels)
    if checkpoint_dir is not None:
        opts = dataclasses.replace(opts, checkpoint_dir=checkpoint_dir)
    if opts.mem_budget_mb is None:
        # VERDICT coverage row 3: an unset budget derives from the
        # device's reported memory instead of running unbounded (pass
        # float("inf") to opt out); the options object is copied, not
        # mutated
        derived = default_mem_budget_mb()
        if derived is not None:
            opts = dataclasses.replace(opts, mem_budget_mb=derived)
    fs = failsafe.harness(opts, driver="centralized")
    tr = obs_trace.get_tracer()
    # closed-loop run governor (off unless opts.govern/PMMGTPU_GOVERN):
    # lazy import — control is a consumer of the obs layer, not of the
    # drivers, so this cannot cycle
    from .. import control as run_control

    gov = run_control.resolve_governor(opts)
    # unique-edge capacity multiplier: ~1.19 edges/tet asymptotically, but
    # pathological meshes can exceed 1.6x — grown on overflow
    emult = [1.6]

    # sequential phase spans: each _phase() closes the previous phase's
    # span and opens the next, so the whole run partitions into
    # phase:<name> spans under the root (the `printim` boundaries)
    _phase_span = [None]
    _phase_name = [None]

    def _close_phase():
        if _phase_name[0] is not None:
            # HBM watermark at the boundary, attributed to the phase
            # just finished (device memory_stats, host-RSS fallback)
            obs_costs.record_hbm(_phase_name[0])
            _phase_name[0] = None
        if _phase_span[0] is not None:
            _phase_span[0].__exit__(None, None, None)
            _phase_span[0] = None

    def _phase(name):
        # progress marker per setup phase: jit COMPILATION is host-
        # synchronous, so on remote backends (where a single compile can
        # take minutes) these lines are the only liveness signal before
        # the first sweep prints — watchdogs key off them
        if phase_hook is not None:
            phase_hook(name)
        # boundary bookkeeping (watermark + span close) runs even
        # untraced: the hbm/* gauges are always-on metrics
        _close_phase()
        _phase_name[0] = name
        # live endpoint: phase + heartbeat refresh (obs.health run
        # state, served under PMMGTPU_STATUS_PORT)
        obs_health.run_state().update(phase=name, driver="centralized")
        if tr.enabled:
            _phase_span[0] = tr.span(f"phase:{name}")
            _phase_span[0].__enter__()
        if opts.verbose >= 2:
            print(f"  ## phase: {name}", flush=True)

    # live run endpoint (PMMGTPU_STATUS_PORT contract): serves
    # /healthz + /metrics from the first phase through the iteration
    # loop. Lazy import — the service package is a consumer of this
    # module. Closed in the loop's finally; a pre-loop exception leaks
    # only a daemon thread (same contract as the open phase span).
    from ..service import status as service_status

    status_srv = service_status.serve_run_from_env()
    resume = fs.resume()
    if resume is not None:
        _phase("resume")
        mesh = resume.mesh
        old_snapshot = resume.meshes.get("old")
        history: List[dict] = resume.history
        emult = [resume.emult]
        start_it = resume.it + 1
        h0 = failsafe._histo_from_json(resume.meta.get("qual_in"))
        hausd = resume.meta.get("hausd")
        if hausd is None and "hausd" in resume.meta.get("aux_arrays", {}):
            hausd = jnp.asarray(
                resume.meta["aux_arrays"]["hausd"], mesh.dtype
            )
        presize_skipped = resume.meta.get("presize_skipped")
        if opts.verbose >= 1:
            print(
                f"  ## resuming from checkpoint: iteration {resume.it} "
                f"complete, continuing at {start_it}", flush=True,
            )
        _phase("sweeps")
    else:
        mesh = ensure_capacity(mesh, opts)
        _phase("analysis")
        mesh = analysis.analyze(mesh, ang=opts.angle, opnbdy=opts.opnbdy)
        mesh = fs.fire(0, "analysis", mesh)
        _phase("metric")
        mesh = prepare_metric(mesh, opts, int(mesh.tcap * emult[0]) + 64)
        mesh = fs.fire(0, "metric", mesh)
        hausd = local_hausd_table(mesh, opts, resolve_hausd(mesh, opts))
        _phase("input histogram")
        h0 = quality.quality_histogram(mesh)
        _phase("sweeps")

        # pre-size capacities for the predicted unit mesh so sweeps
        # compile once instead of once per growth bucket. Presizing is
        # an optimization: when it would blow the memory budget it is
        # skipped (the sweeps then grow incrementally until the budget
        # genuinely blocks a needed growth, which raises from
        # ensure_capacity).
        est_ne = int(estimate_target_ntet(mesh) * 1.35) + 64
        if est_ne > mesh.tcap:
            want = (
                max(mesh.pcap, est_ne // 5 + 64),
                est_ne,
                max(mesh.fcap, est_ne // 4 + 64),
                max(mesh.ecap, est_ne // 16 + 64),
            )
            try:
                _check_budget(mesh, opts, *want)
            except RuntimeError as exc:
                # intended degradation: grow incrementally under the
                # budget instead — but leave a visible trace so
                # budget-bound runs are diagnosable
                presize_skipped = str(exc)
                if opts.verbose >= 1:
                    print(f"  ## Warning: presizing skipped ({exc}); "
                          "growing incrementally under the memory budget")
            else:
                presize_skipped = None
                mesh = mesh.with_capacity(*want)
        else:
            presize_skipped = None

        # snapshot for the solution-field post-pass (reference:
        # per-iteration `PMMG_interpMetricsAndFields`,
        # `src/libparmmg1.c:829`; here fields are re-pulled once from
        # the input so relocation drift cannot accumulate)
        has_sols = (
            mesh.fields.shape[1] + mesh.ls.shape[1] + mesh.disp.shape[1]
        ) > 0
        # deep copy: the sweep loop donates its input buffers
        old_snapshot = (
            jax.tree_util.tree_map(jnp.copy, mesh) if has_sols else None
        )
        history = []
        start_it = 0

    status = tags.ReturnStatus.SUCCESS
    last_good = fs.snapshot(mesh)
    it = start_it
    attempts = 0
    fs.arm_preemption()
    try:
        while it < opts.niter:
            obs_health.run_state().update(iteration=it)
            if fs.preempt_requested:
                raise failsafe.PreemptionError(
                    f"SIGTERM received before iteration {it} — the "
                    "last committed checkpoint stands; resume to "
                    "continue"
                )

            def _iteration(m):
                m = run_batched_sweep_loop(
                    m, opts, emult, history, it, hausd, governor=gov
                )
                m = fs.fire(it, "remesh", m)
                fs.validate(m, it, phase="remesh")
                return m

            try:
                with tr.span("iteration", it=it):
                    if attempts:
                        # recovery re-entry: its recompiles (grown
                        # shapes / cleared caches) are accounted to a
                        # recovery phase, not charged against the
                        # steady budgets
                        with contracts.budget_exempt("iteration-retry"):
                            mesh = _iteration(mesh)
                    else:
                        mesh = _iteration(mesh)
            except failsafe.MemoryBudgetError:
                raise
            except failsafe.CapacityError as e:
                history.append(dict(iter=it, phase="remesh",
                                    failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e, phase="remesh")
                if last_good is None:
                    raise
                mesh = failsafe.snapshot(last_good)
                if attempts < fs.attempts:
                    attempts += 1
                    try:
                        mesh = _grow_for_recovery(mesh, opts)
                    except failsafe.MemoryBudgetError as e2:
                        history.append(dict(iter=it, failure=str(e2),
                                            error=type(e2).__name__))
                        status = tags.ReturnStatus.LOWFAILURE
                        break
                    continue
                status = tags.ReturnStatus.LOWFAILURE
                break
            except failsafe.RetraceError as e:
                history.append(dict(iter=it, phase="remesh",
                                    failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e, phase="remesh")
                if last_good is None:
                    raise
                mesh = failsafe.snapshot(last_good)
                if attempts < fs.attempts:
                    attempts += 1
                    jax.clear_caches()
                    continue
                status = tags.ReturnStatus.LOWFAILURE
                break
            except (failsafe.NumericalError, FloatingPointError) as e:
                # deterministic numerical poisoning: a re-run
                # reproduces it, so the recovery is rollback + graded
                # degradation, not retry (the reference's
                # failed_handling ladder)
                history.append(dict(iter=it, phase="remesh",
                                    failure=str(e),
                                    error=type(e).__name__))
                failsafe.record_rollback(it, e, phase="remesh")
                if last_good is None:
                    raise
                mesh = failsafe.snapshot(last_good)
                status = tags.ReturnStatus.LOWFAILURE
                break
            attempts = 0
            last_good = fs.snapshot(mesh)
            # per-iteration watermark: the sweeps phase spans the whole
            # loop, so the boundary snapshot alone would miss the peak
            obs_costs.record_hbm("sweeps")
            if tr.enabled:
                obs_metrics.registry().snapshot(it)
            if fs.ckpt is not None and (
                fs.ckpt.due(it) or fs.preempt_requested
                # a maintenance-event notice forces an out-of-cadence
                # checkpoint NOW, before the platform's SIGTERM lands
                or fs.preempt_notice()
            ):
                meshes = {"mesh": mesh}
                if old_snapshot is not None:
                    meshes["old"] = old_snapshot
                meta = dict(
                    qual_in=failsafe._histo_to_json(h0),
                    presize_skipped=presize_skipped,
                )
                aux = {}
                if isinstance(hausd, (int, float)):
                    meta["hausd"] = float(hausd)
                else:
                    aux["hausd"] = hausd
                with tr.span("checkpoint", it=it):
                    fs.save(it, meshes, history=history, emult=emult[0],
                            meta=meta, aux_arrays=aux, force=True)
            if fs.preempt_requested:
                # the grace window of a real preemption notice: the
                # iteration's checkpoint is committed, so exit through
                # the same unabsorbable path the injected kill takes
                raise failsafe.PreemptionError(
                    f"SIGTERM received: iteration {it} checkpointed — "
                    "exiting for preemption; resume to continue"
                )
            mesh = fs.post_iteration(it, mesh, history)
            if gov is not None and gov.check_iteration(
                    history, it, opts.niter):
                it += 1
                break
            it += 1
    finally:
        fs.disarm_preemption()
        # async staging: any staged epoch is serialized, stored and
        # COMMITTED before control leaves the loop — every exit path
        # (completion, typed failure, preemption) ends drained
        fs.finish()
        # the open phase span must not leak past an exception exit —
        # the timeline should end where the run did
        _close_phase()
        if status_srv is not None:
            status_srv.close()

    # once, after the final iteration — polishing between iterations is
    # wasted work (the next iteration's insertion sweeps disturb it)
    _phase("finalize")
    mesh = _polish(mesh, opts, emult, hausd)
    mesh = compact(mesh)
    if old_snapshot is not None:
        from ..ops import interp

        mesh = interp.interp_fields_only(mesh, old_snapshot)
    h1 = quality.quality_histogram(mesh)
    # unit-mesh goal on the FINAL mesh (-prilen role): exact edge tables
    # from the compacted connectivity, one device reduction
    len_out = quality.mesh_length_stats(mesh)
    len_doc = quality.length_stats_doc(len_out)
    verdict = obs_health.assess(
        history, converge_frac=opts.converge_frac,
        max_sweeps=opts.max_sweeps, status=int(status),
    )
    if gov is not None:
        verdict = gov.finalize(verdict)
    obs_health.emit_run_health(
        history, length_doc=len_doc, verdict=verdict,
        driver="centralized", tracer=tr,
    )
    obs_health.run_state().update(
        phase="done", verdict=verdict["verdict"],
        in_band=len_doc["in_band"],
    )
    _close_phase()
    info = dict(history=history, qual_in=h0, qual_out=h1,
                len_out=len_out, health=verdict,
                presize_skipped=presize_skipped,
                mem_budget_mb=opts.mem_budget_mb,
                ckpt_overlap_s=round(fs.ckpt_overlap_s, 3),
                status=status)
    return mesh, info
