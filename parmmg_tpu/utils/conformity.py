"""Mesh conformity / integrity checker.

The single-shard analog of the reference's communicator invariant checker
(`src/chkcomm_pmmg.c`, used as asserts at phase boundaries): verifies that a
mesh is a valid conforming tetrahedrization so remeshing bugs surface
immediately in tests and debug runs instead of corrupting later phases.

Host-side numpy (used in tests/debug paths, not in the hot loop).
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import List

import numpy as np

from ..core.mesh import FACE_VERTS, Mesh


@dataclass
class ConformityReport:
    ok: bool
    errors: List[str] = field(default_factory=list)

    def __str__(self):
        return "conforming" if self.ok else "; ".join(self.errors)


def check_mesh(mesh: Mesh, check_boundary: bool = True) -> ConformityReport:
    d = mesh.to_numpy()
    verts, tets, trias = d["verts"], d["tets"], d["trias"]
    errors: List[str] = []

    if len(tets):
        if tets.min() < 0 or tets.max() >= len(verts):
            errors.append("tet vertex index out of range")

        # positive volumes
        c = verts[tets]
        vol = np.einsum(
            "ti,ti->t",
            np.cross(c[:, 1] - c[:, 0], c[:, 2] - c[:, 0]),
            c[:, 3] - c[:, 0],
        ) / 6.0
        ninv = int((vol <= 0).sum())
        if ninv:
            errors.append(f"{ninv} non-positive tets (minvol {vol.min():.3e})")

        # degenerate tets (repeated vertex)
        srt = np.sort(tets, axis=1)
        if np.any(srt[:, :-1] == srt[:, 1:]):
            errors.append("tet with repeated vertex")

        # duplicate tets
        _, cnt = np.unique(srt, axis=0, return_counts=True)
        if (cnt > 1).any():
            errors.append(f"{int((cnt > 1).sum())} duplicate tets")

        # every face shared by at most 2 tets; count boundary faces
        faces = np.sort(tets[:, FACE_VERTS].reshape(-1, 3), axis=1)
        fkeys, fcnt = np.unique(faces, axis=0, return_counts=True)
        over = fcnt > 2
        if over.any():
            errors.append(f"{int(over.sum())} faces shared by >2 tets")
        bfaces = {tuple(r) for r in fkeys[fcnt == 1]}

        if check_boundary and len(trias):
            tset = Counter(tuple(r) for r in np.sort(trias, axis=1))
            dup_tria = sum(1 for k, v in tset.items() if v > 1)
            if dup_tria:
                errors.append(f"{dup_tria} duplicate trias")
            missing = [t for t in tset if t not in bfaces]
            # trias may also sit on internal material interfaces (faces
            # shared by 2 tets with different refs) — only flag trias
            # matching no tet face at all
            allf = {tuple(r) for r in fkeys}
            ghost = sum(1 for t in missing if t not in allf)
            if ghost:
                errors.append(f"{ghost} trias matching no tet face")
            uncovered = sum(1 for t in bfaces if t not in tset)
            if uncovered:
                errors.append(f"{uncovered} boundary faces without tria")

        # vertices referenced must be valid (to_numpy guarantees range) —
        # check no orphan NaN coords among referenced vertices
        if np.isnan(verts[np.unique(tets)]).any():
            errors.append("NaN coordinates")

    return ConformityReport(ok=not errors, errors=errors)
