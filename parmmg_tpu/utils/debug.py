"""Debug/observability dumps: meshes with quality/partition scalars,
entity statistics, communicator printer.

Role of the reference's debug layer (`src/debug_pmmg.c`:
`PMMG_grp_quality_to_saveMesh:619`, `PMMG_grp_mark_to_saveMesh:583` and
the all-groups variants `:653-706`; `PMMG_printCommunicator`,
`src/libparmmg.h:2554`): write visualizable artifacts (Medit mesh + a
scalar sol over tetrahedra) and human-readable summaries of the
communicator tables.
"""

from __future__ import annotations

import numpy as np

from ..core import tags
from ..core.mesh import Mesh


def _save_tet_sol(path: str, values: np.ndarray) -> None:
    """Medit sol with one scalar per tetrahedron (SolAtTetrahedra)."""
    with open(path, "w") as f:
        f.write("MeshVersionFormatted 2\n\nDimension 3\n\nSolAtTetrahedra\n")
        f.write(f"{len(values)}\n1 1\n")
        np.savetxt(f, np.asarray(values).reshape(-1, 1), fmt="%.9g")
        f.write("\nEnd\n")


def save_quality(mesh: Mesh, basename: str) -> None:
    """Write `basename.mesh` + `basename.sol` with per-tet quality —
    `PMMG_grp_quality_to_saveMesh` (`src/debug_pmmg.c:619`)."""
    from ..io import medit
    from ..ops.quality import tet_quality

    medit.save_mesh(mesh, basename + ".mesh")
    q = np.asarray(tet_quality(mesh))[np.asarray(mesh.tmask)]
    _save_tet_sol(basename + ".sol", q)


def save_partition(mesh: Mesh, part: np.ndarray, basename: str) -> None:
    """Write mesh + per-tet partition color — the `mark`-dump role
    (`PMMG_grp_mark_to_saveMesh`, `src/debug_pmmg.c:583`)."""
    from ..io import medit

    medit.save_mesh(mesh, basename + ".mesh")
    colors = np.asarray(part)[np.asarray(mesh.tmask)]
    _save_tet_sol(basename + ".sol", colors.astype(np.float64))


def save_stacked_quality(stacked: Mesh, basename: str) -> None:
    """Per-shard quality dumps `basename-S<k>.mesh/.sol` (the all-groups
    variant, `src/debug_pmmg.c:653-706`)."""
    from ..parallel.distribute import unstack_mesh

    for s, m in enumerate(unstack_mesh(stacked)):
        save_quality(m, f"{basename}-S{s:02d}")


def mesh_stats(mesh: Mesh) -> str:
    """Entity counts + tag breakdown, one line per class."""
    vm = np.asarray(mesh.vmask)
    vt = np.asarray(mesh.vtag)[vm]
    em = np.asarray(mesh.edmask)
    et = np.asarray(mesh.edtag)[em]
    tm = np.asarray(mesh.trmask)
    tt = np.asarray(mesh.trtag)[tm]

    def n(bits, arr):
        return int(((arr & bits) != 0).sum())

    lines = [
        f"  vertices {vm.sum()}  tets {int(np.asarray(mesh.tmask).sum())}"
        f"  trias {tm.sum()}  edges {em.sum()}",
        f"  vtag: BDY {n(tags.BDY, vt)}  RIDGE {n(tags.RIDGE, vt)}"
        f"  CORNER {n(tags.CORNER, vt)}  REQ {n(tags.REQUIRED, vt)}"
        f"  NOM {n(tags.NOM, vt)}  PARBDY {n(tags.PARBDY, vt)}",
        f"  edtag: RIDGE {n(tags.RIDGE, et)}  REF {n(tags.REF, et)}"
        f"  REQ {n(tags.REQUIRED, et)}  NOM {n(tags.NOM, et)}",
        f"  trtag: REQ {n(tags.REQUIRED, tt)}"
        f"  PARBDY {n(tags.PARBDY, tt)}  NOSURF {n(tags.NOSURF, tt)}",
    ]
    return "\n".join(lines)


def format_comm(comm) -> str:
    """Human-readable node-communicator tables —
    `PMMG_printCommunicator` (`src/libparmmg.h:2554`)."""
    counts = np.asarray(comm.counts)
    l2g = np.asarray(comm.l2g)
    D = counts.shape[0]
    lines = [f"  node communicators over {D} shards "
             f"(table capacity {comm.icap}):"]
    for s in range(D):
        nbrs = [
            f"{r}:{counts[s, r]}" for r in range(D)
            if r != s and counts[s, r] > 0
        ]
        owned = int(np.asarray(comm.owner)[s].sum())
        lines.append(
            f"    shard {s}: owned {owned}, shared with "
            f"{{{', '.join(nbrs) if nbrs else '-'}}}"
        )
    total = int(counts.sum()) // 2
    ci = np.asarray(comm.comm_idx)
    ifc: set = set()
    for s in range(D):
        for r in range(D):
            c = int(counts[s, r])
            if r != s and c:
                ifc.update(l2g[s][ci[s, r, :c]].tolist())
    lines.append(
        f"    total shared pairs {total}, distinct interface gids {len(ifc)}"
    )
    return "\n".join(lines)
