"""Phase timers + verbosity ladder.

The tracing/observability role of the reference's `mytime`/`chrono`/
`printim` phase timers (`src/parmmg.c:91-92`, per-phase at
`src/libparmmg.c:334-425`, per-iteration gated by verbosity at
`src/libparmmg1.c:637-660`) and the `PMMG_VERB_*` ladder
(`src/parmmg.h:128-163`).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List


class Verb:
    """Verbosity levels (PMMG_VERB_* analog)."""

    NO = -1        # silent
    VERSION = 0    # banner only
    QUAL = 1       # quality histograms + phase times
    STEPS = 2      # main phases
    ITWAVES = 3    # per-iteration / per-sweep detail
    DEBUG = 4


class Timers:
    """Named phase timers with nesting, printed like `printim`."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.records: List[tuple] = []   # (depth, name, seconds)
        self.totals: Dict[str, float] = {}
        self._depth = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        # the legacy printim-style timers double as obs spans: a CLI
        # run under PMMGTPU_TRACE gets its top-level phases in the
        # same Perfetto timeline as the driver's internal spans
        from ..obs import trace as obs_trace

        t0 = time.perf_counter()
        self._depth += 1
        try:
            with obs_trace.get_tracer().span(f"timer:{name}"):
                yield
        finally:
            self._depth -= 1
            dt = time.perf_counter() - t0
            self.records.append((self._depth, name, dt))
            self.totals[name] = self.totals.get(name, 0.0) + dt

    def report(self, file=None) -> str:
        """Phase-time summary (the `-endcod` style summary of
        `src/parmmg.c:42`)."""
        lines = ["", "  -- PHASE TIMES (s)"]
        for depth, name, dt in self.records:
            lines.append(f"     {'  ' * depth}{name:<28s} {dt:10.3f}")
        out = "\n".join(lines)
        if self.enabled:
            print(out, file=file)
        return out
