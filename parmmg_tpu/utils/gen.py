"""Structured test-mesh generation.

Stands in for the reference CI's mesh fixtures and its mesh-generator helper
binary (`cmake/testing/pmmg_tests.cmake:250-304` drives
`libexamples/.../genDistributedMesh`): a unit-cube structured tet mesh of
n^3 cells x 6 tets, with boundary triangles and refs, at any size — used by
tests and by `bench.py` to build the 10M-tet class workloads of
BASELINE.json without external fixture downloads.
"""

from __future__ import annotations

import numpy as np

# 6-tet Kuhn decomposition of the unit cube: each tet is a chain of corners
# along a permutation of the axes (vertex 0 = cube corner 0, vertex 3 =
# corner 7) — all positively oriented, face-to-face compatible between cells.
_KUHN_PERMS = [
    (0, 1, 2), (0, 2, 1), (1, 0, 2), (1, 2, 0), (2, 0, 1), (2, 1, 0)
]


def _kuhn_tets() -> np.ndarray:
    tets = []
    for p in _KUHN_PERMS:
        corners = [0]
        acc = np.zeros(3, np.int64)
        for ax in p:
            acc[ax] = 1
            corners.append(acc[0] + 2 * acc[1] + 4 * acc[2])
        tets.append(corners)
    t = np.array(tets, np.int64)
    # fix orientation: ensure positive volume for corner coords
    corner = np.array([[i & 1, (i >> 1) & 1, (i >> 2) & 1] for i in range(8)], float)
    for i, row in enumerate(t):
        c = corner[row]
        v = np.dot(np.cross(c[1] - c[0], c[2] - c[0]), c[3] - c[0])
        if v < 0:
            t[i] = t[i, [0, 1, 3, 2]]
    return t


_KUHN = _kuhn_tets()


def unit_cube(n: int, perturb: float = 0.0, seed: int = 0):
    """Structured unit-cube mesh: (n+1)^3 vertices, 6*n^3 tets.

    Returns dict(verts, tets, trias, trrefs, vrefs) of 0-based numpy arrays.
    `perturb` jitters interior vertices by a fraction of the cell size (to
    de-structure the mesh while keeping it valid for perturb <~ 0.25).
    """
    k = n + 1
    idx = np.arange(k)
    z, y, x = np.meshgrid(idx, idx, idx, indexing="ij")
    verts = np.stack([x, y, z], axis=-1).reshape(-1, 3).astype(np.float64) / n

    def vid(ix, iy, iz):
        return ix + k * (iy + k * iz)

    cz, cy, cx = np.meshgrid(
        np.arange(n), np.arange(n), np.arange(n), indexing="ij"
    )
    cx, cy, cz = cx.reshape(-1), cy.reshape(-1), cz.reshape(-1)
    # 8 cube corner ids per cell, bit i of corner index = axis offset
    corners = np.stack(
        [
            vid(cx + (c & 1), cy + ((c >> 1) & 1), cz + ((c >> 2) & 1))
            for c in range(8)
        ],
        axis=1,
    )  # [ncell, 8]
    tets = corners[:, _KUHN].reshape(-1, 4)

    if perturb:
        rng = np.random.default_rng(seed)
        interior = np.all((verts > 1e-12) & (verts < 1 - 1e-12), axis=1)
        verts[interior] += (
            rng.uniform(-perturb, perturb, (interior.sum(), 3)) / n
        )

    # boundary triangles: the two face-diagonal triangles per boundary cell
    # face, extracted from tet faces lying on the box sides (ref = side id)
    from ..core.mesh import FACE_VERTS

    fv = tets[:, FACE_VERTS].reshape(-1, 3)  # all tet faces
    c = verts[fv]  # [F,3,3]
    trias, trrefs = [], []
    for axis in range(3):
        for side, val, ref in ((0, 0.0, 2 * axis + 1), (1, 1.0, 2 * axis + 2)):
            on = np.all(np.abs(c[..., axis] - val) < 1e-12, axis=1)
            trias.append(fv[on])
            trrefs.append(np.full(on.sum(), ref, np.int64))
    trias = np.concatenate(trias)
    trrefs = np.concatenate(trrefs)
    return dict(
        verts=verts,
        tets=tets.astype(np.int64),
        trias=trias.astype(np.int64),
        trrefs=trrefs,
        vrefs=np.zeros(len(verts), np.int64),
    )


def unit_ball(n: int):
    """Tetrahedral mesh of the unit ball, by the norm-swap map of the
    structured cube: p -> p * (||p||_inf / ||p||_2) on the [-1,1]^3 cube.
    The map is radial (cube surface -> unit sphere), keeps the Kuhn tets
    positively oriented for the sizes used in tests, and gives a smooth
    curved boundary with no true ridges — the fixture class the reference
    CI gets from its sphere meshes (`cmake/testing/pmmg_tests.cmake:71-150`).

    Returns dict(verts, tets, trias, trrefs, vrefs).
    """
    raw = unit_cube(n)
    v = raw["verts"] * 2.0 - 1.0  # [-1,1]^3
    linf = np.max(np.abs(v), axis=1)
    l2 = np.linalg.norm(v, axis=1)
    scale = np.where(l2 > 1e-12, linf / np.maximum(l2, 1e-12), 1.0)
    raw["verts"] = v * scale[:, None]
    raw["trrefs"] = np.ones_like(raw["trrefs"])  # one smooth surface
    return raw


def unit_ball_mesh(n: int, dtype=None, headroom: float = 1.5, **kw):
    """unit_ball as a device Mesh with adjacency built."""
    import jax.numpy as jnp

    from ..core import adjacency
    from ..core.mesh import Mesh

    raw = unit_ball(n)
    m = Mesh.from_numpy(
        raw["verts"],
        raw["tets"],
        trias=raw["trias"],
        trrefs=raw["trrefs"],
        dtype=dtype or jnp.float32,
        headroom=headroom,
        **kw,
    )
    return adjacency.build_adjacency(m)


def unit_cube_mesh(n: int, dtype=None, perturb: float = 0.0, seed: int = 0,
                   headroom: float = 1.5, **kw):
    """unit_cube as a device Mesh with adjacency built."""
    import jax.numpy as jnp

    from ..core import adjacency
    from ..core.mesh import Mesh

    raw = unit_cube(n, perturb=perturb, seed=seed)
    m = Mesh.from_numpy(
        raw["verts"],
        raw["tets"],
        trias=raw["trias"],
        trrefs=raw["trrefs"],
        dtype=dtype or jnp.float32,
        headroom=headroom,
        **kw,
    )
    return adjacency.build_adjacency(m)
