"""Host-side (numpy) row-set utilities shared by the connectivity code."""

from __future__ import annotations

import numpy as np


def row_member(query: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """[Q] bool: does each query row appear among `keys` rows?
    Row-wise set membership via one np.unique over the concatenation —
    used by the migration retag and the level-set orphan-tria filter."""
    query = np.asarray(query)
    keys = np.asarray(keys)
    if len(query) == 0:
        return np.zeros(0, bool)
    if len(keys) == 0:
        return np.zeros(len(query), bool)
    allr = np.concatenate([keys, query])
    _, inv = np.unique(allr, axis=0, return_inverse=True)
    seen = np.zeros(inv.max() + 1, bool)
    seen[inv[: len(keys)]] = True
    return seen[inv[len(keys):]]
