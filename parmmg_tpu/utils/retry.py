"""Clear-caches-and-retry for transient XLA/executable errors.

Promoted from `ops.analysis._jit_retry`: on this jaxlib (0.9.0-era CPU
backend) a stale cached executable occasionally receives a misaligned
argument list on re-invocation ("Executable expected parameter N of
size X but got buffer with incompatible size Y" — sequence-dependent,
observed only on the CPU backend). Clearing the executable cache and
recompiling always recovers, so every host-side jitted entry point
(analysis, distribute/migrate/chkcomm factories) funnels its first
invocation through :func:`jit_retry` to keep long-running CLI/library
sessions alive. The failsafe layer treats the same class as
`failsafe.RetraceError` when it escapes anyway.
"""

from __future__ import annotations

import jax

# substrings identifying the transient executable/buffer mismatch class
TRANSIENT_XLA_MARKERS = ("Executable expected parameter",)


def is_transient_xla_error(exc: BaseException) -> bool:
    """True for the stale-executable error class that a cache clear +
    recompile reliably fixes."""
    msg = str(exc)
    return isinstance(exc, ValueError) and any(
        m in msg for m in TRANSIENT_XLA_MARKERS
    )


def jit_retry(fn, *args, **kwargs):
    """Invoke a jitted fn, retrying once after ``jax.clear_caches()``
    when the transient executable/buffer mismatch fires. Anything else
    propagates unchanged."""
    try:
        return fn(*args, **kwargs)
    except ValueError as e:
        if not is_transient_xla_error(e):
            raise
        jax.clear_caches()
        return fn(*args, **kwargs)
