"""Bounded retry with exponential backoff — shared by the transient-XLA
sites and the checkpoint-store I/O.

Two layers:

- :func:`retry` is the generic engine: bounded attempts, exponential
  backoff with DETERMINISTIC jitter (seeded `random.Random`, so tests
  replay the exact delay sequence), a `retry_on` filter (exception
  types or a predicate) and an `on_retry` hook between attempts. It is
  what the checkpoint stores (`io.ckpt_store`) wrap every put/get/list/
  delete in, and what :func:`jit_retry` is now built on.

- :func:`jit_retry` keeps its historical contract (promoted from
  `ops.analysis._jit_retry`): on this jaxlib (0.9.0-era CPU backend) a
  stale cached executable occasionally receives a misaligned argument
  list on re-invocation ("Executable expected parameter N of size X but
  got buffer with incompatible size Y" — sequence-dependent, observed
  only on the CPU backend). Clearing the executable cache and
  recompiling always recovers, so every host-side jitted entry point
  (analysis, distribute/migrate/chkcomm factories) funnels its first
  invocation through it. The failsafe layer treats the same class as
  `failsafe.RetraceError` when it escapes anyway.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence, Union

import jax

# substrings identifying the transient executable/buffer mismatch class
TRANSIENT_XLA_MARKERS = ("Executable expected parameter",)


def is_transient_xla_error(exc: BaseException) -> bool:
    """True for the stale-executable error class that a cache clear +
    recompile reliably fixes."""
    msg = str(exc)
    return isinstance(exc, ValueError) and any(
        m in msg for m in TRANSIENT_XLA_MARKERS
    )


RetryPredicate = Union[
    Callable[[BaseException], bool],
    Sequence[type],
    type,
]


def _should_retry(exc: BaseException, retry_on: RetryPredicate) -> bool:
    if isinstance(retry_on, type):
        return isinstance(exc, retry_on)
    if callable(retry_on):
        return bool(retry_on(exc))
    return isinstance(exc, tuple(retry_on))


def retry(
    fn: Callable,
    *,
    attempts: int = 3,
    backoff: float = 0.05,
    jitter: float = 0.5,
    retry_on: RetryPredicate = Exception,
    seed: Optional[int] = 0,
    on_retry: Optional[Callable[[BaseException, int], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Invoke `fn()` up to `attempts` times.

    An exception matching `retry_on` (an exception type, a tuple of
    types, or a predicate) triggers a retry after a delay of
    ``backoff * 2**k * (1 + jitter * u)`` seconds, where ``u`` is drawn
    from ``random.Random(seed)`` — a SEEDED stream, so the delay
    schedule (and therefore every test that exercises a retry path) is
    deterministic; pass ``seed=None`` for real entropy. An exception
    carrying a ``retry_after`` attribute (a server's ``Retry-After``
    hint in seconds — `io.ckpt_store.TransientStoreError` from an HTTP
    429/503) FLOORS the next delay at that value: the backoff stays
    seeded-deterministic but never hammers a backend that asked for
    room. The final attempt's exception propagates unchanged.
    `on_retry(exc, attempt)` runs between attempts (the clear-caches
    hook of :func:`jit_retry`); `sleep` is injectable so tests need
    not wait out real delays.
    """
    if attempts < 1:
        raise ValueError(f"attempts={attempts} must be >= 1")
    rng = random.Random(seed)
    for k in range(attempts):
        try:
            return fn()
        except BaseException as e:
            if k == attempts - 1 or not _should_retry(e, retry_on):
                raise
            # observability: every re-attempt is counted (lazy import —
            # this module must stay importable standalone)
            from ..obs import metrics as _obs_metrics

            _obs_metrics.registry().counter("retry/attempts").inc()
            if on_retry is not None:
                on_retry(e, k)
            delay = 0.0
            if backoff > 0:
                delay = backoff * (2 ** k) * (1.0 + jitter * rng.random())
            hint = getattr(e, "retry_after", None)
            if hint:
                delay = max(delay, float(hint))
            if delay > 0:
                sleep(delay)


def jit_retry(fn, *args, **kwargs):
    """Invoke a jitted fn, retrying once after ``jax.clear_caches()``
    when the transient executable/buffer mismatch fires. Anything else
    propagates unchanged."""
    return retry(
        lambda: fn(*args, **kwargs),
        attempts=2,
        backoff=0.0,
        retry_on=is_transient_xla_error,
        on_retry=lambda e, k: jax.clear_caches(),
    )
