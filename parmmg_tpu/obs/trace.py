"""Structured span tracer with device-profile alignment.

The reference ships a real observability surface — the `mytime`/
`printim` phase timers and the `PMMG_VERB_*` ladder (reference
`src/parmmg.c:91-92`, `src/libparmmg1.c:637-660`) — but host wall
clocks cannot attribute time inside jitted/SPMD regions, where all the
cost of this port lives. This module is the host half of a two-sided
story:

- **hierarchical spans** (run → iteration → phase → op) recorded into a
  thread-safe in-process buffer and exported two ways: a Chrome-trace-
  event JSON (``trace.json``, loadable in Perfetto / chrome://tracing)
  and an append-only JSONL event log (``events.jsonl``) written line-
  by-line with an explicit flush, so a process that dies via
  ``os._exit`` (the injected ``kill`` fault, a real preemption) still
  leaves a complete timeline up to the instant of death;
- **device alignment**: spans around jitted dispatch additionally enter
  a `jax.profiler.TraceAnnotation`, so when a device profile is
  captured (``PMMGTPU_TRACE=dir,profile`` arms
  ``jax.profiler.start_trace``) the host spans line up with the XLA
  device trace in the same Perfetto view;
- **zero-cost disabled path**: when ``PMMGTPU_TRACE`` is unset the
  process tracer is a :class:`NullTracer` whose ``span()`` returns one
  shared no-op context manager — no allocation, no clock read, no
  branch beyond the method call (guarded by a measured test in
  tests/test_m16_obs.py).

Env contract::

  PMMGTPU_TRACE=<dir>[,profile][,nocosts]

``<dir>`` receives ``trace.json`` + ``events.jsonl`` +
``metrics_rank<r>.json`` (one per process under `jax.distributed`);
``,profile`` additionally opens a `jax.profiler` capture window for the
tracer's lifetime, writing the device profile under the same directory.
Traced runs also capture per-phase XLA cost docs (`obs.costs`, written
as ``costs_rank<r>.json``); ``,nocosts`` opts out of that capture's
extra AOT lower/compile per entry point.

The process-global tracer is resolved once from the environment
(`get_tracer`); drivers accept an explicit ``tracer=`` argument which
is installed for the duration of the run (`activate`/`restore`), so
module-level emitters (`emit_event`, the failsafe fault hooks, the
checkpoint store) reach the right sink without plumbing a handle
through every call.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "Tracer", "NullTracer", "get_tracer", "install", "activate",
    "restore", "emit_event", "traced", "from_env",
]


class _NullSpan:
    """Shared no-op context manager: the whole disabled-path cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every operation is a no-op returning shared
    singletons. `adapt` runs with exactly this unless PMMGTPU_TRACE is
    set or a Tracer is passed in — the hot path must not pay for
    observability it did not ask for."""

    enabled = False
    dir: Optional[str] = None
    costs = False

    def span(self, name, **args):
        return _NULL_SPAN

    def device_span(self, name, **args):
        return _NULL_SPAN

    def event(self, name, **args):
        return None

    def set_clock_offset(self, offset_us, err_us=0.0, rounds=0):
        return None

    def current_span(self) -> Optional[str]:
        return None

    def flush(self):
        return None


class _Span:
    """One live span: context manager handed out by `Tracer.span`."""

    __slots__ = ("tracer", "name", "args", "t0", "annotation")

    def __init__(self, tracer: "Tracer", name: str, args: dict,
                 annotate: bool = False):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = 0
        # device-profile alignment: the same named region appears on
        # the host track of a jax.profiler capture
        self.annotation = None
        if annotate:
            try:
                from jax.profiler import TraceAnnotation

                self.annotation = TraceAnnotation(name)
            except Exception:
                self.annotation = None

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        self.tracer._push(self.name)
        if self.annotation is not None:
            self.annotation.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.annotation is not None:
            self.annotation.__exit__(exc_type, exc, tb)
        t1 = time.perf_counter_ns()
        if exc_type is not None:
            # a span cut short by an exception is still closed — the
            # timeline must show where the failure path left the run
            self.args = dict(self.args, error=exc_type.__name__)
        self.tracer._pop(self.name, self.t0, t1, self.args)
        return False


class Tracer:
    """Enabled tracer: spans + instant events into `dir`.

    Thread-safe: the event buffer and the JSONL stream are guarded by
    one lock; span nesting is tracked per thread (Chrome trace derives
    nesting from ts/dur containment per ``tid``, the JSONL records an
    explicit ``depth``). Every JSONL line is flushed on write so the
    log survives ``os._exit`` — the chaos timelines depend on it.
    """

    enabled = True

    def __init__(self, dirpath: str, profile: bool = False,
                 rank: Optional[int] = None, costs: bool = True):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        # XLA cost capture (obs.costs): traced runs record per-phase
        # cost docs by default — one extra AOT lower/compile per
        # (entry point, shape signature); `,nocosts` opts out when the
        # trace must stay compile-cheap
        self.costs = bool(costs)
        self.rank = self._rank() if rank is None else int(rank)
        self._t0 = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._events: List[dict] = []      # Chrome trace events
        self._local = threading.local()
        self._jsonl = open(
            os.path.join(dirpath, f"events_rank{self.rank}.jsonl"), "a"
        )
        # clock header: opens a new CLOCK SEGMENT in the (append-mode)
        # JSONL — every ts_us below it is relative to this tracer's
        # _t0. A resumed run appends a fresh header with its restarted
        # origin, which is what lets obs.dist re-align the two runs
        # onto one timebase instead of interleaving them.
        self._clock_offset_us = 0.0
        self._clock_err_us = 0.0
        with self._lock:
            self._write_jsonl(dict(
                type="clock", rank=self.rank, restart=True,
                t0_us=self._t0 // 1000, offset_us=0.0,
            ))
        self._profiling = False
        if profile:
            self._start_profile()

    @staticmethod
    def _rank() -> int:
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0

    def _start_profile(self):
        """Opt-in jax.profiler capture window: the device half of the
        aligned view. Failure to start (no profiler backend, an already
        active session) degrades to host-only tracing, never raises."""
        try:
            import jax

            jax.profiler.start_trace(os.path.join(self.dir, "profile"))
            self._profiling = True
        except Exception:
            self._profiling = False

    # -- span bookkeeping -------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _pop(self, name: str, t0_ns: int, t1_ns: int, args: dict) -> None:
        st = self._stack()
        depth = max(len(st) - 1, 0)
        if st and st[-1] == name:
            st.pop()
        ts = (t0_ns - self._t0) // 1000
        dur = max((t1_ns - t0_ns) // 1000, 0)
        ev = {
            "name": name, "ph": "X", "ts": ts, "dur": dur,
            "pid": self.rank, "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            ev["args"] = args
        rec = dict(type="span", name=name, ts_us=ts, dur_us=dur,
                   depth=depth, rank=self.rank)
        if args:
            rec["args"] = args
        with self._lock:
            self._events.append(ev)
            self._write_jsonl(rec)

    def _write_jsonl(self, rec: dict) -> None:
        # default=str: span args may carry numpy scalars / enums
        self._jsonl.write(json.dumps(rec, default=str) + "\n")
        # explicit flush per line: the timeline must be on disk before
        # an os._exit (injected kill / preemption) can cut the process
        self._jsonl.flush()

    # -- public API --------------------------------------------------------
    def span(self, name: str, **args):
        """Hierarchical span context manager; nesting follows the call
        stack of the current thread."""
        return _Span(self, name, args)

    def device_span(self, name: str, **args):
        """Span that also enters a `jax.profiler.TraceAnnotation`, so a
        captured device profile shows the same named region — use around
        jitted dispatch (the sweep calls)."""
        return _Span(self, name, args, annotate=True)

    def event(self, name: str, **args) -> None:
        """Instant event (fault injected, rollback, checkpoint commit,
        preemption notice...): lands in both exports immediately."""
        ts = (time.perf_counter_ns() - self._t0) // 1000
        ev = {
            "name": name, "ph": "i", "s": "p", "ts": ts,
            "pid": self.rank, "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            ev["args"] = args
        rec = dict(type="event", name=name, ts_us=ts, rank=self.rank)
        if args:
            rec["args"] = args
        with self._lock:
            self._events.append(ev)
            self._write_jsonl(rec)

    def current_span(self) -> Optional[str]:
        st = self._stack()
        return st[-1] if st else None

    def set_clock_offset(self, offset_us: float, err_us: float = 0.0,
                         rounds: int = 0) -> None:
        """Record this rank's estimated clock offset to rank 0's
        monotonic clock (µs, ADD to a local absolute time to land on
        rank 0's timebase). Persisted as a ``type="clock"`` JSONL
        record updating the current clock segment — `obs.dist` applies
        it when merging rank timelines. Estimated by
        `parallel.multihost.sync_tracer_clock` (median of K barrier
        exchanges); 0.0 with no error on a single-process run."""
        self._clock_offset_us = float(offset_us)
        self._clock_err_us = float(err_us)
        with self._lock:
            self._write_jsonl(dict(
                type="clock", rank=self.rank, restart=False,
                t0_us=self._t0 // 1000, offset_us=float(offset_us),
                err_us=float(err_us), rounds=int(rounds),
            ))

    def flush(self) -> None:
        """Write the Chrome trace JSON (idempotent — rewrites the whole
        file from the buffer), flush the JSONL stream, snapshot the
        process metrics registry next to them, and close an armed
        profiler window. Safe to call repeatedly; the drivers call it
        on every exit path."""
        with self._lock:
            events = list(self._events)
            self._jsonl.flush()
        doc = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": self.rank,
                 "tid": 0, "args": {"name": f"rank{self.rank}"}},
            ] + events,
            "displayTimeUnit": "ms",
            # clock segment of THIS tracer (ts values are relative to
            # t0_us on the local monotonic clock): obs.dist uses it to
            # shift every rank's Chrome events onto rank 0's timebase
            # in the merged Perfetto trace
            "clock": {
                "rank": self.rank, "t0_us": self._t0 // 1000,
                "offset_us": self._clock_offset_us,
                "err_us": self._clock_err_us,
            },
        }
        path = os.path.join(self.dir, f"trace_rank{self.rank}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        from . import costs as _costs
        from . import metrics as _metrics

        _metrics.registry().write(self.dir, rank=self.rank)
        # captured XLA cost docs land beside the metrics (no file when
        # nothing was captured — e.g. `,nocosts` runs)
        _costs.collector().write(self.dir, rank=self.rank)
        if self._profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._profiling = False


# ---------------------------------------------------------------------------
# process-global tracer
# ---------------------------------------------------------------------------

_NULL = NullTracer()
_TRACER: Optional[object] = None
_ENV_RESOLVED = False
_STATE_LOCK = threading.Lock()


def from_env() -> object:
    """Tracer per the PMMGTPU_TRACE contract
    (``dir[,profile][,nocosts]``), or the shared NullTracer when
    unset."""
    spec = os.environ.get("PMMGTPU_TRACE")
    if not spec:
        return _NULL
    parts = [p.strip() for p in spec.split(",")]
    dirpath, flags = parts[0], parts[1:]
    return Tracer(dirpath, profile="profile" in flags,
                  costs="nocosts" not in flags)


def get_tracer() -> object:
    """The process tracer: an installed one, else the PMMGTPU_TRACE
    environment resolution (performed once), else the NullTracer."""
    global _TRACER, _ENV_RESOLVED
    tr = _TRACER
    if tr is not None:
        return tr
    with _STATE_LOCK:
        if _TRACER is None and not _ENV_RESOLVED:
            _ENV_RESOLVED = True
            env_tr = from_env()
            if env_tr.enabled:
                _TRACER = env_tr
        return _TRACER if _TRACER is not None else _NULL


def install(tracer: Optional[object]):
    """Install `tracer` as the process tracer; returns the previous
    one (None if the environment resolution was still pending)."""
    global _TRACER
    with _STATE_LOCK:
        prev = _TRACER
        _TRACER = tracer
    return prev


def activate(tracer: Optional[object]):
    """Driver entry: install an explicitly passed tracer (None keeps
    the current/global one). Returns (tracer-in-effect, restore-token).
    """
    if tracer is None:
        return get_tracer(), False
    prev = install(tracer)
    return tracer, (True, prev)


def restore(token) -> None:
    if token:
        install(token[1])


def emit_event(name: str, **args) -> None:
    """Instant event on the process tracer (no-op when disabled) — the
    hook used by call sites that hold no tracer handle (fault plan,
    checkpoint store, preemption notices)."""
    get_tracer().event(name, **args)


def traced(span_name: str, **span_args):
    """Decorator for driver entry points: accepts an extra ``tracer=``
    keyword, installs it for the call, wraps the body in a root span
    and flushes the exports on the way out (every exit path — normal,
    typed failure, preemption — leaves trace.json/events.jsonl
    consistent; the hard-kill path is covered by the per-line JSONL
    flush)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, tracer=None, **kwargs):
            tr, token = activate(tracer)
            try:
                with tr.span(span_name, **span_args):
                    return fn(*args, **kwargs)
            finally:
                try:
                    tr.flush()
                finally:
                    restore(token)
        return wrapper
    return deco
