"""Typed per-rank metrics registry with a cross-rank merge.

The numeric half of the observability layer (`obs.trace` is the time
half): counters, gauges and histograms keyed by slash-namespaced names,
recorded from the drivers, the migration/comm layer, the checkpoint
store and the retrace counter. All recording is plain locked host
arithmetic — a few dict operations per sweep — so the registry is
ALWAYS on; only the exports are gated on tracing.

Naming convention (what `tools/obs_report.py` renders):

  ops/<op>_accepted      accepted operations per operator (split /
                         collapse / swap / smooth), exactly the
                         driver-reported history counts
  ops/candidates         active edges offered to the operators
  sweeps                 executed operator sweeps
  sweep_active_fraction  gauge: last sweep's active fraction
  len/in_band            gauge: last sweep's unit-band edge fraction
                         (metric length in [1/sqrt2, sqrt2] — the
                         obs.health unit-mesh telemetry)
  migrate/cells_moved    tets exchanged between shards
  migrate/payload_bytes  estimated migration payload
  migrate/wall_s         histogram: wall seconds per balancing block
                         (coloring + contiguity repair + exchange or
                         re-cut)
  migrate/rebalances     balance decisions that moved cells or re-cut
                         (each also emits a `rebalance` trace event)
  comm/barriers          coordination barriers entered
  comm/collectives       cross-process gathers dispatched
  comm/wait_s            gauge: seconds this rank spent blocked
                         inside coordination collectives
  work/imbalance         gauge: live-tets max/mean across shards
  work/live_tets/shard<i>  gauge: live tets on shard i
  compile_s/<name>       gauge: AOT lower+compile seconds per jitted
                         entry point (obs.costs capture)
  ckpt/ops, ckpt/retries, ckpt/commits, ckpt/put_bytes, ckpt/get_bytes
  ckpt/op_seconds        histogram of store-operation latency
  retry/attempts         generic utils.retry re-attempts
  recompiles/<phase>     jit cache misses per RetraceCounter phase
  failsafe/faults_injected, failsafe/rollbacks

Per-rank story: each process owns one registry and writes
``metrics_rank<r>.json`` into the trace directory
(`MetricsRegistry.write`, called by `Tracer.flush`); `merge_rank_docs`
folds any number of rank documents into ONE world document (counters
and histograms summed, gauges kept per rank with a world max), so a
single JSON describes the whole world post-mortem.

Iteration series: `snapshot(it)` appends a row of the current counter
values — the per-iteration trajectory the run report plots, and how a
chaos run's failure timeline lines up with the metric state at each
boundary.
"""

from __future__ import annotations

import glob
import json
import os
import threading
from typing import Dict, List, Optional

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "record_sweep", "merge_rank_docs", "read_rank_docs", "merge_dir",
]


class Counter:
    """Monotone int counter."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += int(n)


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Streaming summary (count / sum / min / max) — enough for the
    latency tables the report renders, with no bin-edge contract to
    version across ranks."""

    __slots__ = ("count", "sum", "min", "max")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def to_doc(self) -> dict:
        if self.count == 0:
            return dict(count=0, sum=0.0)
        return dict(count=self.count, sum=self.sum, min=self.min,
                    max=self.max, mean=self.sum / self.count)


class MetricsRegistry:
    """One process's metric state. Thread-safe (one lock — recording
    is a handful of ops, contention is negligible next to a device
    dispatch)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self.series: List[dict] = []

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            return h

    def snapshot(self, it: int) -> None:
        """Append the per-iteration row: current counter values plus
        gauges, stamped with the iteration id."""
        with self._lock:
            row = {"it": int(it)}
            row.update({k: c.value for k, c in self._counters.items()})
            row.update({k: g.value for k, g in self._gauges.items()})
            self.series.append(row)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self.series.clear()

    def to_doc(self, rank: int = 0) -> dict:
        with self._lock:
            return dict(
                rank=int(rank),
                counters={k: c.value for k, c in
                          sorted(self._counters.items())},
                gauges={k: g.value for k, g in
                        sorted(self._gauges.items())},
                histograms={k: h.to_doc() for k, h in
                            sorted(self._histograms.items())},
                series=list(self.series),
            )

    def write(self, dirpath: str, rank: int = 0) -> str:
        """Atomic per-rank metrics file in the trace directory."""
        path = os.path.join(dirpath, f"metrics_rank{rank}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_doc(rank), f)
        os.replace(tmp, path)
        return path


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every instrumentation site records
    into (tests reset it around a measured run)."""
    return _REGISTRY


def record_sweep(rec: dict) -> None:
    """Fold one sweep history record (the drivers' HIST_COLS dict) into
    the registry — the single definition shared by the single-shard,
    vmapped and SPMD sweep engines, so `ops/*_accepted` is EXACTLY the
    sum of the driver-reported history.

    Distributed records additionally carry `active_fraction` (world
    candidates over world unique edges — the single-shard ratio falls
    back to n_active/n_unique) and `shard_active` (per-shard fractions,
    recorded as `sweep_active_fraction/shard<i>` gauges so
    `tools/obs_report.py` can render a per-shard column and a drained
    shard is visible even while its neighbors still churn)."""
    reg = _REGISTRY
    reg.counter("sweeps").inc()
    reg.counter("ops/split_accepted").inc(rec.get("nsplit", 0))
    reg.counter("ops/collapse_accepted").inc(rec.get("ncollapse", 0))
    reg.counter("ops/swap_accepted").inc(rec.get("nswap", 0))
    reg.counter("ops/smooth_moved").inc(rec.get("nmoved", 0))
    n_act = rec.get("n_active", rec.get("n_unique", 0))
    reg.counter("ops/candidates").inc(n_act)
    if "active_fraction" in rec:
        reg.gauge("sweep_active_fraction").set(rec["active_fraction"])
    else:
        nu = rec.get("n_unique", 0)
        if nu:
            reg.gauge("sweep_active_fraction").set(n_act / nu)
    for i, frac in enumerate(rec.get("shard_active", ())):
        reg.gauge(f"sweep_active_fraction/shard{i}").set(frac)
    # load-imbalance accounting (round 11): live tets per shard and
    # the max/mean imbalance factor the distributed records carry —
    # the gauges `obs_report --dist` and the BENCH envelope read.
    # NOT the only writer: the distributed driver republishes both at
    # every iteration boundary (`_publish_shard_gauges`), so the gauges
    # track post-migration state even when an iteration records no
    # sweep (drained skip) or balances after its last sweep
    if "imbalance" in rec:
        reg.gauge("work/imbalance").set(rec["imbalance"])
    for i, ne in enumerate(rec.get("shard_ne", ())):
        reg.gauge(f"work/live_tets/shard{i}").set(ne)
    # unit-mesh telemetry (round 12): the in-band edge fraction rides
    # every sweep record — gauge for the live endpoint / reports, and
    # the obs.health run state is refreshed in the same stroke
    if "in_band" in rec:
        reg.gauge("len/in_band").set(rec["in_band"])
    from . import health as health_mod  # deferred: health is pure host

    health_mod.note_sweep(rec)


# ---------------------------------------------------------------------------
# cross-rank merge
# ---------------------------------------------------------------------------


def merge_rank_docs(docs: List[dict]) -> dict:
    """Fold per-rank metric documents into one world document:
    counters and histograms are summed, gauges keep a per-rank map plus
    the world max, iteration series are kept per rank. Input order is
    irrelevant; ranks are read from each document."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, dict] = {}
    hists: Dict[str, dict] = {}
    series: Dict[str, list] = {}
    ranks = []
    for doc in docs:
        r = int(doc.get("rank", 0))
        ranks.append(r)
        for k, v in doc.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in doc.get("gauges", {}).items():
            g = gauges.setdefault(k, {"per_rank": {}, "max": None})
            g["per_rank"][str(r)] = v
            g["max"] = v if g["max"] is None else max(g["max"], v)
        for k, h in doc.get("histograms", {}).items():
            m = hists.setdefault(
                k, dict(count=0, sum=0.0, min=float("inf"),
                        max=float("-inf")),
            )
            m["count"] += int(h.get("count", 0))
            m["sum"] += float(h.get("sum", 0.0))
            if h.get("count"):
                m["min"] = min(m["min"], float(h["min"]))
                m["max"] = max(m["max"], float(h["max"]))
        series[str(r)] = doc.get("series", [])
    for m in hists.values():
        if m["count"]:
            m["mean"] = m["sum"] / m["count"]
        else:
            m.pop("min"), m.pop("max")
    return dict(
        world=len(docs),
        ranks=sorted(ranks),
        counters=dict(sorted(counters.items())),
        gauges=dict(sorted(gauges.items())),
        histograms=dict(sorted(hists.items())),
        series=series,
    )


def read_rank_docs(dirpath: str) -> List[dict]:
    docs = []
    for path in sorted(glob.glob(
            os.path.join(dirpath, "metrics_rank*.json"))):
        with open(path) as f:
            docs.append(json.load(f))
    return docs


def merge_dir(dirpath: str) -> Optional[dict]:
    """One world metrics document from every per-rank file in a trace
    directory (None when the directory holds none)."""
    docs = read_rank_docs(dirpath)
    return merge_rank_docs(docs) if docs else None
