"""Run-report renderer over a trace directory.

Consumes what `obs.trace.Tracer.flush` leaves behind —
``trace_rank<r>.json`` (Chrome trace events), ``events_rank<r>.jsonl``
(the durable line log) and ``metrics_rank<r>.json`` (per-rank registry
snapshots) — and renders the post-mortem a run operator wants first:

- phase breakdown: wall time per span name (count / total / mean),
  top-level phases separated from nested op spans;
- cost attribution (``costs_rank*.json``, captured by `obs.costs` on
  traced runs): per-phase flops / bytes accessed / arithmetic
  intensity and the roofline verdict (bound=compute|memory, achieved
  fraction of the binding roof) using the MEASURED device-span mean as
  the per-call time;
- memory: HBM watermark gauges (run-wide peak bytes, live bytes per
  phase boundary, device vs host-RSS source);
- operator acceptance: candidates offered vs accepted per operator;
- comm / migration / checkpoint volume (collectives, cells moved,
  payload and checkpoint bytes, store retry and latency summary);
- retrace table: jit cache misses per RetraceCounter phase;
- failure timeline: every instant event (faults injected, rollbacks,
  checkpoint commits, preemption notices) in time order;
- **chaos post-mortem** (:func:`chaos_summary` / :func:`render_chaos`,
  CLI ``tools/obs_report.py --chaos``): per-rank fault → detection →
  recovery event chains assembled from the FILE-ORDERED JSONL
  timelines (a resumed run appends to its rank file with a restarted
  clock, so happened-order is line order, not timestamp order),
  merged with whatever ``metrics_rank*.json`` snapshots survived —
  a hard-killed rank leaves only its JSONL, which is part of the
  story the report tells.

`tools/obs_report.py` is the CLI wrapper; tests and the obs smoke
stage call :func:`render` directly.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from . import costs as costs_mod
from . import metrics as metrics_mod

__all__ = [
    "load_trace_events", "load_timeline", "summarize", "render",
    "rank_timelines", "chaos_summary", "render_chaos",
    "serve_summary", "render_serve", "dist_summary", "render_dist",
    "health_summary", "render_health",
    "control_summary", "render_control",
]


def dist_summary(dirpath: str) -> dict:
    """Cross-rank view (clock-aligned timelines, collective skew,
    imbalance, critical path) — delegates to :mod:`..obs.dist`."""
    from . import dist as dist_mod
    return dist_mod.dist_summary(dirpath)


def render_dist(dirpath: str) -> str:
    """Render the cross-rank ``--dist`` report (see obs.dist)."""
    from . import dist as dist_mod
    dist_mod.write_merged_trace(dirpath)
    return dist_mod.render_dist(dirpath)


def health_summary(dirpath: str) -> dict:
    """Run-health view (unit-length histogram, termination verdict,
    drain curve, sweep history) — delegates to :mod:`..obs.health`."""
    from . import health as health_mod
    return health_mod.health_summary(dirpath)


def render_health(dirpath: str) -> str:
    """Render the run-health ``--health`` report (see obs.health)."""
    from . import health as health_mod
    return health_mod.render_health(dirpath)


def load_trace_events(dirpath: str) -> List[dict]:
    """All Chrome trace events of every rank's trace_rank*.json."""
    events: List[dict] = []
    for path in sorted(glob.glob(
            os.path.join(dirpath, "trace_rank*.json"))):
        with open(path) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
    return events


def load_timeline(dirpath: str) -> List[dict]:
    """All span/event JSONL records of every rank, time-ordered.
    Tolerates a truncated final line (a process killed mid-write).
    ``type="clock"`` headers (the obs.dist alignment contract) are
    bookkeeping, not timeline content — skipped here; `obs.dist`
    reads them via :func:`parmmg_tpu.obs.dist.rank_segments`."""
    recs: List[dict] = []
    for path in sorted(glob.glob(
            os.path.join(dirpath, "events_rank*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("type") != "clock":
                    recs.append(rec)
    recs.sort(key=lambda r: (r.get("ts_us", 0), r.get("rank", 0)))
    return recs


def rank_timelines(dirpath: str) -> Dict[int, List[dict]]:
    """Per-rank JSONL records in FILE order (NOT ts-sorted: a resumed
    run appends to the same rank file with a restarted clock, so the
    happened-order of a fault → death → resume → recovery chain is the
    line order, and a global ts sort would interleave the two runs).
    Tolerates truncated final lines (a process killed mid-write)."""
    out: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(
            os.path.join(dirpath, "events_rank*.jsonl"))):
        stem = os.path.basename(path)[len("events_rank"):-len(".jsonl")]
        try:
            rank = int(stem)
        except ValueError:
            continue
        recs: List[dict] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # clock headers are segment bookkeeping for obs.dist,
                # not chain content
                if rec.get("type") != "clock":
                    recs.append(rec)
        out[rank] = recs
    return out


# the event vocabulary of a chaos chain, by role: what was INJECTED,
# how the failure was DETECTED, what the run did to RECOVER, and how
# the WORLD itself changed shape (elastic reformations). The render
# tags each chain line with its role so a post-mortem reads as
# fault -> detection -> recovery without knowing the emitter sites.
CHAOS_FAULT_EVENTS = ("fault_injected",)
CHAOS_DETECT_EVENTS = ("sigterm_received", "peer_lost",
                       "preempt_notice", "preempt_notice_cleared",
                       "capacity_restored", "collective_divergence")
CHAOS_RECOVER_EVENTS = ("rollback", "checkpoint_commit", "resume")
CHAOS_WORLD_EVENTS = ("world_reform", "world_shrink", "world_grow")
_CHAOS_ROLES = (
    [(n, "fault") for n in CHAOS_FAULT_EVENTS]
    + [(n, "detect") for n in CHAOS_DETECT_EVENTS]
    + [(n, "recover") for n in CHAOS_RECOVER_EVENTS]
    + [(n, "world") for n in CHAOS_WORLD_EVENTS]
)


def chaos_summary(dirpath: str) -> dict:
    """Structured per-rank post-mortem of a chaos run's trace
    directory: every rank's fault → detection → recovery event chain
    (file-ordered, so it spans a kill and the subsequent resume) plus
    the merged per-rank metrics that survived (hard-killed ranks leave
    only their JSONL — their metrics snapshot never flushed, which is
    itself part of the story)."""
    roles = dict(_CHAOS_ROLES)
    ranks: Dict[int, dict] = {}
    for rank, recs in rank_timelines(dirpath).items():
        chain = []
        for r in recs:
            if r.get("type") != "event" or r.get("name") not in roles:
                continue
            chain.append(dict(
                name=r.get("name"), role=roles[r.get("name")],
                ts_us=r.get("ts_us", 0), args=r.get("args", {}),
            ))
        faults = [
            dict(kind=c["args"].get("kind"),
                 where=c["args"].get("where")
                 or (f"store op {c['args'].get('store_op')} "
                     f"({c['args'].get('op')})"
                     if "store_op" in c["args"] else None))
            for c in chain if c["name"] == "fault_injected"
        ]
        ranks[rank] = dict(
            events=len(recs),
            faults=faults,
            detections=[c for c in chain if c["role"] == "detect"],
            recoveries=[c for c in chain if c["role"] == "recover"],
            chain=chain,
        )
    # world-size timeline: elastic transitions deduped by (epoch, name)
    # — every rank of a reformed epoch emits its own copy — ordered by
    # epoch (the reformation counter is the only clock that survives
    # process restarts)
    seen = set()
    world_timeline: List[dict] = []
    for rank in sorted(ranks):
        for c in ranks[rank]["chain"]:
            if c["name"] not in ("world_shrink", "world_grow"):
                continue
            args = c["args"]
            key = (args.get("epoch"), c["name"])
            if key in seen:
                continue
            seen.add(key)
            world_timeline.append(dict(
                name=c["name"], epoch=args.get("epoch"),
                old=args.get("old"), new=args.get("new"),
                downtime_s=args.get("downtime_s"),
                reason=args.get("reason", ""),
            ))
    world_timeline.sort(key=lambda t: (t["epoch"] is None,
                                       t["epoch"] or 0))
    metrics = metrics_mod.merge_dir(dirpath)
    counters = (metrics or {}).get("counters", {})
    return dict(
        dir=dirpath,
        ranks=ranks,
        world=len(ranks),
        world_timeline=world_timeline,
        metrics_ranks=(metrics or {}).get("world", 0),
        counters=dict(
            faults_injected=counters.get("failsafe/faults_injected", 0),
            rollbacks=counters.get("failsafe/rollbacks", 0),
            ckpt_commits=counters.get("ckpt/commits", 0),
            ckpt_retries=counters.get("ckpt/retries", 0),
            resumes=counters.get("ckpt/resumes", 0),
            barriers=counters.get("comm/barriers", 0),
            world_shrinks=counters.get("elastic/world_shrink", 0),
            world_grows=counters.get("elastic/world_grow", 0),
        ),
    )


def render_chaos(dirpath: str) -> str:
    """Human-readable chaos post-mortem: one section per rank naming
    the injected fault(s) and the detection/recovery event chain."""
    s = chaos_summary(dirpath)
    lines = [f"== chaos post-mortem: {s['dir']} =="]
    if not s["ranks"]:
        lines.append("   (no per-rank timelines found)")
    for rank in sorted(s["ranks"]):
        r = s["ranks"][rank]
        lines.append("")
        lines.append(f"-- rank {rank} ({r['events']} timeline "
                     "records) --")
        if r["faults"]:
            for f in r["faults"]:
                at = f" @ {f['where']}" if f.get("where") else ""
                lines.append(f"   injected: {f['kind']}{at}")
        else:
            lines.append("   injected: (none on this rank)")
        if not r["chain"]:
            lines.append("   chain: (no chaos events)")
            continue
        lines.append("   chain:")
        for c in r["chain"]:
            args = c["args"]
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(args.items())
            )
            lines.append(
                f"     [{c['ts_us'] / 1e6:9.3f}s] "
                f"{c['role']:<8s} {c['name']}"
                + (f"  {extra}" if extra else "")
            )
    if s["world_timeline"]:
        lines.append("")
        lines.append("-- world-size timeline (elastic reformations) --")
        for t in s["world_timeline"]:
            arrow = f"{t['old']} -> {t['new']}"
            dt = t.get("downtime_s")
            dt_s = (f", downtime {dt:.3f}s"
                    if isinstance(dt, (int, float)) and dt >= 0 else "")
            why = f"  ({t['reason']})" if t.get("reason") else ""
            lines.append(
                f"   epoch {t['epoch']}: {t['name']}  world "
                f"{arrow}{dt_s}{why}"
            )
    c = s["counters"]
    lines.append("")
    lines.append(
        f"-- world: {s['world']} rank timeline(s), "
        f"{s['metrics_ranks']} metrics snapshot(s) --"
    )
    lines.append(
        f"   faults injected {c['faults_injected']}  rollbacks "
        f"{c['rollbacks']}  ckpt commits {c['ckpt_commits']}  "
        f"ckpt retries {c['ckpt_retries']}  resumes {c['resumes']}  "
        f"barriers {c['barriers']}"
    )
    if c["world_shrinks"] or c["world_grows"]:
        lines.append(
            f"   world shrinks {c['world_shrinks']}  world grows "
            f"{c['world_grows']}"
        )
    lines.append("")
    return "\n".join(lines)


# the event vocabulary of the adaptation service (`parmmg_tpu.service`):
# per-job lifecycle events carrying job_id/tenant labels, plus the
# server-level warmup record. File order is happened-order — a server
# restart appends to the same rank file, so one job's
# submitted -> running -> requeued -> running -> terminal chain spans
# a SIGKILL without any clock reconciliation.
SERVE_JOB_EVENTS = ("job_submitted", "job_running", "job_requeued",
                    "job_terminal")
SERVE_REFUSAL_EVENT = "job_refused"
SERVE_WARMUP_EVENT = "serve_warmup"


def serve_summary(dirpath: str) -> dict:
    """Structured per-job post-mortem of a serving run's trace
    directory: every job's lifecycle chain (file-ordered, spanning
    server restarts), transient refusals by code, per-tenant job
    counts, warmups, and the merged serve/* counters."""
    jobs: Dict[str, dict] = {}
    refusals: Dict[str, int] = {}
    warmups: List[dict] = []
    order: List[str] = []
    timelines = rank_timelines(dirpath)
    for rank in sorted(timelines):
        for r in timelines[rank]:
            if r.get("type") != "event":
                continue
            name, args = r.get("name"), r.get("args", {})
            if name == SERVE_WARMUP_EVENT:
                warmups.append(args)
                continue
            if name == SERVE_REFUSAL_EVENT:
                code = args.get("code", "?")
                refusals[code] = refusals.get(code, 0) + 1
                continue
            if name not in SERVE_JOB_EVENTS:
                continue
            jid = args.get("job_id")
            if jid is None:
                continue
            if jid not in jobs:
                order.append(jid)
                jobs[jid] = dict(job_id=jid,
                                 tenant=args.get("tenant", "?"),
                                 size_class=None, state=None,
                                 code=None, attempts=0, chain=[])
            j = jobs[jid]
            if args.get("size_class"):
                j["size_class"] = args["size_class"]
            if name == "job_running":
                j["attempts"] = max(j["attempts"],
                                    int(args.get("attempt", 1)))
            if name == "job_terminal":
                j["state"] = args.get("state")
                j["code"] = args.get("code")
                j["wall_s"] = args.get("wall_s")
                j["digest"] = args.get("digest")
                # round 12 quality column: the server stamps the final
                # unit-band edge fraction and the obs.health verdict
                # on the terminal event
                j["in_band"] = args.get("in_band")
                j["verdict"] = args.get("verdict")
            j["chain"].append(dict(name=name, ts_us=r.get("ts_us", 0),
                                   args=args))
    tenants: Dict[str, dict] = {}
    by_state: Dict[str, int] = {}
    for jid in order:
        j = jobs[jid]
        t = tenants.setdefault(j["tenant"],
                               dict(jobs=0, done=0, failed=0))
        t["jobs"] += 1
        state = j["state"] or "in-flight"
        by_state[state] = by_state.get(state, 0) + 1
        if state == "done":
            t["done"] += 1
        elif state in ("failed", "deadline", "rejected", "cancelled"):
            t["failed"] += 1
    counters = ((metrics_mod.merge_dir(dirpath) or {})
                .get("counters", {}))
    return dict(
        dir=dirpath,
        jobs=[jobs[jid] for jid in order],
        by_state=by_state,
        tenants=tenants,
        refusals=refusals,
        warmups=warmups,
        counters={k: v for k, v in sorted(counters.items())
                  if k.startswith("serve/")},
    )


def render_serve(dirpath: str) -> str:
    """Human-readable serving post-mortem: one timeline per job
    (submitted → running → … → typed terminal, spanning restarts),
    then the per-tenant and refusal rollups."""
    s = serve_summary(dirpath)
    lines = [f"== serve post-mortem: {s['dir']} =="]
    if s["warmups"]:
        for w in s["warmups"]:
            lines.append(
                f"   warmup: classes {','.join(w.get('classes', []))} "
                f"in {w.get('seconds')}s"
            )
    if not s["jobs"]:
        lines.append("   (no job events found)")
    for j in s["jobs"]:
        lines.append("")
        state = j["state"] or "in-flight"
        code = f" ({j['code']})" if j.get("code") else ""
        att = (f", {j['attempts']} attempt(s)"
               if j["attempts"] > 1 else "")
        qual = ""
        if j.get("in_band") is not None:
            qual = f"  in-band {float(j['in_band']):.3f}"
        if j.get("verdict"):
            qual += f"  verdict {j['verdict']}"
        lines.append(
            f"-- job {j['job_id']} [tenant {j['tenant']}, class "
            f"{j['size_class'] or '?'}] -> {state}{code}{att}{qual} --"
        )
        for c in j["chain"]:
            args = c["args"]
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(args.items())
                if k not in ("job_id", "tenant")
            )
            lines.append(
                f"     [{c['ts_us'] / 1e6:9.3f}s] {c['name']}"
                + (f"  {extra}" if extra else "")
            )
    lines.append("")
    lines.append("-- rollup --")
    states = "  ".join(f"{k} {v}"
                       for k, v in sorted(s["by_state"].items()))
    lines.append(f"   jobs {len(s['jobs'])}: {states or '(none)'}")
    for tenant, t in sorted(s["tenants"].items()):
        lines.append(
            f"   tenant {tenant}: {t['jobs']} job(s), {t['done']} "
            f"done, {t['failed']} failed/typed"
        )
    if s["refusals"]:
        ref = "  ".join(f"{k} {v}"
                        for k, v in sorted(s["refusals"].items()))
        lines.append(f"   transient refusals: {ref}")
    if s["counters"]:
        cnt = "  ".join(
            f"{k[len('serve/'):]} {v}"
            for k, v in s["counters"].items()
        )
        lines.append(f"   counters: {cnt}")
    lines.append("")
    return "\n".join(lines)


def control_summary(dirpath: str) -> dict:
    """Structured view of the run governor's decision log
    (``control_decision`` tracer events, `parmmg_tpu.control`). The
    governor reads only replicated host history, so its decisions are
    identical on every rank — the summary takes the lowest rank that
    carries any, rather than multiplying replicas into the rollup.
    Folds in the final ``health:verdict`` control block (refund total,
    window, early-stop flag) and the merged ``control/*`` counters."""
    timelines = rank_timelines(dirpath)
    decisions: List[dict] = []
    source_rank: Optional[int] = None
    for rank in sorted(timelines):
        recs = [r for r in timelines[rank]
                if r.get("type") == "event"
                and r.get("name") == "control_decision"]
        if recs:
            source_rank = rank
            decisions = [dict(ts_us=r.get("ts_us", 0),
                              **r.get("args", {})) for r in recs]
            break
    by_action: Dict[str, int] = {}
    refunded = 0
    for d in decisions:
        act = d.get("action", "?")
        by_action[act] = by_action.get(act, 0) + 1
        if act == "early_stop":
            refunded += int(d.get("refunded", 0) or 0)
        elif act == "tune_budget":
            refunded += int(d.get("was", 0) or 0) - int(
                d.get("budget", 0) or 0)
    verdict: Optional[dict] = None
    for rank in sorted(timelines):
        for r in reversed(timelines[rank]):
            if (r.get("type") == "event"
                    and r.get("name") == "health:verdict"):
                verdict = r.get("args", {})
                break
        if verdict is not None:
            break
    counters = ((metrics_mod.merge_dir(dirpath) or {})
                .get("counters", {}))
    return dict(
        dir=dirpath,
        rank=source_rank,
        decisions=decisions,
        by_action=by_action,
        refunded_sweeps=refunded,
        verdict=verdict,
        counters={k: v for k, v in sorted(counters.items())
                  if k.startswith("control/")},
    )


def render_control(dirpath: str) -> str:
    """Human-readable governor log: one line per control decision in
    happened order (hold / early_stop / tune_budget / shorten_niter
    with its reason), then the refund and final-verdict rollup."""
    s = control_summary(dirpath)
    lines = [f"== control decisions: {s['dir']} =="]
    if not s["decisions"]:
        lines.append("   (no control_decision events found — "
                     "governor unarmed or run predates it)")
    for d in s["decisions"]:
        bits = []
        if d.get("it") is not None:
            bits.append(f"iter {d['it']}")
        if d.get("sweep") is not None:
            bits.append(f"sweep {d['sweep']}")
        if d.get("action") == "early_stop":
            bits.append(f"verdict {d.get('verdict')}")
            bits.append(f"refunded {d.get('refunded')} sweep(s)")
        elif d.get("action") == "tune_budget":
            bits.append(f"budget {d.get('was')} -> {d.get('budget')}")
        elif d.get("action") == "hold":
            bits.append(f"verdict {d.get('verdict')} held")
        lines.append(
            f"   [{d.get('ts_us', 0) / 1e6:9.3f}s] "
            f"{d.get('action', '?'):13s} {', '.join(bits)}"
        )
        if d.get("reason"):
            lines.append(f"{'':16s}{d['reason']}")
    lines.append("")
    lines.append("-- rollup --")
    acts = "  ".join(f"{k} {v}"
                     for k, v in sorted(s["by_action"].items()))
    lines.append(
        f"   decisions {len(s['decisions'])}: {acts or '(none)'}")
    lines.append(f"   refunded sweeps: {s['refunded_sweeps']}")
    v = s.get("verdict")
    if v is not None:
        ctl = v.get("control") or {}
        lines.append(
            f"   final verdict: {v.get('verdict')} "
            f"(early_stop={bool(v.get('early_stop'))}, "
            f"window={ctl.get('window')})"
        )
        if v.get("reason"):
            lines.append(f"     {v['reason']}")
    if s["counters"]:
        cnt = "  ".join(
            f"{k[len('control/'):]} {v}"
            for k, v in s["counters"].items()
        )
        lines.append(f"   counters: {cnt}")
    lines.append("")
    return "\n".join(lines)


def _span_table(events: List[dict]) -> Dict[str, dict]:
    table: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        row = table.setdefault(
            ev["name"], dict(count=0, total_us=0, max_us=0)
        )
        row["count"] += 1
        dur = int(ev.get("dur", 0))
        row["total_us"] += dur
        row["max_us"] = max(row["max_us"], dur)
        # first completed sample per span name: on a cold-cache trace
        # it contains the jit compile, so the roofline attribution
        # (obs.costs.attribute) drops it from the per-call mean
        if row["count"] == 1:
            row["first_us"] = dur
    return table


def summarize(dirpath: str) -> dict:
    """Structured summary document (what `render` formats, and what
    the obs smoke stage asserts on)."""
    events = load_trace_events(dirpath)
    timeline = load_timeline(dirpath)
    metrics = metrics_mod.merge_dir(dirpath)
    spans = _span_table(events)
    counters = (metrics or {}).get("counters", {})
    gauges = (metrics or {}).get("gauges", {})

    def _gval(v):
        # merged docs store gauges as {"per_rank": ..., "max": x}
        return v.get("max", 0.0) if isinstance(v, dict) else v

    shard_active = {
        int(k[len("sweep_active_fraction/shard"):]): _gval(v)
        for k, v in gauges.items()
        if k.startswith("sweep_active_fraction/shard")
    }
    # cost attribution: captured XLA cost docs x measured span means
    cost_docs = costs_mod.load_cost_docs(dirpath)
    cost_rows = costs_mod.attribute(cost_docs, spans)
    # HBM watermarks from the hbm/* gauges (obs.costs.record_hbm)
    phase_bytes = {
        k[len("hbm/phase_bytes/"):]: _gval(v)
        for k, v in gauges.items() if k.startswith("hbm/phase_bytes/")
    }
    memory = dict(
        peak_bytes=_gval(gauges.get("hbm/peak_bytes", 0.0)),
        bytes_in_use=_gval(gauges.get("hbm/bytes_in_use", 0.0)),
        limit_bytes=_gval(gauges.get("hbm/limit_bytes", 0.0)),
        source=("device"
                if _gval(gauges.get("hbm/device_source", 0.0))
                else "host_rss"),
        phase_bytes=phase_bytes,
    )
    ops = {}
    for op in ("split", "collapse", "swap"):
        ops[op] = counters.get(f"ops/{op}_accepted", 0)
    accepted = sum(ops.values())
    candidates = counters.get("ops/candidates", 0)
    return dict(
        dir=dirpath,
        n_spans=sum(r["count"] for r in spans.values()),
        spans=spans,
        costs=cost_rows,
        memory=memory,
        ops=dict(
            accepted=accepted,
            accepted_per_op=ops,
            moved=counters.get("ops/smooth_moved", 0),
            candidates=candidates,
            acceptance=(accepted / candidates) if candidates else None,
            sweeps=counters.get("sweeps", 0),
            active_fraction=_gval(
                gauges.get("sweep_active_fraction", 0.0)
            ),
            shard_active=shard_active,
        ),
        comm=dict(
            barriers=counters.get("comm/barriers", 0),
            collectives=counters.get("comm/collectives", 0),
            cells_moved=counters.get("migrate/cells_moved", 0),
            payload_bytes=counters.get("migrate/payload_bytes", 0),
        ),
        ckpt=dict(
            ops=counters.get("ckpt/ops", 0),
            retries=counters.get("ckpt/retries", 0),
            commits=counters.get("ckpt/commits", 0),
            put_bytes=counters.get("ckpt/put_bytes", 0),
            get_bytes=counters.get("ckpt/get_bytes", 0),
            op_seconds=(metrics or {}).get("histograms", {}).get(
                "ckpt/op_seconds"
            ),
        ),
        retries=counters.get("retry/attempts", 0),
        recompiles={
            k[len("recompiles/"):]: v for k, v in counters.items()
            if k.startswith("recompiles/")
        },
        failsafe=dict(
            faults_injected=counters.get("failsafe/faults_injected", 0),
            rollbacks=counters.get("failsafe/rollbacks", 0),
        ),
        events=[r for r in timeline if r.get("type") == "event"],
        metrics=metrics,
    )


def _fmt_us(us: int) -> str:
    if us >= 1_000_000:
        return f"{us / 1e6:9.3f} s"
    return f"{us / 1e3:9.3f} ms"


def _fmt_qty(x: float) -> str:
    """Engineering-style quantity (flops, bytes): 1.23G, 45.6M, 789."""
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                           (1e3, "k")):
        if abs(x) >= thresh:
            return f"{x / thresh:.2f}{suffix}"
    return f"{x:.0f}"


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"


def render(dirpath: str) -> str:
    """Human-readable run report (the `printim`-style summary of the
    traced run)."""
    s = summarize(dirpath)
    lines = [f"== obs report: {s['dir']} =="]

    lines.append("")
    lines.append("-- phase breakdown (span wall time) --")
    if not s["spans"]:
        lines.append("   (no spans recorded)")
    for name, row in sorted(
        s["spans"].items(), key=lambda kv: -kv[1]["total_us"]
    ):
        lines.append(
            f"   {name:<28s} x{row['count']:<5d} "
            f"total {_fmt_us(row['total_us'])}  "
            f"max {_fmt_us(row['max_us'])}"
        )

    lines.append("")
    lines.append("-- cost attribution (roofline per jitted phase) --")
    if not s["costs"]:
        lines.append("   (no cost docs captured — trace with costs "
                     "armed: PMMGTPU_TRACE=<dir> without ,nocosts)")
    else:
        lines.append(
            f"   {'phase':<20s} {'calls':>5s} {'mean/call':>11s} "
            f"{'flops':>9s} {'bytes':>9s} {'F/B':>7s} {'%roof':>7s} "
            f"bound"
        )
        for r in s["costs"]:
            if r.get("error"):
                lines.append(f"   {r['name']:<20s}  (capture failed: "
                             f"{r['error']})")
                continue
            pct = (f"{r['pct_of_roof']:.2%}" if "pct_of_roof" in r
                   else "-")
            mean = _fmt_us(int(r["mean_s"] * 1e6)) if r["calls"] else "  (no span)"
            lines.append(
                f"   {r['name']:<20s} x{r['calls']:<4d} {mean:>11s} "
                f"{_fmt_qty(r['flops']):>9s} "
                f"{_fmt_qty(r['bytes_accessed']):>9s} "
                f"{r['intensity']:>7.2f} {pct:>7s} {r['bound']}"
            )

    m = s["memory"]
    lines.append("")
    lines.append("-- memory (HBM watermarks) --")
    if m["peak_bytes"]:
        limit = (f" of {_fmt_bytes(int(m['limit_bytes']))}"
                 if m["limit_bytes"] else "")
        lines.append(
            f"   HBM peak bytes {_fmt_bytes(int(m['peak_bytes']))}"
            f"{limit}  in use {_fmt_bytes(int(m['bytes_in_use']))}  "
            f"(source: {m['source']})"
        )
        if m["phase_bytes"]:
            cells = "  ".join(
                f"{ph} {_fmt_bytes(int(v))}"
                for ph, v in sorted(m["phase_bytes"].items())
            )
            lines.append(f"   per phase boundary: {cells}")
    else:
        lines.append("   (no watermark gauges recorded)")

    o = s["ops"]
    lines.append("")
    lines.append("-- operators --")
    per_op = "  ".join(
        f"{k} {v}" for k, v in o["accepted_per_op"].items()
    )
    lines.append(
        f"   sweeps {o['sweeps']}  candidates {o['candidates']}  "
        f"accepted {o['accepted']} ({per_op})  moved {o['moved']}"
    )
    if o["acceptance"] is not None:
        lines.append(f"   acceptance rate {o['acceptance']:.3%}")
    if o.get("shard_active"):
        # per-shard active fraction at the last recorded sweep: a
        # drained shard reads 0.000 while its neighbors still churn
        cells = "  ".join(
            f"s{i} {o['shard_active'][i]:.3f}"
            for i in sorted(o["shard_active"])
        )
        lines.append(
            f"   active fraction {o.get('active_fraction', 0.0):.3f}  "
            f"per shard: {cells}"
        )

    c = s["comm"]
    lines.append("")
    lines.append("-- comm / migration --")
    lines.append(
        f"   barriers {c['barriers']}  collectives {c['collectives']}  "
        f"cells moved {c['cells_moved']}  "
        f"payload {_fmt_bytes(c['payload_bytes'])}"
    )

    k = s["ckpt"]
    lines.append("")
    lines.append("-- checkpoint I/O --")
    lines.append(
        f"   ops {k['ops']}  retries {k['retries']}  "
        f"commits {k['commits']}  put {_fmt_bytes(k['put_bytes'])}  "
        f"get {_fmt_bytes(k['get_bytes'])}"
    )
    if k["op_seconds"] and k["op_seconds"].get("count"):
        h = k["op_seconds"]
        lines.append(
            f"   op latency mean {h['mean'] * 1e3:.1f} ms  "
            f"max {h['max'] * 1e3:.1f} ms over {h['count']} ops"
        )

    lines.append("")
    lines.append("-- recompiles (jit cache misses per phase) --")
    if s["recompiles"]:
        for phase, n in sorted(s["recompiles"].items()):
            lines.append(f"   {phase:<28s} {n}")
    else:
        lines.append("   (none recorded)")

    lines.append("")
    lines.append("-- failure timeline --")
    fs = s["failsafe"]
    lines.append(
        f"   faults injected {fs['faults_injected']}  "
        f"rollbacks {fs['rollbacks']}"
    )
    for ev in s["events"]:
        extra = ev.get("args", {})
        lines.append(
            f"   [{ev.get('ts_us', 0) / 1e6:9.3f}s r{ev.get('rank', 0)}] "
            f"{ev.get('name')} {extra if extra else ''}".rstrip()
        )
    if not s["events"]:
        lines.append("   (no events)")
    lines.append("")
    return "\n".join(lines)
