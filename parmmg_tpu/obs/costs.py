"""Device cost attribution: XLA cost/roofline analytics + HBM watermarks.

The PR-6 tracer answers *where the wall time goes* (span table per
phase); this module answers *why* — which jitted phases are memory-
vs compute-bound, and how far from the hardware roof they run. That is
the selection instrument for the Pallas arc: hand-fusing a
gather→compute→scatter chain only pays when the chain is memory-bound
and far from the bandwidth roof, and the "after" kernel must prove its
win against the numbers recorded here.

Three surfaces, all host-side (nothing here is jit-reachable — the
timing that feeds the roofline comes from the tracer's device spans,
per lint rule PML010, never from host clocks inside traced code):

- **cost capture** (`capture`/`cost_doc`): per-phase XLA cost
  attribution via the AOT path — ``jit(...).lower(args).compile()``
  then ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/code bytes). Dispatch
  sites call :func:`capture` with the same jit wrapper + args they are
  about to execute; capture is once per (name, shape signature), armed
  only while an enabled tracer with costs on is installed (the
  ``PMMGTPU_TRACE=dir[,profile][,nocosts]`` contract — costs ride the
  tracing opt-in, ``nocosts`` drops them), and degrades to a recorded
  error rather than ever failing the run. Lowering never executes, so
  donated input buffers are untouched.
- **roofline verdicts** (`roofline`/`attribute`): arithmetic intensity
  (flops / bytes accessed) against a small per-platform peak table
  (:data:`PEAKS` — order-of-magnitude anchors, overridable via
  ``PMMGTPU_PEAKS=<flops>,<bytes_per_s>``), classifying each phase
  ``bound=compute|memory`` and, when a measured device-span time is
  available, the achieved fraction of the binding roof.
- **HBM watermarks** (`memory_watermark`/`record_hbm`): peak-bytes
  snapshots at phase boundaries from ``device.memory_stats()``
  (accelerator backends), falling back to the process peak RSS
  (``/proc/self/status`` VmHWM) on the CPU backend whose allocator
  draws from host RAM — recorded as ``hbm/*`` gauges in the metrics
  registry and rendered by `obs.report` as the memory table.

The shared timing helpers at the bottom (`timed_mean`,
`chained_seconds`) are the single steady-state measurement definition
the profiling tools (`tools/profile_ops.py`, `tools/profile_chain.py`,
`tools/phase_times.py`) consolidate onto.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "PEAKS", "peaks_for", "roofline", "cost_doc", "capture",
    "collector", "CostCollector", "load_cost_docs", "attribute",
    "memory_watermark", "record_hbm", "timed_mean", "chained_seconds",
]


# ---------------------------------------------------------------------------
# per-platform peak table
# ---------------------------------------------------------------------------

# Order-of-magnitude roofline anchors per PJRT platform name. These are
# NOT calibrated device specs — they exist to classify phases as
# memory- vs compute-bound (the ridge point) and to express achieved
# throughput as a fraction of a plausible roof, which is stable under
# 2x anchor error because the interesting phases sit 10-1000x below
# the roof. Override with PMMGTPU_PEAKS="<flops_per_s>,<bytes_per_s>"
# when a calibrated pair for the actual part is known.
PEAKS: Dict[str, dict] = {
    "tpu": dict(flops=2.0e14, bw=1.0e12,
                label="TPU-class (~200 Tflop/s, HBM ~1 TB/s)"),
    "gpu": dict(flops=5.0e13, bw=1.5e12,
                label="datacenter-GPU-class (~50 Tflop/s f32, ~1.5 TB/s)"),
    "cuda": dict(flops=5.0e13, bw=1.5e12,
                 label="datacenter-GPU-class (~50 Tflop/s f32, ~1.5 TB/s)"),
    "cpu": dict(flops=1.0e11, bw=2.0e10,
                label="host-CPU-class (~100 Gflop/s, ~20 GB/s)"),
}


def peaks_for(platform: str) -> dict:
    """Peak (flops/s, bytes/s) anchors for `platform`, honoring the
    PMMGTPU_PEAKS override; unknown platforms fall back to the CPU
    anchors (the most conservative roof)."""
    spec = os.environ.get("PMMGTPU_PEAKS")
    if spec:
        try:
            fl, bw = (float(x) for x in spec.split(",")[:2])
            return dict(flops=fl, bw=bw, label="PMMGTPU_PEAKS override")
        except ValueError:
            pass
    return PEAKS.get(platform, PEAKS["cpu"])


def roofline(flops: float, bytes_accessed: float, seconds: float,
             platform: str) -> dict:
    """Roofline verdict for one program: arithmetic intensity vs the
    platform ridge point, bound classification, and — when a measured
    per-call `seconds` is available (a tracer device-span mean, never a
    host clock under trace) — achieved rates as fractions of the
    binding roof."""
    p = peaks_for(platform)
    ridge = p["flops"] / p["bw"]
    out = dict(ridge=ridge, peak_flops=p["flops"], peak_bw=p["bw"])
    if flops <= 0 and bytes_accessed <= 0:
        out.update(intensity=0.0, bound="n/a")
        return out
    intensity = flops / max(bytes_accessed, 1.0)
    bound = "compute" if intensity >= ridge else "memory"
    out.update(intensity=intensity, bound=bound)
    if seconds and seconds > 0:
        achieved_flops = flops / seconds
        achieved_bw = bytes_accessed / seconds
        out.update(
            seconds=seconds,
            achieved_flops=achieved_flops,
            achieved_bw=achieved_bw,
            pct_peak_flops=achieved_flops / p["flops"],
            pct_peak_bw=achieved_bw / p["bw"],
            # fraction of the roof that binds this phase — the headroom
            # number a kernel rewrite is judged against
            pct_of_roof=(achieved_flops / p["flops"] if bound == "compute"
                         else achieved_bw / p["bw"]),
        )
    return out


# ---------------------------------------------------------------------------
# XLA cost capture (AOT lower/compile analysis)
# ---------------------------------------------------------------------------


def cost_doc(fn, args=(), kwargs=None) -> dict:
    """Static XLA cost/memory analysis of one jitted callable at the
    given args: ``fn.lower(*args).compile()`` then ``cost_analysis()``
    + ``memory_analysis()``. Lowering traces but never executes — safe
    to call with buffers the subsequent real dispatch will donate.

    The wall spent in lower+compile is recorded as ``compile_s`` in
    the doc (round 11): the AOT capture pays the SAME compile the
    first real dispatch would, so this measures each entry point's
    compile cost without folding it into any measured span mean — the
    data that closes the PR-8 "cold-cache folds compile into the span
    mean" caveat."""
    import jax

    t0 = time.perf_counter()
    lowered = fn.lower(*args, **(kwargs or {}))
    comp = lowered.compile()
    compile_s = time.perf_counter() - t0
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    doc = dict(
        flops=float(ca.get("flops", 0.0)),
        bytes_accessed=float(ca.get("bytes accessed", 0.0)),
        transcendentals=float(ca.get("transcendentals", 0.0)),
        platform=jax.devices()[0].platform,
        compile_s=round(compile_s, 6),
    )
    ma = comp.memory_analysis()
    if ma is not None:
        doc.update(
            argument_bytes=int(ma.argument_size_in_bytes),
            output_bytes=int(ma.output_size_in_bytes),
            temp_bytes=int(ma.temp_size_in_bytes),
            alias_bytes=int(ma.alias_size_in_bytes),
            code_bytes=int(ma.generated_code_size_in_bytes),
        )
    return doc


def _signature(args, kwargs) -> str:
    """Shape signature of a call: leaf (shape, dtype) pairs for arrays,
    repr for everything else — the once-per-shape capture key."""
    import jax

    parts: List[str] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{tuple(shape)}:{dtype}")
        else:
            parts.append(repr(leaf))
    return "|".join(parts)


class CostCollector:
    """Process-global store of captured cost docs, one per span name.

    A name captured at several shape signatures (capacity growth
    re-buckets the arrays) keeps the doc with the largest
    ``bytes_accessed`` — the dominant steady-state shape — and counts
    the variants, so the report stays one row per phase."""

    def __init__(self):
        self._lock = threading.Lock()
        self._docs: Dict[str, dict] = {}
        self._seen: set = set()
        self._compile_s = 0.0

    def capture(self, name: str, fn, args=(), kwargs=None) -> None:
        key = (name, _signature(args, kwargs))
        with self._lock:
            if key in self._seen:
                return
            self._seen.add(key)
        try:
            doc = cost_doc(fn, args, kwargs)
        except Exception as exc:  # never fail the run for analytics
            doc = dict(flops=0.0, bytes_accessed=0.0,
                       error=f"{type(exc).__name__}: {exc}")
        if "compile_s" in doc:
            # per-entry-point compile gauge (summed over shape
            # variants): lets the bench/report exclude compile from
            # wall comparisons instead of warning about it
            from . import metrics as _metrics

            g = _metrics.registry().gauge(f"compile_s/{name}")
            g.set(round(g.value + doc["compile_s"], 6))
        with self._lock:
            self._compile_s += doc.get("compile_s", 0.0)
            prev = self._docs.get(name)
            if prev is None:
                doc["variants"] = 1
                self._docs[name] = doc
            else:
                doc["variants"] = prev.get("variants", 1) + 1
                if doc.get("bytes_accessed", 0.0) >= prev.get(
                        "bytes_accessed", 0.0):
                    self._docs[name] = doc
                else:
                    prev["variants"] = doc["variants"]

    def docs(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._docs.items()}

    def total_compile_s(self) -> float:
        """Total AOT lower+compile seconds across every capture this
        process paid (all names, ALL shape variants — not just the
        dominant doc per name): the run-level ``compile_s`` BENCH
        field."""
        with self._lock:
            return round(self._compile_s, 6)

    def reset(self) -> None:
        with self._lock:
            self._docs.clear()
            self._seen.clear()
            self._compile_s = 0.0

    def write(self, dirpath: str, rank: int = 0) -> Optional[str]:
        """Atomic per-rank cost-doc file in the trace directory (None
        when nothing was captured)."""
        docs = self.docs()
        if not docs:
            return None
        path = os.path.join(dirpath, f"costs_rank{rank}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(docs, f)
        os.replace(tmp, path)
        return path


_COLLECTOR = CostCollector()


def collector() -> CostCollector:
    return _COLLECTOR


def capture(name: str, fn, args=(), kwargs=None) -> None:
    """Dispatch-site hook: capture the XLA cost doc of `fn` at these
    args under span name `name`, once per shape signature — a no-op
    unless the installed tracer is enabled with costs armed, so
    untraced runs pay one attribute read."""
    from . import trace as trace_mod

    tr = trace_mod.get_tracer()
    if not (tr.enabled and getattr(tr, "costs", False)):
        return
    _COLLECTOR.capture(name, fn, args, kwargs)


def load_cost_docs(dirpath: str) -> Dict[str, dict]:
    """Merge every rank's costs_rank*.json (largest bytes_accessed doc
    wins per name — ranks run the same programs)."""
    import glob

    merged: Dict[str, dict] = {}
    for path in sorted(glob.glob(
            os.path.join(dirpath, "costs_rank*.json"))):
        with open(path) as f:
            docs = json.load(f)
        for name, doc in docs.items():
            prev = merged.get(name)
            if prev is None or doc.get("bytes_accessed", 0.0) > prev.get(
                    "bytes_accessed", 0.0):
                merged[name] = doc
    return merged


def attribute(cost_docs: Dict[str, dict], span_table: Dict[str, dict],
              platform: Optional[str] = None) -> List[dict]:
    """Combine captured cost docs with the tracer's measured span table
    (per-call mean seconds = total_us / count) into one roofline row
    per phase, sorted by bytes_accessed — the per-phase cost table
    `obs.report` renders. Pure host arithmetic, no jax."""
    rows: List[dict] = []
    for name, doc in cost_docs.items():
        span = span_table.get(name)
        calls = int(span["count"]) if span else 0
        cold = False
        if calls > 1 and span.get("first_us") is not None:
            # drop the first sample per span: on a cold-cache trace it
            # folds the jit compile into the device-span mean, turning
            # the %-of-roof fraction into fiction (the PR-8 wart). A
            # 1-warmup trace therefore changes the reported mean.
            mean_s = (
                (span["total_us"] - span["first_us"]) / (calls - 1) / 1e6
            )
        elif calls:
            # a single sample cannot be separated from its compile —
            # keep it, flagged cold, so the fraction is readable as an
            # upper bound on the honest mean
            mean_s = span["total_us"] / calls / 1e6
            cold = True
        else:
            mean_s = 0.0
        plat = platform or doc.get("platform", "cpu")
        row = dict(
            name=name, calls=calls, mean_s=mean_s,
            flops=doc.get("flops", 0.0),
            bytes_accessed=doc.get("bytes_accessed", 0.0),
            variants=doc.get("variants", 1),
            platform=plat,
        )
        if cold:
            row["cold"] = True
        if "error" in doc:
            row["error"] = doc["error"]
        row.update(roofline(row["flops"], row["bytes_accessed"],
                            mean_s, plat))
        rows.append(row)
    rows.sort(key=lambda r: -r["bytes_accessed"])
    return rows


# ---------------------------------------------------------------------------
# HBM watermarks
# ---------------------------------------------------------------------------


def memory_watermark() -> Optional[dict]:
    """Current device-memory watermark: ``device.memory_stats()`` where
    the backend reports it (TPU/GPU HBM), else the process RSS /
    peak-RSS from /proc (the CPU backend allocates from host RAM, so
    VmHWM is the honest peak there). None when neither is readable."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats:
        in_use = int(stats.get("bytes_in_use", 0))
        return dict(
            source="device",
            bytes_in_use=in_use,
            peak_bytes=int(stats.get("peak_bytes_in_use", in_use)),
            bytes_limit=int(stats.get("bytes_limit", 0)),
        )
    try:
        rss = peak = 0
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    peak = int(line.split()[1]) * 1024
        if peak or rss:
            return dict(source="host_rss", bytes_in_use=rss,
                        peak_bytes=max(peak, rss), bytes_limit=0)
    except (OSError, ValueError, IndexError):
        pass
    return None


def record_hbm(phase: Optional[str] = None) -> Optional[dict]:
    """Phase-boundary HBM snapshot into the metrics registry (always
    on, like every other metric — one stats call per boundary):

    - ``hbm/peak_bytes``: monotone run-wide peak;
    - ``hbm/bytes_in_use``: last boundary's live bytes;
    - ``hbm/limit_bytes``: the device's reported capacity (0 unknown);
    - ``hbm/device_source``: 1 when read from device.memory_stats(),
      0 for the host-RSS fallback;
    - ``hbm/phase_bytes/<phase>``: max live bytes observed at this
      phase's boundaries (the per-phase watermark the report renders).
    """
    w = memory_watermark()
    if w is None:
        return None
    from . import metrics as metrics_mod

    reg = metrics_mod.registry()
    g = reg.gauge("hbm/peak_bytes")
    g.set(max(g.value, float(w["peak_bytes"])))
    reg.gauge("hbm/bytes_in_use").set(float(w["bytes_in_use"]))
    reg.gauge("hbm/limit_bytes").set(float(w.get("bytes_limit", 0)))
    reg.gauge("hbm/device_source").set(
        1.0 if w["source"] == "device" else 0.0
    )
    if phase:
        pg = reg.gauge(f"hbm/phase_bytes/{phase}")
        pg.set(max(pg.value, float(w["bytes_in_use"])))
    return w


# ---------------------------------------------------------------------------
# shared steady-state timing (the profiler consolidation surface)
# ---------------------------------------------------------------------------


def timed_mean(fn, reps: int = 5) -> float:
    """Warm once (compile), then mean wall seconds per call over `reps`
    fully-synchronized calls — the single steady-state timing
    definition shared by the profiling tools. Host-side harness code
    only: timings INSIDE traced programs come from tracer device
    spans (PML010)."""
    import time

    import jax

    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn())
    return (time.perf_counter() - t0) / reps


def chained_seconds(step, carry, reps: int = 20) -> float:
    """Per-iteration seconds of `step` run `reps` times inside ONE
    jitted `lax.fori_loop` with `carry` as the loop state (true data
    dependency) — real device compute on backends whose
    block_until_ready does not synchronize (the remote TPU tunnel).
    `step(carry) -> carry`. The shared engine of
    tools/profile_chain.py."""
    import time

    import jax

    @jax.jit
    def run(c):
        return jax.lax.fori_loop(0, reps, lambda i, cc: step(cc), c)

    def force(out):
        # a SCALAR device_get, not block_until_ready: the remote-tunnel
        # backend returns from block_until_ready before the chain has
        # executed — pulling one element is a true synchronization
        leaf = jax.tree_util.tree_leaves(out)[0]
        return jax.device_get(leaf.ravel()[0])

    force(run(carry))
    t0 = time.perf_counter()
    force(run(carry))
    return (time.perf_counter() - t0) / reps
