"""Perf-history record envelope, PERF_DB store, and the regression gate.

Before this module the bench trajectory was unreadable as data: six
``BENCH_r0*.json`` files (two different shapes — a driver wrapper with
``parsed``/``tail`` and raw records) plus ``SCALE_RUNS.jsonl`` shared
no record envelope, so no tool could answer "did PR N regress phase X".
Three pieces fix that:

- **envelope** (:func:`make_record`): every record — full or partial —
  carries ``schema`` / ``run_id`` / ``git_sha`` / ``timestamp`` /
  ``platform`` / ``rung`` stamped by ONE constructor. `bench.py` and
  `tools/scale_run.py` route both their worker-committed and
  parent-synthesized partial records through it, so the two paths can
  never drift apart again.
- **PERF_DB** (:func:`append_db`/:func:`load_db`): an append-only
  JSONL of enveloped records, one line per measurement, plus the
  backfill importer (:func:`backfill_records`) that normalizes the
  historical ``BENCH_r01–r06`` + ``SCALE_RUNS.jsonl`` into it —
  git-archaeology fills ``git_sha``/``timestamp`` from the commit that
  added each file, and the workload rung is inferred from the output
  element count via the bench's own sizing formula.
- **gate** (:func:`gate`): a noise-aware regression verdict — per
  metric key, rolling median ± MAD-scaled tolerance over the last
  `window` non-partial records of the same (platform, rung, metric)
  group. MAD (scaled by 1.4826 to estimate sigma) absorbs the shared-
  TPU run-to-run swings; the relative floor keeps a zero-MAD group
  (single baseline) from gating at zero tolerance. One-sided per key:
  only the bad direction (lower value, higher wall) regresses, so
  improvements always pass and ratchet the baseline when appended
  (``tools/perf_gate.py --update-baseline``).

Pure stdlib + git subprocess — safe to import from tools that must not
touch the accelerator.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Dict, List, Optional

__all__ = [
    "SCHEMA", "REGRESSION_EXIT", "GATE_KEYS", "make_record", "git_sha",
    "append_db", "load_db", "normalize", "infer_rung",
    "backfill_records", "gate", "GateResult", "baseline_records",
    "quote",
]

SCHEMA = "parmmg-perfdb/1"

# typed exit code of the gate CLI on a detected regression (the
# failsafe taxonomy owns 86-89; 91 is the perf-gate verdict)
REGRESSION_EXIT = 91

# gated metric keys and their good direction: "higher" regresses when
# the candidate falls below median - tol, "lower" when it rises above
# median + tol. Keys absent from a record or its baseline are skipped.
GATE_KEYS: Dict[str, str] = {
    "value": "higher",
    "wall_s": "lower",
    "steady_recompiles": "lower",
    "qmin": "higher",
    # load-imbalance factor (live-tets max/mean across shards, worst
    # iteration): distributed records carry it so the gate ratchets
    # BALANCE, not just throughput — absent from centralized records,
    # and absent keys are skipped
    "imbalance": "lower",
    # unit-mesh goal (obs.health, round 12): final unit-band edge
    # fraction of the run — the gate ratchets mesh QUALITY in the
    # reference's own -prilen terms, alongside qmin
    "len/in_band": "higher",
}

_ENVELOPE = ("schema", "run_id", "git_sha", "timestamp", "platform",
             "rung")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


_GIT_SHA_CACHE: List[Optional[str]] = []


def _git(args: List[str], cwd: str) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git"] + args, capture_output=True, text=True, cwd=cwd,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    val = out.stdout.strip()
    return val if out.returncode == 0 and val else None


def git_sha() -> str:
    """HEAD sha of the repo this module lives in (cached; env override
    PMMGTPU_GIT_SHA for detached/archival runs; "unknown" when git is
    unavailable)."""
    env = os.environ.get("PMMGTPU_GIT_SHA")
    if env:
        return env
    if not _GIT_SHA_CACHE:
        _GIT_SHA_CACHE.append(
            _git(["rev-parse", "HEAD"], _repo_root()) or "unknown"
        )
    return _GIT_SHA_CACHE[0] or "unknown"


def make_record(payload: dict, rung: Optional[str] = None,
                platform: Optional[str] = None,
                run_id: Optional[str] = None,
                sha: Optional[str] = None,
                timestamp: Optional[str] = None) -> dict:
    """The one record constructor: envelope fields first, then the
    payload (payload keys win over inferred envelope values except
    ``schema``). Stamps full AND partial records — a record without
    this envelope cannot enter PERF_DB."""
    rec = dict(
        schema=SCHEMA,
        run_id=run_id or uuid.uuid4().hex[:12],
        git_sha=sha or git_sha(),
        timestamp=timestamp or time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        platform=payload.get("platform", platform or "unknown"),
        # explicit rung > inferred; a legacy payload "rung" tag (the
        # old SCALE_RUNS ladder letters) is consumed by infer_rung, not
        # copied verbatim — the envelope owns this key
        rung=rung or infer_rung(payload),
    )
    rec.update({k: v for k, v in payload.items()
                if k not in ("schema", "rung")})
    rec["platform"] = rec.get("platform") or "unknown"
    return rec


def append_db(path: str, rec: dict) -> None:
    """Append one enveloped record line (the DB is append-only; no
    rewrite, no compaction — history is the point)."""
    if rec.get("schema") != SCHEMA:
        rec = make_record(rec)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()


def load_db(path: str) -> List[dict]:
    """All parseable record lines (a truncated tail line — a killed
    appender — is skipped, like the tracer's timeline loader)."""
    recs: List[dict] = []
    if not os.path.exists(path):
        return recs
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                recs.append(rec)
    return recs


# ---------------------------------------------------------------------------
# normalization + backfill of the historical trajectory
# ---------------------------------------------------------------------------

# the bench ladder's workload classes: hsiz -> (n, est output tets via
# bench.est_out_tets = 12/hsiz^3). Used ONLY to label historical bare
# records with the rung they came from; new records carry their rung
# explicitly from the tool that measured them.
_RUNG_CLASSES = (
    ("n10-hsiz0.05", 12.0 / 0.05**3),
    ("n12-hsiz0.04", 12.0 / 0.04**3),
    ("n14-hsiz0.03", 12.0 / 0.03**3),
    ("n16-hsiz0.02", 12.0 / 0.02**3),
)


def infer_rung(rec: dict) -> str:
    """Best-effort rung label for a bare (pre-envelope) record: dist
    records key on nparts, cold scale records keep their own rung tag,
    headline records map the output tet count onto the nearest bench
    workload class."""
    metric = rec.get("metric", "")
    if rec.get("nparts") or metric.endswith("_distributed"):
        return f"dist-p{rec.get('nparts', '?')}"
    if "rung" in rec:
        return f"xl-{rec['rung']}"
    ne = rec.get("ne")
    if not ne:
        return rec.get("stage", "unknown")
    best = min(_RUNG_CLASSES, key=lambda c: abs(ne - c[1]) / c[1])
    return best[0]


def normalize(rec: dict, **env) -> dict:
    """Normalize any historical record shape into one enveloped record:
    already-enveloped records pass through untouched (idempotent), bare
    records get stamped, BENCH driver wrappers are unwrapped by the
    caller (they may hold several records — see backfill_records)."""
    if rec.get("schema") == SCHEMA:
        return rec
    return make_record(rec, **env)


def _wrapper_records(doc: dict) -> List[dict]:
    """Records inside one BENCH driver wrapper ({n, cmd, rc, tail,
    parsed}): every JSON line in the tail (r04 carried two), else the
    parsed record, else one synthesized partial that keeps the blind
    round visible in the trajectory (r01/r03's rc=124-with-nothing)."""
    recs: List[dict] = []
    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    if not recs and doc.get("parsed"):
        recs.append(doc["parsed"])
    if not recs:
        recs.append({
            "metric": "tets_per_sec", "value": 0.0, "unit": "tet/s",
            "partial": True, "platform": "unknown",
            "error": f"no record committed (driver rc={doc.get('rc')})",
        })
    return recs


def backfill_records(repo_dir: str) -> List[dict]:
    """Normalize the historical trajectory under `repo_dir` —
    ``BENCH_r*.json`` (driver wrappers AND raw records) +
    ``SCALE_RUNS.jsonl`` — into enveloped records. ``git_sha`` /
    ``timestamp`` come from the commit that last touched each source
    file (the measurement landed with that commit); ``run_id`` is the
    deterministic source tag so re-running the backfill is
    reproducible."""
    import glob

    out: List[dict] = []

    def _env_for(path: str) -> dict:
        sha = _git(["log", "-1", "--format=%H", "--", os.path.basename(
            path)], repo_dir)
        ts = _git(["log", "-1", "--format=%cI", "--",
                   os.path.basename(path)], repo_dir)
        return dict(sha=sha or git_sha(), timestamp=ts)

    for path in sorted(glob.glob(os.path.join(repo_dir,
                                              "BENCH_r*.json"))):
        tag = os.path.splitext(os.path.basename(path))[0].lower()
        env = _env_for(path)
        with open(path) as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                continue
        if isinstance(doc, list):
            recs = doc  # committed A/B pair (r07 shape: [off, on])
        elif "cmd" in doc and "tail" in doc:
            recs = _wrapper_records(doc)
        else:
            recs = [doc]  # raw record file (r06 shape)
        for i, rec in enumerate(recs):
            rid = tag if len(recs) == 1 else f"{tag}.{i}"
            out.append(normalize(rec, run_id=rid, **env))

    scale = os.path.join(repo_dir, "SCALE_RUNS.jsonl")
    if os.path.exists(scale):
        env = _env_for(scale)
        for i, rec in enumerate(load_db(scale)):
            out.append(normalize(rec, run_id=f"scale-runs.{i}", **env))
    return out


# ---------------------------------------------------------------------------
# the noise-aware regression gate
# ---------------------------------------------------------------------------


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _group_key(rec: dict) -> tuple:
    return (rec.get("platform", "unknown"), rec.get("rung", "unknown"),
            rec.get("metric", "unknown"))


class GateResult:
    """Structured gate verdict: per-key rows plus the overall call."""

    def __init__(self, group: tuple, baseline_n: int):
        self.group = group
        self.baseline_n = baseline_n
        self.rows: List[dict] = []

    @property
    def regressions(self) -> List[str]:
        return [r["key"] for r in self.rows if r["verdict"] == "REGRESS"]

    @property
    def ok(self) -> bool:
        return not self.regressions

    @property
    def no_baseline(self) -> bool:
        return self.baseline_n == 0

    def lines(self) -> List[str]:
        plat, rung, metric = self.group
        out = [f"[perf-gate] platform={plat} rung={rung} "
               f"metric={metric} baseline_n={self.baseline_n}"]
        for r in self.rows:
            out.append(
                f"  {r['key']:<18s} {r['candidate']:>12.4g} vs median "
                f"{r['median']:>12.4g} (tol ±{r['tol']:.4g})  "
                f"{r['verdict']}"
            )
        if self.no_baseline:
            out.append("  (no baseline for this group yet — record "
                       "admitted; gate arms on the next run)")
        out.append(
            f"[perf-gate] {'OK' if self.ok else 'REGRESSION: ' + ','.join(self.regressions)}"
        )
        return out


def baseline_records(db: List[dict], key: tuple,
                     window: int = 8) -> List[dict]:
    """The last `window` non-partial records of the (platform, rung,
    metric) group `key` — falling back to (platform, metric) when the
    exact rung has no history, so a renamed rung degrades to a coarser
    baseline instead of selecting nothing. The SINGLE baseline
    selection shared by the regression gate and the admission
    :func:`quote` — the two must never disagree on what "history"
    means for a group."""
    base = [r for r in db
            if _group_key(r) == key and not r.get("partial")]
    if not base:
        # coarse fallback (platform, metric) still honors the Pallas-
        # kernel marker: a `…-pk` rung must never be gated against
        # lax-baseline history (and vice versa) — the two backends are
        # distinct baseline keys by contract
        pk = str(key[1]).endswith("-pk")
        base = [r for r in db
                if (r.get("platform"), r.get("metric")) == (key[0], key[2])
                and str(r.get("rung", "")).endswith("-pk") == pk
                and not r.get("partial")]
    return base[-window:]


def quote(db: List[dict], platform: str, rung: str,
          window: int = 8) -> Dict[str, dict]:
    """Rolling-median quote for a (platform, rung) pair from PERF_DB
    history — the admission-time mirror of :func:`gate`, built on the
    same :func:`baseline_records` selection (same window, same
    partial-skip, same rung fallback), so what admission promises is
    exactly what the gate will hold the run to.

    Returns ``{metric: {"value": median(value), "wall_s":
    median(wall_s), "n": baseline_n, "unit": ...}}`` per distinct
    metric recorded under the rung; keys without any numeric history
    are omitted, and an empty dict means no usable history at all
    (callers fall back to configured defaults)."""
    metrics = sorted({r.get("metric") for r in db
                      if r.get("rung") == rung
                      and r.get("platform") == platform
                      and r.get("metric")})
    if not metrics:
        # rung fallback mirrors baseline_records: quote every metric
        # that has (platform, metric) history at matching -pk parity
        pk = str(rung).endswith("-pk")
        metrics = sorted({r.get("metric") for r in db
                          if r.get("platform") == platform
                          and str(r.get("rung", "")).endswith("-pk") == pk
                          and r.get("metric")})
    out: Dict[str, dict] = {}
    for metric in metrics:
        base = baseline_records(db, (platform, rung, metric), window)
        doc: dict = {"n": len(base)}
        for mkey in ("value", "wall_s", "imbalance", "warmup_s"):
            vals = [float(r[mkey]) for r in base
                    if isinstance(r.get(mkey), (int, float))]
            if vals:
                doc[mkey] = _median(vals)
        units = [r.get("unit") for r in base if r.get("unit")]
        if units:
            doc["unit"] = units[-1]
        if len(doc) > 1:
            out[metric] = doc
    return out


def gate(db: List[dict], rec: dict, window: int = 8,
         rel_floor: float = 0.5, mad_k: float = 4.0) -> GateResult:
    """Gate `rec` against its rolling baseline in `db`.

    Baseline = :func:`baseline_records` of the candidate's (platform,
    rung, metric) group — the selection shared with the admission
    :func:`quote`. Per gated key the tolerance is ``max(mad_k * 1.4826
    * MAD, rel_floor * |median|)`` and only the bad direction
    regresses. A partial candidate is never gated on its zeroed
    measurement keys (its partial-ness already exits nonzero at the
    tool that produced it) — it reports SKIP rows instead."""
    rec = normalize(rec)
    key = _group_key(rec)
    base = baseline_records(db, key, window)
    res = GateResult(key, len(base))
    partial = bool(rec.get("partial"))
    for mkey, direction in GATE_KEYS.items():
        if mkey not in rec:
            continue
        vals = [float(r[mkey]) for r in base
                if isinstance(r.get(mkey), (int, float))]
        if not vals:
            continue
        cand = float(rec[mkey])
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        tol = max(mad_k * 1.4826 * mad, rel_floor * abs(med))
        if partial:
            verdict = "SKIP(partial)"
        elif direction == "higher":
            verdict = "REGRESS" if cand < med - tol else "OK"
        else:
            verdict = "REGRESS" if cand > med + tol else "OK"
        res.rows.append(dict(key=mkey, candidate=cand, median=med,
                             mad=mad, tol=tol, direction=direction,
                             verdict=verdict))
    return res
