"""Cross-rank performance observatory (round 11).

The single-rank report (`obs.report`) answers "where did THIS process
spend its wall"; this module answers the question the distributed perf
arc is blocked on — *which rank's which phase gated the world*. The
reference's schedule lives or dies on balance (the remesh/repartition
loop exists to keep per-group work even, `PMMG_loadBalancing`), and
BENCH_r06's ~500x distributed gap cannot be attributed from per-rank
wall numbers alone: per-rank clocks are unaligned, collective waits
fold stragglers' lag into everyone's wall, and migration stalls hide
inside one span mean.

Four lenses over one trace directory, all host/stdlib (never touches
the accelerator):

- **clock alignment** (:func:`rank_segments` / :func:`aligned_
  timelines`): every ``events_rank<r>.jsonl`` starts each tracer life
  with a ``type="clock"`` header (``t0_us`` = the tracer's monotonic
  origin) and `multihost.sync_tracer_clock` appends the rank's
  median-of-K offset to rank 0's clock. Aligned time of a record is
  ``t0_us + ts_us + offset_us`` — one timebase for the world, per
  SEGMENT, so a resume-restarted clock (fresh tracer appending to the
  same file) re-aligns instead of interleaving;
- **collective decomposition** (:func:`collective_instances` /
  :func:`decompose_collectives`): the ``coll:<name>`` spans
  (`multihost._coll_span`) and the ``migrate_exchange`` device-spans
  are matched across ranks by per-name sequence — dispatch order is
  identical on every process — and each world instance splits into
  straggler lag (last entrant minus first entrant: time the early
  ranks burned waiting) vs true transfer (last entrant to last exit:
  time the collective itself cost);
- **load imbalance**: the distributed history records carry
  ``shard_ne``/``imbalance`` (live-tets max/mean), mirrored into the
  ``work/*`` gauges by `metrics.record_sweep` and into the BENCH/
  PERF_DB envelope by `bench.run_dist` (gate key ``imbalance``,
  lower-better);
- **critical path** (:func:`critical_path`): per iteration, walk the
  world-matched collectives in completion order — the segment between
  two sync points is gated by the rank that entered the closing
  collective LAST, and the gating phase is whatever span that rank was
  inside — rendered as a table plus a Perfetto-loadable merged trace
  (:func:`write_merged_trace`).

CLI: ``python tools/obs_report.py <dir> --dist 1`` (``--json 1`` for
the structured document); asserted end to end by
``tools/dist_obs_smoke.py`` (the check.sh ``dist-obs`` stage).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from . import metrics as metrics_mod

__all__ = [
    "rank_segments", "aligned_timelines", "collective_instances",
    "decompose_collectives", "critical_path", "write_merged_trace",
    "dist_summary", "render_dist",
]

# span names treated as world-synchronous collectives: the nth
# occurrence on each rank is the same world instance
_COLL_PREFIX = "coll:"
_COLL_NAMES = ("migrate_exchange",)


def _is_coll(name: str) -> bool:
    return name.startswith(_COLL_PREFIX) or name in _COLL_NAMES


# ---------------------------------------------------------------------------
# clock segments + alignment
# ---------------------------------------------------------------------------


def rank_segments(dirpath: str) -> Dict[int, List[dict]]:
    """Per-rank clock segments of a trace directory, file-ordered.

    Each segment is one tracer life: ``{"t0_us", "offset_us",
    "err_us", "rounds", "aligned", "records"}``. A ``type="clock"``
    record with ``restart`` opens a new segment; a non-restart clock
    record (the persisted offset estimate) updates the CURRENT
    segment. Records preceding any header (pre-round-11 files) land in
    an implicit unaligned segment with ``t0_us=0``. Tolerates
    truncated final lines (a process killed mid-write)."""
    out: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(
            os.path.join(dirpath, "events_rank*.jsonl"))):
        stem = os.path.basename(path)[len("events_rank"):-len(".jsonl")]
        try:
            rank = int(stem)
        except ValueError:
            continue
        segs: List[dict] = []

        def seg(t0_us=0.0, offset_us=0.0, aligned=False):
            s = dict(t0_us=float(t0_us), offset_us=float(offset_us),
                     err_us=0.0, rounds=0, aligned=bool(aligned),
                     records=[])
            segs.append(s)
            return s

        cur: Optional[dict] = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("type") == "clock":
                    if rec.get("restart") or cur is None:
                        cur = seg(t0_us=rec.get("t0_us", 0.0),
                                  offset_us=rec.get("offset_us", 0.0))
                    else:
                        cur["offset_us"] = float(
                            rec.get("offset_us", 0.0)
                        )
                        cur["err_us"] = float(rec.get("err_us", 0.0))
                        cur["rounds"] = int(rec.get("rounds", 0))
                        cur["aligned"] = True
                    continue
                if cur is None:
                    cur = seg()
                cur["records"].append(rec)
        out[rank] = segs
    return out


def aligned_timelines(dirpath: str) -> Dict[int, List[dict]]:
    """Per-rank span/event records with aligned timestamps applied:
    every record gains ``ats_us`` (= segment ``t0_us + ts_us +
    offset_us`` — rank 0's timebase) and spans gain ``aend_us``.
    Sorted by aligned START time: the JSONL writes a span at its
    EXIT, so file order is completion order — sorting restores
    dispatch order (what occurrence matching needs) and, with correct
    offsets, keeps segment-2 records after segment-1 records even
    across a mid-file clock restart."""
    out: Dict[int, List[dict]] = {}
    for rank, segs in rank_segments(dirpath).items():
        recs: List[dict] = []
        for s in segs:
            base = s["t0_us"] + s["offset_us"]
            for r in s["records"]:
                r = dict(r)
                r["ats_us"] = base + float(r.get("ts_us", 0))
                if r.get("type") == "span":
                    r["aend_us"] = r["ats_us"] + float(
                        r.get("dur_us", 0)
                    )
                recs.append(r)
        recs.sort(key=lambda r: r["ats_us"])
        out[rank] = recs
    return out


# ---------------------------------------------------------------------------
# collective skew / straggler decomposition
# ---------------------------------------------------------------------------


def collective_instances(
        timelines: Dict[int, List[dict]]) -> List[dict]:
    """World-matched collective instances from aligned timelines.

    Spans named ``coll:*`` (and ``migrate_exchange``) are matched by
    ``(name, seq)`` — ``args.seq`` when the emitter recorded one
    (`multihost._coll_span`), else the rank's occurrence index of that
    name. Each instance decomposes into::

      lag_us      last entrant - first entrant  (straggler lag: what
                  the early ranks burned waiting at the rendezvous)
      transfer_us last exit - last entrant      (the collective's own
                  cost once everyone arrived)
      straggler   the last-entering rank

    Sorted by world enter time."""
    inst: Dict[tuple, dict] = {}
    for rank, recs in timelines.items():
        occ: Dict[str, int] = {}
        for r in recs:
            if r.get("type") != "span" or not _is_coll(r.get("name", "")):
                continue
            name = r["name"]
            n = occ.get(name, 0)
            occ[name] = n + 1
            args = r.get("args") or {}
            seq = args.get("seq", n)
            key = (name, seq)
            it = inst.setdefault(key, dict(
                name=name, seq=seq, enter_us={}, exit_us={},
                tag=args.get("tag"), it=args.get("it"),
            ))
            it["enter_us"][rank] = r["ats_us"]
            it["exit_us"][rank] = r["aend_us"]
    rows = []
    for it in inst.values():
        enters = it["enter_us"]
        first = min(enters.values())
        last = max(enters.values())
        end = max(it["exit_us"].values())
        it["first_enter_us"] = first
        it["last_enter_us"] = last
        it["lag_us"] = last - first
        it["transfer_us"] = max(end - last, 0.0)
        it["straggler"] = max(enters, key=lambda r: enters[r])
        it["world"] = len(enters)
        rows.append(it)
    rows.sort(key=lambda d: d["first_enter_us"])
    return rows


def decompose_collectives(
        timelines: Dict[int, List[dict]]) -> dict:
    """Aggregate the instance decomposition per collective phase and
    per rank: ``phases[name]`` carries calls / lag_s / transfer_s and
    the worst straggler rank (most accumulated lag while last in);
    ``per_rank[r]`` carries ``wait_s`` (seconds rank r sat inside
    collectives) and ``skew_s`` (seconds rank r arrived after the
    first entrant — how much it straggled)."""
    rows = collective_instances(timelines)
    phases: Dict[str, dict] = {}
    per_rank: Dict[int, dict] = {
        r: dict(wait_s=0.0, skew_s=0.0) for r in timelines
    }
    for it in rows:
        ph = phases.setdefault(it["name"], dict(
            calls=0, lag_s=0.0, transfer_s=0.0, by_rank_lag={},
        ))
        ph["calls"] += 1
        ph["lag_s"] += it["lag_us"] / 1e6
        ph["transfer_s"] += it["transfer_us"] / 1e6
        brl = ph["by_rank_lag"]
        brl[it["straggler"]] = (
            brl.get(it["straggler"], 0.0) + it["lag_us"] / 1e6
        )
        first = it["first_enter_us"]
        for r, ent in it["enter_us"].items():
            per_rank.setdefault(r, dict(wait_s=0.0, skew_s=0.0))
            per_rank[r]["wait_s"] += (
                it["exit_us"][r] - ent
            ) / 1e6
            per_rank[r]["skew_s"] += (ent - first) / 1e6
    for name, ph in phases.items():
        brl = ph.pop("by_rank_lag")
        if brl:
            worst = max(brl, key=lambda r: brl[r])
            ph["worst_rank"] = worst
            ph["worst_rank_lag_s"] = round(brl[worst], 6)
        ph["lag_s"] = round(ph["lag_s"], 6)
        ph["transfer_s"] = round(ph["transfer_s"], 6)
    for r in per_rank:
        per_rank[r]["wait_s"] = round(per_rank[r]["wait_s"], 6)
        per_rank[r]["skew_s"] = round(per_rank[r]["skew_s"], 6)
    return dict(phases=phases, per_rank=per_rank,
                instances=len(rows))


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------


def _gating_phase(recs: List[dict], at_us: float) -> Optional[str]:
    """Deepest non-collective span on one rank covering ``at_us`` —
    the phase label a critical-path segment is attributed to."""
    best = None
    best_depth = -1
    for r in recs:
        if r.get("type") != "span" or _is_coll(r.get("name", "")):
            continue
        if r["ats_us"] <= at_us <= r["aend_us"]:
            d = int(r.get("depth", 0))
            if d >= best_depth:
                best_depth = d
                best = r["name"]
    return best


def critical_path(timelines: Dict[int, List[dict]]) -> List[dict]:
    """The cross-rank critical path, per iteration.

    Collectives are the world's sync points: the wall between two of
    them is gated by whichever rank entered the CLOSING collective
    last (everyone else was already waiting at the rendezvous). Per
    iteration (matched across ranks by the ``iteration`` span's
    ``it`` arg), walk its collective instances in completion order and
    emit one row per inter-sync segment::

      {it, rank, phase, gate, start_us, dur_us}

    where ``phase`` is the deepest span the gating rank was inside
    mid-segment and ``gate`` names the closing sync (the final segment
    closes at the iteration's world end). Degenerates gracefully on a
    single rank: every segment is gated by rank 0."""
    # iteration windows: it -> (world start, world end)
    iters: Dict[int, List[float]] = {}
    for recs in timelines.values():
        for r in recs:
            if r.get("type") == "span" and r.get("name") == "iteration":
                itn = (r.get("args") or {}).get("it")
                if itn is None:
                    continue
                itn = int(itn)
                w = iters.setdefault(itn, [r["ats_us"], r["aend_us"]])
                w[0] = min(w[0], r["ats_us"])
                w[1] = max(w[1], r["aend_us"])
    colls = collective_instances(timelines)
    rows: List[dict] = []
    for itn in sorted(iters):
        lo, hi = iters[itn]
        inside = [
            c for c in colls
            if lo <= c["first_enter_us"] and c["last_enter_us"] <= hi
        ]
        inside.sort(key=lambda c: c["last_enter_us"])
        cursor = lo
        for c in inside:
            seg_end = c["last_enter_us"]
            dur = seg_end - cursor
            if dur <= 0:
                cursor = max(cursor, max(c["exit_us"].values()))
                continue
            gater = c["straggler"]
            mid = cursor + dur / 2.0
            phase = _gating_phase(
                timelines.get(gater, []), mid
            ) or c["name"]
            rows.append(dict(
                it=itn, rank=gater, phase=phase, gate=c["name"],
                start_us=round(cursor, 1), dur_us=round(dur, 1),
            ))
            cursor = max(c["exit_us"].values())
        if hi > cursor:
            # tail segment: whoever finished the iteration last
            ends = {
                r: max((x["aend_us"] for x in recs
                        if x.get("type") == "span"
                        and x.get("name") == "iteration"
                        and (x.get("args") or {}).get("it") == itn),
                       default=None)
                for r, recs in timelines.items()
            }
            ends = {r: e for r, e in ends.items() if e is not None}
            gater = max(ends, key=lambda r: ends[r]) if ends else 0
            mid = cursor + (hi - cursor) / 2.0
            phase = _gating_phase(
                timelines.get(gater, []), mid
            ) or "iteration"
            rows.append(dict(
                it=itn, rank=gater, phase=phase, gate="iteration_end",
                start_us=round(cursor, 1),
                dur_us=round(hi - cursor, 1),
            ))
    return rows


# ---------------------------------------------------------------------------
# merged Perfetto trace
# ---------------------------------------------------------------------------


def write_merged_trace(dirpath: str,
                       out_path: Optional[str] = None) -> Optional[str]:
    """One Perfetto-loadable Chrome trace of every rank on rank 0's
    timebase: each ``trace_rank<r>.json`` carries its tracer's clock
    segment (``t0_us``/``offset_us`` — `Tracer.flush` stamps it), so
    every timed event is shifted by ``t0_us + offset_us``. Rank tracks
    keep their pid; load the result in Perfetto and the ranks line up.
    Returns the written path (default ``trace_merged.json`` inside the
    directory), or None when no rank traces exist."""
    events: List[dict] = []
    found = False
    for path in sorted(glob.glob(
            os.path.join(dirpath, "trace_rank*.json"))):
        with open(path) as f:
            doc = json.load(f)
        found = True
        clock = doc.get("clock") or {}
        shift = float(clock.get("t0_us", 0.0)) \
            + float(clock.get("offset_us", 0.0))
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") != "M" and "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            events.append(ev)
    if not found:
        return None
    out_path = out_path or os.path.join(dirpath, "trace_merged.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out_path)
    return out_path


# ---------------------------------------------------------------------------
# summary + render
# ---------------------------------------------------------------------------


def dist_summary(dirpath: str) -> dict:
    """The structured ``--dist`` document: clock table, per-rank
    aligned spans + wait/skew, per-phase collective decomposition,
    the work/imbalance picture from the merged metrics, and the
    critical-path rows."""
    segs = rank_segments(dirpath)
    tls = aligned_timelines(dirpath)
    clocks = {
        r: [
            dict(t0_us=s["t0_us"], offset_us=s["offset_us"],
                 err_us=s["err_us"], rounds=s["rounds"],
                 aligned=s["aligned"], records=len(s["records"]))
            for s in ss
        ]
        for r, ss in segs.items()
    }
    ranks = {}
    for r, recs in tls.items():
        spans = [x for x in recs if x.get("type") == "span"]
        remesh = sum(
            x["dur_us"] for x in spans
            if x.get("name", "").startswith("phase:remesh")
        ) / 1e6
        ranks[r] = dict(
            spans=len(spans),
            events=len(recs) - len(spans),
            start_us=round(min((x["ats_us"] for x in recs),
                               default=0.0), 1),
            end_us=round(max((x.get("aend_us", x["ats_us"])
                              for x in recs), default=0.0), 1),
            remesh_wall_s=round(remesh, 6),
        )
    comm = decompose_collectives(tls)
    for r, d in comm["per_rank"].items():
        if r in ranks:
            ranks[r].update(wait_s=d["wait_s"], skew_s=d["skew_s"])
    merged = metrics_mod.merge_dir(dirpath)
    work = {}
    if merged:
        g = merged.get("gauges", {})
        if "work/imbalance" in g:
            work["imbalance"] = g["work/imbalance"]
        shards = {
            k[len("work/live_tets/shard"):]: v
            for k, v in g.items()
            if k.startswith("work/live_tets/shard")
        }
        if shards:
            work["live_tets_per_shard"] = {
                k: v.get("max") if isinstance(v, dict) else v
                for k, v in sorted(shards.items(),
                                   key=lambda kv: int(kv[0]))
            }
        if "comm/wait_s" in g:
            work["comm_wait_s_gauge"] = g["comm/wait_s"]
    # balance decisions: one `rebalance` event per iteration whose
    # balancing block moved cells or re-cut (emitted by the distributed
    # driver with trigger/pre/post imbalance/cells/wall). Rank 0's
    # stream suffices — the decision is replicated-deterministic.
    balance = []
    for r in sorted(tls):
        evs = [x for x in tls[r]
               if x.get("type") == "event" and x.get("name") == "rebalance"]
        if evs:
            balance = [dict(x.get("args", {})) for x in evs]
            break
    if balance:
        work["balance_decisions"] = balance
    return dict(
        dir=dirpath,
        world=len(tls),
        clocks=clocks,
        ranks=ranks,
        collectives=comm,
        work=work,
        critical_path=critical_path(tls),
    )


def _fmt_s(us: float) -> str:
    return f"{us / 1e6:9.4f}"


def render_dist(dirpath: str) -> str:
    """Human-readable ``--dist`` report (see README "Distributed
    observability" for how to read it)."""
    s = dist_summary(dirpath)
    L: List[str] = []
    L.append(f"== obs report: distributed ({s['world']} rank(s)) ==")
    L.append("")
    L.append("-- clock alignment --")
    L.append("rank  seg  offset_us      err_us  rounds  aligned  "
             "records")
    for r in sorted(s["clocks"]):
        for i, seg in enumerate(s["clocks"][r]):
            L.append(
                f"{r:4d}  {i:3d}  {seg['offset_us']:12.1f}  "
                f"{seg['err_us']:8.1f}  {seg['rounds']:6d}  "
                f"{str(seg['aligned']):>7s}  {seg['records']:7d}"
            )
    L.append("")
    L.append("-- per-rank aligned timelines --")
    L.append("rank   spans  events     start_s       end_s  "
             "remesh_s    wait_s    skew_s")
    for r in sorted(s["ranks"]):
        d = s["ranks"][r]
        L.append(
            f"{r:4d}  {d['spans']:6d}  {d['events']:6d}  "
            f"{_fmt_s(d['start_us']):>10s}  {_fmt_s(d['end_us']):>10s}"
            f"  {d['remesh_wall_s']:8.4f}"
            f"  {d.get('wait_s', 0.0):8.4f}"
            f"  {d.get('skew_s', 0.0):8.4f}"
        )
    L.append("")
    L.append("-- collective decomposition (straggler lag vs "
             "transfer) --")
    phases = s["collectives"]["phases"]
    if phases:
        L.append("phase                      calls     lag_s  "
                 "transfer_s  worst-rank (lag_s)")
        for name in sorted(phases):
            ph = phases[name]
            worst = ph.get("worst_rank")
            wtxt = (f"rank {worst} ({ph.get('worst_rank_lag_s', 0.0):.4f})"
                    if worst is not None else "-")
            L.append(
                f"{name:<24s}  {ph['calls']:5d}  {ph['lag_s']:8.4f}  "
                f"{ph['transfer_s']:10.4f}  {wtxt}"
            )
    else:
        L.append("(no collective spans — single-process run?)")
    if s["work"]:
        L.append("")
        L.append("-- load imbalance --")
        imb = s["work"].get("imbalance")
        if imb is not None:
            per = imb.get("per_rank", imb) if isinstance(imb, dict) \
                else {"*": imb}
            txt = ", ".join(
                f"rank {k}: {v:.4f}" for k, v in sorted(per.items())
            )
            L.append(f"imbalance (live-tets max/mean): {txt}")
        shards = s["work"].get("live_tets_per_shard")
        if shards:
            L.append("live tets per shard: " + ", ".join(
                f"s{k}={int(v)}" for k, v in shards.items()
            ))
        decisions = s["work"].get("balance_decisions")
        if decisions:
            L.append(f"balance decisions: {len(decisions)}")
            for d in decisions:
                L.append(
                    f"  it {int(d.get('it', -1)):3d}  "
                    f"{str(d.get('trigger', '?')):<14s} "
                    f"imb {float(d.get('imbalance_pre', 0.0)):.4f}"
                    f" -> {float(d.get('imbalance_post', 0.0)):.4f}  "
                    f"cells {int(d.get('cells', 0)):6d}  "
                    f"wall {float(d.get('wall_s', 0.0)):.4f}s"
                )
    L.append("")
    L.append("-- critical path (which rank gated the world) --")
    cp = s["critical_path"]
    if cp:
        L.append("  it  rank  phase                      "
                 "gate                      dur_s")
        for row in cp:
            L.append(
                f"{row['it']:4d}  {row['rank']:4d}  "
                f"{row['phase']:<24s}  {row['gate']:<24s}  "
                f"{row['dur_us'] / 1e6:8.4f}"
            )
    else:
        L.append("(no matched iteration spans)")
    merged = os.path.join(dirpath, "trace_merged.json")
    L.append("")
    L.append(
        f"merged Perfetto trace: {merged}"
        + ("" if os.path.exists(merged)
           else "  (write with obs.dist.write_merged_trace)")
    )
    return "\n".join(L)
